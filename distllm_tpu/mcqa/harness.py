"""MCQA evaluation pipeline.

Reference parity: ``rag_argonium_score_parallel_v3.py`` ``main``
(``:3075-3786``): load config + questions → (optionally) boot a local engine
server → resume from checkpoints → answer questions in a thread pool with
client-side batching → grade with a second LLM (JSON retry ladder) → compute
accuracy and retrieval-traceability metrics → export incorrect answers and
the full config alongside the results.

Run: ``python -m distllm_tpu.mcqa.harness --config mcqa.yaml``
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor, as_completed
from pathlib import Path
from typing import Any

from distllm_tpu.mcqa.batching import BatchingClient
from distllm_tpu.mcqa.checkpoint import CheckpointManager
from distllm_tpu.mcqa.config import MCQAConfig
from distllm_tpu.mcqa.grading import grade_answer
from distllm_tpu.observability.flight import StallWatchdog
from distllm_tpu.observability.instruments import log_event


# --------------------------------------------------------------- chunk ids
def chunk_id(path: str, index: int) -> str:
    """Stable chunk identifier ``sha256(path)[:16]_{idx:04d}``
    (``v3:447-456``)."""
    digest = hashlib.sha256(str(path).encode()).hexdigest()[:16]
    return f'{digest}_{index:04d}'


def question_hash(question: str) -> str:
    return hashlib.sha256(question.strip().encode()).hexdigest()[:16]


# ------------------------------------------------------------- progress bar
class _PlainProgress:
    """tqdm fallback (``v3:3000-3036``)."""

    def __init__(self, total: int) -> None:
        self.total = total
        self.count = 0  # guarded by self._lock
        self._lock = threading.Lock()

    def update(self, n: int = 1) -> None:
        with self._lock:
            self.count += n
            if self.count % max(1, self.total // 20) == 0 or self.count == self.total:
                log_event(f'[mcqa] {self.count}/{self.total}', component='mcqa')

    def close(self) -> None:
        pass


def _progress(total: int):
    try:
        from tqdm import tqdm

        return tqdm(total=total, desc='mcqa')
    except ImportError:
        return _PlainProgress(total)


# ----------------------------------------------------------------- loading
def load_questions(path: str | Path) -> list[dict[str, Any]]:
    """Argonium-style questions: JSON list (or jsonl) of
    ``{question, answer, ...}`` entries."""
    path = Path(path)
    text = path.read_text()
    if path.suffix == '.jsonl':
        entries = [json.loads(line) for line in text.splitlines() if line.strip()]
    else:
        entries = json.loads(text)
    for entry in entries:
        if 'question' not in entry or 'answer' not in entry:
            raise ValueError(
                'each question entry needs "question" and "answer" fields'
            )
    return entries


# -------------------------------------------------------------- generation
class RagAnswerer:
    """Answer generation with retrieval chunk logging
    (``RagGeneratorWithChunkLogging``, ``v3:1744-1912``)."""

    def __init__(self, config: MCQAConfig, client: BatchingClient) -> None:
        self.config = config
        self.client = client
        self.retriever = None
        if config.retriever_config is not None:
            from distllm_tpu.rag.search import RetrieverConfig

            self.retriever = RetrieverConfig(
                **config.retriever_config
            ).get_retriever(register=True)

    def answer(self, question: str) -> dict[str, Any]:
        retrieval_log: list[dict[str, Any]] = []
        prompt = question
        if self.retriever is not None:
            results, _ = self.retriever.search(
                question,
                top_k=self.config.retrieval_top_k,
                score_threshold=self.config.retrieval_score_threshold,
            )
            indices = results.total_indices[0]
            scores = results.total_scores[0]
            texts = self.retriever.get_texts(indices) if indices else []
            def column(key: str) -> list:
                try:
                    return self.retriever.get(indices, key) if indices else []
                except KeyError:
                    return ['' for _ in indices]

            paths = column('path')
            # Chunks produced by question-generation pipelines may carry the
            # hash of the question they were generated from (``v3:594-641``).
            qhashes = column('question_hash')
            for rank, (idx, score, text, path, qhash) in enumerate(
                zip(indices, scores, texts, paths, qhashes)
            ):
                entry = {
                    'rank': rank,
                    'dataset_index': idx,
                    'score': score,
                    'chunk_id': chunk_id(path, idx),
                    'path': path,
                    'text_preview': text[:200],
                }
                if qhash:
                    entry['question_hash'] = qhash
                retrieval_log.append(entry)
            context = '\n\n'.join(texts)
            prompt = (
                f'Context:\n{context}\n\nQuestion: {question}\n'
                'Answer the question by choosing one of the options. '
                'Output only your chosen option.\nAnswer: '
            )

        # No outer retry: the transport (ApiGenerator._chat) already does
        # exponential backoff; a second layer here would multiply attempts.
        # prefix_hint: per-choice prompts of one question share the same
        # retrieval context + stem — batching them adjacently lets a
        # prefix-caching server prefill the stem once.
        response = self.client.generate(
            prompt, timeout=600, prefix_hint=question_hash(question)
        )
        return {'answer': response, 'retrieval': retrieval_log, 'prompt': prompt}


# ----------------------------------------------------------------- metrics
def retrieval_metrics(results: dict[int, dict[str, Any]]) -> dict[str, float]:
    """Source-chunk-retrieved and question-hash-retrieved rates
    (``v3:504-647``): among questions that carry source ``chunk_id`` /
    ``question_hash`` metadata, how often retrieval surfaced them."""
    chunk_hits = chunk_total = 0
    hash_hits = hash_total = 0
    # Hash matching is meaningful only when the *corpus* carries
    # question-hash metadata (chunks from question-generation pipelines,
    # v3:594-641) — decided globally, so a question whose retrieval came
    # back empty still counts as a miss rather than dropping out of the
    # denominator (which would inflate the rate). A total retrieval miss
    # would hide the hash evidence, so hash-annotated *questions* also mark
    # the metric applicable — then a zero-retrieval run reports 0.0 instead
    # of silently omitting the metric.
    hashes_in_corpus = any(
        'question_hash' in r
        for result in results.values()
        for r in result.get('retrieval', [])
    ) or any(
        'question_hash' in result.get('entry', {})
        for result in results.values()
    )
    for result in results.values():
        question = result.get('entry', {})
        retrieved = result.get('retrieval', [])
        source = question.get('chunk_id')
        if source:
            chunk_total += 1
            chunk_hits += any(r['chunk_id'] == source for r in retrieved)
        if hashes_in_corpus:
            qhash = question.get('question_hash') or question_hash(
                question.get('question', '')
            )
            hash_total += 1
            hash_hits += any(
                r.get('question_hash') == qhash for r in retrieved
            )
    metrics = {}
    if chunk_total:
        metrics['source_chunk_retrieved_rate'] = chunk_hits / chunk_total
    if hash_total:
        metrics['question_hash_retrieved_rate'] = hash_hits / hash_total
    return metrics


# -------------------------------------------------------------------- main
def run_mcqa(config: MCQAConfig) -> dict[str, Any]:
    config.output_dir.mkdir(parents=True, exist_ok=True)
    config.write_yaml(config.output_dir / 'config.yaml')  # audit copy
    questions = load_questions(config.questions_file)

    # Optional local engine-server boot.
    server = None
    model_base, model_key, model_name = config.resolve_model_endpoint()
    if config.local_model_path:
        from distllm_tpu.mcqa.server_boot import LocalServerManager

        server = LocalServerManager(
            config.local_model_path,
            log_dir=config.output_dir / 'server_logs',
            engine_args={
                'max_model_len': config.vllm_args.max_model_len,
                'max_num_seqs': config.vllm_args.max_num_seqs,
                'block_size': config.vllm_args.block_size,
                'num_blocks': config.vllm_args.num_blocks,
                'tensor_parallel_size': config.vllm_args.tensor_parallel_size,
            },
        )
        server.start()
        model_base, model_key = server.base_url, ''

    from distllm_tpu.generate.generators.api_backend import (
        ApiGenerator,
        ApiGeneratorConfig,
    )

    model_client = ApiGenerator(
        ApiGeneratorConfig(
            provider='openai',
            openai_api_base=model_base,
            model=model_name,
            api_key=model_key,
            temperature=config.request_temperature,
            max_tokens=config.request_max_tokens,
        )
    )
    batcher = BatchingClient(
        model_client.generate,
        batch_size=config.batch_size,
        batch_timeout=config.batch_timeout,
    )
    answerer = RagAnswerer(config, batcher)

    grader_base, grader_key, grader_model = config.resolve_grader_endpoint()
    grader_client = ApiGenerator(
        ApiGeneratorConfig(
            provider='openai',
            openai_api_base=grader_base,
            model=grader_model,
            api_key=grader_key,
            temperature=config.grader_temperature,
            max_tokens=config.grader_max_new_tokens,
        )
    )

    checkpoints = CheckpointManager(
        config.output_dir / 'checkpoints',
        metadata={
            'model': model_name,
            'questions_file': str(config.questions_file),
        },
        every=config.checkpoint_every,
        save_incremental=config.save_incremental,
    )
    if config.resume:
        checkpoints.try_resume()
    todo = [
        i for i in range(len(questions))
        if i not in checkpoints.completed_indices
    ]
    log_event(
        f'[mcqa] {len(todo)}/{len(questions)} questions to process',
        component='mcqa',
    )

    progress = _progress(len(todo))
    start_time = time.perf_counter()

    def process_question(index: int) -> None:
        entry = questions[index]
        generated = answerer.answer(entry['question'])
        verdict = grade_answer(
            lambda p: grader_client.generate([p])[0],
            question=entry['question'],
            reference=entry['answer'],
            answer=generated['answer'],
        )
        checkpoints.record(
            index,
            {
                'entry': entry,
                'answer': generated['answer'],
                'retrieval': generated['retrieval'],
                'correct': verdict['correct'],
                'grader_reason': verdict.get('reason', ''),
                'grader_ladder_level': verdict.get('ladder_level', 0),
            },
        )
        progress.update(1)

    errors: list[tuple[int, str]] = []
    # Stall watchdog over question completions: a wedged model server or a
    # deadlocked batcher shows up as zero progress, and the dumped bundle
    # (flight ring + metrics + traces in output_dir/debug_bundle) explains
    # the wedge even if the run is later killed. DISTLLM_MCQA_WATCHDOG_S=0
    # disables; the dog never kills the run itself.
    watchdog_s = float(os.environ.get('DISTLLM_MCQA_WATCHDOG_S', '900') or 0)
    watchdog = None
    if todo and watchdog_s > 0:
        watchdog = StallWatchdog(
            watchdog_s,
            progress_fn=lambda: len(checkpoints.completed_indices),
            bundle_dir=config.output_dir / 'debug_bundle',
            name='mcqa',
        ).start()
    try:
        with ThreadPoolExecutor(max_workers=config.parallel_workers) as pool:
            futures = {pool.submit(process_question, i): i for i in todo}
            for future in as_completed(futures):
                index = futures[future]
                try:
                    future.result()
                except Exception as exc:  # noqa: BLE001 - recorded + reported
                    errors.append((index, repr(exc)))
    finally:
        if watchdog is not None:
            watchdog.stop()
        progress.close()
        batcher.close()
        if server is not None:
            server.stop()
        checkpoints.save()

    elapsed = time.perf_counter() - start_time
    results = checkpoints.results
    graded = [r for r in results.values() if 'correct' in r]
    correct = sum(bool(r['correct']) for r in graded)
    summary: dict[str, Any] = {
        'total_questions': len(questions),
        'graded': len(graded),
        'correct': correct,
        'accuracy': correct / len(graded) if graded else 0.0,
        'errors': errors,
        'elapsed_s': elapsed,
        'throughput_qps': len(todo) / elapsed if elapsed > 0 else 0.0,
        'batches_sent': batcher.batches_sent,
        **retrieval_metrics(results),
        'model': model_name,
        'questions_file': str(config.questions_file),
    }
    (config.output_dir / 'results.json').write_text(
        json.dumps(
            {'summary': summary, 'results': {str(k): v for k, v in results.items()}},
            indent=2,
        )
    )
    # Incorrect-answer export (``v3:3620-3750``).
    incorrect = [
        {'index': k, **v} for k, v in results.items() if not v.get('correct', True)
    ]
    (config.output_dir / 'incorrect_answers.json').write_text(
        json.dumps(incorrect, indent=2)
    )
    log_event(
        f'[mcqa] accuracy={summary["accuracy"]:.3f} ({correct}/{len(graded)})',
        component='mcqa',
    )
    return summary


def main(argv: list[str] | None = None) -> int:
    from distllm_tpu.utils import apply_platform_env

    apply_platform_env()
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--config', required=True, type=Path)
    args = parser.parse_args(argv)
    run_mcqa(MCQAConfig.from_yaml(args.config))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
