"""MCQA checkpoint/resume.

Reference parity: ``rag_argonium_score_parallel_v3.py:2891-3073`` — JSON
checkpoints ``{timestamp, completed_indices, results, metadata, config,
version}`` saved every N questions (or per question in ultra-safe mode),
auto-resume from the latest compatible checkpoint (model + questions-file
validation), thread-safe progress updates.
"""

from __future__ import annotations

import json
import threading
import time
from pathlib import Path
from typing import Any

from distllm_tpu.observability.instruments import log_event

CHECKPOINT_VERSION = 1


class CheckpointManager:
    def __init__(
        self,
        checkpoint_dir: str | Path,
        metadata: dict[str, Any],
        every: int = 10,
        save_incremental: bool = False,
    ) -> None:
        self.checkpoint_dir = Path(checkpoint_dir)
        self.checkpoint_dir.mkdir(parents=True, exist_ok=True)
        self.metadata = metadata
        self.every = max(1, every)
        self.save_incremental = save_incremental
        self._lock = threading.Lock()
        self.results: dict[int, dict[str, Any]] = {}  # guarded by self._lock
        self._since_save = 0  # guarded by self._lock

    # ---------------------------------------------------------------- save
    def record(self, index: int, result: dict[str, Any]) -> None:
        """Thread-safe progress update with periodic checkpointing
        (``update_progress_with_checkpointing``, ``v3:3459-3511``)."""
        with self._lock:
            self.results[index] = result
            self._since_save += 1
            if self.save_incremental or self._since_save >= self.every:
                self._save_locked()
                self._since_save = 0

    def save(self) -> Path:
        with self._lock:
            return self._save_locked()

    def _save_locked(self) -> Path:  # guarded by self._lock
        payload = {
            'version': CHECKPOINT_VERSION,
            'timestamp': time.time(),
            'completed_indices': sorted(self.results),
            'results': {str(k): v for k, v in self.results.items()},
            'metadata': self.metadata,
        }
        path = self.checkpoint_dir / f'checkpoint_{int(time.time()*1000)}.json'
        tmp = path.with_suffix('.tmp')
        tmp.write_text(json.dumps(payload))
        tmp.rename(path)
        # Keep only the 3 newest checkpoints.
        checkpoints = sorted(self.checkpoint_dir.glob('checkpoint_*.json'))
        for old in checkpoints[:-3]:
            old.unlink(missing_ok=True)
        return path

    # --------------------------------------------------------------- resume
    @staticmethod
    def find_latest(checkpoint_dir: str | Path) -> Path | None:
        checkpoints = sorted(Path(checkpoint_dir).glob('checkpoint_*.json'))
        return checkpoints[-1] if checkpoints else None

    def try_resume(self) -> int:
        """Load the newest compatible checkpoint; returns #completed.

        Falls back through the retained checkpoints (newest → oldest) so a
        corrupt or incompatible newest file doesn't discard the older valid
        ones.
        """
        candidates = sorted(
            self.checkpoint_dir.glob('checkpoint_*.json'), reverse=True
        )
        for path in candidates:
            results = self._load_compatible(path)
            if results is not None:
                with self._lock:
                    self.results = results
                log_event(
                    f'[checkpoint] resumed {len(results)} results '
                    f'from {path.name}',
                    component='checkpoint',
                )
                return len(results)
        return 0

    def _load_compatible(self, path: Path) -> dict[int, dict[str, Any]] | None:
        try:
            payload = json.loads(path.read_text())
        except (json.JSONDecodeError, OSError):
            log_event(f'[checkpoint] ignoring corrupt {path}', component='checkpoint')
            return None
        if payload.get('version') != CHECKPOINT_VERSION:
            log_event(
                f'[checkpoint] version mismatch in {path}; ignoring',
                component='checkpoint',
            )
            return None
        meta = payload.get('metadata', {})
        for key in ('model', 'questions_file'):
            if key in self.metadata and meta.get(key) != self.metadata[key]:
                log_event(
                    f'[checkpoint] {key} mismatch in {path.name} '
                    f'({meta.get(key)!r} != {self.metadata[key]!r}); ignoring',
                    component='checkpoint',
                )
                return None
        return {int(k): v for k, v in payload.get('results', {}).items()}

    @property
    def completed_indices(self) -> set[int]:
        with self._lock:
            return set(self.results)
