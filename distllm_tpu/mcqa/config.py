"""MCQA harness configuration.

Reference parity: ``MCQAConfig`` (``rag_argonium_score_parallel_v3.py:401-445``)
and the ``model_servers.yaml`` shortname registry (``v3:716-751``).
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

import yaml
from pydantic import Field

from distllm_tpu.utils import BaseConfig


class ModelServerEntry(BaseConfig):
    """One row of the model-servers registry."""

    server: str = ''
    shortname: str
    openai_api_key: str = ''
    openai_api_base: str = ''
    openai_model: str = ''


def load_model_servers(path: str | Path) -> dict[str, ModelServerEntry]:
    """Read a ``model_servers.yaml`` into a shortname-keyed registry."""
    with open(path) as fh:
        raw = yaml.safe_load(fh) or {}
    entries = raw.get('servers', raw) if isinstance(raw, dict) else raw
    if isinstance(entries, dict):
        entries = list(entries.values())
    registry = {}
    for item in entries:
        entry = ModelServerEntry(**item)
        registry[entry.shortname] = entry
    return registry


class VllmArgs(BaseConfig):
    """Engine knobs for the locally booted server (vLLM-arg parity)."""

    tensor_parallel_size: int = 1
    max_model_len: int = 4096
    max_num_seqs: int = 16
    block_size: int = 16
    num_blocks: int = 2048


class MCQAConfig(BaseConfig):
    questions_file: Path
    output_dir: Path = Path('mcqa_results')

    # Model under test: either a registry shortname, an explicit endpoint,
    # or a local checkpoint to boot a server for.
    model_servers_file: Path | None = None
    model_shortname: str = ''
    model_api_base: str = ''
    model_api_key: str = ''
    model_name: str = 'distllm-tpu'
    local_model_path: str = ''  # non-empty => boot a local engine server
    vllm_args: VllmArgs = VllmArgs()

    # Grader LLM.
    grader_shortname: str = ''
    grader_api_base: str = ''
    grader_api_key: str = ''
    grader_model: str = ''
    grader_max_new_tokens: int = 64
    grader_temperature: float = 0.0

    # RAG (optional).
    retriever_config: dict[str, Any] | None = None
    retrieval_top_k: int = 5
    retrieval_score_threshold: float = 0.0

    # Parallelism + client batching.
    parallel_workers: int = 8
    batch_size: int = 16
    batch_timeout: float = 0.5
    request_temperature: float = 0.0
    request_max_tokens: int = 256

    # Checkpointing.
    checkpoint_every: int = Field(
        default=10, description='Save a checkpoint every N questions.'
    )
    save_incremental: bool = Field(
        default=False, description='Ultra-safe per-question checkpointing.'
    )
    resume: bool = True

    def resolve_model_endpoint(self) -> tuple[str, str, str]:
        """Returns (api_base, api_key, model) for the model under test."""
        if self.model_shortname and self.model_servers_file:
            entry = load_model_servers(self.model_servers_file)[
                self.model_shortname
            ]
            return entry.openai_api_base, entry.openai_api_key, entry.openai_model
        return self.model_api_base, self.model_api_key, self.model_name

    def resolve_grader_endpoint(self) -> tuple[str, str, str]:
        if self.grader_shortname and self.model_servers_file:
            entry = load_model_servers(self.model_servers_file)[
                self.grader_shortname
            ]
            return entry.openai_api_base, entry.openai_api_key, entry.openai_model
        return self.grader_api_base, self.grader_api_key, self.grader_model
