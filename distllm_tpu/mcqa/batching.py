"""Client-side request batching for the MCQA harness.

Reference parity: ``rag_argonium_score_parallel_v3.py:1407-1605`` — worker
threads enqueue single requests; a background batch thread collects up to
``batch_size`` requests (or whatever arrived within ``batch_timeout``
seconds) and ships them to the OpenAI-compatible endpoint together, feeding
the server's continuous-batching engine properly instead of dribbling one
request per HTTP call.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable


@dataclass
class _Pending:
    prompt: str
    # Requests sharing a prefix_hint (e.g. one MCQA question's stem, sent
    # once per answer choice) are kept ADJACENT within a batch so the
    # server engine's automatic prefix cache (docs/prefix_caching.md) sees
    # the shared stem back-to-back and reuses its KV blocks.
    prefix_hint: str = ''
    arrival: int = 0
    event: threading.Event = field(default_factory=threading.Event)
    result: str | None = None
    error: Exception | None = None
    abandoned: bool = False


class BatchingClient:
    """Queue + condition-variable batcher in front of a generate function.

    ``send_batch(prompts) -> responses`` is the transport (HTTP client or
    in-process generator); callers use :meth:`generate` from any thread.
    """

    def __init__(
        self,
        send_batch: Callable[[list[str]], list[str]],
        batch_size: int = 16,
        batch_timeout: float = 0.5,
    ) -> None:
        self._send_batch = send_batch
        self.batch_size = batch_size
        self.batch_timeout = batch_timeout
        self._queue: list[_Pending] = []
        self._arrivals = 0
        self._cond = threading.Condition()
        self._closed = False
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        self.batches_sent = 0
        self.requests_sent = 0

    def generate(
        self,
        prompt: str,
        timeout: float | None = None,
        prefix_hint: str = '',
    ) -> str:
        """``prefix_hint`` marks prompts that share a cacheable prefix
        (same hint = same stem): hinted prompts are grouped adjacently
        within each batch so a prefix-caching server reuses their KV."""
        pending = _Pending(prompt, prefix_hint=prefix_hint)
        with self._cond:
            if self._closed:
                raise RuntimeError('BatchingClient is closed')
            pending.arrival = self._arrivals
            self._arrivals += 1
            self._queue.append(pending)
            self._cond.notify()
        if not pending.event.wait(timeout):
            # Drop the stale entry so a retry doesn't duplicate load on an
            # already-slow backend (if still queued, remove; if in flight,
            # mark so its late result is discarded).
            with self._cond:
                pending.abandoned = True
                if pending in self._queue:
                    self._queue.remove(pending)
            raise TimeoutError('batched request timed out')
        if pending.error is not None:
            raise pending.error
        return pending.result

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                if self._closed and not self._queue:
                    return
                # Collect until full or batch_timeout after the first arrival
                # (a fixed per-batch deadline, not a rolling quiet period —
                # steady sub-timeout arrivals must not starve the batch).
                deadline = time.monotonic() + self.batch_timeout
                while len(self._queue) < self.batch_size and not self._closed:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
                batch = [
                    p for p in self._queue[: self.batch_size] if not p.abandoned
                ]
                del self._queue[: self.batch_size]
                if not batch:
                    continue
                # Group shared-stem prompts adjacently (stable on arrival
                # order, un-hinted prompts keep their relative ordering).
                # The engine's prefix match runs at request-add time, so
                # prompts inside ONE server batch all miss a brand-new
                # stem; adjacency makes same-stem prompts land in the same
                # or consecutive server batches, so every batch after the
                # stem's first prefill hits the cache — and keeps the
                # stem's blocks hot (most-recently-used) against eviction.
                if any(p.prefix_hint for p in batch):
                    batch.sort(key=lambda p: (p.prefix_hint, p.arrival))
            self._dispatch(batch)
            self.batches_sent += 1
            self.requests_sent += len(batch)

    def _dispatch(self, batch: list[_Pending], depth: int = 0) -> bool:
        try:
            responses = self._send_batch([p.prompt for p in batch])
            if len(responses) != len(batch):
                raise RuntimeError(
                    f'send_batch returned {len(responses)} responses for '
                    f'{len(batch)} prompts'
                )
            for pending, response in zip(batch, responses):
                pending.result = response
                pending.event.set()
            return True
        except Exception as exc:  # noqa: BLE001 - delivered to callers
            if len(batch) > 1 and depth < 2:
                # Isolate the failure: retry prompts alone so one poison
                # prompt doesn't error the healthy ones. If two retries fail
                # back-to-back, stop serializing backoff ladders — but a
                # failing pair may just be adjacent poison prompts, so the
                # UNTRIED remainder gets one batch-level retry (bounded by
                # ``depth``) instead of inheriting another prompt's error.
                # Backend-down worst case: ~2 batch sends + 4 single sends.
                consecutive = 0
                for i, pending in enumerate(batch):
                    if pending.abandoned:
                        # Caller already timed out; don't burn a transport
                        # backoff ladder on a result nobody will read.
                        continue
                    if consecutive >= 2:
                        remainder = [p for p in batch[i:] if not p.abandoned]
                        self._dispatch(remainder, depth + 1)
                        return False
                    if self._dispatch([pending], depth + 1):
                        consecutive = 0
                    else:
                        consecutive += 1
                return False
            for pending in batch:
                pending.error = exc
                pending.event.set()
            return False

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
