"""Nanosecond-precision timers with parseable log lines.

Behavioral parity target: ``distllm/timer.py:36-163`` — a ``Timer`` context
manager that prints one machine-parseable line per timed span to stdout, and a
``TimeLogger`` that recovers structured stats from captured logs. Workers time
every pipeline stage with these, and the lines are the primary telemetry
channel across the process/node boundary (they survive in scheduler logs).

``Timer`` is now a shim over :mod:`distllm_tpu.observability`: each stop
emits BOTH the legacy ``[timer]`` line below (so ``TimeLogger.parse_logs``
and every existing log-scraping tool keep working) and a
:class:`~distllm_tpu.observability.tracing.Span` into the process trace
ring, tagged ``ok``/``error`` by how the timed block exited, plus a
``distllm_stage_duration_seconds`` histogram observation.

Line format (one line per completed span)::

    [timer] tags=load-encoder,file-3 elapsed_s=1.234567890 start_ns=... end_ns=...
"""

from __future__ import annotations

import math
import re
import time
from dataclasses import dataclass, field
from pathlib import Path

from distllm_tpu.observability import instruments, tracing

_LINE_RE = re.compile(
    r'\[timer\] tags=(?P<tags>\S*) '
    r'elapsed_s=(?P<elapsed>[0-9.eE+-]+) '
    r'start_ns=(?P<start>\d+) end_ns=(?P<end>\d+)'
)


@dataclass
class TimeStats:
    """Aggregated statistics for one tag set."""

    tags: tuple[str, ...]
    elapsed_s: list[float] = field(default_factory=list)
    start_ns: list[int] = field(default_factory=list)
    end_ns: list[int] = field(default_factory=list)

    @property
    def total_s(self) -> float:
        return sum(self.elapsed_s)

    @property
    def mean_s(self) -> float:
        return self.total_s / len(self.elapsed_s) if self.elapsed_s else 0.0

    @property
    def count(self) -> int:
        return len(self.elapsed_s)

    def _percentile(self, q: float) -> float:
        """Nearest-rank percentile (0.0 on empty stats, like ``mean_s``)."""
        if not self.elapsed_s:
            return 0.0
        ordered = sorted(self.elapsed_s)
        rank = max(1, math.ceil(q * len(ordered)))
        return ordered[rank - 1]

    @property
    def p50_s(self) -> float:
        return self._percentile(0.50)

    @property
    def p95_s(self) -> float:
        return self._percentile(0.95)

    @property
    def p99_s(self) -> float:
        return self._percentile(0.99)

    @property
    def max_s(self) -> float:
        return max(self.elapsed_s) if self.elapsed_s else 0.0


class Timer:
    """Context manager that times a span and prints a parseable line.

    >>> with Timer('load-encoder', 'file-3'):
    ...     do_work()
    """

    def __init__(self, *tags: str, echo: bool = True) -> None:
        self.tags = tuple(str(t) for t in tags)
        self.echo = echo
        self.start_ns: int | None = None
        self.end_ns: int | None = None
        self.status: str | None = None
        self._span: tracing.Span | None = None

    @property
    def elapsed_s(self) -> float:
        if self.start_ns is None:
            raise RuntimeError(
                'Timer.elapsed_s read before start() — a never-started '
                'timer has no elapsed time'
            )
        end = self.end_ns if self.end_ns is not None else time.monotonic_ns()
        return (end - self.start_ns) / 1e9

    def start(self) -> 'Timer':
        if self._span is not None:  # restart without stop(): drop stale span
            tracing.abandon_span(self._span)
        self._span = tracing.begin_span(
            self.tags[0] if self.tags else 'timer', *self.tags
        )
        self.start_ns = self._span.start_ns
        self.end_ns = None
        self.status = None
        return self

    def stop(self, status: str | None = None,
             error: BaseException | None = None) -> float:
        if self.start_ns is None or self._span is None:
            raise RuntimeError('Timer.stop() called before start()')
        self.status = status or 'ok'
        finished = tracing.end_span(self._span, status=self.status, error=error)
        self.end_ns = finished.end_ns
        self._span = None
        instruments.STAGE_SECONDS.labels(
            stage=self.tags[0] if self.tags else 'untagged',
            status=self.status,
        ).observe(self.elapsed_s)
        if self.echo:
            print(self.log_line(), flush=True)
        return self.elapsed_s

    def log_line(self) -> str:
        return (
            f'[timer] tags={",".join(self.tags)} '
            f'elapsed_s={self.elapsed_s:.9f} '
            f'start_ns={self.start_ns} end_ns={self.end_ns}'
        )

    def __enter__(self) -> 'Timer':
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        # The legacy line is printed either way (log scrapers expect every
        # span); only the span record distinguishes failed work.
        self.stop(
            status='error' if exc_type is not None else 'ok',
            error=exc if isinstance(exc, BaseException) else None,
        )


class TimeLogger:
    """Parse ``[timer]`` lines from captured stdout/log files back to stats.

    Parity with ``TimeLogger.parse_logs`` (``distllm/timer.py:129-154``).
    Multi-file/multi-host rollups live in
    ``distllm_tpu.observability.aggregate``.
    """

    def parse_lines(self, lines: list[str] | str) -> dict[tuple[str, ...], TimeStats]:
        if isinstance(lines, str):
            lines = lines.splitlines()
        stats: dict[tuple[str, ...], TimeStats] = {}
        for line in lines:
            m = _LINE_RE.search(line)
            if not m:
                continue
            tags = tuple(t for t in m.group('tags').split(',') if t)
            entry = stats.setdefault(tags, TimeStats(tags=tags))
            entry.elapsed_s.append(float(m.group('elapsed')))
            entry.start_ns.append(int(m.group('start')))
            entry.end_ns.append(int(m.group('end')))
        return stats

    def parse_logs(self, path: str | Path) -> dict[tuple[str, ...], TimeStats]:
        return self.parse_lines(Path(path).read_text().splitlines())
