"""Nanosecond-precision timers with parseable log lines.

Behavioral parity target: ``distllm/timer.py:36-163`` — a ``Timer`` context
manager that prints one machine-parseable line per timed span to stdout, and a
``TimeLogger`` that recovers structured stats from captured logs. Workers time
every pipeline stage with these, and the lines are the primary telemetry
channel across the process/node boundary (they survive in scheduler logs).

Line format (one line per completed span)::

    [timer] tags=load-encoder,file-3 elapsed_s=1.234567890 start_ns=... end_ns=...
"""

from __future__ import annotations

import re
import time
from dataclasses import dataclass, field
from pathlib import Path

_LINE_RE = re.compile(
    r'\[timer\] tags=(?P<tags>\S*) '
    r'elapsed_s=(?P<elapsed>[0-9.eE+-]+) '
    r'start_ns=(?P<start>\d+) end_ns=(?P<end>\d+)'
)


@dataclass
class TimeStats:
    """Aggregated statistics for one tag set."""

    tags: tuple[str, ...]
    elapsed_s: list[float] = field(default_factory=list)
    start_ns: list[int] = field(default_factory=list)
    end_ns: list[int] = field(default_factory=list)

    @property
    def total_s(self) -> float:
        return sum(self.elapsed_s)

    @property
    def mean_s(self) -> float:
        return self.total_s / len(self.elapsed_s) if self.elapsed_s else 0.0

    @property
    def count(self) -> int:
        return len(self.elapsed_s)


class Timer:
    """Context manager that times a span and prints a parseable line.

    >>> with Timer('load-encoder', 'file-3'):
    ...     do_work()
    """

    def __init__(self, *tags: str, echo: bool = True) -> None:
        self.tags = tuple(str(t) for t in tags)
        self.echo = echo
        self.start_ns: int | None = None
        self.end_ns: int | None = None

    @property
    def elapsed_s(self) -> float:
        if self.start_ns is None:
            return 0.0
        end = self.end_ns if self.end_ns is not None else time.monotonic_ns()
        return (end - self.start_ns) / 1e9

    def start(self) -> 'Timer':
        self.start_ns = time.monotonic_ns()
        self.end_ns = None
        return self

    def stop(self) -> float:
        if self.start_ns is None:
            raise RuntimeError('Timer.stop() called before start()')
        self.end_ns = time.monotonic_ns()
        if self.echo:
            print(self.log_line(), flush=True)
        return self.elapsed_s

    def log_line(self) -> str:
        return (
            f'[timer] tags={",".join(self.tags)} '
            f'elapsed_s={self.elapsed_s:.9f} '
            f'start_ns={self.start_ns} end_ns={self.end_ns}'
        )

    def __enter__(self) -> 'Timer':
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class TimeLogger:
    """Parse ``[timer]`` lines from captured stdout/log files back to stats.

    Parity with ``TimeLogger.parse_logs`` (``distllm/timer.py:129-154``).
    """

    def parse_lines(self, lines: list[str] | str) -> dict[tuple[str, ...], TimeStats]:
        if isinstance(lines, str):
            lines = lines.splitlines()
        stats: dict[tuple[str, ...], TimeStats] = {}
        for line in lines:
            m = _LINE_RE.search(line)
            if not m:
                continue
            tags = tuple(t for t in m.group('tags').split(',') if t)
            entry = stats.setdefault(tags, TimeStats(tags=tags))
            entry.elapsed_s.append(float(m.group('elapsed')))
            entry.start_ns.append(int(m.group('start')))
            entry.end_ns.append(int(m.group('end')))
        return stats

    def parse_logs(self, path: str | Path) -> dict[tuple[str, ...], TimeStats]:
        return self.parse_lines(Path(path).read_text().splitlines())
