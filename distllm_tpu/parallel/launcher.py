"""Compute-platform configs: how workers are provisioned on each platform.

Reference parity: ``distllm/parsl.py`` — ``BaseComputeConfig.get_config``
returning a Parsl config for Local / Workstation / Polaris(PBS) /
Leonardo(Slurm). Here the analogue is ``get_executor(run_dir)`` returning an
object with ``.map(fn, items)``:

- :class:`LocalConfig` — in-process serial executor ("mainly for testing",
  ``parsl.py:49-73``); identical worker code path as the pod.
- :class:`WorkstationConfig` — multiprocessing pool on one machine. On TPU a
  host's chips belong to ONE JAX process (mesh-level parallelism inside),
  unlike the reference's one-process-per-GPU, so ``max_workers`` defaults
  to 1 and is only raised for CPU-bound pipelines (tokenization).
- :class:`PodConfig` — ZMQ fabric coordinator for multi-host TPU pods; hosts
  run ``python -m distllm_tpu.parallel.worker``. PBS/Slurm submission stays
  outside (the scheduler script launches one worker per host), matching how
  the reference's MpiExecLauncher starts one manager per node.
"""

from __future__ import annotations

import multiprocessing as mp
from pathlib import Path
from typing import Any, Callable, Iterable, Literal, Union

from pydantic import Field

from distllm_tpu.observability.instruments import log_event
from distllm_tpu.utils import BaseConfig


class SerialExecutor:
    """Run tasks inline — the Local platform and the unit-test stand-in."""

    def map(self, fn: Callable, items: Iterable[Any]) -> list[Any]:
        return [fn(item) for item in items]


class ProcessPoolMapExecutor:
    """Spawn-based process pool for CPU-bound per-file work."""

    def __init__(self, max_workers: int) -> None:
        self.max_workers = max_workers

    def map(self, fn: Callable, items: Iterable[Any]) -> list[Any]:
        items = list(items)
        if self.max_workers <= 1 or len(items) <= 1:
            return [fn(item) for item in items]
        ctx = mp.get_context('spawn')
        with ctx.Pool(processes=self.max_workers) as pool:
            return pool.map(fn, items)


class LocalConfig(BaseConfig):
    """Single in-process worker (testing / single host)."""

    name: Literal['local'] = 'local'

    def get_executor(self, run_dir: str | Path) -> SerialExecutor:
        Path(run_dir).mkdir(parents=True, exist_ok=True)
        return SerialExecutor()


class WorkstationConfig(BaseConfig):
    """Single machine, optional process pool (CPU-bound stages only)."""

    name: Literal['workstation'] = 'workstation'
    max_workers: int = Field(
        default=1,
        description='Worker processes. Keep 1 for TPU compute (one JAX '
        'process owns the chips); raise for CPU-only pipelines.',
    )

    def get_executor(self, run_dir: str | Path) -> ProcessPoolMapExecutor:
        Path(run_dir).mkdir(parents=True, exist_ok=True)
        return ProcessPoolMapExecutor(self.max_workers)


class PodConfig(BaseConfig):
    """Multi-host TPU pod via the ZMQ fabric.

    The coordinator binds ``bind_address`` and advertises
    ``tcp://<advertise_host>:<port>`` (hostname by default) — workers on
    other hosts pass that advertised endpoint to
    ``python -m distllm_tpu.parallel.worker --coordinator ...``.
    ``retries``/``heartbeat_threshold`` mirror the reference's Parsl retry +
    heartbeat settings (``parsl.py:197,216-217``).
    """

    name: Literal['pod'] = 'pod'
    bind_address: str = 'tcp://*:5555'
    advertise_host: str | None = Field(
        default=None,
        description='Routable address workers should dial; defaults to '
        'this hostname.',
    )
    retries: int = 1
    heartbeat_threshold: float = 120.0

    def get_executor(self, run_dir: str | Path):
        from distllm_tpu.parallel.fabric import Coordinator, ZmqPoolExecutor

        Path(run_dir).mkdir(parents=True, exist_ok=True)
        coordinator = Coordinator(
            bind=self.bind_address,
            retries=self.retries,
            heartbeat_threshold=self.heartbeat_threshold,
            advertise_host=self.advertise_host,
        )
        log_event(f'[fabric] coordinator at {coordinator.endpoint}', component='fabric')
        return ZmqPoolExecutor(coordinator)


class _BatchSchedulerConfig(BaseConfig):
    """Shared knobs for scheduler-submitted pods (reference: the PBSPro /
    Slurm providers in ``distllm/parsl.py:106-252`` — account, queue,
    walltime, worker_init, scheduler_options, retries, heartbeats).

    ``get_executor`` starts the ZMQ coordinator in THIS process (the
    reference's interchange also stays on the login node), renders a job
    script that boots one ``distllm_tpu.parallel.worker`` per pod host
    dialing back to it, and submits the script. ``submit=False`` renders
    without submitting (dry runs, CI).
    """

    account: str
    queue: str
    walltime: str = '01:00:00'
    num_nodes: int = 1
    worker_init: str = Field(
        default='',
        description='Shell run on each host before the worker starts '
        '(module loads, venv activation, TPU env vars).',
    )
    scheduler_options: str = Field(
        default='',
        description='Extra verbatim #PBS/#SBATCH directive lines.',
    )
    coordinator_port: int = 5555
    advertise_host: str | None = None
    retries: int = 1
    heartbeat_threshold: float = 120.0
    submit: bool = True
    jax_distributed: bool = Field(
        default=False,
        description='Join every pod host into ONE global JAX runtime '
        '(multi-host mesh over DCN) instead of independent per-host '
        'processes; the job script exports DISTLLM_JAX_* and the worker '
        'calls jax.distributed.initialize (parallel/multihost.py).',
    )
    jax_coordinator_port: int = Field(
        default=8476,
        description='Port the first pod host serves the JAX coordination '
        'service on (jax_distributed only).',
    )

    def _worker_command(self, endpoint: str) -> str:
        cmd = (
            'python -m distllm_tpu.parallel.worker '
            f'--coordinator {endpoint}'
        )
        if self.jax_distributed:
            cmd += ' --jax-distributed'
        return cmd

    def render_script(self, endpoint: str, run_dir: Path) -> str:
        raise NotImplementedError

    def _submit_command(self, script_path: Path) -> list[str]:
        raise NotImplementedError

    @property
    def _script_name(self) -> str:
        raise NotImplementedError

    def get_executor(self, run_dir: str | Path):
        import subprocess

        from distllm_tpu.parallel.fabric import Coordinator, ZmqPoolExecutor

        run_dir = Path(run_dir)
        run_dir.mkdir(parents=True, exist_ok=True)
        coordinator = Coordinator(
            bind=f'tcp://*:{self.coordinator_port}',
            retries=self.retries,
            heartbeat_threshold=self.heartbeat_threshold,
            advertise_host=self.advertise_host,
        )
        script = self.render_script(coordinator.endpoint, run_dir)
        script_path = run_dir / self._script_name
        script_path.write_text(script)
        log_event(f'[fabric] coordinator at {coordinator.endpoint}', component='fabric')
        if self.submit:
            proc = subprocess.run(
                self._submit_command(script_path),
                capture_output=True,
                text=True,
            )
            if proc.returncode != 0:
                raise RuntimeError(
                    f'job submission failed ({proc.returncode}): '
                    f'{proc.stderr.strip()[-500:]}'
                )
            log_event(f'[fabric] submitted job: {proc.stdout.strip()}', component='fabric')
        return ZmqPoolExecutor(coordinator)


class TpuPodPbsConfig(_BatchSchedulerConfig):
    """PBSPro-submitted TPU pod (the Polaris analogue, ref
    ``parsl.py:106-180``): one fabric worker per pod host via mpiexec."""

    name: Literal['pbspro'] = 'pbspro'
    select: str = Field(
        default='',
        description='Extra -l select resource suffix, e.g. '
        '":tpu_accelerator=v5e"; rendered as select=<num_nodes><select>.',
    )

    @property
    def _script_name(self) -> str:
        return 'submit.pbs'

    def _submit_command(self, script_path: Path) -> list[str]:
        return ['qsub', str(script_path)]

    def render_script(self, endpoint: str, run_dir: Path) -> str:
        lines = [
            '#!/bin/bash',
            f'#PBS -A {self.account}',
            f'#PBS -q {self.queue}',
            f'#PBS -l walltime={self.walltime}',
            f'#PBS -l select={self.num_nodes}{self.select}',
            f'#PBS -o {run_dir}/pbs.out',
            f'#PBS -e {run_dir}/pbs.err',
        ]
        if self.scheduler_options:
            lines.extend(self.scheduler_options.splitlines())
        lines += ['', self.worker_init, '']
        if self.jax_distributed:
            lines += [
                '# Global JAX runtime: first pod host runs the coordination',
                '# service; per-rank process id comes from PMI_RANK/',
                '# PALS_RANKID (read by parallel/multihost.py).',
                'export DISTLLM_JAX_COORDINATOR='
                f'"$(head -n1 "$PBS_NODEFILE"):{self.jax_coordinator_port}"',
                f'export DISTLLM_JAX_NUM_PROCESSES={self.num_nodes}',
                '',
            ]
        lines += [
            '# One fabric worker per pod host, dialing the coordinator.',
            f'mpiexec -n {self.num_nodes} --ppn 1 --envall '
            + self._worker_command(endpoint),
            '',
        ]
        return '\n'.join(lines)


class TpuPodSlurmConfig(_BatchSchedulerConfig):
    """Slurm-submitted TPU pod (the Leonardo analogue, ref
    ``parsl.py:183-252``): one fabric worker per pod host via srun."""

    name: Literal['slurm'] = 'slurm'
    partition: str = Field(
        default='',
        description='Slurm partition (falls back to queue when empty).',
    )
    qos: str = ''

    @property
    def _script_name(self) -> str:
        return 'submit.sbatch'

    def _submit_command(self, script_path: Path) -> list[str]:
        return ['sbatch', str(script_path)]

    def render_script(self, endpoint: str, run_dir: Path) -> str:
        lines = [
            '#!/bin/bash',
            f'#SBATCH --account={self.account}',
            f'#SBATCH --partition={self.partition or self.queue}',
            f'#SBATCH --time={self.walltime}',
            f'#SBATCH --nodes={self.num_nodes}',
            '#SBATCH --ntasks-per-node=1',
            f'#SBATCH --output={run_dir}/slurm.out',
            f'#SBATCH --error={run_dir}/slurm.err',
        ]
        if self.qos:
            lines.append(f'#SBATCH --qos={self.qos}')
        if self.scheduler_options:
            lines.extend(self.scheduler_options.splitlines())
        lines += ['', self.worker_init, '']
        if self.jax_distributed:
            lines += [
                '# Global JAX runtime: first pod host runs the coordination',
                '# service; per-rank process id comes from SLURM_PROCID',
                '# (read by parallel/multihost.py).',
                'export DISTLLM_JAX_COORDINATOR='
                '"$(scontrol show hostnames "$SLURM_JOB_NODELIST" '
                f'| head -n1):{self.jax_coordinator_port}"',
                f'export DISTLLM_JAX_NUM_PROCESSES={self.num_nodes}',
                '',
            ]
        lines += [
            '# One fabric worker per pod host, dialing the coordinator.',
            f'srun --ntasks={self.num_nodes} --ntasks-per-node=1 '
            + self._worker_command(endpoint),
            '',
        ]
        return '\n'.join(lines)


ComputeConfigs = Union[
    LocalConfig,
    WorkstationConfig,
    PodConfig,
    TpuPodPbsConfig,
    TpuPodSlurmConfig,
]


def get_compute_config(kwargs: dict[str, Any]) -> ComputeConfigs:
    name = kwargs.get('name', 'local')
    for cls in (
        LocalConfig,
        WorkstationConfig,
        PodConfig,
        TpuPodPbsConfig,
        TpuPodSlurmConfig,
    ):
        if name == cls.model_fields['name'].default:
            return cls(**kwargs)
    raise ValueError(f'Unknown compute config name: {name!r}')
