"""Compute-platform configs: how workers are provisioned on each platform.

Reference parity: ``distllm/parsl.py`` — ``BaseComputeConfig.get_config``
returning a Parsl config for Local / Workstation / Polaris(PBS) /
Leonardo(Slurm). Here the analogue is ``get_executor(run_dir)`` returning an
object with ``.map(fn, items)``:

- :class:`LocalConfig` — in-process serial executor ("mainly for testing",
  ``parsl.py:49-73``); identical worker code path as the pod.
- :class:`WorkstationConfig` — multiprocessing pool on one machine. On TPU a
  host's chips belong to ONE JAX process (mesh-level parallelism inside),
  unlike the reference's one-process-per-GPU, so ``max_workers`` defaults
  to 1 and is only raised for CPU-bound pipelines (tokenization).
- :class:`PodConfig` — ZMQ fabric coordinator for multi-host TPU pods; hosts
  run ``python -m distllm_tpu.parallel.worker``. PBS/Slurm submission stays
  outside (the scheduler script launches one worker per host), matching how
  the reference's MpiExecLauncher starts one manager per node.
"""

from __future__ import annotations

import multiprocessing as mp
from pathlib import Path
from typing import Any, Callable, Iterable, Literal, Union

from pydantic import Field

from distllm_tpu.utils import BaseConfig


class SerialExecutor:
    """Run tasks inline — the Local platform and the unit-test stand-in."""

    def map(self, fn: Callable, items: Iterable[Any]) -> list[Any]:
        return [fn(item) for item in items]


class ProcessPoolMapExecutor:
    """Spawn-based process pool for CPU-bound per-file work."""

    def __init__(self, max_workers: int) -> None:
        self.max_workers = max_workers

    def map(self, fn: Callable, items: Iterable[Any]) -> list[Any]:
        items = list(items)
        if self.max_workers <= 1 or len(items) <= 1:
            return [fn(item) for item in items]
        ctx = mp.get_context('spawn')
        with ctx.Pool(processes=self.max_workers) as pool:
            return pool.map(fn, items)


class LocalConfig(BaseConfig):
    """Single in-process worker (testing / single host)."""

    name: Literal['local'] = 'local'

    def get_executor(self, run_dir: str | Path) -> SerialExecutor:
        Path(run_dir).mkdir(parents=True, exist_ok=True)
        return SerialExecutor()


class WorkstationConfig(BaseConfig):
    """Single machine, optional process pool (CPU-bound stages only)."""

    name: Literal['workstation'] = 'workstation'
    max_workers: int = Field(
        default=1,
        description='Worker processes. Keep 1 for TPU compute (one JAX '
        'process owns the chips); raise for CPU-only pipelines.',
    )

    def get_executor(self, run_dir: str | Path) -> ProcessPoolMapExecutor:
        Path(run_dir).mkdir(parents=True, exist_ok=True)
        return ProcessPoolMapExecutor(self.max_workers)


class PodConfig(BaseConfig):
    """Multi-host TPU pod via the ZMQ fabric.

    The coordinator binds ``bind_address`` and advertises
    ``tcp://<advertise_host>:<port>`` (hostname by default) — workers on
    other hosts pass that advertised endpoint to
    ``python -m distllm_tpu.parallel.worker --coordinator ...``.
    ``retries``/``heartbeat_threshold`` mirror the reference's Parsl retry +
    heartbeat settings (``parsl.py:197,216-217``).
    """

    name: Literal['pod'] = 'pod'
    bind_address: str = 'tcp://*:5555'
    advertise_host: str | None = Field(
        default=None,
        description='Routable address workers should dial; defaults to '
        'this hostname.',
    )
    retries: int = 1
    heartbeat_threshold: float = 120.0

    def get_executor(self, run_dir: str | Path):
        from distllm_tpu.parallel.fabric import Coordinator, ZmqPoolExecutor

        Path(run_dir).mkdir(parents=True, exist_ok=True)
        coordinator = Coordinator(
            bind=self.bind_address,
            retries=self.retries,
            heartbeat_threshold=self.heartbeat_threshold,
            advertise_host=self.advertise_host,
        )
        print(f'[fabric] coordinator at {coordinator.endpoint}', flush=True)
        return ZmqPoolExecutor(coordinator)


ComputeConfigs = Union[LocalConfig, WorkstationConfig, PodConfig]


def get_compute_config(kwargs: dict[str, Any]) -> ComputeConfigs:
    name = kwargs.get('name', 'local')
    for cls in (LocalConfig, WorkstationConfig, PodConfig):
        if name == cls.model_fields['name'].default:
            return cls(**kwargs)
    raise ValueError(f'Unknown compute config name: {name!r}')
