"""Parallelism: device meshes, sharding rules, collectives, cross-host fabric.

This package is the TPU replacement for the reference's scale-out substrate
(Parsl HTEX + NCCL-inside-vLLM; SURVEY.md section 2.5): intra-slice parallelism
is expressed as ``jax.sharding`` over an explicit ``Mesh`` (XLA emits ICI
collectives), and cross-host fan-out is a file-sharded pool executor.
"""

from distllm_tpu.parallel.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    MODEL_AXIS,
    SEQ_AXIS,
    MeshSpec,
    make_mesh,
)
from distllm_tpu.parallel.sharding import (
    named_sharding,
    replicate,
    shard_pytree,
)

__all__ = [
    'DATA_AXIS',
    'MODEL_AXIS',
    'SEQ_AXIS',
    'EXPERT_AXIS',
    'MeshSpec',
    'make_mesh',
    'named_sharding',
    'replicate',
    'shard_pytree',
]
