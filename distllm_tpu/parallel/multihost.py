"""Multi-host JAX runtime initialization (the DCN control plane).

Reference parity: ``distllm/parsl.py:172-252`` — the reference's multi-node
substrate is Parsl HTEX (one manager per node, interchange on the login
node); the NCCL data plane lives inside vLLM. Here the data plane is XLA
collectives over ICI/DCN, and the control plane that stitches per-host JAX
processes into ONE global device view is ``jax.distributed.initialize`` —
this module owns that call so the pod worker, launcher scripts, and tests
initialize identically.

Topology sources, in precedence order:

1. Explicit arguments (tests, ad-hoc two-process runs).
2. ``DISTLLM_JAX_COORDINATOR`` / ``DISTLLM_JAX_NUM_PROCESSES`` /
   ``DISTLLM_JAX_PROCESS_ID`` environment variables — what the rendered
   PBS/Slurm pod scripts export per host (process id falls back to the
   scheduler rank: ``SLURM_PROCID`` or ``PMI_RANK``).
3. JAX's own cluster auto-detection (TPU pod metadata, Slurm) when nothing
   is specified at all.

On CPU the cross-process backend is Gloo, which is what lets CI exercise
this exact code path with two local processes (tests/test_multihost.py)
without TPU pod hardware.
"""

from __future__ import annotations

import os

_ENV_COORD = 'DISTLLM_JAX_COORDINATOR'
_ENV_NPROC = 'DISTLLM_JAX_NUM_PROCESSES'
_ENV_PID = 'DISTLLM_JAX_PROCESS_ID'
# Scheduler ranks, in the order the pod launchers start workers.
_RANK_ENVS = (_ENV_PID, 'SLURM_PROCID', 'PMI_RANK', 'PALS_RANKID')


def _env_rank() -> int | None:
    for var in _RANK_ENVS:
        value = os.environ.get(var)
        if value is not None:
            return int(value)
    return None


def init_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> tuple[int, int]:
    """Join this process to the global JAX runtime; returns (rank, size).

    Idempotent: a second call (e.g. worker restart inside one process)
    returns the existing topology instead of re-initializing. With no
    arguments and no ``DISTLLM_JAX_*`` environment, defers to JAX's
    cluster auto-detection (TPU pod / Slurm).
    """
    import jax

    if jax.distributed.is_initialized():
        return jax.process_index(), jax.process_count()

    coordinator_address = coordinator_address or os.environ.get(_ENV_COORD)
    if num_processes is None and os.environ.get(_ENV_NPROC):
        num_processes = int(os.environ[_ENV_NPROC])
    if process_id is None:
        process_id = _env_rank()

    kwargs: dict = {}
    if coordinator_address is not None:
        # jax.distributed wants host:port; tolerate the fabric's tcp:// form.
        kwargs['coordinator_address'] = coordinator_address.removeprefix(
            'tcp://'
        )
    if num_processes is not None:
        kwargs['num_processes'] = num_processes
    if process_id is not None:
        kwargs['process_id'] = process_id
    jax.distributed.initialize(**kwargs)
    return jax.process_index(), jax.process_count()


def process_rank() -> tuple[int, int]:
    """(process_index, process_count) of the current global runtime."""
    import jax

    return jax.process_index(), jax.process_count()
