"""Cross-host work distribution fabric (ZMQ) — the Parsl-HTEX replacement.

The reference scales out via Parsl's HighThroughputExecutor: a ZMQ/TCP task
fabric shipping pickled worker functions to persistent per-GPU processes
(``distllm/parsl.py``; SURVEY.md section 2.5 row N7). Parsl is not available
here, and on TPU pods the right granularity is one worker process per *host*
(a host owns all its chips through one JAX process) — so this module
implements the same pattern directly:

- :class:`Coordinator` — binds a ZMQ ROUTER socket, hands out (task_id, fn,
  args) pickles to idle workers, collects results, retries on worker loss.
- :class:`FabricWorker` — DEALER socket loop: request → execute → reply,
  with a background heartbeat thread so long-running tasks (file embeds can
  take many minutes) never get the worker falsely reaped.
- :class:`ZmqPoolExecutor` — ``map(fn, items)`` facade over the coordinator
  matching the in-process executors' API.
- :class:`KVBlockServer` / :class:`KVBlockClient` — digest-keyed KV block
  exchange between serving replicas (docs/routing.md "Peer KV tier"): a
  replica serves its own spilled ``.kvblock`` payloads, a sibling's
  :class:`~distllm_tpu.generate.engine.kv_cache.PeerKVTier` fetches them —
  the content-addressed KV-handoff seed of prefill/decode disaggregation.

Worker functions must be module-level (pickle), exactly as with Parsl.
"""

from __future__ import annotations

import pickle
import socket as _socket
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable

from distllm_tpu.observability import instruments, tracing
from distllm_tpu.observability.instruments import log_event

_READY = b'READY'
_HEARTBEAT = b'HB'
_RESULT = b'RESULT'
# Poison pill: [b'', _SHUTDOWN] ends the worker loop. Needed because a
# worker that joined the global JAX runtime no longer dies on SIGTERM
# (jax.distributed installs a preemption notifier that swallows it) —
# drivers end a run by telling workers to exit, like Parsl's
# interchange shutdown, instead of relying on signals.
_SHUTDOWN = b'SHUTDOWN'


@dataclass
class _Task:
    task_id: bytes
    payload: bytes
    tries: int = 0


@dataclass
class _WorkerState:
    ident: bytes
    last_seen: float = field(default_factory=time.monotonic)
    current: bytes | None = None


class Coordinator:
    """ROUTER-socket task pump with heartbeat-based failure detection.

    Failure semantics mirror the reference's Parsl config: tasks are retried
    up to ``retries`` times (``parsl.py:85,130,197``), and a worker silent for
    ``heartbeat_threshold`` seconds is declared lost, its in-flight task
    requeued (``parsl.py:216-217`` uses 15s/120s). Workers heartbeat during
    task execution, so the threshold bounds *network* silence, not task
    duration. A reaped worker that later reports its (requeued) task's result
    is accepted if the task has not been re-dispatched yet.
    """

    def __init__(
        self,
        bind: str = 'tcp://*:0',
        retries: int = 1,
        heartbeat_threshold: float = 120.0,
        advertise_host: str | None = None,
    ) -> None:
        import zmq

        self._ctx = zmq.Context.instance()
        self._socket = self._ctx.socket(zmq.ROUTER)
        host = advertise_host or _socket.gethostname()
        if bind.endswith(':0'):
            port = self._socket.bind_to_random_port('tcp://*')
            self.endpoint = f'tcp://{host}:{port}'
        else:
            self._socket.bind(bind)
            self.endpoint = bind.replace('*', host)
        self.retries = retries
        self.heartbeat_threshold = heartbeat_threshold
        self._workers: dict[bytes, _WorkerState] = {}

    def run(self, tasks: list[_Task]) -> dict[bytes, Any]:
        """Dispatch all tasks; block until every result (or failure) arrives."""
        import zmq

        pending: list[_Task] = list(tasks)
        in_flight: dict[bytes, _Task] = {}
        results: dict[bytes, Any] = {}
        poller = zmq.Poller()
        poller.register(self._socket, zmq.POLLIN)

        def record(task: _Task, ok: bytes, payload: bytes) -> None:
            if ok == b'1':
                results[task.task_id] = pickle.loads(payload)
            elif task.tries <= self.retries:
                pending.append(task)
            else:
                results[task.task_id] = pickle.loads(payload)

        while len(results) < len(tasks):
            self._reap_lost_workers(in_flight, pending)
            events = dict(poller.poll(timeout=1000))
            if self._socket not in events:
                continue
            frames = self._socket.recv_multipart()
            ident, kind = frames[0], frames[1]
            worker = self._workers.setdefault(ident, _WorkerState(ident))
            worker.last_seen = time.monotonic()
            if kind == _HEARTBEAT:
                # Ack so an idle-but-alive run keeps resetting the workers'
                # idle_timeout self-destruct (liveness flows both ways).
                self._socket.send_multipart([ident, b'', _HEARTBEAT])
            if kind == _READY:
                worker.current = None
            elif kind == _RESULT:
                task_id, ok, payload = frames[2], frames[3], frames[4]
                worker.current = None
                task = in_flight.pop(task_id, None)
                if task is None:
                    # Worker was reaped mid-task; accept the result if the
                    # requeued copy hasn't been re-dispatched yet.
                    for i, queued in enumerate(pending):
                        if queued.task_id == task_id:
                            pending.pop(i)
                            task = queued
                            break
                if task is not None and task_id not in results:
                    record(task, ok, payload)
            # Dispatch on ANY message kind (READY, RESULT, or HB): a reaped
            #-and-revived worker must be able to pick work back up even if
            # its next frame is only a heartbeat.
            if pending and worker.current is None:
                task = pending.pop(0)
                task.tries += 1
                worker.current = task.task_id
                in_flight[task.task_id] = task
                self._socket.send_multipart([ident, task.task_id, task.payload])
        return results

    def shutdown_workers(self, drain_seconds: float = 3.0) -> None:
        """Send every worker the poison pill (graceful pod teardown).

        After pilling the registered set, keeps draining the socket for
        ``drain_seconds`` and pills any ident that still speaks up: a
        late-booting host whose READY arrived after ``run`` returned, or a
        reaped-but-alive worker, would otherwise never get the pill and —
        since jax_distributed workers swallow SIGTERM — burn walltime.
        """
        import zmq

        pilled: set[bytes] = set()

        def pill(ident: bytes) -> None:
            if ident not in pilled:
                self._socket.send_multipart([ident, b'', _SHUTDOWN])
                pilled.add(ident)

        for ident in list(self._workers):
            pill(ident)
        poller = zmq.Poller()
        poller.register(self._socket, zmq.POLLIN)
        deadline = time.monotonic() + drain_seconds
        while time.monotonic() < deadline:
            events = dict(poller.poll(timeout=200))
            if self._socket in events:
                pill(self._socket.recv_multipart()[0])
        self._workers.clear()

    def _reap_lost_workers(
        self, in_flight: dict[bytes, _Task], pending: list[_Task]
    ) -> None:
        now = time.monotonic()
        for ident in list(self._workers):
            worker = self._workers[ident]
            if now - worker.last_seen > self.heartbeat_threshold:
                if worker.current is not None:
                    task = in_flight.pop(worker.current, None)
                    if task is not None:
                        pending.append(task)
                del self._workers[ident]

    def close(self) -> None:
        self._socket.close(linger=0)


class FabricWorker:
    """Worker loop: announce READY, execute tasks, reply, heartbeat always.

    Heartbeats flow in both phases (ZMQ sockets are not thread-safe, so
    all socket use shares one unfair lock): while a task executes the
    background thread sends them (the run loop is busy and the lock is
    free), and while idle the poll loop sends them itself under the lock
    it already holds (the tight poll cycle could otherwise starve the
    thread out of the lock indefinitely) — the coordinator therefore
    only reaps on real network/process loss.

    ``idle_timeout`` bounds how long the worker survives without hearing
    ANYTHING from the coordinator (which acks heartbeats while pumping).
    A straggler host that boots after the driver already exited — or
    outlives a crashed driver — would otherwise poll a dead endpoint
    forever, and a worker in the global JAX runtime cannot be SIGTERMed
    (preemption notifier); this is its self-destruct. Must cover worst-case
    boot stagger plus any driver dead time between ``map`` calls.
    """

    def __init__(
        self,
        coordinator: str,
        heartbeat_interval: float = 5.0,
        idle_timeout: float = 900.0,
    ) -> None:
        import zmq

        self._ctx = zmq.Context.instance()
        self._socket = self._ctx.socket(zmq.DEALER)  # guarded by self._send_lock
        self._socket.connect(coordinator)
        self.heartbeat_interval = heartbeat_interval
        self.idle_timeout = idle_timeout
        self._stop = threading.Event()
        self._send_lock = threading.Lock()

    def _send(self, frames: list[bytes]) -> None:
        with self._send_lock:
            self._socket.send_multipart(frames)

    def _recv(self) -> list[bytes]:
        """Receive under the socket lock: zmq sockets are not thread-safe,
        and the heartbeat thread's sends would otherwise interleave with
        the run loop's receives on the same DEALER socket. The poller has
        already reported POLLIN, so the locked recv never blocks."""
        with self._send_lock:
            return self._socket.recv_multipart()

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.heartbeat_interval):
            self._send([_HEARTBEAT])
            instruments.WORKER_HEARTBEATS.inc()

    def run(self) -> None:
        import zmq

        # Register BEFORE the heartbeat thread exists (no concurrent
        # socket use yet), and keep a local handle for the poll-result
        # membership test so the loop never touches the guarded slot.
        poller = zmq.Poller()
        with self._send_lock:
            sock = self._socket
            poller.register(sock, zmq.POLLIN)
        hb_thread = threading.Thread(target=self._heartbeat_loop, daemon=True)
        hb_thread.start()
        self._send([_READY])
        last_contact = time.monotonic()
        last_heartbeat = time.monotonic()
        while not self._stop.is_set():
            # Polling reads the shared socket's event state, so it holds
            # the socket lock too — the socket is only ever touched by
            # one thread at a time. threading.Lock is NOT fair: an idle
            # loop re-acquires microseconds after each release, so the
            # heartbeat thread could starve for the whole idle phase —
            # the poll loop therefore sends the idle-phase heartbeats
            # itself, under the lock it already holds. The thread covers
            # the in-task phase, where the lock sits free.
            with self._send_lock:
                events = dict(poller.poll(timeout=500))
                now = time.monotonic()
                if now - last_heartbeat >= self.heartbeat_interval:
                    self._socket.send_multipart([_HEARTBEAT])
                    instruments.WORKER_HEARTBEATS.inc()
                    last_heartbeat = now
            if sock not in events:
                if time.monotonic() - last_contact > self.idle_timeout:
                    log_event(
                        f'[worker] no coordinator contact for '
                        f'{self.idle_timeout:.0f}s; exiting',
                        component='worker',
                    )
                    break
                continue
            last_contact = time.monotonic()
            task_id, payload = self._recv()
            if not task_id:
                if payload == _SHUTDOWN:
                    break
                continue
            task_start = time.monotonic()
            try:
                with tracing.span('fabric-task', task_id.hex()):
                    fn, args, kwargs = pickle.loads(payload)
                    result = fn(*args, **kwargs)
                instruments.WORKER_TASKS.labels(outcome='ok').inc()
                self._send([_RESULT, task_id, b'1', pickle.dumps(result)])
            except BaseException as exc:  # noqa: BLE001 - shipped to coordinator
                instruments.WORKER_TASKS.labels(outcome='error').inc()
                self._send(
                    [_RESULT, task_id, b'0', pickle.dumps(RuntimeError(repr(exc)))]
                )
            finally:
                instruments.WORKER_TASK_SECONDS.observe(
                    time.monotonic() - task_start
                )
        self._stop.set()  # ends the heartbeat thread on poison-pill exit

    def stop(self) -> None:
        self._stop.set()


class ZmqPoolExecutor:
    """``map`` facade over :class:`Coordinator` (ParslPoolExecutor parity)."""

    def __init__(self, coordinator: Coordinator) -> None:
        self.coordinator = coordinator

    def shutdown(self) -> None:
        """Poison-pill every connected worker (end of the pod run)."""
        self.coordinator.shutdown_workers()

    def map(self, fn: Callable, items: Iterable[Any]) -> list[Any]:
        tasks = []
        order = []
        for item in items:
            task_id = uuid.uuid4().bytes
            order.append(task_id)
            tasks.append(
                _Task(task_id=task_id, payload=pickle.dumps((fn, (item,), {})))
            )
        results = self.coordinator.run(tasks)
        out = []
        for task_id in order:
            value = results[task_id]
            if isinstance(value, BaseException):
                raise value
            out.append(value)
        return out


_KV_HAS = b'HAS'
_KV_GET = b'GET'
KV_HIT = b'KVHIT'
KV_MISS = b'KVMISS'
KV_ERR = b'KVERR'


class KVBlockServer:
    """ROUTER-socket server answering digest-keyed HAS/GET for one
    replica's spilled KV blocks (docs/routing.md "Peer KV tier").

    Transport only: ``has_fn(digest) -> bool`` and ``get_fn(digest) ->
    bytes | None`` are injected (the engine wires them to its
    ``HostKVTier.contains_local`` / ``encoded_local`` — metric-free,
    peer-recursion-free), so the fabric never imports the KV layer. The
    reply payload is the ``.kvblock`` v2 encoding — the same bytes the
    disk tier persists, so peer handoff and restart-warm promotion share
    one format. A handler exception answers ``KVERR`` instead of killing
    the serve thread: one bad digest must not take the tier down.

    Frame protocol (REQ client side adds/strips its empty delimiter):
    request ``[cmd, digest]`` with cmd in ``{HAS, GET}``; reply
    ``[status, payload]`` with status in ``{KVHIT, KVMISS, KVERR}``
    (payload empty except for a GET hit).
    """

    def __init__(
        self,
        has_fn: Callable[[bytes], bool],
        get_fn: Callable[[bytes], bytes | None],
        bind: str = 'tcp://127.0.0.1:0',
        advertise_host: str | None = None,
    ) -> None:
        import zmq

        self._has_fn = has_fn
        self._get_fn = get_fn
        self._ctx = zmq.Context.instance()
        # Touched only by the serve thread after start(); close() joins
        # the thread before closing the socket.
        self._socket = self._ctx.socket(zmq.ROUTER)
        host = advertise_host or '127.0.0.1'
        if bind.endswith(':0'):
            port = self._socket.bind_to_random_port(bind[: bind.rfind(':')])
            self.endpoint = f'tcp://{host}:{port}'
        else:
            self._socket.bind(bind)
            self.endpoint = bind.replace('*', host)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._serve, name='kvblock-server', daemon=True
        )
        self.served_blocks = 0
        self.served_bytes = 0

    def start(self) -> 'KVBlockServer':
        self._thread.start()
        return self

    def _serve(self) -> None:
        import zmq

        poller = zmq.Poller()
        poller.register(self._socket, zmq.POLLIN)
        while not self._stop.is_set():
            if self._socket not in dict(poller.poll(timeout=200)):
                continue
            frames = self._socket.recv_multipart()
            ident, rest = frames[0], frames[1:]
            # REQ clients carry an empty delimiter frame; DEALER probes
            # may not — accept both.
            if rest and rest[0] == b'':
                rest = rest[1:]
            status, payload = KV_ERR, b''
            if len(rest) == 2:
                cmd, digest = rest
                try:
                    if cmd == _KV_HAS:
                        status = KV_HIT if self._has_fn(digest) else KV_MISS
                    elif cmd == _KV_GET:
                        encoded = self._get_fn(digest)
                        if encoded is None:
                            status = KV_MISS
                        else:
                            status, payload = KV_HIT, encoded
                            self.served_blocks += 1
                            self.served_bytes += len(encoded)
                # distlint: disable=swallowed-exception -- surfaced on the wire as KVERR; the FETCHING side counts the degradation (distllm_prefix_tier_errors_total{tier="peer"}) and falls through to cold prefill
                except Exception:
                    status = KV_ERR
            self._socket.send_multipart([ident, b'', status, payload])

    def close(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=2.0)
        self._socket.close(linger=0)


class KVBlockClient:
    """Bounded-timeout REQ client for :class:`KVBlockServer` endpoints.

    One REQ socket per endpoint, recreated after any timeout or transport
    error (the lazy-pirate pattern: a REQ that missed its reply is wedged
    in send state and must be discarded). ``request`` returns ``(status,
    payload)`` or None on transport failure — the caller
    (:class:`~distllm_tpu.generate.engine.kv_cache.PeerKVTier`) owns the
    backoff and metric accounting. Thread-safe: the engine loop and the
    server's admission thread may race fetches.
    """

    def __init__(self, timeout_ms: int = 500) -> None:
        import zmq

        self._ctx = zmq.Context.instance()
        self.timeout_ms = int(timeout_ms)
        self._lock = threading.Lock()
        self._sockets: dict[str, Any] = {}  # guarded by self._lock

    def request(
        self, endpoint: str, cmd: bytes, digest: bytes
    ) -> tuple[bytes, bytes] | None:
        import zmq

        with self._lock:
            sock = self._sockets.get(endpoint)
            if sock is None:
                sock = self._ctx.socket(zmq.REQ)
                sock.setsockopt(zmq.LINGER, 0)
                sock.connect(endpoint)
                self._sockets[endpoint] = sock
            try:
                sock.send_multipart([cmd, digest])
                if sock.poll(self.timeout_ms, zmq.POLLIN):
                    frames = sock.recv_multipart()
                    return (
                        frames[0],
                        frames[1] if len(frames) > 1 else b'',
                    )
            # distlint: disable=swallowed-exception -- degradation is the contract: None routes through PeerKVTier._note_failure, which counts distllm_prefix_tier_errors_total{tier="peer"} and backs the endpoint off
            except zmq.ZMQError:
                pass
            # Timeout or error: the REQ state machine is wedged — drop
            # the socket so the next request starts clean.
            sock.close(linger=0)
            del self._sockets[endpoint]
            return None

    def close(self) -> None:
        with self._lock:
            for sock in self._sockets.values():
                sock.close(linger=0)
            self._sockets.clear()


def map_with_teardown(executor, fn: Callable, items: Iterable[Any]) -> list[Any]:
    """``executor.map`` that ALWAYS shuts the pool down afterwards.

    The drivers' single entry to a pool: pod workers that joined the global
    JAX runtime ignore SIGTERM (preemption notifier), so they must receive
    the poison pill even when a task exhausts its retries and ``map``
    raises — otherwise a failed run leaves the worker job burning its full
    walltime. In-process executors have no ``shutdown`` and pass through.
    """
    try:
        return executor.map(fn, items)
    finally:
        getattr(executor, 'shutdown', lambda: None)()
