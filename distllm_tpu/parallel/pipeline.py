"""Pipeline parallelism: GPipe-style microbatched stage loop over a mesh axis.

The reference only ever *forwards a config knob* for pipeline parallelism to
vLLM and never exercises it (``pipeline_parallel_size: 1`` in
``examples/miscellaneous/multi_gpu_batch_config.yaml``; SURVEY.md §2.5).
Here it is a real construction: the stacked layer pytree ``[L, ...]`` is
sharded over a ``pipe`` mesh axis (each stage holds ``L / P`` layers), the
batch is split into microbatches, and activations flow stage-to-stage with
``lax.ppermute`` in the classic ``M + P - 1``-step schedule. Autodiff works
through the permutes, so the same function serves training (GPipe backward)
under ``jax.grad``.

Status framing (honest scope): this is a *library capability* exercised by
its unit suite (``tests/test_pipeline.py``), not a serving-engine mode — no
model config enables pp for the engine, mirroring the reference, whose own
serving never runs pp either. On a v5e slice, TP over ICI (engine mesh
path) dominates pp for the model sizes this framework targets; wire pp
into the engine only when a model no longer fits TP-sharded in a slice's
combined HBM.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

PIPE_AXIS = 'pipe'


def make_pipeline_mesh(num_stages: int, *, devices=None) -> Mesh:
    """1-axis ``pipe`` mesh over the first ``num_stages`` devices."""
    import numpy as np

    if devices is None:
        devices = jax.devices()
    if len(devices) < num_stages:
        raise ValueError(
            f'need {num_stages} devices for {num_stages} stages, '
            f'have {len(devices)}'
        )
    return Mesh(np.asarray(devices[:num_stages]), (PIPE_AXIS,))


def _stage_specs(params, axis: str):
    """Leading-dim sharding spec for every leaf of the stacked layer pytree."""
    return jax.tree_util.tree_map(
        lambda leaf: P(axis, *([None] * (leaf.ndim - 1))), params
    )


def _pipeline_local(
    stage_params,
    x_microbatches,  # [M, mb, ...] replicated input
    *,
    axis_name: str,
    layer_fn: Callable,
    num_microbatches: int,
):
    """Per-stage body (under shard_map).

    ``stage_params`` holds this stage's ``L/P`` stacked layers; each stage
    applies them with an inner ``lax.scan``. The outer ``fori_loop`` runs the
    ``M + P - 1`` schedule; stage 0 feeds microbatch ``t`` at step ``t``, the
    last stage collects its result at step ``t + P - 1``.
    """
    p_size = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    m = num_microbatches
    mb_shape = x_microbatches.shape[1:]

    def apply_stage(x):
        def body(x, lp):
            return layer_fn(lp, x), None

        out, _ = lax.scan(body, x, stage_params)
        return out

    out_buf = jnp.zeros((m,) + mb_shape, x_microbatches.dtype)
    state = jnp.zeros(mb_shape, x_microbatches.dtype)
    perm = [(i, (i + 1) % p_size) for i in range(p_size)]

    def step(t, carry):
        state, out_buf = carry
        # Stage 0 ingests microbatch t (clamped; masked out when t >= M).
        feed = x_microbatches[jnp.minimum(t, m - 1)]
        inp = jnp.where(idx == 0, feed, state)
        out = apply_stage(inp)
        # The last stage finished microbatch (t - P + 1) at this step.
        done = t - (p_size - 1)
        collect = (idx == p_size - 1) & (done >= 0) & (done < m)
        out_buf = lax.dynamic_update_index_in_dim(
            out_buf,
            jnp.where(collect, out, out_buf[jnp.clip(done, 0, m - 1)]),
            jnp.clip(done, 0, m - 1),
            axis=0,
        )
        # Hand activations to the next stage (ring permute; the wraparound
        # last->0 link carries garbage that stage 0 overwrites with `feed`).
        state = lax.ppermute(out, axis_name, perm)
        return state, out_buf

    _, out_buf = lax.fori_loop(
        0, m + p_size - 1, step, (state, out_buf)
    )
    # Only the last stage's buffer is real; psum broadcasts it (other
    # stages contribute zeros).
    out_buf = jnp.where(idx == p_size - 1, out_buf, jnp.zeros_like(out_buf))
    return lax.psum(out_buf, axis_name)


def pipeline_apply(
    stacked_params,
    x: jnp.ndarray,  # [B, ...]
    layer_fn: Callable,  # (layer_params, x) -> x
    mesh: Mesh,
    *,
    num_microbatches: int = 4,
    axis: str = PIPE_AXIS,
):
    """Apply an ``[L, ...]``-stacked layer pytree as a ``P``-stage pipeline.

    Equivalent to ``lax.scan(layer_fn, x, stacked_params)`` over the full
    stack, but with layers stage-sharded over ``mesh``'s ``axis`` and the
    batch pipelined in ``num_microbatches`` microbatches. ``B`` must divide
    by ``num_microbatches``, ``L`` by the stage count.
    """
    p_size = mesh.shape[axis]
    num_layers = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if num_layers % p_size != 0:
        raise ValueError(
            f'{num_layers} layers not divisible by {p_size} pipeline stages'
        )
    b = x.shape[0]
    if b % num_microbatches != 0:
        raise ValueError(
            f'batch {b} not divisible by {num_microbatches} microbatches'
        )
    x_mb = x.reshape((num_microbatches, b // num_microbatches) + x.shape[1:])

    fn = jax.shard_map(
        partial(
            _pipeline_local,
            axis_name=axis,
            layer_fn=layer_fn,
            num_microbatches=num_microbatches,
        ),
        mesh=mesh,
        in_specs=(_stage_specs(stacked_params, axis), P()),
        out_specs=P(),
        check_vma=False,
    )
    out_mb = fn(stacked_params, x_mb)
    return out_mb.reshape((b,) + out_mb.shape[2:])
