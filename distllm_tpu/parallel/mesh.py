"""Device mesh construction.

The framework uses one explicit mesh with up to four named axes:

- ``data``   — batch-dim sharding (DP); maps to the reference's file-level
  data parallelism *within* a host (SURVEY.md section 2.5, row DP).
- ``model``  — tensor parallelism over attention heads / MLP widths (the
  reference passes ``tensor_parallel_size`` through to vLLM; here it is a
  first-class mesh axis laid out over ICI).
- ``seq``    — sequence/context parallelism (ring attention) for long inputs;
  absent in the reference (it truncates instead) but first-class here.
- ``expert`` — expert parallelism for MoE checkpoints (reserved).

Axis sizes are chosen so ``data`` is outermost (DCN-friendly) and ``model`` is
innermost (ICI-friendly), following the standard TPU scaling recipe.
"""

from __future__ import annotations

import math
from typing import Literal

import jax
import numpy as np
from jax.sharding import Mesh

from distllm_tpu.utils import BaseConfig

DATA_AXIS = 'data'
MODEL_AXIS = 'model'
SEQ_AXIS = 'seq'
EXPERT_AXIS = 'expert'

AXIS_ORDER = (DATA_AXIS, SEQ_AXIS, EXPERT_AXIS, MODEL_AXIS)


class MeshSpec(BaseConfig):
    """Declarative mesh shape; ``-1`` on one axis means "fill remaining".

    Example: on 8 chips, ``MeshSpec(data=-1, model=2)`` builds a 4x2
    ``(data, model)`` mesh.
    """

    name: Literal['mesh'] = 'mesh'
    data: int = -1
    seq: int = 1
    expert: int = 1
    model: int = 1

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = {
            DATA_AXIS: self.data,
            SEQ_AXIS: self.seq,
            EXPERT_AXIS: self.expert,
            MODEL_AXIS: self.model,
        }
        fills = [ax for ax, s in sizes.items() if s == -1]
        if len(fills) > 1:
            raise ValueError(f'at most one mesh axis may be -1, got {fills}')
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if fills:
            if n_devices % fixed != 0:
                raise ValueError(
                    f'{n_devices} devices not divisible by fixed axes {fixed}'
                )
            sizes[fills[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f'mesh {sizes} needs {fixed} devices, have {n_devices}'
            )
        return sizes


def make_mesh(
    spec: MeshSpec | None = None,
    *,
    devices: list | None = None,
    **axis_sizes: int,
) -> Mesh:
    """Build a ``jax.sharding.Mesh`` from a spec or keyword axis sizes.

    Keeps every declared axis in the mesh (size-1 axes are free), so model
    code can always annotate with all four logical axes regardless of the
    physical configuration.
    """
    if spec is None:
        spec = MeshSpec(**axis_sizes)
    if devices is None:
        devices = jax.devices()
    sizes = spec.resolve(len(devices))
    shape = tuple(sizes[ax] for ax in AXIS_ORDER)
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        # CPU/virtual device fallback: plain reshape (no ICI topology to
        # optimize for anyway).
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)


def local_device_count() -> int:
    return jax.local_device_count()


def single_device_mesh() -> Mesh:
    """1-chip mesh (all axes size 1) — used by single-host CLI paths."""
    return make_mesh(MeshSpec(data=1, seq=1, expert=1, model=1), devices=jax.devices()[:1])
