"""Per-host fabric worker entry point.

Launched once per TPU host by the cluster scheduler (PBS/Slurm script or ssh
loop), analogous to Parsl's ``process_worker_pool`` that the reference's
MpiExecLauncher starts per node (``distllm/parsl.py:227-230``)::

    python -m distllm_tpu.parallel.worker --coordinator tcp://login-node:5555

``--jax-distributed`` additionally joins the host's JAX process to the
global runtime (``parallel/multihost.py``) before serving tasks, so a task
fn can build a mesh spanning every pod host. Topology comes from the
``DISTLLM_JAX_*`` environment the rendered job script exports (or JAX's
own pod auto-detection).
"""

from __future__ import annotations

import argparse

from distllm_tpu.observability.instruments import log_event


def main(argv: list[str] | None = None) -> int:
    from distllm_tpu.utils import apply_platform_env

    apply_platform_env()
    parser = argparse.ArgumentParser(description='distllm-tpu fabric worker')
    parser.add_argument('--coordinator', required=True, help='tcp://host:port')
    parser.add_argument('--heartbeat-interval', type=float, default=5.0)
    parser.add_argument(
        '--idle-timeout',
        type=float,
        default=900.0,
        help='Exit after this many seconds without coordinator contact '
        '(self-destruct for stragglers that outlive the driver).',
    )
    parser.add_argument(
        '--jax-distributed',
        action='store_true',
        help='Join the global JAX runtime (multi-host mesh) before serving.',
    )
    args = parser.parse_args(argv)

    if args.jax_distributed:
        from distllm_tpu.parallel.multihost import init_multihost

        rank, size = init_multihost()
        log_event(f'[worker] jax runtime rank {rank}/{size}', component='worker')

    from distllm_tpu.parallel.fabric import FabricWorker

    worker = FabricWorker(
        args.coordinator,
        heartbeat_interval=args.heartbeat_interval,
        idle_timeout=args.idle_timeout,
    )
    log_event(f'[worker] connected to {args.coordinator}', component='worker')
    worker.run()
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
