"""Per-host fabric worker entry point.

Launched once per TPU host by the cluster scheduler (PBS/Slurm script or ssh
loop), analogous to Parsl's ``process_worker_pool`` that the reference's
MpiExecLauncher starts per node (``distllm/parsl.py:227-230``)::

    python -m distllm_tpu.parallel.worker --coordinator tcp://login-node:5555
"""

from __future__ import annotations

import argparse


def main(argv: list[str] | None = None) -> int:
    from distllm_tpu.utils import apply_platform_env

    apply_platform_env()
    parser = argparse.ArgumentParser(description='distllm-tpu fabric worker')
    parser.add_argument('--coordinator', required=True, help='tcp://host:port')
    parser.add_argument('--heartbeat-interval', type=float, default=5.0)
    args = parser.parse_args(argv)

    from distllm_tpu.parallel.fabric import FabricWorker

    worker = FabricWorker(
        args.coordinator, heartbeat_interval=args.heartbeat_interval
    )
    print(f'[worker] connected to {args.coordinator}', flush=True)
    worker.run()
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
