"""Sharding helpers: NamedSharding construction and rule-based pytree sharding.

Models in this framework expose a ``param_specs(config) -> pytree[PartitionSpec]``
alongside ``init``/``apply``; these helpers place a host-side params pytree
onto the mesh accordingly. XLA then inserts the ICI collectives (all-reduce
for TP matmuls, all-gather at layout boundaries) — nothing here issues
explicit communication.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def replicate(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_pytree(params: Any, specs: Any, mesh: Mesh) -> Any:
    """Device-put ``params`` with per-leaf PartitionSpecs from ``specs``.

    ``specs`` must be a pytree prefix-compatible with ``params`` whose leaves
    are ``PartitionSpec``s. Axes named in a spec that have size 1 in the mesh
    are legal (no-op sharding), so the same specs work from 1 chip to a pod.

    Quantized leaves (:class:`~distllm_tpu.ops.quantization.QTensor`) are
    treated as single leaves and **replicated**: their packed code layout does
    not line up with the original weight's partition axes, and at 4-8 bits
    per weight replication costs less HBM than the unquantized sharded copy.
    """
    from distllm_tpu.ops.quantization import QTensor

    def _is_leaf(x):
        return isinstance(x, QTensor)

    flat_p, tree = jax.tree_util.tree_flatten(params, is_leaf=_is_leaf)
    flat_s = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P) or x is None
    )
    if len(flat_p) != len(flat_s):
        raise ValueError(
            f'params/specs mismatch: {len(flat_p)} arrays vs {len(flat_s)} specs'
        )
    placed = [
        jax.device_put(
            p,
            NamedSharding(mesh, P())
            if isinstance(p, QTensor)
            else NamedSharding(mesh, s if s is not None else P()),
        )
        for p, s in zip(flat_p, flat_s)
    ]
    return jax.tree_util.tree_unflatten(tree, placed)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Standard activation sharding: batch over data axis, rest replicated."""
    from distllm_tpu.parallel.mesh import DATA_AXIS

    return NamedSharding(mesh, P(DATA_AXIS))
