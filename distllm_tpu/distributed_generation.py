"""Distributed generation driver: file-sharded map over a compute fabric.

Reference parity: ``distllm/distributed_generation.py`` — YAML config, glob
inputs, warmstarted generator per worker, responses postprocessed and
empty-response items dropped (``:69-75``), per-file UUID output shards, and
the guard that the output directory must NOT pre-exist (``:115-121``) so a
finished run is never clobbered.

Run: ``python -m distllm_tpu.distributed_generation --config generate.yaml``
"""

from __future__ import annotations

import argparse
import functools
import uuid
from pathlib import Path
from typing import Any

from distllm_tpu.observability.instruments import log_event
from distllm_tpu.parallel.fabric import map_with_teardown
from distllm_tpu.parallel.launcher import ComputeConfigs, LocalConfig
from distllm_tpu.timer import Timer
from distllm_tpu.utils import BaseConfig, canonical_function


def generate_worker(
    file: str,
    output_dir: str,
    reader_kwargs: dict[str, Any],
    prompt_kwargs: dict[str, Any],
    generator_kwargs: dict[str, Any],
    writer_kwargs: dict[str, Any],
) -> str:
    """Generate responses for one input file into a UUID output shard."""
    from distllm_tpu.generate import (
        get_generator,
        get_prompt_template,
        get_reader,
        get_writer,
    )

    file_tag = Path(file).name
    with Timer('loaded-generator', file_tag):
        generator = get_generator(generator_kwargs, register=True)
    reader = get_reader(reader_kwargs)
    prompt = get_prompt_template(prompt_kwargs)
    writer = get_writer(writer_kwargs)

    with Timer('read-input', file_tag):
        texts, paths = reader.read(file)
    with Timer('generated-responses', file_tag):
        prompts = prompt.preprocess(texts)
        raw = generator.generate(prompts)
        responses = prompt.postprocess(raw)
    # Drop items whose postprocessed response is empty (reference :69-75).
    kept = [
        (p, t, r) for p, t, r in zip(paths, texts, responses) if r
    ]
    paths, texts, responses = (
        [k[0] for k in kept],
        [k[1] for k in kept],
        [k[2] for k in kept],
    )
    shard_dir = Path(output_dir) / uuid.uuid4().hex
    with Timer('wrote-responses', file_tag):
        writer.write(shard_dir, paths, texts, responses)
    return str(shard_dir)


class Config(BaseConfig):
    """Driver configuration (reference: ``distributed_generation.py:89-121``)."""

    input_dir: Path
    output_dir: Path
    glob_patterns: list[str] = ['*']
    reader_config: dict[str, Any]
    prompt_config: dict[str, Any]
    generator_config: dict[str, Any]
    writer_config: dict[str, Any]
    compute_config: ComputeConfigs = LocalConfig()


def run_generation(config: Config) -> int:
    if config.output_dir.exists():
        # Clobber guard (reference :115-121).
        log_event(
            f'Output directory {config.output_dir} already exists; refusing '
            'to overwrite a finished run.',
            component='generate',
        )
        return 1
    generation_dir = config.output_dir / 'generations'
    generation_dir.mkdir(parents=True)
    config.write_yaml(config.output_dir / 'config.yaml')

    files: list[str] = []
    for pattern in config.glob_patterns:
        files.extend(str(p) for p in sorted(config.input_dir.glob(pattern)))
    if not files:
        log_event(
            f'No input files matched {config.glob_patterns} in '
            f'{config.input_dir}',
            component='generate',
        )
        return 1
    log_event(
        f'Generating over {len(files)} files -> {generation_dir}',
        component='generate',
    )

    worker_fn = functools.partial(
        # Run as `python -m`, this module is __main__; rebind the
        # worker fn to its importable path so fabric workers can
        # unpickle it (Parsl has the same module-level-fn rule).
        canonical_function(generate_worker, 'distllm_tpu.distributed_generation'),
        output_dir=str(generation_dir),
        reader_kwargs=config.reader_config,
        prompt_kwargs=config.prompt_config,
        generator_kwargs=config.generator_config,
        writer_kwargs=config.writer_config,
    )
    executor = config.compute_config.get_executor(config.output_dir / 'run')
    shards = map_with_teardown(executor, worker_fn, files)
    log_event(f'Finished: {len(shards)} shards written', component='generate')
    return 0


def main(argv: list[str] | None = None) -> int:
    from distllm_tpu.utils import apply_platform_env

    apply_platform_env()
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--config', required=True, type=Path)
    args = parser.parse_args(argv)
    return run_generation(Config.from_yaml(args.config))


if __name__ == '__main__':
    raise SystemExit(main())
