"""Weight-only int8 matmul: dequantize in VMEM, not in HBM.

Why this exists (measured): ``common.dense`` used to call
``QTensor.dequantize()`` and feed the bf16 result to the dot. Inside the
unrolled decode loop XLA materializes both the converted weight AND the
scale-multiplied copy in HBM — per layer, per step. The int8 serving run
that motivated this (`chipback_r05/bench_run1.json`) decoded 16-step
windows in 1242 ms at batch 128 against a ~200 ms weights+KV streaming
floor: the "quantized" model was streaming ~3x the bytes of the bf16 one.

The fix has two tiers, chosen by :func:`int8_dense`:

- **XLA scale-after-dot** — the tier ``'auto'`` always picks, because it
  WINS on hardware: ``(x @ q.astype(dtype)) * scale`` is algebraically
  identical to ``x @ (q * scale)`` (the int8 scale is per-OUTPUT-channel;
  `quantization.quantize_int8` reduces only the input dim), the full-size
  elementwise multiply on the weight is gone, and XLA fuses the int8→bf16
  convert into the dot's weight stream. Measured at the 7B unrolled
  16-step decode window (`chipback_r05/probe_decode_int8.log`): 315 ms at
  batch 32 = 1623 tok/s, vs 465 ms bf16 and 1242 ms for the old
  dequant-before-dot serving path.
- **Pallas kernel** (:func:`int8_matmul_pallas`): streams int8 tiles
  HBM->VMEM, converts in VMEM, applies the per-output-channel scale once
  to the fp32 accumulator at the last K step. Kept for explicit selection
  and as the substrate for future fused variants, but it LOSES to the XLA
  tier everywhere measured (same log: 720 ms/window at batch 32, 1676 ms
  at batch 128; 5.4x slower than bf16 on the 4096x32000 lm_head, where
  its 256-wide N tiles yield 2000 grid steps) — so 'auto' never picks it.

Reference parity note: the reference gets weight-only-quantized serving
from bitsandbytes via HF (`distllm/generate/generators/huggingface_backend.py:66-77`)
— CUDA kernels that likewise fuse dequant into the GEMM. SURVEY.md §2.4 N4.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distllm_tpu.ops import tpu_compiler_params

BACKENDS = ('auto', 'pallas', 'xla', 'interpret')

_default_backend = os.environ.get('DISTLLM_QMM_BACKEND', 'auto')


def set_default_backend(backend: str) -> None:
    """Set the process-wide tier for :func:`int8_dense` callers that don't
    pass one (``models.common.dense``).

    Applies at TRACE time: executables already compiled keep the tier they
    were traced with (jax.jit caches by shape, not by this setting) — set
    it before the first compile, as the engine does for TP meshes.
    """
    if backend not in BACKENDS:
        raise ValueError(
            f'unknown quantized-matmul backend {backend!r}; one of {BACKENDS}'
        )
    global _default_backend
    _default_backend = backend


def default_backend() -> str:
    return _default_backend


def _kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, k_steps: int):
    """One (n, k) grid step: acc += x_tile @ dequant(q_tile).

    Grid is (n_steps, k_steps), k innermost: the x row-block stays
    resident while each output tile accumulates over K; q tiles stream
    exactly once. The scale lands on the [M, bn] accumulator — never on
    the weight.
    """
    @pl.when(pl.program_id(1) == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jax.lax.dot_general(
        x_ref[...],
        q_ref[...].astype(x_ref.dtype),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(pl.program_id(1) == k_steps - 1)
    def _done():
        o_ref[...] = (acc_ref[...] * s_ref[...]).astype(o_ref.dtype)


def _pick_tile(dim: int, candidates=(512, 256, 128)) -> int | None:
    for c in candidates:
        if dim % c == 0:
            return c
    return None


# M beyond this, the (n, k) grid's "one x row-block" layout stops making
# sense (the accumulator scratch grows linearly with M) and the regime is
# compute-bound prefill where the XLA path is fine.
MAX_PALLAS_ROWS = 512


def pallas_supported(m: int, k: int, n: int) -> bool:
    """Can :func:`int8_matmul_pallas` take this shape?"""
    return (
        m <= MAX_PALLAS_ROWS
        and _pick_tile(k) is not None
        and _pick_tile(n) is not None
    )


@functools.partial(jax.jit, static_argnames=('interpret',))
def int8_matmul_pallas(
    x: jnp.ndarray,  # [M, K] float
    q: jnp.ndarray,  # [K, N] int8
    scale: jnp.ndarray,  # [1, N] (or [N]) f32 per-output-channel
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    """``(x @ q) * scale`` with q staying int8 until VMEM. Returns x.dtype.

    ``interpret=True`` runs the kernel in Pallas interpret mode so CPU
    tests exercise the real index maps.
    """
    m, k = x.shape
    k2, n = q.shape
    assert k == k2, (x.shape, q.shape)
    bk = _pick_tile(k)
    bn = _pick_tile(n)
    if bk is None or bn is None or m > MAX_PALLAS_ROWS:
        raise ValueError(
            f'shape (M={m}, K={k}, N={n}) outside the pallas tile contract'
        )
    # Row-pad to the bf16 sublane multiple; padded rows are zeros and their
    # outputs are sliced away.
    m_pad = max(16, -(-m // 16) * 16)
    if m_pad != m:
        x = jnp.pad(x, ((0, m_pad - m), (0, 0)))
    scale = scale.reshape(1, n).astype(jnp.float32)

    out = pl.pallas_call(
        functools.partial(_kernel, k_steps=k // bk),
        grid=(n // bn, k // bk),
        in_specs=[
            pl.BlockSpec((m_pad, bk), lambda j, kk: (0, kk)),
            pl.BlockSpec((bk, bn), lambda j, kk: (kk, j)),
            pl.BlockSpec((1, bn), lambda j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((m_pad, bn), lambda j, kk: (0, j)),
        out_shape=jax.ShapeDtypeStruct((m_pad, n), x.dtype),
        scratch_shapes=[pltpu.VMEM((m_pad, bn), jnp.float32)],
        compiler_params=tpu_compiler_params(
            dimension_semantics=('parallel', 'arbitrary'),
        ),
        interpret=interpret,
    )(x, q, scale)
    return out[:m] if m_pad != m else out


def int8_matmul_xla(
    x: jnp.ndarray, q: jnp.ndarray, scale: jnp.ndarray
) -> jnp.ndarray:
    """Scale-after-dot formulation; portable tier of :func:`int8_dense`."""
    y = jax.lax.dot_general(
        x,
        q.astype(x.dtype),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return (y * scale.reshape(1, -1).astype(jnp.float32)).astype(x.dtype)


def int8_dense(
    x: jnp.ndarray,  # [..., K]
    q: jnp.ndarray,  # [K, N] int8
    scale: jnp.ndarray,  # [..., 1, N] f32
    backend: str = 'auto',
) -> jnp.ndarray:
    """``x @ dequant(q, scale)`` for a 2-D int8 QTensor, any leading dims.

    ``backend``: 'auto' == 'xla' (scale-after-dot — measured fastest tier,
    module docstring), 'pallas' / 'interpret' force the Pallas kernel
    (compiled / interpret mode).
    """
    if backend not in BACKENDS:
        raise ValueError(
            f'unknown quantized-matmul backend {backend!r}; one of {BACKENDS}'
        )
    k, n = q.shape
    lead = x.shape[:-1]
    m = 1
    for d in lead:
        m *= d
    x2 = x.reshape(m, k)
    use_pallas = backend in ('pallas', 'interpret')
    if use_pallas:
        out = int8_matmul_pallas(
            x2, q, scale, interpret=(backend == 'interpret')
        )
    else:
        out = int8_matmul_xla(x2, q, scale)
    return out.reshape(*lead, n)
