"""Pallas TPU flash-style attention for the *encoder* (embed) forward.

Why not XLA SDPA here: at the embed pipeline's hot shape ([512, 256],
12 heads) XLA materializes the masked ``[B, N, S, S]`` score/softmax
tensors in HBM — ~0.8 GB per intermediate per layer, several GB of HBM
traffic that caps the whole forward at ~0.43 MFU (measured,
``scripts/probe_attn.py``). Why not ``jax.experimental.pallas.ops.tpu.
flash_attention``: its ``MIN_BLOCK_SIZE = 128`` forces sequence lengths to
multiples of 128, which conflicts with the fine bucket ladder (160/224/320
rungs) that keeps embed padding waste low (``models/tokenizer.py
bucket_ladder``).

This kernel instead:

- takes Q/K/V in the ``[B, S, N*Hd]`` layout the QKV projections already
  produce — no head transpose is ever materialized;
- grids over the batch only; one grid step holds a full ``[S, N*Hd]``
  Q/K/V slice in VMEM (<= 2.3 MB each at S=512, H=768) and loops the
  heads in-kernel, so K/V bytes move HBM->VMEM exactly once;
- keeps the whole ``[S, S]`` per-head score tile in VMEM registers
  (<= 1 MB fp32 at S=512) — scores never touch HBM;
- masks invalid keys from the ``[B, S]`` attention mask with a -1e9 bias
  (finite, so fully-padded rows softmax to uniform garbage instead of
  NaN; poolers mask those rows out downstream).

Supported: S a multiple of 32, head_dim a multiple of 8 (BERT/ESM's 64
included), encoder-style bidirectional attention with key-validity mask.
The serving path's decode kernel is separate (``ops/paged_attention.py``).

Reference parity note: the reference gets this op from flash-attn/SDPA
inside HF models (``distllm/embed/encoders/auto.py:119-138``, faesm for
ESM); this is the TPU-native equivalent (SURVEY.md section 2.4 N3).

Routing policy (data: ``scripts/probe_encoder_matrix.py`` on a v5e,
2026-07-31, ``chipback_r05/probe_encoder_matrix.log``; constant token
budget B*S = 128k per forward):

- bert-base S=160..512: kernel 538-557k tok/s vs XLA 364-445k
  (+21-52%), and the kernel is FLAT across the bucket ladder where XLA
  degrades with S — exactly the shape regime the embed bench serves.
- esm2-650m S=256/512: kernel 78-81k vs XLA 47-62k (+27-72%).
- modernbert-base S=256/512 (windowed bias): kernel 357k vs XLA
  257-343k (+4-39%).
- S=1024 rows at 650m/modernbert dims exceed the VMEM working-set gate
  (shape_supported) and serve on XLA SDPA — 79k / 147k tok/s there.

So ``'auto'`` = kernel wherever :func:`shape_supported` passes, XLA
otherwise — the policy below implements exactly that, now measured
rather than assumed (the r3 probe that saw a tie was timing the tunnel
round trip, not the device).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from distllm_tpu.ops import tpu_compiler_params

_NEG_BIG = -1e9

# Leave headroom under the ~16 MB/core VMEM for Mosaic's own buffers.
_VMEM_BUDGET_BYTES = 12 * 1024 * 1024


def shape_supported(
    seq_len: int, hidden: int, num_heads: int, itemsize: int = 2,
    has_bias: bool = False,
) -> bool:
    """True when this kernel can run the shape: S % 32 == 0, head_dim % 8
    == 0, and the per-grid-step working set (double-buffered Q/K/V/O blocks
    + the [S, S] fp32 score tile, doubled when an additive ``[S, S]`` bias
    rides along) fits in VMEM. Callers fall back to XLA SDPA otherwise
    (e.g. ESM2-3B's hidden=2560 at S=512). ``itemsize`` is the activation
    dtype's bytes (2 for bf16, 4 for fp32 parity runs)."""
    if seq_len % 32 or hidden % num_heads or (hidden // num_heads) % 8:
        return False
    blocks = 4 * seq_len * hidden * itemsize * 2  # q/k/v/o, double-buffered
    # Bias is an input operand too, so cost it double-buffered like the
    # blocks, on top of the in-kernel [S, S] fp32 score tile.
    scores = seq_len * seq_len * 4 * (3 if has_bias else 1)
    return blocks + scores <= _VMEM_BUDGET_BYTES


def resolve_use_pallas(
    attn_impl: str,
    seq_len: int,
    hidden: int,
    num_heads: int,
    dtype,
    has_bias: bool = False,
) -> bool:
    """Shared encoder-model policy for ``attn_impl``: ``'pallas'`` forces
    the kernel, ``'auto'`` picks it on TPU when :func:`shape_supported`,
    anything else means XLA SDPA. One definition so BERT/ESM can't
    silently diverge in backend selection."""
    if attn_impl == 'pallas':
        return True
    if attn_impl != 'auto':
        return False
    return jax.default_backend() == 'tpu' and shape_supported(
        seq_len, hidden, num_heads, jnp.dtype(dtype).itemsize, has_bias
    )


def _kernel(q_ref, k_ref, v_ref, mask_ref, *rest, num_heads: int,
            scale: float, has_bias: bool):
    if has_bias:
        bias_ref, o_ref = rest
    else:
        (o_ref,) = rest
    seq, dim = q_ref.shape[1], q_ref.shape[2]
    head_dim = dim // num_heads
    # [S] key-validity bias, shared by every head of this batch row. (The
    # mask arrives as [B, 1, S] — Mosaic requires a block's last two dims
    # to divide (8, 128) or equal the array's, which a [1, S] block of a
    # [B, S] array does not.)
    bias = jnp.where(mask_ref[0, 0] != 0, 0.0, _NEG_BIG).astype(jnp.float32)
    if has_bias:
        # Additive [S, S] term (e.g. ModernBERT's sliding-window mask),
        # shared by every head and batch row; folded into the key bias.
        bias = bias[None, :] + bias_ref[...].astype(jnp.float32)
    else:
        bias = bias[None, :]
    for h in range(num_heads):
        lo = h * head_dim
        qh = q_ref[0, :, lo:lo + head_dim]
        kh = k_ref[0, :, lo:lo + head_dim]
        vh = v_ref[0, :, lo:lo + head_dim]
        scores = jax.lax.dot_general(
            qh, kh, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        scores = scores * scale + bias
        m = jnp.max(scores, axis=-1, keepdims=True)
        p = jnp.exp(scores - m)
        p = p / jnp.sum(p, axis=-1, keepdims=True)
        out = jax.lax.dot_general(
            p.astype(vh.dtype), vh, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        o_ref[0, :, lo:lo + head_dim] = out.astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=('num_heads', 'scale', 'interpret')
)
def encoder_attention(
    q: jnp.ndarray,  # [B, S, N*Hd]
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: jnp.ndarray,  # [B, S] nonzero = valid key
    num_heads: int,
    scale: float | None = None,
    bias: jnp.ndarray | None = None,  # [S, S] additive fp32 score term
    interpret: bool = False,
) -> jnp.ndarray:
    """Bidirectional multi-head attention, heads packed in the last dim.

    ``bias``, when given, is an additive ``[S, S]`` score term shared by
    every batch row and head — ModernBERT's sliding-window mask
    (``models/modernbert.py``) or any relative-position bias.
    """
    b, s, d = q.shape
    if d % num_heads:
        raise ValueError(f'hidden {d} not divisible by {num_heads} heads')
    if scale is None:
        scale = (d // num_heads) ** -0.5
    has_bias = bias is not None
    kernel = functools.partial(_kernel, num_heads=num_heads,
                               scale=float(scale), has_bias=has_bias)
    in_specs = [
        pl.BlockSpec((1, s, d), lambda i: (i, 0, 0)),
        pl.BlockSpec((1, s, d), lambda i: (i, 0, 0)),
        pl.BlockSpec((1, s, d), lambda i: (i, 0, 0)),
        pl.BlockSpec((1, 1, s), lambda i: (i, 0, 0)),
    ]
    operands = [q, k, v, mask.astype(jnp.int32).reshape(b, 1, s)]
    if has_bias:
        in_specs.append(pl.BlockSpec((s, s), lambda i: (0, 0)))
        operands.append(bias.astype(jnp.float32))
    return pl.pallas_call(
        kernel,
        grid=(b,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, s, d), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, s, d), q.dtype),
        compiler_params=tpu_compiler_params(
            dimension_semantics=('arbitrary',),
        ),
        interpret=interpret,
    )(*operands)


def encoder_attention_reference(q, k, v, mask, num_heads, scale=None,
                                bias=None):
    """Pure-jnp oracle for tests (same layout/mask semantics)."""
    b, s, d = q.shape
    hd = d // num_heads
    if scale is None:
        scale = hd ** -0.5
    qh = q.reshape(b, s, num_heads, hd).transpose(0, 2, 1, 3)
    kh = k.reshape(b, s, num_heads, hd).transpose(0, 2, 1, 3)
    vh = v.reshape(b, s, num_heads, hd).transpose(0, 2, 1, 3)
    scores = jnp.einsum('bnqh,bnkh->bnqk', qh, kh).astype(jnp.float32) * scale
    score_bias = jnp.where(mask[:, None, None, :] != 0, 0.0, _NEG_BIG)
    if bias is not None:
        score_bias = score_bias + bias[None, None].astype(jnp.float32)
    p = jax.nn.softmax(scores + score_bias, axis=-1)
    out = jnp.einsum('bnqk,bnkh->bnqh', p.astype(vh.dtype), vh)
    return out.transpose(0, 2, 1, 3).reshape(b, s, d)
