"""Weight-only quantization for TPU inference.

Reference parity: the bitsandbytes NF4 4-bit load path
(``distllm/embed/encoders/auto.py:46-56``,
``distllm/generate/generators/huggingface_backend.py:66-77``). bitsandbytes is
CUDA-only; the TPU-native equivalent stores weights in HBM as int8
(per-output-channel symmetric) or nf4 (blockwise 4-bit normal-float codebook,
two codes packed per byte) and dequantizes to the compute dtype *inside* the
jitted forward — storage is 2x/4x smaller while the MXU still sees bf16.
Quantization itself runs once on host at load time (numpy), mirroring the
"quantize on load" semantics of ``BitsAndBytesConfig(load_in_4bit=True)``.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

# The 16 normal-float levels from the QLoRA NF4 data type: quantiles of a
# standard normal, normalized to [-1, 1]. Public constants.
NF4_CODEBOOK = np.array(
    [
        -1.0,
        -0.6961928009986877,
        -0.5250730514526367,
        -0.39491748809814453,
        -0.28444138169288635,
        -0.18477343022823334,
        -0.09105003625154495,
        0.0,
        0.07958029955625534,
        0.16093020141124725,
        0.24611230194568634,
        0.33791524171829224,
        0.44070982933044434,
        0.5626170039176941,
        0.7229568362236023,
        1.0,
    ],
    dtype=np.float32,
)


@jax.tree_util.register_pytree_node_class
class QTensor:
    """A quantized weight leaf: codes + scales + enough metadata to restore.

    Lives inside the params pytree in place of the float array; jit treats
    ``q``/``scale`` as traced children and the metadata as static, so the
    dequant lowers to a fused gather/multiply in the forward program.
    """

    def __init__(
        self,
        q: jnp.ndarray,
        scale: jnp.ndarray,
        kind: str,
        shape: tuple[int, ...],
        out_dtype: str,
        block_size: int = 0,
    ) -> None:
        self.q = q
        self.scale = scale
        self.kind = kind
        self.shape = tuple(shape)
        self.out_dtype = out_dtype
        self.block_size = block_size

    # -- pytree protocol -------------------------------------------------
    def tree_flatten(self):
        return (self.q, self.scale), (
            self.kind,
            self.shape,
            self.out_dtype,
            self.block_size,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        q, scale = children
        kind, shape, out_dtype, block_size = aux
        return cls(q, scale, kind, shape, out_dtype, block_size)

    # -- numerics --------------------------------------------------------
    def dequantize(self) -> jnp.ndarray:
        """Restore the float weight.

        Works both on the whole leaf AND on a ``lax.scan``-sliced view: when
        a stacked ``[L, ...]`` QTensor rides a scan over layers, scan slices
        the ``q``/``scale`` children (dropping the leading dim) while the
        static ``shape`` metadata still describes the full stack — so the
        target shape is derived from the *children's* runtime shapes, using
        ``self.shape`` only for the trailing dims. Scanning the quantized
        tree is what lets dequantization happen per layer inside the layer
        scan: dequantizing the full 7B stack outside the scan materializes
        ~13 GiB of bf16 HLO temps and OOMs a 16 GiB chip (measured,
        BENCH r3 gen_q attempt 1).
        """
        if self.kind == 'int8':
            # q keeps the weight's own shape (sliced or not); scale is
            # keepdims-broadcastable against it.
            return self.q.astype(self.out_dtype) * self.scale.astype(
                self.out_dtype
            )
        if self.kind == 'nf4':
            # Packed codes: [..., nblocks, block_size // 2]; scale
            # [..., nblocks]. Leading stack dims (if still present) pass
            # through untouched.
            high = (self.q >> 4) & 0x0F
            low = self.q & 0x0F
            codes = jnp.stack([high, low], axis=-1).reshape(
                *self.q.shape[:-1], -1
            )
            codebook = jnp.asarray(NF4_CODEBOOK, dtype=self.out_dtype)
            values = codebook[codes] * self.scale.astype(self.out_dtype)[
                ..., None
            ]
            # The core weight is always 2-D; any dims of `q` before its
            # last two ([..., nblocks, packed]) are stack dims that pass
            # through (present when unsliced, gone when scan-sliced).
            lead_dims = self.q.shape[:-2]
            weight_tail = self.shape[-2:]
            tail_elems = int(np.prod(weight_tail))
            flat = values.reshape(*lead_dims, -1)[..., :tail_elems]
            return flat.reshape(*lead_dims, *weight_tail)
        raise ValueError(f'unknown quantization kind {self.kind!r}')

    @property
    def nbytes(self) -> int:
        return int(self.q.size * self.q.dtype.itemsize) + int(
            self.scale.size * self.scale.dtype.itemsize
        )


def quantize_int8(w: np.ndarray, out_dtype: str = 'bfloat16') -> QTensor:
    """Symmetric per-output-channel int8 (channel = last axis).

    For stacked-layer kernels ``[L, in, out]`` (``common.stack_layers``) the
    scale is per ``(layer, channel)`` — each layer keeps its own dynamic
    range. ``q`` keeps the original shape; ``scale`` is keepdims-broadcastable
    so dequant is a single fused multiply.
    """
    w = np.asarray(w, dtype=np.float32)
    # Reduce ONLY the input dim (second-to-last): every leading dim —
    # layer stack [L, in, out], expert banks [L, E, in, out] — keeps its
    # own per-channel dynamic range.
    reduce_axes = (w.ndim - 2,)
    absmax = np.abs(w).max(axis=reduce_axes, keepdims=True)
    scale = (absmax / 127.0).astype(np.float32)
    scale = np.where(scale == 0.0, 1.0, scale)
    q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
    return QTensor(
        jnp.asarray(q), jnp.asarray(scale), 'int8', w.shape, out_dtype
    )


def quantize_nf4(
    w: np.ndarray, block_size: int = 64, out_dtype: str = 'bfloat16'
) -> QTensor:
    """Blockwise NF4: per-block absmax scale + 4-bit codebook codes.

    Codes are packed two per uint8 (high nibble first). Blocks run over the
    flattened weight; a partial tail block is zero-padded (zero maps to code
    7, exactly representable, so padding adds no error).
    """
    w = np.asarray(w, dtype=np.float32)
    # Stacked [L, in, out] kernels pack per layer ([L, nblocks, packed]) so
    # the leading dim survives — a lax.scan over layers can slice the codes
    # and dequantize ONE layer at a time inside the loop body (see
    # QTensor.dequantize).
    lead = w.shape[:-2] if w.ndim >= 3 else ()
    flat = w.reshape(*lead, -1)
    pad = (-flat.shape[-1]) % block_size
    if pad:
        flat = np.concatenate(
            [flat, np.zeros((*lead, pad), dtype=np.float32)], axis=-1
        )
    blocks = flat.reshape(*lead, -1, block_size)
    absmax = np.abs(blocks).max(axis=-1)
    scale = np.where(absmax == 0.0, 1.0, absmax).astype(np.float32)
    normalized = blocks / scale[..., None]
    # Nearest codebook level via searchsorted on the midpoints between
    # adjacent levels — same result as argmin(|x - codebook|) without the
    # 16x host-memory blowup (a 7B stacked kernel is ~2e9 elements).
    midpoints = (NF4_CODEBOOK[1:] + NF4_CODEBOOK[:-1]) / 2.0
    idx = np.searchsorted(midpoints, normalized).astype(np.uint8)
    packed = (idx[..., 0::2] << 4) | idx[..., 1::2]
    return QTensor(
        jnp.asarray(packed),
        jnp.asarray(scale),
        'nf4',
        w.shape,
        out_dtype,
        block_size,
    )


def _should_quantize(path: tuple, leaf: Any, min_size: int) -> bool:
    # Linear kernels are 2-D [in, out], stacked-per-layer 3-D [L, in, out]
    # (models/common.py stack_layers), or stacked expert banks 4-D
    # [L, E, in, out] (models/mixtral.py); anything else stays float.
    if (
        not hasattr(leaf, 'ndim')
        or leaf.ndim not in (2, 3, 4)
        or leaf.size < min_size
        or not jnp.issubdtype(leaf.dtype, jnp.floating)
    ):
        return False
    keys = '/'.join(str(getattr(k, 'key', k)) for k in path).lower()
    # Embedding tables, norm scales, biases, the output head, and MoE
    # router kernels stay full precision (bnb quantizes only nn.Linear
    # weights and exempts lm_head via llm_int8_skip_modules; routers are
    # tiny [H, E] and routing is precision-sensitive). Stacked biases
    # are 2-D [L, out], hence the name gate rather than an ndim gate.
    return not any(
        tag in keys
        for tag in ('embed', 'norm', 'ln', 'bias', 'head', 'router')
    )


def normalize_mode(value: bool | str | None) -> str | None:
    """Coerce a config's ``quantization`` field to a mode string.

    ``True`` means ``'nf4'`` — the reference's quantization flag loads
    bitsandbytes NF4 (``auto.py:46-56``); ``False``/``None``/``''`` disable.
    """
    if value is True:
        return 'nf4'
    return value or None


def quantize_pytree(
    params: Any,
    mode: str = 'nf4',
    min_size: int = 4096,
    block_size: int = 64,
    out_dtype: str = 'bfloat16',
    delete_source: bool = False,
) -> Any:
    """Replace large 2-D float leaves with :class:`QTensor`.

    ``mode`` is ``'int8'`` or ``'nf4'``. Embedding/norm leaves and small
    tensors are left untouched.

    ``delete_source=True`` streams the conversion: each replaced device
    leaf is copied to host and **deleted before its quantized replacement
    is materialized**, so device memory peaks at the unquantized weights
    and then decreases monotonically. Without it, quantizing a 7B bf16
    model (13.5 GiB) would hold source + codes (~20.5 GiB) simultaneously
    — past a 16 GiB v5e's HBM. Only set it when the caller owns ``params``
    (the source leaves become unusable).
    """
    if mode not in ('int8', 'nf4'):
        raise ValueError(f'unknown quantization mode {mode!r}')

    def _quantize(path, leaf):
        if isinstance(leaf, QTensor) or not _should_quantize(
            path, leaf, min_size
        ):
            return leaf
        # An owned fp32 copy, not np.asarray: on some backends asarray of a
        # jax.Array is a zero-copy view into the device/host buffer, which
        # delete() below would free out from under the quantizer.
        host = np.array(leaf, dtype=np.float32, copy=True)
        if delete_source and hasattr(leaf, 'delete'):
            leaf.delete()
        if mode == 'int8':
            return quantize_int8(host, out_dtype)
        return quantize_nf4(host, block_size, out_dtype)

    return jax.tree_util.tree_map_with_path(
        _quantize, params, is_leaf=lambda x: isinstance(x, QTensor)
    )


def quantize_pytree_abstract(
    shapes: Any,
    mode: str = 'int8',
    min_size: int = 4096,
    make_leaf=None,
    out_dtype: str = 'bfloat16',
) -> Any:
    """Shape-level analogue of :func:`quantize_pytree` for AOT compiles.

    Maps a tree of ``ShapeDtypeStruct``-like leaves to the pytree the real
    quantizer would produce — same quantize-or-pass-through policy, same
    code/scale shapes — without any data. ``make_leaf(shape, dtype)``
    constructs abstract leaves (defaults to ``jax.ShapeDtypeStruct``).
    Keeping this NEXT TO the quantizer means compile-only preflights and
    CI lowering tests can't drift from the layout serving actually runs.
    Currently int8 only (the AOT-validated serving mode). ``out_dtype``
    must match what the real quantizer is called with (the engine passes
    the model dtype) or the compiled program diverges from serving.
    """
    if mode != 'int8':
        raise NotImplementedError(f'abstract quantization for {mode!r}')
    if make_leaf is None:
        def make_leaf(shape, dtype):
            return jax.ShapeDtypeStruct(tuple(shape), dtype)

    def convert(path, leaf):
        if isinstance(leaf, QTensor):
            return leaf
        if not _should_quantize(path, leaf, min_size):
            return make_leaf(leaf.shape, leaf.dtype)
        shape = tuple(leaf.shape)
        # Mirrors quantize_int8: only the input dim (second-to-last)
        # reduces, keepdims — [1, out] for 2-D, [L, 1, out] for stacked
        # 3-D, [L, E, 1, out] for expert banks.
        scale_shape = (*shape[:-2], 1, shape[-1])
        return QTensor(
            make_leaf(shape, jnp.int8),
            make_leaf(scale_shape, jnp.float32),
            'int8',
            shape,
            out_dtype,
        )

    return jax.tree_util.tree_map_with_path(
        convert, shapes, is_leaf=lambda x: isinstance(x, QTensor)
    )


def dequantize_pytree(params: Any) -> Any:
    """Restore float arrays from :class:`QTensor` leaves (jit-safe)."""
    return jax.tree_util.tree_map(
        lambda leaf: leaf.dequantize() if isinstance(leaf, QTensor) else leaf,
        params,
        is_leaf=lambda leaf: isinstance(leaf, QTensor),
    )


def quantized_nbytes(params: Any) -> tuple[int, int]:
    """(quantized_bytes, float_bytes) over the pytree — for telemetry."""
    q_bytes = 0
    f_bytes = 0
    for leaf in jax.tree_util.tree_leaves(
        params, is_leaf=lambda x: isinstance(x, QTensor)
    ):
        if isinstance(leaf, QTensor):
            q_bytes += leaf.nbytes
        else:
            f_bytes += int(leaf.size * leaf.dtype.itemsize)
    return q_bytes, f_bytes
