"""Exact inner-product top-k over device-sharded corpora.

The FAISS replacement's compute core (SURVEY.md section 2.4 N2): embeddings
live row-sharded across chips (mesh ``data`` axis); each chip computes its
shard's ``Q @ E_shard^T`` on the MXU and a local ``lax.top_k``; the per-shard
candidates (k per chip) are concatenated — a tiny ICI all-gather instead of
gathering the full ``[B, N]`` score matrix — and reduced with one final
``top_k``. Also hosts the binary (Hamming) scoring path used by ubinary
quantized indexes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


def topk_inner_product(
    queries: jnp.ndarray,  # [B, H] fp32
    corpus: jnp.ndarray,  # [N, H] (possibly sharded over mesh 'data')
    k: int,
    mesh: Mesh | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Exact top-k by inner product. Returns (scores [B, k], indices [B, k])."""
    k = min(k, corpus.shape[0])
    if mesh is None or mesh.shape.get('data', 1) == 1:
        scores = queries @ corpus.T
        return jax.lax.top_k(scores, k)
    return _topk_sharded(queries, corpus, k, mesh)


def _topk_sharded(queries, corpus, k, mesh):
    from jax import shard_map

    n_shards = mesh.shape['data']
    shard_rows = corpus.shape[0] // n_shards

    def per_shard(q, e_shard):
        scores = q @ e_shard.T  # [B, n/shards] on-chip MXU matmul
        local_k = min(k, e_shard.shape[0])
        s, i = jax.lax.top_k(scores, local_k)
        offset = jax.lax.axis_index('data') * shard_rows
        return s, i + offset

    sharded = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(), P('data', None)),
        out_specs=(P(None, 'data'), P(None, 'data')),
    )
    cand_scores, cand_idx = sharded(queries, corpus)  # [B, k*shards]
    merged_scores, merged_pos = jax.lax.top_k(cand_scores, k)
    merged_idx = jnp.take_along_axis(cand_idx, merged_pos, axis=1)
    return merged_scores, merged_idx


def pack_sign_bits(embeddings: np.ndarray) -> np.ndarray:
    """fp32 ``[N, H]`` → uint8 ``[N, H/8]`` sign-bit packing (ubinary).

    Matches sentence-transformers' ``quantize_embeddings(..., 'ubinary')``:
    bit = 1 where value > 0, packed big-endian within each byte.
    """
    if embeddings.shape[1] % 8 != 0:
        raise ValueError(f'embedding dim {embeddings.shape[1]} not divisible by 8')
    bits = (embeddings > 0).astype(np.uint8)
    return np.packbits(bits, axis=1)


def hamming_topk(
    query_bits: jnp.ndarray,  # [B, H/8] uint8
    corpus_bits: jnp.ndarray,  # [N, H/8] uint8
    k: int,
    chunk_size: int = 1 << 16,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k by smallest Hamming distance. Returns (distances, indices).

    The corpus axis is processed in chunks with a running top-k so peak
    memory is ``O(B * chunk_size)`` — ubinary indexes exist precisely for
    corpora too large to materialize ``[B, N, H/8]`` intermediates.
    """
    n = corpus_bits.shape[0]
    k = min(k, n)

    @functools.partial(jax.jit, static_argnums=(2,))
    def chunk_distances(q, corpus_chunk, chunk_k):
        xor = jnp.bitwise_xor(q[:, None, :], corpus_chunk[None, :, :])
        distances = jnp.sum(
            jax.lax.population_count(xor).astype(jnp.int32), axis=-1
        )
        neg, idx = jax.lax.top_k(-distances, chunk_k)
        return neg, idx

    best_neg = None
    best_idx = None
    for start in range(0, n, chunk_size):
        chunk = corpus_bits[start : start + chunk_size]
        chunk_k = min(k, chunk.shape[0])
        neg, idx = chunk_distances(query_bits, chunk, chunk_k)
        idx = idx + start
        if best_neg is None:
            best_neg, best_idx = neg, idx
        else:
            cat_neg = jnp.concatenate([best_neg, neg], axis=1)
            cat_idx = jnp.concatenate([best_idx, idx], axis=1)
            best_neg, pos = jax.lax.top_k(cat_neg, k)
            best_idx = jnp.take_along_axis(cat_idx, pos, axis=1)
    return -best_neg, best_idx
