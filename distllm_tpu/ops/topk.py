"""Exact inner-product top-k over device-sharded corpora.

The FAISS replacement's compute core (SURVEY.md section 2.4 N2): embeddings
live row-sharded across chips (mesh ``data`` axis); each chip computes its
shard's ``Q @ E_shard^T`` on the MXU and a local ``lax.top_k``; the per-shard
candidates (k per chip) are concatenated — a tiny ICI all-gather instead of
gathering the full ``[B, N]`` score matrix — and reduced with one final
``top_k``. Also hosts the binary (Hamming) scoring path used by ubinary
quantized indexes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


def topk_inner_product(
    queries: jnp.ndarray,  # [B, H] fp32
    corpus: jnp.ndarray,  # [N, H] (possibly sharded over mesh 'data')
    k: int,
    mesh: Mesh | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Exact top-k by inner product. Returns (scores [B, k], indices [B, k])."""
    k = min(k, corpus.shape[0])
    if mesh is None or mesh.shape.get('data', 1) == 1:
        scores = queries @ corpus.T
        return jax.lax.top_k(scores, k)
    return _topk_sharded(queries, corpus, k, mesh)


def _sharded_topk(score_fn, row_count, operands, in_specs, k, mesh):
    """Shared multi-chip top-k scaffold: each chip scores its row shard
    (``score_fn(replicated..., sharded...) -> [B, rows/shard]``), takes a
    local top-k, offsets indices by its shard start, and the k-per-chip
    candidates are concatenated (tiny ICI all-gather vs the full [B, N]
    score matrix) and reduced with one final ``top_k``. Both the exact
    fp32 and the int8 tiers route here so the offset/merge math has one
    home."""
    from jax import shard_map

    n_shards = mesh.shape['data']
    shard_rows = row_count // n_shards

    def per_shard(*args):
        scores = score_fn(*args)
        local_k = min(k, scores.shape[1])
        s, i = jax.lax.top_k(scores, local_k)
        offset = jax.lax.axis_index('data') * shard_rows
        return s, i + offset

    sharded = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(P(None, 'data'), P(None, 'data')),
    )
    cand_scores, cand_idx = sharded(*operands)  # [B, k*shards]
    merged_scores, merged_pos = jax.lax.top_k(cand_scores, k)
    merged_idx = jnp.take_along_axis(cand_idx, merged_pos, axis=1)
    return merged_scores, merged_idx


def _topk_sharded(queries, corpus, k, mesh):
    def score(q, e_shard):
        return q @ e_shard.T  # [B, n/shards] on-chip MXU matmul

    return _sharded_topk(
        score, corpus.shape[0], (queries, corpus),
        (P(), P('data', None)), k, mesh,
    )


def quantize_int8_rows(
    embeddings: np.ndarray,
) -> tuple[np.ndarray, np.ndarray]:
    """fp32 ``[N, H]`` → (``int8`` codes ``[N, H]``, fp32 scales ``[N]``).

    Symmetric per-row absmax quantization (sentence-transformers' int8
    precision semantics). 4x smaller than fp32 — the single-chip middle
    tier between exact fp32 (~4M x 768 rows in 16 GiB HBM) and ubinary
    (32x smaller, Hamming-approximate): scores stay MXU matmuls (int8
    inputs, int32 accumulate) and ranking error is ~1e-2 relative, which
    the oversampled fp32 rescore absorbs.
    """
    absmax = np.abs(embeddings).max(axis=1)
    scales = np.where(absmax == 0, 1.0, absmax / 127.0).astype(np.float32)
    codes = np.clip(
        np.round(embeddings / scales[:, None]), -127, 127
    ).astype(np.int8)
    return codes, scales


def int8_topk(
    queries: jnp.ndarray,  # [B, H] fp32
    codes: jnp.ndarray,  # [N, H] int8 (possibly sharded over mesh 'data')
    scales: jnp.ndarray,  # [N] fp32 (sharded alongside codes)
    k: int,
    mesh: Mesh | None = None,
    chunk_size: int = 1 << 19,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k inner product against an int8-quantized corpus.

    Queries are quantized per-row on the fly so the score matmul runs
    int8 x int8 → int32 on the MXU; the true scale is reapplied before
    ``top_k``. The single-device path processes the corpus axis in
    ``chunk_size`` slabs with a running top-k, so peak memory is
    ``O(B * chunk_size)`` rather than ``[B, N]`` — this tier exists for
    corpora past the fp32 HBM limit, where a full score matrix at batch
    128 would itself OOM. Returns (approx scores [B, k], indices [B, k]).
    """
    n = codes.shape[0]
    k = min(k, n)
    qmax = jnp.abs(queries).max(axis=1)
    qscale = jnp.where(qmax == 0, 1.0, qmax / 127.0)
    qi = jnp.clip(
        jnp.round(queries / qscale[:, None]), -127, 127
    ).astype(jnp.int8)

    def score(q_codes, q_scale, codes_part, scales_part):
        raw = jax.lax.dot_general(
            q_codes, codes_part, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32,
        )
        return (
            raw.astype(jnp.float32) * q_scale[:, None] * scales_part[None, :]
        )

    if mesh is not None and mesh.shape.get('data', 1) > 1:
        # Per-shard rows are already N/shards; each chip scores its slab
        # in one matmul (shard the corpus further if [B, N/shards] scores
        # ever dominate a chip's HBM).
        return _sharded_topk(
            score, n, (qi, qscale, codes, scales),
            (P(), P(), P('data', None), P('data')), k, mesh,
        )

    # Chunk-local candidate selection: exact below APPROX_TOPK_MIN_ROWS
    # total rows, TPU approx_max_k above (this tier rescored in fp32
    # anyway; exact sort over large chunks dominated the 10M scan).
    approx = n >= APPROX_TOPK_MIN_ROWS

    @functools.partial(jax.jit, static_argnums=(4,))
    def chunk_topk(q_codes, q_scale, codes_part, scales_part, chunk_k):
        return _chunk_candidates(
            score(q_codes, q_scale, codes_part, scales_part), chunk_k, approx
        )

    best_scores = None
    best_idx = None
    for start in range(0, n, chunk_size):
        codes_part = codes[start : start + chunk_size]
        scales_part = scales[start : start + chunk_size]
        chunk_k = min(k, codes_part.shape[0])
        s, i = chunk_topk(qi, qscale, codes_part, scales_part, chunk_k)
        i = i + start
        if best_scores is None:
            best_scores, best_idx = s, i
        else:
            cat_s = jnp.concatenate([best_scores, s], axis=1)
            cat_i = jnp.concatenate([best_idx, i], axis=1)
            best_scores, pos = jax.lax.top_k(cat_s, k)
            best_idx = jnp.take_along_axis(cat_i, pos, axis=1)
    return best_scores, best_idx


def pack_sign_bits(embeddings: np.ndarray) -> np.ndarray:
    """fp32 ``[N, H]`` → uint8 ``[N, H/8]`` sign-bit packing (ubinary).

    Matches sentence-transformers' ``quantize_embeddings(..., 'ubinary')``:
    bit = 1 where value > 0, packed big-endian within each byte.
    """
    if embeddings.shape[1] % 8 != 0:
        raise ValueError(f'embedding dim {embeddings.shape[1]} not divisible by 8')
    bits = (embeddings > 0).astype(np.uint8)
    return np.packbits(bits, axis=1)


# Corpora past this row count switch the per-chunk candidate selection
# from exact lax.top_k (a full bitonic sort over the chunk — measured
# 12.5 s for one 10M-row ubinary scan, chipback_r05) to the TPU-native
# jax.lax.approx_max_k (~0.95 per-element recall). Quantized-tier
# candidates feed an oversampled fp32 rescore, so serving quality is set
# by top1/rescore behavior, not the last near-tie in the candidate set.
APPROX_TOPK_MIN_ROWS = 1 << 20


def _chunk_candidates(scores_f32: jnp.ndarray, k: int, approx: bool):
    if approx:
        return jax.lax.approx_max_k(scores_f32, k)
    return jax.lax.top_k(scores_f32, k)


def _unpack_bits(packed: jnp.ndarray) -> jnp.ndarray:
    """uint8 ``[..., H/8]`` → 0/1 int8 ``[..., H]`` (big-endian, matching
    :func:`pack_sign_bits` / np.packbits)."""
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    bits = (packed[..., :, None] >> shifts) & jnp.uint8(1)
    return bits.astype(jnp.int8).reshape(*packed.shape[:-1], -1)


def hamming_topk(
    query_bits: jnp.ndarray,  # [B, H/8] uint8
    corpus_bits: jnp.ndarray,  # [N, H/8] uint8
    k: int,
    chunk_size: int = 1 << 18,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k by smallest Hamming distance. Returns (distances, indices).

    Scoring is an MXU matmul, not a VPU popcount sweep:
    ``hamming(a, b) = |a| + |b| - 2 a·b`` over the unpacked 0/1 vectors,
    so each chunk unpacks to int8 in VMEM-sized slabs and scores as an
    int8 x int8 → int32 dot. (The first implementation XOR+popcounted a
    materialized [B, chunk, H/8] tensor and exact-sorted every chunk:
    12.5 s per 10M-row scan on the chip; this formulation is ~50 ms.)
    Distances are exact ints; candidate selection per chunk is exact
    below ``APPROX_TOPK_MIN_ROWS`` rows and TPU ``approx_max_k`` above.
    The corpus axis is processed in chunks with a running top-k so peak
    memory is ``O(B * chunk_size)``.
    """
    n = corpus_bits.shape[0]
    k = min(k, n)
    approx = n >= APPROX_TOPK_MIN_ROWS
    qu = _unpack_bits(query_bits)  # [B, H] int8
    q_pop = jnp.sum(qu.astype(jnp.int32), axis=1)  # [B]

    @functools.partial(jax.jit, static_argnums=(3,))
    def chunk_distances(q_unpacked, q_popcount, corpus_chunk, chunk_k):
        cu = _unpack_bits(corpus_chunk)  # [C, H] int8
        dots = jax.lax.dot_general(
            q_unpacked, cu, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # [B, C]
        c_pop = jnp.sum(cu.astype(jnp.int32), axis=1)  # [C]
        distances = q_popcount[:, None] + c_pop[None, :] - 2 * dots
        neg, idx = _chunk_candidates(
            -distances.astype(jnp.float32), chunk_k, approx
        )
        return neg, idx

    best_neg = None
    best_idx = None
    for start in range(0, n, chunk_size):
        chunk = corpus_bits[start : start + chunk_size]
        chunk_k = min(k, chunk.shape[0])
        neg, idx = chunk_distances(qu, q_pop, chunk, chunk_k)
        idx = idx + start
        if best_neg is None:
            best_neg, best_idx = neg, idx
        else:
            cat_neg = jnp.concatenate([best_neg, neg], axis=1)
            cat_idx = jnp.concatenate([best_idx, idx], axis=1)
            best_neg, pos = jax.lax.top_k(cat_neg, k)
            best_idx = jnp.take_along_axis(cat_idx, pos, axis=1)
    return (-best_neg).astype(jnp.int32), best_idx
