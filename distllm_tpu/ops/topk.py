"""Exact inner-product top-k over device-sharded corpora.

The FAISS replacement's compute core (SURVEY.md section 2.4 N2): embeddings
live row-sharded across chips (mesh ``data`` axis); each chip computes its
shard's ``Q @ E_shard^T`` on the MXU and a local ``lax.top_k``; the per-shard
candidates (k per chip) are concatenated — a tiny ICI all-gather instead of
gathering the full ``[B, N]`` score matrix — and reduced with one final
``top_k``. Also hosts the binary (Hamming) scoring path used by ubinary
quantized indexes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


def topk_inner_product(
    queries: jnp.ndarray,  # [B, H] fp32
    corpus: jnp.ndarray,  # [N, H] (possibly sharded over mesh 'data')
    k: int,
    mesh: Mesh | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Exact top-k by inner product. Returns (scores [B, k], indices [B, k])."""
    k = min(k, corpus.shape[0])
    if mesh is None or mesh.shape.get('data', 1) == 1:
        scores = queries @ corpus.T
        return jax.lax.top_k(scores, k)
    return _topk_sharded(queries, corpus, k, mesh)


def _sharded_topk(score_fn, row_count, operands, in_specs, k, mesh):
    """Shared multi-chip top-k scaffold: each chip scores its row shard
    (``score_fn(replicated..., sharded...) -> [B, rows/shard]``), takes a
    local top-k, offsets indices by its shard start, and the k-per-chip
    candidates are concatenated (tiny ICI all-gather vs the full [B, N]
    score matrix) and reduced with one final ``top_k``. Both the exact
    fp32 and the int8 tiers route here so the offset/merge math has one
    home."""
    try:
        from jax import shard_map
    except ImportError:  # jax < 0.5 spelling of the same API
        from jax.experimental.shard_map import shard_map

    n_shards = mesh.shape['data']
    shard_rows = row_count // n_shards

    def per_shard(*args):
        scores = score_fn(*args)
        local_k = min(k, scores.shape[1])
        s, i = jax.lax.top_k(scores, local_k)
        offset = jax.lax.axis_index('data') * shard_rows
        return s, i + offset

    sharded = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(P(None, 'data'), P(None, 'data')),
    )
    cand_scores, cand_idx = sharded(*operands)  # [B, k*shards]
    merged_scores, merged_pos = jax.lax.top_k(cand_scores, k)
    merged_idx = jnp.take_along_axis(cand_idx, merged_pos, axis=1)
    return merged_scores, merged_idx


def _topk_sharded(queries, corpus, k, mesh):
    def score(q, e_shard):
        return q @ e_shard.T  # [B, n/shards] on-chip MXU matmul

    return _sharded_topk(
        score, corpus.shape[0], (queries, corpus),
        (P(), P('data', None)), k, mesh,
    )


def quantize_int8_rows(
    embeddings: np.ndarray,
) -> tuple[np.ndarray, np.ndarray]:
    """fp32 ``[N, H]`` → (``int8`` codes ``[N, H]``, fp32 scales ``[N]``).

    Symmetric per-row absmax quantization (sentence-transformers' int8
    precision semantics). 4x smaller than fp32 — the single-chip middle
    tier between exact fp32 (~4M x 768 rows in 16 GiB HBM) and ubinary
    (32x smaller, Hamming-approximate): scores stay MXU matmuls (int8
    inputs, int32 accumulate) and ranking error is ~1e-2 relative, which
    the oversampled fp32 rescore absorbs.
    """
    absmax = np.abs(embeddings).max(axis=1)
    scales = np.where(absmax == 0, 1.0, absmax / 127.0).astype(np.float32)
    codes = np.clip(
        np.round(embeddings / scales[:, None]), -127, 127
    ).astype(np.int8)
    return codes, scales


def int8_topk(
    queries: jnp.ndarray,  # [B, H] fp32
    codes: jnp.ndarray,  # [N, H] int8, or grouped [G, C, H] (group_rows)
    scales: jnp.ndarray,  # [N] fp32 ([G, C] when grouped)
    k: int,
    mesh: Mesh | None = None,
    chunk_size: int = 1 << 19,
    n_valid: int | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k inner product against an int8-quantized corpus.

    Queries are quantized per-row on the fly so the score matmul runs
    int8 x int8 → int32 on the MXU; the true scale is reapplied before
    ``top_k``. The single-device path processes the corpus axis in
    ``chunk_size`` slabs with a running top-k, so peak memory is
    ``O(B * chunk_size)`` rather than ``[B, N]`` — this tier exists for
    corpora past the fp32 HBM limit, where a full score matrix at batch
    128 would itself OOM. Returns (approx scores [B, k], indices [B, k]).

    Pass ``codes`` pre-grouped as ``[G, C, H]`` (:func:`group_rows`, with
    ``scales [G, C]`` and ``n_valid`` = real row count) for the fast
    single-dispatch ``lax.scan`` path — what ``TpuIndexV2`` serves with.
    """
    if codes.ndim == 3:
        if n_valid is None:
            # group_rows zero-pads the last slab; without the real row
            # count those all-zero rows would rank as valid neighbors and
            # leak out-of-range indices to the caller.
            raise ValueError('grouped codes [G, C, H] require n_valid')
        if mesh is not None and mesh.shape.get('data', 1) > 1:
            # The grouped scan is a single-device serving layout; silently
            # ignoring the mesh would score the FULL corpus on every chip
            # and return duplicate candidates. Mirror the n_valid guard.
            raise ValueError(
                'grouped codes [G, C, H] cannot combine with a data-sharded '
                'mesh; pass flat [N, H] codes for the sharded path'
            )
        n = n_valid
        k = min(k, n)
        qmax = jnp.abs(queries).max(axis=1)
        qscale = jnp.where(qmax == 0, 1.0, qmax / 127.0)
        qi = jnp.clip(
            jnp.round(queries / qscale[:, None]), -127, 127
        ).astype(jnp.int8)
        return _grouped_scan_topk(
            (qi, qscale), codes, (scales,),
            scorer='int8', k=k,
            n_valid=n, approx=n >= APPROX_TOPK_MIN_ROWS,
        )
    n = codes.shape[0]
    k = min(k, n)
    qmax = jnp.abs(queries).max(axis=1)
    qscale = jnp.where(qmax == 0, 1.0, qmax / 127.0)
    qi = jnp.clip(
        jnp.round(queries / qscale[:, None]), -127, 127
    ).astype(jnp.int8)

    if mesh is not None and mesh.shape.get('data', 1) > 1:
        # Per-shard rows are already N/shards; each chip scores its slab
        # in one matmul (shard the corpus further if [B, N/shards] scores
        # ever dominate a chip's HBM).
        return _sharded_topk(
            _score_int8, n, (qi, qscale, codes, scales),
            (P(), P(), P('data', None), P('data')), k, mesh,
        )

    # Chunk-local candidate selection: exact below APPROX_TOPK_MIN_ROWS
    # total rows, TPU approx_max_k above (this tier rescored in fp32
    # anyway; exact sort over large chunks dominated the 10M scan).
    approx = n >= APPROX_TOPK_MIN_ROWS

    @functools.partial(jax.jit, static_argnums=(4,))
    def chunk_topk(q_codes, q_scale, codes_part, scales_part, chunk_k):
        return _chunk_candidates(
            _score_int8(q_codes, q_scale, codes_part, scales_part),
            chunk_k,
            approx,
        )

    best_scores = None
    best_idx = None
    for start in range(0, n, chunk_size):
        codes_part = codes[start : start + chunk_size]
        scales_part = scales[start : start + chunk_size]
        chunk_k = min(k, codes_part.shape[0])
        s, i = chunk_topk(qi, qscale, codes_part, scales_part, chunk_k)
        i = i + start
        if best_scores is None:
            best_scores, best_idx = s, i
        else:
            cat_s = jnp.concatenate([best_scores, s], axis=1)
            cat_i = jnp.concatenate([best_idx, i], axis=1)
            best_scores, pos = jax.lax.top_k(cat_s, k)
            best_idx = jnp.take_along_axis(cat_i, pos, axis=1)
    return best_scores, best_idx


def pack_sign_bits(embeddings: np.ndarray) -> np.ndarray:
    """fp32 ``[N, H]`` → uint8 ``[N, H/8]`` sign-bit packing (ubinary).

    Matches sentence-transformers' ``quantize_embeddings(..., 'ubinary')``:
    bit = 1 where value > 0, packed big-endian within each byte.
    """
    if embeddings.shape[1] % 8 != 0:
        raise ValueError(f'embedding dim {embeddings.shape[1]} not divisible by 8')
    bits = (embeddings > 0).astype(np.uint8)
    return np.packbits(bits, axis=1)


# Corpora past this row count switch the per-chunk candidate selection
# from exact lax.top_k (a full bitonic sort over the chunk — measured
# 12.5 s for one 10M-row ubinary scan, chipback_r05) to the TPU-native
# jax.lax.approx_max_k (~0.95 per-element recall). Quantized-tier
# candidates feed an oversampled fp32 rescore, so serving quality is set
# by top1/rescore behavior, not the last near-tie in the candidate set.
APPROX_TOPK_MIN_ROWS = 1 << 20

# Grouped-scan slab sizes (rows per lax.scan step) for the quantized
# tiers — ONE home so the index (rag/search.py) and the retrieval bench
# measure the same serving layout.
SCAN_CHUNK_BITS = 1 << 18
SCAN_CHUNK_INT8 = 1 << 19


def group_rows(arr: np.ndarray, chunk: int) -> np.ndarray:
    """Host-side: pad ``[N, ...]`` to a chunk multiple and reshape to
    ``[G, chunk, ...]`` — the layout the grouped-scan tops consume.

    Do this ONCE at index build: the grouped tensors ride a single-
    dispatch ``lax.scan`` whose chunk slabs are contiguous scan slices.
    Measured on the chip at 10M x 768 int8: 32 ms/scan grouped vs
    seconds for the python slice-per-chunk loop over a monolithic
    device array (chipback_r05/probe_retrieval_scan.log and the
    prof_slice experiments behind it).
    """
    n = arr.shape[0]
    pad = (-n) % chunk
    if pad:
        arr = np.concatenate(
            [arr, np.zeros((pad, *arr.shape[1:]), arr.dtype)]
        )
    return arr.reshape(arr.shape[0] // chunk, chunk, *arr.shape[1:])


def _score_int8(qi, qscale, codes_part, scales_part):
    """int8 x int8 → int32 MXU scores with the true scales reapplied —
    the ONE home for the int8 scoring formula (flat loop, grouped scan,
    and the sharded path all call this)."""
    raw = jax.lax.dot_general(
        qi, codes_part, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return raw.astype(jnp.float32) * qscale[:, None] * scales_part[None, :]


def _score_hamming(qu, q_pop, chunk_bits):
    """Negated Hamming distances via the MXU identity
    ``hamming(a,b) = |a| + |b| - 2 a·b`` over unpacked 0/1 int8 vectors
    (higher = closer, so top-k machinery applies unchanged)."""
    cu = _unpack_bits(chunk_bits)
    dots = jax.lax.dot_general(
        qu, cu, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    c_pop = jnp.sum(cu.astype(jnp.int32), axis=1)
    distances = q_pop[:, None] + c_pop[None, :] - 2 * dots
    return -distances.astype(jnp.float32)


def _score_grouped_chunk(scorer: str, queries, chunk, extras):
    """Per-chunk fp32 scores [B, C] for the grouped-scan tops."""
    if scorer == 'int8':
        qi, qscale = queries
        (scales_c,) = extras
        return _score_int8(qi, qscale, chunk, scales_c)
    if scorer == 'hamming':
        qu, q_pop = queries
        return _score_hamming(qu, q_pop, chunk)
    raise ValueError(scorer)


@functools.partial(
    jax.jit, static_argnames=('scorer', 'k', 'n_valid', 'approx')
)
def _grouped_scan_topk(
    queries, corpus3, extras, *, scorer, k, n_valid, approx
):
    """Single-dispatch top-k over a grouped corpus ``[G, C, ...]``.

    Padded rows (global index >= n_valid) mask to -inf before candidate
    selection; per-chunk candidates merge once at the end (G*chunk_k is
    tiny). One executable per (scorer, shapes) — the scan runs all G
    chunks inside a single dispatch, which is what makes the 10M scan
    ~32 ms instead of seconds of per-chunk dispatch/slice overhead.
    """
    c = corpus3.shape[1]
    chunk_k = min(k, c)

    def body(g, xs):
        scores = _score_grouped_chunk(scorer, queries, xs[0], xs[1:])
        base = g * c
        col = base + jnp.arange(c)[None, :]
        scores = jnp.where(col < n_valid, scores, -jnp.inf)
        s, i = _chunk_candidates(scores, chunk_k, approx)
        return g + 1, (s, i + base)

    _, (ss, ii) = jax.lax.scan(body, 0, (corpus3, *extras))
    b = ss.shape[1]
    flat_s = jnp.transpose(ss, (1, 0, 2)).reshape(b, -1)
    flat_i = jnp.transpose(ii, (1, 0, 2)).reshape(b, -1)
    # Final exact merge returns the CALLER'S k (bounded by what exists),
    # not the per-chunk k — k > chunk size must not truncate silently.
    top_s, pos = jax.lax.top_k(flat_s, min(k, flat_s.shape[1]))
    return top_s, jnp.take_along_axis(flat_i, pos, axis=1)


def _chunk_candidates(scores_f32: jnp.ndarray, k: int, approx: bool):
    if approx:
        return jax.lax.approx_max_k(scores_f32, k)
    return jax.lax.top_k(scores_f32, k)


def _unpack_bits(packed: jnp.ndarray) -> jnp.ndarray:
    """uint8 ``[..., H/8]`` → 0/1 int8 ``[..., H]`` (big-endian, matching
    :func:`pack_sign_bits` / np.packbits)."""
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    bits = (packed[..., :, None] >> shifts) & jnp.uint8(1)
    return bits.astype(jnp.int8).reshape(*packed.shape[:-1], -1)


def hamming_topk(
    query_bits: jnp.ndarray,  # [B, H/8] uint8
    corpus_bits: jnp.ndarray,  # [N, H/8] uint8, or grouped [G, C, H/8]
    k: int,
    chunk_size: int = 1 << 18,
    n_valid: int | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Top-k by smallest Hamming distance. Returns (distances, indices).

    Scoring is an MXU matmul, not a VPU popcount sweep:
    ``hamming(a, b) = |a| + |b| - 2 a·b`` over the unpacked 0/1 vectors,
    so each chunk unpacks to int8 in VMEM-sized slabs and scores as an
    int8 x int8 → int32 dot. (The first implementation XOR+popcounted a
    materialized [B, chunk, H/8] tensor and exact-sorted every chunk:
    12.5 s per 10M-row scan on the chip.) Distances are exact ints;
    candidate selection per chunk is exact below ``APPROX_TOPK_MIN_ROWS``
    rows and TPU ``approx_max_k`` above. The corpus axis is processed in
    chunks with a running top-k so peak memory is ``O(B * chunk_size)``.

    Pass ``corpus_bits`` pre-grouped as ``[G, C, H/8]``
    (:func:`group_rows`, with ``n_valid`` = real row count) for the
    single-dispatch ``lax.scan`` path serving uses.
    """
    if corpus_bits.ndim == 3:
        if n_valid is None:
            raise ValueError('grouped corpus [G, C, H/8] requires n_valid')
        n = n_valid
        k = min(k, n)
        qu3 = _unpack_bits(query_bits)
        q_pop3 = jnp.sum(qu3.astype(jnp.int32), axis=1)
        neg, idx = _grouped_scan_topk(
            (qu3, q_pop3), corpus_bits, (),
            scorer='hamming', k=k,
            n_valid=n, approx=n >= APPROX_TOPK_MIN_ROWS,
        )
        # approx_max_k's bin maxima can surface -inf-masked padded rows as
        # candidates when a chunk has fewer valid rows than bins; casting
        # -(-inf) to int32 is UB in XLA. Clamp those candidates to a finite
        # max-distance sentinel so callers see an unambiguous "no neighbor"
        # distance (true distances are <= H) instead of garbage. The
        # sentinel must be fp32-REPRESENTABLE below 2**31: -(2**31 - 1)
        # rounds to -2**31 in fp32 and its negation overflows the very
        # int32 cast this guards; 2**31 - 128 is the largest fp32 value
        # strictly under INT32_MAX.
        neg = jnp.maximum(neg, jnp.float32(-2147483520.0))
        return (-neg).astype(jnp.int32), idx
    n = corpus_bits.shape[0]
    k = min(k, n)
    approx = n >= APPROX_TOPK_MIN_ROWS
    qu = _unpack_bits(query_bits)  # [B, H] int8
    q_pop = jnp.sum(qu.astype(jnp.int32), axis=1)  # [B]

    @functools.partial(jax.jit, static_argnums=(3,))
    def chunk_distances(q_unpacked, q_popcount, corpus_chunk, chunk_k):
        return _chunk_candidates(
            _score_hamming(q_unpacked, q_popcount, corpus_chunk),
            chunk_k,
            approx,
        )

    best_neg = None
    best_idx = None
    for start in range(0, n, chunk_size):
        chunk = corpus_bits[start : start + chunk_size]
        chunk_k = min(k, chunk.shape[0])
        neg, idx = chunk_distances(qu, q_pop, chunk, chunk_k)
        idx = idx + start
        if best_neg is None:
            best_neg, best_idx = neg, idx
        else:
            cat_neg = jnp.concatenate([best_neg, neg], axis=1)
            cat_idx = jnp.concatenate([best_idx, idx], axis=1)
            best_neg, pos = jax.lax.top_k(cat_neg, k)
            best_idx = jnp.take_along_axis(cat_idx, pos, axis=1)
    return (-best_neg).astype(jnp.int32), best_idx
