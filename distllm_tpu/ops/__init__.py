"""TPU kernels and numeric ops: attention, paged KV attention, sampling,
top-k retrieval, quantization. XLA implementations are the portable baseline;
Pallas kernels provide the TPU fast paths (same signatures, tested against
each other)."""
