"""TPU kernels and numeric ops: attention, paged KV attention, sampling,
top-k retrieval, quantization. XLA implementations are the portable baseline;
Pallas kernels provide the TPU fast paths (same signatures, tested against
each other)."""


def tpu_compiler_params(**kwargs):
    """Build Pallas TPU compiler params across the jax 0.4.x/0.5 rename
    (``TPUCompilerParams`` -> ``CompilerParams``) — one home so every
    kernel resolves the installed spelling the same way and a missing
    class fails with the actual requirement instead of a bare
    ``NoneType is not callable``."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(
        pltpu, 'CompilerParams', getattr(pltpu, 'TPUCompilerParams', None)
    )
    if cls is None:
        raise ImportError(
            'jax.experimental.pallas.tpu exposes neither CompilerParams '
            'nor TPUCompilerParams; this jax version is unsupported'
        )
    return cls(**kwargs)
