"""Paged-KV attention — the core kernel of the generation engine.

The reference delegates this to vLLM's CUDA paged-attention
(``generate/generators/vllm_backend.py``; SURVEY.md section 2.4 N1). Here the
KV cache lives in HBM as fixed-size blocks::

    k_cache, v_cache : [num_blocks, block_size, num_kv_heads, head_dim]

and each sequence owns a row of ``block_tables`` (block ids, padded) plus a
``context_lens`` entry (valid tokens). Every serving dispatch — decode
windows, mixed prefill+decode, chunked/prefix-cache tail prefill, and
speculative verification — funnels through the RAGGED per-row-query-span
formulation, which has two implementations behind one backend selector
(:func:`ragged_paged_attention`):

- :func:`ragged_paged_attention_xla` — gather + masked softmax; XLA fuses
  this well and it is the portable, always-available baseline (also runs on
  CPU for tests) and the bit-exactness reference.
- :func:`ragged_paged_attention_pallas` — fused Pallas TPU kernel: grid
  over (row, query tile, KV chunk); block tables are scalar-prefetched and
  each grid step explicitly DMAs only the row's live KV pages HBM→VMEM
  with double buffering (issue chunk c+1 while computing chunk c),
  online-softmax accumulation in fp32 scratch — no ``[.., S, T]`` score
  tensor is ever materialized. Chunks outside a row's valid window (beyond
  ``context_lens``, past the row's last query, or before the
  sliding-window start) are skipped: no DMA, no compute.

Both handle GQA (query heads grouped natively over KV heads), per-row query
spans with ``q_lens`` padding masks, static or TRACED sliding windows
(gemma2 alternating layers), ``logit_softcap``, custom score scales, and
fp32 softmax/accumulation. A decode row is just the span-1 degenerate case:
:func:`paged_attention_pallas` is a thin span-1 wrapper over the ragged
kernel, while :func:`paged_attention_xla` keeps its own dense decode-shaped
formulation (same math, separately maintained — fixes to the ragged XLA op
do NOT automatically reach it).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from distllm_tpu.observability.instruments import ATTN_BACKEND_LABELS

# Head dims the Pallas kernel is exercised at in CI (tests/test_aot_tpu.py
# compiles these against a real v5e topology). The kernel's structural
# requirement is only head_dim % 128 == 0 (Mosaic DMA alignment, checked in
# paged_attention_pallas), but 'auto' backend selection routes through
# supported_head_dim so untested shapes never auto-enable the kernel —
# widen this tuple when a new shape gains AOT coverage.
TESTED_HEAD_DIMS = (128,)


def supported_head_dim(head_dim: int) -> bool:
    """True when `attn_backend='auto'` may select the Pallas kernel."""
    return head_dim in TESTED_HEAD_DIMS


# Legal values for the engine/generator `attn_backend` selector. 'auto'
# resolves at engine construction (pinned like qmm_backend, never re-read
# mid-serve): 'pallas' on TPU when supports_model passes, else 'xla'.
# 'interpret' runs the SAME ragged Pallas kernel through the Pallas
# interpreter — CPU-runnable, the parity/identity test tier. The non-'auto'
# labels are owned by the metrics catalog (one source for the selector set
# and the distllm_engine_attn_backend_info scrape schema).
ATTN_BACKENDS = ('auto', *ATTN_BACKEND_LABELS)


def supports_model(model_cfg) -> bool:
    """May `attn_backend='auto'` select the Pallas kernel for this model?

    The ragged kernel natively implements attention logit softcapping,
    traced per-layer (gemma2 alternating) sliding windows, and custom
    score scales, so eligibility is purely the head-dim DMA/CI contract.
    """
    return supported_head_dim(model_cfg.head_size)


def kv_sublane_tile(kv_dtype) -> int:
    """Sublane-tile rows for a KV-cache dtype (Mosaic: 8 for 4-byte,
    16 for 2-byte, 32 for 1-byte). The ragged kernel DMAs each page into
    a ``block_size``-row band of its folded VMEM buffer, so ``block_size``
    must be a multiple of this."""
    return max(1, 32 // jnp.dtype(kv_dtype).itemsize)


class QuantizedKV(NamedTuple):
    """Int8 paged-KV container: block data plus per-block-per-KV-head scales.

    ``data`` keeps the paged layout (``[..., num_blocks, block_size,
    num_kv_heads, head_dim]`` int8) and ``scale`` a parallel fp32 array
    with the block-size and head-dim axes dropped (``[..., num_blocks,
    num_kv_heads]``) — symmetric quantization, ``x ≈ data * scale`` with
    ``scale = absmax / 127`` over the block's live rows per KV head. The
    engine's pool carries a leading layer axis on both members; per-layer
    slices inside the model scans drop it.

    A NamedTuple is an automatic pytree, so a ``QuantizedKV`` rides the
    existing k/v argument slots through ``jax.jit`` (donation applies to
    both leaves), ``lax.scan`` carries, and ``jax.tree.map``-written
    block ops (gather/scatter/copy treat data and scale uniformly
    because the block axis is axis -4 of ``data`` and axis -2 of
    ``scale`` — axis 1 of each for the engine's pool). Full-precision
    caches stay bare arrays: every op in this module dispatches on
    ``isinstance(cache, QuantizedKV)`` so the unquantized paths emit
    bit-identical HLO to the pre-int8 code.
    """

    data: jnp.ndarray
    scale: jnp.ndarray


# Symmetric int8 range: scale = absmax / KV_QUANT_MAX maps the block's
# largest magnitude to +/-127 (-128 unused, keeping the code symmetric).
KV_QUANT_MAX = 127.0


def kv_storage_dtype(cache):
    """The dtype KV blocks are stored as (int8 for :class:`QuantizedKV`)."""
    if isinstance(cache, QuantizedKV):
        return jnp.dtype(cache.data.dtype)
    return jnp.dtype(cache.dtype)


def _kv_data(cache):
    return cache.data if isinstance(cache, QuantizedKV) else cache


def quantize_kv_rows(rows, scale):  # distlint: traced
    """Quantize ``rows`` (``[..., num_kv_heads, head_dim]``) against a
    per-KV-head ``scale`` (``[..., num_kv_heads]``). Zero scales (fresh
    all-zero blocks, trash-block garbage) emit exact zeros — the guarded
    denominator keeps the traced division finite so no NaN can reach the
    scatter even on the dead branch of the ``where``."""
    denom = jnp.where(scale > 0, scale, 1.0)[..., None]
    q = jnp.round(rows.astype(jnp.float32) / denom)
    q = jnp.clip(q, -KV_QUANT_MAX, KV_QUANT_MAX)
    return jnp.where(scale[..., None] > 0, q, 0.0).astype(jnp.int8)


def _rescale_int8_blocks(data, old_scale, new_scale):  # distlint: traced
    """Re-express int8 block rows quantized at ``old_scale`` in units of
    ``new_scale`` (``data [..., block_size, num_kv_heads, head_dim]``,
    scales ``[..., num_kv_heads]``). Appends only ever GROW a block's
    running absmax (``new_scale >= old_scale``), so the ratio is <= 1 and
    the rounded product stays in range; zero ``new_scale`` (fresh or
    trash blocks) zeroes the stale rows."""
    denom = jnp.where(new_scale > 0, new_scale, 1.0)
    ratio = jnp.where(new_scale > 0, old_scale / denom, 0.0)
    out = jnp.round(data.astype(jnp.float32) * ratio[..., None, :, None])
    return jnp.clip(out, -KV_QUANT_MAX, KV_QUANT_MAX).astype(jnp.int8)


def _gather_kv_blocks(cache, block_tables):  # distlint: traced
    """Gather ``[B, max_blocks, block_size, num_kv_heads, head_dim]``
    blocks for attention, dequantizing int8 caches in the same fused
    expression (XLA folds the scale multiply into the gather consumers —
    no separate dequant pass or fp32 cache copy is ever materialized).
    Bare-array caches take the exact pre-int8 gather."""
    if isinstance(cache, QuantizedKV):
        scales = cache.scale[block_tables]  # [B, max_blocks, num_kv_heads]
        return (
            cache.data[block_tables].astype(jnp.float32)
            * scales[:, :, None, :, None]
        )
    return cache[block_tables]


def resolve_attn_backend(
    attn_backend: str,
    model_cfg,
    *,
    block_size: 'int | None' = None,
    kv_dtype=None,
) -> str:
    """Resolve the ``attn_backend`` selector to a concrete kernel, once.

    Mirrors the ``qmm_backend`` pinning pattern: the engine calls this at
    construction and closes its jitted serving functions over the result,
    so a config change after init can never re-route live dispatches.
    'auto' picks the Pallas kernel on TPU for CI-covered head dims —
    AND, when the caller provides the KV block geometry, only when
    ``block_size`` meets the kernel's sublane-tile DMA contract — and
    falls back to the always-available XLA path everywhere else (an
    'auto' config must never trace into the kernel's ValueErrors).
    """
    if attn_backend not in ATTN_BACKENDS:
        raise ValueError(
            f'attn_backend must be one of {ATTN_BACKENDS}, '
            f'got {attn_backend!r}'
        )
    if attn_backend != 'auto':
        return attn_backend
    eligible = jax.default_backend() == 'tpu' and supports_model(model_cfg)
    if eligible and block_size is not None and kv_dtype is not None:
        eligible = block_size % kv_sublane_tile(kv_dtype) == 0
    return 'pallas' if eligible else 'xla'


def paged_attention_xla(  # distlint: traced
    q: jnp.ndarray,  # [B, num_heads, head_dim]
    k_cache: jnp.ndarray,  # [num_blocks, block_size, num_kv_heads, head_dim]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks] int32
    context_lens: jnp.ndarray,  # [B] int32 (valid tokens incl. current)
    sliding_window: 'int | jnp.ndarray | None' = None,
    scale: float | None = None,
    logit_softcap: float | None = None,
) -> jnp.ndarray:
    """Reference implementation: gather blocks then masked attention.

    ``sliding_window`` may be a static int, None, or a TRACED int32 scalar
    (per-layer windows riding a layer scan — gemma2's alternating
    local/global pattern; 0/negative means no window on that layer).
    ``scale`` overrides the 1/sqrt(head_dim) score scale
    (query_pre_attn_scalar); ``logit_softcap`` applies tanh(s/cap)*cap to
    the scaled scores before masking (both gemma2).
    """
    b, num_heads, head_dim = q.shape
    _, block_size, num_kv_heads, _ = _kv_data(k_cache).shape
    max_blocks = block_tables.shape[1]
    group = num_heads // num_kv_heads

    # [B, max_blocks, block_size, Nkv, Hd] -> [B, T, Nkv, Hd]
    # (int8 caches dequantize inside the gather expression)
    k = _gather_kv_blocks(k_cache, block_tables).reshape(
        b, max_blocks * block_size, num_kv_heads, head_dim
    )
    v = _gather_kv_blocks(v_cache, block_tables).reshape(
        b, max_blocks * block_size, num_kv_heads, head_dim
    )

    qg = q.reshape(b, num_kv_heads, group, head_dim).astype(jnp.float32)
    scores = jnp.einsum('bkgd,btkd->bkgt', qg, k.astype(jnp.float32))
    scores = scores * jnp.float32(
        scale if scale is not None else head_dim ** -0.5
    )
    if logit_softcap is not None:
        from distllm_tpu.models.common import softcap

        scores = softcap(scores, logit_softcap)
    positions = jnp.arange(max_blocks * block_size)[None, :]
    valid = positions < context_lens[:, None]
    if sliding_window is not None:
        # Match prefill's window mask: only the last `sliding_window` keys.
        # For a traced window, <= 0 disables the clamp on that layer.
        windowed = positions > context_lens[:, None] - 1 - sliding_window
        if isinstance(sliding_window, int):
            valid = valid & windowed
        else:
            valid = valid & (windowed | (sliding_window <= 0))
    scores = jnp.where(valid[:, None, None, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum('bkgt,btkd->bkgd', probs, v.astype(jnp.float32))
    return out.reshape(b, num_heads, head_dim).astype(q.dtype)


def ragged_paged_attention_xla(  # distlint: traced
    q: jnp.ndarray,  # [B, S, num_heads, head_dim] per-row query spans
    k_cache: jnp.ndarray,  # [num_blocks, block_size, num_kv_heads, head_dim]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks] int32
    context_lens: jnp.ndarray,  # [B] total valid tokens incl. the span
    q_positions: jnp.ndarray,  # [B, S] absolute position of each query
    q_lens: 'jnp.ndarray | None' = None,  # [B] valid queries per row
    sliding_window: 'int | jnp.ndarray | None' = None,
    scale: float | None = None,
    logit_softcap: float | None = None,
) -> jnp.ndarray:
    """Ragged per-row-query-length attention over paged KV — the shared
    op of prefix-cache tail prefill, chunked prefill, mixed
    prefill+decode serving windows, and speculative verification
    (docs/serving.md).

    Each row carries a SPAN of queries at absolute ``q_positions``; every
    query attends to all cached positions ``<=`` its own (the span's K/V
    must already be written into the paged blocks — write-then-attend,
    exactly like the decode path). Rows are ragged: a decode row is the
    span-1 DEGENERATE CASE (its single query at position
    ``context_lens - 1`` sees the whole context — numerically the
    :func:`paged_attention_xla` result, though that op keeps its own
    standalone dense formulation: a masking or numeric fix here must be
    mirrored there), while a prefill-chunk row's queries attend
    causally over chunk + paged prefix. ``q_lens`` (optional) masks each
    row's padding queries so their softmax rows stay finite; with
    ``q_lens=None`` padding queries compute garbage the caller discards
    (masking only touches pad rows — valid rows are bit-identical either
    way). Gather + masked fp32 softmax; XLA fuses this well and it runs
    on CPU for tests.

    This is the portable baseline and bit-exactness reference of the
    backend pair: :func:`ragged_paged_attention_pallas` is the fused TPU
    fast path (grid over row × query tile × KV chunk, online softmax, no
    dense score tensor), selected per engine via
    :func:`ragged_paged_attention`'s ``backend`` argument. This XLA path
    stays the always-available fallback and the identity baseline the
    parity matrix (``tests/test_ragged_attention.py``) pins the kernel
    against.
    """
    b, s, num_heads, head_dim = q.shape
    _, block_size, num_kv_heads, _ = _kv_data(k_cache).shape
    max_blocks = block_tables.shape[1]
    group = num_heads // num_kv_heads

    k = _gather_kv_blocks(k_cache, block_tables).reshape(
        b, max_blocks * block_size, num_kv_heads, head_dim
    )
    v = _gather_kv_blocks(v_cache, block_tables).reshape(
        b, max_blocks * block_size, num_kv_heads, head_dim
    )
    qg = q.reshape(b, s, num_kv_heads, group, head_dim).astype(jnp.float32)
    scores = jnp.einsum('bskgd,btkd->bkgst', qg, k.astype(jnp.float32))
    scores = scores * jnp.float32(
        scale if scale is not None else head_dim ** -0.5
    )
    if logit_softcap is not None:
        from distllm_tpu.models.common import softcap

        scores = softcap(scores, logit_softcap)
    kv_pos = jnp.arange(max_blocks * block_size)[None, None, :]  # [1, 1, T]
    qp = q_positions[:, :, None]  # [B, S, 1]
    valid = (kv_pos < context_lens[:, None, None]) & (kv_pos <= qp)
    if sliding_window is not None:
        # Same window semantics as the dense prefill mask: query at
        # position p sees keys in (p - window, p]. Traced windows <= 0
        # disable the clamp (gemma2 alternating layers).
        windowed = kv_pos > qp - sliding_window
        if isinstance(sliding_window, int):
            valid = valid & windowed
        else:
            valid = valid & (windowed | (sliding_window <= 0))
    if q_lens is not None:
        # Padding queries keep key 0 visible: an all-masked softmax row is
        # NaN, and a NaN in a pad row can poison reductions downstream.
        q_valid = jnp.arange(s)[None, :, None] < q_lens[:, None, None]
        valid = valid | (~q_valid & (kv_pos == 0))
    scores = jnp.where(valid[:, None, None, :, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum('bkgst,btkd->bskgd', probs, v.astype(jnp.float32))
    return out.reshape(b, s, num_heads, head_dim).astype(q.dtype)


def paged_prefill_attention_xla(  # distlint: traced
    q: jnp.ndarray,  # [B, S, num_heads, head_dim] tail queries
    k_cache: jnp.ndarray,  # [num_blocks, block_size, num_kv_heads, head_dim]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks] int32
    context_lens: jnp.ndarray,  # [B] total valid tokens incl. the tail
    q_positions: jnp.ndarray,  # [B, S] absolute position of each query
    sliding_window: 'int | jnp.ndarray | None' = None,
    scale: float | None = None,
    logit_softcap: float | None = None,
) -> jnp.ndarray:
    """Multi-query attention over paged KV: prefix-cache / chunked prefill
    tail queries attending to cached history + themselves.

    Now a thin alias of :func:`ragged_paged_attention_xla` (every tail row
    is a ragged span; ``q_lens`` stays ``None`` so the emitted HLO — and
    bit pattern — is unchanged from the pre-ragged op; padding-row logits
    are garbage the caller discards).
    """
    return ragged_paged_attention_xla(
        q, k_cache, v_cache, block_tables, context_lens, q_positions,
        q_lens=None, sliding_window=sliding_window, scale=scale,
        logit_softcap=logit_softcap,
    )


def _ragged_paged_attn_kernel(
    # Operand order (positional, by grid-spec contract):
    #
    # scalar-prefetch (SMEM):
    #   block_tables_ref,  # [B, max_blocks] int32
    #   context_lens_ref,  # [B] int32
    #   q_start_ref,  # [B] int32 — absolute position of row's first query
    #   q_lens_ref,  # [B] int32 — valid queries per row (0 = fully padded)
    #   window_ref,  # [1] int32 — sliding window; <= 0 disables
    # array operands. The KV caches arrive HEAD-FOLDED: the caller
    # bitcast-reshapes [num_blocks, block_size, num_kv_heads, head_dim]
    # to [num_blocks, block_size, num_kv_heads * head_dim] (row-major —
    # free), so each KV head occupies a 128-aligned LANE band. This is
    # the layout trick that retires the Mosaic rejections the decode-only
    # kernel died on (both reproduced + pinpointed on this container's
    # toolchain, 2026-08-04): slicing the kv-head dim out of the MIDDLE
    # of a page buffer (kb[:, h, :]) is an "implicit dim change", and
    # per-head HBM DMA slices (cache[page, :, h]) break sublane tile
    # alignment whenever num_kv_heads < the tile — while a static lane
    # slice at a 128 multiple is always tile-aligned.
    #   q_ref,  # [num_kv_heads, span_tile * group, head_dim] (VMEM)
    #   k_cache_ref,  # [num_blocks, block_size, num_kv_heads*head_dim] (HBM)
    #   v_cache_ref,
    #   [k_scale_ref, v_scale_ref]  # quantized only: [num_blocks, 128]
    #       fp32 (HBM) — per-block per-KV-head scales, lane-padded to 128
    #       so each page's scale row DMAs with an aligned minor dim
    #   out_ref,  # [num_kv_heads, span_tile * group, head_dim] (VMEM)
    # scratch — KV buffers are pre-flattened [slot, chunk_tokens, folded]:
    # each page DMAs into a statically-offset row band, so the compute
    # side never reshapes at all (a traced-slot reshape was the third
    # Mosaic lowering rejection this layout designs out).
    #   k_buf,  # [2, chunk_tokens, num_kv_heads * head_dim] VMEM
    #   v_buf,
    #   [ks_buf, vs_buf]  # quantized only: [2, pages_per_chunk, 128] fp32
    #   sems,  # DMA semaphores [2, pages_per_chunk, 2 (4 when quantized)]
    #   acc_ref,  # [num_kv_heads, span_tile * group, head_dim] fp32
    #   m_ref,  # [num_kv_heads, span_tile*group, 128] fp32, lane-replicated
    #   l_ref,  # [num_kv_heads, span_tile*group, 128] fp32, lane-replicated
    *refs,
    block_size: int,
    pages_per_chunk: int,
    num_kv_heads: int,
    group: int,
    span_tile: int,
    scale: float,
    logit_softcap: float | None,
    quantized: bool = False,
):
    """Grid (B, q_tiles, kv_chunks): one row × one query tile × one chunk
    of KV pages per step.

    Pages of a chunk are DMA'd HBM→VMEM individually (they are scattered
    by the paged allocator), double-buffered across grid steps: while
    chunk c computes, chunk c+1's copies are in flight. Chunks a tile
    cannot see — beyond ``context_lens``, past the tile's last query
    (causality), or entirely before the sliding-window start of its first
    query — issue no DMAs and no compute, so a decode row (span 1) pays
    exactly the old decode-only kernel's traffic and a chunk row streams
    only its causal prefix per tile.

    Online softmax is the flash-attention recurrence per (query, head)
    lane: running max ``m`` and denominator ``l`` live lane-replicated in
    fp32 scratch (minor dim 128 — never a 1-wide minor dim, which is what
    tripped Mosaic's "implicit dim change" lowering on the retired
    decode-only kernel), the chunk's probabilities are folded into the
    fp32 accumulator with the usual ``exp(m_prev - m_new)`` correction,
    and no ``[.., S, T]`` score tensor ever exists.
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if quantized:
        (
            block_tables_ref, context_lens_ref, q_start_ref, q_lens_ref,
            window_ref, q_ref, k_cache_ref, v_cache_ref, k_scale_ref,
            v_scale_ref, out_ref, k_buf, v_buf, ks_buf, vs_buf, sems,
            acc_ref, m_ref, l_ref,
        ) = refs
    else:
        (
            block_tables_ref, context_lens_ref, q_start_ref, q_lens_ref,
            window_ref, q_ref, k_cache_ref, v_cache_ref, out_ref,
            k_buf, v_buf, sems, acc_ref, m_ref, l_ref,
        ) = refs

    seq = pl.program_id(0)
    qt = pl.program_id(1)
    c = pl.program_id(2)
    num_chunks = pl.num_programs(2)
    ctx = context_lens_ref[seq]
    q0 = q_start_ref[seq]
    q_len = q_lens_ref[seq]
    win = window_ref[0]
    chunk_tokens = pages_per_chunk * block_size
    head_dim = q_ref.shape[-1]
    rows = span_tile * group  # query-tile rows per KV head

    # Pages this row actually owns (valid block-table prefix).
    n_pages = (ctx + block_size - 1) // block_size
    span_off = qt * span_tile  # first span index of this query tile
    # Keys this tile can ever see: [lo, hi). The tile's FIRST query has
    # the lowest sliding-window floor; its LAST valid query bounds the
    # causal ceiling. Fully padded tiles (span_off >= q_len) skip
    # everything and emit zeros.
    lo = jnp.where(win > 0, jnp.maximum(q0 + span_off - win + 1, 0), 0)
    hi = jnp.minimum(ctx, q0 + jnp.minimum(q_len, span_off + span_tile))
    tile_active = q_len > span_off

    def chunk_needed(ci):
        start = ci * chunk_tokens
        return tile_active & (start < hi) & ((ci + 1) * chunk_tokens > lo)

    def issue(ci, slot):
        # Clamp logical page ids into the row's valid range: the DMA
        # engine must copy *something* per issued descriptor, and the
        # compute mask discards anything outside [lo, hi). One contiguous
        # whole-page descriptor per page (the head fold keeps pages
        # contiguous, so the descriptor count stays 2 per page).
        for p in range(pages_per_chunk):
            logical = ci * pages_per_chunk + p
            page = jnp.clip(logical, 0, jnp.maximum(n_pages - 1, 0))
            page_id = block_tables_ref[seq, page]
            rows_at = slice(p * block_size, (p + 1) * block_size)
            pltpu.make_async_copy(
                k_cache_ref.at[page_id],
                k_buf.at[slot, rows_at],
                sems.at[slot, p, 0],
            ).start()
            pltpu.make_async_copy(
                v_cache_ref.at[page_id],
                v_buf.at[slot, rows_at],
                sems.at[slot, p, 1],
            ).start()
            if quantized:
                # The page's scale row rides the same double-buffered
                # prefetch: a 128-lane fp32 row per page (512 B) next to
                # the page's int8 payload — dequant needs no extra pass.
                pltpu.make_async_copy(
                    k_scale_ref.at[page_id],
                    ks_buf.at[slot, p],
                    sems.at[slot, p, 2],
                ).start()
                pltpu.make_async_copy(
                    v_scale_ref.at[page_id],
                    vs_buf.at[slot, p],
                    sems.at[slot, p, 3],
                ).start()

    def wait(slot):
        for p in range(pages_per_chunk):
            rows_at = slice(p * block_size, (p + 1) * block_size)
            pltpu.make_async_copy(
                k_cache_ref.at[0],
                k_buf.at[slot, rows_at],
                sems.at[slot, p, 0],
            ).wait()
            pltpu.make_async_copy(
                v_cache_ref.at[0],
                v_buf.at[slot, rows_at],
                sems.at[slot, p, 1],
            ).wait()
            if quantized:
                pltpu.make_async_copy(
                    k_scale_ref.at[0],
                    ks_buf.at[slot, p],
                    sems.at[slot, p, 2],
                ).wait()
                pltpu.make_async_copy(
                    v_scale_ref.at[0],
                    vs_buf.at[slot, p],
                    sems.at[slot, p, 3],
                ).wait()

    @pl.when(c == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

        @pl.when(chunk_needed(0))
        def _():
            issue(0, 0)

    # Double buffering: start chunk c+1's copies before computing chunk c.
    @pl.when((c + 1 < num_chunks) & chunk_needed(c + 1))
    def _():
        issue(c + 1, (c + 1) % 2)

    def compute(slot):
        # ``slot`` is a PYTHON int (the caller branches on chunk parity):
        # every KV access below is a static-slot, static-lane-band load
        # straight from the ref. This toolchain's Mosaic rejects a
        # full-plane bf16 load of the folded buffer ("invalid offsets in
        # tiling target" — construct-probed 2026-08-04: full-plane f32
        # loads and per-band bf16 loads both compile; only the
        # full-plane bf16 load fails), so the head band IS the load.
        # Per-score-row span index / absolute query position. Query-tile
        # rows interleave (span, group): row r serves span span_off +
        # r // group, so GQA head grouping is native — one [rows, C] dot
        # per KV head scores every query x grouped-head pair at once.
        span_idx = span_off + (
            jax.lax.broadcasted_iota(jnp.int32, (rows, 1), 0) // group
        )  # [rows, 1]
        qp = q0 + span_idx  # [rows, 1] absolute query positions
        kvp = c * chunk_tokens + jax.lax.broadcasted_iota(
            jnp.int32, (1, chunk_tokens), 1
        )  # [1, C] absolute key positions
        valid = (kvp < ctx) & (kvp <= qp) & (span_idx < q_len)
        # Sliding window: query at position p sees keys in (p - win, p];
        # win <= 0 disables (gemma2 alternating layers ride a traced
        # per-layer window where 0 means global).
        valid = valid & ((kvp > qp - win) | (win <= 0))

        if quantized:
            # Per-key page index [1, C]: dequant applies each page's
            # per-head scale to its block_size-column band of the scores
            # (q · (k_int8 · s) == (q · k_int8) · s per key column), so
            # the int8 band feeds the MXU untouched and the scale is one
            # VPU multiply on the [rows, C] scores — the fused-dequant
            # shape, never an fp32 KV copy in VMEM.
            col_page = jax.lax.broadcasted_iota(
                jnp.int32, (1, chunk_tokens), 1
            ) // block_size

            def page_scale_vec(scale_buf, slot, h):
                vec = jnp.zeros((1, chunk_tokens), jnp.float32)
                for p in range(pages_per_chunk):  # static unroll
                    vec = jnp.where(
                        col_page == p, scale_buf[slot, p, h], vec
                    )
                return vec

        for h in range(num_kv_heads):  # static unroll over KV heads
            qh = q_ref[h]  # [rows, Hd]
            # Head h is a static LANE band of the folded buffer — a
            # 128-aligned slice, always tile-aligned.
            kh = k_buf[slot, :, h * head_dim:(h + 1) * head_dim]  # [C, Hd]
            if kh.dtype != qh.dtype:
                # int8 bands (and bf16 pools under fp32 models) promote
                # to the query dtype for the MXU dot; int8 magnitudes
                # (<= 127) are exact in bf16's 8-bit significand.
                kh = kh.astype(qh.dtype)
            scores = (
                jax.lax.dot_general(
                    qh, kh,
                    dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * scale
            )  # [rows, C]
            if quantized:
                scores = scores * page_scale_vec(ks_buf, slot, h)
            if logit_softcap is not None:
                cap = jnp.float32(logit_softcap)
                scores = jnp.tanh(scores / cap) * cap
            scores = jnp.where(valid, scores, -jnp.inf)
            m_prev = m_ref[h]  # [rows, 128] lane-replicated
            blk_max = jnp.max(scores, axis=-1, keepdims=True)  # [rows, 1]
            new_m = jnp.maximum(m_prev, blk_max)
            # A query row can be fully masked in an in-range chunk (the
            # chunk serves a LATER query of the same tile): keep the
            # recurrence NaN-free by rebasing on 0 until the row sees its
            # first live key — exp(-inf - 0) = 0, so l/acc stay 0.
            safe_m = jnp.where(new_m == -jnp.inf, 0.0, new_m)
            correction = jnp.exp(m_prev - safe_m)  # m_prev=-inf -> 0
            probs = jnp.exp(scores - safe_m[:, :1])  # masked lanes -> 0
            l_ref[h] = l_ref[h] * correction + jnp.sum(
                probs, axis=-1, keepdims=True
            )
            vh = v_buf[slot, :, h * head_dim:(h + 1) * head_dim]  # [C, Hd]
            if quantized:
                # probs · (v_int8 · s) == (probs · s_per_key) · v_int8:
                # fold V's per-page scale into the probabilities (one
                # [rows, C] VPU multiply) and promote the int8 band to
                # the query dtype for the MXU — same fusion as K.
                probs = probs * page_scale_vec(vs_buf, slot, h)
                vh = vh.astype(q_ref.dtype)
            pv = jax.lax.dot_general(
                probs.astype(vh.dtype), vh,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [rows, Hd]
            acc_ref[h] = acc_ref[h] * correction[:, :1] + pv
            m_ref[h] = new_m

    @pl.when(chunk_needed(c))
    def _():
        wait(c % 2)
        # Compute is branched on chunk parity so every KV-buffer access
        # uses a STATIC slot index (DMA descriptors take traced indices
        # fine — construct-probed). The duplicated trace is two copies
        # of the same straight-line block — free at runtime, one branch
        # executes.
        @pl.when(c % 2 == 0)
        def _():
            compute(0)

        @pl.when(c % 2 == 1)
        def _():
            compute(1)

    @pl.when(c == num_chunks - 1)
    def _():
        # Rows that never saw a live key (q_lens padding, padded tile
        # tail) have l = 0 and emit exact zeros — finite, so a pad row
        # can never poison downstream reductions.
        for h in range(num_kv_heads):
            out = acc_ref[h] / jnp.maximum(l_ref[h][:, :1], 1e-9)
            out_ref[h] = out.astype(out_ref.dtype)


def ragged_paged_attention_pallas(
    q: jnp.ndarray,  # [B, S, num_heads, head_dim] per-row query spans
    k_cache: jnp.ndarray,  # [num_blocks, block_size, num_kv_heads, head_dim]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks] int32
    context_lens: jnp.ndarray,  # [B] total valid tokens incl. the span
    q_positions: jnp.ndarray,  # [B, S] absolute position of each query
    q_lens: 'jnp.ndarray | None' = None,  # [B] valid queries per row
    sliding_window: 'int | jnp.ndarray | None' = None,
    scale: float | None = None,
    logit_softcap: float | None = None,
    *,
    pages_per_chunk: int | None = None,
    span_tile: int | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Fused Pallas TPU kernel twin of :func:`ragged_paged_attention_xla`.

    One kernel serves the whole serving surface: decode rows (span 1),
    prefill-chunk / cache-hit tail rows (causal over chunk + paged
    prefix), and speculative verify spans, with GQA grouping, ``q_lens``
    pad-query masking, static or TRACED ``sliding_window`` (gemma2
    alternating layers; ``<= 0`` disables), ``logit_softcap``, custom
    ``scale``, and fp32 online-softmax accumulation — never a dense
    ``[.., S, T]`` score tensor.

    CONTRACT beyond the XLA twin: each row's ``q_positions`` must be
    CONSECUTIVE (``q_positions[b, i] == q_positions[b, 0] + i``) — true
    for every serving span (decode rows, chunk tails, verify spans), and
    what lets the kernel scalar-prefetch one start position per row
    instead of streaming a position tensor. Pad-query rows (``>=
    q_lens``) emit exact zeros where the XLA twin emits key-0 garbage;
    both are finite and both are discarded by every caller, so valid
    rows are the parity surface (pinned by the interpret-mode matrix in
    ``tests/test_ragged_attention.py``).

    ``pages_per_chunk`` controls how many KV pages one grid step fetches
    and computes (default: enough for 128 tokens); ``span_tile`` caps the
    query-span positions per grid tile (default: up to 512 query rows
    after GQA flattening) — both bound VMEM. ``interpret=True`` runs the
    same kernel on the Pallas interpreter (CPU-runnable; the
    ``attn_backend='interpret'`` engine tier).
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    quantized = isinstance(k_cache, QuantizedKV)
    k_data, v_data = _kv_data(k_cache), _kv_data(v_cache)
    b, s, num_heads, head_dim = q.shape
    num_blocks, block_size, num_kv_heads, _ = k_data.shape
    max_blocks = block_tables.shape[1]
    group = num_heads // num_kv_heads
    if head_dim % 128 and not interpret:
        # Mosaic requires HBM DMA slices 128-aligned in the minor dim; the
        # engine's backend resolution (supports_model) routes such models
        # to XLA, so reaching here means an explicit 'pallas' pin.
        raise ValueError(
            f'pallas paged attention needs head_dim % 128 == 0, got {head_dim}'
        )
    # Each page DMAs into a [block_size]-row band of the folded KV buffer,
    # so the band offsets must land on sublane-tile boundaries (16 rows
    # for 2-byte dtypes, 8 for fp32, 32 for int8). EngineConfig's default
    # block_size of 16 satisfies every full-precision serving dtype but
    # NOT int8 KV, and 'auto' resolution (resolve_attn_backend with the
    # block geometry) routes misaligned configs to XLA before ever
    # tracing here — reaching this raise means an explicit 'pallas' pin.
    sublane = kv_sublane_tile(k_data.dtype)
    if block_size % sublane and not interpret:
        raise ValueError(
            f'pallas paged attention needs block_size % {sublane} == 0 '
            f'for {jnp.dtype(k_data.dtype).name} KV caches, '
            f'got {block_size}; use block_size={sublane} '
            "(EngineConfig.block_size) or attn_backend='xla'"
        )
    if pages_per_chunk is None:
        pages_per_chunk = max(1, 128 // block_size)
    pages_per_chunk = min(pages_per_chunk, max_blocks)
    num_chunks = -(-max_blocks // pages_per_chunk)
    if span_tile is None:
        # ~512 post-GQA query rows per tile keeps q/out/acc + the m/l
        # scratch + double-buffered KV pages comfortably inside VMEM at
        # 7B dims while still feeding the MXU full tiles.
        span_tile = max(1, 512 // group)
    span_tile = min(span_tile, s)
    num_q_tiles = -(-s // span_tile)

    if scale is None:
        scale = head_dim ** -0.5
    # One compiled signature for every window variant: the sliding window
    # rides a scalar-prefetch operand whether static, absent (0 = off),
    # or a traced per-layer value (gemma2 alternating layers).
    if sliding_window is None:
        window_arr = jnp.zeros((1,), jnp.int32)
    else:
        window_arr = jnp.asarray(sliding_window, jnp.int32).reshape((1,))
    if q_lens is None:
        # No pad masking requested: every span position is a live query
        # (the XLA twin's q_lens=None semantics for valid rows).
        q_lens = jnp.full((b,), s, jnp.int32)

    # Group-major query layout: [B, S, Nh, Hd] -> [B, Nkv, S*G, Hd] so the
    # kernel reads one contiguous [rows, Hd] plane per KV head with no
    # in-kernel reshapes across the head dim (row r = span r//G, group
    # member r%G). The transpose touches only the tiny activation tensor.
    qg = q.reshape(b, s, num_kv_heads, group, head_dim)
    qg = qg.transpose(0, 2, 1, 3, 4).reshape(
        b, num_kv_heads, s * group, head_dim
    )
    # Head-folded cache view: [nb, bs, Nkv, Hd] -> [nb, bs, Nkv*Hd] is a
    # row-major bitcast (no copy), and inside the kernel each head is a
    # 128-aligned lane band — the layout that keeps whole-page DMA
    # descriptors contiguous AND per-head slices tile-aligned (see the
    # kernel docstring for the two Mosaic rejections this designs out).
    k_folded = k_data.reshape(
        num_blocks, block_size, num_kv_heads * head_dim
    )
    v_folded = v_data.reshape(
        num_blocks, block_size, num_kv_heads * head_dim
    )
    extra_operands = []
    if quantized:
        if num_kv_heads > 128:
            raise ValueError(
                'pallas int8 paged attention supports at most 128 KV '
                f'heads (one scale lane row per page), got {num_kv_heads}'
            )
        # Scale rows pad to a full 128-lane minor dim so each page's
        # per-head scales DMA as one aligned [128] fp32 row (512 B)
        # beside the page's int8 payload. The pad is a tiny HLO pad of
        # the [nb, nkv] scale array per dispatch, not a cache copy.
        extra_operands = [
            jnp.pad(
                c.scale.astype(jnp.float32),
                ((0, 0), (0, 128 - num_kv_heads)),
            )
            for c in (k_cache, v_cache)
        ]

    rows = span_tile * group
    kernel = functools.partial(
        _ragged_paged_attn_kernel,
        block_size=block_size,
        pages_per_chunk=pages_per_chunk,
        num_kv_heads=num_kv_heads,
        group=group,
        span_tile=span_tile,
        scale=float(scale),
        logit_softcap=(
            None if logit_softcap is None else float(logit_softcap)
        ),
        quantized=quantized,
    )
    kv_scratch = [
        pltpu.VMEM(
            (2, pages_per_chunk * block_size,
             num_kv_heads * head_dim),
            k_data.dtype,
        ),
        pltpu.VMEM(
            (2, pages_per_chunk * block_size,
             num_kv_heads * head_dim),
            v_data.dtype,
        ),
    ]
    if quantized:
        kv_scratch += [
            pltpu.VMEM((2, pages_per_chunk, 128), jnp.float32),
            pltpu.VMEM((2, pages_per_chunk, 128), jnp.float32),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(b, num_q_tiles, num_chunks),
        in_specs=[
            pl.BlockSpec(
                (None, num_kv_heads, rows, head_dim),
                lambda i, qi, j, *_: (i, 0, qi, 0),
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ] + [pl.BlockSpec(memory_space=pl.ANY)] * len(extra_operands),
        out_specs=pl.BlockSpec(
            (None, num_kv_heads, rows, head_dim),
            lambda i, qi, j, *_: (i, 0, qi, 0),
        ),
        scratch_shapes=kv_scratch + [
            pltpu.SemaphoreType.DMA(
                (2, pages_per_chunk, 4 if quantized else 2)
            ),
            pltpu.VMEM((num_kv_heads, rows, head_dim), jnp.float32),
            pltpu.VMEM((num_kv_heads, rows, 128), jnp.float32),
            pltpu.VMEM((num_kv_heads, rows, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (b, num_kv_heads, s * group, head_dim), q.dtype
        ),
        interpret=interpret,
    )(
        block_tables.astype(jnp.int32),
        context_lens.astype(jnp.int32),
        q_positions[:, 0].astype(jnp.int32),
        q_lens.astype(jnp.int32),
        window_arr,
        qg,
        k_folded,
        v_folded,
        *extra_operands,
    )
    return (
        out.reshape(b, num_kv_heads, s, group, head_dim)
        .transpose(0, 2, 1, 3, 4)
        .reshape(b, s, num_heads, head_dim)
    )


def ragged_paged_attention(
    q: jnp.ndarray,  # [B, S, num_heads, head_dim] per-row query spans
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,
    context_lens: jnp.ndarray,
    q_positions: jnp.ndarray,
    q_lens: 'jnp.ndarray | None' = None,
    sliding_window: 'int | jnp.ndarray | None' = None,
    scale: float | None = None,
    logit_softcap: float | None = None,
    *,
    backend: str = 'xla',
) -> jnp.ndarray:
    """THE serving attention callsite: dispatch one ragged paged span
    batch through the selected backend.

    ``backend`` is a RESOLVED selector value ('xla' | 'pallas' |
    'interpret' — see :data:`ATTN_BACKENDS`; the engine resolves 'auto'
    once at construction via :func:`resolve_attn_backend` and closes its
    jitted serving functions over the result, mirroring ``qmm_backend``).
    'xla' is the always-available bit-exact baseline; 'pallas' is the
    fused TPU kernel; 'interpret' runs the same kernel on the Pallas
    interpreter (CPU parity/identity tests). Every serving dispatch —
    ``decode_loop``/``decode_step`` span-1 rows, ``prefill_paged`` tails,
    ``mixed_window`` chunk rows, ``spec_window`` verify spans — routes
    through here, so one kernel accelerates the whole serving surface.
    """
    if backend in ('pallas', 'interpret'):
        return ragged_paged_attention_pallas(
            q, k_cache, v_cache, block_tables, context_lens, q_positions,
            q_lens=q_lens, sliding_window=sliding_window, scale=scale,
            logit_softcap=logit_softcap, interpret=backend == 'interpret',
        )
    if backend != 'xla':
        raise ValueError(
            f'unresolved or unknown attn backend {backend!r}; expected '
            "'xla', 'pallas', or 'interpret' (resolve 'auto' via "
            'resolve_attn_backend before dispatch)'
        )
    return ragged_paged_attention_xla(
        q, k_cache, v_cache, block_tables, context_lens, q_positions,
        q_lens=q_lens, sliding_window=sliding_window, scale=scale,
        logit_softcap=logit_softcap,
    )


def paged_attention_pallas(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,
    context_lens: jnp.ndarray,
    *,
    sliding_window: 'int | jnp.ndarray | None' = None,
    scale: float | None = None,
    logit_softcap: float | None = None,
    pages_per_chunk: int | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Pallas kernel twin of :func:`paged_attention_xla` — now a thin
    span-1 wrapper over :func:`ragged_paged_attention_pallas` (a decode
    row is the ragged kernel's degenerate case: one query at position
    ``context_lens - 1`` over the whole context). The standalone
    decode-only kernel this used to be is retired; its block layout
    tripped Mosaic's "implicit dim change" lowering on some toolchains
    (xfail-gated since ISSUE 3), which the ragged kernel's lane-friendly
    layout avoids — ``tests/test_aot_tpu.py`` now compiles it gate-free.
    """
    return ragged_paged_attention_pallas(
        q[:, None],
        k_cache,
        v_cache,
        block_tables,
        context_lens,
        q_positions=(context_lens.astype(jnp.int32) - 1)[:, None],
        q_lens=None,
        sliding_window=sliding_window,
        scale=scale,
        logit_softcap=logit_softcap,
        pages_per_chunk=pages_per_chunk,
        interpret=interpret,
    )[:, 0]


def _write_token_kv_quantized(k_cache, v_cache, new_k, new_v, block_ids,
                              offsets):  # distlint: traced
    """Quantize-at-write for the decode path: rescale-on-append.

    Each touched block keeps a RUNNING absmax (its scale only grows):
    the appended row's per-head absmax joins the block's current scale,
    the block's existing int8 rows are ratio-multiplied into the new
    units (one gathered [B, bs, nkv, hd] rescale — never a re-walk of
    the original activations), and the fresh row is quantized once at
    the final scale. A row landing at block offset 0 starts a fresh
    block, so its inherited scale resets to 0. Frozen/dead rows arrive
    routed to the trash block 0 (duplicate scatter indices land there
    nondeterministically — garbage, but finite: scales are amax/127 and
    the guarded quant/rescale divisions can never mint a NaN for the
    masked softmax to multiply).
    """

    def write_one(cache, new):
        amax = jnp.max(
            jnp.abs(new.astype(jnp.float32)), axis=-1
        )  # [B, nkv]
        scale_before = jnp.where(
            (offsets == 0)[:, None], 0.0, cache.scale[block_ids]
        )
        new_scale = jnp.maximum(scale_before, amax / KV_QUANT_MAX)
        blocks = _rescale_int8_blocks(
            cache.data[block_ids], scale_before, new_scale
        )
        data = cache.data.at[block_ids].set(blocks)
        data = data.at[block_ids, offsets].set(
            quantize_kv_rows(new, new_scale)
        )
        return QuantizedKV(data, cache.scale.at[block_ids].set(new_scale))

    return write_one(k_cache, new_k), write_one(v_cache, new_v)


def write_token_kv(  # distlint: traced
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    new_k: jnp.ndarray,  # [B, num_kv_heads, head_dim]
    new_v: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks]
    positions: jnp.ndarray,  # [B] token index being written
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter one new token's K/V per sequence into its paged block
    (quantizing at write time for int8 :class:`QuantizedKV` pools)."""
    block_size = _kv_data(k_cache).shape[1]
    batch = positions.shape[0]
    block_ids = block_tables[jnp.arange(batch), positions // block_size]
    offsets = positions % block_size
    if isinstance(k_cache, QuantizedKV):
        return _write_token_kv_quantized(
            k_cache, v_cache, new_k, new_v, block_ids, offsets
        )
    k_cache = k_cache.at[block_ids, offsets].set(new_k.astype(k_cache.dtype))
    v_cache = v_cache.at[block_ids, offsets].set(new_v.astype(v_cache.dtype))
    return k_cache, v_cache


def write_chunk_kv(  # distlint: traced
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    new_k: jnp.ndarray,  # [B, S, num_kv_heads, head_dim] tail K
    new_v: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks]
    positions: jnp.ndarray,  # [B, S] absolute position per tail token
    valid: jnp.ndarray,  # [B, S] bool — padding rows/tokens route to trash
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter a batch of ragged spans' K/V into their paged blocks.

    The multi-token sibling of :func:`write_token_kv` and the write half
    of the ragged path (prefix-cache tail prefill, chunked prefill, and
    chunk rows riding mixed serving windows): ``valid`` carries the
    per-row raggedness — invalid positions write to the reserved trash
    block 0, the same pad-safety contract as :func:`write_prefill_kv`.
    """
    block_size = _kv_data(k_cache).shape[1]
    b, s = positions.shape
    block_ids = jnp.where(
        valid,
        jnp.take_along_axis(block_tables, positions // block_size, axis=1),
        0,
    )
    offsets = jnp.where(valid, positions % block_size, 0)
    if isinstance(k_cache, QuantizedKV):
        return _write_chunk_kv_quantized(
            k_cache, v_cache, new_k, new_v, block_tables, positions,
            valid, block_ids, offsets,
        )
    flat_blocks = block_ids.reshape(-1)
    flat_offsets = offsets.reshape(-1)
    k_flat = new_k.reshape(b * s, *new_k.shape[2:])
    v_flat = new_v.reshape(b * s, *new_v.shape[2:])
    k_cache = k_cache.at[flat_blocks, flat_offsets].set(
        k_flat.astype(k_cache.dtype)
    )
    v_cache = v_cache.at[flat_blocks, flat_offsets].set(
        v_flat.astype(v_cache.dtype)
    )
    return k_cache, v_cache


def _write_chunk_kv_quantized(k_cache, v_cache, new_k, new_v, block_tables,
                              positions, valid, block_ids,
                              offsets):  # distlint: traced
    """Ragged-span quantize-at-write (the :func:`write_chunk_kv` int8
    path). A row's span covers a CONTIGUOUS run of at most
    ``S // block_size + 1`` blocks (spans are position-consecutive with
    trailing-pad ``valid`` masks — the same contract the Pallas kernel
    scalar-prefetches one start position per row on), so the touched set
    is a static-width gather: per touched block take the running-absmax
    max of the block's prior scale (0 when the span covers the block's
    offset 0 — a fresh block) and the span tokens landing in it, rescale
    the gathered int8 rows once, scatter them back, then scatter the new
    tokens quantized at the final per-block scales. Dead rows / dead
    touched slots route to the trash block 0 exactly like the
    full-precision path (finite garbage, see
    :func:`_write_token_kv_quantized`)."""
    block_size = k_cache.data.shape[1]
    b, s = positions.shape
    max_blocks = block_tables.shape[1]
    nt = s // block_size + 1  # static max blocks a span can touch
    start_blk = positions[:, 0] // block_size  # [B] first logical block
    touched = start_blk[:, None] + jnp.arange(nt)[None, :]  # [B, nt]
    touched_cl = jnp.clip(touched, 0, max_blocks - 1)
    last_pos = jnp.max(jnp.where(valid, positions, -1), axis=1)  # [B]
    live = (touched <= last_pos[:, None] // block_size) & (
        last_pos[:, None] >= 0
    )
    phys = jnp.where(
        live, jnp.take_along_axis(block_tables, touched_cl, axis=1), 0
    )  # [B, nt] physical touched blocks (dead -> trash)
    fresh = touched * block_size >= positions[:, :1]  # span covers row 0
    tb = jnp.clip(
        positions // block_size - start_blk[:, None], 0, nt - 1
    )  # [B, S] touched-slot index per token
    onehot = (
        tb[:, :, None] == jnp.arange(nt)[None, None, :]
    ) & valid[:, :, None]  # [B, S, nt]

    def write_one(cache, new):
        amax_tok = jnp.max(
            jnp.abs(new.astype(jnp.float32)), axis=-1
        )  # [B, S, nkv]
        contrib = jnp.max(
            jnp.where(onehot[..., None], amax_tok[:, :, None, :], 0.0),
            axis=1,
        )  # [B, nt, nkv] span absmax per touched block
        scale_before = jnp.where(fresh[..., None], 0.0, cache.scale[phys])
        new_scale = jnp.maximum(scale_before, contrib / KV_QUANT_MAX)
        blocks = _rescale_int8_blocks(
            cache.data[phys], scale_before, new_scale
        )
        flat_phys = phys.reshape(-1)
        data = cache.data.at[flat_phys].set(
            blocks.reshape(-1, *blocks.shape[2:])
        )
        scale = cache.scale.at[flat_phys].set(
            new_scale.reshape(-1, new_scale.shape[-1])
        )
        scale_tok = jnp.take_along_axis(
            new_scale, tb[:, :, None], axis=1
        )  # [B, S, nkv]
        q = quantize_kv_rows(new, scale_tok)
        data = data.at[block_ids.reshape(-1), offsets.reshape(-1)].set(
            q.reshape(b * s, *q.shape[2:])
        )
        return QuantizedKV(data, scale)

    return write_one(k_cache, new_k), write_one(v_cache, new_v)


def write_prefill_kv(  # distlint: traced
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    k_seq: jnp.ndarray,  # [S, num_kv_heads, head_dim] one sequence's K
    v_seq: jnp.ndarray,
    block_table_row: jnp.ndarray,  # [max_blocks]
    length: jnp.ndarray,  # scalar — valid tokens in k_seq
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter a prefilled sequence's K/V into its blocks (pad-safe).

    Padded positions (``>= length``) are routed to the TRASH BLOCK: block 0
    is reserved by the allocator (never handed to a sequence), so garbage
    writes land there harmlessly. Clamping to a valid slot instead would race
    real data through XLA's nondeterministic duplicate-index scatter.
    """
    seq_len = k_seq.shape[0]
    block_size = _kv_data(k_cache).shape[1]
    positions = jnp.arange(seq_len)
    valid = positions < length
    block_ids = jnp.where(valid, block_table_row[positions // block_size], 0)
    offsets = jnp.where(valid, positions % block_size, 0)
    if isinstance(k_cache, QuantizedKV):
        return _write_prefill_kv_quantized(
            k_cache, v_cache, k_seq, v_seq, block_table_row, length,
            block_ids, offsets, valid,
        )
    k_cache = k_cache.at[block_ids, offsets].set(k_seq.astype(k_cache.dtype))
    v_cache = v_cache.at[block_ids, offsets].set(v_seq.astype(v_cache.dtype))
    return k_cache, v_cache


def _write_prefill_kv_quantized(k_cache, v_cache, k_seq, v_seq,
                                block_table_row, length, block_ids,
                                offsets, valid):  # distlint: traced
    """Whole-sequence quantize-at-write (the :func:`write_prefill_kv`
    int8 path). A full prefill writes every block from its offset 0, so
    every touched block is FRESH: each block's scale is simply the
    absmax of its live rows (token → block is the static ``s //
    block_size`` map — no running-absmax bookkeeping needed), and each
    row quantizes once at its block's final scale. Pad rows and dead
    blocks route to the trash block 0 (finite garbage, same contract as
    the full-precision path)."""
    seq_len = k_seq.shape[0]
    block_size = k_cache.data.shape[1]
    nt = -(-seq_len // block_size)
    pad = nt * block_size - seq_len
    live_blk = jnp.arange(nt) * block_size < length
    phys = jnp.where(live_blk, block_table_row[jnp.arange(nt)], 0)

    def write_one(cache, seq):
        amax = jnp.max(jnp.abs(seq.astype(jnp.float32)), axis=-1)
        amax = jnp.where(valid[:, None], amax, 0.0)  # [S, nkv]
        contrib = jnp.pad(amax, ((0, pad), (0, 0))).reshape(
            nt, block_size, -1
        ).max(axis=1)  # [nt, nkv]
        new_scale = contrib / KV_QUANT_MAX
        scale = cache.scale.at[phys].set(new_scale)
        scale_tok = jnp.repeat(new_scale, block_size, axis=0)[:seq_len]
        data = cache.data.at[block_ids, offsets].set(
            quantize_kv_rows(seq, scale_tok)
        )
        return QuantizedKV(data, scale)

    return write_one(k_cache, k_seq), write_one(v_cache, v_seq)
