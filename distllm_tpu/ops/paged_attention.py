"""Paged-KV decode attention — the core kernel of the generation engine.

The reference delegates this to vLLM's CUDA paged-attention
(``generate/generators/vllm_backend.py``; SURVEY.md section 2.4 N1). Here the
KV cache lives in HBM as fixed-size blocks::

    k_cache, v_cache : [num_blocks, block_size, num_kv_heads, head_dim]

and each decoding sequence owns a row of ``block_tables`` (block ids, padded)
plus a ``context_lens`` entry (valid tokens). Two implementations share a
signature:

- :func:`paged_attention_xla` — gather + masked softmax; XLA fuses this well
  and it is the portable baseline (also runs on CPU for tests).
- :func:`paged_attention_pallas` — Pallas TPU kernel: grid over
  (sequence, KV chunk); block tables are scalar-prefetched and each grid
  step explicitly DMAs its chunk's pages HBM→VMEM with double buffering
  (issue chunk c+1 while computing chunk c), online-softmax accumulation
  in fp32 scratch. Chunks that lie entirely outside a sequence's valid
  window (beyond ``context_lens`` or before the sliding-window start) are
  skipped: no DMA, no compute.

Both handle GQA (query heads grouped over KV heads), sliding windows, and
fp32 softmax.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# Head dims the Pallas kernel is exercised at in CI (tests/test_aot_tpu.py
# compiles these against a real v5e topology). The kernel's structural
# requirement is only head_dim % 128 == 0 (Mosaic DMA alignment, checked in
# paged_attention_pallas), but 'auto' backend selection routes through
# supported_head_dim so untested shapes never auto-enable the kernel —
# widen this tuple when a new shape gains AOT coverage.
TESTED_HEAD_DIMS = (128,)


def supported_head_dim(head_dim: int) -> bool:
    """True when `attn_backend='auto'` may select the Pallas kernel."""
    return head_dim in TESTED_HEAD_DIMS


def supports_model(model_cfg) -> bool:
    """May `attn_backend='auto'` select the Pallas kernel for this model?

    Beyond the head-dim contract, the kernel implements neither attention
    logit softcapping, nor per-layer (alternating) sliding windows, nor a
    non-default score scale — gemma2 checkpoints route to XLA regardless
    of head_dim.
    """
    return (
        supported_head_dim(model_cfg.head_size)
        and getattr(model_cfg, 'attn_logit_softcap', None) is None
        and getattr(model_cfg, 'query_scale', None) is None
        and getattr(model_cfg, 'sliding_window_pattern', 'all') == 'all'
    )


def paged_attention_xla(
    q: jnp.ndarray,  # [B, num_heads, head_dim]
    k_cache: jnp.ndarray,  # [num_blocks, block_size, num_kv_heads, head_dim]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks] int32
    context_lens: jnp.ndarray,  # [B] int32 (valid tokens incl. current)
    sliding_window: 'int | jnp.ndarray | None' = None,
    scale: float | None = None,
    logit_softcap: float | None = None,
) -> jnp.ndarray:
    """Reference implementation: gather blocks then masked attention.

    ``sliding_window`` may be a static int, None, or a TRACED int32 scalar
    (per-layer windows riding a layer scan — gemma2's alternating
    local/global pattern; 0/negative means no window on that layer).
    ``scale`` overrides the 1/sqrt(head_dim) score scale
    (query_pre_attn_scalar); ``logit_softcap`` applies tanh(s/cap)*cap to
    the scaled scores before masking (both gemma2).
    """
    b, num_heads, head_dim = q.shape
    _, block_size, num_kv_heads, _ = k_cache.shape
    max_blocks = block_tables.shape[1]
    group = num_heads // num_kv_heads

    # [B, max_blocks, block_size, Nkv, Hd] -> [B, T, Nkv, Hd]
    k = k_cache[block_tables].reshape(b, max_blocks * block_size, num_kv_heads, head_dim)
    v = v_cache[block_tables].reshape(b, max_blocks * block_size, num_kv_heads, head_dim)

    qg = q.reshape(b, num_kv_heads, group, head_dim).astype(jnp.float32)
    scores = jnp.einsum('bkgd,btkd->bkgt', qg, k.astype(jnp.float32))
    scores = scores * jnp.float32(
        scale if scale is not None else head_dim ** -0.5
    )
    if logit_softcap is not None:
        from distllm_tpu.models.common import softcap

        scores = softcap(scores, logit_softcap)
    positions = jnp.arange(max_blocks * block_size)[None, :]
    valid = positions < context_lens[:, None]
    if sliding_window is not None:
        # Match prefill's window mask: only the last `sliding_window` keys.
        # For a traced window, <= 0 disables the clamp on that layer.
        windowed = positions > context_lens[:, None] - 1 - sliding_window
        if isinstance(sliding_window, int):
            valid = valid & windowed
        else:
            valid = valid & (windowed | (sliding_window <= 0))
    scores = jnp.where(valid[:, None, None, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum('bkgt,btkd->bkgd', probs, v.astype(jnp.float32))
    return out.reshape(b, num_heads, head_dim).astype(q.dtype)


def ragged_paged_attention_xla(
    q: jnp.ndarray,  # [B, S, num_heads, head_dim] per-row query spans
    k_cache: jnp.ndarray,  # [num_blocks, block_size, num_kv_heads, head_dim]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks] int32
    context_lens: jnp.ndarray,  # [B] total valid tokens incl. the span
    q_positions: jnp.ndarray,  # [B, S] absolute position of each query
    q_lens: 'jnp.ndarray | None' = None,  # [B] valid queries per row
    sliding_window: 'int | jnp.ndarray | None' = None,
    scale: float | None = None,
    logit_softcap: float | None = None,
) -> jnp.ndarray:
    """Ragged per-row-query-length attention over paged KV — the shared
    kernel of prefix-cache tail prefill, chunked prefill, and mixed
    prefill+decode serving windows (docs/serving.md).

    Each row carries a SPAN of queries at absolute ``q_positions``; every
    query attends to all cached positions ``<=`` its own (the span's K/V
    must already be written into the paged blocks — write-then-attend,
    exactly like the decode path). Rows are ragged: a decode row is a
    span of length 1 (its single query sees the whole context, 1-vs-
    context — numerically the :func:`paged_attention_xla` result), while
    a prefill-chunk row's queries attend causally over chunk + paged
    prefix. ``q_lens`` (optional) masks each row's padding queries so
    their softmax rows stay finite; with ``q_lens=None`` padding queries
    compute garbage the caller discards (masking only touches pad rows —
    valid rows are bit-identical either way). Gather + masked fp32
    softmax; XLA fuses this well and it runs on CPU for tests. Prefill
    spans are compute-bound, so unlike decode there is no Pallas variant.
    """
    b, s, num_heads, head_dim = q.shape
    _, block_size, num_kv_heads, _ = k_cache.shape
    max_blocks = block_tables.shape[1]
    group = num_heads // num_kv_heads

    k = k_cache[block_tables].reshape(
        b, max_blocks * block_size, num_kv_heads, head_dim
    )
    v = v_cache[block_tables].reshape(
        b, max_blocks * block_size, num_kv_heads, head_dim
    )
    qg = q.reshape(b, s, num_kv_heads, group, head_dim).astype(jnp.float32)
    scores = jnp.einsum('bskgd,btkd->bkgst', qg, k.astype(jnp.float32))
    scores = scores * jnp.float32(
        scale if scale is not None else head_dim ** -0.5
    )
    if logit_softcap is not None:
        from distllm_tpu.models.common import softcap

        scores = softcap(scores, logit_softcap)
    kv_pos = jnp.arange(max_blocks * block_size)[None, None, :]  # [1, 1, T]
    qp = q_positions[:, :, None]  # [B, S, 1]
    valid = (kv_pos < context_lens[:, None, None]) & (kv_pos <= qp)
    if sliding_window is not None:
        # Same window semantics as the dense prefill mask: query at
        # position p sees keys in (p - window, p]. Traced windows <= 0
        # disable the clamp (gemma2 alternating layers).
        windowed = kv_pos > qp - sliding_window
        if isinstance(sliding_window, int):
            valid = valid & windowed
        else:
            valid = valid & (windowed | (sliding_window <= 0))
    if q_lens is not None:
        # Padding queries keep key 0 visible: an all-masked softmax row is
        # NaN, and a NaN in a pad row can poison reductions downstream.
        q_valid = jnp.arange(s)[None, :, None] < q_lens[:, None, None]
        valid = valid | (~q_valid & (kv_pos == 0))
    scores = jnp.where(valid[:, None, None, :, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum('bkgst,btkd->bskgd', probs, v.astype(jnp.float32))
    return out.reshape(b, s, num_heads, head_dim).astype(q.dtype)


def paged_prefill_attention_xla(
    q: jnp.ndarray,  # [B, S, num_heads, head_dim] tail queries
    k_cache: jnp.ndarray,  # [num_blocks, block_size, num_kv_heads, head_dim]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks] int32
    context_lens: jnp.ndarray,  # [B] total valid tokens incl. the tail
    q_positions: jnp.ndarray,  # [B, S] absolute position of each query
    sliding_window: 'int | jnp.ndarray | None' = None,
    scale: float | None = None,
    logit_softcap: float | None = None,
) -> jnp.ndarray:
    """Multi-query attention over paged KV: prefix-cache / chunked prefill
    tail queries attending to cached history + themselves.

    Now a thin alias of :func:`ragged_paged_attention_xla` (every tail row
    is a ragged span; ``q_lens`` stays ``None`` so the emitted HLO — and
    bit pattern — is unchanged from the pre-ragged op; padding-row logits
    are garbage the caller discards).
    """
    return ragged_paged_attention_xla(
        q, k_cache, v_cache, block_tables, context_lens, q_positions,
        q_lens=None, sliding_window=sliding_window, scale=scale,
        logit_softcap=logit_softcap,
    )


def _paged_attn_kernel(
    # scalar-prefetch operands (SMEM)
    block_tables_ref,  # [B, max_blocks] int32
    context_lens_ref,  # [B] int32
    # array operands
    q_ref,  # [num_heads, head_dim] (VMEM) — one sequence
    k_cache_ref,  # [num_blocks, block_size, num_kv_heads, head_dim] (HBM)
    v_cache_ref,
    out_ref,  # [num_heads, head_dim] (VMEM)
    # scratch
    k_buf,  # [2, pages_per_chunk, block_size, num_kv_heads, head_dim] VMEM
    v_buf,
    sems,  # DMA semaphores [2, pages_per_chunk, 2]
    acc_ref,  # [num_heads, head_dim] fp32
    m_ref,  # [num_heads, 1] fp32
    l_ref,  # [num_heads, 1] fp32
    *,
    block_size: int,
    pages_per_chunk: int,
    num_kv_heads: int,
    group: int,
    sliding_window: int | None,
):
    """Grid (B, num_chunks): one sequence × one chunk of KV pages per step.

    Pages of a chunk are DMA'd HBM→VMEM individually (they are scattered by
    the paged allocator), double-buffered across grid steps: while chunk c
    computes, chunk c+1's copies are in flight. Out-of-range chunks (beyond
    ``context_lens`` or entirely before the sliding-window start) issue no
    DMAs and no compute.
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    seq = pl.program_id(0)
    c = pl.program_id(1)
    num_chunks = pl.num_programs(1)
    ctx = context_lens_ref[seq]
    chunk_tokens = pages_per_chunk * block_size
    num_heads = q_ref.shape[0]
    head_dim = q_ref.shape[1]

    # Number of pages this sequence actually uses, and the window floor.
    n_pages = (ctx + block_size - 1) // block_size
    if sliding_window is not None:
        lo = jnp.maximum(ctx - sliding_window, 0)
    else:
        lo = jnp.int32(0)

    def chunk_needed(ci):
        start = ci * chunk_tokens
        return (start < ctx) & ((ci + 1) * chunk_tokens > lo)

    def issue(ci, slot):
        # Clamp logical page ids into the sequence's valid range: the DMA
        # engine must copy *something* per issued descriptor, and the
        # compute mask discards anything outside [lo, ctx).
        for p in range(pages_per_chunk):
            logical = ci * pages_per_chunk + p
            page = jnp.clip(logical, 0, jnp.maximum(n_pages - 1, 0))
            page_id = block_tables_ref[seq, page]
            pltpu.make_async_copy(
                k_cache_ref.at[page_id], k_buf.at[slot, p], sems.at[slot, p, 0]
            ).start()
            pltpu.make_async_copy(
                v_cache_ref.at[page_id], v_buf.at[slot, p], sems.at[slot, p, 1]
            ).start()

    def wait(slot):
        for p in range(pages_per_chunk):
            pltpu.make_async_copy(
                k_cache_ref.at[0], k_buf.at[slot, p], sems.at[slot, p, 0]
            ).wait()
            pltpu.make_async_copy(
                v_cache_ref.at[0], v_buf.at[slot, p], sems.at[slot, p, 1]
            ).wait()

    @pl.when(c == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)

        @pl.when(chunk_needed(0))
        def _():
            issue(0, 0)

    # Double buffering: start chunk c+1's copies before computing chunk c.
    @pl.when((c + 1 < num_chunks) & chunk_needed(c + 1))
    def _():
        issue(c + 1, (c + 1) % 2)

    @pl.when(chunk_needed(c))
    def _():
        slot = c % 2
        wait(slot)
        scale = jax.lax.rsqrt(jnp.float32(head_dim))
        kb = k_buf[slot].reshape(chunk_tokens, num_kv_heads, head_dim)
        vb = v_buf[slot].reshape(chunk_tokens, num_kv_heads, head_dim)
        positions = c * chunk_tokens + jax.lax.broadcasted_iota(
            jnp.int32, (1, chunk_tokens), 1
        )
        valid = positions < ctx
        if sliding_window is not None:
            valid = valid & (positions >= lo)

        q = q_ref[...]
        for h in range(num_kv_heads):  # static unroll over KV heads
            qh = q[h * group : (h + 1) * group, :]  # [g, Hd]
            kh = kb[:, h, :]  # [C, Hd]
            scores = (
                jax.lax.dot_general(
                    qh, kh,
                    dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                * scale
            )  # [g, C]
            scores = jnp.where(valid, scores, -jnp.inf)
            m_h = m_ref[h * group : (h + 1) * group, :]  # [g, 1]
            blk_max = jnp.max(scores, axis=-1, keepdims=True)
            new_m = jnp.maximum(m_h, blk_max)
            correction = jnp.exp(
                jnp.where(m_h == -jnp.inf, -jnp.inf, m_h - new_m)
            )
            probs = jnp.exp(scores - new_m)  # masked lanes: exp(-inf) = 0
            l_h = l_ref[h * group : (h + 1) * group, :]
            l_ref[h * group : (h + 1) * group, :] = (
                l_h * correction + jnp.sum(probs, axis=-1, keepdims=True)
            )
            vh = vb[:, h, :]  # [C, Hd]
            pv = jax.lax.dot_general(
                probs.astype(vh.dtype), vh,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [g, Hd]
            acc_h = acc_ref[h * group : (h + 1) * group, :]
            acc_ref[h * group : (h + 1) * group, :] = (
                acc_h * correction + pv
            )
            m_ref[h * group : (h + 1) * group, :] = new_m

    @pl.when(c == num_chunks - 1)
    def _():
        out = acc_ref[...] / jnp.maximum(l_ref[...], 1e-9)
        out_ref[...] = out.astype(out_ref.dtype)


def paged_attention_pallas(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,
    context_lens: jnp.ndarray,
    *,
    sliding_window: int | None = None,
    pages_per_chunk: int | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Pallas TPU kernel version of :func:`paged_attention_xla`.

    ``pages_per_chunk`` controls how many KV pages one grid step fetches
    and computes (default: enough for 128 tokens) — larger chunks amortize
    DMA-issue overhead and feed the MXU bigger tiles, at the cost of VMEM.
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, num_heads, head_dim = q.shape
    num_blocks, block_size, num_kv_heads, _ = k_cache.shape
    max_blocks = block_tables.shape[1]
    group = num_heads // num_kv_heads
    if head_dim % 128 and not interpret:
        # Mosaic requires HBM DMA slices 128-aligned in the minor dim; the
        # engine probes this at warmup and falls back to the XLA path.
        raise ValueError(
            f'pallas paged attention needs head_dim % 128 == 0, got {head_dim}'
        )
    if pages_per_chunk is None:
        pages_per_chunk = max(1, 128 // block_size)
    pages_per_chunk = min(pages_per_chunk, max_blocks)
    num_chunks = -(-max_blocks // pages_per_chunk)

    kernel = functools.partial(
        _paged_attn_kernel,
        block_size=block_size,
        pages_per_chunk=pages_per_chunk,
        num_kv_heads=num_kv_heads,
        group=group,
        sliding_window=sliding_window,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, num_chunks),
        in_specs=[
            pl.BlockSpec(
                (None, num_heads, head_dim), lambda i, j, *_: (i, 0, 0)
            ),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (None, num_heads, head_dim), lambda i, j, *_: (i, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM(
                (2, pages_per_chunk, block_size, num_kv_heads, head_dim),
                k_cache.dtype,
            ),
            pltpu.VMEM(
                (2, pages_per_chunk, block_size, num_kv_heads, head_dim),
                v_cache.dtype,
            ),
            pltpu.SemaphoreType.DMA((2, pages_per_chunk, 2)),
            pltpu.VMEM((num_heads, head_dim), jnp.float32),
            pltpu.VMEM((num_heads, 1), jnp.float32),
            pltpu.VMEM((num_heads, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, num_heads, head_dim), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), context_lens.astype(jnp.int32), q, k_cache, v_cache)


def write_token_kv(
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    new_k: jnp.ndarray,  # [B, num_kv_heads, head_dim]
    new_v: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks]
    positions: jnp.ndarray,  # [B] token index being written
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter one new token's K/V per sequence into its paged block."""
    block_size = k_cache.shape[1]
    batch = positions.shape[0]
    block_ids = block_tables[jnp.arange(batch), positions // block_size]
    offsets = positions % block_size
    k_cache = k_cache.at[block_ids, offsets].set(new_k.astype(k_cache.dtype))
    v_cache = v_cache.at[block_ids, offsets].set(new_v.astype(v_cache.dtype))
    return k_cache, v_cache


def write_chunk_kv(
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    new_k: jnp.ndarray,  # [B, S, num_kv_heads, head_dim] tail K
    new_v: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks]
    positions: jnp.ndarray,  # [B, S] absolute position per tail token
    valid: jnp.ndarray,  # [B, S] bool — padding rows/tokens route to trash
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter a batch of ragged spans' K/V into their paged blocks.

    The multi-token sibling of :func:`write_token_kv` and the write half
    of the ragged path (prefix-cache tail prefill, chunked prefill, and
    chunk rows riding mixed serving windows): ``valid`` carries the
    per-row raggedness — invalid positions write to the reserved trash
    block 0, the same pad-safety contract as :func:`write_prefill_kv`.
    """
    block_size = k_cache.shape[1]
    b, s = positions.shape
    block_ids = jnp.where(
        valid,
        jnp.take_along_axis(block_tables, positions // block_size, axis=1),
        0,
    )
    offsets = jnp.where(valid, positions % block_size, 0)
    flat_blocks = block_ids.reshape(-1)
    flat_offsets = offsets.reshape(-1)
    k_flat = new_k.reshape(b * s, *new_k.shape[2:])
    v_flat = new_v.reshape(b * s, *new_v.shape[2:])
    k_cache = k_cache.at[flat_blocks, flat_offsets].set(
        k_flat.astype(k_cache.dtype)
    )
    v_cache = v_cache.at[flat_blocks, flat_offsets].set(
        v_flat.astype(v_cache.dtype)
    )
    return k_cache, v_cache


def write_prefill_kv(
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    k_seq: jnp.ndarray,  # [S, num_kv_heads, head_dim] one sequence's K
    v_seq: jnp.ndarray,
    block_table_row: jnp.ndarray,  # [max_blocks]
    length: jnp.ndarray,  # scalar — valid tokens in k_seq
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter a prefilled sequence's K/V into its blocks (pad-safe).

    Padded positions (``>= length``) are routed to the TRASH BLOCK: block 0
    is reserved by the allocator (never handed to a sequence), so garbage
    writes land there harmlessly. Clamping to a valid slot instead would race
    real data through XLA's nondeterministic duplicate-index scatter.
    """
    seq_len = k_seq.shape[0]
    block_size = k_cache.shape[1]
    positions = jnp.arange(seq_len)
    valid = positions < length
    block_ids = jnp.where(valid, block_table_row[positions // block_size], 0)
    offsets = jnp.where(valid, positions % block_size, 0)
    k_cache = k_cache.at[block_ids, offsets].set(k_seq.astype(k_cache.dtype))
    v_cache = v_cache.at[block_ids, offsets].set(v_seq.astype(v_cache.dtype))
    return k_cache, v_cache
