"""Paged-KV decode attention — the core kernel of the generation engine.

The reference delegates this to vLLM's CUDA paged-attention
(``generate/generators/vllm_backend.py``; SURVEY.md section 2.4 N1). Here the
KV cache lives in HBM as fixed-size blocks::

    k_cache, v_cache : [num_blocks, block_size, num_kv_heads, head_dim]

and each decoding sequence owns a row of ``block_tables`` (block ids, padded)
plus a ``context_lens`` entry (valid tokens). Two implementations share a
signature:

- :func:`paged_attention_xla` — gather + masked softmax; XLA fuses this well
  and it is the portable baseline (also runs on CPU for tests).
- :func:`paged_attention_pallas` — Pallas TPU kernel: grid over sequences,
  block tables scalar-prefetched so each program DMAs exactly its own KV
  blocks VMEM-side, online-softmax accumulation in fp32.

Both handle GQA (query heads grouped over KV heads) and fp32 softmax.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def paged_attention_xla(
    q: jnp.ndarray,  # [B, num_heads, head_dim]
    k_cache: jnp.ndarray,  # [num_blocks, block_size, num_kv_heads, head_dim]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks] int32
    context_lens: jnp.ndarray,  # [B] int32 (valid tokens incl. current)
    sliding_window: int | None = None,
) -> jnp.ndarray:
    """Reference implementation: gather blocks then masked attention."""
    b, num_heads, head_dim = q.shape
    _, block_size, num_kv_heads, _ = k_cache.shape
    max_blocks = block_tables.shape[1]
    group = num_heads // num_kv_heads

    # [B, max_blocks, block_size, Nkv, Hd] -> [B, T, Nkv, Hd]
    k = k_cache[block_tables].reshape(b, max_blocks * block_size, num_kv_heads, head_dim)
    v = v_cache[block_tables].reshape(b, max_blocks * block_size, num_kv_heads, head_dim)

    qg = q.reshape(b, num_kv_heads, group, head_dim).astype(jnp.float32)
    scores = jnp.einsum('bkgd,btkd->bkgt', qg, k.astype(jnp.float32))
    scores = scores / jnp.sqrt(jnp.float32(head_dim))
    positions = jnp.arange(max_blocks * block_size)[None, :]
    valid = positions < context_lens[:, None]
    if sliding_window is not None:
        # Match prefill's window mask: only the last `sliding_window` keys.
        valid = valid & (positions > context_lens[:, None] - 1 - sliding_window)
    scores = jnp.where(valid[:, None, None, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum('bkgt,btkd->bkgd', probs, v.astype(jnp.float32))
    return out.reshape(b, num_heads, head_dim).astype(q.dtype)


def _paged_attn_kernel(
    # scalar-prefetch operands
    block_tables_ref,  # [B, max_blocks] int32 (SMEM)
    context_lens_ref,  # [B] int32 (SMEM)
    # array operands
    q_ref,  # [num_heads, head_dim] (VMEM) — one sequence
    k_cache_ref,  # [num_blocks, block_size, num_kv_heads, head_dim] (ANY/HBM)
    v_cache_ref,
    out_ref,  # [num_heads, head_dim]
    *,
    block_size: int,
    max_blocks: int,
    num_kv_heads: int,
    group: int,
):
    """One grid program = one sequence: online softmax over its KV blocks."""
    import jax.experimental.pallas as pl

    seq = pl.program_id(0)
    ctx = context_lens_ref[seq]
    num_heads = q_ref.shape[0]
    head_dim = q_ref.shape[1]
    q = q_ref[...].astype(jnp.float32).reshape(num_kv_heads, group, head_dim)
    scale = jax.lax.rsqrt(jnp.float32(head_dim))

    def body(i, carry):
        m, l, acc = carry  # running max, normalizer, weighted values
        block_id = block_tables_ref[seq, i]
        k_blk = k_cache_ref[block_id].astype(jnp.float32)  # [bs, Nkv, Hd]
        v_blk = v_cache_ref[block_id].astype(jnp.float32)
        scores = (
            jnp.einsum('kgd,skd->kgs', q, k_blk, preferred_element_type=jnp.float32)
            * scale
        )
        positions = i * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, 1, block_size), 2
        )
        scores = jnp.where(positions < ctx, scores, -jnp.inf)
        blk_max = jnp.max(scores, axis=-1)
        new_m = jnp.maximum(m, blk_max)
        # Guard fully-masked blocks: exp(-inf - -inf) -> use finite correction.
        correction = jnp.exp(jnp.where(m == -jnp.inf, 0.0, m - new_m))
        probs = jnp.exp(scores - new_m[..., None])
        probs = jnp.where(jnp.isfinite(scores), probs, 0.0)
        new_l = l * correction + jnp.sum(probs, axis=-1)
        new_acc = acc * correction[..., None] + jnp.einsum(
            'kgs,skd->kgd', probs, v_blk, preferred_element_type=jnp.float32
        )
        return new_m, new_l, new_acc

    n_blocks = (ctx + block_size - 1) // block_size
    m0 = jnp.full((num_kv_heads, group), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((num_kv_heads, group), jnp.float32)
    acc0 = jnp.zeros((num_kv_heads, group, head_dim), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, acc0))
    out = acc / jnp.maximum(l, 1e-9)[..., None]
    out_ref[...] = out.reshape(num_heads, head_dim).astype(out_ref.dtype)


def paged_attention_pallas(
    q: jnp.ndarray,
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,
    context_lens: jnp.ndarray,
    *,
    interpret: bool = False,
) -> jnp.ndarray:
    """Pallas TPU kernel version of :func:`paged_attention_xla`."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, num_heads, head_dim = q.shape
    num_blocks, block_size, num_kv_heads, _ = k_cache.shape
    max_blocks = block_tables.shape[1]
    group = num_heads // num_kv_heads

    kernel = functools.partial(
        _paged_attn_kernel,
        block_size=block_size,
        max_blocks=max_blocks,
        num_kv_heads=num_kv_heads,
        group=group,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((None, num_heads, head_dim), lambda i, *_: (i, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (None, num_heads, head_dim), lambda i, *_: (i, 0, 0)
        ),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, num_heads, head_dim), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), context_lens.astype(jnp.int32), q, k_cache, v_cache)


def write_token_kv(
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    new_k: jnp.ndarray,  # [B, num_kv_heads, head_dim]
    new_v: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks]
    positions: jnp.ndarray,  # [B] token index being written
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter one new token's K/V per sequence into its paged block."""
    block_size = k_cache.shape[1]
    batch = positions.shape[0]
    block_ids = block_tables[jnp.arange(batch), positions // block_size]
    offsets = positions % block_size
    k_cache = k_cache.at[block_ids, offsets].set(new_k.astype(k_cache.dtype))
    v_cache = v_cache.at[block_ids, offsets].set(new_v.astype(v_cache.dtype))
    return k_cache, v_cache


def write_prefill_kv(
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    k_seq: jnp.ndarray,  # [S, num_kv_heads, head_dim] one sequence's K
    v_seq: jnp.ndarray,
    block_table_row: jnp.ndarray,  # [max_blocks]
    length: jnp.ndarray,  # scalar — valid tokens in k_seq
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter a prefilled sequence's K/V into its blocks (pad-safe).

    Padded positions (``>= length``) are routed to the TRASH BLOCK: block 0
    is reserved by the allocator (never handed to a sequence), so garbage
    writes land there harmlessly. Clamping to a valid slot instead would race
    real data through XLA's nondeterministic duplicate-index scatter.
    """
    seq_len = k_seq.shape[0]
    block_size = k_cache.shape[1]
    positions = jnp.arange(seq_len)
    valid = positions < length
    block_ids = jnp.where(valid, block_table_row[positions // block_size], 0)
    offsets = jnp.where(valid, positions % block_size, 0)
    k_cache = k_cache.at[block_ids, offsets].set(k_seq.astype(k_cache.dtype))
    v_cache = v_cache.at[block_ids, offsets].set(v_seq.astype(v_cache.dtype))
    return k_cache, v_cache
