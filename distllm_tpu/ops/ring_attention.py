"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has **no** long-context strategy — it truncates to
``model_max_length`` (``distllm/embed/encoders/auto.py:74``) or chunks text
(``embed/datasets/jsonl_chunk.py``; SURVEY.md §5 "Long-context"). Here
sequence parallelism is first-class: inputs longer than one chip's HBM are
sharded over the ``seq`` mesh axis and attention runs distributed:

- :func:`ring_attention` — blockwise attention with online-softmax
  accumulation; K/V blocks rotate around the ring via ``lax.ppermute`` so
  each chip only ever holds ``S/P`` keys (memory O(S/P), comm rides ICI
  neighbor links). This is the Ring Attention construction (Liu et al.) in
  its jax/shard_map form.
- :func:`ulysses_attention` — all-to-all alternative: scatter heads /
  gather sequence, run full local attention per head group, reverse. One
  collective pair instead of P-1 permutes; better when heads >= ring size
  and ICI all-to-all bandwidth is plentiful.

Both are exact (not approximations): tests pin them against single-device
full attention in fp32.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _block_attn_update(q, k_blk, v_blk, mask_blk, m, l, o, scale):
    """One online-softmax accumulation step against a K/V block.

    q ``[B, Sq, N, H]``; k_blk/v_blk ``[B, Sb, N, H]``; mask_blk boolean
    ``[B, N, Sq, Sb]`` (True = attend). Running stats: m/l ``[B, N, Sq]``,
    o ``[B, Sq, N, H]`` — all fp32.
    """
    s = jnp.einsum(
        'bqnh,bknh->bnqk',
        q.astype(jnp.float32),
        k_blk.astype(jnp.float32),
    ) * scale
    s = jnp.where(mask_blk, s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    # Rows with no valid key yet keep m == NEG_INF; exp(s - m) would be
    # exp(0) there, but l stays 0 and the final divide guards against it.
    p = jnp.exp(s - m_new[..., None])
    p = jnp.where(mask_blk, p, 0.0)
    correction = jnp.exp(m - m_new)
    l_new = l * correction + jnp.sum(p, axis=-1)
    o_new = o * correction.transpose(0, 2, 1)[..., None] + jnp.einsum(
        'bnqk,bknh->bqnh', p, v_blk.astype(jnp.float32)
    )
    return m_new, l_new, o_new


def _ring_attention_local(
    q,
    k,
    v,
    kv_mask,
    *,
    axis_name: str,
    causal: bool,
    scale: float,
):
    """Per-shard ring attention body (run under ``shard_map``).

    Shapes (local shard): q/k/v ``[B, S_loc, N, H]``, kv_mask ``[B, S_loc]``
    boolean. Sequence is sharded contiguously: shard ``i`` holds global
    positions ``[i*S_loc, (i+1)*S_loc)``.
    """
    ring_size = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    b, s_loc, n, h = q.shape
    perm = [(i, (i + 1) % ring_size) for i in range(ring_size)]

    q_pos = my_idx * s_loc + jnp.arange(s_loc)  # global query positions

    m0 = jnp.full((b, n, s_loc), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, n, s_loc), jnp.float32)
    o0 = jnp.zeros((b, s_loc, n, h), jnp.float32)

    def body(step, carry):
        m, l, o, k_blk, v_blk, mask_blk = carry
        # After `step` rotations we hold the block originating at shard
        # (my_idx - step) mod P.
        src = (my_idx - step) % ring_size
        k_pos = src * s_loc + jnp.arange(s_loc)
        block_mask = mask_blk[:, None, None, :]  # [B, 1, 1, Sb]
        if causal:
            block_mask = block_mask & (
                k_pos[None, None, None, :] <= q_pos[None, None, :, None]
            )
        block_mask = jnp.broadcast_to(block_mask, (b, n, s_loc, s_loc))
        m, l, o = _block_attn_update(q, k_blk, v_blk, block_mask, m, l, o, scale)
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        mask_blk = lax.ppermute(mask_blk, axis_name, perm)
        return m, l, o, k_blk, v_blk, mask_blk

    m, l, o, _, _, _ = lax.fori_loop(
        0, ring_size, body, (m0, l0, o0, k, v, kv_mask)
    )
    out = o / jnp.clip(l, 1e-30, None).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    kv_mask: jnp.ndarray | None = None,
    causal: bool = False,
    scale: float | None = None,
    axis: str = 'seq',
    batch_axis: str | None = 'data',
) -> jnp.ndarray:
    """Exact attention over sequence-sharded ``[B, S, N, H]`` tensors.

    ``q``/``k``/``v`` must have equal head counts (apply
    :func:`distllm_tpu.models.common.repeat_kv` first for GQA). ``kv_mask``
    is a boolean ``[B, S]`` key-validity mask (padding); ``None`` means all
    keys valid. Batch may additionally be sharded over ``batch_axis``.
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if kv_mask is None:
        kv_mask = jnp.ones(k.shape[:2], bool)
    bspec = batch_axis if batch_axis in mesh.shape else None
    qkv_spec = P(bspec, axis, None, None)
    mask_spec = P(bspec, axis)
    fn = jax.shard_map(
        partial(
            _ring_attention_local,
            axis_name=axis,
            causal=causal,
            scale=scale,
        ),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, mask_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )
    return fn(q, k, v, kv_mask.astype(bool))


def _ulysses_local(q, k, v, kv_mask, *, axis_name: str, causal: bool, scale: float):
    """Ulysses body: all_to_all heads<->sequence, local full attention, undo.

    Local shapes in: ``[B, S_loc, N, H]`` with N divisible by the axis size.
    After the first all_to_all each chip holds the FULL sequence for N/P
    heads; attention is ordinary full attention; the second all_to_all
    restores sequence sharding.
    """
    p_size = lax.axis_size(axis_name)
    b, s_loc, n, h = q.shape

    def scatter_heads(x):
        # [B, S_loc, N, H] -> [B, P*S_loc, N/P, H]: device d keeps the
        # contiguous head group d for the FULL sequence (tiled all_to_all:
        # head axis divided by P, seq axis concatenated in ring order).
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def gather_seq_mask(mask):
        # [B, S_loc] -> [B, P*S_loc] (every chip needs the full key mask)
        return lax.all_gather(mask, axis_name, axis=1, tiled=True)

    qg, kg, vg = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    mask_g = gather_seq_mask(kv_mask)  # [B, S_glob]
    s_glob = p_size * s_loc

    # Blockwise online-softmax over key blocks of S_loc: peak score-matrix
    # memory is O(S_glob * S_loc) per chip instead of O(S_glob^2) — the
    # whole point of sharding the sequence in the first place.
    n_loc = n // p_size
    q_pos = jnp.arange(s_glob)
    m0 = jnp.full((b, n_loc, s_glob), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, n_loc, s_glob), jnp.float32)
    o0 = jnp.zeros((b, s_glob, n_loc, h), jnp.float32)

    def body(i, carry):
        m, l, o = carry
        k_blk = lax.dynamic_slice_in_dim(kg, i * s_loc, s_loc, axis=1)
        v_blk = lax.dynamic_slice_in_dim(vg, i * s_loc, s_loc, axis=1)
        mask_blk = lax.dynamic_slice_in_dim(mask_g, i * s_loc, s_loc, axis=1)
        k_pos = i * s_loc + jnp.arange(s_loc)
        block_mask = mask_blk[:, None, None, :]
        if causal:
            block_mask = block_mask & (
                k_pos[None, None, None, :] <= q_pos[None, None, :, None]
            )
        block_mask = jnp.broadcast_to(block_mask, (b, n_loc, s_glob, s_loc))
        m, l, o = _block_attn_update(qg, k_blk, v_blk, block_mask, m, l, o, scale)
        return m, l, o

    m, l, og = lax.fori_loop(0, p_size, body, (m0, l0, o0))
    og = (og / jnp.clip(l, 1e-30, None).transpose(0, 2, 1)[..., None]).astype(
        q.dtype
    )

    # [B, S_glob, N/P, H] -> [B, S_loc, N, H]: seq axis divided back to the
    # local block; head groups concatenate in source order, restoring the
    # original head ordering.
    return lax.all_to_all(og, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    kv_mask: jnp.ndarray | None = None,
    causal: bool = False,
    scale: float | None = None,
    axis: str = 'seq',
    batch_axis: str | None = 'data',
) -> jnp.ndarray:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses construction).

    Requires ``num_heads %% mesh.shape[axis] == 0``. Same exact semantics as
    :func:`ring_attention`; different collective pattern (one all_to_all pair
    + mask all_gather instead of P-1 ppermutes).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if kv_mask is None:
        kv_mask = jnp.ones(k.shape[:2], bool)
    p_size = mesh.shape[axis]
    if q.shape[2] % p_size != 0:
        raise ValueError(
            f'ulysses needs heads ({q.shape[2]}) divisible by the {axis!r} '
            f'axis size ({p_size}); use ring_attention instead'
        )
    bspec = batch_axis if batch_axis in mesh.shape else None
    qkv_spec = P(bspec, axis, None, None)
    fn = jax.shard_map(
        partial(_ulysses_local, axis_name=axis, causal=causal, scale=scale),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, P(bspec, axis)),
        out_specs=qkv_spec,
        check_vma=False,
    )
    return fn(q, k, v, kv_mask.astype(bool))
