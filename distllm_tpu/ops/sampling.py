"""Token sampling: temperature, top-p, min-p, greedy — vectorized and jitted.

Reference parity: vLLM ``SamplingParams`` as configured by
``generate/generators/vllm_backend.py:48-60`` (temperature, max_tokens, and
top_p XOR min_p; greedy when temperature == 0). All filtering happens on
fp32 logits; each sequence carries its own parameters so one decode batch can
mix sampling configs (continuous batching requirement).

This runs INSIDE the engine's fused decode scan (one sample per decode
step), so it is written for the TPU hot path: a single descending sort
serves the top-p cutoff, and min-p is applied as a pure log-space
comparison (``prob >= min_p * max_prob  <=>  logit >= max_logit +
log(min_p)``) — no softmax materialization, no second sort.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _top_p_from_sorted(
    logits: jnp.ndarray, sorted_desc: jnp.ndarray, top_p: jnp.ndarray
) -> jnp.ndarray:
    sorted_probs = jax.nn.softmax(sorted_desc, axis=-1)
    cumulative = jnp.cumsum(sorted_probs, axis=-1)
    # Keep the smallest prefix with cumulative >= top_p (always >= 1 token).
    cutoff_idx = jnp.sum(cumulative < top_p[:, None], axis=-1)
    cutoff_logit = jnp.take_along_axis(
        sorted_desc, cutoff_idx[:, None], axis=-1
    )
    keep = logits >= cutoff_logit
    return jnp.where(keep, logits, -jnp.inf)


def sample_tokens(
    logits: jnp.ndarray,  # [B, V] fp32
    key: jax.Array,
    temperature: jnp.ndarray,  # [B]
    top_p: jnp.ndarray,  # [B] (1.0 disables)
    min_p: jnp.ndarray,  # [B] (0.0 disables)
) -> jnp.ndarray:
    """Per-sequence sampling; temperature == 0 rows are greedy."""
    logits = logits.astype(jnp.float32)

    safe_temp = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits / safe_temp[:, None]
    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    greedy = jnp.argmax(logits, axis=-1)

    filtered = _top_p_from_sorted(scaled, sorted_desc, top_p)
    # min-p in log space: prob >= min_p * max_prob is equivalent to
    # logit >= max_logit + log(min_p); log(0) = -inf disables the filter.
    max_logit = sorted_desc[:, :1]
    min_p_threshold = max_logit + jnp.log(jnp.maximum(min_p, 0.0))[:, None]
    filtered = jnp.where(scaled >= min_p_threshold, filtered, -jnp.inf)

    sampled = jax.random.categorical(key, filtered, axis=-1)
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)
