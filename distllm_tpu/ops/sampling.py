"""Token sampling: temperature, top-p, min-p, greedy — vectorized and jitted.

Reference parity: vLLM ``SamplingParams`` as configured by
``generate/generators/vllm_backend.py:48-60`` (temperature, max_tokens, and
top_p XOR min_p; greedy when temperature == 0). All filtering happens on
fp32 logits; each sequence carries its own parameters so one decode batch can
mix sampling configs (continuous batching requirement).

This runs INSIDE the engine's fused decode scan (one sample per decode
step), so it is written for the TPU hot path: ONE implementation over the
``top_window`` largest logits (``jax.lax.top_k``), with ``top_window = V``
recovering the exact full-vocabulary semantics (top_k(V) is a descending
sort). Probabilities always use the full-vocab logsumexp normalizer, so
top-p prefixes and min-p thresholds are exact whenever the top-p cutoff
falls inside the window; min-p is a pure log-space comparison
(``prob >= min_p * max_prob  <=>  logit >= max_logit + log(min_p)``) — no
softmax materialization.

Why a window at all: XLA's TPU sort over V=32k is a multi-pass bitonic
network, paid once per decode step inside a 16-step window scan. A
``top_window`` of 64 (the engine's recommended serving setting; vLLM's
``top_k`` semantic, applied before top-p) replaces it with one
``lax.top_k`` pass. The library default is 0 (= exact) to preserve
reference parity for pure-temperature sampling.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_tokens(  # distlint: traced
    logits: jnp.ndarray,  # [B, V] fp32
    key: jax.Array,
    temperature: jnp.ndarray,  # [B]
    top_p: jnp.ndarray,  # [B] (1.0 disables)
    min_p: jnp.ndarray,  # [B] (0.0 disables)
    top_window: int = 0,
) -> jnp.ndarray:
    """Per-sequence sampling; temperature == 0 rows are greedy.

    ``top_window > 0`` caps the kept set at that many tokens (see module
    docstring); ``0`` or ``>= V`` is exact.
    """
    vocab = logits.shape[-1]
    k = vocab if top_window <= 0 else min(top_window, vocab)

    logits = logits.astype(jnp.float32)
    safe_temp = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits / safe_temp[:, None]

    top_vals, top_idx = jax.lax.top_k(scaled, k)  # descending
    # Exact probabilities: normalize against the whole vocabulary.
    lse = jax.scipy.special.logsumexp(scaled, axis=-1, keepdims=True)
    probs = jnp.exp(top_vals - lse)
    cumulative = jnp.cumsum(probs, axis=-1)
    # Keep the smallest prefix with cumulative >= top_p (always >= 1 token).
    cutoff_idx = jnp.minimum(
        jnp.sum(cumulative < top_p[:, None], axis=-1), k - 1
    )
    cutoff_logit = jnp.take_along_axis(top_vals, cutoff_idx[:, None], axis=-1)
    filtered = jnp.where(top_vals >= cutoff_logit, top_vals, -jnp.inf)
    # min-p in log space; log(0) = -inf disables the filter.
    min_p_threshold = top_vals[:, :1] + jnp.log(
        jnp.maximum(min_p, 0.0)
    )[:, None]
    filtered = jnp.where(top_vals >= min_p_threshold, filtered, -jnp.inf)

    choice = jax.random.categorical(key, filtered, axis=-1)
    sampled = jnp.take_along_axis(top_idx, choice[:, None], axis=-1)[:, 0]
    return jnp.where(temperature > 0, sampled, top_idx[:, 0]).astype(
        jnp.int32
    )


def sample_tokens_windowed(  # distlint: traced
    logits: jnp.ndarray,
    key: jax.Array,
    temperature: jnp.ndarray,
    top_p: jnp.ndarray,
    min_p: jnp.ndarray,
    top_window: int,
) -> jnp.ndarray:
    """Alias for :func:`sample_tokens` with an explicit window (kept for
    call sites that always window)."""
    return sample_tokens(
        logits, key, temperature, top_p, min_p,
        top_window=max(1, top_window),
    )
