"""Token sampling & speculative verification: temperature, top-p/top-k,
min-p, rejection sampling — vectorized and jitted.

Reference parity: vLLM ``SamplingParams`` as configured by
``generate/generators/vllm_backend.py:48-60`` (temperature, max_tokens, and
top_p XOR min_p; greedy when temperature == 0). All filtering happens on
fp32 logits; each sequence carries its own parameters so one decode batch can
mix sampling configs (continuous batching requirement).

This runs INSIDE the engine's fused decode scan (one sample per decode
step), so it is written for the TPU hot path: ONE implementation over the
``top_window`` largest logits (``jax.lax.top_k``), with ``top_window = V``
recovering the exact full-vocabulary semantics (top_k(V) is a descending
sort). Probabilities always use the full-vocab logsumexp normalizer, so
top-p prefixes and min-p thresholds are exact whenever the top-p cutoff
falls inside the window; min-p is a pure log-space comparison
(``prob >= min_p * max_prob  <=>  logit >= max_logit + log(min_p)``) — no
softmax materialization. Per-request ``top_k`` is a rank mask over the same
descending window (0 disables, a bitwise no-op).

Why a window at all: XLA's TPU sort over V=32k is a multi-pass bitonic
network, paid once per decode step inside a 16-step window scan. A
``top_window`` of 64 (the engine's recommended serving setting; vLLM's
``top_k`` semantic, applied before top-p) replaces it with one
``lax.top_k`` pass. The library default is 0 (= exact) to preserve
reference parity for pure-temperature sampling.

PRNG contract (docs/speculative.md "Sampled verification"): the draw for
the token at absolute sequence index ``i`` of a request uses
``fold_in(fold_in(PRNGKey(request_seed), i), tag)``. ``_ACCEPT_FOLD`` tags
the speculative accept/reject uniform; ``_SAMPLE_FOLD`` tags every
categorical draw (ordinary sampling, residual resampling, and the bonus
token). Because the key depends only on (request seed, token index), a
request's sampled stream is deterministic per (seed, schedule) and
identical across decode_window / mixed_window / spec_window dispatch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_ACCEPT_FOLD = 1
_SAMPLE_FOLD = 2


def fold_row_keys(  # distlint: traced
    seeds: jnp.ndarray,  # [B] uint32 per-request seeds
    counters: jnp.ndarray,  # [B] int32 absolute token indices
    fold: int = _SAMPLE_FOLD,
) -> jax.Array:
    """Derive one PRNG key per row from (seed, token counter, tag).

    Counter-based rather than split-based: the key for a draw is a pure
    function of the request seed and the absolute index of the token being
    produced, so replays and cross-dispatch paths (decode scan vs. spec
    verify) agree bit-for-bit.
    """

    def one(seed, counter):
        key = jax.random.PRNGKey(seed)
        return jax.random.fold_in(jax.random.fold_in(key, counter), fold)

    return jax.vmap(one)(seeds, counters)


def filter_logits(  # distlint: traced
    logits: jnp.ndarray,  # [B, V] fp32
    temperature: jnp.ndarray,  # [B]
    top_p: jnp.ndarray,  # [B] (1.0 disables)
    min_p: jnp.ndarray,  # [B] (0.0 disables)
    top_k: jnp.ndarray | None = None,  # [B] int32 (0 disables)
    top_window: int = 0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Temperature-scale and filter logits; shared by sampling and verify.

    Returns ``(filtered, top_idx)``: the temperature-scaled logits over the
    descending ``top_window`` set with every filtered-out entry at ``-inf``
    (categorical over ``filtered`` samples the served distribution), and the
    vocab indices of that window. At least one token always survives.
    """
    vocab = logits.shape[-1]
    k = vocab if top_window <= 0 else min(top_window, vocab)

    logits = logits.astype(jnp.float32)
    safe_temp = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits / safe_temp[:, None]

    top_vals, top_idx = jax.lax.top_k(scaled, k)  # descending
    # Exact probabilities: normalize against the whole vocabulary.
    lse = jax.scipy.special.logsumexp(scaled, axis=-1, keepdims=True)
    probs = jnp.exp(top_vals - lse)
    cumulative = jnp.cumsum(probs, axis=-1)
    # Keep the smallest prefix with cumulative >= top_p (always >= 1 token).
    cutoff_idx = jnp.minimum(
        jnp.sum(cumulative < top_p[:, None], axis=-1), k - 1
    )
    cutoff_logit = jnp.take_along_axis(top_vals, cutoff_idx[:, None], axis=-1)
    filtered = jnp.where(top_vals >= cutoff_logit, top_vals, -jnp.inf)
    # min-p in log space; log(0) = -inf disables the filter.
    min_p_threshold = top_vals[:, :1] + jnp.log(
        jnp.maximum(min_p, 0.0)
    )[:, None]
    filtered = jnp.where(top_vals >= min_p_threshold, filtered, -jnp.inf)
    if top_k is not None:
        # Rank mask over the descending window; intersects with top-p/min-p
        # rather than renormalizing first, so top_k == 0 is a bitwise no-op.
        eff = jnp.where(top_k > 0, jnp.minimum(top_k, k), k)
        keep = jnp.arange(k)[None, :] < eff[:, None]
        filtered = jnp.where(keep, filtered, -jnp.inf)
    return filtered, top_idx


def sample_tokens(  # distlint: traced
    logits: jnp.ndarray,  # [B, V] fp32
    key: jax.Array | None,
    temperature: jnp.ndarray,  # [B]
    top_p: jnp.ndarray,  # [B] (1.0 disables)
    min_p: jnp.ndarray,  # [B] (0.0 disables)
    top_window: int = 0,
    top_k: jnp.ndarray | None = None,  # [B] int32 (0 disables)
    row_keys: jax.Array | None = None,  # [B] keys from fold_row_keys
) -> jnp.ndarray:
    """Per-sequence sampling; temperature == 0 rows are greedy.

    ``top_window > 0`` caps the kept set at that many tokens (see module
    docstring); ``0`` or ``>= V`` is exact. With ``row_keys`` each row draws
    from its own counter-derived key (the engine's deterministic path);
    otherwise one batch ``key`` feeds a single categorical (legacy path).
    """
    filtered, top_idx = filter_logits(
        logits, temperature, top_p, min_p, top_k=top_k,
        top_window=top_window,
    )
    if row_keys is not None:
        choice = jax.vmap(
            lambda rk, row: jax.random.categorical(rk, row)
        )(row_keys, filtered)
    else:
        choice = jax.random.categorical(key, filtered, axis=-1)
    sampled = jnp.take_along_axis(top_idx, choice[:, None], axis=-1)[:, 0]
    return jnp.where(temperature > 0, sampled, top_idx[:, 0]).astype(
        jnp.int32
    )


def sample_tokens_windowed(  # distlint: traced
    logits: jnp.ndarray,
    key: jax.Array | None,
    temperature: jnp.ndarray,
    top_p: jnp.ndarray,
    min_p: jnp.ndarray,
    top_window: int,
    top_k: jnp.ndarray | None = None,
    row_keys: jax.Array | None = None,
) -> jnp.ndarray:
    """Alias for :func:`sample_tokens` with an explicit window (kept for
    call sites that always window)."""
    return sample_tokens(
        logits, key, temperature, top_p, min_p,
        top_window=max(1, top_window), top_k=top_k, row_keys=row_keys,
    )


def verify_spans(  # distlint: traced
    span_logits: jnp.ndarray,  # [B, S, V] fp32, all_logits=True span scores
    span_ids: jnp.ndarray,  # [B, S] int32: [last committed, draft_1..m]
    span_lens: jnp.ndarray,  # [B] int32: 1 + m (0 = inactive row)
    span_positions: jnp.ndarray,  # [B, S] int32 absolute span positions
    temperature: jnp.ndarray,  # [B]
    top_p: jnp.ndarray,  # [B]
    min_p: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B] int32
    seeds: jnp.ndarray,  # [B] uint32 per-request seeds
    top_window: int = 0,
) -> jnp.ndarray:
    """Device-side speculative verification (rejection sampling).

    Standard speculative-sampling rule over the *served* (filtered target)
    distribution p̃ with the prompt-lookup point-mass proposal q: accept
    draft d_i with probability min(1, p̃(d_i)/q(d_i)) = p̃(d_i); on
    rejection sample the normalized positive residual (p̃ − q)+ — p̃ with
    the draft masked out — and stop the span. Greedy rows (temperature
    <= 0) keep the exact pre-existing argmax semantics bit-for-bit:
    out[i] = argmax and a draft is accepted iff it equals that argmax.

    Returns packed ``[B, S+1]`` int32: ``out`` tokens per span position
    followed by ``accept_len`` (number of leading accepted drafts, in
    [0, m]). The host emits ``out[0..accept_len]`` inclusive —
    ``out[accept_len]`` is the residual correction, or the bonus token
    sampled from the full filtered target when every draft was accepted.
    """
    b, s, vocab = span_logits.shape
    flat = span_logits.reshape(b * s, vocab)

    def rep(x):
        return jnp.repeat(x, s)

    filtered, top_idx = filter_logits(
        flat, rep(temperature), rep(top_p), rep(min_p), top_k=rep(top_k),
        top_window=top_window,
    )
    kw = filtered.shape[-1]
    # The token produced at span position i has absolute index pos_i + 1 —
    # the same counter the decode scan uses for that token, so sampled
    # streams agree across dispatch flavors.
    counters = (span_positions + 1).astype(jnp.int32).reshape(b * s)
    u_keys = fold_row_keys(rep(seeds), counters, _ACCEPT_FOLD)
    s_keys = fold_row_keys(rep(seeds), counters, _SAMPLE_FOLD)

    filtered = filtered.reshape(b, s, kw)
    top_idx = top_idx.reshape(b, s, kw)
    cand = top_idx[:, :, 0]  # greedy candidate per position

    m = jnp.maximum(span_lens - 1, 0)  # drafts per row
    drafts = jnp.concatenate(
        [span_ids[:, 1:], jnp.zeros((b, 1), span_ids.dtype)], axis=1
    )
    pos_in_draft = jnp.arange(s)[None, :] < m[:, None]

    # log p̃(draft) under the filtered target; -inf when the draft fell
    # outside the kept set (q point mass outside supp(p̃) never accepts).
    match = top_idx == drafts[:, :, None]
    logz = jax.scipy.special.logsumexp(filtered, axis=-1)
    draft_val = jnp.max(jnp.where(match, filtered, -jnp.inf), axis=-1)
    log_p_draft = draft_val - logz

    u = jax.vmap(jax.random.uniform)(u_keys).reshape(b, s)
    sampled_row = temperature[:, None] > 0
    accept = jnp.where(sampled_row, u < jnp.exp(log_p_draft), cand == drafts)
    accept = accept & pos_in_draft

    # Residual (p̃ − q)+ for the point-mass q: p̃ with the draft masked out
    # (categorical renormalizes). The bonus slot (past the drafts) and rows
    # whose kept set is exactly {draft} — where acceptance is certain and
    # the residual is empty — sample the full filtered target instead.
    residual = jnp.where(match, -jnp.inf, filtered)
    res_valid = jnp.any(jnp.isfinite(residual), axis=-1)
    use_residual = pos_in_draft & res_valid
    corr_src = jnp.where(use_residual[:, :, None], residual, filtered)
    choice = jax.vmap(jax.random.categorical)(
        s_keys, corr_src.reshape(b * s, kw)
    ).reshape(b, s)
    corr_sampled = jnp.take_along_axis(
        top_idx, choice[:, :, None], axis=-1
    )[:, :, 0]
    correction = jnp.where(sampled_row, corr_sampled, cand)

    out = jnp.where(accept, drafts, correction).astype(jnp.int32)
    accept_len = jnp.sum(
        jnp.cumprod(accept.astype(jnp.int32), axis=-1), axis=-1
    ).astype(jnp.int32)
    return jnp.concatenate([out, accept_len[:, None]], axis=-1)
