"""Token sampling: temperature, top-p, min-p, greedy — vectorized and jitted.

Reference parity: vLLM ``SamplingParams`` as configured by
``generate/generators/vllm_backend.py:48-60`` (temperature, max_tokens, and
top_p XOR min_p; greedy when temperature == 0). All filtering happens on
fp32 logits; each sequence carries its own parameters so one decode batch can
mix sampling configs (continuous batching requirement).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _apply_top_p(logits: jnp.ndarray, top_p: jnp.ndarray) -> jnp.ndarray:
    """Nucleus filtering per row; ``top_p >= 1`` disables."""
    sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cumulative = jnp.cumsum(sorted_probs, axis=-1)
    # Keep the smallest prefix with cumulative >= top_p (always >= 1 token).
    cutoff_idx = jnp.sum(cumulative < top_p[:, None], axis=-1)
    cutoff_logit = jnp.take_along_axis(
        sorted_logits, cutoff_idx[:, None], axis=-1
    )
    keep = logits >= cutoff_logit
    return jnp.where(keep, logits, -jnp.inf)


def _apply_min_p(logits: jnp.ndarray, min_p: jnp.ndarray) -> jnp.ndarray:
    """Keep tokens with prob >= min_p * max_prob; ``min_p <= 0`` disables."""
    probs = jax.nn.softmax(logits, axis=-1)
    threshold = min_p[:, None] * jnp.max(probs, axis=-1, keepdims=True)
    keep = probs >= threshold
    return jnp.where(keep, logits, -jnp.inf)


def sample_tokens(
    logits: jnp.ndarray,  # [B, V] fp32
    key: jax.Array,
    temperature: jnp.ndarray,  # [B]
    top_p: jnp.ndarray,  # [B] (1.0 disables)
    min_p: jnp.ndarray,  # [B] (0.0 disables)
) -> jnp.ndarray:
    """Per-sequence sampling; temperature == 0 rows are greedy."""
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1)

    safe_temp = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits / safe_temp[:, None]
    scaled = _apply_top_p(scaled, top_p)
    scaled = _apply_min_p(scaled, min_p)
    sampled = jax.random.categorical(key, scaled, axis=-1)
    return jnp.where(temperature > 0, sampled, greedy).astype(jnp.int32)
