"""RAG layer: sharded semantic search index, retriever, response synthesis,
and QA evaluation tasks (reference: ``distllm/rag/``)."""
