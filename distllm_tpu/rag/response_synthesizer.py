"""RAG response synthesis: retrieve → prompt → generate → postprocess.

Reference parity: ``distllm/rag/response_synthesizer.py:18-92`` — with no
retriever attached the generator runs as the no-RAG baseline; with one, each
query's top-k texts and scores are passed to the prompt template.
"""

from __future__ import annotations

from distllm_tpu.generate.generators.base import LLMGenerator
from distllm_tpu.generate.prompts import get_prompt_template
from distllm_tpu.generate.prompts.base import PromptTemplate
from distllm_tpu.rag.search import Retriever


class RagGenerator:
    """Generate responses to queries with optional retrieval augmentation."""

    def __init__(
        self,
        generator: LLMGenerator,
        retriever: Retriever | None = None,
    ) -> None:
        self.generator = generator
        self.retriever = retriever

    def generate(
        self,
        texts: str | list[str],
        prompt_template: PromptTemplate | None = None,
        retrieval_top_k: int = 5,
        retrieval_score_threshold: float = 0.0,
    ) -> list[str]:
        if isinstance(texts, str):
            texts = [texts]
        if prompt_template is None:
            prompt_template = get_prompt_template({'name': 'identity'})

        contexts, scores = None, None
        if self.retriever is not None:
            results, _ = self.retriever.search(
                texts,
                top_k=retrieval_top_k,
                score_threshold=retrieval_score_threshold,
            )
            contexts = [
                self.retriever.get_texts(indices)
                for indices in results.total_indices
            ]
            scores = results.total_scores

        prompts = prompt_template.preprocess(texts, contexts, scores)
        responses = self.generator.generate(prompts)
        responses = prompt_template.postprocess(responses)
        assert len(texts) == len(responses), (
            'Mismatch between queries and responses.'
        )
        return responses
