"""LitQA evaluation task (reference: ``distllm/rag/tasks/litqa.py:44-110``)."""

from __future__ import annotations

import json
import random

from pydantic import BaseModel, Field, field_validator

from distllm_tpu.rag.tasks.base import QuestionAnswerTask
from distllm_tpu.utils import curl_download

LITQA_URL = (
    'https://raw.githubusercontent.com/Future-House/LitQA/main/litqa-v0.jsonl'
)


class QuestionAnswerEntry(BaseModel):
    id: str = Field(default='')
    question: str
    ideal: str
    distractors: list[str]
    sources: str | list[str] = Field(default='')

    @field_validator('ideal', mode='before')
    @classmethod
    def _lower_ideal(cls, value: str) -> str:
        return value.lower()

    @field_validator('distractors', mode='before')
    @classmethod
    def _lower_distractors(cls, value: list[str]) -> list[str]:
        return [v.lower() for v in value]

    def get_multiple_choice(self, rng: random.Random | None = None) -> str:
        """Random 3 distractors (padded with '' when fewer) + shuffle.

        Sampling/shuffling uses an RNG seeded per entry (question hash) so
        every model in an eval suite is graded on the SAME rendering and runs
        are reproducible — the reference's unseeded global ``random`` makes
        accuracy partly an RNG artifact across models.
        """
        if rng is None:
            seed = int.from_bytes(
                __import__('hashlib').sha256(self.question.encode()).digest()[:8],
                'little',
            )
            rng = random.Random(seed)
        k = 3
        distractors = rng.sample(
            self.distractors, min(k, len(self.distractors))
        )
        distractors.extend([''] * (k - len(distractors)))
        options = [self.ideal, *distractors]
        rng.shuffle(options)
        mark = '' if self.question.endswith('?') else '?'
        return '{}\nOptions:\n1. {}\n2. {}\n3. {}\n4. {}\n'.format(
            f'{self.question}{mark}', *options
        )


class LitQATask(QuestionAnswerTask):
    task_name = 'litqa'

    def download(self) -> None:
        self.data_file = self.download_dir / 'litqa.jsonl'
        curl_download(LITQA_URL, self.data_file)

    def load_data(self) -> tuple[list[str], list[str]]:
        lines = self.data_file.read_text().strip().split('\n')
        entries = [QuestionAnswerEntry(**json.loads(line)) for line in lines]
        questions = [e.get_multiple_choice() for e in entries]
        ground_truths = [e.ideal for e in entries]
        return questions, ground_truths
