"""AmpQA protein tasks (reference: ``rag/tasks/protein_function_qa.py`` and
``rag/tasks/protein_interaction_qa.py``).

Both filter out entries whose ideal answer exceeds 200 words and build
shuffled 4-option multiple-choice questions like LitQA.
"""

from __future__ import annotations

import json

from distllm_tpu.rag.tasks.base import QuestionAnswerTask
from distllm_tpu.rag.tasks.litqa import QuestionAnswerEntry
from distllm_tpu.utils import curl_download

FUNCTION_QA_URL = (
    'https://raw.githubusercontent.com/ramanathanlab/AmpQA/main/FunctionQA.jsonl'
)
INTERACTION_QA_URL = (
    'https://raw.githubusercontent.com/ramanathanlab/AmpQA/main/interactionQA.json'
)

_MAX_IDEAL_WORDS = 200


def _filter_long_ideals(
    entries: list[QuestionAnswerEntry],
) -> list[QuestionAnswerEntry]:
    return [
        e for e in entries if len(e.ideal.split()) <= _MAX_IDEAL_WORDS
    ]


def _to_questions(
    entries: list[QuestionAnswerEntry],
) -> tuple[list[str], list[str]]:
    entries = _filter_long_ideals(entries)
    return (
        [e.get_multiple_choice() for e in entries],
        [e.ideal for e in entries],
    )


class ProteinFunctionQATask(QuestionAnswerTask):
    task_name = 'protein_function_qa'

    def download(self) -> None:
        self.data_file = self.download_dir / 'functionQA.jsonl'
        curl_download(FUNCTION_QA_URL, self.data_file)

    def load_data(self) -> tuple[list[str], list[str]]:
        lines = self.data_file.read_text().strip().split('\n')
        entries = [QuestionAnswerEntry(**json.loads(line)) for line in lines]
        return _to_questions(entries)


class ProteinInteractionQATask(QuestionAnswerTask):
    task_name = 'protein_interaction_qa'

    def download(self) -> None:
        self.data_file = self.download_dir / 'interactionQA.json'
        curl_download(INTERACTION_QA_URL, self.data_file)

    def load_data(self) -> tuple[list[str], list[str]]:
        with open(self.data_file) as fh:
            data = json.load(fh)
        entries = [QuestionAnswerEntry(**entry) for entry in data]
        return _to_questions(entries)
