"""PubMedQA evaluation task (reference: ``distllm/rag/tasks/pubmedqa.py``)."""

from __future__ import annotations

import json

from pydantic import BaseModel, Field

from distllm_tpu.rag.tasks.base import QuestionAnswerTask
from distllm_tpu.utils import curl_download

PUBMEDQA_URL = (
    'https://raw.githubusercontent.com/pubmedqa/pubmedqa/master/data/ori_pqal.json'
)


class PubmedQAEntry(BaseModel):
    QUESTION: str
    CONTEXTS: list[str]
    final_decision: str = Field(description='yes / no / maybe')

    model_config = {'extra': 'ignore'}

    def get_multiple_choice(self) -> str:
        """yes/no/maybe options with the PubmedQA-provided contexts inline."""
        mark = '' if self.QUESTION.endswith('?') else '?'
        options = ['yes', 'no', 'maybe']
        joined = '\n'.join(self.CONTEXTS)
        return '{}\n{}\n{}\nOptions:\n1. {}\n2. {}\n3. {}\n'.format(
            'Most relevant context:', joined, f'{self.QUESTION}{mark}', *options
        )


class PubmedQATask(QuestionAnswerTask):
    task_name = 'pubmedqa'

    def download(self) -> None:
        self.data_file = self.download_dir / 'pubmedQA.json'
        curl_download(PUBMEDQA_URL, self.data_file)

    def load_data(self) -> tuple[list[str], list[str]]:
        with open(self.data_file) as fh:
            data = json.load(fh)
        entries = [PubmedQAEntry(**value) for value in data.values()]
        questions = [e.get_multiple_choice() for e in entries]
        ground_truths = [e.final_decision for e in entries]
        return questions, ground_truths
