"""SciQ evaluation task (reference: ``distllm/rag/tasks/sciq.py:35-110``).

Deliberate fixes over the reference: the reference's format string drops the
fourth option ('1..2..3.' placeholders for 4 options) and compares lowercased
predictions against unlowered ground truths; here all four options render and
ground truths are lowercased to match the question_answer postprocess.
"""

from __future__ import annotations

import json

from pydantic import BaseModel, Field

from distllm_tpu.rag.tasks.base import QuestionAnswerTask
from distllm_tpu.utils import curl_download

SCIQ_URL = (
    'https://raw.githubusercontent.com/ogkdmr/sciqa_questions/main/test.json'
)


class SciQEntry(BaseModel):
    question: str
    distractor1: str
    distractor2: str
    distractor3: str
    correct_answer: str
    support: str = Field(default='')

    model_config = {'extra': 'ignore'}

    def get_multiple_choice(self) -> str:
        mark = '' if self.question.endswith('?') else '?'
        options = [
            self.correct_answer,
            self.distractor1,
            self.distractor2,
            self.distractor3,
        ]
        return '{}\nOptions:\n1. {}\n2. {}\n3. {}\n4. {}\n'.format(
            f'{self.question}{mark}', *options
        )


class SciQTask(QuestionAnswerTask):
    task_name = 'sciq'

    def download(self) -> None:
        self.data_file = self.download_dir / 'sciq.json'
        curl_download(SCIQ_URL, self.data_file)

    def load_data(self) -> tuple[list[str], list[str]]:
        with open(self.data_file) as fh:
            data = json.load(fh)
        entries = [SciQEntry(**entry) for entry in data]
        questions = [e.get_multiple_choice() for e in entries]
        ground_truths = [e.correct_answer.lower() for e in entries]
        return questions, ground_truths
