"""Task registry (reference: ``distllm/rag/tasks/__init__.py:14-20``)."""

from __future__ import annotations

from pathlib import Path

from distllm_tpu.rag.tasks.base import EvaluationTask, QuestionAnswerTask
from distllm_tpu.rag.tasks.litqa import LitQATask
from distllm_tpu.rag.tasks.protein_qa import (
    ProteinFunctionQATask,
    ProteinInteractionQATask,
)
from distllm_tpu.rag.tasks.pubmedqa import PubmedQATask
from distllm_tpu.rag.tasks.sciq import SciQTask

TASKS: dict[str, type] = {
    'litqa': LitQATask,
    'pubmedqa': PubmedQATask,
    'sciq': SciQTask,
    'protein_function_qa': ProteinFunctionQATask,
    'protein_interaction_qa': ProteinInteractionQATask,
}


def get_task(name: str, download_dir: Path) -> EvaluationTask:
    cls = TASKS.get(name)
    if cls is None:
        raise ValueError(f'Unknown task: {name!r}. Available: {sorted(TASKS)}')
    return cls(download_dir)


__all__ = ['EvaluationTask', 'QuestionAnswerTask', 'TASKS', 'get_task']
