"""Evaluation task interfaces (reference: ``distllm/rag/tasks/base.py``).

``QuestionAnswerTask`` drives: download (curl, skipped when cached) →
load_data → RagGenerator.generate with the ``question_answer`` template →
accuracy + precision, where precision excludes abstentions
('I cannot answer.', reference ``base.py:108-131``).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from pathlib import Path
from typing import Any, Protocol, runtime_checkable

from distllm_tpu.generate.prompts import get_prompt_template
from distllm_tpu.rag.response_synthesizer import RagGenerator

ABSTAIN_ANSWER = 'I cannot answer.'


def _normalize_answer(text: str) -> str:
    """Match the question_answer postprocess normalization (trailing-period
    strip + lowercase) so abstentions are recognized after postprocessing."""
    text = text.strip()
    if text.endswith('.'):
        text = text[:-1]
    return text.lower()


_ABSTAIN_NORMALIZED = _normalize_answer(ABSTAIN_ANSWER)


@runtime_checkable
class EvaluationTask(Protocol):
    task_name: str

    def __init__(self, download_dir: Path) -> None: ...

    def evaluate(self, generator: RagGenerator) -> dict[str, Any]: ...


class QuestionAnswerTask(ABC):
    task_name = ''

    def __init__(self, download_dir: Path) -> None:
        if not self.task_name:
            raise NotImplementedError('task_name must be set in the subclass.')
        self.prompt_template = get_prompt_template({'name': 'question_answer'})
        self.download_dir = Path(download_dir) / self.task_name
        self.download_dir.mkdir(parents=True, exist_ok=True)
        self.data_file: Path | None = None

    @abstractmethod
    def download(self) -> None:
        """Fetch the dataset (no-op when the file is already on disk)."""

    @abstractmethod
    def load_data(self) -> tuple[list[str], list[str]]:
        """Return (questions, ground_truth_answers)."""

    @staticmethod
    def compute_accuracy(ground_truths: list[str], preds: list[str]) -> float:
        if not ground_truths:
            return 0.0
        correct = sum(g == p for g, p in zip(ground_truths, preds))
        return correct / len(ground_truths)

    def compute_precision(
        self, ground_truths: list[str], preds: list[str]
    ) -> float:
        """Accuracy over the subset where the model did not abstain.

        Deliberate fix over the reference (``base.py:108-131``): the
        reference zips the FULL ground-truth list against the filtered
        predictions, misaligning every pair after the first abstention and
        dividing by the unfiltered count; here pairs stay aligned and the
        denominator is the answered subset.
        """
        kept = [
            (g, p)
            for g, p in zip(ground_truths, preds)
            if _normalize_answer(p) != _ABSTAIN_NORMALIZED
        ]
        return self.compute_accuracy([g for g, _ in kept], [p for _, p in kept])

    def evaluate(self, generator: RagGenerator) -> dict[str, float]:
        self.download()
        questions, ground_truths = self.load_data()
        preds = generator.generate(questions, self.prompt_template)
        return {
            'accuracy': self.compute_accuracy(ground_truths, preds),
            'precision': self.compute_precision(ground_truths, preds),
        }
