"""Evaluation suite: models × tasks grid (reference: ``distllm/rag/evaluate.py``).

Run: ``python -m distllm_tpu.rag.evaluate --config eval.yaml``
"""

from __future__ import annotations

import argparse
from pathlib import Path
from typing import Any

from distllm_tpu.observability.instruments import log_event
from distllm_tpu.rag.tasks import get_task
from distllm_tpu.utils import BaseConfig


class RetrievalAugmentedGenerationConfig(BaseConfig):
    """One RAG setup: a generator plus an optional retriever.

    Parity with ``rag/evaluate.py:18-45``.
    """

    generator_config: dict[str, Any]
    retriever_config: dict[str, Any] | None = None
    retrieval_top_k: int = 5
    retrieval_score_threshold: float = 0.0

    def get_rag_generator(self, register: bool = True):
        from distllm_tpu.generate import get_generator
        from distllm_tpu.rag.response_synthesizer import RagGenerator
        from distllm_tpu.rag.search import RetrieverConfig

        generator = get_generator(self.generator_config, register=register)
        retriever = None
        if self.retriever_config is not None:
            retriever = RetrieverConfig(**self.retriever_config).get_retriever(
                register=register
            )
        return RagGenerator(generator=generator, retriever=retriever)


class EvalSuiteConfig(BaseConfig):
    """Parity with ``EvalSuiteConfig`` (``rag/evaluate.py``)."""

    rag_configs: list[RetrievalAugmentedGenerationConfig]
    tasks: list[str]
    download_dir: Path
    output_path: Path | None = None


def run_eval_suite(config: EvalSuiteConfig) -> dict[str, dict[str, Any]]:
    """Evaluate every rag_config on every task; returns nested results."""
    results: dict[str, dict[str, Any]] = {}
    for model_idx, rag_config in enumerate(config.rag_configs):
        generator = rag_config.get_rag_generator()
        for task_name in config.tasks:
            task = get_task(task_name, config.download_dir)
            metrics = task.evaluate(generator)
            results.setdefault(f'model_{model_idx}', {})[task_name] = metrics
            log_event(
                f'[eval] model_{model_idx} {task_name}: {metrics}',
                component='eval',
            )
    if config.output_path is not None:
        import json

        config.output_path.parent.mkdir(parents=True, exist_ok=True)
        config.output_path.write_text(json.dumps(results, indent=2))
    return results


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--config', required=True, type=Path)
    args = parser.parse_args(argv)
    run_eval_suite(EvalSuiteConfig.from_yaml(args.config))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
