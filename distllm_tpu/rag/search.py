"""Semantic similarity search: sharded TPU index + Retriever.

TPU-native replacement for the reference's FAISS stack
(``distllm/rag/search.py``; SURVEY.md section 2.4 N2):

- :class:`TpuIndexV2` mirrors ``FaissIndexV2``'s surface — build-if-missing
  from an embeddings dataset, persist to disk, precision ``float32`` (exact
  inner product, MXU matmul + ``lax.top_k``, multi-chip via shard_map) or
  ``ubinary`` (sign-bit packed, Hamming search + fp32 **rescore** with
  ``rescore_multiplier`` oversampling, same semantics as
  sentence-transformers' ``semantic_search_faiss`` path, ``search.py:314-322``),
  score-threshold filtering, and row access ``get(indices, key)``.
  ``index_type`` accepts the reference's HNSW names but serves them with the
  exact search (on TPU the brute-force matmul IS the fast path; approximate
  graphs are a CPU workaround).
- :class:`TpuIndexV1` — deprecated V1 surface kept for config compatibility
  (``search.py:402-666``), same engine underneath.
- :class:`Retriever` — query path with sort-by-length batching, encoder +
  pooler, L2 normalization, order restoration (``search.py:743-928``).
"""

from __future__ import annotations

import warnings
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Literal

import jax.numpy as jnp
import numpy as np
from pydantic import Field

from distllm_tpu.embed.encoders.base import Encoder
from distllm_tpu.embed.poolers.base import Pooler
from distllm_tpu.ops.topk import hamming_topk, pack_sign_bits, topk_inner_product
from distllm_tpu.utils import BaseConfig


@dataclass
class BatchedSearchResults:
    """Parity with the reference's result container (``search.py:26-31``)."""

    total_indices: list[list[int]]
    total_scores: list[list[float]]


def _load_embeddings_dataset(dataset_dir: str | Path):
    from datasets import load_from_disk

    return load_from_disk(str(dataset_dir))


class TpuIndexV2Config(BaseConfig):
    name: Literal['tpu_index_v2', 'faiss_index_v2'] = 'tpu_index_v2'
    dataset_dir: Path
    index_dir: Path | None = Field(
        default=None,
        description='Where the packed index file lives; defaults to '
        'dataset_dir/tpu_index.',
    )
    index_type: str = Field(
        default='flat',
        description="'flat' (exact) — 'hnsw*' names accepted and served "
        'exactly (TPU brute force beats CPU graphs).',
    )
    precision: Literal['float32', 'ubinary'] = 'float32'
    rescore_multiplier: int = Field(
        default=4,
        description='ubinary: oversample factor before fp32 rescoring.',
    )
    metric: Literal['inner_product'] = 'inner_product'
    normalize: bool = Field(
        default=True, description='L2-normalize embeddings (cosine/IP).'
    )
    mesh: dict | None = Field(
        default=None,
        description='MeshSpec kwargs (e.g. {"data": -1}) to shard the corpus '
        'over chips; None = single device.',
    )

    def get_index(self) -> 'TpuIndexV2':
        mesh = None
        if self.mesh is not None:
            from distllm_tpu.parallel.mesh import MeshSpec, make_mesh

            mesh = make_mesh(MeshSpec(**self.mesh))
        return TpuIndexV2(self, mesh=mesh)


class TpuIndexV2:
    def __init__(self, config: TpuIndexV2Config, mesh=None) -> None:
        self.config = config
        self.mesh = mesh
        self.dataset = _load_embeddings_dataset(config.dataset_dir)
        index_dir = config.index_dir or (Path(config.dataset_dir) / 'tpu_index')
        self._index_file = Path(index_dir) / f'index_{config.precision}.npz'
        self._build_or_load()

    # ------------------------------------------------------------ building
    def _build_or_load(self) -> None:
        if self._index_file.exists():
            data = np.load(self._index_file)
            embeddings = data['embeddings']
        else:
            embeddings = np.asarray(
                self.dataset['embeddings'], dtype=np.float32
            )
            if self.config.normalize:
                norms = np.linalg.norm(embeddings, axis=1, keepdims=True)
                embeddings = embeddings / np.clip(norms, 1e-12, None)
            if self.config.precision == 'ubinary':
                embeddings_store = pack_sign_bits(embeddings)
            else:
                embeddings_store = embeddings
            self._index_file.parent.mkdir(parents=True, exist_ok=True)
            np.savez_compressed(self._index_file, embeddings=embeddings_store)
            embeddings = embeddings_store
        self._num_real = embeddings.shape[0]
        if self.config.precision == 'ubinary':
            self._packed = jnp.asarray(embeddings)
            # fp32 copy for rescoring candidates (host-side gather).
            self._rescore_host = np.asarray(
                self.dataset['embeddings'], dtype=np.float32
            )
            if self.config.normalize:
                norms = np.linalg.norm(self._rescore_host, axis=1, keepdims=True)
                self._rescore_host /= np.clip(norms, 1e-12, None)
            self._corpus = None
        else:
            if self.mesh is not None and self.mesh.shape.get('data', 1) > 1:
                import jax
                from jax.sharding import NamedSharding, PartitionSpec as P

                shards = self.mesh.shape['data']
                pad = (-embeddings.shape[0]) % shards
                if pad:
                    # Zero rows pad to a shardable row count; their indices
                    # (>= _num_real) are dropped in the search filter.
                    embeddings = np.concatenate(
                        [embeddings, np.zeros((pad, embeddings.shape[1]), embeddings.dtype)]
                    )
                self._corpus = jax.device_put(
                    embeddings, NamedSharding(self.mesh, P('data', None))
                )
            else:
                self._corpus = jnp.asarray(embeddings)
            self._packed = None

    def __len__(self) -> int:
        return len(self.dataset)

    # ------------------------------------------------------------- search
    def search(
        self,
        query_embeddings: np.ndarray,  # [B, H] fp32 (normalized by Retriever)
        top_k: int = 1,
        score_threshold: float = 0.0,
    ) -> BatchedSearchResults:
        if self.config.precision == 'ubinary':
            scores, indices = self._search_ubinary(query_embeddings, top_k)
        else:
            scores, indices = topk_inner_product(
                jnp.asarray(query_embeddings), self._corpus, top_k, self.mesh
            )
            scores, indices = np.asarray(scores), np.asarray(indices)
        # Score-threshold filter (reference ``search.py:338-382``); padding
        # rows from the sharded layout (index >= corpus size) are dropped.
        total_indices, total_scores = [], []
        for row_scores, row_idx in zip(scores, indices):
            keep = (row_scores >= score_threshold) & (row_idx < self._num_real)
            total_indices.append([int(i) for i in row_idx[keep]])
            total_scores.append([float(s) for s in row_scores[keep]])
        return BatchedSearchResults(total_indices, total_scores)

    def _search_ubinary(self, queries: np.ndarray, top_k: int):
        query_bits = jnp.asarray(pack_sign_bits(queries))
        oversample = min(
            top_k * self.config.rescore_multiplier, len(self.dataset)
        )
        _, cand = hamming_topk(query_bits, self._packed, oversample)
        cand = np.asarray(cand)
        # fp32 rescore of the binary candidates against the full-precision
        # query (sentence-transformers rescore semantics).
        cand_vectors = self._rescore_host[cand]  # [B, oversample, H]
        rescored = np.einsum('bh,boh->bo', queries.astype(np.float32), cand_vectors)
        order = np.argsort(-rescored, axis=1)[:, :top_k]
        indices = np.take_along_axis(cand, order, axis=1)
        scores = np.take_along_axis(rescored, order, axis=1)
        return scores, indices

    # ------------------------------------------------------------ row access
    def get(self, indices: list[int], key: str) -> list[Any]:
        """Row field access (reference ``search.py:384-399``)."""
        rows = self.dataset[indices]
        return list(rows[key])


class TpuIndexV1Config(BaseConfig):
    """Deprecated V1 surface (reference ``search.py:402-666``)."""

    name: Literal['tpu_index_v1', 'faiss_index_v1'] = 'tpu_index_v1'
    dataset_dir: Path
    metric: Literal['inner_product', 'l2'] = 'inner_product'

    def get_index(self) -> 'TpuIndexV2':
        warnings.warn(
            'TpuIndexV1 is deprecated; use TpuIndexV2.',
            DeprecationWarning,
            stacklevel=2,
        )
        v2 = TpuIndexV2Config(dataset_dir=self.dataset_dir)
        return TpuIndexV2(v2)


class RetrieverConfig(BaseConfig):
    """Parity with ``RetrieverConfig.get_retriever`` (``search.py:669-712``)."""

    faiss_config: dict[str, Any]
    encoder_config: dict[str, Any]
    pooler_config: dict[str, Any]
    batch_size: int = 8

    def get_retriever(self, register: bool = False) -> 'Retriever':
        from distllm_tpu.embed import get_encoder, get_pooler

        index_config = dict(self.faiss_config)
        index_config.pop('name', None)
        index = TpuIndexV2Config(**index_config).get_index()
        encoder = get_encoder(self.encoder_config, register=register)
        pooler = get_pooler(self.pooler_config)
        return Retriever(index, encoder, pooler, self.batch_size)


class Retriever:
    """Query encoding + index search (reference ``search.py:715-928``)."""

    def __init__(
        self,
        index: TpuIndexV2,
        encoder: Encoder,
        pooler: Pooler,
        batch_size: int = 8,
    ) -> None:
        self.index = index
        self.encoder = encoder
        self.pooler = pooler
        self.batch_size = batch_size

    def get_pooled_embeddings(self, queries: list[str]) -> np.ndarray:
        """Sort-by-length → batch → encode → pool → normalize → restore order."""
        from distllm_tpu.embed.embedders.full_sequence import compute_embeddings

        embeddings = compute_embeddings(
            queries, self.encoder, self.pooler, self.batch_size, normalize=False
        )
        norms = np.linalg.norm(embeddings, axis=1, keepdims=True)
        return embeddings / np.clip(norms, 1e-12, None)

    def search(
        self,
        query: str | list[str],
        top_k: int = 1,
        score_threshold: float = 0.0,
    ) -> tuple[BatchedSearchResults, np.ndarray]:
        """Returns (results, query_embeddings) — reference ``search.py:743-798``."""
        queries = [query] if isinstance(query, str) else list(query)
        embeddings = self.get_pooled_embeddings(queries)
        return self.index.search(embeddings, top_k, score_threshold), embeddings

    def get(self, indices: list[int], key: str) -> list[Any]:
        return self.index.get(indices, key)

    def get_texts(self, indices: list[int]) -> list[str]:
        """Parity with ``Retriever.get_texts`` (``search.py:915-928``)."""
        return self.index.get(indices, 'text')
