"""Semantic similarity search: sharded TPU index + Retriever.

TPU-native replacement for the reference's FAISS stack
(``distllm/rag/search.py``; SURVEY.md section 2.4 N2):

- :class:`TpuIndexV2` mirrors ``FaissIndexV2``'s surface — build-if-missing
  from an embeddings dataset, persist to disk, precision ``float32`` (exact
  inner product, MXU matmul + ``lax.top_k``, multi-chip via shard_map) or
  ``ubinary`` (sign-bit packed, Hamming search + fp32 **rescore** with
  ``rescore_multiplier`` oversampling, same semantics as
  sentence-transformers' ``semantic_search_faiss`` path, ``search.py:314-322``),
  score-threshold filtering, and row access ``get(indices, key)``.
  ``index_type`` accepts the reference's HNSW names but serves them with the
  exact search (on TPU the brute-force matmul IS the fast path; approximate
  graphs are a CPU workaround).
- :class:`TpuIndexV1` — deprecated V1 surface kept for config compatibility
  (``search.py:402-666``), same engine underneath.
- :class:`Retriever` — query path with sort-by-length batching, encoder +
  pooler, L2 normalization, order restoration (``search.py:743-928``).
"""

from __future__ import annotations

import warnings
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Literal

import jax.numpy as jnp
import numpy as np
from pydantic import Field

from distllm_tpu.embed.encoders.base import Encoder
from distllm_tpu.embed.poolers.base import Pooler
from distllm_tpu.ops.topk import (
    SCAN_CHUNK_BITS,
    SCAN_CHUNK_INT8,
    group_rows,
    hamming_topk,
    int8_topk,
    pack_sign_bits,
    quantize_int8_rows,
    topk_inner_product,
)
from distllm_tpu.utils import BaseConfig


@dataclass
class BatchedSearchResults:
    """Parity with the reference's result container (``search.py:26-31``)."""

    total_indices: list[list[int]]
    total_scores: list[list[float]]


def _load_embeddings_dataset(dataset_dir: str | Path):
    """Load an embeddings dataset; a directory of UUID shard subdirs (the
    distributed-embedding output layout) is concatenated automatically, so
    indexes build straight from unmerged multi-shard runs."""
    from datasets import concatenate_datasets, load_from_disk

    dataset_dir = Path(dataset_dir)
    if not (dataset_dir / 'dataset_info.json').exists():
        shards = sorted(
            p
            for p in dataset_dir.iterdir()
            if p.is_dir() and (p / 'dataset_info.json').exists()
        )
        if shards:
            return concatenate_datasets(
                [load_from_disk(str(p)) for p in shards]
            )
    return load_from_disk(str(dataset_dir))


class TpuIndexV2Config(BaseConfig):
    name: Literal['tpu_index_v2', 'faiss_index_v2'] = 'tpu_index_v2'
    dataset_dir: Path
    index_dir: Path | None = Field(
        default=None,
        description='Where the packed index file lives; defaults to '
        'dataset_dir/tpu_index.',
    )
    index_type: str = Field(
        default='flat',
        description="'flat' (exact) — 'hnsw*' names accepted and served "
        'exactly (TPU brute force beats CPU graphs).',
    )
    precision: Literal['float32', 'int8', 'ubinary'] = 'float32'
    rescore_multiplier: int = Field(
        default=4,
        description='int8/ubinary: oversample factor before fp32 rescoring.',
    )
    metric: Literal['inner_product'] = 'inner_product'
    normalize: bool = Field(
        default=True, description='L2-normalize embeddings (cosine/IP).'
    )
    mesh: dict | None = Field(
        default=None,
        description='MeshSpec kwargs (e.g. {"data": -1}) to shard the corpus '
        'over chips; None = single device.',
    )

    def get_index(self) -> 'TpuIndexV2':
        mesh = None
        if self.mesh is not None:
            from distllm_tpu.parallel.mesh import MeshSpec, make_mesh

            mesh = make_mesh(MeshSpec(**self.mesh))
        return TpuIndexV2(self, mesh=mesh)


class TpuIndexV2:
    def __init__(self, config: TpuIndexV2Config, mesh=None) -> None:
        self.config = config
        self.mesh = mesh
        self.dataset = _load_embeddings_dataset(config.dataset_dir)
        index_dir = config.index_dir or (Path(config.dataset_dir) / 'tpu_index')
        self._index_file = Path(index_dir) / f'index_{config.precision}.npz'
        self._build_or_load()

    # ------------------------------------------------------------ building
    # Rows per build/load chunk: bounds peak host RSS at O(chunk), not
    # O(corpus) (the reference streams its quantization through a
    # ProcessPoolExecutor for the same reason, search.py:210-221).
    _CHUNK_ROWS = 65536

    def _chunk(self, lo: int) -> np.ndarray:
        hi = min(lo + self._CHUNK_ROWS, len(self.dataset))
        rows = np.asarray(
            self.dataset[lo:hi]['embeddings'], dtype=np.float32
        )
        if self.config.normalize:
            norms = np.linalg.norm(rows, axis=1, keepdims=True)
            rows = rows / np.clip(norms, 1e-12, None)
        return rows

    def _build_shards(self) -> None:
        """Stream the corpus into per-chunk index shard files.

        Chunks are read, normalized, and (for ubinary) sign-bit packed in a
        thread pool — numpy releases the GIL, giving the reference's
        parallel-quantization behavior without pickling the corpus.
        """
        import json
        from concurrent.futures import ThreadPoolExecutor

        shard_dir = self._index_file.parent
        shard_dir.mkdir(parents=True, exist_ok=True)
        offsets = list(range(0, len(self.dataset), self._CHUNK_ROWS))

        def build_one(part: int) -> str:
            rows = self._chunk(offsets[part])
            if self.config.precision == 'ubinary':
                rows = pack_sign_bits(rows)
            elif self.config.precision == 'int8':
                codes, scales = quantize_int8_rows(rows)
                name = f'{self._index_file.stem}.part{part:05d}.npz'
                np.savez(shard_dir / name, codes=codes, scales=scales)
                return name
            name = f'{self._index_file.stem}.part{part:05d}.npy'
            np.save(shard_dir / name, rows)
            return name

        with ThreadPoolExecutor(max_workers=8) as pool:
            parts = list(pool.map(build_one, range(len(offsets))))
        meta = {'num_rows': len(self.dataset), 'parts': parts}
        self._meta_file.write_text(json.dumps(meta))

    def _iter_stored_chunks(self):
        """Yield index chunks (mmap'd shard parts, or the legacy npz)."""
        import json

        if self._meta_file.exists():
            meta = json.loads(self._meta_file.read_text())
            for name in meta['parts']:
                yield np.load(self._index_file.parent / name, mmap_mode='r')
        else:  # legacy single-file layout
            yield np.load(self._index_file)['embeddings']

    def _build_or_load(self) -> None:
        import json

        self._meta_file = self._index_file.with_suffix('.meta.json')
        if self._meta_file.exists():
            # A stale index (dataset re-embedded since the build) would
            # silently mis-align rows; rebuild when the row count moved.
            meta = json.loads(self._meta_file.read_text())
            if meta.get('num_rows') != len(self.dataset):
                self._build_shards()
        elif not self._index_file.exists():
            self._build_shards()
        self._num_real = len(self.dataset)

        if self.config.precision == 'ubinary':
            # Packed bits are corpus/32 bytes — assemble on host, GROUP
            # into [G, chunk, H/8] (ops/topk.group_rows), then one
            # device_put: the grouped layout rides hamming_topk's single-
            # dispatch lax.scan (~32 ms at 10M rows vs seconds for a
            # sliced-chunk loop — chipback_r05). NO second fp32 host
            # copy: rescore candidates are gathered per query batch from
            # the arrow-mmap'd dataset.
            self._packed = jnp.asarray(group_rows(
                np.concatenate(
                    [np.asarray(c) for c in self._iter_stored_chunks()]
                ),
                SCAN_CHUNK_BITS,
            ))
            self._corpus = None
            self._int8 = None
            return

        if self.config.precision == 'int8':
            # corpus/4 bytes on device (codes) + tiny scales: the middle
            # tier — MXU int8 scoring with fp32 rescore (same rescore path
            # as ubinary). Beyond-reference extension: the reference
            # validates only float32/ubinary (search.py:172-176). Single-
            # device codes are grouped for the scan path like ubinary.
            parts = list(self._iter_stored_chunks())
            codes = np.concatenate([np.asarray(p['codes']) for p in parts])
            scales = np.concatenate([np.asarray(p['scales']) for p in parts])
            if self.mesh is not None and self.mesh.shape.get('data', 1) > 1:
                self._int8 = self._put_row_sharded((codes, 0), (scales, 1))
            else:
                self._int8 = (
                    jnp.asarray(group_rows(codes, SCAN_CHUNK_INT8)),
                    jnp.asarray(group_rows(scales, SCAN_CHUNK_INT8)),
                )
            self._packed = None
            self._corpus = None
            return

        self._packed = None
        self._int8 = None
        if self.mesh is not None and self.mesh.shape.get('data', 1) > 1:
            # Multi-chip: assemble on host (pod hosts have the RAM), pad to
            # a shardable row count — padded indices (>= _num_real) are
            # dropped in the search filter.
            embeddings = np.concatenate(
                [np.asarray(c) for c in self._iter_stored_chunks()]
            )
            (self._corpus,) = self._put_row_sharded((embeddings, 0))
            return

        # Single device: assemble directly in HBM chunk by chunk via a
        # donated dynamic-update-slice, so host RSS stays O(chunk).
        import jax

        update = jax.jit(
            lambda buf, part, lo: jax.lax.dynamic_update_slice(
                buf, part, (lo, 0)
            ),
            donate_argnums=0,
        )
        buf = None
        lo = 0
        for chunk in self._iter_stored_chunks():
            part = np.asarray(chunk, dtype=np.float32)
            if buf is None:
                dim = part.shape[1]
                buf = jnp.zeros((self._num_real, dim), jnp.float32)
            buf = update(buf, part, lo)
            lo += part.shape[0]
        self._corpus = buf

    def _put_row_sharded(self, *arrays_with_fill) -> tuple:
        """Pad each host array to a row count divisible by the mesh's
        ``data`` axis (with the given fill value) and device_put it
        row-sharded. One home for the pad+shard math of every precision
        tier; padded indices (>= ``_num_real``) are dropped downstream."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        shards = self.mesh.shape['data']
        out = []
        for arr, fill in arrays_with_fill:
            pad = (-arr.shape[0]) % shards
            if pad:
                block = np.full((pad, *arr.shape[1:]), fill, arr.dtype)
                arr = np.concatenate([arr, block])
            spec = P('data', *([None] * (arr.ndim - 1)))
            out.append(jax.device_put(arr, NamedSharding(self.mesh, spec)))
        return tuple(out)

    def __len__(self) -> int:
        return len(self.dataset)

    # ------------------------------------------------------------- search
    def search(
        self,
        query_embeddings: np.ndarray,  # [B, H] fp32 (normalized by Retriever)
        top_k: int = 1,
        score_threshold: float = 0.0,
    ) -> BatchedSearchResults:
        if self.config.precision == 'ubinary':
            scores, indices = self._search_ubinary(query_embeddings, top_k)
        elif self.config.precision == 'int8':
            scores, indices = self._search_int8(query_embeddings, top_k)
        else:
            scores, indices = topk_inner_product(
                jnp.asarray(query_embeddings), self._corpus, top_k, self.mesh
            )
            scores, indices = np.asarray(scores), np.asarray(indices)
        # Score-threshold filter (reference ``search.py:338-382``); padding
        # rows from the sharded layout (index >= corpus size) are dropped.
        total_indices, total_scores = [], []
        for row_scores, row_idx in zip(scores, indices):
            keep = (row_scores >= score_threshold) & (row_idx < self._num_real)
            total_indices.append([int(i) for i in row_idx[keep]])
            total_scores.append([float(s) for s in row_scores[keep]])
        return BatchedSearchResults(total_indices, total_scores)

    def _search_ubinary(self, queries: np.ndarray, top_k: int):
        query_bits = jnp.asarray(pack_sign_bits(queries))
        oversample = min(
            top_k * self.config.rescore_multiplier, len(self.dataset)
        )
        _, cand = hamming_topk(
            query_bits, self._packed, oversample, n_valid=self._num_real
        )
        return self._rescore(queries, np.asarray(cand), top_k)

    def _search_int8(self, queries: np.ndarray, top_k: int):
        oversample = min(
            top_k * self.config.rescore_multiplier, len(self.dataset)
        )
        codes, scales = self._int8
        _, cand = int8_topk(
            jnp.asarray(queries.astype(np.float32)), codes, scales,
            oversample, self.mesh, n_valid=self._num_real,
        )
        return self._rescore(queries, np.asarray(cand), top_k)

    def _rescore(self, queries: np.ndarray, cand: np.ndarray, top_k: int):
        """fp32 rescore of quantized-tier candidates against the
        full-precision query (sentence-transformers rescore semantics).
        Candidate vectors come from the arrow-mmap'd dataset per batch —
        the index keeps NO fp32 corpus copy (that second copy doubled host
        RSS in earlier revisions).

        ``cand`` may contain padded-row indices (>= ``_num_real``) from a
        sharded layout; their ORIGINAL indices are preserved (so the
        ``search()`` filter drops them) while the dataset gather uses a
        clamped copy and their rescores are pinned to -inf so they can
        never displace a real neighbor in the top-k.
        """
        valid = cand < self._num_real
        flat = np.minimum(cand, self._num_real - 1).reshape(-1)
        order_back = np.argsort(np.argsort(flat))
        gathered = np.asarray(
            self.dataset[np.sort(flat).tolist()]['embeddings'],
            dtype=np.float32,
        )[order_back]
        cand_vectors = gathered.reshape(*cand.shape, -1)
        if self.config.normalize:
            norms = np.linalg.norm(cand_vectors, axis=-1, keepdims=True)
            cand_vectors = cand_vectors / np.clip(norms, 1e-12, None)
        rescored = np.einsum('bh,boh->bo', queries.astype(np.float32), cand_vectors)
        rescored = np.where(valid, rescored, -np.inf)
        order = np.argsort(-rescored, axis=1)[:, :top_k]
        indices = np.take_along_axis(cand, order, axis=1)
        scores = np.take_along_axis(rescored, order, axis=1)
        return scores, indices

    # ------------------------------------------------------------ row access
    def get(self, indices: list[int], key: str) -> list[Any]:
        """Row field access (reference ``search.py:384-399``)."""
        rows = self.dataset[indices]
        return list(rows[key])


class TpuIndexV1Config(BaseConfig):
    """Deprecated V1 surface (reference ``search.py:402-666``)."""

    name: Literal['tpu_index_v1', 'faiss_index_v1'] = 'tpu_index_v1'
    dataset_dir: Path
    metric: Literal['inner_product', 'l2'] = 'inner_product'

    def get_index(self) -> 'TpuIndexV2':
        warnings.warn(
            'TpuIndexV1 is deprecated; use TpuIndexV2.',
            DeprecationWarning,
            stacklevel=2,
        )
        v2 = TpuIndexV2Config(dataset_dir=self.dataset_dir)
        return TpuIndexV2(v2)


class RetrieverConfig(BaseConfig):
    """Parity with ``RetrieverConfig.get_retriever`` (``search.py:669-712``)."""

    faiss_config: dict[str, Any]
    encoder_config: dict[str, Any]
    pooler_config: dict[str, Any]
    batch_size: int = 8

    def get_retriever(self, register: bool = False) -> 'Retriever':
        from distllm_tpu.embed import get_encoder, get_pooler

        index_config = dict(self.faiss_config)
        index_config.pop('name', None)
        index = TpuIndexV2Config(**index_config).get_index()
        encoder = get_encoder(self.encoder_config, register=register)
        pooler = get_pooler(self.pooler_config)
        return Retriever(index, encoder, pooler, self.batch_size)


class Retriever:
    """Query encoding + index search (reference ``search.py:715-928``)."""

    def __init__(
        self,
        index: TpuIndexV2,
        encoder: Encoder,
        pooler: Pooler,
        batch_size: int = 8,
    ) -> None:
        self.index = index
        self.encoder = encoder
        self.pooler = pooler
        self.batch_size = batch_size

    def get_pooled_embeddings(self, queries: list[str]) -> np.ndarray:
        """Sort-by-length → batch → encode → pool → normalize → restore order."""
        from distllm_tpu.embed.embedders.full_sequence import compute_embeddings

        embeddings = compute_embeddings(
            queries, self.encoder, self.pooler, self.batch_size, normalize=False
        )
        norms = np.linalg.norm(embeddings, axis=1, keepdims=True)
        return embeddings / np.clip(norms, 1e-12, None)

    def search(
        self,
        query: str | list[str],
        top_k: int = 1,
        score_threshold: float = 0.0,
    ) -> tuple[BatchedSearchResults, np.ndarray]:
        """Returns (results, query_embeddings) — reference ``search.py:743-798``."""
        queries = [query] if isinstance(query, str) else list(query)
        embeddings = self.get_pooled_embeddings(queries)
        return self.index.search(embeddings, top_k, score_threshold), embeddings

    def get(self, indices: list[int], key: str) -> list[Any]:
        return self.index.get(indices, key)

    def get_texts(self, indices: list[int]) -> list[str]:
        """Parity with ``Retriever.get_texts`` (``search.py:915-928``)."""
        return self.index.get(indices, 'text')
