"""Numpy writer: ``embeddings.npy`` / ``text.npy`` / ``metadata.npy``.

Reference parity: ``distllm/embed/writers/numpy.py:20-69`` (metadata stored
via pickle-enabled object arrays; merge concatenates all shards).
"""

from __future__ import annotations

from pathlib import Path
from typing import Literal

import numpy as np

from distllm_tpu.embed.embedders.base import EmbedderResult
from distllm_tpu.utils import BaseConfig


class NumpyWriterConfig(BaseConfig):
    name: Literal['numpy'] = 'numpy'


class NumpyWriter:
    def __init__(self, config: NumpyWriterConfig) -> None:
        self.config = config

    def write(self, output_dir: str | Path, result: EmbedderResult) -> None:
        output_dir = Path(output_dir)
        output_dir.mkdir(parents=True, exist_ok=True)
        np.save(output_dir / 'embeddings.npy', result.embeddings)
        np.save(output_dir / 'text.npy', np.array(result.text, dtype=object))
        if result.metadata is not None:
            np.save(
                output_dir / 'metadata.npy',
                np.array(result.metadata, dtype=object),
            )

    def merge(
        self, dataset_dirs: list[str | Path], output_dir: str | Path
    ) -> None:
        embeddings, texts, metadata = [], [], []
        have_metadata = False
        for path in dataset_dirs:
            path = Path(path)
            embeddings.append(np.load(path / 'embeddings.npy'))
            texts.append(np.load(path / 'text.npy', allow_pickle=True))
            meta_path = path / 'metadata.npy'
            if meta_path.exists():
                have_metadata = True
                metadata.append(np.load(meta_path, allow_pickle=True))
        result = EmbedderResult(
            embeddings=np.concatenate(embeddings, axis=0),
            text=list(np.concatenate(texts, axis=0)),
            metadata=(
                list(np.concatenate(metadata, axis=0)) if have_metadata else None
            ),
        )
        self.write(output_dir, result)
