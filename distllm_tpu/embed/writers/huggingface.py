"""HuggingFace datasets writer.

Reference parity: ``distllm/embed/writers/huggingface.py`` — builds the
dataset from an in-memory list (the reference deliberately avoids
``from_generator`` for NFS safety, ``:61-70``); ``merge`` loads every shard,
concatenates, and saves with ``num_proc`` workers. Shards that are missing or
corrupt are skipped with a warning (matching the generate-writer behavior the
drivers rely on for partial re-runs).
"""

from __future__ import annotations

from pathlib import Path
from typing import Literal

from pydantic import Field

from distllm_tpu.embed.embedders.base import EmbedderResult
from distllm_tpu.observability.instruments import log_event
from distllm_tpu.utils import BaseConfig


class HuggingFaceWriterConfig(BaseConfig):
    name: Literal['huggingface'] = 'huggingface'
    num_proc: int | None = Field(
        default=None, description='Workers for merge save_to_disk.'
    )


class HuggingFaceWriter:
    def __init__(self, config: HuggingFaceWriterConfig) -> None:
        self.config = config

    def write(self, output_dir: str | Path, result: EmbedderResult) -> None:
        from datasets import Dataset

        rows: dict[str, list] = {
            'text': list(result.text),
            'embeddings': [e for e in result.embeddings],
        }
        if result.metadata:
            keys = result.metadata[0].keys()
            for key in keys:
                rows[key] = [m.get(key) for m in result.metadata]
        dataset = Dataset.from_dict(rows)
        dataset.save_to_disk(str(output_dir))

    def merge(
        self, dataset_dirs: list[str | Path], output_dir: str | Path
    ) -> None:
        from datasets import concatenate_datasets, load_from_disk

        shards = []
        for path in dataset_dirs:
            try:
                shards.append(load_from_disk(str(path)))
            except Exception as exc:  # noqa: BLE001 - skip bad shards
                log_event(
                    f'[writer] skipping shard {path}: {exc}',
                    component='writer',
                )
        if not shards:
            raise ValueError(f'no readable shards among {len(dataset_dirs)} dirs')
        merged = concatenate_datasets(shards)
        merged.save_to_disk(str(output_dir), num_proc=self.config.num_proc)
