"""Writer protocol: persist EmbedderResults and merge shard outputs.

Reference parity: ``distllm/embed/writers/base.py:12-41``.
"""

from __future__ import annotations

from pathlib import Path
from typing import Protocol, runtime_checkable

from distllm_tpu.embed.embedders.base import EmbedderResult


@runtime_checkable
class Writer(Protocol):
    config: object

    def write(self, output_dir: str | Path, result: EmbedderResult) -> None: ...

    def merge(
        self, dataset_dirs: list[str | Path], output_dir: str | Path
    ) -> None: ...
