"""Writer strategy factory (reference: ``distllm/embed/writers/__init__.py``)."""

from __future__ import annotations

from typing import Any, Union

from distllm_tpu.embed.writers.base import Writer
from distllm_tpu.embed.writers.huggingface import (
    HuggingFaceWriter,
    HuggingFaceWriterConfig,
)
from distllm_tpu.embed.writers.numpy import NumpyWriter, NumpyWriterConfig

WriterConfigs = Union[HuggingFaceWriterConfig, NumpyWriterConfig]

STRATEGIES: dict[str, tuple[type, type]] = {
    'huggingface': (HuggingFaceWriterConfig, HuggingFaceWriter),
    'numpy': (NumpyWriterConfig, NumpyWriter),
}


def get_writer(kwargs: dict[str, Any]) -> Writer:
    name = kwargs.get('name', '')
    entry = STRATEGIES.get(name)
    if entry is None:
        raise ValueError(
            f'Unknown writer name: {name!r}. Available: {sorted(STRATEGIES)}'
        )
    config_cls, cls = entry
    return cls(config_cls(**kwargs))


__all__ = ['Writer', 'WriterConfigs', 'get_writer', 'STRATEGIES']
