"""Sequence-per-line dataset: plain text, one item per line, skip N headers.

Reference parity: ``distllm/embed/datasets/single_line.py:32-68``.
"""

from __future__ import annotations

from pathlib import Path
from typing import Literal

from distllm_tpu.embed.datasets.base import TextCorpus
from distllm_tpu.utils import BaseConfig


class SequencePerLineDatasetConfig(BaseConfig):
    name: Literal['sequence_per_line'] = 'sequence_per_line'
    header_lines: int = 0
    batch_size: int = 8


class SequencePerLineDataset:
    def __init__(self, config: SequencePerLineDatasetConfig) -> None:
        self.config = config

    def read(self, data_file: str | Path) -> TextCorpus:
        lines = Path(data_file).read_text().splitlines()
        texts = [
            line.strip()
            for line in lines[self.config.header_lines :]
            if line.strip()
        ]
        return TextCorpus(texts=texts, metadata=None)
