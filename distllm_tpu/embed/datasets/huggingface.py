"""HuggingFace on-disk dataset with selectable metadata columns.

Reference parity: ``distllm/embed/datasets/huggingface.py:35-83``.
"""

from __future__ import annotations

from pathlib import Path
from typing import Literal

from distllm_tpu.embed.datasets.base import TextCorpus
from distllm_tpu.utils import BaseConfig


class HuggingFaceDatasetConfig(BaseConfig):
    name: Literal['huggingface'] = 'huggingface'
    text_field: str = 'text'
    metadata_fields: list[str] = []
    batch_size: int = 8


class HuggingFaceDataset:
    def __init__(self, config: HuggingFaceDatasetConfig) -> None:
        self.config = config

    def read(self, data_file: str | Path) -> TextCorpus:
        from datasets import load_from_disk

        ds = load_from_disk(str(data_file))
        texts = list(ds[self.config.text_field])
        metadata = None
        if self.config.metadata_fields:
            columns = {f: ds[f] for f in self.config.metadata_fields}
            metadata = [
                {f: columns[f][i] for f in self.config.metadata_fields}
                for i in range(len(texts))
            ]
        return TextCorpus(texts=texts, metadata=metadata)
