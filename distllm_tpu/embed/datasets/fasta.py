"""FASTA dataset for protein sequences.

Reference parity: ``distllm/embed/datasets/fasta.py:29-115`` — regex parse,
uppercased sequences, metadata ``{tags, paths}``.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from pathlib import Path
from typing import Literal

from distllm_tpu.embed.datasets.base import TextCorpus
from distllm_tpu.utils import BaseConfig


@dataclass
class Sequence:
    sequence: str
    tag: str


def read_fasta(fasta_file: str | Path) -> list[Sequence]:
    """Parse a FASTA file into (uppercased sequence, tag) records."""
    text = Path(fasta_file).read_text()
    entries = []
    for block in re.split(r'^>', text, flags=re.MULTILINE):
        block = block.strip()
        if not block:
            continue
        lines = block.splitlines()
        tag = lines[0].strip()
        seq = ''.join(line.strip() for line in lines[1:]).upper()
        if seq:
            entries.append(Sequence(sequence=seq, tag=tag))
    return entries


def write_fasta(sequences: list[Sequence], fasta_file: str | Path) -> None:
    with open(fasta_file, 'w') as fh:
        for record in sequences:
            fh.write(f'>{record.tag}\n{record.sequence}\n')


class FastaDatasetConfig(BaseConfig):
    name: Literal['fasta'] = 'fasta'
    batch_size: int = 8


class FastaDataset:
    def __init__(self, config: FastaDatasetConfig) -> None:
        self.config = config

    def read(self, data_file: str | Path) -> TextCorpus:
        entries = read_fasta(data_file)
        return TextCorpus(
            texts=[e.sequence for e in entries],
            metadata=[
                {'tags': e.tag, 'paths': str(data_file)} for e in entries
            ],
        )
