"""Dataset strategy factory (reference: ``distllm/embed/datasets/__init__.py``)."""

from __future__ import annotations

from typing import Any, Union

from distllm_tpu.embed.datasets.base import Dataset, TextCorpus
from distllm_tpu.embed.datasets.fasta import FastaDataset, FastaDatasetConfig
from distllm_tpu.embed.datasets.huggingface import (
    HuggingFaceDataset,
    HuggingFaceDatasetConfig,
)
from distllm_tpu.embed.datasets.jsonl import JsonlDataset, JsonlDatasetConfig
from distllm_tpu.embed.datasets.jsonl_chunk import (
    JsonlChunkDataset,
    JsonlChunkDatasetConfig,
)
from distllm_tpu.embed.datasets.single_line import (
    SequencePerLineDataset,
    SequencePerLineDatasetConfig,
)

DatasetConfigs = Union[
    JsonlDatasetConfig,
    JsonlChunkDatasetConfig,
    FastaDatasetConfig,
    SequencePerLineDatasetConfig,
    HuggingFaceDatasetConfig,
]

STRATEGIES: dict[str, tuple[type, type]] = {
    'jsonl': (JsonlDatasetConfig, JsonlDataset),
    'jsonl_chunk': (JsonlChunkDatasetConfig, JsonlChunkDataset),
    'fasta': (FastaDatasetConfig, FastaDataset),
    'sequence_per_line': (SequencePerLineDatasetConfig, SequencePerLineDataset),
    'huggingface': (HuggingFaceDatasetConfig, HuggingFaceDataset),
}


def get_dataset(kwargs: dict[str, Any]) -> Dataset:
    """Build a dataset strategy from ``{'name': ..., **config}`` kwargs."""
    name = kwargs.get('name', '')
    entry = STRATEGIES.get(name)
    if entry is None:
        raise ValueError(
            f'Unknown dataset name: {name!r}. Available: {sorted(STRATEGIES)}'
        )
    config_cls, cls = entry
    return cls(config_cls(**kwargs))


__all__ = ['Dataset', 'TextCorpus', 'DatasetConfigs', 'get_dataset', 'STRATEGIES']
