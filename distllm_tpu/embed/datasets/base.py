"""Dataset protocol: read one input file into an in-memory text corpus.

Reference parity: ``distllm/embed/datasets/base.py:14-40`` returns a torch
``DataLoader``; here a dataset returns a :class:`TextCorpus` (texts + aligned
metadata) and batching/tokenization happen downstream in the embedder with
bucketed fixed shapes (TPU recompile discipline).
"""

from __future__ import annotations

from dataclasses import dataclass
from pathlib import Path
from typing import Protocol, runtime_checkable


@dataclass
class TextCorpus:
    """Texts plus optional aligned per-text metadata."""

    texts: list[str]
    metadata: list[dict] | None = None

    def __post_init__(self) -> None:
        if self.metadata is not None and len(self.metadata) != len(self.texts):
            raise ValueError(
                f'metadata length {len(self.metadata)} != texts {len(self.texts)}'
            )

    def __len__(self) -> int:
        return len(self.texts)


@runtime_checkable
class Dataset(Protocol):
    """Strategy protocol for reading an input file."""

    config: object

    def read(self, data_file: str | Path) -> TextCorpus: ...
