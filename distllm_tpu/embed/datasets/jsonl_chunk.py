"""Jsonl dataset with sentence splitting and buffered windows.

Reference parity: ``distllm/embed/datasets/jsonl_chunk.py`` — NLTK Punkt
sentence spans (keeping inter-sentence whitespace by extending each span to
the start of the next), +/-``buffer_size`` sentence windows, and a
min-character filter on buffers (defaults match the reference: 750 chars,
buffer 1). Per-buffer metadata carries all non-text jsonl fields plus the
originating ``sentence`` so the semantic-chunk embedder can rebuild chunks.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Callable, Literal

from pydantic import Field

from distllm_tpu.embed.datasets.base import TextCorpus
from distllm_tpu.utils import BaseConfig


def split_by_sentence_tokenizer() -> Callable[[str], list[str]]:
    """NLTK Punkt span-based splitter preserving inter-sentence whitespace."""
    import nltk

    tokenizer = nltk.tokenize.PunktSentenceTokenizer()

    def split(text: str) -> list[str]:
        spans = list(tokenizer.span_tokenize(text))
        sentences = []
        for i, (start, _end) in enumerate(spans):
            end = spans[i + 1][0] if i < len(spans) - 1 else len(text)
            sentences.append(text[start:end])
        return sentences

    return split


def sentences_to_buffers(sentences: list[str], buffer_size: int) -> list[str]:
    """Sliding +/-buffer_size sentence windows joined into buffer strings."""
    buffers = []
    for i in range(len(sentences)):
        lo = max(0, i - buffer_size)
        hi = min(i + 1 + buffer_size, len(sentences))
        buffers.append(''.join(sentences[lo:hi]))
    return buffers


class JsonlChunkDatasetConfig(BaseConfig):
    name: Literal['jsonl_chunk'] = 'jsonl_chunk'
    text_field: str = 'text'
    batch_size: int = 8
    min_buffer_length: int = Field(
        default=750,
        description='Buffers with fewer characters are filtered out '
        '(removes citations etc).',
    )
    buffer_size: int = Field(
        default=1,
        description='Sentences on each side grouped into a buffer window.',
    )


class JsonlChunkDataset:
    def __init__(self, config: JsonlChunkDatasetConfig) -> None:
        self.config = config
        self._split = split_by_sentence_tokenizer()

    def read(self, data_file: str | Path) -> TextCorpus:
        texts: list[str] = []
        metadata: list[dict] = []
        with open(data_file) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                entry = json.loads(line)
                text = entry[self.config.text_field]
                extra = {
                    k: v for k, v in entry.items() if k != self.config.text_field
                }
                sentences = self._split(text)
                buffers = sentences_to_buffers(sentences, self.config.buffer_size)
                for sentence, buffer in zip(sentences, buffers):
                    if len(buffer) < self.config.min_buffer_length:
                        continue
                    texts.append(buffer)
                    metadata.append({**extra, 'sentence': sentence})
        return TextCorpus(texts, metadata)
