"""Jsonl dataset: one JSON object per line, extract a text field.

Reference parity: ``distllm/embed/datasets/jsonl.py:33-73``.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Literal

from distllm_tpu.embed.datasets.base import TextCorpus
from distllm_tpu.utils import BaseConfig


class JsonlDatasetConfig(BaseConfig):
    name: Literal['jsonl'] = 'jsonl'
    text_field: str = 'text'
    batch_size: int = 8


class JsonlDataset:
    def __init__(self, config: JsonlDatasetConfig) -> None:
        self.config = config

    def read(self, data_file: str | Path) -> TextCorpus:
        texts: list[str] = []
        metadata: list[dict] = []
        with open(data_file) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                entry = json.loads(line)
                texts.append(entry[self.config.text_field])
                metadata.append(
                    {k: v for k, v in entry.items() if k != self.config.text_field}
                )
        return TextCorpus(texts, metadata)
