"""Last-token pooler (SFR-Embedding-Mistral style).

Reference parity: ``distllm/embed/poolers/last_token.py:30-39`` — if the
batch is left-padded (every row's final position is valid) take position -1,
otherwise gather each row's last valid token at ``mask.sum(1) - 1``.
"""

from __future__ import annotations

from typing import Literal

import jax
import jax.numpy as jnp

from distllm_tpu.utils import BaseConfig


@jax.jit
def last_token_pool(
    last_hidden_states: jnp.ndarray, attention_mask: jnp.ndarray
) -> jnp.ndarray:
    """``[B, S, H]`` → ``[B, H]`` last valid token per row."""
    batch = last_hidden_states.shape[0]
    left_padded = jnp.sum(attention_mask[:, -1]) == batch
    lengths = jnp.sum(attention_mask, axis=1)
    gather_idx = jnp.clip(lengths - 1, min=0)
    gathered = last_hidden_states[jnp.arange(batch), gather_idx]
    return jnp.where(
        left_padded, last_hidden_states[:, -1], gathered
    ).astype(jnp.float32)


class LastTokenPoolerConfig(BaseConfig):
    name: Literal['last_token'] = 'last_token'


class LastTokenPooler:
    def __init__(self, config: LastTokenPoolerConfig) -> None:
        self.config = config

    def pool(
        self, embeddings: jnp.ndarray, attention_mask: jnp.ndarray
    ) -> jnp.ndarray:
        return last_token_pool(embeddings, jnp.asarray(attention_mask))
