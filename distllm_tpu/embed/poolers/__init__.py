"""Pooler strategy factory (reference: ``distllm/embed/poolers/__init__.py``)."""

from __future__ import annotations

from typing import Any, Union

from distllm_tpu.embed.poolers.base import Pooler
from distllm_tpu.embed.poolers.last_token import (
    LastTokenPooler,
    LastTokenPoolerConfig,
)
from distllm_tpu.embed.poolers.mean import MeanPooler, MeanPoolerConfig

PoolerConfigs = Union[MeanPoolerConfig, LastTokenPoolerConfig]

STRATEGIES: dict[str, tuple[type, type]] = {
    'mean': (MeanPoolerConfig, MeanPooler),
    'last_token': (LastTokenPoolerConfig, LastTokenPooler),
}


def get_pooler(kwargs: dict[str, Any]) -> Pooler:
    name = kwargs.get('name', '')
    entry = STRATEGIES.get(name)
    if entry is None:
        raise ValueError(
            f'Unknown pooler name: {name!r}. Available: {sorted(STRATEGIES)}'
        )
    config_cls, cls = entry
    return cls(config_cls(**kwargs))


__all__ = ['Pooler', 'PoolerConfigs', 'get_pooler', 'STRATEGIES']
