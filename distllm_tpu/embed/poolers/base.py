"""Pooler protocol: ``[B, S, H]`` hidden states + ``[B, S]`` mask → ``[B, H]``.

Reference parity: ``distllm/embed/poolers/base.py:12-42``; here ``pool`` is a
jitted JAX op operating on device arrays.
"""

from __future__ import annotations

from typing import Protocol, runtime_checkable

import jax.numpy as jnp


@runtime_checkable
class Pooler(Protocol):
    config: object

    def pool(
        self, embeddings: jnp.ndarray, attention_mask: jnp.ndarray
    ) -> jnp.ndarray: ...
