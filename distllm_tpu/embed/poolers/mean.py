"""Mean pooler: masked average excluding start and end special tokens.

Reference parity: ``distllm/embed/poolers/mean.py:13-49`` — average over
valid positions with the [CLS]-position and final-token positions masked out
and a clamped denominator. Deliberate fix over the reference: the reference's
``attention_mask[:, seq_lengths - 1] = 0`` zeroes the *union* of every row's
end-column across the whole batch (torch advanced indexing on the column
axis); here the end token is excluded per row, which is the documented intent
("does not include the pad, start, or end tokens"). The reference also
mutates the caller's mask in place; this implementation is pure.
"""

from __future__ import annotations

from typing import Literal

import jax
import jax.numpy as jnp

from distllm_tpu.utils import BaseConfig


@jax.jit
def average_pool(
    embeddings: jnp.ndarray, attention_mask: jnp.ndarray
) -> jnp.ndarray:
    """Masked mean over interior tokens: ``[B, S, H]`` → ``[B, H]``."""
    seq_len = attention_mask.shape[1]
    positions = jnp.arange(seq_len)[None, :]
    lengths = jnp.sum(attention_mask, axis=1, keepdims=True)
    interior = (
        attention_mask.astype(bool)
        & (positions != 0)  # start token
        & (positions != lengths - 1)  # per-row end token
    )
    weights = interior.astype(jnp.float32)[..., None]
    summed = jnp.sum(embeddings.astype(jnp.float32) * weights, axis=1)
    denom = jnp.clip(jnp.sum(weights, axis=1), min=1e-9)
    return summed / denom


class MeanPoolerConfig(BaseConfig):
    name: Literal['mean'] = 'mean'


class MeanPooler:
    """Averages interior hidden states (no pad/start/end tokens)."""

    def __init__(self, config: MeanPoolerConfig) -> None:
        self.config = config

    def pool(
        self, embeddings: jnp.ndarray, attention_mask: jnp.ndarray
    ) -> jnp.ndarray:
        return average_pool(embeddings, jnp.asarray(attention_mask))
