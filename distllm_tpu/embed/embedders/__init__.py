"""Embedder strategy factory (reference: ``distllm/embed/embedders/__init__.py``)."""

from __future__ import annotations

from typing import Any, Union

from distllm_tpu.embed.embedders.base import Embedder, EmbedderResult
from distllm_tpu.embed.embedders.full_sequence import (
    FullSequenceEmbedder,
    FullSequenceEmbedderConfig,
)
from distllm_tpu.embed.embedders.semantic_chunk import (
    SemanticChunkEmbedder,
    SemanticChunkEmbedderConfig,
)

EmbedderConfigs = Union[FullSequenceEmbedderConfig, SemanticChunkEmbedderConfig]

STRATEGIES: dict[str, tuple[type, type]] = {
    'full_sequence': (FullSequenceEmbedderConfig, FullSequenceEmbedder),
    'semantic_chunk': (SemanticChunkEmbedderConfig, SemanticChunkEmbedder),
}


def get_embedder(kwargs: dict[str, Any]) -> Embedder:
    name = kwargs.get('name', '')
    entry = STRATEGIES.get(name)
    if entry is None:
        raise ValueError(
            f'Unknown embedder name: {name!r}. Available: {sorted(STRATEGIES)}'
        )
    config_cls, cls = entry
    return cls(config_cls(**kwargs))


__all__ = [
    'Embedder',
    'EmbedderResult',
    'EmbedderConfigs',
    'get_embedder',
    'STRATEGIES',
]
