"""Semantic-chunk embedder: two-pass chunking at embedding-distance breakpoints.

Reference parity: ``distllm/embed/embedders/semantic_chunk.py`` (itself
adapted from llama-index's semantic splitter): (1) embed sentence buffers;
(2) within each document (grouped by consecutive equal metadata ``path``),
compute cosine distances between consecutive buffers in fp32, split at the
``breakpoint_percentile_threshold`` percentile, join each group's
``sentence`` strings into chunks, drop chunks ``<= min_chunk_length`` chars;
(3) re-embed the chunks with ``chunk_batch_size``. Distance math is
vectorized (the reference loops per pair, ``semantic_chunk.py:44-55``).
"""

from __future__ import annotations

from typing import Literal

import numpy as np
from pydantic import Field

from distllm_tpu.embed.datasets.base import TextCorpus
from distllm_tpu.embed.embedders.base import EmbedderResult
from distllm_tpu.embed.embedders.full_sequence import compute_embeddings
from distllm_tpu.embed.encoders.base import Encoder
from distllm_tpu.embed.poolers.base import Pooler
from distllm_tpu.utils import BaseConfig


def calculate_distances_between_buffer(buffer_embeds: np.ndarray) -> np.ndarray:
    """Cosine distances between consecutive rows, computed in fp32."""
    x = buffer_embeds.astype(np.float32)
    if len(x) < 2:
        return np.zeros(0, dtype=np.float32)
    a, b = x[:-1], x[1:]
    sims = np.sum(a * b, axis=1) / (
        np.linalg.norm(a, axis=1) * np.linalg.norm(b, axis=1)
    )
    return 1.0 - sims


def build_chunks(
    distances: np.ndarray, breakpoint_percentile_threshold: int
) -> list[tuple[int, int]]:
    """Half-open-ish index groups [(start, end)] per reference semantics.

    ``end`` is inclusive of the buffer at that index when slicing
    ``metadata[start:end]`` (the reference returns ``(0, 0)`` for
    single-buffer docs, yielding an empty slice — preserved here).
    """
    if len(distances) == 0:
        return [(0, 0)]
    threshold = np.percentile(distances, breakpoint_percentile_threshold)
    above = [i for i, d in enumerate(distances) if d > threshold]
    groups = []
    start = 0
    for idx in above:
        groups.append((start, idx + 1))
        start = idx + 1
    groups.append((start, len(distances) + 1))
    return groups


def _document_spans(metadata: list[dict]) -> list[tuple[int, int]]:
    """Consecutive runs of equal ``path`` → [(start, end)] spans."""
    spans = []
    start = 0
    current = metadata[0]['path']
    for i, meta in enumerate(metadata):
        if meta['path'] != current:
            spans.append((start, i))
            start = i
            current = meta['path']
    spans.append((start, len(metadata)))
    return spans


def compute_semantic_chunks(
    corpus: TextCorpus,
    encoder: Encoder,
    pooler: Pooler,
    batch_size: int,
    breakpoint_percentile_threshold: int,
    min_chunk_length: int,
) -> TextCorpus:
    """First pass: buffer embeddings → chunk texts + metadata."""
    if corpus.metadata is None:
        raise ValueError('Metadata is required for semantic chunking.')
    if corpus.metadata[0].get('path') is None:
        raise ValueError('Metadata path is required for semantic chunking.')

    buffer_embeds = compute_embeddings(corpus.texts, encoder, pooler, batch_size)

    dataset_indices: list[tuple[int, int]] = []
    for doc_start, doc_end in _document_spans(corpus.metadata):
        distances = calculate_distances_between_buffer(
            buffer_embeds[doc_start:doc_end]
        )
        for start, end in build_chunks(distances, breakpoint_percentile_threshold):
            dataset_indices.append((doc_start + start, doc_start + end))

    chunks: list[str] = []
    metadata: list[dict] = []
    for start, end in dataset_indices:
        group = corpus.metadata[start:end]
        chunk = ''.join(g['sentence'] for g in group)
        if len(chunk) <= min_chunk_length:
            continue
        chunks.append(chunk)
        meta = dict(corpus.metadata[start])
        meta.pop('sentence', None)
        metadata.append(meta)
    return TextCorpus(chunks, metadata)


class SemanticChunkEmbedderConfig(BaseConfig):
    name: Literal['semantic_chunk'] = 'semantic_chunk'
    breakpoint_percentile_threshold: int = Field(
        default=90,
        description='Cosine-dissimilarity percentile that must be exceeded '
        'between consecutive sentence groups to start a new chunk; smaller '
        'values produce more chunks.',
    )
    chunk_batch_size: int = Field(
        default=8, description='Batch size for the second (chunk) pass.'
    )
    min_chunk_length: int = Field(
        default=750,
        description='Chunks with fewer characters are dropped.',
    )
    normalize_embeddings: bool = False


class SemanticChunkEmbedder:
    def __init__(self, config: SemanticChunkEmbedderConfig) -> None:
        self.config = config

    def embed(
        self,
        corpus: TextCorpus,
        encoder: Encoder,
        pooler: Pooler,
        batch_size: int,
    ) -> EmbedderResult:
        chunked = compute_semantic_chunks(
            corpus,
            encoder,
            pooler,
            batch_size,
            self.config.breakpoint_percentile_threshold,
            self.config.min_chunk_length,
        )
        embeddings = compute_embeddings(
            chunked.texts,
            encoder,
            pooler,
            self.config.chunk_batch_size,
            normalize=self.config.normalize_embeddings,
        )
        return EmbedderResult(
            embeddings=embeddings,
            text=chunked.texts,
            metadata=chunked.metadata,
        )
