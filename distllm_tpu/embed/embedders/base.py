"""Embedder protocol and result container.

Reference parity: ``distllm/embed/embedders/base.py:17-58``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Protocol, runtime_checkable

import numpy as np

from distllm_tpu.embed.datasets.base import TextCorpus
from distllm_tpu.embed.encoders.base import Encoder
from distllm_tpu.embed.poolers.base import Pooler


@dataclass
class EmbedderResult:
    """Pooled embeddings ``[N, H]`` with aligned texts and metadata."""

    embeddings: np.ndarray
    text: list[str]
    metadata: list[dict] | None = None


@runtime_checkable
class Embedder(Protocol):
    config: object

    def embed(
        self,
        corpus: TextCorpus,
        encoder: Encoder,
        pooler: Pooler,
        batch_size: int,
    ) -> EmbedderResult: ...
