"""Full-sequence embedder — the hot loop of the embed pipeline.

Reference parity: ``distllm/embed/embedders/full_sequence.py:20-80`` — a
preallocated host ``[N, H]`` buffer filled batch by batch. TPU adaptations:

- texts are sorted by whitespace length and restored afterwards, so each
  bucketed batch wastes minimal padding (the reference's Retriever does this
  for queries, ``rag/search.py:800-836``; we apply it to the hot loop too);
- partial final batches are padded to the fixed batch size with fully-masked
  rows (jit re-specializes on batch shape otherwise);
- encode+pool+normalize stay on device; only pooled ``[B, H]`` rows transfer
  to host per batch (vs per-batch ``[B, S, H]`` ``.cpu()`` in torch).
"""

from __future__ import annotations

from typing import Literal

import jax.numpy as jnp
import numpy as np
from pydantic import Field

from distllm_tpu.embed.datasets.base import TextCorpus
from distllm_tpu.embed.embedders.base import EmbedderResult
from distllm_tpu.embed.encoders.base import Encoder
from distllm_tpu.embed.poolers.base import Pooler
from distllm_tpu.utils import BaseConfig


def compute_embeddings(
    texts: list[str],
    encoder: Encoder,
    pooler: Pooler,
    batch_size: int,
    normalize: bool = False,
    flush_every: int = 64,
) -> np.ndarray:
    """Embed ``texts`` → host ``[N, H]`` float32 array in original order.

    Dispatch is asynchronous: each batch's forward+pool is enqueued and the
    pooled ``[B, H]`` device arrays are collected without blocking, so host
    tokenization of batch *i+1* overlaps device compute of batch *i*. Results
    flush to the host buffer every ``flush_every`` batches (bounds retained
    pooled outputs at ``flush_every * B * H`` floats — ~100 MB at B=512,
    H=768; lower ``flush_every`` for large-H models on small-HBM chips).
    """
    n = len(texts)
    out = np.empty((n, encoder.embedding_size), dtype=np.float32)
    if n == 0:
        return out
    order = sorted(range(n), key=lambda i: len(texts[i].split()))
    pending: list[tuple[list[int], jnp.ndarray]] = []
    # Fused encode+pool (one dispatch/batch) when the encoder supports it;
    # composed per-stage dispatches otherwise (e.g. FakeEncoder).
    fused = (
        encoder.pooled_forward(pooler, normalize)
        if hasattr(encoder, 'pooled_forward')
        else None
    )

    def flush() -> None:
        for idx, dev in pending:
            out[idx] = np.asarray(dev, dtype=np.float32)[: len(idx)]
        pending.clear()

    for lo in range(0, n, batch_size):
        idx = order[lo : lo + batch_size]
        batch = encoder.tokenizer([texts[i] for i in idx])
        batch = batch.pad_batch_to(batch_size, pad_id=encoder.tokenizer.pad_id)
        if fused is not None:
            pooled = fused(batch)
        else:
            pooled = pooler.pool(encoder.forward(batch), batch.attention_mask)
            if normalize:
                # Same guarded normalize as the fused path (zero vectors from
                # fully-masked pad rows must not produce NaN).
                pooled = pooled / jnp.clip(
                    jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-12
                )
            pooled = pooled.astype(jnp.float32)
        # Start the device→host copy now so it overlaps later batches'
        # compute; flush()'s np.asarray then finds the bytes already local.
        copy_async = getattr(pooled, 'copy_to_host_async', None)
        if copy_async is not None:
            copy_async()
        pending.append((idx, pooled))
        if len(pending) >= flush_every:
            flush()
    flush()
    return out


class FullSequenceEmbedderConfig(BaseConfig):
    name: Literal['full_sequence'] = 'full_sequence'
    normalize_embeddings: bool = Field(
        default=False, description='L2-normalize pooled embeddings.'
    )


class FullSequenceEmbedder:
    def __init__(self, config: FullSequenceEmbedderConfig) -> None:
        self.config = config

    def embed(
        self,
        corpus: TextCorpus,
        encoder: Encoder,
        pooler: Pooler,
        batch_size: int,
    ) -> EmbedderResult:
        embeddings = compute_embeddings(
            corpus.texts,
            encoder,
            pooler,
            batch_size,
            normalize=self.config.normalize_embeddings,
        )
        return EmbedderResult(
            embeddings=embeddings, text=corpus.texts, metadata=corpus.metadata
        )
