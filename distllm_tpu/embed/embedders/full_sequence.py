"""Full-sequence embedder — the hot loop of the embed pipeline.

Reference parity: ``distllm/embed/embedders/full_sequence.py:20-80`` — a
preallocated host ``[N, H]`` buffer filled batch by batch. TPU adaptations:

- texts are sorted by whitespace length and restored afterwards, so each
  bucketed batch wastes minimal padding (the reference's Retriever does this
  for queries, ``rag/search.py:800-836``; we apply it to the hot loop too);
- partial final batches are padded to the fixed batch size with fully-masked
  rows (jit re-specializes on batch shape otherwise);
- encode+pool+normalize stay on device; only pooled ``[B, H]`` rows transfer
  to host per batch (vs per-batch ``[B, S, H]`` ``.cpu()`` in torch).
"""

from __future__ import annotations

from typing import Literal

import jax.numpy as jnp
import numpy as np
from pydantic import Field

from distllm_tpu.embed.datasets.base import TextCorpus
from distllm_tpu.embed.embedders.base import EmbedderResult
from distllm_tpu.embed.encoders.base import Encoder
from distllm_tpu.embed.poolers.base import Pooler
from distllm_tpu.utils import BaseConfig


def compute_embeddings(
    texts: list[str],
    encoder: Encoder,
    pooler: Pooler,
    batch_size: int,
    normalize: bool = False,
    flush_every: int = 64,
    max_resident_groups: int = 8,
    tokenize_ahead: int = 2,
    stats: dict | None = None,
) -> np.ndarray:
    """Embed ``texts`` → host ``[N, H]`` float32 array in original order.

    Dispatch is asynchronous: each batch's forward+pool is enqueued and the
    pooled ``[B, H]`` device arrays are collected without blocking, so host
    tokenization of batch *i+1* overlaps device compute of batch *i*. Every
    ``flush_every`` batches the pooled rows are concatenated ON DEVICE into
    one array whose host copy starts asynchronously (one device→host round
    trip per group rather than per batch). At most ``max_resident_groups``
    sealed groups stay on device: past that the oldest (whose async copy has
    had the longest to land) is drained into the host buffer, so device
    residency stays O(flush_every · batch · H) rather than O(corpus).

    ``tokenize_ahead`` batches are tokenized on a background thread while
    the main thread dispatches: dispatch itself is ~free (async), so the
    device only starves when HOST tokenization of the next batch outlasts
    device compute of the current one — true for heavy HF tokenizers on
    long chunks (fast tokenizers release the GIL, so the overlap is real).
    ``0`` restores inline tokenization.

    ``stats``, when given, is filled with bucket-occupancy telemetry:
    ``tokens_real`` / ``tokens_padded`` (device token slots incl. padding)
    and ``bucket_batches`` (batches dispatched per bucket length) — the
    numbers that say whether the bucket ladder is wasting MXU cycles.
    """
    n = len(texts)
    out = np.empty((n, encoder.embedding_size), dtype=np.float32)
    if n == 0:
        return out
    order = sorted(range(n), key=lambda i: len(texts[i].split()))
    pending: list[tuple[list[int], jnp.ndarray]] = []
    # (indices, concatenated device array) per flush group, fetched at the
    # end. Pooled rows are tiny ([N, H] fp32), so whole-corpus residency on
    # device is trivial next to the model — what matters is ROUND TRIPS: on
    # a remote-tunneled chip a device→host fetch costs ~70-90 ms latency
    # regardless of size (measured, scripts/probe_embed2.py), so fetching
    # per batch serializes ~90 ms × batches into the loop, while one
    # device-side concat per flush group + one async copy amortizes it.
    groups: list[tuple[list[int], jnp.ndarray]] = []
    # Fused encode+pool (one dispatch/batch) when the encoder supports it;
    # composed per-stage dispatches otherwise (e.g. FakeEncoder).
    fused = (
        encoder.pooled_forward(pooler, normalize)
        if hasattr(encoder, 'pooled_forward')
        else None
    )

    def drain_group() -> None:
        idx_all, group = groups.pop(0)
        out[idx_all] = np.asarray(group, dtype=np.float32)

    def seal_group() -> None:
        if not pending:
            return
        idx_all = [i for idx, _ in pending for i in idx]
        rows = [dev[: len(idx)] for idx, dev in pending]
        group = jnp.concatenate(rows, axis=0) if len(rows) > 1 else rows[0]
        copy_async = getattr(group, 'copy_to_host_async', None)
        if copy_async is not None:
            copy_async()  # overlaps later groups' compute
        groups.append((idx_all, group))
        pending.clear()
        # Bound device residency: drain the OLDEST group (its async copy has
        # had the longest to complete, so this rarely blocks) once more than
        # max_resident_groups are outstanding.
        while len(groups) > max_resident_groups:
            drain_group()

    def tokenize(lo: int):
        idx = order[lo : lo + batch_size]
        batch = encoder.tokenizer([texts[i] for i in idx])
        return idx, batch.pad_batch_to(
            batch_size, pad_id=encoder.tokenizer.pad_id
        )

    starts = list(range(0, n, batch_size))
    if tokenize_ahead > 0 and len(starts) > 1:
        batches = _prefetched(tokenize, starts, tokenize_ahead)
    else:
        batches = (tokenize(s) for s in starts)

    # try/finally around the consumer loop: deterministically finalize the
    # prefetch generator (its own finally stops the tokenizer thread) even
    # when the loop raises, e.g. an encoder OOM — GC finalization can be
    # arbitrarily deferred while the exception's traceback pins this frame.
    try:
        for idx, batch in batches:
            if stats is not None:
                stats['tokens_real'] = stats.get('tokens_real', 0) + int(
                    batch.attention_mask.sum()
                )
                stats['tokens_padded'] = (
                    stats.get('tokens_padded', 0) + batch.input_ids.size
                )
                hist = stats.setdefault('bucket_batches', {})
                bucket = int(batch.input_ids.shape[1])
                hist[bucket] = hist.get(bucket, 0) + 1
            if fused is not None:
                pooled = fused(batch)
            else:
                pooled = pooler.pool(
                    encoder.forward(batch), batch.attention_mask
                )
                if normalize:
                    # Same guarded normalize as the fused path (zero vectors
                    # from fully-masked pad rows must not produce NaN).
                    pooled = pooled / jnp.clip(
                        jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-12
                    )
                pooled = pooled.astype(jnp.float32)
            pending.append((idx, pooled))
            if len(pending) >= flush_every:
                seal_group()
    finally:
        batches.close()
    seal_group()
    while groups:
        drain_group()
    return out


def _prefetched(tokenize, starts, depth):
    """Yield tokenized batches in order, keeping ``depth`` submissions in
    flight on one background thread. Owns the pool: created on first
    iteration, shut down in the generator's ``finally`` — which the caller
    triggers deterministically via ``close()`` on error."""
    from concurrent.futures import ThreadPoolExecutor

    pool = ThreadPoolExecutor(max_workers=1)
    try:
        # Bounded lookahead: at most `depth` tokenized batches wait in
        # flight, keeping host memory O(depth · batch · seq).
        window = [pool.submit(tokenize, s) for s in starts[:depth]]
        for i, _ in enumerate(starts):
            if i + depth < len(starts):
                window.append(pool.submit(tokenize, starts[i + depth]))
            yield window.pop(0).result()
    finally:
        pool.shutdown(wait=False, cancel_futures=True)


class FullSequenceEmbedderConfig(BaseConfig):
    name: Literal['full_sequence'] = 'full_sequence'
    normalize_embeddings: bool = Field(
        default=False, description='L2-normalize pooled embeddings.'
    )


class FullSequenceEmbedder:
    def __init__(self, config: FullSequenceEmbedderConfig) -> None:
        self.config = config

    def embed(
        self,
        corpus: TextCorpus,
        encoder: Encoder,
        pooler: Pooler,
        batch_size: int,
    ) -> EmbedderResult:
        embeddings = compute_embeddings(
            corpus.texts,
            encoder,
            pooler,
            batch_size,
            normalize=self.config.normalize_embeddings,
        )
        return EmbedderResult(
            embeddings=embeddings, text=corpus.texts, metadata=corpus.metadata
        )
