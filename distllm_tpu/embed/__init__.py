"""Embedding pipeline: datasets → encoders → poolers → embedders → writers.

Mirrors the reference's five strategy families (``distllm/embed/__init__.py``)
with the same YAML-discriminated-union configuration scheme, re-designed for
TPU: fixed-shape bucketed batching, jit-cached encoder forwards, and jitted
pooling kernels.
"""

from distllm_tpu.embed.datasets import DatasetConfigs, get_dataset
from distllm_tpu.embed.embedders import EmbedderConfigs, get_embedder
from distllm_tpu.embed.encoders import EncoderConfigs, get_encoder
from distllm_tpu.embed.poolers import PoolerConfigs, get_pooler
from distllm_tpu.embed.writers import WriterConfigs, get_writer

__all__ = [
    'DatasetConfigs',
    'EmbedderConfigs',
    'EncoderConfigs',
    'PoolerConfigs',
    'WriterConfigs',
    'get_dataset',
    'get_embedder',
    'get_encoder',
    'get_pooler',
    'get_writer',
]
