"""Deterministic fake encoder for tests and pipeline benchmarks.

The reference has no fake backends (SURVEY.md section 4 flags this as a gap):
small real models stand in, which requires downloads. This encoder is fully
local: a fixed PRNG embedding table indexed by token id, so outputs are
reproducible across processes and platforms.
"""

from __future__ import annotations

from typing import Literal

import jax
import jax.numpy as jnp

from distllm_tpu.models.tokenizer import TokenBatch, WhitespaceTokenizer
from distllm_tpu.utils import BaseConfig


class FakeEncoderConfig(BaseConfig):
    name: Literal['fake'] = 'fake'
    embedding_size: int = 64
    vocab_size: int = 4096
    model_max_length: int = 128
    seed: int = 0


class FakeEncoder:
    def __init__(self, config: FakeEncoderConfig) -> None:
        self.config = config
        self.embedding_size = config.embedding_size
        self._tokenizer = WhitespaceTokenizer(
            vocab_size=config.vocab_size,
            model_max_length=config.model_max_length,
        )
        self._table = jax.random.normal(
            jax.random.PRNGKey(config.seed),
            (config.vocab_size, config.embedding_size),
            dtype=jnp.float32,
        )

    @property
    def tokenizer(self) -> WhitespaceTokenizer:
        return self._tokenizer

    @property
    def dtype(self):
        return jnp.float32

    def forward(self, batch: TokenBatch) -> jnp.ndarray:
        return self._table[jnp.asarray(batch.input_ids)]

    def shutdown(self) -> None:
        self._table = None
