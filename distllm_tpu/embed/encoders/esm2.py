"""ESM-2 protein encoder strategy.

Reference parity: ``distllm/embed/encoders/esm2.py`` — the reference needs
faesm/flash-attn CUDA kernels for speed with a transformers fallback; on TPU
the fused attention comes from XLA, so there is a single code path.
"""

from __future__ import annotations

from typing import Literal

from distllm_tpu.embed.encoders.base import JaxEncoder
from distllm_tpu.models import esm2
from distllm_tpu.models.loader import read_checkpoint, read_hf_config
from distllm_tpu.models.tokenizer import HFTokenizer
from distllm_tpu.utils import BaseConfig


class Esm2EncoderConfig(BaseConfig):
    name: Literal['esm2'] = 'esm2'
    pretrained_model_name_or_path: str
    half_precision: bool = True
    model_max_length: int = 1024


class Esm2Encoder(JaxEncoder):
    def __init__(self, config: Esm2EncoderConfig) -> None:
        hf_cfg = read_hf_config(config.pretrained_model_name_or_path)
        model_cfg = esm2.Esm2Config.from_hf_config(hf_cfg)
        model_cfg.dtype = 'bfloat16' if config.half_precision else 'float32'
        params = esm2.params_from_hf(
            read_checkpoint(config.pretrained_model_name_or_path), model_cfg
        )
        tokenizer = HFTokenizer(
            config.pretrained_model_name_or_path,
            model_max_length=config.model_max_length,
        )
        super().__init__(
            config=config,
            apply_fn=esm2.apply,
            model_cfg=model_cfg,
            params=params,
            tokenizer=tokenizer,
            embedding_size=model_cfg.hidden_size,
        )


class EsmCambrianEncoderConfig(BaseConfig):
    """ESM-Cambrian (reference: ``embed/encoders/esmc.py``).

    The reference validates the two released ESM-C sizes (960/1152 hidden)
    and caps sequences at 2048 tokens; this port accepts HF-format ESM
    checkpoints with those dims.
    """

    name: Literal['esmc'] = 'esmc'
    pretrained_model_name_or_path: str
    half_precision: bool = True
    model_max_length: int = 2048


class EsmCambrianEncoder(JaxEncoder):
    VALID_HIDDEN_SIZES = (960, 1152)

    def __init__(self, config: EsmCambrianEncoderConfig) -> None:
        hf_cfg = read_hf_config(config.pretrained_model_name_or_path)
        model_cfg = esm2.Esm2Config.from_hf_config(hf_cfg)
        if model_cfg.hidden_size not in self.VALID_HIDDEN_SIZES:
            raise ValueError(
                f'ESM-C checkpoints have hidden size in '
                f'{self.VALID_HIDDEN_SIZES}, got {model_cfg.hidden_size}'
            )
        model_cfg.dtype = 'bfloat16' if config.half_precision else 'float32'
        params = esm2.params_from_hf(
            read_checkpoint(config.pretrained_model_name_or_path), model_cfg
        )
        tokenizer = HFTokenizer(
            config.pretrained_model_name_or_path,
            model_max_length=config.model_max_length,
        )
        super().__init__(
            config=config,
            apply_fn=esm2.apply,
            model_cfg=model_cfg,
            params=params,
            tokenizer=tokenizer,
            embedding_size=model_cfg.hidden_size,
        )
