"""ESM-2 protein encoder strategy.

Reference parity: ``distllm/embed/encoders/esm2.py`` — the reference needs
faesm/flash-attn CUDA kernels for speed with a transformers fallback; on TPU
the fused attention comes from XLA, so there is a single code path.
"""

from __future__ import annotations

from typing import Literal

from distllm_tpu.embed.encoders.base import JaxEncoder
from distllm_tpu.models import esm2
from distllm_tpu.models.loader import read_checkpoint, read_hf_config
from distllm_tpu.models.tokenizer import HFTokenizer
from distllm_tpu.utils import BaseConfig


class Esm2EncoderConfig(BaseConfig):
    name: Literal['esm2'] = 'esm2'
    pretrained_model_name_or_path: str
    half_precision: bool = True
    model_max_length: int = 1024


class Esm2Encoder(JaxEncoder):
    def __init__(self, config: Esm2EncoderConfig) -> None:
        hf_cfg = read_hf_config(config.pretrained_model_name_or_path)
        model_cfg = esm2.Esm2Config.from_hf_config(hf_cfg)
        model_cfg.dtype = 'bfloat16' if config.half_precision else 'float32'
        params = esm2.params_from_hf(
            read_checkpoint(config.pretrained_model_name_or_path), model_cfg
        )
        tokenizer = HFTokenizer(
            config.pretrained_model_name_or_path,
            model_max_length=config.model_max_length,
        )
        super().__init__(
            config=config,
            apply_fn=esm2.apply,
            model_cfg=model_cfg,
            params=params,
            tokenizer=tokenizer,
            embedding_size=model_cfg.hidden_size,
        )


class EsmCambrianEncoderConfig(BaseConfig):
    """ESM-Cambrian (reference: ``embed/encoders/esmc.py:28-57``).

    Mirrors the reference's embedding-size validation: the two released
    sizes map 300M→960 and 600M→1152; fine-tuned checkpoints must set
    ``embedding_size`` explicitly. Sequences cap at 2048 tokens
    (ref ``esmc.py:84``).
    """

    name: Literal['esmc'] = 'esmc'
    pretrained_model_name_or_path: str = 'EvolutionaryScale/esmc-300m-2024-12'
    embedding_size: int | None = None
    half_precision: bool = True
    model_max_length: int = 2048

    def resolved_embedding_size(self) -> int:
        if self.embedding_size is not None:
            return self.embedding_size
        sizes = {
            'EvolutionaryScale/esmc-300m-2024-12': 960,
            'EvolutionaryScale/esmc-600m-2024-12': 1152,
        }
        for name, size in sizes.items():
            # Accept both registry names and local paths ending in them.
            if self.pretrained_model_name_or_path.rstrip('/').endswith(
                name.split('/')[-1]
            ):
                return size
        raise ValueError(
            f'Invalid model name for ESMC: '
            f'{self.pretrained_model_name_or_path}. Valid model names are: '
            f'{", ".join(sizes)}. Or set embedding_size explicitly for a '
            'fine-tuned model.'
        )


class EsmCambrianEncoder(JaxEncoder):
    """The TRUE ESM-C stack (``models/esmc.py``): fused-LN QKV, QK
    LayerNorm, SwiGLU, sqrt(L/36) residual scaling — loaded from the
    ``esm``-package ``.pth`` checkpoint format, NOT the ESM-2/HF layout.

    Output parity note: the reference casts bf16 hidden states to fp16 on
    the way out (``esmc.py:95-100``); pooled embeddings here leave the
    fused encode path as fp32, which preserves the same values.
    """

    def __init__(self, config: EsmCambrianEncoderConfig) -> None:
        from distllm_tpu.models import esmc

        hidden = config.resolved_embedding_size()
        model_cfg = esmc.EsmcConfig.from_hidden_size(
            hidden,
            dtype='bfloat16' if config.half_precision else 'float32',
            max_position_embeddings=config.model_max_length,
        )
        state = read_checkpoint(config.pretrained_model_name_or_path)
        # Depth comes from the checkpoint itself (robust to distilled or
        # truncated fine-tunes); released 300M/600M match the canonical 30/36.
        block_ids = [
            int(k.split('.')[2])
            for k in state
            if k.startswith('transformer.blocks.')
        ]
        if not block_ids:
            raise ValueError(
                'checkpoint is not in esm-package ESMC layout (no '
                "'transformer.blocks.*' keys) — ESM-C loads the "
                'EvolutionaryScale .pth format, not HF/ESM-2 checkpoints'
            )
        model_cfg.num_layers = 1 + max(block_ids)
        params = esmc.params_from_esm(state, model_cfg)
        tokenizer = esmc.EsmcSequenceTokenizer(
            model_max_length=config.model_max_length
        )
        super().__init__(
            config=config,
            apply_fn=esmc.apply,
            model_cfg=model_cfg,
            params=params,
            tokenizer=tokenizer,
            embedding_size=hidden,
        )
