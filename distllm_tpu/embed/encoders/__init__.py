"""Encoder strategy factory with optional warmstart registration.

Reference parity: ``distllm/embed/encoders/__init__.py:34-84`` — pass
``register=True`` to keep the (expensive) encoder cached across work items in
persistent workers.
"""

from __future__ import annotations

from typing import Any, Union

from distllm_tpu.embed.encoders.auto import AutoEncoder, AutoEncoderConfig
from distllm_tpu.embed.encoders.base import Encoder, JaxEncoder
from distllm_tpu.embed.encoders.esm2 import (
    Esm2Encoder,
    Esm2EncoderConfig,
    EsmCambrianEncoder,
    EsmCambrianEncoderConfig,
)
from distllm_tpu.embed.encoders.fake import FakeEncoder, FakeEncoderConfig
from distllm_tpu.registry import registry

EncoderConfigs = Union[
    AutoEncoderConfig,
    Esm2EncoderConfig,
    EsmCambrianEncoderConfig,
    FakeEncoderConfig,
]

STRATEGIES: dict[str, tuple[type, type]] = {
    'auto': (AutoEncoderConfig, AutoEncoder),
    'esm2': (Esm2EncoderConfig, Esm2Encoder),
    'esmc': (EsmCambrianEncoderConfig, EsmCambrianEncoder),
    'fake': (FakeEncoderConfig, FakeEncoder),
}


def _build_encoder(**kwargs: Any) -> Encoder:
    name = kwargs.get('name', '')
    entry = STRATEGIES.get(name)
    if entry is None:
        raise ValueError(
            f'Unknown encoder name: {name!r}. Available: {sorted(STRATEGIES)}'
        )
    config_cls, cls = entry
    return cls(config_cls(**kwargs))


def get_encoder(kwargs: dict[str, Any], register: bool = False) -> Encoder:
    """Build an encoder; with ``register=True`` reuse a cached instance."""
    if register:
        return registry().get(_build_encoder, slot='encoder', **kwargs)
    return _build_encoder(**kwargs)


__all__ = [
    'Encoder',
    'JaxEncoder',
    'EncoderConfigs',
    'get_encoder',
    'STRATEGIES',
]
