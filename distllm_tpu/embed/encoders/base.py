"""Encoder protocol and the shared JAX encoder runtime.

Reference parity: ``distllm/embed/encoders/base.py:14-55`` — an encoder owns
a tokenizer and produces ``[B, S, H]`` last hidden states. Here the forward
is a jitted pure function cached per bucket shape; params can be sharded over
a mesh for tensor parallelism (the reference's GPU equivalent relies on
``torch.compile`` + CUDA, ``auto.py:92-93``).
"""

from __future__ import annotations

from typing import Protocol, runtime_checkable

import jax
import jax.numpy as jnp

from distllm_tpu.models.tokenizer import TokenBatch


@runtime_checkable
class Encoder(Protocol):
    config: object
    embedding_size: int

    @property
    def tokenizer(self): ...

    def forward(self, batch: TokenBatch) -> jnp.ndarray: ...


class JaxEncoder:
    """Concrete encoder driving a functional model's ``apply``.

    ``apply_fn(params, model_cfg, ids, mask) -> [B, S, H]`` is jitted once
    per input shape; bucketed tokenization keeps the set of shapes small.
    """

    def __init__(
        self,
        config,
        apply_fn,
        model_cfg,
        params,
        tokenizer,
        embedding_size: int,
        quantization: str | None = None,
    ) -> None:
        self.config = config
        self.model_cfg = model_cfg
        self._tokenizer = tokenizer
        self.embedding_size = embedding_size
        if quantization:
            # Weight-only quantization (reference: NF4 via bitsandbytes,
            # auto.py:46-56): store int8/nf4 codes in HBM; dequantization
            # happens per layer inside the jitted forward at the point of
            # use (common.dense unpacks QTensor leaves riding the layer
            # scan) — a whole-tree dequant before the forward would
            # materialize the full float model as HLO temps.
            from distllm_tpu.ops.quantization import quantize_pytree

            params = quantize_pytree(
                params,
                mode=quantization,
                out_dtype=getattr(model_cfg, 'dtype', 'bfloat16'),
            )
        self._apply = lambda p, ids, mask: apply_fn(p, model_cfg, ids, mask)
        self._forward = jax.jit(self._apply)
        self._pooled_cache: dict = {}
        self.params = params

    @property
    def tokenizer(self):
        return self._tokenizer

    @property
    def dtype(self):
        return jnp.dtype(getattr(self.model_cfg, 'dtype', 'float32'))

    def forward(self, batch: TokenBatch) -> jnp.ndarray:
        return self._forward(self.params, batch.input_ids, batch.attention_mask)

    def pooled_forward(self, pooler, normalize: bool = False):
        """Fused encode→pool(→normalize)→fp32 as ONE jitted dispatch.

        One device round trip per batch instead of two/three keeps the hot
        loop off the dispatch-latency floor (dominant when the chip sits
        behind a remote tunnel); XLA also fuses the pooling reduction into
        the final layer's epilogue instead of re-reading ``[B, S, H]``.
        Cached per (pooler type, pooler config, normalize): the closure
        captures the pooler instance, so a same-class pooler with different
        config must not reuse another instance's trace — but fresh
        same-config instances (one per work item in the embedding driver)
        MUST share it, or every file recompiles the fused graph.
        """
        pooler_cfg = getattr(pooler, 'config', None)
        cfg_key = (
            pooler_cfg.model_dump_json()
            if hasattr(pooler_cfg, 'model_dump_json')
            else repr(pooler_cfg)
        )
        key = (type(pooler).__qualname__, cfg_key, normalize)
        fused = self._pooled_cache.get(key)
        if fused is None:
            apply = self._apply

            def _fused(p, ids, mask):
                pooled = pooler.pool(apply(p, ids, mask), mask)
                if normalize:
                    pooled = pooled / jnp.clip(
                        jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-12
                    )
                return pooled.astype(jnp.float32)

            fused = jax.jit(_fused)
            self._pooled_cache[key] = fused

        def run(batch: TokenBatch) -> jnp.ndarray:
            return fused(self.params, batch.input_ids, batch.attention_mask)

        return run

    def shard(self, mesh, specs) -> None:
        """Place params on a mesh (TP/DP); jitted fns re-specialize lazily."""
        from distllm_tpu.parallel.sharding import shard_pytree

        self.params = shard_pytree(self.params, specs, mesh)

    def shutdown(self) -> None:
        """Release HBM references so a swapped-in model can fit."""
        self.params = None
        self._forward = None
        self._pooled_cache.clear()
