"""Encoder protocol and the shared JAX encoder runtime.

Reference parity: ``distllm/embed/encoders/base.py:14-55`` — an encoder owns
a tokenizer and produces ``[B, S, H]`` last hidden states. Here the forward
is a jitted pure function cached per bucket shape; params can be sharded over
a mesh for tensor parallelism (the reference's GPU equivalent relies on
``torch.compile`` + CUDA, ``auto.py:92-93``).
"""

from __future__ import annotations

from typing import Protocol, runtime_checkable

import jax
import jax.numpy as jnp

from distllm_tpu.models.tokenizer import TokenBatch


@runtime_checkable
class Encoder(Protocol):
    config: object
    embedding_size: int

    @property
    def tokenizer(self): ...

    def forward(self, batch: TokenBatch) -> jnp.ndarray: ...


class JaxEncoder:
    """Concrete encoder driving a functional model's ``apply``.

    ``apply_fn(params, model_cfg, ids, mask) -> [B, S, H]`` is jitted once
    per input shape; bucketed tokenization keeps the set of shapes small.
    """

    def __init__(
        self,
        config,
        apply_fn,
        model_cfg,
        params,
        tokenizer,
        embedding_size: int,
        quantization: str | None = None,
    ) -> None:
        self.config = config
        self.model_cfg = model_cfg
        self._tokenizer = tokenizer
        self.embedding_size = embedding_size
        if quantization:
            # Weight-only quantization (reference: NF4 via bitsandbytes,
            # auto.py:46-56): store int8/nf4 codes in HBM, dequantize to the
            # compute dtype inside the jitted forward.
            from distllm_tpu.ops.quantization import (
                dequantize_pytree,
                quantize_pytree,
            )

            params = quantize_pytree(
                params,
                mode=quantization,
                out_dtype=getattr(model_cfg, 'dtype', 'bfloat16'),
            )
            self._forward = jax.jit(
                lambda p, ids, mask: apply_fn(
                    dequantize_pytree(p), model_cfg, ids, mask
                )
            )
        else:
            self._forward = jax.jit(
                lambda p, ids, mask: apply_fn(p, model_cfg, ids, mask)
            )
        self.params = params

    @property
    def tokenizer(self):
        return self._tokenizer

    @property
    def dtype(self):
        return jnp.dtype(getattr(self.model_cfg, 'dtype', 'float32'))

    def forward(self, batch: TokenBatch) -> jnp.ndarray:
        return self._forward(self.params, batch.input_ids, batch.attention_mask)

    def shard(self, mesh, specs) -> None:
        """Place params on a mesh (TP/DP); jitted fns re-specialize lazily."""
        from distllm_tpu.parallel.sharding import shard_pytree

        self.params = shard_pytree(self.params, specs, mesh)

    def shutdown(self) -> None:
        """Release HBM references so a swapped-in model can fit."""
        self.params = None
        self._forward = None
