"""Auto encoder: dispatch a local HF checkpoint to the right JAX model.

Reference parity: ``distllm/embed/encoders/auto.py`` (``AutoModel`` with
half precision, optional NF4 quantization, ``torch.compile``). Here the
``model_type`` in ``config.json`` picks the JAX implementation (BERT-family
or Mistral-family); precision is a dtype on the model config (bf16 default —
the TPU-native analogue of ``half_precision``); compilation is jit, cached
per bucket shape. Weight quantization (int8) arrives via
``distllm_tpu.ops.quantization``.
"""

from __future__ import annotations

from typing import Literal

from pydantic import Field

from distllm_tpu.embed.encoders.base import JaxEncoder
from distllm_tpu.models import bert, decoder_families, esm2, modernbert
from distllm_tpu.models.loader import read_checkpoint, read_hf_config
from distllm_tpu.models.tokenizer import HFTokenizer
from distllm_tpu.utils import BaseConfig

# Encoder-only families plus every decoder family (embedding models like
# SFR-Embedding-Mistral ride the decoder stacks with last-token pooling).
_FAMILIES = {
    'bert': (bert.BertConfig, bert),
    'esm': (esm2.Esm2Config, esm2),
    'modernbert': (modernbert.ModernBertConfig, modernbert),
    **decoder_families(),
}


class AutoEncoderConfig(BaseConfig):
    name: Literal['auto'] = 'auto'
    pretrained_model_name_or_path: str = Field(
        description='Local path to an HF-format checkpoint directory.'
    )
    tokenizer_name: str | None = Field(
        default=None, description='Defaults to the model path.'
    )
    half_precision: bool = Field(
        default=True, description='bf16 activations/params (TPU-native).'
    )
    model_max_length: int | None = None
    trust_remote_code: bool = False
    quantization: bool | Literal['int8', 'nf4'] = Field(
        default=False,
        description='Weight-only quantization; True means nf4 (the '
        "reference's bitsandbytes NF4 load path, auto.py:46-56).",
    )


class AutoEncoder(JaxEncoder):
    def __init__(self, config: AutoEncoderConfig) -> None:
        hf_cfg = read_hf_config(config.pretrained_model_name_or_path)
        model_type = hf_cfg.get('model_type', 'bert')
        family = _FAMILIES.get(model_type)
        if family is None:
            raise ValueError(
                f'Unsupported model_type {model_type!r}; '
                f'supported: {sorted(_FAMILIES)}'
            )
        cfg_cls, module = family
        model_cfg = cfg_cls.from_hf_config(hf_cfg)
        model_cfg.dtype = 'bfloat16' if config.half_precision else 'float32'
        state = read_checkpoint(config.pretrained_model_name_or_path)
        params = module.params_from_hf(state, model_cfg)
        tokenizer = HFTokenizer(
            config.tokenizer_name or config.pretrained_model_name_or_path,
            model_max_length=config.model_max_length
            or hf_cfg.get('max_position_embeddings'),
            trust_remote_code=config.trust_remote_code,
        )
        from distllm_tpu.ops.quantization import normalize_mode

        super().__init__(
            config=config,
            apply_fn=module.apply,
            model_cfg=model_cfg,
            params=params,
            tokenizer=tokenizer,
            embedding_size=model_cfg.hidden_size,
            quantization=normalize_mode(config.quantization),
        )
        self._module = module

    def param_specs(self, params=None):
        try:
            return self._module.param_specs(self.model_cfg, params or self.params)
        except TypeError:
            return self._module.param_specs(self.model_cfg)
