"""distllm-tpu: TPU-native distributed LLM inference framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
``ramanathanlab/distllm`` (see /root/reference): corpus embedding, batch text
generation with a paged-KV continuous-batching engine, sharded semantic
similarity search, RAG applications, and MCQA evaluation harnesses.

Layer map (mirrors SURVEY.md section 1, re-architected TPU-first):

- ``distllm_tpu.utils``     — config base (YAML/JSON pydantic models)
- ``distllm_tpu.registry``  — warmstart cache for compiled models
- ``distllm_tpu.timer``     — parseable telemetry timers
- ``distllm_tpu.parallel``  — mesh/sharding helpers + cross-host fabric
- ``distllm_tpu.models``    — pure-JAX model implementations + HF loaders
- ``distllm_tpu.ops``       — pallas/XLA kernels (attention, pooling, topk, ...)
- ``distllm_tpu.embed``     — embedding pipeline (datasets/encoders/poolers/...)
- ``distllm_tpu.generate``  — generation pipeline + paged-KV engine
- ``distllm_tpu.rag``       — retrieval index, RAG synthesis, QA eval tasks
- ``distllm_tpu.mcqa``      — MCQA evaluation harness
"""

from __future__ import annotations

__version__ = '0.1.0'
