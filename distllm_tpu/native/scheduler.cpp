// Continuous-batching scheduler — native runtime core of the generation
// engine (the TPU analogue of vLLM's scheduler; SURVEY.md §2.4 N1).
//
// Owns ALL scheduling state: the block free-list, per-request block lists,
// slot assignment, the waiting queue, and the admission / recompute-
// preemption policy. The Python engine asks it what to do each step and
// only runs the jitted device programs. A pure-Python twin
// (engine/scheduler.py PyScheduler) implements the identical policy;
// differential tests drive both with the same workload and require
// identical decisions.
//
// Policy (must stay in lockstep with PyScheduler):
//   - admit_next: pop the head of the waiting queue into the lowest free
//     slot if blocks for (num_tokens + 1) are available.
//   - prepare_decode(k): every running sequence gets capacity for k more
//     tokens (k > 1 backs the engine's multi-step fused decode windows,
//     where K tokens are generated per dispatch); on OOM, preempt the
//     youngest (highest request id) running request — free its blocks,
//     push it to the FRONT of the waiting queue (recompute preemption: it
//     will re-prefill prompt + generated). The rows_k variant grants
//     PER-ROW headroom (speculative verify windows reserve each row's
//     own 1 + draft span rather than the batch max).
//   - trim(rid): return owned tail blocks beyond blocks_needed(num_tokens
//     + 1) to the free list, newest first (LIFO restore) — the rejected-
//     suffix rollback of speculative windows.
//   - block 0 is the reserved trash block and is never handed out.
//   - borrowed prefixes (automatic prefix caching): the first
//     `num_borrowed` blocks of a request's row are prefix-cache property —
//     attached at add (cache hit) or marked via sched_lend_prefix (freshly
//     prefilled prompt blocks adopted by the cache). They are never
//     returned to the free list here (finish/preemption free only the
//     owned tail; the cache hands evicted blocks back through
//     sched_release_blocks), they survive recompute preemption, and they
//     count toward the admission block budget (only the shortfall is
//     allocated).
//
// C ABI for ctypes; no exceptions across the boundary.

#include <algorithm>
#include <cstdint>
#include <deque>
#include <unordered_map>
#include <vector>

namespace {

struct Request {
    int64_t rid;
    int32_t num_tokens;  // prompt + generated so far
    std::vector<int32_t> blocks;
    int32_t slot = -1;  // -1 = not running
    int32_t num_borrowed = 0;  // leading cache-owned blocks (never freed)
};

struct Scheduler {
    int32_t block_size;
    std::vector<int32_t> free_list;  // LIFO of free block ids (block 0 reserved)
    std::deque<int64_t> waiting;
    std::vector<int64_t> slots;  // slot -> rid, -1 empty
    std::unordered_map<int64_t, Request> requests;

    Scheduler(int32_t num_blocks, int32_t block_size_, int32_t max_num_seqs)
        : block_size(block_size_), slots(max_num_seqs, -1) {
        free_list.reserve(num_blocks > 0 ? num_blocks - 1 : 0);
        for (int32_t i = num_blocks - 1; i >= 1; --i) free_list.push_back(i);
    }

    int32_t blocks_needed(int32_t tokens) const {
        return (tokens + block_size - 1) / block_size;
    }

    int32_t num_free() const {
        return static_cast<int32_t>(free_list.size());
    }

    int32_t alloc_block() {
        if (free_list.empty()) return -1;
        int32_t b = free_list.back();
        free_list.pop_back();
        return b;
    }

    // Free the OWNED tail of a request's row; the borrowed prefix stays
    // (prefix-cache property — see the policy note above).
    void free_request_blocks(Request& req) {
        for (size_t i = req.num_borrowed; i < req.blocks.size(); ++i)
            free_list.push_back(req.blocks[i]);
        req.blocks.resize(req.num_borrowed);
    }

    int32_t free_slot() const {
        for (size_t i = 0; i < slots.size(); ++i)
            if (slots[i] < 0) return static_cast<int32_t>(i);
        return -1;
    }

    int32_t num_running() const {
        int32_t n = 0;
        for (int64_t rid : slots) n += (rid >= 0);
        return n;
    }

    // Grow req.blocks to cover `tokens`; false = pool dry (partial growth
    // is kept — the caller retries after preempting someone).
    bool extend(Request& req, int32_t tokens) {
        while (static_cast<int32_t>(req.blocks.size()) < blocks_needed(tokens)) {
            int32_t b = alloc_block();
            if (b < 0) return false;
            req.blocks.push_back(b);
        }
        return true;
    }

    // Preempt the youngest (max rid) running request. Returns its rid, or
    // -1 when fewer than two are running (never preempt the only one).
    int64_t preempt_youngest() {
        int64_t victim = -1;
        int32_t count = 0;
        for (int64_t rid : slots) {
            if (rid < 0) continue;
            ++count;
            victim = std::max(victim, rid);
        }
        if (count <= 1) return -1;
        Request& req = requests[victim];
        free_request_blocks(req);
        slots[req.slot] = -1;
        req.slot = -1;
        waiting.push_front(victim);
        return victim;
    }
};

}  // namespace

extern "C" {

void* sched_create(int32_t num_blocks, int32_t block_size,
                   int32_t max_num_seqs) {
    if (num_blocks < 2 || block_size < 1 || max_num_seqs < 1) return nullptr;
    return new Scheduler(num_blocks, block_size, max_num_seqs);
}

void sched_destroy(void* h) { delete static_cast<Scheduler*>(h); }

// Enqueue a request with `num_tokens` tokens to recompute (prompt, plus any
// generated tokens when re-adding after an external preemption). Returns 0,
// or -1 if it can never fit even in an empty pool.
int32_t sched_add(void* h, int64_t rid, int32_t num_tokens) {
    auto* s = static_cast<Scheduler*>(h);
    if (s->requests.count(rid)) return -2;
    Request req;
    req.rid = rid;
    req.num_tokens = num_tokens;
    s->requests.emplace(rid, std::move(req));
    s->waiting.push_back(rid);
    return 0;
}

// sched_add with a borrowed prefix: `cached[0..n_cached)` are prefix-cache
// blocks covering the request's first n_cached * block_size tokens. They
// join the row immediately and count toward the admission budget.
int32_t sched_add_cached(void* h, int64_t rid, int32_t num_tokens,
                         const int32_t* cached, int32_t n_cached) {
    auto* s = static_cast<Scheduler*>(h);
    if (s->requests.count(rid)) return -2;
    if (n_cached < 0) return -3;
    Request req;
    req.rid = rid;
    req.num_tokens = num_tokens;
    req.blocks.assign(cached, cached + n_cached);
    req.num_borrowed = n_cached;
    s->requests.emplace(rid, std::move(req));
    s->waiting.push_back(rid);
    return 0;
}

// Admit the head of the waiting queue: assign the lowest free slot and
// allocate blocks for num_tokens + 1. Returns the admitted rid, -1 when
// nothing can be admitted right now, or -2 when the head request cannot get
// blocks while NOTHING is running (caller should raise: pool too small).
int64_t sched_admit_next(void* h) {
    auto* s = static_cast<Scheduler*>(h);
    if (s->waiting.empty()) return -1;
    int32_t slot = s->free_slot();
    if (slot < 0) return -1;
    int64_t rid = s->waiting.front();
    Request& req = s->requests[rid];
    // Blocks already on the row (borrowed prefix) cover part of the
    // budget; only the shortfall is allocated.
    int32_t shortfall = s->blocks_needed(req.num_tokens + 1) -
                        static_cast<int32_t>(req.blocks.size());
    if (shortfall > s->num_free()) {
        return s->num_running() == 0 ? -2 : -1;
    }
    s->waiting.pop_front();
    for (int32_t i = 0; i < shortfall; ++i) req.blocks.push_back(s->alloc_block());
    req.slot = slot;
    s->slots[slot] = rid;
    return rid;
}

// Ensure every running sequence has block capacity for `k` more tokens,
// preempting the youngest on OOM. Preempted rids are written to
// out_preempted (capacity = max_num_seqs). Returns the preempted count, or
// -(1 + n_preempted) when the pool is exhausted with a single running
// sequence (fatal) — preemptions already performed in this call are NOT
// rolled back (their requests sit in the waiting queue), so the caller must
// read out_preempted[0..n_preempted) and sync its request states before
// raising.
// Row-filtered variant (mixed prefill+decode serving windows): only the
// `n_rids` requests listed in `rids` are extended by k. Rows mid-prefill
// inside a mixed window already own blocks for their full prompt from
// admission, so giving them speculative decode headroom would waste pool
// and provoke spurious preemptions. Preemption victims are still chosen
// youngest-first over ALL running rows (a mid-prefill row may be
// recompute-preempted; the engine resets its chunk progress).
// rids == nullptr means "all running rows" (the classic policy).
// ks (nullable, parallel to rids) overrides k per row: speculative verify
// windows reserve each row's own 1 + draft span instead of the batch max.
int32_t sched_prepare_decode_rows_k(void* h, int32_t k, const int64_t* rids,
                                    const int32_t* ks, int32_t n_rids,
                                    int64_t* out_preempted) {
    auto* s = static_cast<Scheduler*>(h);
    // INT32_MIN = argument error; must not collide with the fatal-
    // exhaustion encoding -(1 + n_preempted).
    if (k < 1 || n_rids < 0) return INT32_MIN;
    if (ks != nullptr) {
        if (rids == nullptr) return INT32_MIN;
        for (int32_t i = 0; i < n_rids; ++i) {
            if (ks[i] < 1) return INT32_MIN;
            // Duplicate rids would make the per-row k ambiguous (and
            // first-wins here vs last-wins in the Python twin's dict
            // would silently break lockstep parity): argument error.
            for (int32_t j = 0; j < i; ++j)
                if (rids[j] == rids[i]) return INT32_MIN;
        }
    }
    int32_t n_preempted = 0;
    std::vector<int64_t> snapshot(s->slots);
    for (int64_t rid : snapshot) {
        if (rid < 0) continue;
        int32_t k_row = k;
        if (rids != nullptr) {
            const int64_t* hit = std::find(rids, rids + n_rids, rid);
            if (hit == rids + n_rids)
                continue;  // not selected for decode this window
            if (ks != nullptr) k_row = ks[hit - rids];
        }
        Request& req = s->requests[rid];
        if (req.slot < 0) continue;  // preempted earlier in this loop
        bool preempted_self = false;
        while (!s->extend(req, req.num_tokens + k_row)) {
            int64_t victim = s->preempt_youngest();
            if (victim < 0) return -(1 + n_preempted);
            out_preempted[n_preempted++] = victim;
            if (victim == rid) {
                preempted_self = true;
                break;
            }
        }
        if (preempted_self) continue;
    }
    return n_preempted;
}

int32_t sched_prepare_decode_rows(void* h, int32_t k, const int64_t* rids,
                                  int32_t n_rids, int64_t* out_preempted) {
    return sched_prepare_decode_rows_k(h, k, rids, nullptr, n_rids,
                                       out_preempted);
}

int32_t sched_prepare_decode_k(void* h, int32_t k, int64_t* out_preempted) {
    return sched_prepare_decode_rows_k(h, k, nullptr, nullptr, 0,
                                       out_preempted);
}

// Free owned tail blocks beyond blocks_needed(num_tokens + 1), newest
// first so the LIFO free list is restored to its pre-reservation state (a
// later extension re-pops the identical blocks). Borrowed prefix blocks
// are never touched. Returns the count freed, or -1 for an unknown rid.
int32_t sched_trim(void* h, int64_t rid) {
    auto* s = static_cast<Scheduler*>(h);
    auto it = s->requests.find(rid);
    if (it == s->requests.end()) return -1;
    Request& req = it->second;
    int32_t keep = std::max(s->blocks_needed(req.num_tokens + 1),
                            req.num_borrowed);
    int32_t freed = static_cast<int32_t>(req.blocks.size()) - keep;
    if (freed <= 0) return 0;
    for (int32_t i = static_cast<int32_t>(req.blocks.size()) - 1; i >= keep;
         --i)
        s->free_list.push_back(req.blocks[i]);
    req.blocks.resize(keep);
    return freed;
}

int32_t sched_prepare_decode(void* h, int64_t* out_preempted) {
    return sched_prepare_decode_k(h, 1, out_preempted);
}

int32_t sched_append_token(void* h, int64_t rid) {
    auto* s = static_cast<Scheduler*>(h);
    auto it = s->requests.find(rid);
    if (it == s->requests.end()) return -1;
    it->second.num_tokens += 1;
    return 0;
}

// Finish (or cancel) a request: free blocks, release the slot, drop state.
int32_t sched_finish(void* h, int64_t rid) {
    auto* s = static_cast<Scheduler*>(h);
    auto it = s->requests.find(rid);
    if (it == s->requests.end()) return -1;
    Request& req = it->second;
    s->free_request_blocks(req);
    if (req.slot >= 0) s->slots[req.slot] = -1;
    auto w = std::find(s->waiting.begin(), s->waiting.end(), rid);
    if (w != s->waiting.end()) s->waiting.erase(w);
    s->requests.erase(it);
    return 0;
}

// Extend rid's borrowed prefix to `n` blocks total (idempotent for
// smaller n). Returns 0, -1 for an unknown rid, -2 when n exceeds the row.
int32_t sched_lend_prefix(void* h, int64_t rid, int32_t n) {
    auto* s = static_cast<Scheduler*>(h);
    auto it = s->requests.find(rid);
    if (it == s->requests.end()) return -1;
    Request& req = it->second;
    if (n > static_cast<int32_t>(req.blocks.size())) return -2;
    req.num_borrowed = std::max(req.num_borrowed, n);
    return 0;
}

// Return cache-evicted blocks to the free list.
int32_t sched_release_blocks(void* h, const int32_t* blocks, int32_t n) {
    auto* s = static_cast<Scheduler*>(h);
    if (n < 0) return -1;
    for (int32_t i = 0; i < n; ++i) s->free_list.push_back(blocks[i]);
    return 0;
}

int32_t sched_num_borrowed(void* h, int64_t rid) {
    auto* s = static_cast<Scheduler*>(h);
    auto it = s->requests.find(rid);
    return it == s->requests.end() ? -1 : it->second.num_borrowed;
}

int32_t sched_slot(void* h, int64_t rid) {
    auto* s = static_cast<Scheduler*>(h);
    auto it = s->requests.find(rid);
    return it == s->requests.end() ? -1 : it->second.slot;
}

// Write the request's block ids into out (capacity cap); returns the count
// actually owned, or -1 for an unknown rid.
int32_t sched_block_row(void* h, int64_t rid, int32_t* out, int32_t cap) {
    auto* s = static_cast<Scheduler*>(h);
    auto it = s->requests.find(rid);
    if (it == s->requests.end()) return -1;
    const auto& blocks = it->second.blocks;
    int32_t n = static_cast<int32_t>(blocks.size());
    for (int32_t i = 0; i < n && i < cap; ++i) out[i] = blocks[i];
    return n;
}

// Write the slot table's occupied entries as (slot, rid) pairs; returns the
// count. out_slots/out_rids capacity must be max_num_seqs.
int32_t sched_running(void* h, int32_t* out_slots, int64_t* out_rids) {
    auto* s = static_cast<Scheduler*>(h);
    int32_t n = 0;
    for (size_t i = 0; i < s->slots.size(); ++i) {
        if (s->slots[i] < 0) continue;
        out_slots[n] = static_cast<int32_t>(i);
        out_rids[n] = s->slots[i];
        ++n;
    }
    return n;
}

int32_t sched_num_free(void* h) {
    return static_cast<Scheduler*>(h)->num_free();
}

int32_t sched_num_running(void* h) {
    return static_cast<Scheduler*>(h)->num_running();
}

int32_t sched_num_waiting(void* h) {
    return static_cast<int32_t>(static_cast<Scheduler*>(h)->waiting.size());
}

int32_t sched_has_unfinished(void* h) {
    auto* s = static_cast<Scheduler*>(h);
    return (!s->waiting.empty() || s->num_running() > 0) ? 1 : 0;
}

}  // extern "C"
