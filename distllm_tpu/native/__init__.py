"""Native (C++) runtime components, built on demand with the system toolchain.

The reference's native substrate lives in its dependencies (vLLM's C++ block
manager, FAISS, etc. — SURVEY.md section 2.4); the equivalents here are
first-party C++ compiled into small shared objects and loaded via ctypes.
A pure-Python fallback exists for every component so the framework still
works where no compiler is available.
"""

from __future__ import annotations

import hashlib
import subprocess
from pathlib import Path

_NATIVE_DIR = Path(__file__).parent
_BUILD_DIR = _NATIVE_DIR / '_build'


def build_library(source_name: str) -> Path | None:
    """Compile ``source_name`` (e.g. ``block_allocator.cpp``) to a cached .so.

    Returns the .so path, or None when compilation is unavailable/fails.
    The cache key includes the source hash so edits rebuild automatically.
    """
    source = _NATIVE_DIR / source_name
    digest = hashlib.sha256(source.read_bytes()).hexdigest()[:16]
    so_path = _BUILD_DIR / f'{source.stem}-{digest}.so'
    if so_path.exists():
        return so_path
    _BUILD_DIR.mkdir(exist_ok=True)
    try:
        subprocess.run(
            [
                'g++', '-O2', '-shared', '-fPIC', '-std=c++17',
                str(source), '-o', str(so_path),
            ],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return so_path
    except (subprocess.CalledProcessError, FileNotFoundError, subprocess.TimeoutExpired):
        return None
