// Paged-KV block allocator — native runtime component of the generation
// engine (the TPU analogue of vLLM's C++ block manager; SURVEY.md §2.4 N1).
//
// Free-list allocator with per-block reference counts (refcounts > 1 enable
// prefix sharing of common prompt blocks). Block 0 is reserved as the trash
// block for padded scatter writes (see ops/paged_attention.py) and is never
// handed out.
//
// C ABI for ctypes; no exceptions across the boundary.

#include <cstdint>
#include <mutex>
#include <vector>

namespace {

struct Allocator {
    std::vector<int32_t> free_list;   // LIFO of free block ids
    std::vector<int32_t> refcount;    // per-block refcount (0 = free)
    std::mutex mu;

    explicit Allocator(int32_t num_blocks) : refcount(num_blocks, 0) {
        free_list.reserve(num_blocks > 0 ? num_blocks - 1 : 0);
        // Reserve block 0 (trash block): never enters the free list.
        for (int32_t i = num_blocks - 1; i >= 1; --i) {
            free_list.push_back(i);
        }
        if (num_blocks > 0) refcount[0] = 1;
    }
};

}  // namespace

extern "C" {

void* ba_create(int32_t num_blocks) {
    if (num_blocks < 2) return nullptr;
    return new Allocator(num_blocks);
}

void ba_destroy(void* handle) { delete static_cast<Allocator*>(handle); }

// Returns a block id, or -1 when exhausted.
int32_t ba_alloc(void* handle) {
    auto* a = static_cast<Allocator*>(handle);
    std::lock_guard<std::mutex> lock(a->mu);
    if (a->free_list.empty()) return -1;
    int32_t id = a->free_list.back();
    a->free_list.pop_back();
    a->refcount[id] = 1;
    return id;
}

// Increment refcount (prefix sharing). Returns new refcount or -1 on error.
int32_t ba_incref(void* handle, int32_t id) {
    auto* a = static_cast<Allocator*>(handle);
    std::lock_guard<std::mutex> lock(a->mu);
    if (id <= 0 || id >= (int32_t)a->refcount.size() || a->refcount[id] == 0)
        return -1;
    return ++a->refcount[id];
}

// Decrement refcount; frees the block at zero. Returns new refcount or -1.
int32_t ba_free(void* handle, int32_t id) {
    auto* a = static_cast<Allocator*>(handle);
    std::lock_guard<std::mutex> lock(a->mu);
    if (id <= 0 || id >= (int32_t)a->refcount.size() || a->refcount[id] == 0)
        return -1;
    int32_t rc = --a->refcount[id];
    if (rc == 0) a->free_list.push_back(id);
    return rc;
}

int32_t ba_num_free(void* handle) {
    auto* a = static_cast<Allocator*>(handle);
    std::lock_guard<std::mutex> lock(a->mu);
    return (int32_t)a->free_list.size();
}

}  // extern "C"
