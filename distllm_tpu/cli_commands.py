"""CLI subcommand registrations.

Grows with the framework; each subcommand defers heavy imports to run time.
"""

from __future__ import annotations

import argparse

from distllm_tpu.cli import subcommand
from distllm_tpu.observability.instruments import log_event


@subcommand('version', 'Print the distllm-tpu version.')
def _version(parser: argparse.ArgumentParser):
    def run(args: argparse.Namespace) -> int:
        import distllm_tpu

        log_event(distllm_tpu.__version__, component='cli')
        return 0

    return run


@subcommand('embed', 'Embed input files on this host (single-process loop).')
def _embed(parser: argparse.ArgumentParser):
    """Reference parity: ``distllm/cli.py:14-192`` (single-GPU embed loop)."""
    parser.add_argument('--input_dir', required=True)
    parser.add_argument('--output_dir', required=True)
    parser.add_argument('--glob_patterns', nargs='+', default=['*'])
    parser.add_argument('--encoder_name', default='auto')
    parser.add_argument('--pretrained_model_name_or_path', default=None)
    parser.add_argument('--dataset_name', default='jsonl_chunk')
    parser.add_argument('--batch_size', type=int, default=8)
    parser.add_argument('--pooler_name', default='mean')
    parser.add_argument('--embedder_name', default='full_sequence')
    parser.add_argument('--writer_name', default='huggingface')
    parser.add_argument('--normalize_embeddings', action='store_true')

    def run(args: argparse.Namespace) -> int:
        from distllm_tpu.distributed_embedding import Config, run_embedding

        encoder_kwargs = {'name': args.encoder_name}
        if args.pretrained_model_name_or_path:
            encoder_kwargs['pretrained_model_name_or_path'] = (
                args.pretrained_model_name_or_path
            )
        config = Config(
            input_dir=args.input_dir,
            output_dir=args.output_dir,
            glob_patterns=args.glob_patterns,
            dataset_config={
                'name': args.dataset_name,
                'batch_size': args.batch_size,
            },
            encoder_config=encoder_kwargs,
            pooler_config={'name': args.pooler_name},
            embedder_config={
                'name': args.embedder_name,
                'normalize_embeddings': args.normalize_embeddings,
            },
            writer_config={'name': args.writer_name},
        )
        return run_embedding(config)

    return run


@subcommand('merge', 'Merge embedding shards into one dataset.')
def _merge(parser: argparse.ArgumentParser):
    """Reference parity: ``distllm/cli.py:195-245`` (the map-reduce reduce)."""
    parser.add_argument('--dataset_dir', required=True, help='Dir of shards.')
    parser.add_argument('--output_dir', required=True)
    parser.add_argument('--writer_name', default='huggingface')
    parser.add_argument('--num_proc', type=int, default=None)

    def run(args: argparse.Namespace) -> int:
        from pathlib import Path

        from distllm_tpu.embed import get_writer

        writer_kwargs = {'name': args.writer_name}
        if args.writer_name == 'huggingface' and args.num_proc:
            writer_kwargs['num_proc'] = args.num_proc
        writer = get_writer(writer_kwargs)
        shards = sorted(
            p for p in Path(args.dataset_dir).iterdir() if p.is_dir()
        )
        if not shards:
            log_event(f'No shard dirs in {args.dataset_dir}', component='cli')
            return 1
        writer.merge(shards, args.output_dir)
        log_event(
            f'Merged {len(shards)} shards -> {args.output_dir}',
            component='cli',
        )
        return 0

    return run


@subcommand('generate', 'Generate responses for input files on this host.')
def _generate(parser: argparse.ArgumentParser):
    """Reference parity: ``distllm/cli.py:248-407`` (single-host generate)."""
    parser.add_argument('--input_dir', required=True)
    parser.add_argument('--output_dir', required=True)
    parser.add_argument('--glob_patterns', nargs='+', default=['*'])
    parser.add_argument('--reader_name', default='jsonl')
    parser.add_argument('--prompt_name', default='identity')
    parser.add_argument('--generator_name', default='tpu')
    parser.add_argument('--pretrained_model_name_or_path', default=None)
    parser.add_argument('--temperature', type=float, default=0.5)
    parser.add_argument('--max_tokens', type=int, default=2000)
    parser.add_argument('--writer_name', default='huggingface')

    def run(args: argparse.Namespace) -> int:
        from distllm_tpu.distributed_generation import Config, run_generation

        generator_kwargs = {'name': args.generator_name}
        if args.pretrained_model_name_or_path:
            generator_kwargs['pretrained_model_name_or_path'] = (
                args.pretrained_model_name_or_path
            )
        if args.generator_name in ('tpu', 'vllm', 'api', 'langchain'):
            generator_kwargs['temperature'] = args.temperature
            generator_kwargs['max_tokens'] = args.max_tokens
        config = Config(
            input_dir=args.input_dir,
            output_dir=args.output_dir,
            glob_patterns=args.glob_patterns,
            reader_config={'name': args.reader_name},
            prompt_config={'name': args.prompt_name},
            generator_config=generator_kwargs,
            writer_config={'name': args.writer_name},
        )
        return run_generation(config)

    return run


@subcommand('tokenize', 'Tokenize jsonl files into HF datasets.')
def _tokenize(parser: argparse.ArgumentParser):
    """Reference parity: ``distllm/cli.py:410-473``."""
    parser.add_argument('--input_dir', required=True)
    parser.add_argument('--output_dir', required=True)
    parser.add_argument('--glob_patterns', nargs='+', default=['*.jsonl'])
    parser.add_argument('--tokenizer_name_or_path', required=True)
    parser.add_argument('--text_field', default='text')
    parser.add_argument('--max_length', type=int, default=2048)
    parser.add_argument('--return_labels', action='store_true')

    def run(args: argparse.Namespace) -> int:
        from distllm_tpu.distributed_tokenization import (
            Config,
            run_tokenization,
        )

        config = Config(
            input_dir=args.input_dir,
            output_dir=args.output_dir,
            glob_patterns=args.glob_patterns,
            tokenizer_config={
                'tokenizer_name_or_path': args.tokenizer_name_or_path,
                'text_field': args.text_field,
                'max_length': args.max_length,
                'return_labels': args.return_labels,
            },
        )
        return run_tokenization(config)

    return run


@subcommand('chunk_fasta_file', 'Split a FASTA file into N shard files.')
def _chunk_fasta(parser: argparse.ArgumentParser):
    """Reference parity: ``distllm/cli.py:476-514``."""
    parser.add_argument('--fasta_file', required=True)
    parser.add_argument('--output_dir', required=True)
    parser.add_argument('--num_chunks', type=int, required=True)

    def run(args: argparse.Namespace) -> int:
        from pathlib import Path

        from distllm_tpu.embed.datasets.fasta import read_fasta, write_fasta

        sequences = read_fasta(args.fasta_file)
        if not sequences:
            log_event(f'No sequences found in {args.fasta_file}', component='cli')
            return 1
        out = Path(args.output_dir)
        out.mkdir(parents=True, exist_ok=True)
        n = max(1, args.num_chunks)
        per = (len(sequences) + n - 1) // n
        stem = Path(args.fasta_file).stem
        for i in range(0, len(sequences), per):
            write_fasta(
                sequences[i : i + per], out / f'{stem}.chunk{i // per:04d}.fasta'
            )
        log_event(
            f'Wrote {(len(sequences) + per - 1) // per} chunks to {out}',
            component='cli',
        )
        return 0

    return run
