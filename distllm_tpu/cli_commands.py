"""CLI subcommand registrations.

Grows with the framework; each subcommand defers heavy imports to run time.
"""

from __future__ import annotations

import argparse

from distllm_tpu.cli import subcommand


@subcommand('version', 'Print the distllm-tpu version.')
def _version(parser: argparse.ArgumentParser):
    def run(args: argparse.Namespace) -> int:
        import distllm_tpu

        print(distllm_tpu.__version__)
        return 0

    return run
