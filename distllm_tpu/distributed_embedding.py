"""Distributed embedding driver: file-sharded map over a compute fabric.

Reference parity: ``distllm/distributed_embedding.py`` — YAML config, glob
input files, ship a pure worker function to the pool, each worker:
registry-warmstarted encoder → dataset read → embed → write to a per-file
UUID output shard. Timer lines tag every stage exactly like the reference
(``distributed_embedding.py:45-80``) so existing log tooling keeps working.

Run: ``python -m distllm_tpu.distributed_embedding --config embed.yaml``
"""

from __future__ import annotations

import argparse
import functools
import uuid
from pathlib import Path
from typing import Any

from distllm_tpu.observability.instruments import log_event
from distllm_tpu.parallel.fabric import map_with_teardown
from distllm_tpu.parallel.launcher import ComputeConfigs, LocalConfig
from distllm_tpu.timer import Timer
from distllm_tpu.utils import BaseConfig, canonical_function


def embedding_worker(
    file: str,
    output_dir: str,
    dataset_kwargs: dict[str, Any],
    encoder_kwargs: dict[str, Any],
    pooler_kwargs: dict[str, Any],
    embedder_kwargs: dict[str, Any],
    writer_kwargs: dict[str, Any],
) -> str:
    """Embed one input file into a fresh UUID output shard; returns the shard."""
    from distllm_tpu.embed import (
        get_dataset,
        get_embedder,
        get_encoder,
        get_pooler,
        get_writer,
    )

    file_tag = Path(file).name
    with Timer('loaded-encoder', file_tag):
        encoder = get_encoder(encoder_kwargs, register=True)
    dataset = get_dataset(dataset_kwargs)
    pooler = get_pooler(pooler_kwargs)
    embedder = get_embedder(embedder_kwargs)
    writer = get_writer(writer_kwargs)

    with Timer('loaded-dataset', file_tag):
        corpus = dataset.read(file)
    with Timer('computed-embeddings', file_tag):
        result = embedder.embed(
            corpus, encoder, pooler, batch_size=dataset.config.batch_size
        )
    shard_dir = Path(output_dir) / uuid.uuid4().hex
    with Timer('wrote-embeddings', file_tag):
        writer.write(shard_dir, result)
    return str(shard_dir)


class Config(BaseConfig):
    """Driver configuration (reference: ``distributed_embedding.py:83-109``)."""

    input_dir: Path
    output_dir: Path
    glob_patterns: list[str] = ['*']
    dataset_config: dict[str, Any]
    encoder_config: dict[str, Any]
    pooler_config: dict[str, Any]
    embedder_config: dict[str, Any]
    writer_config: dict[str, Any]
    compute_config: ComputeConfigs = LocalConfig()


def run_embedding(config: Config) -> int:
    """Execute the driver for a parsed config (shared by module CLI + typer-
    style ``embed`` subcommand)."""
    embedding_dir = config.output_dir / 'embeddings'
    embedding_dir.mkdir(parents=True, exist_ok=True)
    # Audit copy for experiment tracking (reference :133).
    config.write_yaml(config.output_dir / 'config.yaml')

    files: list[str] = []
    for pattern in config.glob_patterns:
        files.extend(str(p) for p in sorted(config.input_dir.glob(pattern)))
    if not files:
        log_event(
            f'No input files matched {config.glob_patterns} in '
            f'{config.input_dir}',
            component='embed',
        )
        return 1
    log_event(f'Embedding {len(files)} files -> {embedding_dir}', component='embed')

    worker_fn = functools.partial(
        # Run as `python -m`, this module is __main__; rebind the
        # worker fn to its importable path so fabric workers can
        # unpickle it (Parsl has the same module-level-fn rule).
        canonical_function(embedding_worker, 'distllm_tpu.distributed_embedding'),
        output_dir=str(embedding_dir),
        dataset_kwargs=config.dataset_config,
        encoder_kwargs=config.encoder_config,
        pooler_kwargs=config.pooler_config,
        embedder_kwargs=config.embedder_config,
        writer_kwargs=config.writer_config,
    )
    executor = config.compute_config.get_executor(config.output_dir / 'run')
    shards = map_with_teardown(executor, worker_fn, files)
    log_event(f'Finished: {len(shards)} shards written', component='embed')
    return 0


def main(argv: list[str] | None = None) -> int:
    from distllm_tpu.utils import apply_platform_env

    apply_platform_env()
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--config', required=True, type=Path)
    args = parser.parse_args(argv)
    return run_embedding(Config.from_yaml(args.config))


if __name__ == '__main__':
    raise SystemExit(main())
