"""Core configuration and small utilities.

Behavioral parity target: ``distllm/utils.py:20-128`` in the reference —
pydantic config models with YAML/JSON round-trip, list batching, and a
download helper. The implementation is original; configs additionally support
environment-variable substitution (``${env:VAR}``) which the reference only
offers in its chat app (``chat_argoproxy.py:511-549``).
"""

from __future__ import annotations

import json
import os
import re
import subprocess
from pathlib import Path
from typing import Any, Callable, Iterator, TypeVar

import yaml
from pydantic import BaseModel, ConfigDict

T = TypeVar('T')

PathLike = str | Path

_ENV_PATTERN = re.compile(r'\$\{env:([A-Za-z_][A-Za-z0-9_]*)\}')


def _substitute_env(obj: Any) -> Any:
    """Recursively replace ``${env:VAR}`` markers in strings with os.environ."""
    if isinstance(obj, str):
        return _ENV_PATTERN.sub(lambda m: os.environ.get(m.group(1), ''), obj)
    if isinstance(obj, dict):
        return {k: _substitute_env(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_substitute_env(v) for v in obj]
    return obj


class BaseConfig(BaseModel):
    """Pydantic base for every config object in the framework.

    Subclasses declare a ``name: Literal['...']`` tag where they participate in
    a discriminated union dispatched by a strategy factory (the same
    YAML-driven composition scheme the reference uses throughout).
    """

    model_config = ConfigDict(extra='forbid', validate_assignment=True)

    @classmethod
    def from_yaml(cls: type[T], path: PathLike) -> T:
        with open(path) as fh:
            raw = yaml.safe_load(fh) or {}
        return cls(**_substitute_env(raw))

    @classmethod
    def from_json(cls: type[T], path: PathLike) -> T:
        with open(path) as fh:
            raw = json.load(fh)
        return cls(**_substitute_env(raw))

    def write_yaml(self, path: PathLike) -> None:
        Path(path).parent.mkdir(parents=True, exist_ok=True)
        with open(path, 'w') as fh:
            yaml.safe_dump(
                json.loads(self.model_dump_json()), fh, sort_keys=False
            )

    def write_json(self, path: PathLike) -> None:
        Path(path).parent.mkdir(parents=True, exist_ok=True)
        with open(path, 'w') as fh:
            fh.write(self.model_dump_json(indent=2))


#: Import prefixes ``instantiate`` accepts by default. The reference
#: dispatches ``_target_`` through an explicit class allowlist
#: (``chat_argoproxy.py:511-549``); an unrestricted import+call would let
#: any loaded YAML execute arbitrary code. Extend via the ``allow``
#: argument for operator-trusted configs.
INSTANTIATE_ALLOWED_PREFIXES: tuple[str, ...] = ('distllm_tpu.',)


def instantiate(
    config: Any, _allow_: tuple[str, ...] | None = None, **overrides: Any
) -> Any:
    """``_target_``-field class dispatch (reference ``chat_argoproxy.py:511-549``).

    A dict carrying ``_target_: 'pkg.module.ClassName'`` is resolved by
    import and constructed from the remaining keys; nested dicts instantiate
    recursively (depth-first), and ``${env:VAR}`` markers substitute first.
    Non-``_target_`` values pass through unchanged. Targets must fall under
    ``INSTANTIATE_ALLOWED_PREFIXES`` (or the explicit ``_allow_`` prefixes —
    underscored like ``_target_`` so it can never collide with a
    constructor override name).
    """
    config = _substitute_env(config)
    allowed = INSTANTIATE_ALLOWED_PREFIXES + tuple(_allow_ or ())

    def build(obj: Any) -> Any:
        if isinstance(obj, dict):
            built = {k: build(v) for k, v in obj.items() if k != '_target_'}
            target = obj.get('_target_')
            if target is None:
                return built
            import importlib

            module_name, _, attr = str(target).rpartition('.')
            if not module_name:
                raise ValueError(
                    f"_target_ must be a dotted path, got {target!r}"
                )
            if not any(str(target).startswith(p) for p in allowed):
                raise ValueError(
                    f"_target_ {target!r} is outside the allowed prefixes "
                    f'{allowed}; pass allow=("your.pkg.",) for '
                    'operator-trusted configs'
                )
            cls = getattr(importlib.import_module(module_name), attr)
            return cls(**built)
        if isinstance(obj, list):
            return [build(v) for v in obj]
        return obj

    if isinstance(config, dict):
        config = {**config, **overrides}
    return build(config)


def apply_platform_env() -> None:
    """Honor ``JAX_PLATFORMS`` even when a ``sitecustomize`` has already
    pinned ``jax_platforms`` at interpreter start (the axon TPU-tunnel
    image does: its pin beats the env var, so ``JAX_PLATFORMS=cpu
    python -m distllm_tpu...`` would silently grab the TPU). Call first
    thing in every CLI entrypoint, before any other jax use."""
    platforms = os.environ.get('JAX_PLATFORMS')
    if not platforms:
        return
    try:
        import jax

        jax.config.update('jax_platforms', platforms)
    except Exception:  # jax absent or already initialized — leave as-is
        pass


def batch_data(data: list[T], batch_size: int) -> list[list[T]]:
    """Split ``data`` into consecutive chunks of at most ``batch_size``.

    Parity with ``distllm/utils.py:91-112``; every element appears exactly
    once and order is preserved.
    """
    if batch_size < 1:
        raise ValueError(f'batch_size must be >= 1, got {batch_size}')
    return [data[i : i + batch_size] for i in range(0, len(data), batch_size)]


def iter_batches(data: list[T], batch_size: int) -> Iterator[list[T]]:
    """Lazy variant of :func:`batch_data` for large corpora."""
    if batch_size < 1:
        raise ValueError(f'batch_size must be >= 1, got {batch_size}')
    for i in range(0, len(data), batch_size):
        yield data[i : i + batch_size]


def curl_download(url: str, output_path: PathLike, timeout: int = 600) -> Path:
    """Download ``url`` to ``output_path`` via curl if not already present.

    Parity with ``distllm/utils.py:115-128`` (used by the QA eval tasks to
    fetch datasets). Skips the download when the file already exists.
    """
    output_path = Path(output_path)
    if output_path.exists():
        return output_path
    output_path.parent.mkdir(parents=True, exist_ok=True)
    # Download to a temp name and rename on success so a failed transfer
    # never leaves a partial file that later calls mistake for a cache hit.
    tmp_path = output_path.with_name(output_path.name + '.part')
    subprocess.run(
        ['curl', '-fsSL', url, '-o', str(tmp_path)],
        check=True,
        timeout=timeout,
    )
    tmp_path.rename(output_path)
    return output_path


def canonical_function(fn: Callable, module: str) -> Callable:
    """Re-resolve ``fn`` from its importable module when it was defined in
    ``__main__`` (a driver run as ``python -m ...``). Pickle serializes
    functions by module path, and ``__main__`` inside a fabric worker is
    ``distllm_tpu.parallel.worker`` — the worker could never resolve the
    driver's function without this."""
    if getattr(fn, '__module__', None) != '__main__':
        return fn
    import importlib

    return getattr(importlib.import_module(module), fn.__name__)


def expo_backoff_retry(
    fn,
    *,
    max_tries: int = 5,
    base_delay: float = 1.0,
    max_delay: float = 30.0,
    retry_on: tuple[type[BaseException], ...] = (Exception,),
    give_up_on: tuple[type[BaseException], ...] = (),
    jitter: bool = True,
    sleep=None,
):
    """Call ``fn()`` with exponential backoff (own impl; ``backoff`` pkg absent).

    Parity target: ``@backoff.expo`` usage in the reference MCQA harness
    (``mcqa/rag_argonium_score_parallel_v3.py:1957-1963``) — expo delays with
    jitter, a bounded number of tries, and give-up exception types (the
    reference gives up on auth errors).
    """
    import random
    import time

    if sleep is None:
        sleep = time.sleep
    last: BaseException | None = None
    for attempt in range(max_tries):
        try:
            return fn()
        except give_up_on:
            raise
        except retry_on as exc:  # noqa: PERF203
            last = exc
            if attempt == max_tries - 1:
                raise
            delay = min(max_delay, base_delay * (2**attempt))
            if jitter:
                delay *= 0.5 + random.random() / 2
            sleep(delay)
    raise last  # pragma: no cover - unreachable
