"""Multi-replica serving tier (docs/routing.md).

A prefix-cache-aware HTTP router fronting N ``chat_server`` replicas
(``router/app.py``; entry point ``scripts/router.py``), plus the shared
affinity bookkeeping (``router/affinity.py``) the replicas use to
annotate responses. The peer KV tier that lets replicas hand spilled
blocks to each other lives with the rest of the tier cascade in
``generate/engine/kv_cache.py`` (:class:`PeerKVTier`) and the fabric
transport in ``parallel/fabric.py`` (:class:`KVBlockServer` /
:class:`KVBlockClient`).
"""

from distllm_tpu.router.affinity import (
    AffinityMap,
    prompt_prefix_digests,
)
from distllm_tpu.router.app import Replica, RouterConfig, build_router_app

__all__ = [
    'AffinityMap',
    'Replica',
    'RouterConfig',
    'build_router_app',
    'prompt_prefix_digests',
]
