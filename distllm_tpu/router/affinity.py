"""Prefix-affinity bookkeeping for the multi-replica router.

The routing signal is the same content address the replicas key their KV
tiers by: a chained digest sequence (``kv_cache.block_digests``) over the
request's prompt prefix. The router has no tokenizer, so the chain runs
over the canonical UTF-8 *bytes* of the OpenAI message list instead of
token ids — both sides (router pick, replica response header) compute it
with :func:`prompt_prefix_digests`, so the addresses agree without the
router ever loading a model. A byte-level chain is coarser than the
replica's token-level tier chain, but it has the one property affinity
needs: two requests sharing a message-prefix share a digest-chain prefix,
and a request extending a session extends its chain (append-only render).

Learning protocol (docs/routing.md "Digest learning"): every completion
response carries ``X-Distllm-Prefix-Digest`` (hex of the deepest chain
digest the replica now holds) and ``X-Distllm-Prefix-Depth`` (its chain
index + 1). The router verifies the advertised digest against its own
chain for that request — a mismatch (different block_bytes, a proxy that
rewrote the body) drops the sample instead of poisoning the map — then
inserts ``chain[:depth]`` into that replica's bounded LRU
:class:`AffinityMap`. Routing scores each replica by the longest chain
prefix present in its map; depth 0 everywhere falls back to least-loaded.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Iterable, Mapping, Sequence

from distllm_tpu.generate.engine.kv_cache import block_digests

# Digest-chain block granularity in BYTES of rendered prompt prefix.
# Small enough that a one-turn system prompt already spans several
# blocks, large enough that the per-request chain stays short. Router
# and replica must agree — both default to this constant.
DEFAULT_BLOCK_BYTES = 64

HEADER_DIGEST = 'X-Distllm-Prefix-Digest'
HEADER_DEPTH = 'X-Distllm-Prefix-Depth'
HEADER_RETRY = 'X-Distllm-Router-Retry'
HEADER_REPLICA = 'X-Distllm-Router-Replica'


def prompt_prefix_bytes(messages: Iterable[Mapping]) -> bytes:
    """Canonical append-only byte rendering of an OpenAI message list.

    Unit-separator framing (0x1f between role and content, 0x1e after
    each message) keeps the encoding injective — ``[{'a'},{'b'}]`` and
    ``[{'ab'}]`` must not collide — and appending a message appends
    bytes, so a growing conversation grows its digest chain in place.
    """
    parts = []
    for message in messages:
        role = str(message.get('role', ''))
        content = str(message.get('content', ''))
        parts.append(f'{role}\x1f{content}\x1e')
    return ''.join(parts).encode('utf-8', 'replace')


def prompt_prefix_digests(
    messages: Iterable[Mapping], block_bytes: int = DEFAULT_BLOCK_BYTES
) -> list[bytes]:
    """Chained digests over full ``block_bytes`` blocks of the rendered
    prompt (bytes are a ``Sequence[int]``, so the replicas' own
    ``block_digests`` chain does the hashing). Prompts shorter than one
    block get an empty chain — no affinity signal, by design."""
    return block_digests(prompt_prefix_bytes(messages), block_bytes)


class AffinityMap:
    """Bounded per-replica digest LRU maps learned from response headers.

    Not thread-safe: the router is a single asyncio loop and all
    learn/score/drop calls run on it.
    """

    def __init__(self, max_entries_per_replica: int = 4096) -> None:
        self.max_entries = int(max_entries_per_replica)
        self._maps: dict[str, OrderedDict[bytes, None]] = {}

    def learn(self, replica: str, chain: Sequence[bytes]) -> None:
        lru = self._maps.setdefault(replica, OrderedDict())
        for digest in chain:
            lru[digest] = None
            lru.move_to_end(digest)
        while len(lru) > self.max_entries:
            lru.popitem(last=False)

    def verify_and_learn(
        self, replica: str, chain: Sequence[bytes],
        digest_hex: str | None, depth_text: str | None,
    ) -> int:
        """Apply one response-header learning sample; returns the depth
        learned (0 = sample dropped). The advertised digest must equal
        our own ``chain[depth-1]`` — agreement proves both sides hashed
        the same bytes at the same granularity."""
        if not digest_hex or not depth_text:
            return 0
        try:
            depth = int(depth_text)
            advertised = bytes.fromhex(digest_hex)
        # distlint: disable=swallowed-exception -- a malformed learning header is an untrusted-input sample to drop, not an error: routing falls back to least-loaded and the next well-formed response re-teaches the map
        except ValueError:
            return 0
        if depth < 1 or depth > len(chain) or chain[depth - 1] != advertised:
            return 0
        self.learn(replica, chain[:depth])
        return depth

    def score(self, replica: str, chain: Sequence[bytes]) -> int:
        """Longest chain prefix present in ``replica``'s map (the
        expected warm depth if routed there)."""
        lru = self._maps.get(replica)
        if not lru:
            return 0
        depth = 0
        for digest in chain:
            if digest not in lru:
                break
            depth += 1
        return depth

    def drop(self, replica: str) -> None:
        """Forget a replica (left rotation for good — its cache is gone)."""
        self._maps.pop(replica, None)

    def entries(self) -> int:
        return sum(len(lru) for lru in self._maps.values())
