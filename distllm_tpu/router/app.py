"""Prefix-affinity HTTP router for N chat_server replicas.

An asyncio (aiohttp) front-end that load-balances the OpenAI-compatible
surface (``POST /v1/chat/completions``) across replicas, routing each
request to the replica most likely to already hold its KV blocks
(docs/routing.md). Entry point: ``scripts/router.py``.

Policies (``RouterConfig.policy``):

- ``prefix_affinity`` (default) — score every healthy replica by the
  longest prefix of the request's byte-level digest chain present in its
  learned :class:`~distllm_tpu.router.affinity.AffinityMap`; deepest
  match wins (``decision=affinity``), depth 0 everywhere falls back to
  least-loaded.
- ``least_loaded`` — lightest ``GET /loadinfo`` queue (queue_depth, then
  in-flight, then KV occupancy), probed with a short-TTL cache so one
  routing decision never burns a round trip on a warm entry.
- ``round_robin`` — the baseline rotation (the bench's control arm).

Health integration: a background probe loop polls each replica's
``/health``; connection failure or a non-ready answer removes it from
rotation. ``dead`` replicas rejoin when probes recover; ``draining``
(POST /drain observed) is ONE-WAY — a drained replica never rejoins and
its affinity map is forgotten (its process will restart with a new cache;
the disk tier makes that restart warm, but residency must be re-learned).
An in-flight request whose replica dies mid-proxy (or races a drain) is
retried ONCE on a healthy peer with an honest ``X-Distllm-Router-Retry``
marker; a replica's 429 + Retry-After admission rejection propagates to
the client untouched — backpressure is the replica's call, and retrying
it elsewhere would defeat admission control. Every proxied response also
carries ``X-Distllm-Router-Replica`` naming the serving replica.

The router keeps no per-request state beyond the bounded affinity maps;
it is itself stateless across restarts (maps re-learn from headers).
"""

from __future__ import annotations

import asyncio
import time
from typing import Literal

from distllm_tpu.observability import instruments, render_prometheus
from distllm_tpu.router.affinity import (
    DEFAULT_BLOCK_BYTES,
    HEADER_DEPTH,
    HEADER_DIGEST,
    HEADER_REPLICA,
    HEADER_RETRY,
    AffinityMap,
    prompt_prefix_digests,
)
from distllm_tpu.utils import BaseConfig

# Response headers relayed verbatim from replica to client (plus the
# router's own markers). Hop-by-hop headers stay out.
_RELAY_HEADERS = (
    'Content-Type',
    'Retry-After',
    'X-Request-Id',
    HEADER_DIGEST,
    HEADER_DEPTH,
)


class RouterConfig(BaseConfig):
    """Knobs for the multi-replica router (docs/routing.md knob table)."""

    # Replica base URLs ('http://host:port'), the initial rotation.
    replicas: tuple[str, ...] = ()
    policy: Literal[
        'prefix_affinity', 'least_loaded', 'round_robin'
    ] = 'prefix_affinity'
    # Digest-chain granularity in prompt-prefix BYTES; must match what
    # the replicas hash into their response headers (both sides default
    # to affinity.DEFAULT_BLOCK_BYTES).
    affinity_block_bytes: int = DEFAULT_BLOCK_BYTES
    # Bound of each per-replica digest LRU map.
    affinity_map_size: int = 4096
    # /loadinfo probe cache TTL: one routing decision on a warm entry
    # costs zero round trips.
    loadinfo_ttl_s: float = 0.25
    # Background /health probe period.
    health_interval_s: float = 2.0
    # Upstream completion timeout per proxy attempt.
    request_timeout_s: float = 300.0


class Replica:
    """Rotation state for one replica (mutated only on the router loop)."""

    def __init__(self, url: str) -> None:
        self.url = url.rstrip('/')
        # Short display name for headers/traces: 'host:port'.
        self.name = self.url.split('//', 1)[-1]
        self.state = 'healthy'  # healthy | dead | draining
        self.load: dict | None = None
        self.load_at = 0.0

    @property
    def in_rotation(self) -> bool:
        return self.state == 'healthy'

    def mark_dead(self) -> None:
        # Drain outranks dead: a draining replica that stops answering
        # is still drained — it must not rejoin when probes recover.
        if self.state != 'draining':
            self.state = 'dead'

    def mark_draining(self) -> None:
        self.state = 'draining'

    def mark_healthy(self) -> None:
        # One-way drain: only dead recovers.
        if self.state == 'dead':
            self.state = 'healthy'


def build_router_app(config: RouterConfig):
    from aiohttp import ClientSession, ClientTimeout, web
    import aiohttp

    replicas = [Replica(url) for url in config.replicas]
    affinity = AffinityMap(config.affinity_map_size)
    state = {'rr_index': 0, 'client': None, 'health_task': None}

    def client() -> 'ClientSession':
        # Created lazily on the router loop (ClientSession binds to it).
        if state['client'] is None:
            state['client'] = ClientSession(
                timeout=ClientTimeout(total=config.request_timeout_s)
            )
        return state['client']

    def _publish_states() -> None:
        for label in ('healthy', 'draining', 'dead'):
            instruments.ROUTER_REPLICAS.labels(state=label).set(
                sum(1 for r in replicas if r.state == label)
            )
        instruments.ROUTER_AFFINITY_ENTRIES.set(affinity.entries())

    _publish_states()

    async def _probe(replica: Replica) -> None:
        try:
            async with client().get(
                f'{replica.url}/health',
                timeout=ClientTimeout(total=max(1.0, config.health_interval_s)),
            ) as resp:
                doc = await resp.json()
        # distlint: disable=swallowed-exception -- an unreachable replica IS the probe's answer: it leaves rotation (state=dead, ROUTER_REPLICAS gauge) and rejoins when probes recover
        except Exception:
            replica.mark_dead()
            return
        if doc.get('draining'):
            if replica.state != 'draining':
                replica.mark_draining()
                # Its cache dies with the process; re-learning on a
                # restart is cheaper than routing warm traffic to a
                # replica that will refuse it.
                affinity.drop(replica.name)
        elif doc.get('ready'):
            replica.mark_healthy()
        else:
            replica.mark_dead()

    async def _health_loop() -> None:
        while True:
            await asyncio.gather(*(_probe(r) for r in replicas))
            _publish_states()
            await asyncio.sleep(config.health_interval_s)

    async def _loadinfo(replica: Replica) -> dict | None:
        now = time.monotonic()
        if replica.load is not None and (
            now - replica.load_at < config.loadinfo_ttl_s
        ):
            return replica.load
        try:
            async with client().get(
                f'{replica.url}/loadinfo',
                timeout=ClientTimeout(total=max(1.0, config.loadinfo_ttl_s * 4)),
            ) as resp:
                replica.load = await resp.json()
                replica.load_at = now
                return replica.load
        # distlint: disable=swallowed-exception -- a failed load probe demotes the replica to dead (gauge + rotation state), and the pick falls through to the remaining candidates
        except Exception:
            replica.mark_dead()
            return None

    async def _pick_least_loaded(
        candidates: list[Replica],
    ) -> Replica | None:
        loads = await asyncio.gather(*(_loadinfo(r) for r in candidates))
        best: tuple | None = None
        best_replica: Replica | None = None
        for replica, load in zip(candidates, loads):
            if load is None or not replica.in_rotation:
                continue
            key = (
                int(load.get('queue_depth', 0)),
                int(load.get('in_flight', 0)),
                float(load.get('kv_occupancy', 0.0)),
            )
            if best is None or key < best:
                best, best_replica = key, replica
        return best_replica

    def _pick_round_robin(candidates: list[Replica]) -> Replica:
        pick = candidates[state['rr_index'] % len(candidates)]
        state['rr_index'] += 1
        return pick

    async def _pick(
        chain: list[bytes], exclude: Replica | None = None
    ) -> tuple[Replica | None, str]:
        """One routing decision: (replica, decision-label)."""
        candidates = [
            r for r in replicas if r.in_rotation and r is not exclude
        ]
        if not candidates:
            return None, 'least_loaded'
        if config.policy == 'round_robin':
            return _pick_round_robin(candidates), 'round_robin'
        if config.policy == 'prefix_affinity' and chain:
            scored = [
                (affinity.score(r.name, chain), i, r)
                for i, r in enumerate(candidates)
            ]
            depth, _, best = max(scored)
            if depth > 0:
                return best, 'affinity'
        picked = await _pick_least_loaded(candidates)
        if picked is None and candidates:
            # Every load probe failed this instant but candidates were
            # in rotation — rotate rather than refuse.
            alive = [r for r in candidates if r.in_rotation]
            if alive:
                return _pick_round_robin(alive), 'round_robin'
        return picked, 'least_loaded'

    async def _proxy_once(
        replica: Replica, body: bytes, headers: dict
    ) -> tuple[int, dict, bytes]:
        async with client().post(
            f'{replica.url}/v1/chat/completions',
            data=body,
            headers=headers,
        ) as resp:
            payload = await resp.read()
            return resp.status, dict(resp.headers), payload

    async def chat_completions(request: 'web.Request') -> 'web.Response':
        t_start = time.perf_counter()
        body = await request.read()
        try:
            import json as _json

            messages = _json.loads(body or b'{}').get('messages', [])
        # distlint: disable=swallowed-exception -- an unparseable body is the replica's 400 to issue, not the router's: routing degrades to least-loaded and the request is proxied as-is
        except ValueError:
            messages = []
        chain = (
            prompt_prefix_digests(messages, config.affinity_block_bytes)
            if isinstance(messages, list)
            else []
        )
        fwd_headers = {'Content-Type': 'application/json'}
        inbound_rid = request.headers.get('X-Request-Id')
        if inbound_rid:
            fwd_headers['X-Request-Id'] = inbound_rid

        retried = False
        attempt_exclude: Replica | None = None
        for attempt in range(2):
            replica, decision = await _pick(chain, exclude=attempt_exclude)
            if replica is None:
                break
            try:
                status, up_headers, payload = await _proxy_once(
                    replica, body, fwd_headers
                )
            except (aiohttp.ClientError, asyncio.TimeoutError):
                # The failover contract: the dead replica leaves
                # rotation, the request retries ONCE on a healthy peer
                # (ROUTER_RETRIES counts it), and exhaustion lands in
                # distllm_router_failures_total below.
                replica.mark_dead()
                _publish_states()
                attempt_exclude = replica
                if attempt == 0:
                    retried = True
                    instruments.ROUTER_RETRIES.inc()
                continue
            if status == 503 and replica.state != 'draining':
                # The replica refused because it is going away (drain
                # races the health poll). Nothing was processed — safe
                # to move the request, with the honest retry marker.
                replica.mark_draining()
                affinity.drop(replica.name)
                _publish_states()
                attempt_exclude = replica
                if attempt == 0:
                    retried = True
                    instruments.ROUTER_RETRIES.inc()
                    continue
            instruments.ROUTER_REQUESTS.labels(decision=decision).inc()
            if status == 429:
                # Admission control spoke: propagate untouched (body,
                # Retry-After and all) — never retried elsewhere.
                instruments.ROUTER_UPSTREAM_REJECTIONS.inc()
            else:
                learned = affinity.verify_and_learn(
                    replica.name,
                    chain,
                    up_headers.get(HEADER_DIGEST),
                    up_headers.get(HEADER_DEPTH),
                )
                if learned:
                    instruments.ROUTER_AFFINITY_ENTRIES.set(
                        affinity.entries()
                    )
            out_headers = {
                k: up_headers[k] for k in _RELAY_HEADERS if k in up_headers
            }
            out_headers[HEADER_REPLICA] = replica.name
            if retried:
                out_headers[HEADER_RETRY] = '1'
            instruments.ROUTER_PROXY_SECONDS.observe(
                time.perf_counter() - t_start
            )
            return web.Response(
                status=status, body=payload, headers=out_headers
            )
        instruments.ROUTER_FAILURES.inc()
        instruments.ROUTER_PROXY_SECONDS.observe(
            time.perf_counter() - t_start
        )
        return web.json_response(
            {
                'error': {
                    'message': 'no replica available',
                    'type': 'router_unavailable',
                }
            },
            status=503,
            headers={'Retry-After': '5'},
        )

    async def health(request: 'web.Request') -> 'web.Response':
        healthy = sum(1 for r in replicas if r.in_rotation)
        return web.json_response(
            {
                'status': 'ok' if healthy else 'unavailable',
                'ready': healthy > 0,
                'policy': config.policy,
                'replicas': {r.name: r.state for r in replicas},
                'affinity_entries': affinity.entries(),
            },
            status=200 if healthy else 503,
        )

    async def metrics(request: 'web.Request') -> 'web.Response':
        return web.Response(
            body=render_prometheus().encode('utf-8'),
            headers={
                'Content-Type': 'text/plain; version=0.0.4; charset=utf-8'
            },
        )

    async def _start(app) -> None:
        state['health_task'] = asyncio.create_task(_health_loop())

    async def _stop(app) -> None:
        task = state['health_task']
        if task is not None:
            task.cancel()
            try:
                await task
            # distlint: disable=swallowed-exception -- the cancellation IS the intended outcome of shutdown; nothing degraded
            except asyncio.CancelledError:
                pass
        if state['client'] is not None:
            await state['client'].close()

    app = web.Application()
    app.router.add_post('/v1/chat/completions', chat_completions)
    app.router.add_get('/health', health)
    app.router.add_get('/metrics', metrics)
    app.on_startup.append(_start)
    app.on_cleanup.append(_stop)
    # Exposed for tests/bench: drive rotation state directly.
    app['router_replicas'] = replicas
    app['router_affinity'] = affinity
    app['router_config'] = config
    return app
