"""Gemma / Gemma-2 family decoders over the shared Mistral-family forward.

The reference serves whatever vLLM supports; Gemma is the canonical
TPU-native open-weights family, so it is first-class here (beyond the
reference's own model list, like Mixtral — SURVEY.md §2.3). One forward
implementation serves every family (``models/mistral.py`` — the anti-drift
design the engine relies on); Gemma lands as config knobs there:

- GeGLU MLP (``activation='gelu_new'``, HF ``gelu_pytorch_tanh``);
- embeddings scaled by sqrt(hidden) cast to the compute dtype;
- ``(1 + w)`` RMSNorm parameterization (weights stay HF-byte-identical);
- Gemma-2 additionally: sandwich norms around attention and MLP
  (``post_norms``), attention/final logit softcapping,
  ``query_pre_attn_scalar`` score scaling, and the alternating
  local/global sliding-window pattern (even layers windowed).

Serving: the ragged Pallas paged-attention kernel natively supports
Gemma-2's softcap, ``query_pre_attn_scalar`` scale, and traced per-layer
alternating windows, so backend 'auto' eligibility is purely the head-dim
CI contract (``ops/paged_attention.supports_model``).
"""

from __future__ import annotations

from typing import Literal

import numpy as np

from distllm_tpu.models import common, mistral
from distllm_tpu.models.mistral import MistralConfig


class GemmaConfig(MistralConfig):
    name: Literal['gemma', 'gemma2'] = 'gemma'  # type: ignore[assignment]

    @classmethod
    def from_hf_config(cls, hf: dict) -> 'GemmaConfig':
        """Map an HF ``GemmaConfig``/``Gemma2Config`` dict.

        HF quirks handled: ``hidden_activation`` (gemma2) vs
        ``hidden_act`` (gemma), both ``gelu_pytorch_tanh``; ``head_dim``
        is explicit (256 for most sizes); embeddings are always tied.
        """
        model_type = hf.get('model_type', 'gemma')
        is_v2 = model_type == 'gemma2'
        act = hf.get('hidden_activation') or hf.get('hidden_act', 'gelu_pytorch_tanh')
        query_pre_attn = hf.get('query_pre_attn_scalar')
        return cls(
            name=model_type,
            vocab_size=hf['vocab_size'],
            hidden_size=hf['hidden_size'],
            num_layers=hf['num_hidden_layers'],
            num_heads=hf['num_attention_heads'],
            num_kv_heads=hf.get('num_key_value_heads', hf['num_attention_heads']),
            head_dim=hf.get('head_dim'),
            intermediate_size=hf['intermediate_size'],
            max_position_embeddings=hf.get('max_position_embeddings', 8192),
            rope_theta=hf.get('rope_theta', 10000.0),
            rms_norm_eps=hf.get('rms_norm_eps', 1e-6),
            tie_word_embeddings=hf.get('tie_word_embeddings', True),
            activation={
                'gelu_pytorch_tanh': 'gelu_new',
                'gelu': 'gelu_new',  # HF Gemma aliases plain gelu to tanh
            }.get(act, act),
            embedding_multiplier=hf['hidden_size'] ** 0.5,
            norm_plus_one=True,
            post_norms=is_v2,
            query_scale=(
                query_pre_attn ** -0.5 if is_v2 and query_pre_attn else None
            ),
            attn_logit_softcap=hf.get('attn_logit_softcapping') if is_v2 else None,
            final_logit_softcap=hf.get('final_logit_softcapping') if is_v2 else None,
            sliding_window=hf.get('sliding_window') if is_v2 else None,
            sliding_window_pattern='alternating' if is_v2 else 'all',
        )


# The engine and embed pipeline drive families through these entry points;
# Gemma's behavior differences live entirely in the config knobs above.
init = mistral.init
init_on_device = mistral.init_on_device
apply = mistral.apply
logits = mistral.logits
prefill = mistral.prefill
decode_step = mistral.decode_step
decode_loop = mistral.decode_loop
param_specs = mistral.param_specs


def params_from_hf(state: dict[str, np.ndarray], cfg: GemmaConfig) -> dict:
    """Convert HF ``GemmaForCausalLM`` / ``Gemma2ForCausalLM`` weights.

    Norm-name mapping (the trap is ``post_attention_layernorm``):

    - gemma:  ``input_layernorm`` → ``attn_ln``,
      ``post_attention_layernorm`` → ``mlp_ln`` (the standard Llama
      pre-MLP meaning);
    - gemma2: ``input_layernorm`` → ``attn_ln``,
      ``post_attention_layernorm`` → ``post_attn_ln`` (a TRUE post-norm),
      ``pre_feedforward_layernorm`` → ``mlp_ln``,
      ``post_feedforward_layernorm`` → ``post_mlp_ln``.
    """
    sd = {k.removeprefix('model.'): v for k, v in state.items()}

    def lin(key):
        return {'kernel': np.ascontiguousarray(sd[key].T)}

    layers = []
    for i in range(cfg.num_layers):
        p = f'layers.{i}'
        lp = {
            'q': lin(f'{p}.self_attn.q_proj.weight'),
            'k': lin(f'{p}.self_attn.k_proj.weight'),
            'v': lin(f'{p}.self_attn.v_proj.weight'),
            'o': lin(f'{p}.self_attn.o_proj.weight'),
            'attn_ln': {'scale': sd[f'{p}.input_layernorm.weight']},
            'gate': lin(f'{p}.mlp.gate_proj.weight'),
            'up': lin(f'{p}.mlp.up_proj.weight'),
            'down': lin(f'{p}.mlp.down_proj.weight'),
        }
        if cfg.post_norms:
            lp['post_attn_ln'] = {
                'scale': sd[f'{p}.post_attention_layernorm.weight']
            }
            lp['mlp_ln'] = {
                'scale': sd[f'{p}.pre_feedforward_layernorm.weight']
            }
            lp['post_mlp_ln'] = {
                'scale': sd[f'{p}.post_feedforward_layernorm.weight']
            }
        else:
            lp['mlp_ln'] = {
                'scale': sd[f'{p}.post_attention_layernorm.weight']
            }
        layers.append(lp)
    return {
        'embed': sd['embed_tokens.weight'],
        'layers': common.stack_layers(layers),
        'final_ln': {'scale': sd['norm.weight']},
    }
