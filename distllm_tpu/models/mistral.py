"""Mistral/Llama/Qwen2-family decoder (SFR-Embedding-Mistral,
Mistral-7B-Instruct; Qwen2 = same architecture + Q/K/V biases).

One implementation serves both reference roles:

- the 7B *embedding* model path (``distllm/embed/encoders/auto.py`` with
  last-token pooling, SURVEY.md section 2.2) via :func:`apply`;
- the *generation* path (vLLM-backed in the reference,
  ``generate/generators/vllm_backend.py``) via :func:`prefill` +
  :func:`decode_step`, which the paged-KV engine drives.

Functional JAX, stacked-layer ``lax.scan``, GQA, RoPE, RMSNorm, SwiGLU; TP
sharding specs over the ``model`` mesh axis (attention heads and MLP width),
matching what the reference delegates to vLLM's ``tensor_parallel_size``.
"""

from __future__ import annotations

from typing import Literal

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distllm_tpu.models import common
from distllm_tpu.utils import BaseConfig


class MistralConfig(BaseConfig):
    name: Literal['mistral'] = 'mistral'
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int | None = None
    intermediate_size: int = 14336
    max_position_embeddings: int = 32768
    rope_theta: float = 10000.0
    # HF rope_scaling dict (Llama-3 'llama3' banding, 'linear') — applied
    # in the RoPE tables; unknown types raise rather than silently
    # mis-position long contexts.
    rope_scaling: dict | None = None
    rms_norm_eps: float = 1e-5
    sliding_window: int | None = None
    tie_word_embeddings: bool = False
    # Qwen2-family checkpoints (same architecture + Q/K/V projection
    # biases; HF Qwen2Model always has them, MistralModel never does).
    attention_bias: bool = False
    # --- Gemma-family knobs (models/gemma.py sets these; defaults keep
    # every existing family bit-identical). ---
    activation: str = 'silu'  # MLP gate activation (gemma: 'gelu_new')
    embedding_multiplier: float | None = None  # gemma: sqrt(hidden_size)
    norm_plus_one: bool = False  # gemma RMSNorm computes (1 + w)
    post_norms: bool = False  # gemma2 sandwich norms around attn + MLP
    query_scale: float | None = None  # gemma2 query_pre_attn_scalar**-0.5
    attn_logit_softcap: float | None = None  # gemma2 tanh cap on scores
    final_logit_softcap: float | None = None  # gemma2 tanh cap on logits
    # 'all' = every layer uses cfg.sliding_window (Mistral semantics);
    # 'alternating' = gemma2's even-layer-local / odd-layer-global split.
    sliding_window_pattern: Literal['all', 'alternating'] = 'all'
    # Quantized-matmul tier pinned for every dense() in the forward; None
    # reads the process default at trace time. The engine resolves this
    # ONCE at construction (after its TP-mesh compatibility check) so a
    # later process-global change cannot re-route serving dispatches.
    qmm_backend: str | None = None
    dtype: str = 'bfloat16'

    @property
    def head_size(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @classmethod
    def from_hf_config(cls, hf: dict) -> 'MistralConfig':
        return cls(
            vocab_size=hf['vocab_size'],
            hidden_size=hf['hidden_size'],
            num_layers=hf['num_hidden_layers'],
            num_heads=hf['num_attention_heads'],
            num_kv_heads=hf.get('num_key_value_heads', hf['num_attention_heads']),
            head_dim=hf.get('head_dim'),
            intermediate_size=hf['intermediate_size'],
            max_position_embeddings=hf.get('max_position_embeddings', 32768),
            rope_theta=hf.get('rope_theta', 10000.0),
            rope_scaling=hf.get('rope_scaling'),
            rms_norm_eps=hf.get('rms_norm_eps', 1e-5),
            # Qwen2 config.json carries sliding_window even when
            # use_sliding_window is false — honor the switch (Mistral
            # configs have no switch; absent means enabled-if-set).
            sliding_window=(
                hf.get('sliding_window')
                if hf.get('use_sliding_window', True)
                else None
            ),
            tie_word_embeddings=hf.get('tie_word_embeddings', False),
            attention_bias=hf.get(
                'attention_bias', hf.get('model_type') == 'qwen2'
            ),
        )


def init(rng: jax.Array, cfg: MistralConfig) -> dict:
    h = cfg.hidden_size
    hd = cfg.head_size
    q_out = cfg.num_heads * hd
    kv_out = cfg.num_kv_heads * hd
    i = cfg.intermediate_size
    scale = 0.02

    def normal(key, shape):
        return np.asarray(jax.random.normal(key, shape) * scale, np.float32)

    keys = jax.random.split(rng, 3)
    layers = []
    for li in range(cfg.num_layers):
        ks = jax.random.split(jax.random.fold_in(keys[0], li), 10)

        def proj(kkey, bkey, shape):
            out = {'kernel': normal(kkey, shape)}
            if cfg.attention_bias:
                out['bias'] = normal(bkey, (shape[-1],))
            return out

        # Gemma's (1+w) norms are identity at w=0; others at w=1.
        ln_init = 0.0 if cfg.norm_plus_one else 1.0
        lp = {
            'q': proj(ks[0], ks[7], (h, q_out)),
            'k': proj(ks[1], ks[8], (h, kv_out)),
            'v': proj(ks[2], ks[9], (h, kv_out)),
            'o': {'kernel': normal(ks[3], (q_out, h))},
            'attn_ln': {'scale': np.full((h,), ln_init, np.float32)},
            'gate': {'kernel': normal(ks[4], (h, i))},
            'up': {'kernel': normal(ks[5], (h, i))},
            'down': {'kernel': normal(ks[6], (i, h))},
            'mlp_ln': {'scale': np.full((h,), ln_init, np.float32)},
        }
        if cfg.post_norms:
            lp['post_attn_ln'] = {'scale': np.full((h,), ln_init, np.float32)}
            lp['post_mlp_ln'] = {'scale': np.full((h,), ln_init, np.float32)}
        layers.append(lp)
    params = {
        'embed': normal(keys[1], (cfg.vocab_size, h)),
        'layers': common.stack_layers(layers),
        'final_ln': {
            'scale': np.full(
                (h,), 0.0 if cfg.norm_plus_one else 1.0, np.float32
            )
        },
    }
    if not cfg.tie_word_embeddings:
        params['lm_head'] = normal(keys[2], (h, cfg.vocab_size))
    return params


def init_on_device(rng: jax.Array, cfg: MistralConfig) -> dict:
    """Random params generated directly on device in ``cfg.dtype``.

    ``init`` materialises fp32 numpy on host (fine for test-sized models,
    and the fp32 master copy is what ``params_from_hf`` produces too); at
    7B dims that is 29 GB and cannot live in a 16 GB chip's HBM.  Serving
    only ever reads the weights in ``cfg.dtype``, so for benchmarks we
    generate the stacked layer tree straight on device in that dtype —
    one RNG call per parameter *kind* (leading L axis), never per layer.
    """
    h = cfg.hidden_size
    hd = cfg.head_size
    q_out = cfg.num_heads * hd
    kv_out = cfg.num_kv_heads * hd
    i = cfg.intermediate_size
    L = cfg.num_layers
    dtype = jnp.dtype(cfg.dtype)
    scale = 0.02

    keys = jax.random.split(rng, 12)

    @jax.jit
    def build():
        def normal(key, shape):
            return jax.random.normal(key, shape, dtype=jnp.float32).astype(
                dtype
            ) * scale

        def proj(kkey, bkey, shape):
            out = {'kernel': normal(kkey, shape)}
            if cfg.attention_bias:
                out['bias'] = normal(bkey, (L, shape[-1]))
            return out

        ln_init = 0.0 if cfg.norm_plus_one else 1.0
        params = {
            'embed': normal(keys[0], (cfg.vocab_size, h)),
            'layers': {
                'q': proj(keys[1], keys[9], (L, h, q_out)),
                'k': proj(keys[2], keys[10], (L, h, kv_out)),
                'v': proj(keys[3], keys[11], (L, h, kv_out)),
                'o': {'kernel': normal(keys[4], (L, q_out, h))},
                'attn_ln': {'scale': jnp.full((L, h), ln_init, dtype)},
                'gate': {'kernel': normal(keys[5], (L, h, i))},
                'up': {'kernel': normal(keys[6], (L, h, i))},
                'down': {'kernel': normal(keys[7], (L, i, h))},
                'mlp_ln': {'scale': jnp.full((L, h), ln_init, dtype)},
            },
            'final_ln': {'scale': jnp.full((h,), ln_init, dtype)},
        }
        if cfg.post_norms:
            params['layers']['post_attn_ln'] = {
                'scale': jnp.full((L, h), ln_init, dtype)
            }
            params['layers']['post_mlp_ln'] = {
                'scale': jnp.full((L, h), ln_init, dtype)
            }
        if not cfg.tie_word_embeddings:
            params['lm_head'] = normal(keys[8], (h, cfg.vocab_size))
        return params

    return build()


def _mlp_block(normed: jnp.ndarray, lp: dict, cfg) -> jnp.ndarray:
    """Per-layer MLP: dense SwiGLU, or the Mixtral MoE bank when the layer
    carries a router (pytree STRUCTURE is static under jit, so this
    branch costs nothing at trace time). One home for the block lets the
    whole serving machinery — prefill, rolled/unrolled paged decode —
    serve both families (the reference's vLLM serves Mistral and Mixtral
    through one engine too)."""
    if 'router' in lp:
        from distllm_tpu.models.mixtral import moe_mlp

        batched = normed[:, None] if normed.ndim == 2 else normed
        out = moe_mlp(
            batched,
            lp['router']['kernel'],
            lp['gate']['kernel'],
            lp['up']['kernel'],
            lp['down']['kernel'],
            # Router present => the config is MoE; a missing field must
            # raise, not silently route top-2.
            cfg.experts_per_token,
        )
        return out[:, 0] if normed.ndim == 2 else out
    act = common.ACTIVATIONS[getattr(cfg, 'activation', 'silu')]
    qb = getattr(cfg, 'qmm_backend', None)
    return common.dense(
        act(common.dense(normed, lp['gate']['kernel'], qmm_backend=qb))
        * common.dense(normed, lp['up']['kernel'], qmm_backend=qb),
        lp['down']['kernel'],
        qmm_backend=qb,
    )


def _rope_tables(cfg: MistralConfig, max_len: int):
    cos, sin = common.rope_frequencies(
        cfg.head_size, max_len, cfg.rope_theta,
        getattr(cfg, 'rope_scaling', None),
    )
    return jnp.asarray(cos), jnp.asarray(sin)


def _norm(x: jnp.ndarray, scale: jnp.ndarray, cfg) -> jnp.ndarray:
    return common.rms_norm(
        x, scale, cfg.rms_norm_eps,
        plus_one=getattr(cfg, 'norm_plus_one', False),
    )


def _embed_tokens(params: dict, cfg, input_ids: jnp.ndarray) -> jnp.ndarray:
    dtype = jnp.dtype(cfg.dtype)
    x = jnp.asarray(params['embed'])[input_ids].astype(dtype)
    if getattr(cfg, 'embedding_multiplier', None) is not None:
        # Gemma scales embeddings by sqrt(hidden) CAST TO THE COMPUTE
        # DTYPE (HF casts the normalizer tensor); matching the rounding
        # keeps bf16 goldens exact.
        x = x * jnp.asarray(cfg.embedding_multiplier, dtype)
    return x


def _layer_window_flags(cfg) -> jnp.ndarray:
    """Per-layer bool [L]: does layer i use the sliding window?
    (gemma2 'alternating': even layers local, odd layers global)."""
    return jnp.arange(cfg.num_layers) % 2 == 0


def _kv_layer(cache, li):
    """Layer ``li``'s slice of a stacked KV cache. ``jax.tree.map`` keeps
    the emitted HLO identical for bare arrays while slicing every member
    of an int8 ``QuantizedKV`` (data AND its per-block scales) in one
    expression — the layer scans stay dtype-agnostic."""
    return jax.tree.map(
        lambda c: jax.lax.dynamic_index_in_dim(c, li, 0, keepdims=False),
        cache,
    )


def _kv_layer_update(cache, cache_l, li):
    """Write a per-layer KV slice back into the stacked cache (the
    :func:`_kv_layer` inverse, same bare-array/``QuantizedKV`` duality)."""
    return jax.tree.map(
        lambda c, cl: jax.lax.dynamic_update_index_in_dim(c, cl, li, 0),
        cache,
        cache_l,
    )


def _attn_mask(attention_mask: jnp.ndarray, cfg: MistralConfig) -> jnp.ndarray:
    """Causal x key-validity boolean mask ``[B, 1, S, S]`` (+ sliding window)."""
    seq = attention_mask.shape[1]
    causal = common.causal_mask(seq, seq)
    if cfg.sliding_window is not None:
        q_pos = jnp.arange(seq)[:, None]
        kv_pos = jnp.arange(seq)[None, :]
        causal = causal & (kv_pos > q_pos - cfg.sliding_window)
    return causal[None, None] & attention_mask[:, None, None, :].astype(bool)


def apply(  # distlint: traced
    params: dict,
    cfg: MistralConfig,
    input_ids: jnp.ndarray,
    attention_mask: jnp.ndarray,
    *,
    mesh=None,
    seq_parallel: str | None = None,
) -> jnp.ndarray:
    """Dense causal forward: ``[B, S]`` → last hidden states ``[B, S, H]``.

    ``seq_parallel`` (``'ring'`` or ``'ulysses'``) activates sequence/context
    parallelism over ``mesh``'s ``seq`` axis: activations stay sharded
    ``S/P`` per chip and attention runs as ring ppermutes / all-to-alls
    (``distllm_tpu.ops.ring_attention``) — the long-context capability the
    reference lacks entirely (it truncates, ``auto.py:74``; SURVEY.md §5).
    """
    hidden, _, _ = _forward(
        params, cfg, input_ids, attention_mask, collect_kv=False,
        mesh=mesh, seq_parallel=seq_parallel,
    )
    return hidden


def prefill(  # distlint: traced
    params: dict,
    cfg: MistralConfig,
    input_ids: jnp.ndarray,
    attention_mask: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Forward that also returns per-layer K/V ``[L, B, S, N_kv, Hd]``."""
    return _forward(params, cfg, input_ids, attention_mask, collect_kv=True)


def prefill_paged(  # distlint: traced
    params: dict,
    cfg: MistralConfig,
    input_ids: jnp.ndarray,  # [B, S] uncached tail tokens (padded)
    positions: jnp.ndarray,  # [B, S] absolute position of each tail token
    k_cache: jnp.ndarray,  # [L, num_blocks, block_size, N_kv, Hd]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks]
    context_lens: jnp.ndarray,  # [B] total valid tokens incl. this tail
    tail_lens: jnp.ndarray,  # [B] valid tokens in input_ids (0 = pad row)
    max_table_positions: int | None = None,
    all_logits: bool = False,
    attn_backend: str = 'xla',
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Prefill an UNCACHED TAIL against KV history already in the paged
    cache — the prefix-cache hit / chunked-prefill forward
    (docs/prefix_caching.md).

    Unlike :func:`prefill` (whole prompt, K/V returned for one batched
    scatter afterwards), the caches ride the layer scan: each layer writes
    its tail K/V into its cache plane FIRST, then the tail queries attend
    over the paged cache — cached prefix and own chunk together — via
    :func:`~distllm_tpu.ops.paged_attention.ragged_paged_attention`
    (``q_lens=tail_lens`` — the rows are ragged per-row query spans;
    ``attn_backend`` selects the XLA baseline or the fused Pallas kernel,
    resolved once by the engine at construction). Returns
    ``(last_logits [B, V] fp32, k_cache, v_cache)`` where ``last_logits``
    is sampled at each row's last valid tail position. Positions at or
    past ``tail_lens`` (padding) write to trash block 0 and their logits
    are garbage the caller discards.

    ``all_logits=True`` (speculative verification, :func:`spec_window`)
    returns logits at EVERY span position — ``[B, S, V]`` — instead of
    only the last one; the forward pass itself is unchanged, so the
    verify dispatch shares every numeric property of this path (the
    greedy-identity backbone of docs/speculative.md).
    """
    from distllm_tpu.ops.paged_attention import (
        ragged_paged_attention,
        write_chunk_kv,
    )

    b, s = input_ids.shape
    table_len = max_table_positions or cfg.max_position_embeddings
    cos, sin = _rope_tables(cfg, table_len)
    alternating = (
        getattr(cfg, 'sliding_window_pattern', 'all') == 'alternating'
    )
    layer_windows = jnp.where(
        _layer_window_flags(cfg), cfg.sliding_window or 0, 0
    ).astype(jnp.int32)
    valid = jnp.arange(s)[None, :] < tail_lens[:, None]  # [B, S]
    x = _embed_tokens(params, cfg, input_ids)  # [B, S, H]
    qb = getattr(cfg, 'qmm_backend', None)

    def layer(carry, xs):
        x, k_cache, v_cache = carry
        lp, li, window_l = xs
        k_cache_l = _kv_layer(k_cache, li)
        v_cache_l = _kv_layer(v_cache, li)
        normed = _norm(x, lp['attn_ln']['scale'], cfg)
        q = common.split_heads(
            common.dense(
                normed, lp['q']['kernel'], lp['q'].get('bias'), qmm_backend=qb
            ),
            cfg.num_heads,
        )
        k = common.split_heads(
            common.dense(
                normed, lp['k']['kernel'], lp['k'].get('bias'), qmm_backend=qb
            ),
            cfg.num_kv_heads,
        )
        v = common.split_heads(
            common.dense(
                normed, lp['v']['kernel'], lp['v'].get('bias'), qmm_backend=qb
            ),
            cfg.num_kv_heads,
        )
        q = common.apply_rope(q, cos, sin, positions)
        k = common.apply_rope(k, cos, sin, positions)
        # Write the tail's K/V first, then attend over the paged cache —
        # cached prefix and own chunk through one gather (decode's
        # write-then-attend order, generalized to S queries).
        k_cache_l, v_cache_l = write_chunk_kv(
            k_cache_l, v_cache_l, k, v, block_tables, positions, valid
        )
        # q_lens masks PADDING queries (XLA: onto key 0; Pallas: to exact
        # zeros): under a sliding window a pad query past the window's
        # reach otherwise has an all-masked score row -> NaN attention ->
        # NaN K/V written to the TRASH block -> every later dispatch
        # whose block-table padding gathers block 0 poisons its softmax·V
        # contraction (0 x NaN = NaN). Valid rows are bit-identical with
        # or without the mask.
        attn = ragged_paged_attention(
            q, k_cache_l, v_cache_l, block_tables, context_lens, positions,
            q_lens=tail_lens,
            sliding_window=(
                window_l if alternating else cfg.sliding_window
            ),
            scale=getattr(cfg, 'query_scale', None),
            logit_softcap=getattr(cfg, 'attn_logit_softcap', None),
            backend=attn_backend,
        )
        attn_out = common.dense(
            common.merge_heads(attn), lp['o']['kernel'], qmm_backend=qb
        )
        if getattr(cfg, 'post_norms', False):
            attn_out = _norm(attn_out, lp['post_attn_ln']['scale'], cfg)
        x = x + attn_out
        normed2 = _norm(x, lp['mlp_ln']['scale'], cfg)
        mlp = _mlp_block(normed2, lp, cfg)
        if getattr(cfg, 'post_norms', False):
            mlp = _norm(mlp, lp['post_mlp_ln']['scale'], cfg)
        k_cache = _kv_layer_update(k_cache, k_cache_l, li)
        v_cache = _kv_layer_update(v_cache, v_cache_l, li)
        return (x + mlp, k_cache, v_cache), None

    (x, k_cache, v_cache), _ = jax.lax.scan(
        layer,
        (x, k_cache, v_cache),
        (
            params['layers'],
            jnp.arange(cfg.num_layers, dtype=jnp.int32),
            layer_windows,
        ),
    )
    hidden = _norm(x, params['final_ln']['scale'], cfg)
    if all_logits:
        # Speculative verification needs every span position's logits;
        # spans are short (1 + draft_k), so [B, S, V] stays small.
        return logits(params, cfg, hidden), k_cache, v_cache
    # Only each row's last valid tail position feeds the lm_head ([B, S, V]
    # logits would waste MXU time and HBM — same policy as prefill).
    last_idx = jnp.maximum(tail_lens - 1, 0)
    last_hidden = jnp.take_along_axis(hidden, last_idx[:, None, None], axis=1)
    return logits(params, cfg, last_hidden)[:, 0], k_cache, v_cache


def _forward(
    params, cfg, input_ids, attention_mask, *, collect_kv,
    mesh=None, seq_parallel=None,
):
    b, s = input_ids.shape
    cos, sin = _rope_tables(cfg, s)
    x = _embed_tokens(params, cfg, input_ids)
    use_sp = (
        seq_parallel is not None
        and mesh is not None
        and mesh.shape.get('seq', 1) > 1
    )
    if use_sp and cfg.sliding_window is not None:
        raise NotImplementedError(
            'sequence parallelism with sliding-window attention'
        )
    if use_sp and getattr(cfg, 'attn_logit_softcap', None) is not None:
        raise NotImplementedError(
            'sequence parallelism with attention logit softcapping'
        )
    alternating = (
        getattr(cfg, 'sliding_window_pattern', 'all') == 'alternating'
    )
    if alternating and not use_sp:
        # Per-layer mask choice (gemma2): global causal for odd layers,
        # windowed for even — both built once, selected per scan step.
        full_mask = _attn_mask(
            attention_mask, cfg.model_copy(update={'sliding_window': None})
        )
        win_mask = _attn_mask(attention_mask, cfg)
        mask = full_mask
    else:
        mask = None if use_sp else _attn_mask(attention_mask, cfg)
    positions = None  # prefill positions are 0..S-1 per row

    def layer(x, xs):
        lp, win_flag = xs
        if alternating and not use_sp:
            mask_l = jnp.where(win_flag, win_mask, full_mask)
        else:
            mask_l = mask
        normed = _norm(x, lp['attn_ln']['scale'], cfg)
        qb = getattr(cfg, 'qmm_backend', None)
        q = common.split_heads(
            common.dense(
                normed, lp['q']['kernel'], lp['q'].get('bias'), qmm_backend=qb
            ),
            cfg.num_heads,
        )
        k = common.split_heads(
            common.dense(
                normed, lp['k']['kernel'], lp['k'].get('bias'), qmm_backend=qb
            ),
            cfg.num_kv_heads,
        )
        v = common.split_heads(
            common.dense(
                normed, lp['v']['kernel'], lp['v'].get('bias'), qmm_backend=qb
            ),
            cfg.num_kv_heads,
        )
        q = common.apply_rope(q, cos, sin, positions)
        k = common.apply_rope(k, cos, sin, positions)
        if use_sp:
            from distllm_tpu.ops.ring_attention import (
                ring_attention,
                ulysses_attention,
            )

            sp_fn = ring_attention if seq_parallel == 'ring' else ulysses_attention
            n_rep = cfg.num_heads // cfg.num_kv_heads
            attn = sp_fn(
                q,
                common.repeat_kv(k, n_rep),
                common.repeat_kv(v, n_rep),
                mesh,
                kv_mask=attention_mask,
                causal=True,
            )
        else:
            # GQA handled natively by the fused attention (no KV
            # materialization).
            attn = common.sdpa(
                q, k, v, mask=mask_l,
                scale=getattr(cfg, 'query_scale', None),
                logit_softcap=getattr(cfg, 'attn_logit_softcap', None),
            )
        attn_out = common.dense(
            common.merge_heads(attn), lp['o']['kernel'], qmm_backend=qb
        )
        if getattr(cfg, 'post_norms', False):
            attn_out = _norm(attn_out, lp['post_attn_ln']['scale'], cfg)
        x = x + attn_out
        normed2 = _norm(x, lp['mlp_ln']['scale'], cfg)
        mlp = _mlp_block(normed2, lp, cfg)
        if getattr(cfg, 'post_norms', False):
            mlp = _norm(mlp, lp['post_mlp_ln']['scale'], cfg)
        x = x + mlp
        return x, (k, v) if collect_kv else None

    x, kv = jax.lax.scan(
        layer, x, (params['layers'], _layer_window_flags(cfg))
    )
    hidden = _norm(x, params['final_ln']['scale'], cfg)
    if collect_kv:
        return hidden, kv[0], kv[1]
    return hidden, None, None


def _decode_core(
    params: dict,
    cfg: MistralConfig,
    input_ids: jnp.ndarray,  # [B]
    positions: jnp.ndarray,  # [B]
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks]
    context_lens: jnp.ndarray,  # [B]
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    attn_backend: str,
    layer_unroll: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One decode step's compute, RoPE tables passed in (so a multi-step
    scan hoists them out of the loop).

    ``layer_unroll=True`` unrolls the layer scan. Decode is weight-
    bandwidth bound, and the rolled scan's per-iteration dynamic-slice of
    the stacked MLP kernels is MATERIALIZED by XLA as a ~0.35 GB/layer
    temp (read slab + write temp + read temp ≈ 3x traffic on 78% of the
    weights — found via AOT HLO census, scripts/probe_decode_hlo.py,
    matching the measured ~3x gap to the weight-streaming roofline in
    BENCH_NOTES_r03.md). Unrolling turns those into static slices that
    fold into the matmuls. Prefill keeps the rolled scan: compute-bound,
    and the slice traffic amortizes over the whole token batch.
    """
    from distllm_tpu.ops.paged_attention import (
        paged_attention_xla,
        ragged_paged_attention_pallas,
        write_token_kv,
    )

    alternating = (
        getattr(cfg, 'sliding_window_pattern', 'all') == 'alternating'
    )

    if attn_backend == 'xla':

        def attend(q, k_cache_l, v_cache_l, window_l):
            return paged_attention_xla(
                q, k_cache_l, v_cache_l, block_tables, context_lens,
                # Traced per-layer window only for the alternating pattern;
                # other families keep the static value so their decode HLO
                # is unchanged.
                sliding_window=window_l if alternating else cfg.sliding_window,
                scale=getattr(cfg, 'query_scale', None),
                logit_softcap=getattr(cfg, 'attn_logit_softcap', None),
            )
    else:
        # A decode row is the ragged kernel's span-1 degenerate case: one
        # query at the token's own position over the whole context. The
        # kernel natively handles softcap / traced per-layer windows /
        # custom scales, so every model family serves through it.
        def attend(q, k_cache_l, v_cache_l, window_l):
            return ragged_paged_attention_pallas(
                q[:, None], k_cache_l, v_cache_l, block_tables,
                context_lens, q_positions=positions[:, None],
                sliding_window=window_l if alternating else cfg.sliding_window,
                scale=getattr(cfg, 'query_scale', None),
                logit_softcap=getattr(cfg, 'attn_logit_softcap', None),
                interpret=attn_backend == 'interpret',
            )[:, 0]

    # int32 [L] per-layer windows (0 = global) riding the layer scan; only
    # consulted when `alternating`.
    layer_windows = jnp.where(
        _layer_window_flags(cfg), cfg.sliding_window or 0, 0
    ).astype(jnp.int32)

    x = _embed_tokens(params, cfg, input_ids)  # [B, H]

    # The FULL caches ride the scan carry and each layer dynamic-update-
    # slices its own [num_blocks, bs, Nkv, Hd] plane in place. Rolled
    # (layer_unroll=False): XLA aliases while-loop carries, so no second
    # cache copy is ever materialized. Unrolled: the same DUS chain sits in
    # straight-line code, where in-place updates rely on XLA's buffer
    # reuse instead of carry aliasing — tests/test_aot_tpu.py asserts the
    # unrolled window's temp budget stays cache-copy-free so a missed
    # reuse cannot land silently. (Scanning the caches as xs/ys instead
    # allocates a full stacked output buffer: +1 GB at 7B dims, and one
    # more when a multi-step window scan wraps this — that overflowed the
    # v5e's 16 GB HBM.)
    qb = getattr(cfg, 'qmm_backend', None)

    def layer(carry, xs):
        x, k_cache, v_cache = carry
        lp, li, window_l = xs
        k_cache_l = _kv_layer(k_cache, li)
        v_cache_l = _kv_layer(v_cache, li)
        normed = _norm(x, lp['attn_ln']['scale'], cfg)
        q = common.dense(
            normed, lp['q']['kernel'], lp['q'].get('bias'), qmm_backend=qb
        ).reshape(-1, cfg.num_heads, cfg.head_size)
        k = common.dense(
            normed, lp['k']['kernel'], lp['k'].get('bias'), qmm_backend=qb
        ).reshape(-1, cfg.num_kv_heads, cfg.head_size)
        v = common.dense(
            normed, lp['v']['kernel'], lp['v'].get('bias'), qmm_backend=qb
        ).reshape(-1, cfg.num_kv_heads, cfg.head_size)
        # RoPE at each sequence's own position ([B, 1, N, Hd] view).
        q = common.apply_rope(q[:, None], cos, sin, positions[:, None])[:, 0]
        k = common.apply_rope(k[:, None], cos, sin, positions[:, None])[:, 0]
        k_cache_l, v_cache_l = write_token_kv(
            k_cache_l, v_cache_l, k, v, block_tables, positions
        )
        attn = attend(q, k_cache_l, v_cache_l, window_l)
        attn_out = common.dense(
            attn.reshape(-1, cfg.num_heads * cfg.head_size),
            lp['o']['kernel'],
            qmm_backend=qb,
        )
        if getattr(cfg, 'post_norms', False):
            attn_out = _norm(attn_out, lp['post_attn_ln']['scale'], cfg)
        x = x + attn_out
        normed2 = _norm(x, lp['mlp_ln']['scale'], cfg)
        mlp = _mlp_block(normed2, lp, cfg)
        if getattr(cfg, 'post_norms', False):
            mlp = _norm(mlp, lp['post_mlp_ln']['scale'], cfg)
        k_cache = _kv_layer_update(k_cache, k_cache_l, li)
        v_cache = _kv_layer_update(v_cache, v_cache_l, li)
        return (x + mlp, k_cache, v_cache), None

    (x, k_cache, v_cache), _ = jax.lax.scan(
        layer,
        (x, k_cache, v_cache),
        (
            params['layers'],
            jnp.arange(cfg.num_layers, dtype=jnp.int32),
            layer_windows,
        ),
        unroll=cfg.num_layers if layer_unroll else 1,
    )
    hidden = _norm(x, params['final_ln']['scale'], cfg)
    return logits(params, cfg, hidden), k_cache, v_cache


def decode_step(  # distlint: traced
    params: dict,
    cfg: MistralConfig,
    input_ids: jnp.ndarray,  # [B] one new token per sequence
    positions: jnp.ndarray,  # [B] 0-based index of that token
    k_cache: jnp.ndarray,  # [L, num_blocks, block_size, N_kv, Hd]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks]
    context_lens: jnp.ndarray,  # [B] valid tokens incl. the new one
    attn_backend: str = 'xla',
    layer_unroll: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Single-token decode over the paged KV cache.

    Returns ``(logits [B, V] fp32, k_cache, v_cache)`` with the new token's
    K/V written into the paged blocks. Inactive batch slots should point
    their block table rows at the reserved trash block 0.

    ``attn_backend`` selects the XLA gather baseline or the fused ragged
    Pallas kernel (span-1 degenerate case; 'interpret' runs the same
    kernel on the Pallas interpreter). All backends support sliding
    windows, gemma2 alternating layers, softcap, and custom scales.
    """
    cos, sin = _rope_tables(cfg, cfg.max_position_embeddings)
    return _decode_core(
        params, cfg, input_ids, positions, k_cache, v_cache, block_tables,
        context_lens, cos, sin, attn_backend, layer_unroll,
    )


def decode_loop(  # distlint: traced
    params: dict,
    cfg: MistralConfig,
    input_ids: jnp.ndarray,  # [B] last emitted token per slot
    positions: jnp.ndarray,  # [B] 0-based index of that token
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks] — covers +num_steps tokens
    context_lens: jnp.ndarray,  # [B] valid tokens incl. the input token
    steps_left: jnp.ndarray,  # [B] int32 — tokens this slot may emit now
    temperature: jnp.ndarray,  # [B]
    top_p: jnp.ndarray,  # [B]
    min_p: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B] int32 (0 disables)
    seeds: jnp.ndarray,  # [B] uint32 per-request sampling seeds
    num_steps: int,
    attn_backend: str = 'xla',
    max_table_positions: int | None = None,
    sampling_top_window: int = 0,
    layer_unroll: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """``num_steps`` fused decode+sample steps in ONE dispatch.

    The TPU-first answer to the reference's per-token GPU decode loop
    (vLLM inside ``generate/generators/vllm_backend.py``): on this
    environment a host↔device round trip costs ~68 ms (measured,
    ``scripts/probe_bw.py``), so the engine generates a *window* of tokens
    per dispatch — each step's sampled token feeds the next step's input
    entirely on device, and only the ``[num_steps, B]`` token block travels
    to host (asynchronously, once per window).

    Per-slot ``steps_left`` masks slots that run out of budget mid-window
    (max_tokens / max_model_len): their KV writes are routed to the
    reserved trash block 0 and their later tokens are garbage the host
    discards. The scheduler must have reserved blocks for ``min(num_steps,
    steps_left)`` extra tokens per slot.

    Returns ``(tokens [num_steps, B] int32, k_cache, v_cache, last_ids)``.
    """
    from distllm_tpu.ops.sampling import fold_row_keys, sample_tokens

    # RoPE tables bounded by what positions can actually reach: the block
    # table row covers max_table_positions tokens (engine max_model_len) —
    # far smaller than the checkpoint's 32k max_position_embeddings.
    table_len = max_table_positions or cfg.max_position_embeddings
    cos, sin = _rope_tables(cfg, table_len)

    def body(carry, _):
        ids, pos, ctx, k_cache, v_cache, live_steps = carry
        live = live_steps > 0
        # Out-of-budget slots write to the trash block (row of zeros) and
        # stop advancing; their sampled tokens are discarded host-side.
        bt_eff = jnp.where(live[:, None], block_tables, 0)
        logits_, k_cache, v_cache = _decode_core(
            params, cfg, ids, pos, k_cache, v_cache, bt_eff, ctx,
            cos, sin, attn_backend, layer_unroll,
        )
        # Counter-derived per-row keys: the token produced this step sits
        # at absolute index pos + 1 (frozen slots repeat a key, but their
        # tokens are discarded host-side anyway).
        row_keys = fold_row_keys(seeds, pos + 1)
        token = sample_tokens(
            logits_, None, temperature, top_p, min_p,
            top_window=sampling_top_window, top_k=top_k, row_keys=row_keys,
        )
        ids = jnp.where(live, token, ids)
        pos = jnp.where(live, pos + 1, pos)
        ctx = jnp.where(live, ctx + 1, ctx)
        return (ids, pos, ctx, k_cache, v_cache, live_steps - 1), token

    (ids, _, _, k_cache, v_cache, _), tokens = jax.lax.scan(
        body,
        (
            input_ids,
            positions,
            context_lens,
            k_cache,
            v_cache,
            steps_left.astype(jnp.int32),
        ),
        None,
        length=num_steps,
    )
    return tokens, k_cache, v_cache, ids


def mixed_window(  # distlint: traced
    params: dict,
    cfg: MistralConfig,
    # --- decode operands (identical to decode_loop) ---
    input_ids: jnp.ndarray,  # [B] last emitted token per slot
    positions: jnp.ndarray,  # [B]
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks]
    context_lens: jnp.ndarray,  # [B]
    steps_left: jnp.ndarray,  # [B] int32
    temperature: jnp.ndarray,  # [B]
    top_p: jnp.ndarray,  # [B]
    min_p: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B] int32 (0 disables)
    seeds: jnp.ndarray,  # [B] uint32 per-request sampling seeds
    # --- ragged prefill-chunk operands (prefill_paged shapes) ---
    chunk_ids: jnp.ndarray,  # [C, S] uncached tail-span tokens (padded)
    chunk_positions: jnp.ndarray,  # [C, S] absolute positions
    chunk_block_tables: jnp.ndarray,  # [C, max_blocks]
    chunk_context_lens: jnp.ndarray,  # [C] valid tokens incl. the span
    chunk_tail_lens: jnp.ndarray,  # [C] valid tokens in chunk_ids (0 = pad)
    chunk_temperature: jnp.ndarray,  # [C]
    chunk_top_p: jnp.ndarray,  # [C]
    chunk_min_p: jnp.ndarray,  # [C]
    chunk_top_k: jnp.ndarray,  # [C] int32 (0 disables)
    chunk_seeds: jnp.ndarray,  # [C] uint32 per-request sampling seeds
    num_steps: int,
    attn_backend: str = 'xla',
    max_table_positions: int | None = None,
    sampling_top_window: int = 0,
    layer_unroll: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One MIXED serving window: ragged prefill-chunk rows + the fused
    decode scan in a single dispatch (docs/serving.md).

    The decode window streams every weight regardless of how many tokens
    ride it, and on the serving tunnel each standalone prefill dispatch
    between windows costs a full host round trip (~68 ms measured) — the
    whole gap between the 830 tok/s serving loop and the 1101 tok/s
    isolated window rate in round 5 (``probe_gen``, BENCH_NOTES_r05.md).
    Folding the uncached prefill-tail chunks into the window dispatch
    removes those round trips: the chunk rows' write-then-attend pass
    (:func:`prefill_paged`, ragged per-row ``chunk_tail_lens`` — decode-
    like rows of span 1 coexist with causal multi-token chunk rows) runs
    first, then the unchanged decode scan. Chunk rows and decode rows own
    disjoint KV blocks, so the fusion is value-exact: both halves compute
    bit-identically to their standalone dispatches.

    Returns ``(tokens [num_steps, B], k_cache, v_cache, last_ids,
    chunk_tokens [C])`` where ``chunk_tokens`` samples each chunk row's
    last valid position (meaningful only for rows that finish their tail
    this window; the engine discards the rest). Every draw — chunk and
    decode alike — uses the counter-derived per-row key for the token
    being produced (``fold_row_keys``), so stochastic tokens are identical
    to the pure separate-prefill path too, not just greedy ones.
    """
    from distllm_tpu.ops.sampling import fold_row_keys, sample_tokens

    chunk_logits, k_cache, v_cache = prefill_paged(
        params, cfg, chunk_ids, chunk_positions, k_cache, v_cache,
        chunk_block_tables, chunk_context_lens, chunk_tail_lens,
        max_table_positions=max_table_positions, attn_backend=attn_backend,
    )
    # A chunk row's sampled token is its prompt's first generated token:
    # absolute index == chunk_context_lens (tokens 0..ctx-1 are prompt).
    chunk_tokens = sample_tokens(
        chunk_logits, None, chunk_temperature, chunk_top_p,
        chunk_min_p, top_window=sampling_top_window, top_k=chunk_top_k,
        row_keys=fold_row_keys(chunk_seeds, chunk_context_lens),
    )
    tokens, k_cache, v_cache, last_ids = decode_loop(
        params, cfg, input_ids, positions, k_cache, v_cache, block_tables,
        context_lens, steps_left, temperature, top_p, min_p, top_k, seeds,
        num_steps=num_steps, attn_backend=attn_backend,
        max_table_positions=max_table_positions,
        sampling_top_window=sampling_top_window, layer_unroll=layer_unroll,
    )
    return tokens, k_cache, v_cache, last_ids, chunk_tokens


def spec_window(  # distlint: traced
    params: dict,
    cfg: MistralConfig,
    # --- ragged verify-span operands (prefill_paged shapes) ---
    span_ids: jnp.ndarray,  # [B, S] last emitted token + draft tokens
    span_positions: jnp.ndarray,  # [B, S] absolute positions
    k_cache: jnp.ndarray,
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks]
    context_lens: jnp.ndarray,  # [B] total valid tokens incl. the span
    span_lens: jnp.ndarray,  # [B] valid span tokens (0 = inactive slot)
    temperature: jnp.ndarray,  # [B]
    top_p: jnp.ndarray,  # [B]
    min_p: jnp.ndarray,  # [B]
    top_k: jnp.ndarray,  # [B] int32 (0 disables)
    seeds: jnp.ndarray,  # [B] uint32 per-request sampling seeds
    # --- optional prefill-chunk operands (mixed batching composition) ---
    chunk: tuple | None = None,  # (ids, pos, bt, ctx, tails, temp, tp,
    #                               mp, tk, seeds)
    max_table_positions: int | None = None,
    sampling_top_window: int = 0,
    attn_backend: str = 'xla',
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray | None]:
    """One SPECULATIVE verify window: score every row's draft span and run
    the accept/resample rule in a single ragged dispatch
    (docs/speculative.md "Sampled verification").

    Each row carries ``[last_emitted_token, d_1, .., d_k]`` at absolute
    positions ``num_tokens-1 ..`` — the exact per-row-query-span shape
    :func:`prefill_paged` already dispatches (write-then-attend through
    ``ragged_paged_attention_xla``), so one weight pass scores all
    ``1+draft_k`` positions. Verification happens device-side in
    :func:`distllm_tpu.ops.sampling.verify_spans`: greedy rows keep the
    longest prefix where draft ``d_{i+1}`` equals the argmax at position
    ``i`` (bit-identical to the pre-sampled-verification host loop);
    temperature > 0 rows run exact rejection sampling against the filtered
    target (accept w.p. min(1, p̃/q); resample the positive residual on
    the first rejection). Acceptance decisions never bounce through the
    host mid-dispatch — only the packed tokens + accept length travel back
    at the engine's one audited fetch point. Rejected suffixes need no
    device-side rollback: their K/V writes sit at positions at or beyond
    the row's post-acceptance ``num_tokens``, which every later dispatch
    either overwrites before attending (write-then-attend) or masks out
    (``kv_pos <= q_pos``).

    ``chunk`` (pytree-static; ``None`` compiles a chunk-free graph)
    carries mixed-batching prefill-chunk rows exactly as
    :func:`mixed_window` does — same :func:`prefill_paged` pass, so the
    chunk half stays bit-identical to its standalone dispatch.

    Returns ``(packed [B, S+1] int32, k_cache, v_cache, chunk_tokens
    [C] | None)`` where ``packed[:, :S]`` are the per-position output
    tokens and ``packed[:, S]`` is the accepted-draft count (see
    :func:`verify_spans`). All draws use counter-derived per-row keys, so
    a span-1 verify of a sampled row emits the exact token the decode
    scan would have.
    """
    from distllm_tpu.ops.sampling import (
        fold_row_keys,
        sample_tokens,
        verify_spans,
    )

    chunk_tokens = None
    if chunk is not None:
        (c_ids, c_pos, c_bt, c_ctx, c_tails, c_temp, c_top_p, c_min_p,
         c_top_k, c_seeds) = chunk
        chunk_logits, k_cache, v_cache = prefill_paged(
            params, cfg, c_ids, c_pos, k_cache, v_cache, c_bt, c_ctx,
            c_tails, max_table_positions=max_table_positions,
            attn_backend=attn_backend,
        )
        chunk_tokens = sample_tokens(
            chunk_logits, None, c_temp, c_top_p, c_min_p,
            top_window=sampling_top_window, top_k=c_top_k,
            row_keys=fold_row_keys(c_seeds, c_ctx),
        )
    span_logits, k_cache, v_cache = prefill_paged(
        params, cfg, span_ids, span_positions, k_cache, v_cache,
        block_tables, context_lens, span_lens,
        max_table_positions=max_table_positions, all_logits=True,
        attn_backend=attn_backend,
    )
    packed = verify_spans(
        span_logits, span_ids, span_lens, span_positions,
        temperature, top_p, min_p, top_k, seeds,
        top_window=sampling_top_window,
    )
    return packed, k_cache, v_cache, chunk_tokens


def logits(params: dict, cfg: MistralConfig, hidden: jnp.ndarray) -> jnp.ndarray:  # distlint: traced
    """LM head: ``[..., H]`` hidden → fp32 ``[..., V]`` logits."""
    if cfg.tie_word_embeddings or 'lm_head' not in params:
        kernel = jnp.asarray(params['embed']).T
    else:
        kernel = jnp.asarray(params['lm_head'])
    out = common.dense(
        hidden, kernel, qmm_backend=getattr(cfg, 'qmm_backend', None)
    ).astype(jnp.float32)
    if getattr(cfg, 'final_logit_softcap', None) is not None:
        out = common.softcap(out, cfg.final_logit_softcap)
    return out


def param_specs(cfg: MistralConfig, params: dict | None = None) -> dict:
    """Sharding specs structurally matching ``params``.

    Encoder-only checkpoints (SFR-Embedding-Mistral) have no ``lm_head`` even
    with untied embeddings, so the spec tree mirrors the actual params when
    they are provided.
    """
    col = {'kernel': P(None, None, 'model')}
    row = {'kernel': P(None, 'model', None)}
    if cfg.attention_bias:
        # Stacked [L, out] biases shard with their column-parallel kernels.
        qkv = {'kernel': P(None, None, 'model'), 'bias': P(None, 'model')}
    else:
        qkv = col
    specs = {
        'embed': P(None, None),
        'layers': {
            'q': dict(qkv),
            'k': dict(qkv),
            'v': dict(qkv),
            'o': dict(row),
            'attn_ln': {'scale': P(None)},
            'gate': dict(col),
            'up': dict(col),
            'down': dict(row),
            'mlp_ln': {'scale': P(None)},
        },
        'final_ln': {'scale': P()},
    }
    if getattr(cfg, 'post_norms', False):
        specs['layers']['post_attn_ln'] = {'scale': P(None)}
        specs['layers']['post_mlp_ln'] = {'scale': P(None)}
    has_lm_head = (
        'lm_head' in params if params is not None else not cfg.tie_word_embeddings
    )
    if has_lm_head:
        specs['lm_head'] = P(None, 'model')
    return specs


def params_from_hf(state: dict[str, np.ndarray], cfg: MistralConfig) -> dict:
    """Convert HF ``MistralForCausalLM``/``MistralModel`` weights."""
    sd = {k.removeprefix('model.'): v for k, v in state.items()}

    def lin(key, bias_ok=False):
        out = {'kernel': np.ascontiguousarray(sd[key].T)}
        bias_key = key.removesuffix('.weight') + '.bias'
        if bias_key in sd:
            if not bias_ok:
                # Only Q/K/V biases flow through the forward passes; a
                # checkpoint with e.g. an o_proj bias (HF Llama with
                # attention_bias=true) must fail loudly, not silently
                # drop the weight and diverge from HF.
                raise ValueError(
                    f'{bias_key}: bias unsupported on this projection'
                )
            out['bias'] = sd[bias_key]
        return out

    layers = []
    for i in range(cfg.num_layers):
        p = f'layers.{i}'
        layers.append(
            {
                'q': lin(f'{p}.self_attn.q_proj.weight', bias_ok=True),
                'k': lin(f'{p}.self_attn.k_proj.weight', bias_ok=True),
                'v': lin(f'{p}.self_attn.v_proj.weight', bias_ok=True),
                'o': lin(f'{p}.self_attn.o_proj.weight'),
                'attn_ln': {'scale': sd[f'{p}.input_layernorm.weight']},
                'gate': lin(f'{p}.mlp.gate_proj.weight'),
                'up': lin(f'{p}.mlp.up_proj.weight'),
                'down': lin(f'{p}.mlp.down_proj.weight'),
                'mlp_ln': {'scale': sd[f'{p}.post_attention_layernorm.weight']},
            }
        )
    params = {
        'embed': sd['embed_tokens.weight'],
        'layers': common.stack_layers(layers),
        'final_ln': {'scale': sd['norm.weight']},
    }
    if 'lm_head.weight' in state and not cfg.tie_word_embeddings:
        params['lm_head'] = np.ascontiguousarray(state['lm_head.weight'].T)
    return params
