"""Shared functional layers for the pure-JAX models.

Everything is a pure function over explicit parameter pytrees; per-layer
weights are stacked on a leading axis and traversed with ``lax.scan`` so a
48-layer model compiles one layer body instead of 48 (compile-time and
HBM-code-size win on TPU). Attention uses ``jax.nn.dot_product_attention``
(XLA fuses to flash-attention-style kernels on TPU); custom Pallas kernels
live in ``distllm_tpu.ops`` and slot in via the ``attn_impl`` argument.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np


def layer_norm(
    x: jnp.ndarray,
    scale: jnp.ndarray,
    bias: jnp.ndarray | None,
    eps: float,
) -> jnp.ndarray:
    """LayerNorm; ``bias=None`` = scale-only (ESM-C's bias-free norms)."""
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    normed = (x - mean) * jax.lax.rsqrt(var + eps)
    out = normed * scale
    if bias is not None:
        out = out + bias
    return out


def rms_norm(
    x: jnp.ndarray, scale: jnp.ndarray, eps: float, plus_one: bool = False
) -> jnp.ndarray:
    # Norm statistics in fp32 for bf16 activations (standard TPU practice).
    # ``plus_one``: the Gemma-family ``(1 + w)`` parameterization — the
    # checkpoint stores zero-centered weights and the forward adds 1
    # (HF ``GemmaRMSNorm``), so loaded weights stay byte-identical to HF.
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    normed = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    w = scale.astype(jnp.float32)
    if plus_one:
        w = 1.0 + w
    return (normed * w).astype(dtype)


def dense(
    x: jnp.ndarray,
    kernel,
    bias: jnp.ndarray | None = None,
    qmm_backend: str | None = None,
) -> jnp.ndarray:
    """``x @ kernel (+ bias)`` with kernel laid out ``[in, out]``.

    ``kernel`` may be a quantized :class:`~distllm_tpu.ops.quantization.
    QTensor` — dequantization happens HERE, at the point of use, so a
    layer scan over a quantized tree only ever materializes one layer's
    bf16 weights at a time (dequantizing the whole stack outside the scan
    costs the full float model in HLO temps and OOMs 7B on 16 GiB HBM).

    int8 2-D kernels never dequantize at all: they route through
    :func:`distllm_tpu.ops.quantized_matmul.int8_dense`, which keeps the
    weight int8 across HBM (scale applied to the dot's OUTPUT, convert
    fused into the weight stream). Measured motivation and tier choice in
    that module's docstring. ``qmm_backend`` pins the tier for THIS call;
    ``None`` falls back to the process default
    (``DISTLLM_QMM_BACKEND=auto|pallas|xla|interpret``, read at import) at
    trace time — serving paths that validated the tier up front (the
    engine's TP-mesh check) must pass their resolved value explicitly so a
    later process-global change cannot re-route traced-at-serve kernels.
    """
    if hasattr(kernel, 'dequantize'):
        if getattr(kernel, 'kind', None) == 'int8' and kernel.q.ndim == 2:
            from distllm_tpu.ops import quantized_matmul as _qmm

            y = _qmm.int8_dense(
                x, kernel.q, kernel.scale,
                backend=qmm_backend or _qmm.default_backend(),
            )
            if bias is not None:
                y = y + bias.astype(y.dtype)
            return y
        kernel = kernel.dequantize()
    y = jnp.einsum('...i,io->...o', x, kernel.astype(x.dtype))
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y


def gelu(x: jnp.ndarray) -> jnp.ndarray:
    """HF-'gelu': the exact erf form, at every dtype.

    Checkpoints trained with erf-GELU get erf-GELU — dtype does not change
    the activation math. Deployments that want the cheaper polynomial opt
    in explicitly with the ``'gelu_tanh'`` activation name (see
    :func:`gelu_tanh` for the measured trade).
    """
    return jax.nn.gelu(x, approximate=False)


def gelu_tanh(x: jnp.ndarray) -> jnp.ndarray:
    """Opt-in tanh-approximated GELU (the HF ``gelu_pytorch_tanh`` form).

    The exact erf lowers to a long VPU polynomial that costs 19% of a
    BERT-base embed forward on a v5e (measured: MFU 0.622 exact vs 0.790
    tanh, ``chipback_r05/probe_embed_ablation.log``). The tanh form's max
    deviation from erf-GELU is ~3e-3 near |x|=2 — the same order as bf16's
    representation step there, so it is a REAL (if small) numerics change,
    not a free lunch; that is why it is an explicit activation choice
    (``hidden_act='gelu_tanh'``) rather than something bf16 turns on
    implicitly.
    """
    return jax.nn.gelu(x, approximate=True)


def silu(x: jnp.ndarray) -> jnp.ndarray:
    return jax.nn.silu(x)


def softcap(x: jnp.ndarray, cap: float) -> jnp.ndarray:
    """Gemma-2 logit soft-capping: ``tanh(x/cap)*cap`` (one home for the
    formula; used on attention scores and final logits)."""
    return jnp.tanh(x / cap) * cap


ACTIVATIONS: dict[str, Callable] = {
    'gelu': gelu,
    'gelu_tanh': gelu_tanh,
    'gelu_new': gelu_tanh,  # HF's historical alias for the tanh form
    'silu': silu,
    'relu': jax.nn.relu,
}


def split_heads(x: jnp.ndarray, num_heads: int) -> jnp.ndarray:
    """``[B, S, N*H] -> [B, S, N, H]``."""
    b, s, d = x.shape
    return x.reshape(b, s, num_heads, d // num_heads)


def merge_heads(x: jnp.ndarray) -> jnp.ndarray:
    """``[B, S, N, H] -> [B, S, N*H]``."""
    b, s, n, h = x.shape
    return x.reshape(b, s, n * h)


def sdpa(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    mask: jnp.ndarray | None = None,
    is_causal: bool = False,
    scale: float | None = None,
    logit_softcap: float | None = None,
) -> jnp.ndarray:
    """Scaled dot-product attention over ``[B, S, N, H]`` tensors.

    ``mask`` is a boolean ``[B, S_kv]`` key-validity mask (attention-mask
    semantics of the embed pipeline) or a broadcastable full
    ``[B, N, S_q, S_kv]`` boolean mask.

    ``logit_softcap`` (Gemma-2) applies ``tanh(s/cap)*cap`` to the scaled
    scores before masking; ``jax.nn.dot_product_attention`` has no such
    hook, so that path is an explicit einsum — XLA still fuses it, it just
    skips the flash-style kernel (acceptable: softcap models also need
    per-layer masks that the fused path cannot express).
    """
    if mask is not None and mask.ndim == 2:
        mask = mask[:, None, None, :].astype(bool)
    if logit_softcap is None:
        return jax.nn.dot_product_attention(
            q, k, v, mask=mask, is_causal=is_causal, scale=scale
        )
    assert not is_causal, 'softcap path expects an explicit mask'
    if k.shape[2] != q.shape[2]:  # GQA: expand KV heads to match q
        k = repeat_kv(k, q.shape[2] // k.shape[2])
        v = repeat_kv(v, q.shape[2] // v.shape[2])
    head_dim = q.shape[-1]
    scale = scale if scale is not None else head_dim ** -0.5
    # [B, S, N, H] -> scores [B, N, Sq, Skv] in fp32.
    scores = jnp.einsum(
        'bqnh,bknh->bnqk', q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    scores = softcap(scores, logit_softcap)
    if mask is not None:
        # Large-finite mask, not -inf (same trick as
        # jax.nn.dot_product_attention): a fully-masked PADDED query row
        # would softmax to NaN, and that row's NaN V then poisons every
        # valid query downstream through exact-zero x NaN products.
        scores = jnp.where(mask, scores, jnp.float32(-0.7 * 3.4e38))
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum('bnqk,bknh->bqnh', probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


def rope_frequencies(
    head_dim: int,
    max_len: int,
    theta: float,
    rope_scaling: dict | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Precompute RoPE cos/sin tables ``[max_len, head_dim//2]`` (host-side).

    ``rope_scaling`` follows the HF config field: ``{'rope_type':
    'llama3', 'factor', 'low_freq_factor', 'high_freq_factor',
    'original_max_position_embeddings'}`` (Llama-3 frequency-banded
    interpolation) or ``{'rope_type': 'linear', 'factor'}``. Unknown
    types raise — silently ignoring a checkpoint's scaling would produce
    wrong positions for every token past the original context.
    """
    inv_freq = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim))
    if rope_scaling:
        kind = rope_scaling.get('rope_type', rope_scaling.get('type'))
        if kind in (None, 'default'):
            pass  # HF's explicit no-op scaling entry
        elif kind == 'linear':
            inv_freq = inv_freq / float(rope_scaling['factor'])
        elif kind == 'llama3':
            # HF _compute_llama3_parameters: low-frequency bands scale by
            # 1/factor, high-frequency bands keep the base frequency, and
            # the middle band interpolates smoothly.
            factor = float(rope_scaling['factor'])
            low = float(rope_scaling['low_freq_factor'])
            high = float(rope_scaling['high_freq_factor'])
            orig = float(rope_scaling['original_max_position_embeddings'])
            wavelen = 2.0 * np.pi / inv_freq
            smooth = (orig / wavelen - low) / (high - low)
            smooth = np.clip(smooth, 0.0, 1.0)
            inv_freq = (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
        else:
            raise NotImplementedError(
                f'rope_scaling type {kind!r} (supported: linear, llama3)'
            )
    t = np.arange(max_len, dtype=np.float64)
    freqs = np.outer(t, inv_freq)
    return np.cos(freqs).astype(np.float32), np.sin(freqs).astype(np.float32)


def apply_rope(
    x: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    positions: jnp.ndarray | None = None,
    *,
    interleaved: bool = False,
) -> jnp.ndarray:
    """Rotate ``[B, S, N, H]`` queries/keys by position.

    ``interleaved=True`` pairs dims ``(0,1),(2,3),...``; ``False`` pairs
    ``(i, i+H/2)`` — the HF rotate_half layout used by Llama/Mistral *and*
    ESM2 (parity tests pin this).
    """
    b, s, n, h = x.shape
    if positions is None:
        table_cos, table_sin = cos[:s], sin[:s]  # [S, H/2]
        table_cos = table_cos[None, :, None, :]
        table_sin = table_sin[None, :, None, :]
    else:
        table_cos = cos[positions][:, :, None, :]  # positions [B, S]
        table_sin = sin[positions][:, :, None, :]
    table_cos = table_cos.astype(x.dtype)
    table_sin = table_sin.astype(x.dtype)
    if interleaved:
        x1 = x[..., 0::2]
        x2 = x[..., 1::2]
        r1 = x1 * table_cos - x2 * table_sin
        r2 = x2 * table_cos + x1 * table_sin
        return jnp.stack([r1, r2], axis=-1).reshape(b, s, n, h)
    x1 = x[..., : h // 2]
    x2 = x[..., h // 2 :]
    r1 = x1 * table_cos - x2 * table_sin
    r2 = x2 * table_cos + x1 * table_sin
    return jnp.concatenate([r1, r2], axis=-1)


def repeat_kv(x: jnp.ndarray, n_rep: int) -> jnp.ndarray:
    """GQA: expand ``[B, S, N_kv, H]`` to ``[B, S, N_kv*n_rep, H]``."""
    if n_rep == 1:
        return x
    b, s, n, h = x.shape
    return jnp.broadcast_to(x[:, :, :, None, :], (b, s, n, n_rep, h)).reshape(
        b, s, n * n_rep, h
    )


def stack_layers(per_layer: list[dict]) -> dict:
    """Stack a list of per-layer param dicts into one pytree with leading L."""
    return jax.tree_util.tree_map(lambda *xs: np.stack(xs, axis=0), *per_layer)


def causal_mask(q_len: int, kv_len: int, offset: int = 0) -> jnp.ndarray:
    """Boolean ``[q_len, kv_len]`` causal mask; query i sees kv <= i+offset."""
    q_pos = jnp.arange(q_len)[:, None] + offset
    kv_pos = jnp.arange(kv_len)[None, :]
    return kv_pos <= q_pos
