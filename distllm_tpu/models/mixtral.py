"""Mixtral-family sparse-MoE decoder with expert parallelism.

The reference has **no** MoE models (SURVEY.md §2.5: "Expert parallel —
absent"); this family makes the ``expert`` mesh axis real: expert weights
``[E, H, I]`` shard over it (``param_specs``), and because routing is
expressed as dense einsums over the expert dimension, pjit partitions the
expert-parallel compute and inserts the psum combine automatically — the
XLA-native formulation of EP (no hand-written all_to_all dispatch needed at
this scale; token-dropping capacity routing can slot in later without
changing the interface).

Architecture: Mistral backbone (GQA + RoPE + RMSNorm) with the SwiGLU MLP
replaced by a top-k-routed bank of expert MLPs (softmax-renormalized gate
weights over the selected experts, HF ``MixtralSparseMoeBlock`` semantics).
"""

from __future__ import annotations

from typing import Literal

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distllm_tpu.models import common
from distllm_tpu.utils import BaseConfig


class MixtralConfig(BaseConfig):
    name: Literal['mixtral'] = 'mixtral'
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int | None = None
    intermediate_size: int = 14336
    num_experts: int = 8
    experts_per_token: int = 2
    max_position_embeddings: int = 32768
    rope_theta: float = 1e6
    rope_scaling: dict | None = None
    rms_norm_eps: float = 1e-5
    sliding_window: int | None = None
    tie_word_embeddings: bool = False
    # Pinned quantized-matmul tier (see MistralConfig.qmm_backend).
    qmm_backend: str | None = None
    dtype: str = 'bfloat16'

    @property
    def head_size(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @classmethod
    def from_hf_config(cls, hf: dict) -> 'MixtralConfig':
        return cls(
            vocab_size=hf['vocab_size'],
            hidden_size=hf['hidden_size'],
            num_layers=hf['num_hidden_layers'],
            num_heads=hf['num_attention_heads'],
            num_kv_heads=hf.get('num_key_value_heads', hf['num_attention_heads']),
            intermediate_size=hf['intermediate_size'],
            num_experts=hf.get('num_local_experts', 8),
            experts_per_token=hf.get('num_experts_per_tok', 2),
            max_position_embeddings=hf.get('max_position_embeddings', 32768),
            rope_theta=hf.get('rope_theta', 1e6),
            rope_scaling=hf.get('rope_scaling'),
            rms_norm_eps=hf.get('rms_norm_eps', 1e-5),
            sliding_window=hf.get('sliding_window'),
            tie_word_embeddings=hf.get('tie_word_embeddings', False),
        )


def init(rng: jax.Array, cfg: MixtralConfig) -> dict:
    h, hd = cfg.hidden_size, cfg.head_size
    q_out, kv_out = cfg.num_heads * hd, cfg.num_kv_heads * hd
    i, e = cfg.intermediate_size, cfg.num_experts
    scale = 0.02

    def normal(key, shape):
        return np.asarray(jax.random.normal(key, shape) * scale, np.float32)

    keys = jax.random.split(rng, 3)
    layers = []
    for li in range(cfg.num_layers):
        ks = jax.random.split(jax.random.fold_in(keys[0], li), 8)
        layers.append(
            {
                'q': {'kernel': normal(ks[0], (h, q_out))},
                'k': {'kernel': normal(ks[1], (h, kv_out))},
                'v': {'kernel': normal(ks[2], (h, kv_out))},
                'o': {'kernel': normal(ks[3], (q_out, h))},
                'attn_ln': {'scale': np.ones((h,), np.float32)},
                'router': {'kernel': normal(ks[4], (h, e))},
                'gate': {'kernel': normal(ks[5], (e, h, i))},
                'up': {'kernel': normal(ks[6], (e, h, i))},
                'down': {'kernel': normal(ks[7], (e, i, h))},
                'mlp_ln': {'scale': np.ones((h,), np.float32)},
            }
        )
    params = {
        'embed': normal(keys[1], (cfg.vocab_size, h)),
        'layers': common.stack_layers(layers),
        'final_ln': {'scale': np.ones((h,), np.float32)},
    }
    if not cfg.tie_word_embeddings:
        params['lm_head'] = normal(keys[2], (h, cfg.vocab_size))
    return params


def moe_mlp(  # distlint: traced
    x: jnp.ndarray,  # [B, S, H]
    router_kernel: jnp.ndarray,  # [H, E]
    gate: jnp.ndarray,  # [E, H, I]
    up: jnp.ndarray,  # [E, H, I]
    down: jnp.ndarray,  # [E, I, H]
    experts_per_token: int,
) -> jnp.ndarray:
    """Top-k routed SwiGLU expert bank (HF Mixtral semantics).

    Router logits → softmax over ALL experts → keep top-k per token →
    renormalize the kept weights. Compute runs as dense einsums over the
    expert dim with the combine weights zeroed for unselected experts:
    under pjit with ``[E, ...]`` weights sharded over the ``expert`` axis,
    each chip computes only its experts and the final einsum psums the
    combine — expert parallelism as XLA sees it.
    """
    dtype = x.dtype

    def deq(w):
        # Expert banks may arrive weight-only quantized (QTensor); the
        # dequant happens HERE, at point of use — inside the layer loop,
        # so only one layer's experts materialize as floats at a time
        # (same policy as common.dense).
        return (w.dequantize() if hasattr(w, 'dequantize') else w).astype(
            dtype
        )

    gate, up, down = deq(gate), deq(up), deq(down)
    logits = jnp.einsum('bsh,he->bse', x.astype(jnp.float32), router_kernel.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)  # [B, S, E]
    top_w, top_idx = jax.lax.top_k(probs, experts_per_token)
    top_w = top_w / jnp.clip(jnp.sum(top_w, axis=-1, keepdims=True), 1e-9)
    # Scatter the kept weights back to a dense [B, S, E] combine matrix.
    combine = jnp.sum(
        jax.nn.one_hot(top_idx, probs.shape[-1], dtype=jnp.float32)
        * top_w[..., None],
        axis=-2,
    )
    hidden = jnp.einsum('bsh,ehi->besi', x, gate)
    hidden = jax.nn.silu(hidden) * jnp.einsum('bsh,ehi->besi', x, up)
    expert_out = jnp.einsum('besi,eih->besh', hidden, down)
    return jnp.einsum(
        'besh,bse->bsh', expert_out, combine.astype(dtype)
    )


def apply(
    params: dict,
    cfg: MixtralConfig,
    input_ids: jnp.ndarray,
    attention_mask: jnp.ndarray,
    *,
    mesh=None,
    seq_parallel: str | None = None,
) -> jnp.ndarray:
    """Dense causal forward: ``[B, S]`` → last hidden states ``[B, S, H]``.

    Delegates to the shared family forward (``models/mistral.py
    _forward``), which dispatches the MLP block on pytree structure
    (``_mlp_block`` sees the router and runs :func:`moe_mlp`) — one
    implementation for masks (incl. sliding window), RoPE, GQA attention,
    and ``seq_parallel`` ring/Ulysses, so the families cannot drift.
    """
    from distllm_tpu.models import mistral

    return mistral.apply(
        params, cfg, input_ids, attention_mask,
        mesh=mesh, seq_parallel=seq_parallel,
    )


def logits(params: dict, cfg: MixtralConfig, hidden: jnp.ndarray) -> jnp.ndarray:
    if cfg.tie_word_embeddings or 'lm_head' not in params:
        kernel = jnp.asarray(params['embed']).T
    else:
        kernel = jnp.asarray(params['lm_head'])
    return common.dense(
        hidden, kernel, qmm_backend=getattr(cfg, 'qmm_backend', None)
    ).astype(jnp.float32)


def prefill(params: dict, cfg: MixtralConfig, input_ids, attention_mask):
    """Serving prefill — the shared machinery in :mod:`.mistral` handles
    MoE layers by pytree structure (``_mlp_block``), so Mixtral serves
    through the same paged engine (the reference's vLLM serves both
    families through one engine as well)."""
    from distllm_tpu.models import mistral

    return mistral.prefill(params, cfg, input_ids, attention_mask)


def decode_step(params: dict, cfg: MixtralConfig, *args, **kwargs):
    from distllm_tpu.models import mistral

    return mistral.decode_step(params, cfg, *args, **kwargs)


def decode_loop(params: dict, cfg: MixtralConfig, *args, **kwargs):
    from distllm_tpu.models import mistral

    return mistral.decode_loop(params, cfg, *args, **kwargs)


def param_specs(cfg: MixtralConfig, params: dict | None = None) -> dict:
    """EP x TP sharding: expert banks over ``expert``, widths over ``model``."""
    col = {'kernel': P(None, None, 'model')}
    row = {'kernel': P(None, 'model', None)}
    specs = {
        'embed': P(None, None),
        'layers': {
            'q': dict(col),
            'k': dict(col),
            'v': dict(col),
            'o': dict(row),
            'attn_ln': {'scale': P(None)},
            'router': {'kernel': P(None, None, None)},
            # [L, E, H, I]: experts over 'expert', MLP width over 'model'.
            'gate': {'kernel': P(None, 'expert', None, 'model')},
            'up': {'kernel': P(None, 'expert', None, 'model')},
            'down': {'kernel': P(None, 'expert', 'model', None)},
            'mlp_ln': {'scale': P(None)},
        },
        'final_ln': {'scale': P()},
    }
    has_lm_head = (
        'lm_head' in params if params is not None else not cfg.tie_word_embeddings
    )
    if has_lm_head:
        specs['lm_head'] = P(None, 'model')
    return specs


def params_from_hf(state: dict[str, np.ndarray], cfg: MixtralConfig) -> dict:
    """Convert HF ``MixtralForCausalLM`` weights (experts stacked on E)."""
    sd = {k.removeprefix('model.'): v for k, v in state.items()}

    def lin(key):
        return {'kernel': np.ascontiguousarray(sd[key].T)}

    def expert_stack(layer: int, proj: str) -> np.ndarray:
        # HF names: layers.{L}.block_sparse_moe.experts.{E}.w1/w3/w2
        return np.stack(
            [
                np.ascontiguousarray(
                    sd[f'layers.{layer}.block_sparse_moe.experts.{e}.{proj}.weight'].T
                )
                for e in range(cfg.num_experts)
            ]
        )

    layers = []
    for i in range(cfg.num_layers):
        p = f'layers.{i}'
        layers.append(
            {
                'q': lin(f'{p}.self_attn.q_proj.weight'),
                'k': lin(f'{p}.self_attn.k_proj.weight'),
                'v': lin(f'{p}.self_attn.v_proj.weight'),
                'o': lin(f'{p}.self_attn.o_proj.weight'),
                'attn_ln': {'scale': sd[f'{p}.input_layernorm.weight']},
                'router': lin(f'{p}.block_sparse_moe.gate.weight'),
                'gate': {'kernel': expert_stack(i, 'w1')},
                'up': {'kernel': expert_stack(i, 'w3')},
                'down': {'kernel': expert_stack(i, 'w2')},
                'mlp_ln': {'scale': sd[f'{p}.post_attention_layernorm.weight']},
            }
        )
    params = {
        'embed': sd['embed_tokens.weight'],
        'layers': common.stack_layers(layers),
        'final_ln': {'scale': sd['norm.weight']},
    }
    if 'lm_head.weight' in state and not cfg.tie_word_embeddings:
        params['lm_head'] = np.ascontiguousarray(state['lm_head.weight'].T)
    return params
