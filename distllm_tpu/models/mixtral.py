"""Mixtral-family sparse-MoE decoder with expert parallelism.

The reference has **no** MoE models (SURVEY.md §2.5: "Expert parallel —
absent"); this family makes the ``expert`` mesh axis real: expert weights
``[E, H, I]`` shard over it (``param_specs``), and because routing is
expressed as dense einsums over the expert dimension, pjit partitions the
expert-parallel compute and inserts the psum combine automatically — the
XLA-native formulation of EP (no hand-written all_to_all dispatch needed at
this scale; token-dropping capacity routing can slot in later without
changing the interface).

Architecture: Mistral backbone (GQA + RoPE + RMSNorm) with the SwiGLU MLP
replaced by a top-k-routed bank of expert MLPs (softmax-renormalized gate
weights over the selected experts, HF ``MixtralSparseMoeBlock`` semantics).
"""

from __future__ import annotations

from typing import Literal

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distllm_tpu.models import common
from distllm_tpu.utils import BaseConfig


class MixtralConfig(BaseConfig):
    name: Literal['mixtral'] = 'mixtral'
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int | None = None
    intermediate_size: int = 14336
    num_experts: int = 8
    experts_per_token: int = 2
    max_position_embeddings: int = 32768
    rope_theta: float = 1e6
    rms_norm_eps: float = 1e-5
    tie_word_embeddings: bool = False
    dtype: str = 'bfloat16'

    @property
    def head_size(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @classmethod
    def from_hf_config(cls, hf: dict) -> 'MixtralConfig':
        return cls(
            vocab_size=hf['vocab_size'],
            hidden_size=hf['hidden_size'],
            num_layers=hf['num_hidden_layers'],
            num_heads=hf['num_attention_heads'],
            num_kv_heads=hf.get('num_key_value_heads', hf['num_attention_heads']),
            intermediate_size=hf['intermediate_size'],
            num_experts=hf.get('num_local_experts', 8),
            experts_per_token=hf.get('num_experts_per_tok', 2),
            max_position_embeddings=hf.get('max_position_embeddings', 32768),
            rope_theta=hf.get('rope_theta', 1e6),
            rms_norm_eps=hf.get('rms_norm_eps', 1e-5),
            tie_word_embeddings=hf.get('tie_word_embeddings', False),
        )


def init(rng: jax.Array, cfg: MixtralConfig) -> dict:
    h, hd = cfg.hidden_size, cfg.head_size
    q_out, kv_out = cfg.num_heads * hd, cfg.num_kv_heads * hd
    i, e = cfg.intermediate_size, cfg.num_experts
    scale = 0.02

    def normal(key, shape):
        return np.asarray(jax.random.normal(key, shape) * scale, np.float32)

    keys = jax.random.split(rng, 3)
    layers = []
    for li in range(cfg.num_layers):
        ks = jax.random.split(jax.random.fold_in(keys[0], li), 8)
        layers.append(
            {
                'q': {'kernel': normal(ks[0], (h, q_out))},
                'k': {'kernel': normal(ks[1], (h, kv_out))},
                'v': {'kernel': normal(ks[2], (h, kv_out))},
                'o': {'kernel': normal(ks[3], (q_out, h))},
                'attn_ln': {'scale': np.ones((h,), np.float32)},
                'router': {'kernel': normal(ks[4], (h, e))},
                'gate': {'kernel': normal(ks[5], (e, h, i))},
                'up': {'kernel': normal(ks[6], (e, h, i))},
                'down': {'kernel': normal(ks[7], (e, i, h))},
                'mlp_ln': {'scale': np.ones((h,), np.float32)},
            }
        )
    params = {
        'embed': normal(keys[1], (cfg.vocab_size, h)),
        'layers': common.stack_layers(layers),
        'final_ln': {'scale': np.ones((h,), np.float32)},
    }
    if not cfg.tie_word_embeddings:
        params['lm_head'] = normal(keys[2], (h, cfg.vocab_size))
    return params


def moe_mlp(
    x: jnp.ndarray,  # [B, S, H]
    router_kernel: jnp.ndarray,  # [H, E]
    gate: jnp.ndarray,  # [E, H, I]
    up: jnp.ndarray,  # [E, H, I]
    down: jnp.ndarray,  # [E, I, H]
    experts_per_token: int,
) -> jnp.ndarray:
    """Top-k routed SwiGLU expert bank (HF Mixtral semantics).

    Router logits → softmax over ALL experts → keep top-k per token →
    renormalize the kept weights. Compute runs as dense einsums over the
    expert dim with the combine weights zeroed for unselected experts:
    under pjit with ``[E, ...]`` weights sharded over the ``expert`` axis,
    each chip computes only its experts and the final einsum psums the
    combine — expert parallelism as XLA sees it.
    """
    dtype = x.dtype
    logits = jnp.einsum('bsh,he->bse', x.astype(jnp.float32), router_kernel.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)  # [B, S, E]
    top_w, top_idx = jax.lax.top_k(probs, experts_per_token)
    top_w = top_w / jnp.clip(jnp.sum(top_w, axis=-1, keepdims=True), 1e-9)
    # Scatter the kept weights back to a dense [B, S, E] combine matrix.
    combine = jnp.sum(
        jax.nn.one_hot(top_idx, probs.shape[-1], dtype=jnp.float32)
        * top_w[..., None],
        axis=-2,
    )
    hidden = jnp.einsum('bsh,ehi->besi', x, gate.astype(dtype))
    hidden = jax.nn.silu(hidden) * jnp.einsum('bsh,ehi->besi', x, up.astype(dtype))
    expert_out = jnp.einsum('besi,eih->besh', hidden, down.astype(dtype))
    return jnp.einsum(
        'besh,bse->bsh', expert_out, combine.astype(dtype)
    )


def apply(
    params: dict,
    cfg: MixtralConfig,
    input_ids: jnp.ndarray,
    attention_mask: jnp.ndarray,
    *,
    mesh=None,
    seq_parallel: str | None = None,
) -> jnp.ndarray:
    """Dense causal forward: ``[B, S]`` → last hidden states ``[B, S, H]``.

    ``seq_parallel`` activates ring/Ulysses attention over the ``seq`` mesh
    axis exactly as in :mod:`distllm_tpu.models.mistral`.
    """
    dtype = jnp.dtype(cfg.dtype)
    b, s = input_ids.shape
    cos, sin = common.rope_frequencies(cfg.head_size, s, cfg.rope_theta)
    cos, sin = jnp.asarray(cos), jnp.asarray(sin)
    x = jnp.asarray(params['embed'])[input_ids].astype(dtype)
    use_sp = (
        seq_parallel is not None
        and mesh is not None
        and mesh.shape.get('seq', 1) > 1
    )
    if use_sp:
        mask = None
    else:
        causal = common.causal_mask(s, s)
        mask = causal[None, None] & attention_mask[:, None, None, :].astype(bool)

    def layer(x, lp):
        normed = common.rms_norm(x, lp['attn_ln']['scale'], cfg.rms_norm_eps)
        q = common.split_heads(common.dense(normed, lp['q']['kernel']), cfg.num_heads)
        k = common.split_heads(common.dense(normed, lp['k']['kernel']), cfg.num_kv_heads)
        v = common.split_heads(common.dense(normed, lp['v']['kernel']), cfg.num_kv_heads)
        q = common.apply_rope(q, cos, sin)
        k = common.apply_rope(k, cos, sin)
        if use_sp:
            from distllm_tpu.ops.ring_attention import (
                ring_attention,
                ulysses_attention,
            )

            sp_fn = ring_attention if seq_parallel == 'ring' else ulysses_attention
            n_rep = cfg.num_heads // cfg.num_kv_heads
            attn = sp_fn(
                q,
                common.repeat_kv(k, n_rep),
                common.repeat_kv(v, n_rep),
                mesh,
                kv_mask=attention_mask,
                causal=True,
            )
        else:
            attn = common.sdpa(q, k, v, mask=mask)
        x = x + common.dense(common.merge_heads(attn), lp['o']['kernel'])
        normed2 = common.rms_norm(x, lp['mlp_ln']['scale'], cfg.rms_norm_eps)
        x = x + moe_mlp(
            normed2,
            lp['router']['kernel'],
            lp['gate']['kernel'],
            lp['up']['kernel'],
            lp['down']['kernel'],
            cfg.experts_per_token,
        )
        return x, None

    x, _ = jax.lax.scan(layer, x, params['layers'])
    return common.rms_norm(x, params['final_ln']['scale'], cfg.rms_norm_eps)


def logits(params: dict, cfg: MixtralConfig, hidden: jnp.ndarray) -> jnp.ndarray:
    if cfg.tie_word_embeddings or 'lm_head' not in params:
        kernel = jnp.asarray(params['embed']).T
    else:
        kernel = jnp.asarray(params['lm_head'])
    return common.dense(hidden, kernel).astype(jnp.float32)


def param_specs(cfg: MixtralConfig, params: dict | None = None) -> dict:
    """EP x TP sharding: expert banks over ``expert``, widths over ``model``."""
    col = {'kernel': P(None, None, 'model')}
    row = {'kernel': P(None, 'model', None)}
    specs = {
        'embed': P(None, None),
        'layers': {
            'q': dict(col),
            'k': dict(col),
            'v': dict(col),
            'o': dict(row),
            'attn_ln': {'scale': P(None)},
            'router': {'kernel': P(None, None, None)},
            # [L, E, H, I]: experts over 'expert', MLP width over 'model'.
            'gate': {'kernel': P(None, 'expert', None, 'model')},
            'up': {'kernel': P(None, 'expert', None, 'model')},
            'down': {'kernel': P(None, 'expert', 'model', None)},
            'mlp_ln': {'scale': P(None)},
        },
        'final_ln': {'scale': P()},
    }
    has_lm_head = (
        'lm_head' in params if params is not None else not cfg.tie_word_embeddings
    )
    if has_lm_head:
        specs['lm_head'] = P(None, 'model')
    return specs


def params_from_hf(state: dict[str, np.ndarray], cfg: MixtralConfig) -> dict:
    """Convert HF ``MixtralForCausalLM`` weights (experts stacked on E)."""
    sd = {k.removeprefix('model.'): v for k, v in state.items()}

    def lin(key):
        return {'kernel': np.ascontiguousarray(sd[key].T)}

    def expert_stack(layer: int, proj: str) -> np.ndarray:
        # HF names: layers.{L}.block_sparse_moe.experts.{E}.w1/w3/w2
        return np.stack(
            [
                np.ascontiguousarray(
                    sd[f'layers.{layer}.block_sparse_moe.experts.{e}.{proj}.weight'].T
                )
                for e in range(cfg.num_experts)
            ]
        )

    layers = []
    for i in range(cfg.num_layers):
        p = f'layers.{i}'
        layers.append(
            {
                'q': lin(f'{p}.self_attn.q_proj.weight'),
                'k': lin(f'{p}.self_attn.k_proj.weight'),
                'v': lin(f'{p}.self_attn.v_proj.weight'),
                'o': lin(f'{p}.self_attn.o_proj.weight'),
                'attn_ln': {'scale': sd[f'{p}.input_layernorm.weight']},
                'router': lin(f'{p}.block_sparse_moe.gate.weight'),
                'gate': {'kernel': expert_stack(i, 'w1')},
                'up': {'kernel': expert_stack(i, 'w3')},
                'down': {'kernel': expert_stack(i, 'w2')},
                'mlp_ln': {'scale': sd[f'{p}.post_attention_layernorm.weight']},
            }
        )
    params = {
        'embed': sd['embed_tokens.weight'],
        'layers': common.stack_layers(layers),
        'final_ln': {'scale': sd['norm.weight']},
    }
    if 'lm_head.weight' in state and not cfg.tie_word_embeddings:
        params['lm_head'] = np.ascontiguousarray(state['lm_head.weight'].T)
    return params
