"""ESM-2 protein language model (and ESM-C-compatible config surface).

TPU-native replacement for the reference's ``Esm2Encoder``
(``distllm/embed/encoders/esm2.py``), which relies on faesm/flash-attn CUDA
kernels with a transformers fallback. Here the model is functional JAX with
rotary position embeddings, pre-LN residual blocks, and the ESM token-dropout
embedding rescale, matching HF ``EsmModel`` numerics (verified in tests).
Attention runs through the shared SDPA path (XLA flash fusion on TPU).
"""

from __future__ import annotations

from typing import Literal

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distllm_tpu.models import common
from distllm_tpu.utils import BaseConfig


class Esm2Config(BaseConfig):
    name: Literal['esm2'] = 'esm2'
    vocab_size: int = 33
    hidden_size: int = 320
    num_layers: int = 6
    num_heads: int = 20
    intermediate_size: int = 1280
    layer_norm_eps: float = 1e-5
    token_dropout: bool = True
    mask_token_id: int = 32
    pad_token_id: int = 1
    dtype: str = 'bfloat16'

    @classmethod
    def from_hf_config(cls, hf: dict) -> 'Esm2Config':
        return cls(
            vocab_size=hf['vocab_size'],
            hidden_size=hf['hidden_size'],
            num_layers=hf['num_hidden_layers'],
            num_heads=hf['num_attention_heads'],
            intermediate_size=hf['intermediate_size'],
            layer_norm_eps=hf.get('layer_norm_eps', 1e-5),
            token_dropout=hf.get('token_dropout', True),
            mask_token_id=hf.get('mask_token_id', 32),
            pad_token_id=hf.get('pad_token_id', 1),
        )


_MASK_RATIO_TRAIN = 0.15 * 0.8  # ESM pretraining mask rate x mask fraction


def init(rng: jax.Array, cfg: Esm2Config) -> dict:
    h, i = cfg.hidden_size, cfg.intermediate_size
    scale = 0.02

    def normal(key, shape):
        return np.asarray(jax.random.normal(key, shape) * scale, np.float32)

    def ln():
        return {'scale': np.ones((h,), np.float32), 'bias': np.zeros((h,), np.float32)}

    keys = jax.random.split(rng, 2)
    layers = []
    for li in range(cfg.num_layers):
        ks = jax.random.split(jax.random.fold_in(keys[0], li), 6)
        layers.append(
            {
                'q': {'kernel': normal(ks[0], (h, h)), 'bias': np.zeros((h,), np.float32)},
                'k': {'kernel': normal(ks[1], (h, h)), 'bias': np.zeros((h,), np.float32)},
                'v': {'kernel': normal(ks[2], (h, h)), 'bias': np.zeros((h,), np.float32)},
                'o': {'kernel': normal(ks[3], (h, h)), 'bias': np.zeros((h,), np.float32)},
                'attn_ln': ln(),
                'up': {'kernel': normal(ks[4], (h, i)), 'bias': np.zeros((i,), np.float32)},
                'down': {'kernel': normal(ks[5], (i, h)), 'bias': np.zeros((h,), np.float32)},
                'mlp_ln': ln(),
            }
        )
    return {
        'embed': normal(keys[1], (cfg.vocab_size, h)),
        'layers': common.stack_layers(layers),
        'final_ln': ln(),
    }


def apply(
    params: dict,
    cfg: Esm2Config,
    input_ids: jnp.ndarray,
    attention_mask: jnp.ndarray,
    attn_impl: str = 'auto',
) -> jnp.ndarray:
    """Forward: ``[B, S]`` ids/mask → ``[B, S, H]`` last hidden states.

    ``attn_impl`` as in ``bert.apply``: ``'auto'`` uses the Pallas
    encoder-attention kernel on TPU (ops/encoder_attention.py — replaces
    the reference's faesm/flash-attn fast path, SURVEY.md section 2.4 N3),
    ``'xla'`` forces SDPA.
    """
    dtype = jnp.dtype(cfg.dtype)
    head_dim = cfg.hidden_size // cfg.num_heads
    seq_len = input_ids.shape[1]
    from distllm_tpu.ops.encoder_attention import (
        encoder_attention,
        resolve_use_pallas,
    )

    use_pallas = resolve_use_pallas(
        attn_impl, seq_len, cfg.hidden_size, cfg.num_heads, cfg.dtype
    )
    cos, sin = common.rope_frequencies(head_dim, input_ids.shape[1], 10000.0)
    cos, sin = jnp.asarray(cos), jnp.asarray(sin)

    x = jnp.asarray(params['embed'])[input_ids]
    if cfg.token_dropout:
        # ESM rescales embeddings by observed-vs-train mask ratio
        # (HF EsmEmbeddings.forward); zero <mask> embeddings first.
        is_mask = (input_ids == cfg.mask_token_id)[..., None]
        x = jnp.where(is_mask, 0.0, x)
        lengths = jnp.sum(attention_mask, axis=1).astype(jnp.float32)
        n_masked = jnp.sum(
            (input_ids == cfg.mask_token_id) & attention_mask.astype(bool), axis=1
        ).astype(jnp.float32)
        observed = n_masked / jnp.maximum(lengths, 1.0)
        x = x * ((1.0 - _MASK_RATIO_TRAIN) / (1.0 - observed))[:, None, None]
    # Zero out padding embeddings (HF multiplies by the attention mask).
    x = x * attention_mask[..., None].astype(x.dtype)
    x = x.astype(dtype)
    key_mask = attention_mask.astype(bool)

    def layer(x, lp):
        normed = common.layer_norm(
            x.astype(jnp.float32), lp['attn_ln']['scale'], lp['attn_ln']['bias'], cfg.layer_norm_eps
        ).astype(dtype)
        q = common.split_heads(common.dense(normed, lp['q']['kernel'], lp['q']['bias']), cfg.num_heads)
        k = common.split_heads(common.dense(normed, lp['k']['kernel'], lp['k']['bias']), cfg.num_heads)
        v = common.split_heads(common.dense(normed, lp['v']['kernel'], lp['v']['bias']), cfg.num_heads)
        q = common.apply_rope(q, cos, sin)
        k = common.apply_rope(k, cos, sin)
        if use_pallas:
            # merge_heads is a reshape (no transpose); heads stay packed.
            attn = encoder_attention(
                common.merge_heads(q),
                common.merge_heads(k),
                common.merge_heads(v),
                attention_mask,
                cfg.num_heads,
            )
        else:
            attn = common.merge_heads(common.sdpa(q, k, v, mask=key_mask))
        x = x + common.dense(attn, lp['o']['kernel'], lp['o']['bias'])
        normed2 = common.layer_norm(
            x.astype(jnp.float32), lp['mlp_ln']['scale'], lp['mlp_ln']['bias'], cfg.layer_norm_eps
        ).astype(dtype)
        mlp = common.dense(
            common.gelu(common.dense(normed2, lp['up']['kernel'], lp['up']['bias'])),
            lp['down']['kernel'],
            lp['down']['bias'],
        )
        x = x + mlp
        return x, None

    x, _ = jax.lax.scan(layer, x, params['layers'])
    return common.layer_norm(
        x.astype(jnp.float32),
        params['final_ln']['scale'],
        params['final_ln']['bias'],
        cfg.layer_norm_eps,
    )


def param_specs(cfg: Esm2Config) -> dict:
    col = {'kernel': P(None, None, 'model'), 'bias': P(None, 'model')}
    row = {'kernel': P(None, 'model', None), 'bias': P(None)}
    ln = {'scale': P(None), 'bias': P(None)}
    return {
        'embed': P(None, None),
        'layers': {
            'q': dict(col),
            'k': dict(col),
            'v': dict(col),
            'o': dict(row),
            'attn_ln': dict(ln),
            'up': dict(col),
            'down': dict(row),
            'mlp_ln': dict(ln),
        },
        'final_ln': {'scale': P(), 'bias': P()},
    }


def params_from_hf(state: dict[str, np.ndarray], cfg: Esm2Config) -> dict:
    """Convert HF ``EsmModel`` weights (contact head / pooler dropped)."""
    sd = {k.removeprefix('esm.'): v for k, v in state.items()}

    def lin(key):
        return {
            'kernel': np.ascontiguousarray(sd[f'{key}.weight'].T),
            'bias': sd[f'{key}.bias'],
        }

    def ln(key):
        return {'scale': sd[f'{key}.weight'], 'bias': sd[f'{key}.bias']}

    layers = []
    for i in range(cfg.num_layers):
        p = f'encoder.layer.{i}'
        layers.append(
            {
                'q': lin(f'{p}.attention.self.query'),
                'k': lin(f'{p}.attention.self.key'),
                'v': lin(f'{p}.attention.self.value'),
                'o': lin(f'{p}.attention.output.dense'),
                'attn_ln': ln(f'{p}.attention.LayerNorm'),
                'up': lin(f'{p}.intermediate.dense'),
                'down': lin(f'{p}.output.dense'),
                'mlp_ln': ln(f'{p}.LayerNorm'),
            }
        )
    return {
        'embed': sd['embeddings.word_embeddings.weight'],
        'layers': common.stack_layers(layers),
        'final_ln': ln('encoder.emb_layer_norm_after'),
    }
