"""BERT-family encoder (PubMedBERT / S-PubMedBert-MS-MARCO class models).

TPU-native replacement for the reference's ``AutoEncoder`` forward pass
(``distllm/embed/encoders/auto.py:119-138``, which returns
``hidden_states[-1]`` from ``transformers.AutoModel``): a functional JAX
transformer with stacked-layer ``lax.scan``, bf16 activations, and megatron
TP sharding specs over the ``model`` mesh axis.
"""

from __future__ import annotations

from typing import Literal

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distllm_tpu.models import common
from distllm_tpu.utils import BaseConfig


class BertConfig(BaseConfig):
    name: Literal['bert'] = 'bert'
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    hidden_act: str = 'gelu'
    dtype: str = 'bfloat16'

    @classmethod
    def from_hf_config(cls, hf: dict) -> 'BertConfig':
        return cls(
            vocab_size=hf['vocab_size'],
            hidden_size=hf['hidden_size'],
            num_layers=hf['num_hidden_layers'],
            num_heads=hf['num_attention_heads'],
            intermediate_size=hf['intermediate_size'],
            max_position_embeddings=hf.get('max_position_embeddings', 512),
            type_vocab_size=hf.get('type_vocab_size', 2),
            layer_norm_eps=hf.get('layer_norm_eps', 1e-12),
            hidden_act=hf.get('hidden_act', 'gelu'),
        )


def _ln_params(rng, size):
    return {
        'scale': np.ones((size,), np.float32),
        'bias': np.zeros((size,), np.float32),
    }


def init(rng: jax.Array, cfg: BertConfig) -> dict:
    """Random-init params (tests/benchmarks); layout matches params_from_hf."""
    rngs = jax.random.split(rng, 8)
    h, i = cfg.hidden_size, cfg.intermediate_size
    scale = 0.02

    def normal(key, shape):
        return np.asarray(jax.random.normal(key, shape) * scale, np.float32)

    layers = []
    for li in range(cfg.num_layers):
        key = jax.random.fold_in(rngs[0], li)
        ks = jax.random.split(key, 6)
        layers.append(
            {
                'q': {'kernel': normal(ks[0], (h, h)), 'bias': np.zeros((h,), np.float32)},
                'k': {'kernel': normal(ks[1], (h, h)), 'bias': np.zeros((h,), np.float32)},
                'v': {'kernel': normal(ks[2], (h, h)), 'bias': np.zeros((h,), np.float32)},
                'o': {'kernel': normal(ks[3], (h, h)), 'bias': np.zeros((h,), np.float32)},
                'attn_ln': _ln_params(None, h),
                'up': {'kernel': normal(ks[4], (h, i)), 'bias': np.zeros((i,), np.float32)},
                'down': {'kernel': normal(ks[5], (i, h)), 'bias': np.zeros((h,), np.float32)},
                'mlp_ln': _ln_params(None, h),
            }
        )
    return {
        'embeddings': {
            'word': normal(rngs[1], (cfg.vocab_size, h)),
            'position': normal(rngs[2], (cfg.max_position_embeddings, h)),
            'token_type': normal(rngs[3], (cfg.type_vocab_size, h)),
            'ln': _ln_params(None, h),
        },
        'layers': common.stack_layers(layers),
    }


def apply(
    params: dict,
    cfg: BertConfig,
    input_ids: jnp.ndarray,
    attention_mask: jnp.ndarray,
    attn_impl: str = 'auto',
) -> jnp.ndarray:
    """Forward pass: ``[B, S]`` ids/mask → ``[B, S, H]`` last hidden states.

    Numerics follow HF ``BertModel`` (post-LN residual transformer, absolute
    position embeddings); verified to ~1e-2 in bf16 / 1e-5 in fp32 against
    ``transformers`` in tests/test_models.py.

    ``attn_impl``: ``'auto'`` (Pallas encoder-attention kernel on TPU,
    XLA SDPA elsewhere — the kernel removes the [B, N, S, S] score
    materialization that caps the embed hot loop, ops/encoder_attention.py),
    ``'xla'``, or ``'pallas'``.
    """
    dtype = jnp.dtype(cfg.dtype)
    act = common.ACTIVATIONS[cfg.hidden_act]
    emb = params['embeddings']
    seq_len = input_ids.shape[1]
    from distllm_tpu.ops.encoder_attention import (
        encoder_attention,
        resolve_use_pallas,
    )

    use_pallas = resolve_use_pallas(
        attn_impl, seq_len, cfg.hidden_size, cfg.num_heads, cfg.dtype
    )

    x = (
        jnp.asarray(emb['word'])[input_ids]
        + jnp.asarray(emb['position'])[None, :seq_len]
        + jnp.asarray(emb['token_type'])[0][None, None, :]
    )
    x = common.layer_norm(x, emb['ln']['scale'], emb['ln']['bias'], cfg.layer_norm_eps)
    x = x.astype(dtype)
    key_mask = attention_mask.astype(bool)

    def layer(x, lp):
        q = common.dense(x, lp['q']['kernel'], lp['q']['bias'])
        k = common.dense(x, lp['k']['kernel'], lp['k']['bias'])
        v = common.dense(x, lp['v']['kernel'], lp['v']['bias'])
        if use_pallas:
            # Heads stay packed in the last dim — no transpose materializes.
            attn = encoder_attention(q, k, v, attention_mask, cfg.num_heads)
        else:
            attn = common.merge_heads(
                common.sdpa(
                    common.split_heads(q, cfg.num_heads),
                    common.split_heads(k, cfg.num_heads),
                    common.split_heads(v, cfg.num_heads),
                    mask=key_mask,
                )
            )
        attn = common.dense(attn, lp['o']['kernel'], lp['o']['bias'])
        # Post-LN residual (BERT): LN(x + sublayer(x)), stats in fp32.
        x = common.layer_norm(
            (x + attn).astype(jnp.float32),
            lp['attn_ln']['scale'],
            lp['attn_ln']['bias'],
            cfg.layer_norm_eps,
        ).astype(dtype)
        mlp = common.dense(act(common.dense(x, lp['up']['kernel'], lp['up']['bias'])), lp['down']['kernel'], lp['down']['bias'])
        x = common.layer_norm(
            (x + mlp).astype(jnp.float32),
            lp['mlp_ln']['scale'],
            lp['mlp_ln']['bias'],
            cfg.layer_norm_eps,
        ).astype(dtype)
        return x, None

    x, _ = jax.lax.scan(layer, x, params['layers'])
    return x


def param_specs(cfg: BertConfig) -> dict:
    """Megatron-style TP over the ``model`` axis; layer-stack axis unsharded."""
    col = {'kernel': P(None, None, 'model'), 'bias': P(None, 'model')}
    row = {'kernel': P(None, 'model', None), 'bias': P(None)}
    ln = {'scale': P(None), 'bias': P(None)}
    return {
        'embeddings': {
            'word': P(None, None),
            'position': P(None, None),
            'token_type': P(None, None),
            'ln': {'scale': P(), 'bias': P()},
        },
        'layers': {
            'q': dict(col),
            'k': dict(col),
            'v': dict(col),
            'o': dict(row),
            'attn_ln': dict(ln),
            'up': dict(col),
            'down': dict(row),
            'mlp_ln': dict(ln),
        },
    }


def params_from_hf(state: dict[str, np.ndarray], cfg: BertConfig) -> dict:
    """Convert an HF ``BertModel`` state dict to this module's params pytree."""
    sd = {k.removeprefix('bert.'): v for k, v in state.items()}

    def lin(prefix):  # torch Linear [out, in] -> [in, out]
        return {
            'kernel': np.ascontiguousarray(sd[f'{prefix}.weight'].T),
            'bias': sd[f'{prefix}.bias'],
        }

    def ln(prefix):
        return {'scale': sd[f'{prefix}.weight'], 'bias': sd[f'{prefix}.bias']}

    layers = []
    for i in range(cfg.num_layers):
        p = f'encoder.layer.{i}'
        layers.append(
            {
                'q': lin(f'{p}.attention.self.query'),
                'k': lin(f'{p}.attention.self.key'),
                'v': lin(f'{p}.attention.self.value'),
                'o': lin(f'{p}.attention.output.dense'),
                'attn_ln': ln(f'{p}.attention.output.LayerNorm'),
                'up': lin(f'{p}.intermediate.dense'),
                'down': lin(f'{p}.output.dense'),
                'mlp_ln': ln(f'{p}.output.LayerNorm'),
            }
        )
    return {
        'embeddings': {
            'word': sd['embeddings.word_embeddings.weight'],
            'position': sd['embeddings.position_embeddings.weight'],
            'token_type': sd['embeddings.token_type_embeddings.weight'],
            'ln': ln('embeddings.LayerNorm'),
        },
        'layers': common.stack_layers(layers),
    }
