"""Tokenization with fixed-shape bucketed padding.

XLA compiles one program per input shape, so dynamic per-batch padding (the
torch way, ``distllm/embed/datasets/utils.py:36-50``) would trigger a
recompile for nearly every batch. Instead, batches are padded to the smallest
*bucket* length from a small geometric ladder, bounding the number of compiled
programs while keeping padding waste low.

Two backends:

- :class:`HFTokenizer` — wraps a local ``transformers`` fast tokenizer
  (no network access; checkpoints must be on disk).
- :class:`WhitespaceTokenizer` — deterministic hash-vocab tokenizer for tests
  and benchmarks; no model files needed (the reference has no fake backends,
  SURVEY.md section 4 calls this out as a gap we close).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Protocol, Sequence

import numpy as np


def bucket_ladder(
    max_length: int, min_bucket: int = 16, scheme: str = 'fine'
) -> list[int]:
    """Ladder of sequence buckets up to ``max_length``.

    ``scheme='fine'`` (embed hot loop): geometric (x2) up to 64, then linear
    steps of 32 (to 384), 64 (to 512), and 128 beyond. Finer rungs than a
    pure x2 ladder cut padding waste from ~35% to ~10% on chunk-sized text
    (120-260 tokens); with length-sorted batching only a handful of rungs are
    ever touched, so the compile count stays small. (The 256-384 range used
    to step by 64: the 320 rung alone cost ~23% padding on 260-token chunk
    tails — measured, BENCH r2 embed breakdown.)

    ``scheme='pow2'`` (serving prefill): pure doubling — at most
    ``log2(max_length)`` compiled prefill programs, since at serving time
    each compilation is a multi-second stall on a real model and prompt
    lengths are not presorted.
    """
    if max_length < 1:
        raise ValueError(f'max_length must be >= 1, got {max_length}')
    if scheme not in ('fine', 'pow2'):
        raise ValueError(f"scheme must be 'fine' or 'pow2', got {scheme!r}")
    buckets: list[int] = []
    b = min(min_bucket, max_length)
    while b < max_length:
        buckets.append(b)
        if scheme == 'pow2' or b < 64:
            b *= 2
        elif b < 384:
            b += 32
        elif b < 512:
            b += 64
        else:
            b += 128
    buckets.append(max_length)
    return buckets


def pick_bucket(length: int, buckets: Sequence[int]) -> int:
    """Smallest bucket >= length (lengths beyond the ladder clamp to max)."""
    for b in buckets:
        if length <= b:
            return b
    return buckets[-1]


@dataclass
class TokenBatch:
    """Fixed-shape tokenized batch: int32 ``[B, S]`` ids and mask."""

    input_ids: np.ndarray
    attention_mask: np.ndarray

    @property
    def shape(self) -> tuple[int, int]:
        return self.input_ids.shape

    def pad_batch_to(self, batch_size: int, pad_id: int = 0) -> 'TokenBatch':
        """Pad the batch dimension with fully-masked rows (for bucketed B)."""
        b, s = self.input_ids.shape
        if b >= batch_size:
            return self
        ids = np.full((batch_size, s), pad_id, dtype=np.int32)
        mask = np.zeros((batch_size, s), dtype=np.int32)
        ids[:b] = self.input_ids
        mask[:b] = self.attention_mask
        return TokenBatch(ids, mask)


class Tokenizer(Protocol):
    """Minimal tokenizer surface the pipelines rely on."""

    vocab_size: int
    pad_id: int
    model_max_length: int

    def __call__(
        self, texts: Sequence[str], *, max_length: int | None = None
    ) -> TokenBatch: ...

    def decode(self, ids: Sequence[int]) -> str: ...


class _BucketingMixin:
    buckets: list[int]

    def _pad_to_bucket(
        self, rows: list[list[int]], pad_id: int, max_length: int
    ) -> TokenBatch:
        longest = max((len(r) for r in rows), default=1)
        target = pick_bucket(min(longest, max_length), self.buckets)
        ids = np.full((len(rows), target), pad_id, dtype=np.int32)
        mask = np.zeros((len(rows), target), dtype=np.int32)
        for i, row in enumerate(rows):
            if len(row) > target:
                # Truncate but keep the terminal special token ([SEP]/EOS) so
                # models never see a malformed sequence.
                row = row[: target - 1] + [row[-1]]
            ids[i, : len(row)] = row
            mask[i, : len(row)] = 1
        return TokenBatch(ids, mask)


class WhitespaceTokenizer(_BucketingMixin):
    """Deterministic test tokenizer: whitespace split + stable hash vocab.

    Token ids are stable across processes (sha1-based), so golden tests and
    multi-host runs agree without any vocabulary files.
    """

    def __init__(
        self,
        vocab_size: int = 32000,
        model_max_length: int = 512,
        min_bucket: int = 16,
    ) -> None:
        if vocab_size <= 8:
            raise ValueError('vocab_size must be > 8')
        self.vocab_size = vocab_size
        self.model_max_length = model_max_length
        self.pad_id = 0
        self.cls_id = 1
        self.sep_id = 2
        self.unk_id = 3
        self._n_special = 4
        self.buckets = bucket_ladder(model_max_length, min_bucket)
        self._reverse: dict[int, str] = {}
        self._cache: dict[str, int] = {}

    def token_id(self, token: str) -> int:
        tid = self._cache.get(token)
        if tid is not None:
            return tid
        digest = hashlib.sha1(token.encode()).digest()
        tid = self._n_special + int.from_bytes(digest[:4], 'little') % (
            self.vocab_size - self._n_special
        )
        self._reverse.setdefault(tid, token)
        self._cache[token] = tid
        return tid

    def __call__(
        self, texts: Sequence[str], *, max_length: int | None = None
    ) -> TokenBatch:
        max_length = max_length or self.model_max_length
        body_limit = max(0, max_length - 2)
        rows = []
        for text in texts:
            body = [self.token_id(t) for t in text.split()]
            rows.append([self.cls_id] + body[:body_limit] + [self.sep_id])
        return self._pad_to_bucket(rows, self.pad_id, max_length)

    def decode(self, ids: Sequence[int]) -> str:
        out = []
        for tid in ids:
            tid = int(tid)
            if tid < self._n_special:
                continue
            out.append(self._reverse.get(tid, f'<{tid}>'))
        return ' '.join(out)


class HFTokenizer(_BucketingMixin):
    """Wrap a local HuggingFace fast tokenizer with bucketed padding.

    Replaces the reference's ``DataCollator`` dynamic padding
    (``embed/datasets/utils.py:36-50``) with fixed-shape buckets. The
    tokenizer's own ``model_max_length`` is respected the way the reference
    sets it from the model config (``embed/encoders/auto.py:74``).
    """

    def __init__(
        self,
        pretrained_model_name_or_path: str,
        model_max_length: int | None = None,
        min_bucket: int = 16,
        trust_remote_code: bool = False,
    ) -> None:
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(
            pretrained_model_name_or_path, trust_remote_code=trust_remote_code
        )
        limit = model_max_length or getattr(self._tok, 'model_max_length', 512)
        # HF uses a huge sentinel when unset.
        self.model_max_length = int(min(limit, 1_000_000)) if limit else 512
        if self.model_max_length >= 1_000_000:
            self.model_max_length = 512
        self.vocab_size = int(self._tok.vocab_size)
        self.pad_id = int(self._tok.pad_token_id or 0)
        self.buckets = bucket_ladder(self.model_max_length, min_bucket)

    def __call__(
        self, texts: Sequence[str], *, max_length: int | None = None
    ) -> TokenBatch:
        max_length = max_length or self.model_max_length
        enc = self._tok(
            list(texts), truncation=True, max_length=max_length, padding=False
        )
        return self._pad_to_bucket(enc['input_ids'], self.pad_id, max_length)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(
            [int(i) for i in ids], skip_special_tokens=True
        )
