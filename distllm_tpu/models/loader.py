"""Checkpoint IO: read HuggingFace-format weights into host numpy arrays.

The reference loads models through ``transformers.AutoModel.from_pretrained``
(``embed/encoders/auto.py:58-71``); here checkpoints are read directly
(safetensors preferred, torch ``*.bin`` fallback) and converted to each
model's params pytree by per-architecture mapping functions that live next to
the model code (``models/bert.py`` etc.). No network access is performed.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np


def read_checkpoint(model_dir: str | Path) -> dict[str, np.ndarray]:
    """Read all weights under ``model_dir`` into a flat {name: ndarray} dict."""
    model_dir = Path(model_dir)
    if not model_dir.is_dir():
        raise FileNotFoundError(
            f'checkpoint dir not found: {model_dir} '
            '(network downloads are disabled; pass a local path)'
        )
    state: dict[str, np.ndarray] = {}
    safetensor_files = sorted(model_dir.glob('*.safetensors'))
    if safetensor_files:
        from safetensors.numpy import load_file

        for path in safetensor_files:
            state.update(load_file(str(path)))
        return state
    bin_files = (
        sorted(model_dir.glob('*.bin'))
        + sorted(model_dir.glob('*.pt'))
        # esm-package checkpoints (ESM-C) ship as .pth, nested under
        # data/weights/ in the released repos.
        + sorted(model_dir.glob('**/*.pth'))
    )
    if bin_files:
        import torch

        for path in bin_files:
            sd = torch.load(str(path), map_location='cpu', weights_only=True)
            for k, v in sd.items():
                state[k] = v.to(torch.float32).numpy() if v.dtype == torch.bfloat16 else v.numpy()
        return state
    raise FileNotFoundError(f'no *.safetensors or *.bin under {model_dir}')


def read_hf_config(model_dir: str | Path) -> dict:
    path = Path(model_dir) / 'config.json'
    with open(path) as fh:
        return json.load(fh)


def save_checkpoint(state: dict[str, np.ndarray], model_dir: str | Path) -> None:
    """Write a safetensors checkpoint (tests create tiny local models)."""
    from safetensors.numpy import save_file

    model_dir = Path(model_dir)
    model_dir.mkdir(parents=True, exist_ok=True)
    save_file(dict(state), str(model_dir / 'model.safetensors'))


def unflatten(flat: dict[str, np.ndarray], sep: str = '.') -> dict:
    """``{'a.b': x}`` → ``{'a': {'b': x}}`` nested params pytree."""
    tree: dict = {}
    for key, value in flat.items():
        parts = key.split(sep)
        node = tree
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = value
    return tree
