"""Pure-JAX model implementations and HF-checkpoint loaders.

Models are functional: a pydantic config, an ``init(rng, config) -> params``
(random init, used in tests and benchmarks), an ``apply(params, batch, ...)``
pure function, a ``param_specs(config)`` pytree of PartitionSpecs for TP/DP
sharding, and a ``params_from_hf(state_dict, config)`` converter from
HuggingFace checkpoints. This replaces the reference's dependence on
``transformers.AutoModel`` forward passes (``distllm/embed/encoders/auto.py``)
with compiled, shardable JAX forwards.
"""

from __future__ import annotations


def decoder_families() -> dict:
    """``model_type -> (config_cls, module)`` for every decoder family.

    The single source of truth: the serving entry points dispatch through
    :func:`decoder_family`, and the embed auto-encoder builds its table
    from these rows plus the encoder-only families
    (``embed/encoders/auto.py``) — a new decoder lands in one place.
    """
    from distllm_tpu.models import gemma, mistral, mixtral

    return {
        'mistral': (mistral.MistralConfig, mistral),
        'llama': (mistral.MistralConfig, mistral),
        'qwen2': (mistral.MistralConfig, mistral),
        'mixtral': (mixtral.MixtralConfig, mixtral),
        'gemma': (gemma.GemmaConfig, gemma),
        'gemma2': (gemma.GemmaConfig, gemma),
    }


def decoder_family(model_type: str):
    """(config_cls, module) for a DECODER checkpoint's HF ``model_type``.

    Encoder-only families (bert/esm/modernbert) are a loud error here,
    not a silent fall-through to the Mistral converter.
    """
    families = decoder_families()
    try:
        return families[model_type]
    except KeyError:
        raise ValueError(
            f'Unsupported decoder model_type {model_type!r}; '
            f'supported: {sorted(families)}'
        ) from None
