"""Pure-JAX model implementations and HF-checkpoint loaders.

Models are functional: a pydantic config, an ``init(rng, config) -> params``
(random init, used in tests and benchmarks), an ``apply(params, batch, ...)``
pure function, a ``param_specs(config)`` pytree of PartitionSpecs for TP/DP
sharding, and a ``params_from_hf(state_dict, config)`` converter from
HuggingFace checkpoints. This replaces the reference's dependence on
``transformers.AutoModel`` forward passes (``distllm/embed/encoders/auto.py``)
with compiled, shardable JAX forwards.
"""
