"""Pure-JAX model implementations and HF-checkpoint loaders.

Models are functional: a pydantic config, an ``init(rng, config) -> params``
(random init, used in tests and benchmarks), an ``apply(params, batch, ...)``
pure function, a ``param_specs(config)`` pytree of PartitionSpecs for TP/DP
sharding, and a ``params_from_hf(state_dict, config)`` converter from
HuggingFace checkpoints. This replaces the reference's dependence on
``transformers.AutoModel`` forward passes (``distllm/embed/encoders/auto.py``)
with compiled, shardable JAX forwards.
"""

from __future__ import annotations


def decoder_family(model_type: str):
    """(config_cls, module) for a DECODER checkpoint's HF ``model_type``.

    One registry for every serving entry point (engine backends, chat
    server boot), so adding a family happens in one place. Encoder-only
    families (bert/esm/modernbert) live in the embed auto-encoder's table
    (``embed/encoders/auto.py``) — asking for one here is a loud error,
    not a silent fall-through to the Mistral converter.
    """
    from distllm_tpu.models import mistral, mixtral

    families = {
        'mistral': (mistral.MistralConfig, mistral),
        'llama': (mistral.MistralConfig, mistral),
        'qwen2': (mistral.MistralConfig, mistral),
        'mixtral': (mixtral.MixtralConfig, mixtral),
    }
    try:
        return families[model_type]
    except KeyError:
        raise ValueError(
            f'Unsupported decoder model_type {model_type!r}; '
            f'supported: {sorted(families)}'
        ) from None
