"""ESM-Cambrian (ESM-C) protein language model — the true architecture.

Reference parity: ``distllm/embed/encoders/esmc.py:28-134`` wraps
EvolutionaryScale's ``esm`` package (``esm.models.esmc.ESMC``); that stack
is NOT ESM-2-shaped, so this module implements it directly in JAX:

- fused pre-norm QKV: LayerNorm → one ``d→3d`` linear (no bias);
- **QK LayerNorm** on the full q/k vectors before head split (scale only);
- rotary position embeddings (rotate-half convention, theta 10000);
- bidirectional attention masked on key validity (no causal mask);
- SwiGLU FFN with hidden ``ceil(8/3·d / 256)·256`` (2560 @ 960, 3072 @ 1152);
- residuals divided by ``sqrt(num_layers / 36)``;
- final LayerNorm; embeddings output = the normed last hidden state.

Released sizes (the two the reference validates): 300M = 960 hidden /
30 layers / 15 heads; 600M = 1152 / 36 / 18. Checkpoint conversion reads
the ``esm`` package's state-dict naming (``transformer.blocks.N.attn.
layernorm_qkv...``). Numerics are golden-tested against an independent
NumPy re-implementation (``tests/test_esmc.py``) — real released weights
cannot be fetched in this environment (zero egress).

The tokenizer mirrors ``EsmSequenceTokenizer``: the fixed 33-symbol protein
vocabulary (cls/pad/eos/unk + amino acids + specials), cls+seq+eos framing,
2048-token cap (ref ``esmc.py:84``).
"""

from __future__ import annotations

from typing import Literal, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from distllm_tpu.models import common
from distllm_tpu.models.tokenizer import TokenBatch, _BucketingMixin, bucket_ladder
from distllm_tpu.utils import BaseConfig

# EsmSequenceTokenizer's vocabulary (fixed, public).
ESMC_VOCAB = (
    ['<cls>', '<pad>', '<eos>', '<unk>']
    + list('LAGVSERTIDPKQNFYMHWCXBUZO')
    + ['.', '-', '|', '<mask>']
)

_SIZES = {960: (30, 15), 1152: (36, 18)}


class EsmcConfig(BaseConfig):
    name: Literal['esmc'] = 'esmc'
    vocab_size: int = 64  # embedding rows are padded past the 33 used ids
    hidden_size: int = 960
    num_layers: int = 30
    num_heads: int = 15
    max_position_embeddings: int = 2048
    layer_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    dtype: str = 'bfloat16'

    @property
    def head_size(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def ffn_hidden(self) -> int:
        # swiglu_correction_fn: 8/3 expansion rounded up to multiple of 256.
        return int(-(-(self.hidden_size * 8 // 3) // 256) * 256)

    @property
    def residue_scale(self) -> float:
        return float(np.sqrt(self.num_layers / 36.0))

    @classmethod
    def from_hidden_size(cls, hidden_size: int, **kwargs) -> 'EsmcConfig':
        if hidden_size not in _SIZES:
            raise ValueError(
                f'ESM-C hidden size must be one of {sorted(_SIZES)} '
                f'(300M/600M releases), got {hidden_size}'
            )
        layers, heads = _SIZES[hidden_size]
        return cls(
            hidden_size=hidden_size,
            num_layers=layers,
            num_heads=heads,
            **kwargs,
        )


def init(rng: jax.Array, cfg: EsmcConfig) -> dict:
    h, f = cfg.hidden_size, cfg.ffn_hidden
    scale = 0.02

    def normal(key, shape):
        return np.asarray(jax.random.normal(key, shape) * scale, np.float32)

    keys = jax.random.split(rng, 2)
    layers = []
    for li in range(cfg.num_layers):
        ks = jax.random.split(jax.random.fold_in(keys[0], li), 4)
        layers.append(
            {
                'qkv_ln': {'scale': np.ones((h,), np.float32),
                           'bias': np.zeros((h,), np.float32)},
                'qkv': {'kernel': normal(ks[0], (h, 3 * h))},
                'q_ln': {'scale': np.ones((h,), np.float32)},
                'k_ln': {'scale': np.ones((h,), np.float32)},
                'out': {'kernel': normal(ks[1], (h, h))},
                'ffn_ln': {'scale': np.ones((h,), np.float32),
                           'bias': np.zeros((h,), np.float32)},
                'ffn_in': {'kernel': normal(ks[2], (h, 2 * f))},
                'ffn_out': {'kernel': normal(ks[3], (f, h))},
            }
        )
    return {
        'embed': normal(keys[1], (cfg.vocab_size, h)),
        'layers': common.stack_layers(layers),
        'final_ln': {'scale': np.ones((h,), np.float32)},
    }


def apply(
    params: dict,
    cfg: EsmcConfig,
    input_ids: jnp.ndarray,  # [B, S]
    attention_mask: jnp.ndarray,  # [B, S]
    attn_impl: str = 'auto',
) -> jnp.ndarray:
    """Forward → last hidden states ``[B, S, H]`` (after the final norm —
    exactly what the reference's ``encode`` returns as embeddings).

    ``attn_impl`` as in ``bert.apply`` (shared policy,
    ops/encoder_attention.py resolve_use_pallas)."""
    from distllm_tpu.ops.encoder_attention import (
        encoder_attention,
        resolve_use_pallas,
    )

    dtype = jnp.dtype(cfg.dtype)
    b, s = input_ids.shape
    eps = cfg.layer_norm_eps
    use_pallas = resolve_use_pallas(
        attn_impl, s, cfg.hidden_size, cfg.num_heads, cfg.dtype
    )
    cos, sin = common.rope_frequencies(cfg.head_size, s, cfg.rope_theta)
    cos, sin = jnp.asarray(cos), jnp.asarray(sin)
    inv_scale = jnp.asarray(1.0 / cfg.residue_scale, dtype)
    # Bidirectional attention over valid keys only.
    mask = attention_mask[:, None, None, :].astype(bool)

    x = jnp.asarray(params['embed'])[input_ids].astype(dtype)

    def ln(h, p, with_bias=True):
        # Norm statistics in fp32 (same discipline as the ESM-2 stack).
        return common.layer_norm(
            h.astype(jnp.float32),
            p['scale'],
            p['bias'] if with_bias else None,
            eps,
        ).astype(dtype)

    def layer(x, lp):
        normed = ln(x, lp['qkv_ln'])
        qkv = common.dense(normed, lp['qkv']['kernel'])
        q, k, v = jnp.split(qkv, 3, axis=-1)
        # QK LayerNorm on the FULL vectors, scale-only, before head split.
        q = ln(q, lp['q_ln'], with_bias=False)
        k = ln(k, lp['k_ln'], with_bias=False)
        q = common.split_heads(q, cfg.num_heads)
        k = common.split_heads(k, cfg.num_heads)
        v = common.split_heads(v, cfg.num_heads)
        q = common.apply_rope(q, cos, sin)
        k = common.apply_rope(k, cos, sin)
        if use_pallas:
            # merge_heads is a reshape (no transpose); heads stay packed.
            attn = encoder_attention(
                common.merge_heads(q),
                common.merge_heads(k),
                common.merge_heads(v),
                attention_mask,
                cfg.num_heads,
            )
        else:
            attn = common.merge_heads(common.sdpa(q, k, v, mask=mask))
        x = x + common.dense(attn, lp['out']['kernel']) * inv_scale
        normed2 = ln(x, lp['ffn_ln'])
        gate_up = common.dense(normed2, lp['ffn_in']['kernel'])
        gate, up = jnp.split(gate_up, 2, axis=-1)
        ffn = common.dense(common.silu(gate) * up, lp['ffn_out']['kernel'])
        return x + ffn * inv_scale, None

    x, _ = jax.lax.scan(layer, x, params['layers'])
    return ln(x, params['final_ln'], with_bias=False)


def params_from_esm(state: dict[str, np.ndarray], cfg: EsmcConfig) -> dict:
    """Convert an ``esm``-package ESMC state dict (``.pth``) to our tree."""
    def lin(key):
        return {'kernel': np.ascontiguousarray(state[key].T)}

    def ln(prefix, with_bias=True):
        out = {'scale': state[f'{prefix}.weight']}
        if with_bias:
            bias = state.get(f'{prefix}.bias')
            out['bias'] = (
                bias
                if bias is not None
                else np.zeros_like(out['scale'])
            )
        return out

    layers = []
    for i in range(cfg.num_layers):
        p = f'transformer.blocks.{i}'
        layers.append(
            {
                'qkv_ln': ln(f'{p}.attn.layernorm_qkv.0'),
                'qkv': lin(f'{p}.attn.layernorm_qkv.1.weight'),
                'q_ln': ln(f'{p}.attn.q_ln', with_bias=False),
                'k_ln': ln(f'{p}.attn.k_ln', with_bias=False),
                'out': lin(f'{p}.attn.out_proj.weight'),
                'ffn_ln': ln(f'{p}.ffn.0'),
                'ffn_in': lin(f'{p}.ffn.1.weight'),
                'ffn_out': lin(f'{p}.ffn.3.weight'),
            }
        )
    return {
        'embed': state['embed.weight'],
        'layers': common.stack_layers(layers),
        'final_ln': ln('transformer.norm', with_bias=False),
    }


class EsmcSequenceTokenizer(_BucketingMixin):
    """``EsmSequenceTokenizer`` equivalent: fixed protein vocab, cls+seq+eos
    framing, bucketed fixed-shape padding (TPU requirement)."""

    def __init__(self, model_max_length: int = 2048, min_bucket: int = 16):
        self.vocab = list(ESMC_VOCAB)
        self.vocab_size = len(self.vocab)
        self._ids = {tok: i for i, tok in enumerate(self.vocab)}
        self.pad_id = self._ids['<pad>']
        self.cls_id = self._ids['<cls>']
        self.eos_id = self._ids['<eos>']
        self.unk_id = self._ids['<unk>']
        self.model_max_length = model_max_length
        self.buckets = bucket_ladder(model_max_length, min_bucket)

    def __call__(
        self, texts: Sequence[str], *, max_length: int | None = None
    ) -> TokenBatch:
        max_length = max_length or self.model_max_length
        body_limit = max(0, max_length - 2)
        rows = []
        for seq in texts:
            body = [
                self._ids.get(ch, self.unk_id) for ch in seq.upper().strip()
            ]
            rows.append([self.cls_id] + body[:body_limit] + [self.eos_id])
        return self._pad_to_bucket(rows, self.pad_id, max_length)

    def decode(self, ids: Sequence[int]) -> str:
        out = []
        for tid in ids:
            tok = self.vocab[int(tid)] if 0 <= int(tid) < self.vocab_size else '<unk>'
            if tok.startswith('<'):
                continue
            out.append(tok)
        return ''.join(out)
