"""ModernBERT encoder (answerdotai/ModernBERT class models) in pure JAX.

The reference embeds ModernBERT checkpoints through ``transformers.AutoModel``
(``distllm/embed/encoders/auto.py:119-138``; its README pairs the encoder with
nomic/ModernBERT embeddings). TPU-native redesign in the house style: one
``lax.scan`` over stacked layer params compiles a single layer body for all
22 layers, with the architecture's per-layer heterogeneity expressed as
traced *flag vectors* instead of Python branching (XLA-friendly):

- layer 0's attention pre-norm is Identity (HF ``ModernBertEncoderLayer``)
  → ``attn_norm_flag[L]`` selects LN(x) vs x;
- every ``global_attn_every_n_layers``-th layer attends globally, the rest
  within a ``local_attention``-token sliding window (|i-j| <= window // 2)
  → ``global_flag[L]`` selects between the two precomputed masks AND
  between the two RoPE tables (global vs local theta).

Numerics follow HF ``ModernBertModel``: pre-LN residuals, bias-free GeGLU
MLP (``act(input) * gate``), RoPE (rotate-half layout), LayerNorm with
optional bias, final norm on the output. Verified against ``transformers``
in tests/test_modernbert.py.
"""

from __future__ import annotations

from typing import Literal

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from distllm_tpu.models import common
from distllm_tpu.utils import BaseConfig


class ModernBertConfig(BaseConfig):
    name: Literal['modernbert'] = 'modernbert'
    vocab_size: int = 50368
    hidden_size: int = 768
    num_layers: int = 22
    num_heads: int = 12
    intermediate_size: int = 1152
    norm_eps: float = 1e-5
    norm_bias: bool = False
    attention_bias: bool = False
    mlp_bias: bool = False
    global_attn_every_n_layers: int = 3
    local_attention: int = 128
    global_rope_theta: float = 160000.0
    local_rope_theta: float = 10000.0
    hidden_act: str = 'gelu'
    max_position_embeddings: int = 8192
    dtype: str = 'bfloat16'

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @classmethod
    def from_hf_config(cls, hf: dict) -> 'ModernBertConfig':
        return cls(
            vocab_size=hf['vocab_size'],
            hidden_size=hf['hidden_size'],
            num_layers=hf['num_hidden_layers'],
            num_heads=hf['num_attention_heads'],
            intermediate_size=hf['intermediate_size'],
            norm_eps=hf.get('norm_eps', 1e-5),
            norm_bias=hf.get('norm_bias', False),
            attention_bias=hf.get('attention_bias', False),
            mlp_bias=hf.get('mlp_bias', False),
            global_attn_every_n_layers=hf.get('global_attn_every_n_layers', 3),
            local_attention=hf.get('local_attention', 128),
            global_rope_theta=hf.get('global_rope_theta', 160000.0),
            local_rope_theta=hf.get('local_rope_theta', 10000.0),
            hidden_act=hf.get('hidden_activation', 'gelu'),
            max_position_embeddings=hf.get('max_position_embeddings', 8192),
        )


def _ln(size):
    return {
        'scale': np.ones((size,), np.float32),
        'bias': np.zeros((size,), np.float32),
    }


def init(rng: jax.Array, cfg: ModernBertConfig) -> dict:
    """Random-init params (tests/benchmarks); layout matches params_from_hf."""
    h, inter = cfg.hidden_size, cfg.intermediate_size
    scale = 0.02

    def normal(key, shape):
        return np.asarray(jax.random.normal(key, shape) * scale, np.float32)

    keys = jax.random.split(rng, 4)
    layers = []
    for li in range(cfg.num_layers):
        ks = jax.random.split(jax.random.fold_in(keys[0], li), 7)

        def lin(key, shape, biased):
            out = {'kernel': normal(key, shape)}
            if biased:
                out['bias'] = np.zeros((shape[-1],), np.float32)
            return out

        layers.append(
            {
                'attn_norm': _ln(h),
                'q': lin(ks[0], (h, h), cfg.attention_bias),
                'k': lin(ks[1], (h, h), cfg.attention_bias),
                'v': lin(ks[2], (h, h), cfg.attention_bias),
                'o': lin(ks[3], (h, h), cfg.attention_bias),
                'mlp_norm': _ln(h),
                'wi_in': lin(ks[4], (h, inter), cfg.mlp_bias),
                'wi_gate': lin(ks[5], (h, inter), cfg.mlp_bias),
                'wo': lin(ks[6], (inter, h), cfg.mlp_bias),
            }
        )
    return {
        'embed': normal(keys[1], (cfg.vocab_size, h)),
        'embed_norm': _ln(h),
        'final_norm': _ln(h),
        'layers': common.stack_layers(layers),
        'attn_norm_flag': _attn_norm_flags(cfg),
        'global_flag': _global_flags(cfg),
    }


def _attn_norm_flags(cfg: ModernBertConfig) -> np.ndarray:
    """1.0 where the attention pre-norm applies (HF: Identity on layer 0)."""
    flags = np.ones((cfg.num_layers, 1), np.float32)
    flags[0] = 0.0
    return flags


def _global_flags(cfg: ModernBertConfig) -> np.ndarray:
    """1.0 for global-attention layers (every n-th, counting from 0)."""
    return np.asarray(
        [
            [1.0 if li % cfg.global_attn_every_n_layers == 0 else 0.0]
            for li in range(cfg.num_layers)
        ],
        np.float32,
    )


def apply(
    params: dict,
    cfg: ModernBertConfig,
    input_ids: jnp.ndarray,  # [B, S]
    attention_mask: jnp.ndarray,  # [B, S]
    attn_impl: str = 'auto',
) -> jnp.ndarray:
    """Forward: ``[B, S]`` ids/mask → ``[B, S, H]`` final hidden states.

    ``attn_impl`` as in ``bert.apply`` (shared policy,
    ops/encoder_attention.py resolve_use_pallas); the Pallas path carries
    the sliding-window mask of local layers as an additive ``[S, S]`` bias,
    so both global and local layers run the kernel.
    """
    dtype = jnp.dtype(cfg.dtype)
    act = common.ACTIVATIONS[cfg.hidden_act]
    seq = input_ids.shape[1]
    eps = cfg.norm_eps
    from distllm_tpu.ops.encoder_attention import (
        encoder_attention,
        resolve_use_pallas,
    )

    use_pallas = resolve_use_pallas(
        attn_impl, seq, cfg.hidden_size, cfg.num_heads, cfg.dtype,
        has_bias=True,
    )

    def maybe_bias(p):
        return p.get('bias') if isinstance(p, dict) else None

    def ln(h, p):
        return common.layer_norm(
            h.astype(jnp.float32), p['scale'], p['bias'], eps
        ).astype(dtype)

    cos_g, sin_g = common.rope_frequencies(
        cfg.head_dim, seq, cfg.global_rope_theta
    )
    cos_l, sin_l = common.rope_frequencies(
        cfg.head_dim, seq, cfg.local_rope_theta
    )
    cos_g, sin_g = jnp.asarray(cos_g), jnp.asarray(sin_g)
    cos_l, sin_l = jnp.asarray(cos_l), jnp.asarray(sin_l)

    # [B, 1, S, S] masks: padding-only (global) and padding+window (local).
    key_valid = attention_mask.astype(bool)[:, None, None, :]
    distance = jnp.abs(
        jnp.arange(seq)[:, None] - jnp.arange(seq)[None, :]
    )
    window = (distance <= cfg.local_attention // 2)[None, None]
    local_valid = key_valid & window
    # Pallas path: the window becomes an additive [S, S] score bias (key
    # padding rides separately as the kernel's [B, S] mask operand).
    window_bias = jnp.where(window[0, 0], 0.0, -1e9).astype(jnp.float32)

    x = ln(jnp.asarray(params['embed'])[input_ids], params['embed_norm'])

    def layer(x, per_layer):
        lp, attn_norm_flag, global_flag = per_layer
        normed = ln(x, lp['attn_norm'])
        # Layer 0: HF uses Identity for the attention pre-norm.
        normed = jnp.where(attn_norm_flag > 0, normed, x)
        # Q/K/V stored as separate column-sharded kernels (HF's fused Wqkv
        # is split at load time): under TP, splitting a fused [B, S, 3H]
        # activation at non-shard-aligned offsets would force per-layer
        # resharding collectives.
        q = common.split_heads(
            common.dense(normed, lp['q']['kernel'], maybe_bias(lp['q'])),
            cfg.num_heads,
        )
        k = common.split_heads(
            common.dense(normed, lp['k']['kernel'], maybe_bias(lp['k'])),
            cfg.num_heads,
        )
        v = common.split_heads(
            common.dense(normed, lp['v']['kernel'], maybe_bias(lp['v'])),
            cfg.num_heads,
        )
        is_global = global_flag > 0
        cos = jnp.where(is_global, cos_g, cos_l)
        sin = jnp.where(is_global, sin_g, sin_l)
        q = common.apply_rope(q, cos, sin)
        k = common.apply_rope(k, cos, sin)
        if use_pallas:
            # merge_heads is a reshape (no transpose); heads stay packed.
            # Global layers zero the window bias via the traced flag.
            attn = encoder_attention(
                common.merge_heads(q),
                common.merge_heads(k),
                common.merge_heads(v),
                attention_mask,
                cfg.num_heads,
                bias=jnp.where(is_global, 0.0, window_bias),
            )
        else:
            mask = jnp.where(is_global, key_valid, local_valid)
            attn = common.merge_heads(common.sdpa(q, k, v, mask=mask))
        x = x + common.dense(attn, lp['o']['kernel'], maybe_bias(lp['o']))
        normed2 = ln(x, lp['mlp_norm'])
        gate_in = common.dense(
            normed2, lp['wi_in']['kernel'], maybe_bias(lp['wi_in'])
        )
        gate = common.dense(
            normed2, lp['wi_gate']['kernel'], maybe_bias(lp['wi_gate'])
        )
        mlp = common.dense(
            act(gate_in) * gate, lp['wo']['kernel'], maybe_bias(lp['wo'])
        )
        return x + mlp, None

    x, _ = jax.lax.scan(
        layer,
        x,
        (
            params['layers'],
            jnp.asarray(params['attn_norm_flag']),
            jnp.asarray(params['global_flag']),
        ),
    )
    return common.layer_norm(
        x.astype(jnp.float32),
        params['final_norm']['scale'],
        params['final_norm']['bias'],
        eps,
    )


def param_specs(cfg: ModernBertConfig) -> dict:
    """Megatron-style TP over the ``model`` axis (QKV/Wi column, O/Wo row)."""
    ln = {'scale': P(None), 'bias': P(None)}
    return {
        'embed': P(None, None),
        'embed_norm': dict(ln),
        'final_norm': dict(ln),
        'attn_norm_flag': P(None, None),
        'global_flag': P(None, None),
        'layers': {
            'attn_norm': dict(ln),
            'q': {'kernel': P(None, None, 'model')},
            'k': {'kernel': P(None, None, 'model')},
            'v': {'kernel': P(None, None, 'model')},
            'o': {'kernel': P(None, 'model', None)},
            'mlp_norm': dict(ln),
            'wi_in': {'kernel': P(None, None, 'model')},
            'wi_gate': {'kernel': P(None, None, 'model')},
            'wo': {'kernel': P(None, 'model', None)},
        },
    }


def params_from_hf(state: dict[str, np.ndarray], cfg: ModernBertConfig) -> dict:
    """Convert an HF ``ModernBertModel`` state dict to this module's tree.

    Accepts both the bare-model layout (``layers.0...``) and the
    task-model layout (``model.layers.0...``). Layer 0 ships no
    ``attn_norm`` weights (Identity) — identity LN params are substituted
    and the flag vector masks the norm out.
    """
    sd = {k.removeprefix('model.'): v for k, v in state.items()}

    def lin(prefix):
        out = {'kernel': np.ascontiguousarray(sd[f'{prefix}.weight'].T)}
        if f'{prefix}.bias' in sd:
            out['bias'] = sd[f'{prefix}.bias']
        return out

    def ln(prefix, size):
        if f'{prefix}.weight' not in sd:  # layer 0 Identity attn_norm
            return _ln(size)
        return {
            'scale': sd[f'{prefix}.weight'],
            'bias': sd.get(
                f'{prefix}.bias',
                np.zeros_like(sd[f'{prefix}.weight']),
            ),
        }

    def split_cols(linear: dict, n: int) -> list[dict]:
        """Split a fused [in, n*out] linear into n separate kernels (TP
        wants each column-sharded on its own)."""
        kernels = np.split(linear['kernel'], n, axis=1)
        outs = [{'kernel': np.ascontiguousarray(kk)} for kk in kernels]
        if 'bias' in linear:
            for out, bb in zip(outs, np.split(linear['bias'], n)):
                out['bias'] = bb
        return outs

    h = cfg.hidden_size
    layers = []
    for i in range(cfg.num_layers):
        p = f'layers.{i}'
        q, k, v = split_cols(lin(f'{p}.attn.Wqkv'), 3)
        wi_in, wi_gate = split_cols(lin(f'{p}.mlp.Wi'), 2)
        layers.append(
            {
                'attn_norm': ln(f'{p}.attn_norm', h),
                'q': q,
                'k': k,
                'v': v,
                'o': lin(f'{p}.attn.Wo'),
                'mlp_norm': ln(f'{p}.mlp_norm', h),
                'wi_in': wi_in,
                'wi_gate': wi_gate,
                'wo': lin(f'{p}.mlp.Wo'),
            }
        )
    return {
        'embed': sd['embeddings.tok_embeddings.weight'],
        'embed_norm': ln('embeddings.norm', h),
        'final_norm': ln('final_norm', h),
        'layers': common.stack_layers(layers),
        'attn_norm_flag': _attn_norm_flags(cfg),
        'global_flag': _global_flags(cfg),
    }
