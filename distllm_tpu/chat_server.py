"""OpenAI-compatible RAG chat server (aiohttp).

Reference parity: ``distllm/chat_server.py`` — ``POST /v1/chat/completions``
plus ``GET /health``; OpenAI messages are folded into the conversation
template; RAG runs in a worker thread (the event loop stays free); optional
single-delta SSE streaming; request extensions ``top_k`` and
``score_threshold``; config path from the ``DISTLLM_CHAT_CONFIG`` env var;
permissive CORS. FastAPI is unavailable in this environment, so the server
is aiohttp.

Serving note: prompts render as system prompt + retrieved contexts +
conversation — a shared, growing prefix across a session's turns — so the
in-process TPU engine runs with automatic prefix caching on by default
(``ChatAppConfig.build_generator``; knobs/metrics in
docs/prefix_caching.md, ``distllm_prefix_cache_*`` series at /metrics).

Observability surface (docs/observability.md):

- ``GET /metrics`` — Prometheus text exposition of the process registry
  (engine throughput, KV occupancy, queue depth, HTTP latency, request
  TTFT/TPOT/queue-wait, ...);
- ``GET /health`` — liveness plus uptime / in-flight / served counts;
- ``GET /loadinfo`` — cheap JSON load probe for the multi-replica router
  (queue depth, readiness, drain state, KV occupancy; docs/routing.md) —
  per-app/per-engine state, never a Prometheus text parse;
- ``GET /debug/traces?limit=N`` — most recent spans from the trace ring;
- ``GET /debug/flight?limit=N`` — most recent engine flight-recorder
  records (prefill/decode steps, request lifecycles, preemptions);
- ``GET /debug/perfetto?limit=N`` — the flight + span rings rendered as a
  Perfetto/``chrome://tracing`` trace-event JSON (open it at
  https://ui.perfetto.dev), request-id-correlated tracks included;
- ``GET /debug/history?limit=N&prefix=...`` — the metric-history ring
  (``observability/history.py``, ``distllm-history/v1`` schema): retained
  counter rates / gauge values / histogram quantile snapshots, sampled
  every ``DISTLLM_HISTORY_S`` seconds (default 1; 0 disables the
  sampler) by a background thread started with the app and stopped on
  cleanup;
- ``GET /debug/slo`` — the ``slo_status()`` ok/warn/page document
  (multi-window burn rates over ``distllm_request_slo_total``) plus the
  regression-sentinel state; arm the sentinel with
  ``DISTLLM_BASELINE=<envelope path>`` (written by
  ``scripts/benchdiff.py --emit-baseline``) — a missing baseline is a
  counted disarm, never a startup failure;
- ``GET /debug/bundle`` — dump a full debug bundle (flight ring + metrics
  + traces + perfetto.json + startup.json + history.json + slo.json) to
  disk and return the written paths;
- ``GET /debug/xprof?seconds=N`` — bounded on-demand ``jax.profiler``
  capture to disk (one at a time; errors reported, never fatal).

Resilience surface (docs/resilience.md): an engine running SLO-aware
admission control sheds over-SLO requests as **429** with an honest
``Retry-After`` header; ``POST /drain?seconds=N`` stops admitting (new
completions get 503 + ``Retry-After``), waits for in-flight requests,
and flips ``GET /health`` to ``{"status": "draining", "ready": false}``
with a 503 status — the readiness signal a multi-replica router polls
(``distllm_server_ready`` is the scrape twin). Draining is one-way per
process: a drained replica restarts (the disk KV tier makes the restart
warm) rather than un-drains.

Request-scoped tracing: every ``POST /v1/chat/completions`` accepts an
``X-Request-Id`` header (one is generated when absent), binds it around
the whole retrieve/generate path (``observability.request_scope`` — spans
and the engine's request lifecycle records carry it), and echoes it back
both as the ``X-Request-Id`` response header and a ``request_id`` field in
the completion payload.

Multi-replica routing (docs/routing.md): every completion response
carries ``X-Distllm-Prefix-Digest`` + ``X-Distllm-Prefix-Depth`` — the
byte-level prefix digest chain the router's affinity maps learn replica
cache residency from (``router/affinity.py``; same chained hashing the
KV tiers key on).

Generation requests run under an optional stall watchdog
(``DISTLLM_WATCHDOG_S`` seconds, 0 = off): if the engine makes no
progress for that long mid-request, a debug bundle is dumped
automatically — the wedge explains itself even if the process is later
killed.

Run: ``DISTLLM_CHAT_CONFIG=cfg.yaml python -m distllm_tpu.chat_server --port 8000``
"""

from __future__ import annotations

import argparse
import asyncio
import json
import math
import os
import re
import time
import uuid

import distllm_tpu
from distllm_tpu.chat import ChatAppConfig, ChatSession
from distllm_tpu.resilience import EngineOverloaded
from distllm_tpu.router.affinity import (
    HEADER_DEPTH,
    HEADER_DIGEST,
    prompt_prefix_digests,
)
from distllm_tpu.observability import (
    HistorySampler,
    StallWatchdog,
    dump_debug_bundle,
    get_flight_recorder,
    get_metrics_history,
    get_profiler_capture,
    get_trace_buffer,
    install_regression_sentinel,
    install_slo_observer,
    instruments,
    render_prometheus,
    request_scope,
    slo_status,
    span,
    to_trace_events,
)

# Accepted inbound X-Request-Id shape; anything else (or nothing) gets a
# generated id — a client header must not be able to smuggle arbitrary
# bytes into trace attributes, flight records, and response headers.
_REQUEST_ID_RE = re.compile(r'^[A-Za-z0-9._:-]{1,128}$')


def _resolve_request_id(request) -> str:
    header = (request.headers.get('X-Request-Id') or '').strip()
    if _REQUEST_ID_RE.match(header):
        return header
    return f'req-{uuid.uuid4().hex[:16]}'


def _debug_dir(kind: str) -> str:
    """Where on-demand debug bundles land (``DISTLLM_DEBUG_DIR`` or
    ``./debug_bundles``), one timestamped directory per dump."""
    base = os.environ.get('DISTLLM_DEBUG_DIR') or os.path.join(
        os.getcwd(), 'debug_bundles'
    )
    stamp = time.strftime('%Y%m%d-%H%M%S')
    return os.path.join(base, f'{kind}_{stamp}_{os.getpid()}')


def _completion_payload(model: str, content: str, request_id: str) -> dict:
    return {
        'id': f'chatcmpl-{uuid.uuid4().hex[:24]}',
        'object': 'chat.completion',
        'created': int(time.time()),
        'model': model,
        'request_id': request_id,
        'choices': [
            {
                'index': 0,
                'message': {'role': 'assistant', 'content': content},
                'finish_reason': 'stop',
            }
        ],
        'usage': {
            'prompt_tokens': 0,
            'completion_tokens': 0,
            'total_tokens': 0,
        },
    }


def build_app(config: ChatAppConfig):
    from concurrent.futures import ThreadPoolExecutor

    from aiohttp import web

    session = ChatSession(config)
    template = session.template
    # Single-worker executor: the engine's scheduler/paged-KV state is NOT
    # thread-safe; concurrency comes from the engine's continuous batching,
    # not from parallel Python threads.
    executor = ThreadPoolExecutor(max_workers=1)
    started_at = time.time()

    # Known routes pre-register their latency/count series so the very
    # first /metrics scrape already carries the full schema.
    known_paths = (
        '/v1/chat/completions', '/health', '/metrics', '/drain', '/loadinfo',
    )
    for path in known_paths:
        instruments.HTTP_LATENCY.labels(path=path)

    # Continuous telemetry (docs/observability.md "Metric history"): one
    # background sampler folds the registry into the history ring every
    # DISTLLM_HISTORY_S seconds (default 1; 0/negative disables). The
    # SLO burn-rate observer and the regression sentinel ride the same
    # tick. The server owns the process sampler — engines only start
    # their own when EngineConfig.history_interval_s asks for one.
    instruments.SERVER_UPTIME.set(0.0)
    history = get_metrics_history()
    slo_observer = install_slo_observer(history)
    sentinel = install_regression_sentinel(
        history, baseline_path=os.environ.get('DISTLLM_BASELINE') or None
    )

    def _uptime_observer(h, now):
        instruments.SERVER_UPTIME.set(max(0.0, now - started_at))

    history.add_observer(_uptime_observer)
    history_interval_s = float(os.environ.get('DISTLLM_HISTORY_S', '1') or 0)
    sampler = (
        HistorySampler(history, interval_s=history_interval_s)
        if history_interval_s > 0
        else None
    )
    if sampler is not None:
        sampler.start()

    async def _stop_history(app) -> None:
        # on_cleanup: join the sampler thread (no leak after shutdown —
        # asserted by tests) and detach this app's observers so a later
        # build_app in the same process doesn't double-tick them.
        if sampler is not None:
            sampler.stop()
        history.remove_observer(_uptime_observer)
        history.remove_observer(slo_observer)
        sentinel.uninstall()

    # Drain lifecycle (docs/resilience.md): POST /drain flips this, new
    # completions get 503 + Retry-After while in-flight ones finish, and
    # /health turns not-ready (503) so a multi-replica router stops
    # sending traffic here. One-way per process by design — a drained
    # replica restarts rather than un-drains (restart is the recovery
    # unit the disk KV tier makes cheap). The SERVER_READY gauge is
    # process-wide and LATCHES that semantic: it starts at 1.0 (set at
    # instruments import) and only /drain ever writes it, so building a
    # second app in a process where an earlier app drained cannot
    # re-declare the process ready to the router — the conservative
    # reading for a scrape-driven route-away decision.
    # completions_in_flight counts ONLY /v1/chat/completions work (the
    # middleware's HTTP_IN_FLIGHT also counts the health/metrics polls a
    # draining server explicitly invites, which would keep /drain's wait
    # spuriously nonzero).
    state = {'draining': False, 'completions_in_flight': 0}

    def answer(messages, top_k, score_threshold, request_id):
        """Stateless per-request RAG (history comes from the client).

        Runs inside ``request_scope(request_id)`` (bound HERE, in the
        executor thread — ``run_in_executor`` does not carry the event
        loop's context over): the retrieve/generate spans and the
        engine's request lifecycle all pick up the propagated id.
        """
        with request_scope(request_id):
            return _answer_in_scope(messages, top_k, score_threshold)

    def _answer_in_scope(messages, top_k, score_threshold):
        latest = next(
            (m['content'] for m in reversed(messages) if m['role'] == 'user'),
            '',
        )
        contexts, scores = [], []
        if session.retriever is not None and latest:
            with span('chat-retrieve', top_k=top_k):
                results, _ = session.retriever.search(
                    latest, top_k=top_k, score_threshold=score_threshold
                )
                indices = results.total_indices[0]
                contexts = (
                    session.retriever.get_texts(indices) if indices else []
                )
                scores = results.total_scores[0]
        prompt = template.render(list(messages), contexts, scores)
        watchdog_s = float(os.environ.get('DISTLLM_WATCHDOG_S', '0') or 0)
        with span('chat-generate'):
            if watchdog_s <= 0:
                return session.generator.generate([prompt])[0]
            # Armed per request (an idle server is not a stall): if the
            # engine's flight ring stops advancing mid-generate, dump a
            # bundle so the wedge explains itself. Never kills the work.
            with StallWatchdog(
                watchdog_s,
                bundle_dir=_debug_dir('watchdog'),
                name='chat-generate',
            ):
                return session.generator.generate([prompt])[0]

    async def chat_completions(request: 'web.Request') -> 'web.StreamResponse':
        if state['draining']:
            # Drain lifecycle: stop admitting, finish in-flight. 503 (not
            # 429): the replica is going away, the client should try
            # another one, soon.
            instruments.RESILIENCE_SHED.labels(reason='draining').inc()
            get_flight_recorder().record('shed', reason='draining')
            return web.json_response(
                {'error': {'message': 'server is draining', 'type':
                           'draining'}},
                status=503,
                headers={'Retry-After': '5'},
            )
        body = await request.json()
        messages = body.get('messages', [])
        if not messages:
            return web.json_response(
                {'error': {'message': 'messages is required'}}, status=400
            )
        top_k = int(body.get('top_k', config.retrieval_top_k))
        score_threshold = float(
            body.get('score_threshold', config.retrieval_score_threshold)
        )
        model = body.get('model', 'distllm-tpu')
        request_id = _resolve_request_id(request)
        loop = asyncio.get_running_loop()
        state['completions_in_flight'] += 1
        try:
            content = await loop.run_in_executor(
                executor, answer, messages, top_k, score_threshold,
                request_id,
            )
        # distlint: disable=swallowed-exception -- the shed is fully surfaced: the engine already counted + flight-recorded it, and the 429 below lands in the HTTP middleware's status-class metric
        except EngineOverloaded as exc:
            # SLO-aware shedding (docs/resilience.md): the engine
            # predicted this request's TTFT would bust the SLO and
            # refused it at enqueue — surface the honest 429 the
            # prediction priced, instead of a response that arrives
            # after the client gave up.
            return web.json_response(
                {
                    'error': {
                        'message': str(exc),
                        'type': 'overloaded',
                        'predicted_ttft_s': round(
                            exc.predicted_ttft_s, 3
                        ),
                    },
                    'request_id': request_id,
                },
                status=429,
                headers={
                    'Retry-After': str(
                        max(1, math.ceil(exc.retry_after_s))
                    ),
                    'X-Request-Id': request_id,
                },
            )
        finally:
            state['completions_in_flight'] -= 1
        # Affinity-learning headers (docs/routing.md "Digest learning"):
        # having served this request, the replica now holds its whole
        # prompt prefix — advertise the deepest byte-chain digest + depth
        # so the router's per-replica map learns where the blocks live.
        # The router verifies the digest against its own chain before
        # trusting the sample, so the header can never poison routing.
        digest_headers = {'X-Request-Id': request_id}
        chain = prompt_prefix_digests(messages)
        if chain:
            digest_headers[HEADER_DIGEST] = chain[-1].hex()
            digest_headers[HEADER_DEPTH] = str(len(chain))
        if body.get('stream'):
            # Single-delta SSE streaming (reference ``chat_server.py:168-270``).
            response = web.StreamResponse(
                headers={
                    'Content-Type': 'text/event-stream',
                    'Cache-Control': 'no-cache',
                    **digest_headers,
                }
            )
            await response.prepare(request)
            chunk = {
                'id': f'chatcmpl-{uuid.uuid4().hex[:24]}',
                'object': 'chat.completion.chunk',
                'created': int(time.time()),
                'model': model,
                'request_id': request_id,
                'choices': [
                    {
                        'index': 0,
                        'delta': {'role': 'assistant', 'content': content},
                        'finish_reason': 'stop',
                    }
                ],
            }
            await response.write(
                f'data: {json.dumps(chunk)}\n\n'.encode()
            )
            await response.write(b'data: [DONE]\n\n')
            await response.write_eof()
            return response
        return web.json_response(
            _completion_payload(model, content, request_id),
            headers=digest_headers,
        )

    async def health(request: 'web.Request') -> 'web.Response':
        # In-flight includes this very request; report the others.
        in_flight = max(0, int(instruments.HTTP_IN_FLIGHT.value) - 1)
        draining = state['draining']
        instruments.SERVER_UPTIME.set(max(0.0, time.time() - started_at))
        # Readiness for the multi-replica router (ROADMAP item 2): the
        # body carries the flag AND the status code flips to 503 while
        # draining, so both field-readers and code-readers route away.
        return web.json_response(
            {
                'status': 'draining' if draining else 'ok',
                'ready': not draining,
                'draining': draining,
                'version': distllm_tpu.__version__,
                'uptime_s': round(time.time() - started_at, 3),
                'in_flight': in_flight,
                'requests_served': int(instruments.HTTP_RESPONSES.value),
            },
            status=503 if draining else 200,
        )

    async def drain(request: 'web.Request') -> 'web.Response':
        """POST /drain: stop admitting, finish in-flight
        (docs/resilience.md "Drain lifecycle"). Flips /health to
        not-ready immediately, then waits (bounded by ``?seconds=N``,
        default 30) for in-flight completions to finish; ``drained`` in
        the response says whether the wait emptied the server."""
        try:
            wait_s = float(request.query.get('seconds', '30'))
        # distlint: disable=swallowed-exception -- input validation surfaced to the client as a 400 and counted by the HTTP middleware's status-class metric
        except ValueError:
            return web.json_response(
                {'error': {'message': 'seconds must be a number'}},
                status=400,
            )
        if not math.isfinite(wait_s):
            return web.json_response(
                {'error': {'message': 'seconds must be finite'}},
                status=400,
            )
        wait_s = min(max(wait_s, 0.0), 300.0)
        state['draining'] = True
        instruments.SERVER_READY.set(0.0)
        get_flight_recorder().record('event', event='drain_started')
        deadline = time.monotonic() + wait_s

        def completions_in_flight() -> int:
            # ONLY completion work counts: the middleware's in-flight
            # gauge also sees the /health polls and /metrics scrapes a
            # draining server invites, which would report drained:false
            # with zero real work running.
            return max(0, int(state['completions_in_flight']))

        while completions_in_flight() > 0 and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        remaining = completions_in_flight()
        get_flight_recorder().record(
            'event', event='drain_finished', in_flight_remaining=remaining,
        )
        return web.json_response(
            {
                'draining': True,
                'drained': remaining == 0,
                'in_flight_remaining': remaining,
            }
        )

    async def loadinfo(request: 'web.Request') -> 'web.Response':
        """``GET /loadinfo`` — the router's hot-path load probe
        (docs/routing.md "Least-loaded fallback"): queue depth,
        readiness, drain state, and KV occupancy as a tiny JSON doc, so
        the router never parses Prometheus text per routing decision.
        ``/metrics`` stays unchanged for scrapes. Reads THIS app's drain
        flag and THIS engine's scheduler — unlike the process-wide
        gauges, correct even with several in-process replicas (the bench
        topology). Always 200: a draining replica still answers, the
        body says to route away."""
        engine = getattr(session.generator, 'engine', None)
        sched = getattr(engine, 'sched', None)
        queue_depth = running = 0
        kv_occupancy = 0.0
        if sched is not None:
            queue_depth = int(sched.num_waiting)
            running = int(sched.num_running)
            usable = max(1, int(engine.config.num_blocks) - 1)
            in_use = max(0, usable - int(sched.num_free_blocks))
            kv_occupancy = round(in_use / usable, 4)
        draining = state['draining']
        return web.json_response(
            {
                'ready': not draining,
                'draining': draining,
                'queue_depth': queue_depth,
                'running': running,
                'in_flight': int(state['completions_in_flight']),
                'kv_occupancy': kv_occupancy,
            }
        )

    async def metrics(request: 'web.Request') -> 'web.Response':
        return web.Response(
            body=render_prometheus().encode('utf-8'),
            headers={
                'Content-Type': 'text/plain; version=0.0.4; charset=utf-8'
            },
        )

    async def traces(request: 'web.Request') -> 'web.Response':
        try:
            limit = int(request.query.get('limit', '100'))
        # distlint: disable=swallowed-exception -- input validation surfaced to the client as a 400 and counted by the HTTP middleware's status-class metric
        except ValueError:
            return web.json_response(
                {'error': {'message': 'limit must be an integer'}}, status=400
            )
        spans = get_trace_buffer().snapshot(limit=max(1, limit))
        return web.json_response(
            {'spans': [s.to_dict() for s in spans if s.end_ns is not None]}
        )

    async def flight(request: 'web.Request') -> 'web.Response':
        try:
            limit = int(request.query.get('limit', '200'))
        # distlint: disable=swallowed-exception -- input validation surfaced to the client as a 400 and counted by the HTTP middleware's status-class metric
        except ValueError:
            return web.json_response(
                {'error': {'message': 'limit must be an integer'}}, status=400
            )
        recorder = get_flight_recorder()
        return web.json_response(
            {
                'records': recorder.snapshot(limit=max(1, limit)),
                'total_recorded': recorder.total_recorded,
                'capacity': recorder.capacity,
            }
        )

    async def perfetto(request: 'web.Request') -> 'web.Response':
        try:
            limit = int(request.query.get('limit', '2000'))
        # distlint: disable=swallowed-exception -- input validation surfaced to the client as a 400 and counted by the HTTP middleware's status-class metric
        except ValueError:
            return web.json_response(
                {'error': {'message': 'limit must be an integer'}}, status=400
            )
        limit = max(1, limit)

        def build() -> str:
            # Rendering + sorting thousands of events is real CPU work;
            # like bundle(), keep it off the event loop (default pool,
            # not the single-worker engine executor).
            doc = to_trace_events(
                get_flight_recorder().snapshot(limit=limit),
                [
                    s.to_dict()
                    for s in get_trace_buffer().snapshot(limit=limit)
                    if s.end_ns is not None
                ],
                history=history.snapshot(limit=limit),
            )
            return json.dumps(doc)

        loop = asyncio.get_running_loop()
        body = await loop.run_in_executor(None, build)
        return web.Response(
            body=body.encode('utf-8'),
            headers={'Content-Type': 'application/json'},
        )

    async def history_endpoint(request: 'web.Request') -> 'web.Response':
        """``GET /debug/history?limit=N&prefix=...`` — the retained
        metric history (``distllm-history/v1`` schema; limit trims each
        series to its newest N points, default 120)."""
        try:
            limit = int(request.query.get('limit', '120'))
        # distlint: disable=swallowed-exception -- input validation surfaced to the client as a 400 and counted by the HTTP middleware's status-class metric
        except ValueError:
            return web.json_response(
                {'error': {'message': 'limit must be an integer'}}, status=400
            )
        prefix = request.query.get('prefix') or None
        doc = history.snapshot(limit=max(1, limit), prefix=prefix)
        doc['sampler_running'] = bool(sampler is not None and sampler.running)
        return web.json_response(doc)

    async def slo_endpoint(request: 'web.Request') -> 'web.Response':
        """``GET /debug/slo`` — burn-rate verdict + sentinel state (the
        per-replica signal feed for the multi-replica router)."""
        instruments.SERVER_UPTIME.set(max(0.0, time.time() - started_at))
        return web.json_response(
            {**slo_status(history), 'sentinel': sentinel.status()}
        )

    async def bundle(request: 'web.Request') -> 'web.Response':
        directory = _debug_dir('bundle')
        # Default thread pool, NOT the single-worker engine executor: the
        # dump (disk writes + possible device-memory capture) must neither
        # freeze the event loop nor queue behind a wedged generate — a
        # wedge is exactly when this endpoint gets called.
        loop = asyncio.get_running_loop()
        paths = await loop.run_in_executor(
            None,
            lambda: dump_debug_bundle(directory, reason='GET /debug/bundle'),
        )
        return web.json_response({'bundle_dir': directory, 'paths': paths})

    async def xprof(request: 'web.Request') -> 'web.Response':
        """On-demand bounded profiler capture (observability/profiling.py):
        ``GET /debug/xprof?seconds=N`` blocks for N seconds of
        ``jax.profiler`` capture and returns the trace directory (XPlane +
        TensorBoard format). One capture at a time — a concurrent request
        gets 409; an unsupported backend gets 501, never a dead server."""
        try:
            seconds = float(request.query.get('seconds', '2'))
        # distlint: disable=swallowed-exception -- the NaN sentinel routes to the 400 response two lines down; the client-surfaced status is the signal
        except ValueError:
            seconds = math.nan
        # NaN passes float() and slides through min/max clamps unchanged.
        if not math.isfinite(seconds):
            return web.json_response(
                {'error': {'message': 'seconds must be a finite number'}},
                status=400,
            )
        seconds = min(max(seconds, 0.1), 60.0)
        directory = _debug_dir('xprof')
        capture = get_profiler_capture()
        # Default thread pool (like bundle/perfetto): the capture sleep
        # must not freeze the event loop or queue behind a wedged
        # generate — a wedge is exactly when an operator wants a profile.
        loop = asyncio.get_running_loop()
        result = await loop.run_in_executor(
            None, lambda: capture.capture(directory, seconds)
        )
        status = (
            200 if result['ok'] else 409 if result['rejected'] else 501
        )
        return web.json_response(
            {**result, 'seconds': seconds, 'state': capture.state()},
            status=status,
        )

    async def preflight(request: 'web.Request') -> 'web.Response':
        return web.Response(status=204)

    @web.middleware
    async def cors(request, handler):
        path = request.path if request.path in known_paths else 'other'
        instruments.HTTP_IN_FLIGHT.inc()
        start = time.perf_counter()
        status = 500
        try:
            response = await handler(request)
            status = response.status
        except web.HTTPException as exc:
            status = exc.status
            raise
        finally:
            instruments.HTTP_IN_FLIGHT.dec()
            instruments.HTTP_LATENCY.labels(path=path).observe(
                time.perf_counter() - start
            )
            instruments.HTTP_REQUESTS.labels(
                path=path, status=f'{status // 100}xx'
            ).inc()
            instruments.HTTP_RESPONSES.inc()
        response.headers['Access-Control-Allow-Origin'] = '*'
        response.headers['Access-Control-Allow-Headers'] = '*'
        response.headers['Access-Control-Allow-Methods'] = 'GET, POST, OPTIONS'
        return response

    app = web.Application(middlewares=[cors])
    app.router.add_post('/v1/chat/completions', chat_completions)
    app.router.add_get('/health', health)
    app.router.add_post('/drain', drain)
    app.router.add_get('/metrics', metrics)
    app.router.add_get('/loadinfo', loadinfo)
    app.router.add_get('/debug/traces', traces)
    app.router.add_get('/debug/flight', flight)
    app.router.add_get('/debug/perfetto', perfetto)
    app.router.add_get('/debug/history', history_endpoint)
    app.router.add_get('/debug/slo', slo_endpoint)
    app.router.add_get('/debug/bundle', bundle)
    app.router.add_get('/debug/xprof', xprof)
    # Browser preflight for any path (CORS headers added by the middleware).
    app.router.add_route('OPTIONS', '/{tail:.*}', preflight)
    app.on_cleanup.append(_stop_history)
    return app


def main(argv: list[str] | None = None) -> int:
    from distllm_tpu.utils import apply_platform_env

    apply_platform_env()
    from aiohttp import web

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--config', type=str, default=None)
    parser.add_argument('--host', default='0.0.0.0')
    parser.add_argument('--port', type=int, default=8000)
    args = parser.parse_args(argv)

    # Attribute the REAL backend init here, before the session/engine
    # build touches the device through weight loading — a wedged PJRT
    # client init is otherwise invisible (the r03/r04 failure mode).
    from distllm_tpu.observability import record_backend_init

    record_backend_init()

    config_path = args.config or os.environ.get('DISTLLM_CHAT_CONFIG')
    config = (
        ChatAppConfig.from_yaml(config_path) if config_path else ChatAppConfig()
    )
    web.run_app(build_app(config), host=args.host, port=args.port)
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
