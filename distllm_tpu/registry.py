"""Warmstart registry: keep ONE expensive object alive across work items.

Behavioral parity target: ``distllm/registry.py:44-207`` — persistent workers
process many files via repeated pool ``map`` calls; reloading a model (and on
TPU, recompiling its jitted functions) per file would dominate runtime. The
registry caches a single active object keyed by a hash of its constructor
arguments; a request with different arguments shuts the old object down and
builds the new one.

TPU-specific addition: the cached object typically owns device-resident params
*and* compiled executables, so eviction calls an optional ``shutdown()`` hook
(to drop HBM references) and the cache key incorporates the factory identity,
so e.g. an encoder and a generator never collide.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import json
import threading
from dataclasses import dataclass
from typing import Any, Callable, TypeVar

T = TypeVar('T')


def _normalize(obj: Any) -> Any:
    """Reduce kwargs to a deterministic JSON-able structure.

    Address-based ``repr`` fallbacks would make every call a cache miss (a
    silent warmstart defeat, rebuilding the model per file), so structured
    objects are decomposed by value first.
    """
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    if isinstance(obj, dict):
        return {str(k): _normalize(v) for k, v in sorted(obj.items(), key=lambda kv: str(kv[0]))}
    if isinstance(obj, (list, tuple, set, frozenset)):
        items = [_normalize(v) for v in obj]
        return sorted(items, key=repr) if isinstance(obj, (set, frozenset)) else items
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {'__dc__': type(obj).__qualname__, **_normalize(dataclasses.asdict(obj))}
    dump = getattr(obj, 'model_dump', None)  # pydantic configs
    if callable(dump):
        return {'__model__': type(obj).__qualname__, **_normalize(dump())}
    return repr(obj)


def _stable_hash(obj: Any) -> str:
    """Deterministic hash of a kwargs structure (by value, not identity)."""
    payload = json.dumps(_normalize(obj), sort_keys=True)
    return hashlib.sha256(payload.encode()).hexdigest()


@dataclass
class _Entry:
    key: str
    value: Any


class WarmstartRegistry:
    """Process-wide cache holding at most one active object per slot.

    ``slots`` exist so that independent object families (encoder vs generator)
    can each keep one instance warm — a deliberate, small extension of the
    reference's single-slot design (``registry.py:90-132``) that matches how
    TPU RAG workers need both a query encoder and a generation engine resident
    at once.
    """

    def __init__(self, max_slots: int = 2) -> None:
        self._lock = threading.RLock()
        self._slots: dict[str, _Entry] = {}  # guarded by self._lock
        self._max_slots = max_slots

    def get(
        self,
        factory: Callable[..., T],
        slot: str | None = None,
        **kwargs: Any,
    ) -> T:
        """Return the cached object for (factory, kwargs), building if needed.

        A cache miss with a pre-existing entry in the same slot shuts the old
        object down first (its HBM buffers become collectible before the new
        model loads — important when two models don't fit together).
        """
        slot = slot or getattr(factory, '__qualname__', repr(factory))
        key = _stable_hash(
            {'factory': getattr(factory, '__qualname__', repr(factory)), 'kwargs': kwargs}
        )
        with self._lock:
            entry = self._slots.get(slot)
            if entry is not None and entry.key == key:
                return entry.value
            if entry is not None:
                self._shutdown(entry.value)
                del self._slots[slot]
            if len(self._slots) >= self._max_slots:
                # Evict the oldest slot (insertion order).
                victim = next(iter(self._slots))
                self._shutdown(self._slots.pop(victim).value)
            value = factory(**kwargs)
            self._slots[slot] = _Entry(key=key, value=value)
            return value

    def clear(self) -> None:
        with self._lock:
            for entry in self._slots.values():
                self._shutdown(entry.value)
            self._slots.clear()

    @property
    def active(self) -> dict[str, Any]:
        with self._lock:
            return {slot: e.value for slot, e in self._slots.items()}

    @staticmethod
    def _shutdown(value: Any) -> None:
        shutdown = getattr(value, 'shutdown', None)
        if callable(shutdown):
            try:
                shutdown()
            except Exception:  # noqa: BLE001 - eviction must not fail
                pass


_REGISTRY: WarmstartRegistry | None = None
_REGISTRY_LOCK = threading.Lock()


def registry() -> WarmstartRegistry:
    """Process-wide singleton accessor."""
    global _REGISTRY
    with _REGISTRY_LOCK:
        if _REGISTRY is None:
            _REGISTRY = WarmstartRegistry()
        return _REGISTRY


def register(slot: str | None = None) -> Callable[[Callable[..., T]], Callable[..., T]]:
    """Decorator: route calls of a factory function through the registry.

    Analogue of the reference's ``@register`` (``registry.py:163-207``): the
    decorated factory returns a cached instance when called twice with the
    same kwargs, and swaps the active instance when kwargs change.
    """

    def deco(factory: Callable[..., T]) -> Callable[..., T]:
        import inspect

        sig = inspect.signature(factory)

        @functools.wraps(factory)
        def wrapper(*args: Any, **kwargs: Any) -> T:
            # Bind positionals to parameter names so make(5) and make(value=5)
            # hash identically and preserve the factory's calling convention.
            bound = sig.bind(*args, **kwargs)
            bound.apply_defaults()
            return registry().get(factory, slot=slot, **bound.arguments)

        return wrapper

    return deco
