"""Interactive RAG chat CLI.

Reference parity: ``distllm/chat.py`` and the argo-proxy variant
(``distllm/chat_argoproxy.py``): a REPL with conversation history, retrieval
on the LATEST user turn only (full history still goes into the prompt,
``chat.py:463-565``), a ``/inspect <query>`` command that prints retrieval
scores/attributes for debugging (``chat.py:362-424``), ``quit`` with
transcript save, and pluggable generator backends:

- ``http``  — OpenAI-compatible chat endpoint (the reference's vLLM server
  client, ``chat.py:124-171``); also covers Argo-proxy style endpoints
  (``chat_argoproxy.py:216-257``) via ``extra_body`` fields like ``user``.
- ``local`` — in-process paged-KV engine (no server needed).
- ``fake``  — deterministic echo for tests.

Config supports ``${env:VAR}`` substitution through BaseConfig (the
reference's ``chat_argoproxy.py:511-549`` feature).
"""

from __future__ import annotations

import argparse
import json
import time
from pathlib import Path
from typing import Any

from distllm_tpu.utils import BaseConfig


class ConversationPromptTemplate:
    """Render history + retrieved context into one prompt.

    Parity with the reference's conversation template (``chat.py:38-82``):
    the retrieval block is appended under a '[Context from retrieval]'
    header, then the full turn history, ending with 'assistant:'.
    """

    def __init__(self, system_prompt: str = '') -> None:
        self.system_prompt = system_prompt

    def render(
        self,
        history: list[dict[str, str]],
        contexts: list[str] | None = None,
        scores: list[float] | None = None,
    ) -> str:
        parts: list[str] = []
        if self.system_prompt:
            parts.append(self.system_prompt)
        if contexts:
            lines = [
                f'- (score {score:.3f}) {ctx}'
                for ctx, score in zip(contexts, scores or [0.0] * len(contexts))
            ]
            parts.append('[Context from retrieval]\n' + '\n'.join(lines))
        for turn in history:
            parts.append(f'{turn["role"]}: {turn["content"]}')
        parts.append('assistant:')
        return '\n\n'.join(parts)


def make_http_generator(
    base_url: str,
    model: str = 'default',
    api_key: str = '',
    temperature: float = 0.2,
    max_tokens: int = 1024,
    extra_body: dict[str, Any] | None = None,
    timeout: float = 300.0,
):
    """OpenAI-compatible HTTP backend — reuses :class:`ApiGenerator` (with
    its expo backoff) rather than maintaining a second client."""
    from distllm_tpu.generate.generators.api_backend import (
        ApiGenerator,
        ApiGeneratorConfig,
    )

    return ApiGenerator(
        ApiGeneratorConfig(
            provider='openai',  # an OpenAI-compatible server, whatever the
            # served model is named (e.g. a proxy hosting 'claude-*')
            openai_api_base=base_url,
            model=model,
            api_key=api_key,
            temperature=temperature,
            max_tokens=max_tokens,
            extra_body=extra_body or {},
            timeout=timeout,
        )
    )


class ChatAppConfig(BaseConfig):
    """YAML config for the chat apps (REPL + server)."""

    generator_config: dict[str, Any] = {'name': 'fake'}
    retriever_config: dict[str, Any] | None = None
    system_prompt: str = ''
    retrieval_top_k: int = 20
    retrieval_score_threshold: float = 0.1
    transcript_dir: Path | None = None

    def build_generator(self):
        backend = dict(self.generator_config)
        name = backend.pop('name', 'fake')
        if name == 'http':
            return make_http_generator(**backend)
        if name in ('tpu', 'vllm'):
            # Chat workloads are prefix-heavy by construction: the system
            # prompt and retrieved contexts lead every rendered prompt and
            # repeat across turns, so the engine's automatic prefix cache
            # (docs/prefix_caching.md) is on unless the config says
            # otherwise.
            backend.setdefault('enable_prefix_cache', True)
            # Server-side resilience defaults (docs/resilience.md): a
            # serving replica degrades per-request, never per-process —
            # a stuck request times out and frees its KV instead of
            # wedging a slot forever, and a failed window retries with
            # bounded backoff before quarantining only the affected
            # requests. Offline/batch callers building engines directly
            # keep the legacy propagate-first-exception contract.
            backend.setdefault('request_deadline_s', 120.0)
            backend.setdefault('max_dispatch_retries', 2)
        from distllm_tpu.generate import get_generator

        return get_generator({'name': name, **backend}, register=True)

    def build_retriever(self):
        if self.retriever_config is None:
            return None
        from distllm_tpu.rag.search import RetrieverConfig

        return RetrieverConfig(**self.retriever_config).get_retriever(
            register=True
        )


class ChatSession:
    """Drives one conversation; shared by the REPL and the server."""

    def __init__(self, config: ChatAppConfig) -> None:
        self.config = config
        self.generator = config.build_generator()
        self.retriever = config.build_retriever()
        self.template = ConversationPromptTemplate(config.system_prompt)
        self.history: list[dict[str, str]] = []

    def _retrieve(self, query: str) -> tuple[list[str], list[float]]:
        if self.retriever is None:
            return [], []
        results, _ = self.retriever.search(
            query,
            top_k=self.config.retrieval_top_k,
            score_threshold=self.config.retrieval_score_threshold,
        )
        indices = results.total_indices[0]
        contexts = self.retriever.get_texts(indices) if indices else []
        return contexts, results.total_scores[0]

    def ask(self, user_message: str) -> str:
        """One turn: retrieval on the latest message, history in prompt."""
        self.history.append({'role': 'user', 'content': user_message})
        contexts, scores = self._retrieve(user_message)
        prompt = self.template.render(self.history, contexts, scores)
        response = self.generator.generate([prompt])[0]
        self.history.append({'role': 'assistant', 'content': response})
        return response

    def inspect(self, query: str) -> list[dict[str, Any]]:
        """Retrieval-only debugging (``/inspect``; reference ``chat.py:362-424``)."""
        if self.retriever is None:
            return []
        results, _ = self.retriever.search(
            query, top_k=self.config.retrieval_top_k, score_threshold=-1e9
        )
        indices = results.total_indices[0]
        texts = self.retriever.get_texts(indices) if indices else []
        return [
            {'index': idx, 'score': score, 'text': text}
            for idx, score, text in zip(
                indices, results.total_scores[0], texts
            )
        ]

    def save_transcript(self) -> Path | None:
        if self.config.transcript_dir is None or not self.history:
            return None
        self.config.transcript_dir.mkdir(parents=True, exist_ok=True)
        path = (
            self.config.transcript_dir
            / f'chat_{time.strftime("%Y%m%d_%H%M%S")}.json'
        )
        path.write_text(json.dumps(self.history, indent=2))
        return path


def chat_with_model(config: ChatAppConfig, input_fn=input, echo=print) -> None:
    """The REPL (reference ``chat_with_model``, ``chat.py:463-565``)."""
    session = ChatSession(config)
    echo('Chat ready. Commands: quit | /inspect <query>')
    while True:
        try:
            user_message = input_fn('you> ').strip()
        except (EOFError, KeyboardInterrupt):
            user_message = 'quit'
        if not user_message:
            continue
        if user_message.lower() in ('quit', 'exit'):
            path = session.save_transcript()
            if path:
                echo(f'Transcript saved to {path}')
            echo('bye')
            return
        if user_message.startswith('/inspect '):
            for hit in session.inspect(user_message[len('/inspect ') :]):
                echo(f'[{hit["index"]}] score={hit["score"]:.4f} {hit["text"][:120]}')
            continue
        echo(f'assistant> {session.ask(user_message)}')


def main(argv: list[str] | None = None) -> int:
    from distllm_tpu.utils import apply_platform_env

    apply_platform_env()
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--config', required=True, type=Path)
    args = parser.parse_args(argv)
    chat_with_model(ChatAppConfig.from_yaml(args.config))
    return 0


if __name__ == '__main__':
    raise SystemExit(main())
