"""Distributed tokenization driver.

Reference parity: ``distllm/distributed_tokenization.py`` — tokenize jsonl
text files with an HF tokenizer into ``input_ids``/``attention_mask``
(+``labels`` when requested) and save per-file HF datasets. HF hub login via
dotenv is replaced by requiring local tokenizer files (zero-egress).

Run: ``python -m distllm_tpu.distributed_tokenization --config tok.yaml``
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import uuid
from pathlib import Path
from typing import Any

from distllm_tpu.observability.instruments import log_event
from distllm_tpu.parallel.fabric import map_with_teardown
from distllm_tpu.parallel.launcher import ComputeConfigs, LocalConfig
from distllm_tpu.timer import Timer
from distllm_tpu.utils import BaseConfig, canonical_function


class TokenizerConfig(BaseConfig):
    """Parity with ``distributed_tokenization.py:18-42``."""

    tokenizer_name_or_path: str
    text_field: str = 'text'
    max_length: int = 2048
    truncation: bool = True
    padding: bool | str = False
    return_labels: bool = False
    trust_remote_code: bool = False


def tokenizer_worker(
    file: str,
    output_dir: str,
    tokenizer_kwargs: dict[str, Any],
) -> str:
    """Tokenize one jsonl file into an HF dataset shard."""
    os.environ.setdefault('TOKENIZERS_PARALLELISM', '0')  # reference :96
    from datasets import Dataset
    from transformers import AutoTokenizer

    config = TokenizerConfig(**tokenizer_kwargs)
    file_tag = Path(file).name
    with Timer('loaded-tokenizer', file_tag):
        tokenizer = AutoTokenizer.from_pretrained(
            config.tokenizer_name_or_path,
            trust_remote_code=config.trust_remote_code,
        )

    with Timer('read-input', file_tag):
        texts = []
        with open(file) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    texts.append(json.loads(line)[config.text_field])

    with Timer('tokenized', file_tag):
        encoded = tokenizer(
            texts,
            truncation=config.truncation,
            max_length=config.max_length,
            padding=config.padding,
        )
        columns: dict[str, Any] = {
            'input_ids': encoded['input_ids'],
            'attention_mask': encoded['attention_mask'],
        }
        if config.return_labels:
            columns['labels'] = [list(row) for row in encoded['input_ids']]

    shard_dir = Path(output_dir) / uuid.uuid4().hex
    with Timer('wrote-dataset', file_tag):
        Dataset.from_dict(columns).save_to_disk(str(shard_dir))
    return str(shard_dir)


class Config(BaseConfig):
    input_dir: Path
    output_dir: Path
    glob_patterns: list[str] = ['*.jsonl']
    tokenizer_config: dict[str, Any]
    compute_config: ComputeConfigs = LocalConfig()


def run_tokenization(config: Config) -> int:
    dataset_dir = config.output_dir / 'tokenized'
    dataset_dir.mkdir(parents=True, exist_ok=True)
    config.write_yaml(config.output_dir / 'config.yaml')

    files: list[str] = []
    for pattern in config.glob_patterns:
        files.extend(str(p) for p in sorted(config.input_dir.glob(pattern)))
    if not files:
        log_event(
            f'No input files matched {config.glob_patterns} in '
            f'{config.input_dir}',
            component='tokenize',
        )
        return 1
    log_event(f'Tokenizing {len(files)} files -> {dataset_dir}', component='tokenize')

    worker_fn = functools.partial(
        # Run as `python -m`, this module is __main__; rebind the
        # worker fn to its importable path so fabric workers can
        # unpickle it (Parsl has the same module-level-fn rule).
        canonical_function(tokenizer_worker, 'distllm_tpu.distributed_tokenization'),
        output_dir=str(dataset_dir),
        tokenizer_kwargs=config.tokenizer_config,
    )
    executor = config.compute_config.get_executor(config.output_dir / 'run')
    shards = map_with_teardown(executor, worker_fn, files)
    log_event(f'Finished: {len(shards)} shards written', component='tokenize')
    return 0


def main(argv: list[str] | None = None) -> int:
    from distllm_tpu.utils import apply_platform_env

    apply_platform_env()
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--config', required=True, type=Path)
    args = parser.parse_args(argv)
    return run_tokenization(Config.from_yaml(args.config))


if __name__ == '__main__':
    raise SystemExit(main())
