"""Resilience layer: fault injection, TTFT-predictive admission control
(docs/resilience.md).

The crash-domain *recovery* half (window retry, quarantine, per-request
deadlines) lives in the engine itself
(``distllm_tpu/generate/engine/engine.py``); this package holds the
parts that are engine-independent: the deterministic fault-injection
framework and the shedding policy. Dependency-free — importable on any
backend, by the server, and by tests without touching jax.
"""

from distllm_tpu.resilience.admission import (
    EngineLoadView,
    EngineOverloaded,
    predict_ttft,
    shed_decision,
)
from distllm_tpu.resilience.faults import (
    FAULT_SITES,
    FaultInjector,
    InjectedFault,
    get_fault_injector,
    parse_fault_spec,
)

__all__ = [
    'EngineLoadView',
    'EngineOverloaded',
    'predict_ttft',
    'shed_decision',
    'FAULT_SITES',
    'FaultInjector',
    'InjectedFault',
    'get_fault_injector',
    'parse_fault_spec',
]
