"""Deterministic, seeded fault injection for the serving stack (ISSUE 15
tentpole).

Three of the five official bench rounds died to init/driver faults, and
until now the stack could only *explain* a fault after the fact (flight
ring, debug bundles, compile attribution) — nothing exercised what the
engine DOES when one lands mid-serve. This module is the chaos half of
the resilience layer (docs/resilience.md): a registry of **named
injection sites** wired into the real hazard points of the engine, the
KV tiers, and the window loop, armed per-site with a deterministic
schedule, and **inert by default** — an unarmed injector is one boolean
read per site visit.

Sites are catalogued in :data:`FAULT_SITES` exactly like
``instruments.FLIGHT_KINDS``: a site minted at a call site (not listed
here) is rejected at arm/fire time, so the chaos schedule's vocabulary
cannot silently fragment. The wired sites:

- ``dispatch`` — raise :class:`InjectedFault` from a window/prefill
  dispatch before the jitted call (the XLA-raise hazard, simulated at
  the boundary where KV donation has not yet consumed the pool arrays);
- ``device_put`` — fail the tier promotion's host→device transfer
  (engine ``_begin_promotion``; degrades to cold prefill);
- ``tier_io`` — raise :class:`OSError` from the disk tier's file
  read/write (``DiskKVTier``; degrades to a tier miss);
- ``sched_exhausted`` — raise ``SchedulerExhausted`` from window
  planning (the pool-pressure hazard without needing a tiny pool);
- ``slow_window`` — sleep ``delay_s`` inside window processing (the
  stall hazard the watchdog and per-request deadlines exist for).

Every fire emits ``distllm_resilience_faults_injected_total{site}`` and
a ``'fault'`` flight record — injected chaos is as attributable as real
faults. Determinism: each site fires on an explicit call schedule
(``after`` skipped calls, then up to ``times`` fires) and/or a seeded
per-site ``random.Random`` probability, so the same arming + the same
call sequence reproduces the same fault pattern (what makes the
``gen_chaos`` bench stage's fault-off token-identity check meaningful).

Arming: programmatic (:meth:`FaultInjector.arm`) or the
``DISTLLM_FAULTS`` env var, a comma-separated list of site clauses::

    DISTLLM_FAULTS="dispatch:times=2:after=4,slow_window:delay_s=0.2"

Dependency-free (stdlib + the observability stack); safe to import on
any backend.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass, field

from distllm_tpu.observability import instruments as _metrics
from distllm_tpu.observability.flight import get_flight_recorder

# Catalog of injectable sites (the FLIGHT_KINDS pattern): arm()/fire()
# reject anything not listed, and docs/resilience.md documents each row.
FAULT_SITES = frozenset({
    'dispatch',         # window/prefill dispatch raise (engine)
    'device_put',       # tier promotion host->device transfer (engine)
    'tier_io',          # disk-tier file IO (kv_cache.DiskKVTier)
    'sched_exhausted',  # scheduler exhaustion during window planning
    'slow_window',      # stall inside window processing
})


class InjectedFault(RuntimeError):
    """The error an armed ``dispatch``/``device_put`` site raises."""

    def __init__(self, site: str, message: str = '') -> None:
        super().__init__(message or f'injected fault at site {site!r}')
        self.site = site


@dataclass
class _SiteState:
    """One armed site's deterministic schedule."""

    site: str
    times: int | None  # max fires; None = unlimited
    prob: float        # per-eligible-call fire probability
    after: int         # eligible calls skipped before firing starts
    delay_s: float     # slow_window sleep per fire
    rng: random.Random = field(default_factory=random.Random)
    calls: int = 0
    fired: int = 0


def parse_fault_spec(spec: str) -> list[dict]:
    """``DISTLLM_FAULTS`` grammar → arm() kwargs, validating site names.

    ``site[:key=value]*`` clauses joined by commas; keys are ``times``
    (int, ``inf``/``-1`` = unlimited), ``prob`` (float), ``after``
    (int), ``delay_s`` (float), ``seed`` (int). Raises ``ValueError``
    on unknown sites/keys — a typo'd chaos schedule must fail loudly,
    not silently run fault-free.
    """
    out: list[dict] = []
    for clause in spec.split(','):
        clause = clause.strip()
        if not clause:
            continue
        parts = clause.split(':')
        site = parts[0].strip()
        if site not in FAULT_SITES:
            raise ValueError(
                f'unknown fault site {site!r}; sites: {sorted(FAULT_SITES)}'
            )
        kwargs: dict = {'site': site}
        for part in parts[1:]:
            key, _, value = part.partition('=')
            key = key.strip()
            value = value.strip()
            if key == 'times':
                kwargs['times'] = (
                    None if value in ('inf', '-1') else int(value)
                )
            elif key == 'prob':
                kwargs['prob'] = float(value)
            elif key == 'after':
                kwargs['after'] = int(value)
            elif key == 'delay_s':
                kwargs['delay_s'] = float(value)
            elif key == 'seed':
                kwargs['seed'] = int(value)
            else:
                raise ValueError(f'unknown fault spec key {key!r}')
        out.append(kwargs)
    return out


class FaultInjector:
    """Process-wide registry of armed fault sites.

    Thread-safe (the engine loop, server threads, and tier IO may hit
    sites concurrently); the unarmed fast path is a single attribute
    read with no lock.
    """

    def __init__(self, env_spec: str | None = None) -> None:
        self._lock = threading.Lock()
        self._sites: dict[str, _SiteState] = {}  # guarded by self._lock
        # Fast inert-path flag; only flipped under the lock, read without
        # it (a stale False just delays the first fire by one visit).
        self._armed = False
        if env_spec:
            for kwargs in parse_fault_spec(env_spec):
                self.arm(**kwargs)

    # ------------------------------------------------------------ arming
    def arm(
        self,
        site: str,
        *,
        times: int | None = 1,
        prob: float = 1.0,
        after: int = 0,
        delay_s: float = 0.0,
        seed: int = 0,
    ) -> None:
        """Arm ``site``: skip the first ``after`` eligible calls, then
        fire (with probability ``prob``, drawn from a ``seed``-determined
        stream) up to ``times`` times (``None`` = forever)."""
        if site not in FAULT_SITES:
            raise ValueError(
                f'unknown fault site {site!r}; sites: {sorted(FAULT_SITES)}'
            )
        if times is not None and times < 0:
            raise ValueError('times must be >= 0 or None')
        if not 0.0 <= prob <= 1.0:
            raise ValueError('prob must be in [0, 1]')
        with self._lock:
            self._sites[site] = _SiteState(
                site=site,
                times=times,
                prob=prob,
                after=max(0, int(after)),
                delay_s=max(0.0, float(delay_s)),
                rng=random.Random(seed),
            )
            self._armed = True

    def disarm(self, site: str | None = None) -> None:
        """Disarm one site (or all of them) — the state (fire counts) is
        discarded with the arming."""
        with self._lock:
            if site is None:
                self._sites.clear()
            else:
                self._sites.pop(site, None)
            self._armed = bool(self._sites)

    @property
    def armed(self) -> bool:
        return self._armed

    def fired(self, site: str | None = None) -> int:
        """Total fires of ``site`` (or all sites) since arming."""
        with self._lock:
            if site is not None:
                state = self._sites.get(site)
                return state.fired if state is not None else 0
            return sum(state.fired for state in self._sites.values())

    # ------------------------------------------------------------ firing
    def fire(self, site: str) -> _SiteState | None:
        """One visit to ``site``: returns the site state when the fault
        fires this visit, None otherwise. Inert default: one boolean
        read. Every fire is counted + flight-recorded."""
        if not self._armed:
            return None
        if site not in FAULT_SITES:
            raise ValueError(f'unknown fault site {site!r}')
        with self._lock:
            state = self._sites.get(site)
            if state is None:
                return None
            state.calls += 1
            if state.calls <= state.after:
                return None
            if state.times is not None and state.fired >= state.times:
                return None
            if state.prob < 1.0 and state.rng.random() >= state.prob:
                return None
            state.fired += 1
            fired, calls = state.fired, state.calls
        _metrics.RESILIENCE_FAULTS.labels(site=site).inc()
        get_flight_recorder().record(
            'fault', site=site, fired=fired, call=calls,
        )
        return state

    def fail(self, site: str, message: str = '') -> None:
        """Raise :class:`InjectedFault` when ``site`` fires this visit."""
        if self.fire(site) is not None:
            raise InjectedFault(site, message)

    def fail_io(self, site: str = 'tier_io') -> None:
        """Raise :class:`OSError` when ``site`` fires — for hazard points
        whose real failure mode is an IO error the caller already
        degrades on (the disk tier's read/write paths)."""
        if self.fire(site) is not None:
            raise OSError(f'injected IO fault at site {site!r}')

    def maybe_sleep(self, site: str = 'slow_window') -> float:
        """Sleep the armed ``delay_s`` when ``site`` fires; returns the
        injected delay (0.0 when nothing fired)."""
        state = self.fire(site)
        if state is None or state.delay_s <= 0:
            return 0.0
        time.sleep(state.delay_s)
        return state.delay_s


_default_injector = FaultInjector(env_spec=os.environ.get('DISTLLM_FAULTS'))


def get_fault_injector() -> FaultInjector:
    """The process-wide injector (env-armed from ``DISTLLM_FAULTS`` at
    import; tests arm/disarm it directly)."""
    return _default_injector
