"""SLO-aware admission control: predict TTFT at enqueue, shed honestly.

ROADMAP item 4 made the case: the measurement plumbing (roofline cost
model, per-request lifecycle timestamps, goodput counters) exists — turn
it into *policy*. This module is the policy half: a dependency-free TTFT
predictor over a snapshot of engine load, and the shed decision the
engine applies inside ``add_request`` when
``EngineConfig.admission_control`` is on (docs/resilience.md "Shedding
policy").

The predictor is deliberately a coarse queueing model, not a simulator —
what matters for shedding is that the estimate is (a) *monotonic in
backlog*, so offered load beyond capacity drives predictions past the
SLO instead of queueing forever, and (b) *calibrated by observation*:
the engine feeds it EWMA-smoothed measured per-token prefill time and
window cadence (``LLMEngine._record_step``), falling back to the
analytic roofline floor (``observability/roofline.py``) before the first
windows land. A shed request gets an honest ``Retry-After`` derived from
the predicted backlog drain, surfaced by ``chat_server`` as
429/``Retry-After`` (and 503 while draining).
"""

from __future__ import annotations

from dataclasses import dataclass


class EngineOverloaded(RuntimeError):
    """Raised by ``LLMEngine.add_request`` (admission control on) when
    the predicted TTFT busts ``ttft_slo_s`` — and by serving front-ends
    that refuse work while draining. Carries what an honest 429 needs."""

    def __init__(
        self, predicted_ttft_s: float, retry_after_s: float,
        slo_s: float = 0.0,
    ) -> None:
        super().__init__(
            f'predicted TTFT {predicted_ttft_s:.3f}s busts the '
            f'{slo_s:.3f}s SLO; retry after {retry_after_s:.1f}s'
        )
        self.predicted_ttft_s = predicted_ttft_s
        self.retry_after_s = retry_after_s
        self.slo_s = slo_s


@dataclass(frozen=True)
class EngineLoadView:
    """One snapshot of engine load, in predictor units.

    ``prefill_s_per_token`` / ``window_s`` are the engine's EWMA-measured
    values (or the roofline floors before any window landed); the rest is
    scheduler state at the enqueue instant.
    """

    waiting_tokens: int          # prompt tokens of WAITING requests
    # Output-token budgets still owed to live requests (waiting requests'
    # max_tokens + running requests' remaining budget): the decode work
    # committed ahead of a new arrival.
    pending_decode_tokens: int
    num_waiting: int
    num_running: int
    max_num_seqs: int
    decode_steps: int            # tokens one window emits per slot
    prefill_s_per_token: float   # measured EWMA or roofline floor
    window_s: float              # one decode-window wall time
    slo_s: float                 # ttft_slo_s (0 = no SLO)


def predict_ttft(view: EngineLoadView, prompt_tokens: int) -> float:
    """Predicted enqueue→first-token latency for a ``prompt_tokens``
    request arriving NOW.

    Three additive terms: the request's own prefill service time, the
    prefill backlog already queued ahead of it, and the committed decode
    work ahead of it expressed in windows — one window serves up to
    ``max_num_seqs * decode_steps`` output tokens, so
    ``pending_decode_tokens`` over that capacity times the measured
    window wall time is the slot-drain floor an arrival behind the queue
    cannot beat. Coarse by design; monotonic in backlog is the property
    shedding needs.
    """
    per_tok = max(0.0, view.prefill_s_per_token)
    service_s = prompt_tokens * per_tok
    backlog_s = view.waiting_tokens * per_tok
    drain_s = 0.0
    window_capacity = max(1, view.max_num_seqs) * max(1, view.decode_steps)
    if view.pending_decode_tokens > 0:
        drain_s = (
            view.pending_decode_tokens / window_capacity
        ) * max(0.0, view.window_s)
    return service_s + backlog_s + drain_s


def shed_decision(
    view: EngineLoadView, prompt_tokens: int
) -> tuple[bool, float, float]:
    """``(admit, predicted_ttft_s, retry_after_s)`` for one arrival.

    Admits whenever no SLO is configured or the prediction fits it;
    otherwise sheds with a ``Retry-After`` covering the predicted excess
    (clamped to [1, 60] s — a router's retry loop needs a sane bound
    more than a precise one).
    """
    predicted = predict_ttft(view, prompt_tokens)
    if view.slo_s <= 0 or predicted <= view.slo_s:
        return True, predicted, 0.0
    retry_after = min(max(predicted - view.slo_s, 1.0), 60.0)
    return False, predicted, retry_after
