"""Command-line interface for distllm-tpu.

Parity target: the reference's typer CLI (``distllm/cli.py``, console script
``distllm``) with subcommands ``embed``, ``merge``, ``generate``, ``tokenize``
and ``chunk_fasta_file``. ``typer`` is not available in this environment, so
the CLI is plain argparse; subcommands are registered lazily so importing the
CLI stays cheap.
"""

from __future__ import annotations

import argparse
import sys
from typing import Callable

_SUBCOMMANDS: dict[str, Callable[[argparse.ArgumentParser], None]] = {}
_RUNNERS: dict[str, Callable[[argparse.Namespace], int | None]] = {}


def subcommand(name: str, help_text: str = ''):
    """Register a CLI subcommand: decorate a (parser-setup, runner) pair."""

    def deco(setup: Callable[[argparse.ArgumentParser], Callable]):
        def register_parser(sub: argparse.ArgumentParser) -> None:
            runner = setup(sub)
            _RUNNERS[name] = runner

        register_parser.help_text = help_text
        _SUBCOMMANDS[name] = register_parser
        return setup

    return deco


def _build_parser() -> argparse.ArgumentParser:
    # Import modules that register subcommands (lazy heavy deps inside).
    from distllm_tpu import cli_commands  # noqa: F401

    parser = argparse.ArgumentParser(
        prog='distllm-tpu',
        description='TPU-native distributed LLM inference toolkit.',
    )
    subparsers = parser.add_subparsers(dest='command')
    for name, register_parser in sorted(_SUBCOMMANDS.items()):
        sub = subparsers.add_parser(
            name, help=getattr(register_parser, 'help_text', '')
        )
        register_parser(sub)
    return parser


def main(argv: list[str] | None = None) -> int:
    from distllm_tpu.utils import apply_platform_env

    apply_platform_env()
    parser = _build_parser()
    args = parser.parse_args(argv)
    if not args.command:
        parser.print_help()
        return 2
    result = _RUNNERS[args.command](args)
    return int(result or 0)


if __name__ == '__main__':
    # Under `python -m distllm_tpu.cli` this file runs as `__main__`; delegate
    # to the canonical module so subcommands register into the same tables.
    from distllm_tpu.cli import main as _canonical_main

    sys.exit(_canonical_main())
