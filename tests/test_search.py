"""Retrieval tests: sharded top-k, ubinary Hamming + rescore, index, retriever."""

import numpy as np
import pytest

import jax.numpy as jnp

from distllm_tpu.ops.topk import hamming_topk, pack_sign_bits, topk_inner_product


def test_topk_single_device(rng):
    corpus = jnp.asarray(rng.normal(size=(100, 16)).astype(np.float32))
    queries = jnp.asarray(rng.normal(size=(3, 16)).astype(np.float32))
    scores, indices = topk_inner_product(queries, corpus, 5)
    ref = np.asarray(queries) @ np.asarray(corpus).T
    ref_idx = np.argsort(-ref, axis=1)[:, :5]
    np.testing.assert_array_equal(np.asarray(indices), ref_idx)


def test_topk_sharded_matches_single(rng):
    from distllm_tpu.parallel.mesh import MeshSpec, make_mesh

    mesh = make_mesh(MeshSpec(data=8, model=1))
    corpus_np = rng.normal(size=(128, 16)).astype(np.float32)
    queries_np = rng.normal(size=(4, 16)).astype(np.float32)
    corpus = jnp.asarray(corpus_np)
    queries = jnp.asarray(queries_np)
    s1, i1 = topk_inner_product(queries, corpus, 7)
    s8, i8 = topk_inner_product(queries, corpus, 7, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i8))
    np.testing.assert_allclose(np.asarray(s1), np.asarray(s8), atol=1e-5)


def test_pack_sign_bits():
    emb = np.array([[1.0, -1.0, 0.5, -0.5, 2.0, -2.0, 0.1, -0.1]], np.float32)
    packed = pack_sign_bits(emb)
    assert packed.shape == (1, 1)
    assert packed[0, 0] == 0b10101010
    with pytest.raises(ValueError):
        pack_sign_bits(np.zeros((1, 7), np.float32))


def test_hamming_topk():
    corpus = jnp.asarray(np.array([[0b0], [0b11111111], [0b1111]], np.uint8))
    query = jnp.asarray(np.array([[0b0]], np.uint8))
    dists, idx = hamming_topk(query, corpus, 3)
    assert list(np.asarray(idx)[0]) == [0, 2, 1]
    assert list(np.asarray(dists)[0]) == [0, 4, 8]


@pytest.fixture
def embeddings_dataset(tmp_path, rng):
    from datasets import Dataset

    n, h = 64, 32
    embeddings = rng.normal(size=(n, h)).astype(np.float32)
    ds = Dataset.from_dict(
        {
            'text': [f'document number {i}' for i in range(n)],
            'embeddings': [e for e in embeddings],
            'path': [f'doc{i % 4}' for i in range(n)],
        }
    )
    ds.save_to_disk(str(tmp_path / 'ds'))
    return tmp_path / 'ds', embeddings


def test_index_flat_exact(embeddings_dataset):
    from distllm_tpu.rag.search import TpuIndexV2, TpuIndexV2Config

    dataset_dir, embeddings = embeddings_dataset
    index = TpuIndexV2(TpuIndexV2Config(dataset_dir=dataset_dir))
    normalized = embeddings / np.linalg.norm(embeddings, axis=1, keepdims=True)
    queries = normalized[:3]
    results = index.search(queries, top_k=4, score_threshold=-10.0)
    # Nearest neighbor of a normalized vector is itself.
    for qi, row in enumerate(results.total_indices):
        assert row[0] == qi
    # Persistence: index file exists, reload hits it.
    index2 = TpuIndexV2(TpuIndexV2Config(dataset_dir=dataset_dir))
    results2 = index2.search(queries, top_k=4, score_threshold=-10.0)
    assert results2.total_indices == results.total_indices


def test_index_score_threshold(embeddings_dataset):
    from distllm_tpu.rag.search import TpuIndexV2, TpuIndexV2Config

    dataset_dir, embeddings = embeddings_dataset
    index = TpuIndexV2(TpuIndexV2Config(dataset_dir=dataset_dir))
    normalized = embeddings / np.linalg.norm(embeddings, axis=1, keepdims=True)
    results = index.search(normalized[:2], top_k=10, score_threshold=0.99)
    # only the self-match passes the 0.99 threshold for random vectors
    assert all(len(row) == 1 for row in results.total_indices)
    assert all(s >= 0.99 for row in results.total_scores for s in row)


def test_index_ubinary_rescore(embeddings_dataset):
    from distllm_tpu.rag.search import TpuIndexV2, TpuIndexV2Config

    dataset_dir, embeddings = embeddings_dataset
    index = TpuIndexV2(
        TpuIndexV2Config(
            dataset_dir=dataset_dir, precision='ubinary', rescore_multiplier=4
        )
    )
    normalized = embeddings / np.linalg.norm(embeddings, axis=1, keepdims=True)
    results = index.search(normalized[:4], top_k=3, score_threshold=-10.0)
    for qi, row in enumerate(results.total_indices):
        assert row[0] == qi  # self-match survives quantization + rescore


def test_index_int8_rescore(embeddings_dataset):
    from distllm_tpu.rag.search import TpuIndexV2, TpuIndexV2Config

    dataset_dir, embeddings = embeddings_dataset
    index = TpuIndexV2(
        TpuIndexV2Config(
            dataset_dir=dataset_dir, precision='int8', rescore_multiplier=4
        )
    )
    normalized = embeddings / np.linalg.norm(embeddings, axis=1, keepdims=True)
    results = index.search(normalized[:4], top_k=3, score_threshold=-10.0)
    for qi, row in enumerate(results.total_indices):
        assert row[0] == qi  # self-match survives int8 quantization
    # int8 scoring error is small; after fp32 rescore the ranking should
    # match the exact index on these shapes.
    exact = TpuIndexV2(
        TpuIndexV2Config(dataset_dir=dataset_dir)
    ).search(normalized[:4], top_k=3, score_threshold=-10.0)
    assert results.total_indices == exact.total_indices


def test_int8_topk_matches_exact(rng):
    from distllm_tpu.ops.topk import int8_topk, quantize_int8_rows

    corpus = rng.normal(size=(200, 64)).astype(np.float32)
    corpus /= np.linalg.norm(corpus, axis=1, keepdims=True)
    queries = corpus[:5] + 0.01 * rng.normal(size=(5, 64)).astype(np.float32)
    codes, scales = quantize_int8_rows(corpus)
    # Codes round-trip near the original.
    recon = codes.astype(np.float32) * scales[:, None]
    assert np.abs(recon - corpus).max() < 0.01
    scores, idx = int8_topk(
        jnp.asarray(queries), jnp.asarray(codes), jnp.asarray(scales), 3
    )
    exact = queries @ corpus.T
    exact_top1 = np.argmax(exact, axis=1)
    assert list(np.asarray(idx)[:, 0]) == list(exact_top1)
    # Approximate scores are close to the exact inner products.
    got = np.asarray(scores)[:, 0]
    want = np.max(exact, axis=1)
    np.testing.assert_allclose(got, want, atol=0.05)


def test_index_int8_sharded_mesh_matches_single(embeddings_dataset):
    from distllm_tpu.rag.search import TpuIndexV2Config

    dataset_dir, embeddings = embeddings_dataset
    single = TpuIndexV2Config(
        dataset_dir=dataset_dir, precision='int8'
    ).get_index()
    sharded = TpuIndexV2Config(
        dataset_dir=dataset_dir, precision='int8', mesh={'data': -1, 'model': 1}
    ).get_index()
    normalized = embeddings / np.linalg.norm(embeddings, axis=1, keepdims=True)
    r1 = single.search(normalized[:3], top_k=5, score_threshold=-10.0)
    r2 = sharded.search(normalized[:3], top_k=5, score_threshold=-10.0)
    assert r1.total_indices == r2.total_indices


def test_index_int8_sharded_padding_no_duplicates(tmp_path, rng):
    """Corpus size NOT divisible by the mesh (61 rows on 8 devices pads to
    64): padded candidates must be filtered, never clamped onto a real row
    — a clamp returns the last real row repeatedly, crowding true
    neighbors out of the top-k."""
    from datasets import Dataset

    from distllm_tpu.rag.search import TpuIndexV2Config

    n = 61
    emb = rng.normal(size=(n, 32)).astype(np.float32)
    Dataset.from_dict(
        {'embeddings': [e for e in emb], 'text': [str(i) for i in range(n)]}
    ).save_to_disk(str(tmp_path / 'ds'))
    normalized = emb / np.linalg.norm(emb, axis=1, keepdims=True)
    sharded = TpuIndexV2Config(
        dataset_dir=tmp_path / 'ds', precision='int8',
        mesh={'data': -1, 'model': 1},
    ).get_index()
    # Query the LAST real row: with clamping, padded candidates would
    # collapse onto index n-1 and duplicate it.
    results = sharded.search(normalized[n - 1 :], top_k=5,
                             score_threshold=-10.0)
    row = results.total_indices[0]
    assert row[0] == n - 1
    assert len(row) == len(set(row)), f'duplicate indices: {row}'
    assert all(i < n for i in row)
    single = TpuIndexV2Config(
        dataset_dir=tmp_path / 'ds', precision='int8'
    ).get_index()
    assert (
        single.search(normalized[n - 1 :], top_k=5, score_threshold=-10.0)
        .total_indices[0] == row
    )


def test_index_sharded_mesh_matches_single(embeddings_dataset):
    """Config-driven mesh sharding returns identical results (odd N pads)."""
    from distllm_tpu.rag.search import TpuIndexV2Config

    dataset_dir, embeddings = embeddings_dataset
    single = TpuIndexV2Config(dataset_dir=dataset_dir).get_index()
    sharded = TpuIndexV2Config(
        dataset_dir=dataset_dir, mesh={'data': -1, 'model': 1}
    ).get_index()
    normalized = embeddings / np.linalg.norm(embeddings, axis=1, keepdims=True)
    r1 = single.search(normalized[:3], top_k=5, score_threshold=-10.0)
    r2 = sharded.search(normalized[:3], top_k=5, score_threshold=-10.0)
    assert r1.total_indices == r2.total_indices


def test_index_get_rows(embeddings_dataset):
    from distllm_tpu.rag.search import TpuIndexV2, TpuIndexV2Config

    dataset_dir, _ = embeddings_dataset
    index = TpuIndexV2(TpuIndexV2Config(dataset_dir=dataset_dir))
    texts = index.get([0, 5], 'text')
    assert texts == ['document number 0', 'document number 5']


def test_v1_deprecation(embeddings_dataset):
    from distllm_tpu.rag.search import TpuIndexV1Config

    dataset_dir, _ = embeddings_dataset
    with pytest.warns(DeprecationWarning):
        index = TpuIndexV1Config(dataset_dir=dataset_dir).get_index()
    assert len(index) == 64


def test_retriever_end_to_end(tmp_path):
    """Fake encoder corpus -> index -> retriever round trip."""
    from datasets import Dataset

    from distllm_tpu.embed import get_encoder, get_pooler
    from distllm_tpu.embed.embedders.full_sequence import compute_embeddings
    from distllm_tpu.rag.search import RetrieverConfig

    encoder = get_encoder({'name': 'fake', 'embedding_size': 32})
    pooler = get_pooler({'name': 'mean'})
    texts = [
        'alpha beta gamma delta words',
        'completely different topic here',
        'alpha beta gamma delta words again',
    ]
    embeddings = compute_embeddings(texts, encoder, pooler, 2)
    Dataset.from_dict(
        {'text': texts, 'embeddings': [e for e in embeddings]}
    ).save_to_disk(str(tmp_path / 'corpus'))

    retriever = RetrieverConfig(
        faiss_config={'dataset_dir': str(tmp_path / 'corpus')},
        encoder_config={'name': 'fake', 'embedding_size': 32},
        pooler_config={'name': 'mean'},
        batch_size=2,
    ).get_retriever()

    results, query_emb = retriever.search('alpha beta gamma delta words', top_k=2)
    assert query_emb.shape == (1, 32)
    assert results.total_indices[0][0] in (0, 2)  # near-duplicate texts win
    found = retriever.get_texts(results.total_indices[0])
    assert any('alpha beta' in t for t in found)
    # batch query order restoration
    batch, _ = retriever.search(['completely different topic here', 'alpha beta gamma delta words'], top_k=1)
    assert batch.total_indices[0][0] == 1
    assert batch.total_indices[1][0] in (0, 2)


def test_index_sharded_build_and_reload(tmp_path, rng):
    """The streaming build writes per-chunk shard files + meta; a reload
    serves identical results without rebuilding."""
    from datasets import Dataset

    from distllm_tpu.rag.search import TpuIndexV2, TpuIndexV2Config

    n, h = 50, 16
    embeddings = rng.normal(size=(n, h)).astype(np.float32)
    Dataset.from_dict(
        {'text': [f't{i}' for i in range(n)], 'embeddings': list(embeddings)}
    ).save_to_disk(str(tmp_path / 'ds'))

    # Force multiple chunks to exercise the streaming path.
    old = TpuIndexV2._CHUNK_ROWS
    TpuIndexV2._CHUNK_ROWS = 16
    try:
        index = TpuIndexV2(TpuIndexV2Config(dataset_dir=tmp_path / 'ds'))
        parts = sorted((tmp_path / 'ds' / 'tpu_index').glob('*.part*.npy'))
        assert len(parts) == 4  # ceil(50/16)
        q = embeddings[:3]
        res = index.search(q, top_k=3, score_threshold=-1e9)
        ref = np.argsort(-(q @ (embeddings / np.linalg.norm(embeddings, axis=1, keepdims=True)).T), axis=1)[:, :3]
        # reload from the shard files (no dataset rebuild)
        index2 = TpuIndexV2(TpuIndexV2Config(dataset_dir=tmp_path / 'ds'))
        res2 = index2.search(q, top_k=3, score_threshold=-1e9)
        assert res.total_indices == res2.total_indices
    finally:
        TpuIndexV2._CHUNK_ROWS = old


def test_index_ubinary_no_fp32_copy(tmp_path, rng):
    """ubinary keeps only packed bits resident; rescore gathers from the
    arrow dataset and still ranks the true nearest first."""
    from datasets import Dataset

    from distllm_tpu.rag.search import TpuIndexV2, TpuIndexV2Config

    n, h = 96, 64
    embeddings = rng.normal(size=(n, h)).astype(np.float32)
    Dataset.from_dict(
        {'text': [f't{i}' for i in range(n)], 'embeddings': list(embeddings)}
    ).save_to_disk(str(tmp_path / 'ds'))
    index = TpuIndexV2(
        TpuIndexV2Config(
            dataset_dir=tmp_path / 'ds', precision='ubinary',
            rescore_multiplier=8,
        )
    )
    assert not hasattr(index, '_rescore_host')
    normed = embeddings / np.linalg.norm(embeddings, axis=1, keepdims=True)
    res = index.search(normed[:5], top_k=1, score_threshold=-1e9)
    assert [row[0] for row in res.total_indices] == [0, 1, 2, 3, 4]


def test_index_builds_from_unmerged_shards(tmp_path, rng):
    """A directory of UUID shard subdirs (distributed embedding output)
    concatenates automatically."""
    from datasets import Dataset

    from distllm_tpu.rag.search import TpuIndexV2, TpuIndexV2Config

    h = 16
    all_embeddings = []
    for shard in ('aaa111', 'bbb222'):
        embeddings = rng.normal(size=(10, h)).astype(np.float32)
        all_embeddings.append(embeddings)
        Dataset.from_dict(
            {
                'text': [f'{shard}-{i}' for i in range(10)],
                'embeddings': list(embeddings),
            }
        ).save_to_disk(str(tmp_path / 'shards' / shard))
    index = TpuIndexV2(TpuIndexV2Config(dataset_dir=tmp_path / 'shards'))
    assert len(index) == 20
    full = np.concatenate(all_embeddings)
    normed = full / np.linalg.norm(full, axis=1, keepdims=True)
    res = index.search(normed[15:16], top_k=1, score_threshold=-1e9)
    assert res.total_indices[0][0] == 15


def test_grouped_topk_matches_flat(rng):
    """The grouped single-dispatch scan (serving layout, ops/topk
    group_rows) must return the same candidates as the 2-D chunk loop,
    including the padded tail of the last group."""
    import jax.numpy as jnp

    from distllm_tpu.ops.topk import (
        group_rows,
        hamming_topk,
        int8_topk,
        pack_sign_bits,
        quantize_int8_rows,
    )

    n, h, k = 1000, 32, 7  # 1000 % 256 != 0 -> padded last group
    corpus = rng.normal(size=(n, h)).astype(np.float32)
    corpus /= np.linalg.norm(corpus, axis=1, keepdims=True)
    queries = corpus[:5] + 0.1 * rng.normal(size=(5, h)).astype(np.float32)

    codes, scales = quantize_int8_rows(corpus)
    flat = int8_topk(jnp.asarray(queries), jnp.asarray(codes),
                     jnp.asarray(scales), k, chunk_size=256)
    grouped = int8_topk(
        jnp.asarray(queries),
        jnp.asarray(group_rows(codes, 256)),
        jnp.asarray(group_rows(scales, 256)),
        k, n_valid=n,
    )
    np.testing.assert_array_equal(np.asarray(flat[1]), np.asarray(grouped[1]))
    np.testing.assert_allclose(
        np.asarray(flat[0]), np.asarray(grouped[0]), rtol=1e-5
    )

    qb = jnp.asarray(pack_sign_bits(queries))
    packed = pack_sign_bits(corpus)
    flat_h = hamming_topk(qb, jnp.asarray(packed), k, chunk_size=256)
    grouped_h = hamming_topk(
        qb, jnp.asarray(group_rows(packed, 256)), k, n_valid=n
    )
    # Hamming distances tie often on random corpora; compare the (sorted)
    # distance multisets and that every grouped index is a real row with
    # the distance the flat path assigned it.
    np.testing.assert_array_equal(
        np.sort(np.asarray(flat_h[0]), axis=1),
        np.sort(np.asarray(grouped_h[0]), axis=1),
    )
    assert np.asarray(grouped_h[1]).max() < n


def test_grouped_topk_k_exceeds_chunk(rng):
    """k larger than the group chunk must return [B, k], not silently
    truncate to the per-chunk candidate count (review finding)."""
    import jax.numpy as jnp

    from distllm_tpu.ops.topk import group_rows, int8_topk, quantize_int8_rows

    n, h, k = 1000, 32, 500
    corpus = rng.normal(size=(n, h)).astype(np.float32)
    queries = corpus[:3]
    codes, scales = quantize_int8_rows(corpus)
    flat = int8_topk(jnp.asarray(queries), jnp.asarray(codes),
                     jnp.asarray(scales), k, chunk_size=256)
    grouped = int8_topk(
        jnp.asarray(queries),
        jnp.asarray(group_rows(codes, 256)),
        jnp.asarray(group_rows(scales, 256)),
        k, n_valid=n,
    )
    assert np.asarray(grouped[1]).shape == (3, k)
    np.testing.assert_array_equal(np.asarray(flat[1]), np.asarray(grouped[1]))


def test_grouped_topk_requires_n_valid(rng):
    """Grouped corpora zero-pad the last slab; omitting the real row
    count must be an error, not out-of-range neighbors (review finding)."""
    import jax.numpy as jnp
    import pytest

    from distllm_tpu.ops.topk import (
        group_rows,
        hamming_topk,
        int8_topk,
        pack_sign_bits,
        quantize_int8_rows,
    )

    corpus = rng.normal(size=(100, 32)).astype(np.float32)
    codes, scales = quantize_int8_rows(corpus)
    with pytest.raises(ValueError, match='n_valid'):
        int8_topk(
            jnp.asarray(corpus[:2]),
            jnp.asarray(group_rows(codes, 64)),
            jnp.asarray(group_rows(scales, 64)),
            5,
        )
    with pytest.raises(ValueError, match='n_valid'):
        hamming_topk(
            jnp.asarray(pack_sign_bits(corpus[:2])),
            jnp.asarray(group_rows(pack_sign_bits(corpus), 64)),
            5,
        )
