"""Compile-only TPU (Mosaic) lowering tests — no hardware needed.

The locally installed libtpu can build a compile-only PJRT topology
(``jax.experimental.topologies``), which catches the class of failures CPU
interpret mode cannot: Mosaic lowering rejections (block-shape rules, DMA
patterns) and HBM budgeting. Round 2's flagship regression — a Pallas
decode kernel that silently failed only on the real chip — is exactly what
these tests pin down in CI. Small dims keep each compile to a few seconds;
``scripts/aot_preflight.py`` runs the full 7B serving matrix.
"""

import numpy as np
import pytest

jax = pytest.importorskip('jax')
import jax.numpy as jnp  # noqa: E402


def _compile_tolerating_mosaic_artifact(build, mosaic_kernel: bool = True):
    """Run a compile, xfail-ing ONLY on the known Mosaic 'implicit dim
    change' rejection of the Pallas decode kernel.

    Some Mosaic toolchains reject the Pallas paged-attention decode
    kernel's block pattern with an "implicit dim change" lowering error;
    the same kernel compiles AND is benchmarked on the real chip
    environment (CHANGES.md PR 2 — left untouched there, gated here per
    ISSUE 3). Re-checked for ISSUE 8: the artifact is still present and
    its message has MUTATED across toolchains — ``Not implemented:
    Overriding implicit dim change`` (the ISSUE-3-era container) is now
    ``Not implemented: Unsupported implicit dim change: from
    "16,{0,0},(16,128),-2" to none`` (this container, measured
    2026-08-04) — so the gate matches the stable ``implicit dim change``
    family marker. Gating on the *message* rather than a toolchain
    version pin means a toolchain that fixes the bug turns these back
    into hard tests automatically. The gate is deliberately narrow so
    nothing else is swallowed (tightened for ISSUE 8):

    - ``mosaic_kernel=False`` (pure-XLA builds, where the artifact
      cannot occur) never xfails — any failure raises;
    - the error must self-identify as the Mosaic TPU compiler's
      (``Mosaic failed to compile TPU kernel``) AND carry the
      ``implicit dim change`` marker — any other Mosaic rejection, or a
      non-Mosaic error whose text merely mentions the phrase, still
      fails loudly.
    """
    try:
        return build()
    except Exception as exc:
        msg = f'{exc!r}'
        if (
            mosaic_kernel
            and 'implicit dim change' in msg
            and 'Mosaic failed to compile TPU kernel' in msg
        ):
            pytest.xfail(
                'known Mosaic toolchain artifact (implicit dim change); '
                'kernel verified on the real chip '
                f'environment: {msg}'[:300]
            )
        raise


@pytest.fixture(scope='module')
def v5e():
    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    try:
        topo = topologies.get_topology_desc(
            platform='tpu', topology_name='v5e:2x2x1'
        )
    except Exception as exc:  # no libtpu / unsupported platform
        pytest.skip(f'no compile-only TPU topology available: {exc!r}')
    mesh = Mesh(np.asarray(topo.devices[:1]).reshape(1), ('x',))
    sharding = NamedSharding(mesh, PartitionSpec())

    def sds(shape, dtype):
        return jax.ShapeDtypeStruct(tuple(shape), dtype, sharding=sharding)

    return sds


# ~10 min Mosaic compile on this container's toolchain (measured
# 2026-08-02) — far past the fast tier's "few seconds per compile" design
# budget, so it runs in the slow tier; the fast tier keeps the same
# kernel's interpret-mode coverage (tests/test_encoder_attention.py).
@pytest.mark.slow
def test_encoder_attention_compiles_for_tpu(v5e):
    from distllm_tpu.ops.encoder_attention import encoder_attention

    # 160 is a fine-ladder rung that is NOT a multiple of 128 — the case
    # the library flash kernel rejects and Mosaic block rules can trip on.
    b, s, d = 8, 160, 256
    jax.jit(
        lambda q, k, v, m: encoder_attention(q, k, v, m, num_heads=4)
    ).lower(
        v5e((b, s, d), jnp.bfloat16),
        v5e((b, s, d), jnp.bfloat16),
        v5e((b, s, d), jnp.bfloat16),
        v5e((b, s), jnp.int32),
    ).compile()


@pytest.mark.parametrize('backend', ['pallas', 'xla'])
def test_decode_window_compiles_for_tpu(v5e, backend):
    """Both scan variants must lower: rolled, and the engine-default
    unrolled graph (whose straight-line cache updates depend on XLA
    buffer reuse rather than while-carry aliasing). A missed reuse in the
    unrolled body would add full-cache-sized temps on top of the rolled
    baseline — asserted against below."""
    from distllm_tpu.models import mistral

    # head_dim must be 128 (the Pallas kernel's DMA alignment contract).
    cfg = mistral.MistralConfig(
        vocab_size=2048, hidden_size=1024, num_layers=2, num_heads=8,
        num_kv_heads=4, intermediate_size=512, dtype='bfloat16',
    )
    shapes = jax.eval_shape(
        lambda: mistral.init_on_device(jax.random.PRNGKey(0), cfg)
    )
    params = jax.tree.map(lambda x: v5e(x.shape, x.dtype), shapes)
    b, nb, bs, rows = 8, 64, 16, 16
    kshape = (cfg.num_layers, nb, bs, cfg.num_kv_heads, cfg.head_size)
    cache_bytes = 2 * int(np.prod(kshape)) * 2  # k + v, bf16
    temps = {}
    for layer_unroll in (False, True):
        compiled = _compile_tolerating_mosaic_artifact(
            mosaic_kernel=(backend == 'pallas'),
            build=lambda un=layer_unroll: jax.jit(
                lambda p, i, po, c, k, v, bt, sl, t, tp, mp, ky,
                       un=un:
                    mistral.decode_loop(
                        p, cfg, i, po, k, v, bt, c, sl, t, tp, mp, ky,
                        num_steps=4, attn_backend=backend,
                        max_table_positions=256,
                        sampling_top_window=16, layer_unroll=un,
                    ),
                donate_argnums=(4, 5),
            ).lower(
                params, v5e((b,), jnp.int32), v5e((b,), jnp.int32),
                v5e((b,), jnp.int32), v5e(kshape, jnp.bfloat16),
                v5e(kshape, jnp.bfloat16), v5e((b, rows), jnp.int32),
                v5e((b,), jnp.int32), v5e((b,), jnp.float32),
                v5e((b,), jnp.float32), v5e((b,), jnp.float32),
                v5e((2,), jnp.uint32),
            ).compile()
        )
        mem = compiled.memory_analysis()
        temps[layer_unroll] = getattr(mem, 'temp_size_in_bytes', None)
    if temps[True] is not None:
        # Unrolling must not degrade in-place cache updates to copies:
        # each missed reuse adds a full-cache-sized temp. (The rolled
        # variant reports ~0 temps — memory_analysis does not descend
        # into while bodies — so the bound is absolute, not relative:
        # activation temps at these dims are ~2.5 MB, well under one
        # 4 MB cache copy.)
        assert temps[True] < cache_bytes, (
            f'unrolled temps {temps[True]} vs one cache copy '
            f'{cache_bytes} (rolled baseline: {temps[False]})'
        )


def test_int8_decode_window_compiles_for_tpu(v5e):
    """Per-layer dequant inside the scan must not materialize the float
    stack as HLO temps (the whole-tree dequant OOMed 7B on 16 GiB)."""
    from distllm_tpu.models import mistral
    from distllm_tpu.ops.quantization import quantize_pytree_abstract

    # head_dim must be 128 (the Pallas kernel's DMA alignment contract).
    cfg = mistral.MistralConfig(
        vocab_size=2048, hidden_size=1024, num_layers=2, num_heads=8,
        num_kv_heads=4, intermediate_size=512, dtype='bfloat16',
    )
    shapes = jax.eval_shape(
        lambda: mistral.init_on_device(jax.random.PRNGKey(0), cfg)
    )

    from distllm_tpu.ops.quantization import QTensor

    params = quantize_pytree_abstract(shapes, make_leaf=v5e)
    # Bytes a whole-tree dequant would materialize as bf16 HLO temps:
    # only the leaves that actually became QTensor.
    float_stack_bytes = sum(
        int(np.prod(leaf.shape)) * 2
        for leaf in jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, QTensor)
        )
        if isinstance(leaf, QTensor)
    )
    b, nb, bs, rows = 8, 64, 16, 16
    kshape = (cfg.num_layers, nb, bs, cfg.num_kv_heads, cfg.head_size)
    compiled = _compile_tolerating_mosaic_artifact(
        lambda: jax.jit(
            lambda p, i, po, c, k, v, bt, sl, t, tp, mp, ky:
                mistral.decode_loop(
                    p, cfg, i, po, k, v, bt, c, sl, t, tp, mp, ky,
                    num_steps=4, attn_backend='pallas',
                    max_table_positions=256,
                    sampling_top_window=16,
                ),
            donate_argnums=(4, 5),
        ).lower(
            params, v5e((b,), jnp.int32), v5e((b,), jnp.int32),
            v5e((b,), jnp.int32), v5e(kshape, jnp.bfloat16),
            v5e(kshape, jnp.bfloat16), v5e((b, rows), jnp.int32),
            v5e((b,), jnp.int32), v5e((b,), jnp.float32),
            v5e((b,), jnp.float32), v5e((b,), jnp.float32),
            v5e((2,), jnp.uint32),
        ).compile()
    )
    mem = compiled.memory_analysis()
    temp = getattr(mem, 'temp_size_in_bytes', None)
    if temp is not None:
        # A whole-tree dequant would materialize the full bf16 stack
        # (float_stack_bytes) as temps; per-layer dequant stays well under.
        assert temp < float_stack_bytes // 2
