"""Compile-only TPU (Mosaic) lowering tests — no hardware needed.

The locally installed libtpu can build a compile-only PJRT topology
(``jax.experimental.topologies``), which catches the class of failures CPU
interpret mode cannot: Mosaic lowering rejections (block-shape rules, DMA
patterns) and HBM budgeting. Round 2's flagship regression — a Pallas
decode kernel that silently failed only on the real chip — is exactly what
these tests pin down in CI. Small dims keep each compile to a few seconds;
``scripts/aot_preflight.py`` runs the full 7B serving matrix.
"""

import numpy as np
import pytest

jax = pytest.importorskip('jax')
import jax.numpy as jnp  # noqa: E402


def _compile(build, mosaic_kernel: bool = True):
    """Run a compile — HARD, no Mosaic-artifact tolerance.

    History (ISSUE 3 → ISSUE 12): the retired decode-only Pallas kernel's
    block layout tripped some Mosaic toolchains with an ``implicit dim
    change`` lowering rejection (message mutated across containers:
    ``Overriding implicit dim change`` → ``Unsupported implicit dim
    change: from "16,{0,0},(16,128),-2" to none``), and these tests
    xfail-gated on that message family for nine PRs. The ragged kernel
    that replaced it (``ragged_paged_attention_pallas``) was designed
    around the artifact — lane-replicated 128-wide softmax state instead
    of 1-wide minor dims, no in-kernel reshapes across the head dim — and
    compiles clean on this container's toolchain, so the gate is retired:
    ANY compile failure, Mosaic or otherwise, is a hard test failure
    again. ``mosaic_kernel`` is kept for call-site documentation of which
    builds lower a Pallas kernel at all.
    """
    del mosaic_kernel
    return build()


@pytest.fixture(scope='module')
def v5e():
    from jax.experimental import topologies
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    try:
        topo = topologies.get_topology_desc(
            platform='tpu', topology_name='v5e:2x2x1'
        )
    except Exception as exc:  # no libtpu / unsupported platform
        pytest.skip(f'no compile-only TPU topology available: {exc!r}')
    mesh = Mesh(np.asarray(topo.devices[:1]).reshape(1), ('x',))
    sharding = NamedSharding(mesh, PartitionSpec())

    def sds(shape, dtype):
        return jax.ShapeDtypeStruct(tuple(shape), dtype, sharding=sharding)

    return sds


# ~10 min Mosaic compile on this container's toolchain (measured
# 2026-08-02) — far past the fast tier's "few seconds per compile" design
# budget, so it runs in the slow tier; the fast tier keeps the same
# kernel's interpret-mode coverage (tests/test_encoder_attention.py).
@pytest.mark.slow
def test_encoder_attention_compiles_for_tpu(v5e):
    from distllm_tpu.ops.encoder_attention import encoder_attention

    # 160 is a fine-ladder rung that is NOT a multiple of 128 — the case
    # the library flash kernel rejects and Mosaic block rules can trip on.
    b, s, d = 8, 160, 256
    jax.jit(
        lambda q, k, v, m: encoder_attention(q, k, v, m, num_heads=4)
    ).lower(
        v5e((b, s, d), jnp.bfloat16),
        v5e((b, s, d), jnp.bfloat16),
        v5e((b, s, d), jnp.bfloat16),
        v5e((b, s), jnp.int32),
    ).compile()


# Moved to the slow tier with the encoder compile (PR 2 precedent): now
# that the pallas variants REALLY compile (the ISSUE-3 xfail used to
# short-circuit them), the five Mosaic window compiles cost ~8 min on
# this container — measured 2026-08-04 blowing the 870 s tier-1 budget
# mid-suite (DOTS 483 -> 225). The fast tier keeps the same kernel's
# interpret-mode parity + engine identity coverage
# (tests/test_ragged_attention.py).
@pytest.mark.slow
@pytest.mark.parametrize('backend', ['pallas', 'xla'])
def test_decode_window_compiles_for_tpu(v5e, backend):
    """Both scan variants must lower: rolled, and the engine-default
    unrolled graph (whose straight-line cache updates depend on XLA
    buffer reuse rather than while-carry aliasing). A missed reuse in the
    unrolled body would add full-cache-sized temps on top of the rolled
    baseline — asserted against below."""
    from distllm_tpu.models import mistral

    # head_dim must be 128 (the Pallas kernel's DMA alignment contract).
    cfg = mistral.MistralConfig(
        vocab_size=2048, hidden_size=1024, num_layers=2, num_heads=8,
        num_kv_heads=4, intermediate_size=512, dtype='bfloat16',
    )
    shapes = jax.eval_shape(
        lambda: mistral.init_on_device(jax.random.PRNGKey(0), cfg)
    )
    params = jax.tree.map(lambda x: v5e(x.shape, x.dtype), shapes)
    b, nb, bs, rows = 8, 64, 16, 16
    kshape = (cfg.num_layers, nb, bs, cfg.num_kv_heads, cfg.head_size)
    cache_bytes = 2 * int(np.prod(kshape)) * 2  # k + v, bf16
    temps = {}
    for layer_unroll in (False, True):
        compiled = _compile(
            mosaic_kernel=(backend == 'pallas'),
            build=lambda un=layer_unroll: jax.jit(
                lambda p, i, po, c, k, v, bt, sl, t, tp, mp, tk, sd,
                       un=un:
                    mistral.decode_loop(
                        p, cfg, i, po, k, v, bt, c, sl, t, tp, mp, tk, sd,
                        num_steps=4, attn_backend=backend,
                        max_table_positions=256,
                        sampling_top_window=16, layer_unroll=un,
                    ),
                donate_argnums=(4, 5),
            ).lower(
                params, v5e((b,), jnp.int32), v5e((b,), jnp.int32),
                v5e((b,), jnp.int32), v5e(kshape, jnp.bfloat16),
                v5e(kshape, jnp.bfloat16), v5e((b, rows), jnp.int32),
                v5e((b,), jnp.int32), v5e((b,), jnp.float32),
                v5e((b,), jnp.float32), v5e((b,), jnp.float32),
                v5e((b,), jnp.int32), v5e((b,), jnp.uint32),
            ).compile()
        )
        mem = compiled.memory_analysis()
        temps[layer_unroll] = getattr(mem, 'temp_size_in_bytes', None)
    if temps[True] is not None:
        # Unrolling must not degrade in-place cache updates to copies:
        # each missed reuse adds a full-cache-sized temp. (The rolled
        # variant reports ~0 temps — memory_analysis does not descend
        # into while bodies — so the bound is absolute, not relative:
        # activation temps at these dims are ~2.5 MB, well under one
        # 4 MB cache copy.)
        assert temps[True] < cache_bytes, (
            f'unrolled temps {temps[True]} vs one cache copy '
            f'{cache_bytes} (rolled baseline: {temps[False]})'
        )


@pytest.mark.slow
def test_ragged_paged_attention_compiles_for_tpu(v5e):
    """The fused ragged kernel must lower clean under Mosaic at every
    serving span shape — the hard version of what nine PRs of 'implicit
    dim change' xfails could not assert for the retired decode-only
    kernel. Covers the standalone op at chunk-span, decode-span, and
    gemma2-knob (traced window + softcap + scale) signatures, plus the
    full prefill_paged forward with the backend pinned 'pallas' (the
    mixed/spec windows' ragged half compiles the same graph)."""
    from distllm_tpu.models import mistral
    from distllm_tpu.ops.paged_attention import ragged_paged_attention_pallas

    b, nb, bs, rows = 8, 64, 16, 16
    nh, nkv, hd = 8, 4, 128

    def op(q, k, v, bt, ctx, pos, ql, w=None, **kw):
        return ragged_paged_attention_pallas(
            q, k, v, bt, ctx, pos, q_lens=ql, sliding_window=w, **kw
        )

    for s in (16, 1):  # chunk span and the decode degenerate span
        _compile(
            lambda s=s: jax.jit(op).lower(
                v5e((b, s, nh, hd), jnp.bfloat16),
                v5e((nb, bs, nkv, hd), jnp.bfloat16),
                v5e((nb, bs, nkv, hd), jnp.bfloat16),
                v5e((b, rows), jnp.int32), v5e((b,), jnp.int32),
                v5e((b, s), jnp.int32), v5e((b,), jnp.int32),
            ).compile()
        )
    # gemma2 knobs through ONE compiled signature: traced per-layer
    # window scalar, logit softcap, custom scale.
    _compile(
        lambda: jax.jit(
            lambda q, k, v, bt, ctx, pos, ql, w: op(
                q, k, v, bt, ctx, pos, ql, w,
                logit_softcap=30.0, scale=0.0884,
            )
        ).lower(
            v5e((b, 16, nh, hd), jnp.bfloat16),
            v5e((nb, bs, nkv, hd), jnp.bfloat16),
            v5e((nb, bs, nkv, hd), jnp.bfloat16),
            v5e((b, rows), jnp.int32), v5e((b,), jnp.int32),
            v5e((b, 16), jnp.int32), v5e((b,), jnp.int32),
            v5e((), jnp.int32),
        ).compile()
    )
    # The serving forward that carries the ragged spans (prefix-cache
    # tails, chunked prefill, and the mixed/spec windows' chunk half).
    cfg = mistral.MistralConfig(
        vocab_size=2048, hidden_size=1024, num_layers=2, num_heads=8,
        num_kv_heads=4, intermediate_size=512, dtype='bfloat16',
    )
    shapes = jax.eval_shape(
        lambda: mistral.init_on_device(jax.random.PRNGKey(0), cfg)
    )
    params = jax.tree.map(lambda x: v5e(x.shape, x.dtype), shapes)
    kshape = (cfg.num_layers, nb, bs, cfg.num_kv_heads, cfg.head_size)
    _compile(
        lambda: jax.jit(
            lambda p, i, po, k, v, bt, c, t: mistral.prefill_paged(
                p, cfg, i, po, k, v, bt, c, t,
                max_table_positions=256, attn_backend='pallas',
            ),
            donate_argnums=(3, 4),
        ).lower(
            params, v5e((4, 16), jnp.int32), v5e((4, 16), jnp.int32),
            v5e(kshape, jnp.bfloat16), v5e(kshape, jnp.bfloat16),
            v5e((4, rows), jnp.int32), v5e((4,), jnp.int32),
            v5e((4,), jnp.int32),
        ).compile()
    )


@pytest.mark.slow  # Mosaic window compile — see the tier note above.
def test_int8_decode_window_compiles_for_tpu(v5e):
    """Per-layer dequant inside the scan must not materialize the float
    stack as HLO temps (the whole-tree dequant OOMed 7B on 16 GiB)."""
    from distllm_tpu.models import mistral
    from distllm_tpu.ops.quantization import quantize_pytree_abstract

    # head_dim must be 128 (the Pallas kernel's DMA alignment contract).
    cfg = mistral.MistralConfig(
        vocab_size=2048, hidden_size=1024, num_layers=2, num_heads=8,
        num_kv_heads=4, intermediate_size=512, dtype='bfloat16',
    )
    shapes = jax.eval_shape(
        lambda: mistral.init_on_device(jax.random.PRNGKey(0), cfg)
    )

    from distllm_tpu.ops.quantization import QTensor

    params = quantize_pytree_abstract(shapes, make_leaf=v5e)
    # Bytes a whole-tree dequant would materialize as bf16 HLO temps:
    # only the leaves that actually became QTensor.
    float_stack_bytes = sum(
        int(np.prod(leaf.shape)) * 2
        for leaf in jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, QTensor)
        )
        if isinstance(leaf, QTensor)
    )
    b, nb, bs, rows = 8, 64, 16, 16
    kshape = (cfg.num_layers, nb, bs, cfg.num_kv_heads, cfg.head_size)
    compiled = _compile(
        lambda: jax.jit(
            lambda p, i, po, c, k, v, bt, sl, t, tp, mp, tk, sd:
                mistral.decode_loop(
                    p, cfg, i, po, k, v, bt, c, sl, t, tp, mp, tk, sd,
                    num_steps=4, attn_backend='pallas',
                    max_table_positions=256,
                    sampling_top_window=16,
                ),
            donate_argnums=(4, 5),
        ).lower(
            params, v5e((b,), jnp.int32), v5e((b,), jnp.int32),
            v5e((b,), jnp.int32), v5e(kshape, jnp.bfloat16),
            v5e(kshape, jnp.bfloat16), v5e((b, rows), jnp.int32),
            v5e((b,), jnp.int32), v5e((b,), jnp.float32),
            v5e((b,), jnp.float32), v5e((b,), jnp.float32),
            v5e((b,), jnp.int32), v5e((b,), jnp.uint32),
        ).compile()
    )
    mem = compiled.memory_analysis()
    temp = getattr(mem, 'temp_size_in_bytes', None)
    if temp is not None:
        # A whole-tree dequant would materialize the full bf16 stack
        # (float_stack_bytes) as temps; per-layer dequant stays well under.
        assert temp < float_stack_bytes // 2
