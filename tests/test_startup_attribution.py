"""Startup & compile attribution, measured XLA cost, and the bounded
profiler capture (ISSUE 11 tentpole + satellites): one ``compile`` flight
record per warmup shape with cache-hit marking on re-warmup, the Perfetto
startup track, measured-vs-analytic MFU gauges from ``cost_analysis()``,
``startup.json`` in debug bundles, and capture error-safety."""

from __future__ import annotations

import json
import time

import jax
import numpy as np
import pytest

from distllm_tpu.generate.engine.engine import (
    EngineConfig,
    LLMEngine,
    SamplingParams,
)
from distllm_tpu.models import mistral
from distllm_tpu.observability import (
    CompileWatcher,
    FlightRecorder,
    ProfilerCapture,
    dump_debug_bundle,
    get_registry,
    instruments,
    record_backend_init,
    to_trace_events,
    validate_trace_events,
)
from distllm_tpu.observability.perfetto import _STARTUP_TID


def _tiny_engine(max_model_len=64, **cfg_kwargs):
    cfg = mistral.MistralConfig(
        vocab_size=64,
        hidden_size=32,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        intermediate_size=64,
        dtype='float32',
    )
    params = mistral.init(jax.random.PRNGKey(0), cfg)

    class IdTokenizer:
        eos_id = None

        def decode(self, ids):
            return ' '.join(str(i) for i in ids)

    engine = LLMEngine(
        cfg,
        params,
        IdTokenizer(),
        EngineConfig(
            block_size=4,
            num_blocks=64,
            max_num_seqs=4,
            max_model_len=max_model_len,
            prefer_native_allocator=False,
            **cfg_kwargs,
        ),
    )
    # Isolate from the process-global watcher: other tests warm the same
    # tiny shapes, and process-level dedup would mark them cache hits.
    recorder = FlightRecorder()
    engine._compile_watcher = CompileWatcher(recorder=recorder)
    return engine, recorder


def _compile_records(recorder):
    return [r for r in recorder.snapshot() if r['kind'] == 'compile']


# ------------------------------------------------- warmup instrumentation
def test_warmup_emits_one_compile_record_per_shape():
    engine, recorder = _tiny_engine()
    engine.warmup()
    records = _compile_records(recorder)
    # The exact ladder: every (batch, bucket) prefill the admission
    # policy can emit — buckets (16, 32, 64) x batch (1, 2, 4) — plus
    # the fused decode window. No prefix cache / chunking / mixed / spec
    # in this config, so nothing else may appear.
    prefill = [r for r in records if r['phase'] == 'prefill']
    decode = [r for r in records if r['phase'] == 'decode_window']
    assert len(prefill) == 9 and len(decode) == 1
    assert len(records) == 10
    assert {r['shape'] for r in prefill} == {
        f'b{b}x{bucket}' for bucket in (16, 32, 64) for b in (1, 2, 4)
    }
    assert decode[0]['shape'] == 'b4x8'  # max_num_seqs x decode_steps
    # One record per shape, none marked as a cache hit on a cold watcher,
    # every duration real.
    assert len({(r['phase'], r['shape']) for r in records}) == len(records)
    assert all(not r['cache_hit'] for r in records)
    assert all(r['duration_s'] > 0 for r in records)
    # Timestamps are monotonic: the ladder is sequential, and the
    # Perfetto startup track depends on the ordering.
    stamps = [r['t_wall'] for r in records]
    assert stamps == sorted(stamps)


def test_rewarmup_marks_cache_hit_fast_path():
    engine, recorder = _tiny_engine()
    engine.warmup()
    cold = _compile_records(recorder)
    engine.warmup()
    warm = _compile_records(recorder)[len(cold):]
    assert len(warm) == len(cold)
    assert all(r['cache_hit'] for r in warm)
    # The fast path is actually fast: jit re-dispatch, not re-compile.
    assert sum(r['duration_s'] for r in warm) < sum(
        r['duration_s'] for r in cold
    )


def test_warmup_ladder_includes_paged_shapes_when_chunking():
    engine, recorder = _tiny_engine(max_model_len=32, prefill_chunk_tokens=16)
    engine.warmup()
    records = _compile_records(recorder)
    prefill = {r['shape'] for r in records if r['phase'] == 'prefill'}
    paged = {r['shape'] for r in records if r['phase'] == 'prefill_paged'}
    assert paged == prefill  # every prefill shape has its paged twin


def test_warmup_renders_as_perfetto_startup_track():
    engine, recorder = _tiny_engine()
    engine.warmup()
    doc = to_trace_events(recorder.snapshot())
    assert validate_trace_events(doc) == []
    startup = [
        e for e in doc['traceEvents'] if e.get('cat') == 'startup'
    ]
    assert len(startup) == len(_compile_records(recorder))
    # One dedicated track, named slices like 'prefill:b1x16', phase
    # fields surviving as args.
    assert {e['tid'] for e in startup} == {_STARTUP_TID}
    names = {e['name'] for e in startup}
    assert 'prefill:b1x16' in names and 'decode_window:b4x8' in names
    assert all(e['args']['cache_hit'] is False for e in startup)
    track_names = {
        e['args']['name'] for e in doc['traceEvents']
        if e['ph'] == 'M' and e['name'] == 'thread_name'
    }
    assert 'startup (compile phases)' in track_names


# --------------------------------------------------- watcher semantics
def test_compile_watcher_failure_records_error_not_hit():
    recorder = FlightRecorder()
    watch = CompileWatcher(recorder=recorder)
    with pytest.raises(RuntimeError, match='boom'):
        with watch.phase('prefill', 'b1x16'):
            raise RuntimeError('boom')
    (record,) = _compile_records(recorder)
    assert 'boom' in record['error']
    assert not record['cache_hit']
    # A failed phase must not poison the dedup set: the retry is a real
    # compile, not a "hit".
    with watch.phase('prefill', 'b1x16'):
        pass
    retry = _compile_records(recorder)[-1]
    assert 'error' not in retry and not retry['cache_hit']
    assert watch.state()['active'] is None


def test_compile_watcher_names_the_phase_in_progress():
    """The r03/r04 failure-mode fix: a bundle dumped mid-phase names the
    exact (kind, shape) the process is stuck in."""
    watch = CompileWatcher(recorder=FlightRecorder())
    with watch.phase('decode_window', 'b32x16') as fields:
        fields['note'] = 'wedged here'
        active = watch.state()['active']
        assert active['phase'] == 'decode_window'
        assert active['shape'] == 'b32x16'
        assert active['t_start_wall'] <= time.time()
    assert watch.state()['active'] is None
    assert watch.state()['phases'][-1]['note'] == 'wedged here'


def test_non_compiling_phase_never_claims_persistent_cache_hit(tmp_path):
    """With a persistent compilation cache dir configured, a phase that
    does work but no XLA compilation (compiles=False) must not read its
    zero cache delta as a 'hit' — a cold migrate/allocate would
    otherwise poison the warm-start evidence."""
    old = jax.config.jax_compilation_cache_dir
    jax.config.update('jax_compilation_cache_dir', str(tmp_path))
    try:
        recorder = FlightRecorder()
        watch = CompileWatcher(recorder=recorder)
        with watch.phase('kv_allocate', 'blocks8', compiles=False):
            pass
        no_compile = _compile_records(recorder)[-1]
        assert no_compile['persistent_cache_delta'] == 0
        assert not no_compile['cache_hit']
        # A COMPILING phase with zero delta IS the warm-persistent-cache
        # fast path (nothing new was lowered to disk).
        with watch.phase('decode_window', 'b1x1'):
            pass
        assert _compile_records(recorder)[-1]['cache_hit']
        # Process-repeat still marks non-compiling phases.
        with watch.phase('kv_allocate', 'blocks8', compiles=False):
            pass
        assert _compile_records(recorder)[-1]['cache_hit']
    finally:
        jax.config.update('jax_compilation_cache_dir', old)


def test_phase_scope_namespaces_process_dedup():
    """A second engine in one process builds NEW jit wrappers whose
    warmup really recompiles — the same (kind, shape) under a fresh
    scope must not read as a cache hit."""
    recorder = FlightRecorder()
    watch = CompileWatcher(recorder=recorder)
    scope_a, scope_b = watch.new_scope(), watch.new_scope()
    assert scope_a != scope_b
    with watch.phase('prefill', 'b1x16', scope=scope_a):
        pass
    with watch.phase('prefill', 'b1x16', scope=scope_a):
        pass
    with watch.phase('prefill', 'b1x16', scope=scope_b):
        pass
    hits = [r['cache_hit'] for r in _compile_records(recorder)]
    assert hits == [False, True, False]


def test_second_engine_sharing_the_watcher_starts_cold():
    recorder = FlightRecorder()
    shared = CompileWatcher(recorder=recorder)
    engine_a, _ = _tiny_engine()
    engine_a._compile_watcher = shared
    engine_b, _ = _tiny_engine()
    engine_b._compile_watcher = shared
    assert engine_a._compile_scope != engine_b._compile_scope
    engine_a.warmup()
    first = _compile_records(recorder)
    engine_b.warmup()
    second = _compile_records(recorder)[len(first):]
    assert {(r['phase'], r['shape']) for r in second} == {
        (r['phase'], r['shape']) for r in first
    }
    assert all(not r['cache_hit'] for r in second)


def test_record_backend_init_phase_and_fast_repeat():
    watch = CompileWatcher(recorder=FlightRecorder())
    devices = record_backend_init(watch)
    assert devices[0].platform == 'cpu'
    first = watch.state()['phases'][-1]
    assert first['phase'] == 'backend_init'
    assert first['platform'] == 'cpu'
    assert first['num_devices'] == len(devices)
    record_backend_init(watch)
    assert watch.state()['phases'][-1]['cache_hit']


def test_compile_series_in_exposition():
    """The catalog carries the new series from the first scrape."""
    text = get_registry().render()
    for name in (
        'distllm_compile_seconds',
        'distllm_compile_cache_hits_total',
        'distllm_engine_mfu_measured',
        'distllm_engine_bandwidth_utilization_measured',
        'distllm_engine_roofline_flops_ratio',
        'distllm_engine_roofline_bytes_ratio',
        'distllm_profiler_captures_total',
    ):
        assert f'# TYPE {name} ' in text, name


# --------------------------------------------------- debug bundle satellite
def test_debug_bundle_includes_startup_state(tmp_path):
    paths = dump_debug_bundle(tmp_path / 'bundle', reason='startup test')
    assert 'startup' in paths
    state = json.loads((tmp_path / 'bundle' / 'startup.json').read_text())
    assert set(state) == {'compile', 'profiler'}
    assert 'active' in state['compile'] and 'phases' in state['compile']
    assert 'captures_total' in state['profiler']


def test_debug_bundle_names_dead_phase_mid_stall(tmp_path):
    """Bundle dumped while a phase is in flight (the init-stall scenario)
    attributes the dead phase."""
    from distllm_tpu.observability.startup import get_compile_watcher

    watch = get_compile_watcher()
    with watch.phase('migrate_params', 'params'):
        dump_debug_bundle(tmp_path / 'stall', reason='wedged migrate')
    state = json.loads((tmp_path / 'stall' / 'startup.json').read_text())
    assert state['compile']['active']['phase'] == 'migrate_params'


# ------------------------------------------- measured XLA cost (xla_cost)
def test_warmup_prices_executables_from_cost_analysis():
    engine, _ = _tiny_engine()
    assert engine.measured_costs() == {}  # warmup fills it
    engine.warmup()
    costs = engine.measured_costs()
    assert set(costs) == {'prefill', 'decode'}
    for cost in costs.values():
        assert cost['flops'] > 0
        assert cost['bytes_accessed'] > 0
        assert cost['source'] in ('aot', 'lowered')


def test_measured_gauges_and_ratios_published_per_step():
    engine, _ = _tiny_engine()
    engine.warmup()
    before = engine.flight.total_recorded
    engine.generate_ids(
        [[5, 9, 12]], SamplingParams(temperature=0.0, max_tokens=4)
    )
    new = engine.flight.snapshot()[
        -(engine.flight.total_recorded - before):
    ]
    decode = [r for r in new if r['kind'] == 'decode']
    assert decode, new
    # Flight records carry the measured twin beside the analytic fields.
    for record in decode:
        assert record['mfu_measured'] > 0
        assert record['bw_util_measured'] > 0
        assert record['mfu'] > 0
    # Prefill dispatches at varying (batch, bucket) shapes: the priced
    # largest-shape executable must NOT be published over their wall
    # time (it would inflate by the shape ratio) — cost is visible via
    # measured_costs() only.
    prefill = [r for r in new if r['kind'] == 'prefill']
    assert prefill and all('mfu_measured' not in r for r in prefill)
    # Gauges: measured MFU next to the analytic one, ratios recorded.
    assert instruments.ENGINE_MFU_MEASURED.labels(kind='decode').value > 0
    assert (
        instruments.ENGINE_BW_UTIL_MEASURED.labels(kind='decode').value > 0
    )
    flops_ratio = instruments.ENGINE_ROOFLINE_FLOPS_RATIO.labels(
        kind='decode'
    ).value
    bytes_ratio = instruments.ENGINE_ROOFLINE_BYTES_RATIO.labels(
        kind='decode'
    ).value
    assert flops_ratio > 0 and bytes_ratio > 0


def test_attribution_off_skips_measured_gauges_but_tokens_identical():
    on_engine, _ = _tiny_engine()
    on_engine.warmup()
    off_engine, _ = _tiny_engine(attribution=False)
    off_engine.warmup()
    prompts = [[7, 3, 22, 31]]
    sp = SamplingParams(temperature=0.0, max_tokens=5)
    on_tokens = on_engine.generate_ids(prompts, sp)
    before = off_engine.flight.total_recorded
    assert on_tokens == off_engine.generate_ids(prompts, sp)
    new = off_engine.flight.snapshot()[
        -(off_engine.flight.total_recorded - before):
    ]
    decode = [r for r in new if r['kind'] == 'decode']
    assert decode and all('mfu_measured' not in r for r in decode)


def test_price_callable_handles_aot_and_failures():
    from distllm_tpu.observability.xla_cost import price_callable

    jitted = jax.jit(lambda a, b: a @ b)
    a = np.zeros((16, 16), np.float32)
    cost = price_callable(jitted, a, a)
    assert cost is not None and cost.flops > 0
    assert cost.source == 'lowered'
    aot = jitted.lower(a, a).compile()
    cost_aot = price_callable(aot)
    assert cost_aot is not None and cost_aot.flops == cost.flops
    assert cost_aot.source == 'aot'
    # Pricing is telemetry: wrong args degrade to None, never raise.
    assert price_callable(jitted, np.zeros((3, 5)), np.zeros((7, 2))) is None


# ------------------------------------------------- bounded profiler capture
def test_profiler_capture_bounded_and_rejecting(tmp_path):
    capture = ProfilerCapture()
    assert capture.state()['active'] is None
    assert capture.start(tmp_path / 'trace', max_seconds=30.0)
    assert capture.state()['active']['log_dir'].endswith('trace')
    # Second start is rejected, not queued — jax's profiler is global.
    assert not capture.start(tmp_path / 'other')
    assert 'already active' in capture.state()['last_error']
    assert capture.stop()
    assert capture.state()['active'] is None
    assert capture.state()['captures_total'] == 1
    assert not capture.stop()  # idempotent


def test_profiler_capture_auto_stops_at_bound(tmp_path):
    capture = ProfilerCapture()
    assert capture.start(tmp_path / 'bounded', max_seconds=0.2)
    deadline = time.monotonic() + 10.0
    # captures_total increments only after the auto-stop flush completes.
    while (
        not capture.state()['captures_total']
        and time.monotonic() < deadline
    ):
        time.sleep(0.05)
    state = capture.state()
    assert state['captures_total'] == 1, state
    assert state['active'] is None


def test_profiler_capture_swallows_backend_errors(tmp_path, monkeypatch):
    """The bench satellite: an unsupported-backend profiler error must
    not kill the caller."""
    capture = ProfilerCapture()

    def boom(*args, **kwargs):
        raise RuntimeError('profiler unsupported on this backend')

    monkeypatch.setattr(jax.profiler, 'start_trace', boom)
    assert not capture.start(tmp_path / 'nope')
    assert 'unsupported' in capture.state()['last_error']
    assert capture.state()['active'] is None
    result = capture.capture(tmp_path / 'nope2', seconds=0.1)
    assert not result['ok'] and not result['rejected']
    assert 'unsupported' in result['error']
