"""MCQA harness tests: batching, grading ladder, checkpointing, pipeline."""

import json
import threading

import pytest

from distllm_tpu.mcqa.batching import BatchingClient
from distllm_tpu.mcqa.checkpoint import CheckpointManager
from distllm_tpu.mcqa.config import MCQAConfig, load_model_servers
from distllm_tpu.mcqa.grading import (
    GraderAuthError,
    grade_answer,
    parse_grader_json,
)
from distllm_tpu.mcqa.harness import chunk_id, load_questions, run_mcqa


# ---------------------------------------------------------------- batching
def test_batching_client_batches_requests():
    batches = []

    def send(prompts):
        batches.append(list(prompts))
        return [f'r:{p}' for p in prompts]

    client = BatchingClient(send, batch_size=4, batch_timeout=0.2)
    results = {}

    def worker(i):
        results[i] = client.generate(f'p{i}', timeout=10)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    client.close()
    assert {results[i] for i in range(8)} == {f'r:p{i}' for i in range(8)}
    assert len(batches) <= 4  # requests were actually coalesced
    assert any(len(b) > 1 for b in batches)


def test_batching_client_propagates_errors():
    def send(prompts):
        raise ConnectionError('backend down')

    client = BatchingClient(send, batch_size=2, batch_timeout=0.05)
    with pytest.raises(ConnectionError):
        client.generate('x', timeout=5)
    client.close()


# ----------------------------------------------------------------- grading
def test_parse_grader_json():
    assert parse_grader_json('{"correct": true}')['correct'] is True
    assert parse_grader_json('blah {"correct": false, "reason": "no"} end')[
        'reason'
    ] == 'no'
    assert parse_grader_json('not json') is None
    assert parse_grader_json('{"correct": "yes"}') is None  # not boolean


def test_grade_answer_ladder_escalates():
    calls = []

    def grader(prompt):
        calls.append(prompt)
        if len(calls) < 2:
            return 'I think the answer is correct!'  # unparseable
        return '{"correct": true, "reason": "matches"}'

    verdict = grade_answer(grader, 'Q', 'ref', 'ans', max_tries_per_level=1)
    assert verdict['correct'] is True
    assert verdict['ladder_level'] == 1  # escalated once
    assert 'ONLY a JSON object' in calls[1]


def test_grade_answer_auth_gives_up():
    def grader(prompt):
        raise GraderAuthError('bad key')

    with pytest.raises(GraderAuthError):
        grade_answer(grader, 'Q', 'ref', 'ans')


def test_grade_answer_all_levels_fail():
    def grader(prompt):
        return 'gibberish'

    with pytest.raises(RuntimeError, match='no parseable JSON'):
        grade_answer(grader, 'Q', 'ref', 'ans', max_tries_per_level=1)


# -------------------------------------------------------------- checkpoint
def test_checkpoint_save_resume(tmp_path):
    meta = {'model': 'm1', 'questions_file': 'q.json'}
    ckpt = CheckpointManager(tmp_path, meta, every=2)
    ckpt.record(0, {'correct': True})
    ckpt.record(1, {'correct': False})  # triggers save
    ckpt.record(2, {'correct': True})
    ckpt.save()

    fresh = CheckpointManager(tmp_path, meta, every=2)
    assert fresh.try_resume() == 3
    assert fresh.completed_indices == {0, 1, 2}


def test_checkpoint_rejects_mismatched_model(tmp_path):
    ckpt = CheckpointManager(tmp_path, {'model': 'm1', 'questions_file': 'q'}, every=1)
    ckpt.record(0, {'correct': True})
    other = CheckpointManager(tmp_path, {'model': 'OTHER', 'questions_file': 'q'})
    assert other.try_resume() == 0


def test_checkpoint_incremental(tmp_path):
    ckpt = CheckpointManager(tmp_path, {}, every=100, save_incremental=True)
    ckpt.record(0, {'correct': True})
    assert CheckpointManager.find_latest(tmp_path) is not None


# ------------------------------------------------------------------ config
def test_model_servers_registry(tmp_path):
    f = tmp_path / 'servers.yaml'
    f.write_text(
        'servers:\n'
        '  - shortname: llama\n'
        '    openai_api_base: http://h1:8000/v1\n'
        '    openai_model: meta/llama\n'
        '  - shortname: grader\n'
        '    openai_api_base: http://h2:8000/v1\n'
        '    openai_model: gpt-x\n'
        '    openai_api_key: sk-test\n'
    )
    registry = load_model_servers(f)
    assert registry['llama'].openai_api_base == 'http://h1:8000/v1'
    assert registry['grader'].openai_api_key == 'sk-test'


def test_chunk_id_stable():
    assert chunk_id('doc.pdf', 3) == chunk_id('doc.pdf', 3)
    assert chunk_id('doc.pdf', 3) != chunk_id('doc.pdf', 4)
    assert chunk_id('doc.pdf', 3).endswith('_0003')


def test_load_questions(tmp_path):
    f = tmp_path / 'q.json'
    f.write_text(json.dumps([{'question': 'Q1?', 'answer': 'A'}]))
    assert load_questions(f)[0]['question'] == 'Q1?'
    bad = tmp_path / 'bad.json'
    bad.write_text(json.dumps([{'question': 'no answer field'}]))
    with pytest.raises(ValueError):
        load_questions(bad)


# --------------------------------------------------- end-to-end (stub HTTP)
@pytest.fixture
def stub_openai_server():
    """OpenAI-compatible stub: echoes for the model, grades 'correct' when
    the model answer contains the reference."""
    import re
    import socket

    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            length = int(self.headers['Content-Length'])
            body = json.loads(self.rfile.read(length))
            prompt = body['messages'][0]['content']
            if 'grading a multiple-choice answer' in prompt or 'Grade the answer' in prompt or 'minified JSON' in prompt:
                ref = re.search(r'Reference(?: answer)?: (.*)', prompt).group(1).splitlines()[0]
                ans = re.search(r'(?:Model answer|Answer): (.*)', prompt).group(1).splitlines()[0]
                verdict = {'correct': ref.strip().lower() in ans.strip().lower()}
                content = json.dumps(verdict)
            else:
                # The model: answer 'paris' to everything.
                content = 'paris'
            payload = {
                'choices': [{'message': {'role': 'assistant', 'content': content}}]
            }
            data = json.dumps(payload).encode()
            self.send_response(200)
            self.send_header('Content-Type', 'application/json')
            self.send_header('Content-Length', str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, *args):
            pass

    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        port = s.getsockname()[1]
    server = ThreadingHTTPServer(('127.0.0.1', port), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f'http://127.0.0.1:{port}/v1'
    server.shutdown()


def test_run_mcqa_end_to_end(tmp_path, stub_openai_server):
    questions = [
        {'question': 'Capital of France?\n1. paris\n2. rome', 'answer': 'paris'},
        {'question': 'Capital of Italy?\n1. paris\n2. rome', 'answer': 'rome'},
    ]
    qfile = tmp_path / 'questions.json'
    qfile.write_text(json.dumps(questions))

    config = MCQAConfig(
        questions_file=qfile,
        output_dir=tmp_path / 'out',
        model_api_base=stub_openai_server,
        model_name='stub',
        grader_api_base=stub_openai_server,
        grader_model='stub-grader',
        parallel_workers=2,
        batch_size=2,
        batch_timeout=0.1,
        checkpoint_every=1,
    )
    summary = run_mcqa(config)
    assert summary['graded'] == 2
    assert summary['correct'] == 1  # model always says paris
    assert summary['accuracy'] == 0.5
    results = json.loads((tmp_path / 'out' / 'results.json').read_text())
    assert results['summary']['model'] == 'stub'
    incorrect = json.loads(
        (tmp_path / 'out' / 'incorrect_answers.json').read_text()
    )
    assert len(incorrect) == 1
    # Resume: everything already done.
    summary2 = run_mcqa(config)
    assert summary2['graded'] == 2
