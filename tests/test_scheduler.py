"""Native C++ scheduler vs the Python twin: identical decisions.

The continuous-batching policy (admission, block budget, recompute
preemption — the role vLLM's scheduler plays for the reference,
SURVEY.md §2.4 N1) ships as a C++ core with a Python oracle; these tests
drive both with the same workloads and require decision-for-decision
equality, then exercise the policy edges on either implementation.
"""

from __future__ import annotations

import numpy as np
import pytest

from distllm_tpu.generate.engine.scheduler import (
    NativeScheduler,
    PyScheduler,
    SchedulerExhausted,
    make_scheduler,
)


def native_available() -> bool:
    try:
        NativeScheduler(8, 4, 2)
        return True
    except (RuntimeError, OSError):
        return False


requires_native = pytest.mark.skipif(
    not native_available(), reason='no C++ toolchain'
)


def drive(sched, seed: int, steps: int = 200):
    """Random workload driver; returns the full decision trace."""
    rng = np.random.default_rng(seed)
    trace = []
    next_rid = 0
    live: set[int] = set()
    for _ in range(steps):
        action = rng.integers(0, 4)
        if action == 0 or not live:
            tokens = int(rng.integers(1, 40))
            sched.add(next_rid, tokens)
            live.add(next_rid)
            trace.append(('add', next_rid, tokens))
            next_rid += 1
        elif action == 1:
            admitted = []
            try:
                while (rid := sched.admit_next()) is not None:
                    admitted.append(rid)
            except SchedulerExhausted:
                admitted.append('EXHAUSTED')
            trace.append(('admit', tuple(admitted)))
        elif action == 2:
            if sched.num_running:
                # k > 1 exercises the multi-step window reservation path.
                k = int(rng.integers(1, 6))
                # Half the time restrict to a random running subset — the
                # mixed-serving-window path (rows mid-prefill get no
                # decode headroom); None = classic all-rows policy.
                rids = None
                ks = None
                if rng.integers(0, 2):
                    rids = [
                        rid for rid in sorted(live)
                        if sched.slot(rid) >= 0 and rng.integers(0, 2)
                    ]
                    if rng.integers(0, 2):
                        # Per-row headroom (speculative verify windows):
                        # each selected row gets its own k.
                        ks = [int(rng.integers(1, 6)) for _ in rids]
                try:
                    preempted = sched.prepare_decode(k, rids, ks)
                except SchedulerExhausted as exc:
                    # Fatal path reports prior same-call preemptions too;
                    # both implementations must agree on them.
                    preempted = ['EXHAUSTED', tuple(exc.preempted)]
                trace.append(
                    (
                        'prepare', k,
                        tuple(rids) if rids is not None else None,
                        tuple(ks) if ks is not None else None,
                        tuple(preempted),
                    )
                )
                for rid in list(live):
                    if sched.slot(rid) >= 0:
                        sched.append_token(rid)
                        trace.append(('token', rid))
                # Rejected-suffix rollback: trim a random running row's
                # over-reservation back to num_tokens + 1 coverage.
                running_now = [r for r in sorted(live) if sched.slot(r) >= 0]
                if running_now and rng.integers(0, 2):
                    victim = running_now[
                        int(rng.integers(0, len(running_now)))
                    ]
                    trace.append(('trim', victim, sched.trim(victim)))
        else:
            running = [rid for rid in live if sched.slot(rid) >= 0]
            if running:
                rid = running[int(rng.integers(0, len(running)))]
                sched.finish(rid)
                live.discard(rid)
                trace.append(('finish', rid))
        trace.append(
            ('state', sched.num_free_blocks, sched.num_running, sched.num_waiting)
        )
    # Block rows of everything still live (allocation order must agree too).
    for rid in sorted(live):
        trace.append(('blocks', rid, tuple(sched.block_row(rid))))
    return trace


@requires_native
@pytest.mark.parametrize('seed', [0, 1, 2, 3, 4])
def test_native_matches_python_oracle(seed):
    py = PyScheduler(num_blocks=24, block_size=4, max_num_seqs=3)
    cc = NativeScheduler(num_blocks=24, block_size=4, max_num_seqs=3)
    assert drive(cc, seed) == drive(py, seed)


@requires_native
def test_make_scheduler_prefers_native():
    sched = make_scheduler(16, 4, 2, prefer_native=True)
    assert isinstance(sched, NativeScheduler)


@pytest.fixture(params=['py', 'native'])
def sched_factory(request):
    if request.param == 'native' and not native_available():
        pytest.skip('no C++ toolchain')
    cls = PyScheduler if request.param == 'py' else NativeScheduler

    def make(num_blocks=16, block_size=4, max_num_seqs=2):
        return cls(num_blocks, block_size, max_num_seqs)

    return make


class TestPolicy:
    def test_admission_assigns_lowest_slot_and_blocks(self, sched_factory):
        s = sched_factory()
        s.add(0, 5)  # needs ceil(6/4) = 2 blocks
        assert s.admit_next() == 0
        assert s.slot(0) == 0
        assert len(s.block_row(0)) == 2
        assert s.num_free_blocks == 15 - 2
        assert s.admit_next() is None

    def test_admission_blocked_until_slot_frees(self, sched_factory):
        s = sched_factory(max_num_seqs=1)
        s.add(0, 3)
        s.add(1, 3)
        assert s.admit_next() == 0
        assert s.admit_next() is None  # no slot
        s.finish(0)
        assert s.admit_next() == 1

    def test_preemption_frees_youngest_to_waiting_front(self, sched_factory):
        # 7 usable blocks, block_size 1: two sequences of 3 fit, then the
        # older one's growth preempts the younger.
        s = sched_factory(num_blocks=8, block_size=1, max_num_seqs=2)
        s.add(0, 3)
        s.add(1, 3)
        assert s.admit_next() == 0  # takes 4 blocks (3 tokens + 1 headroom)
        assert s.admit_next() is None  # rid 1 needs 4, only 3 free
        assert s.slot(1) == -1
        assert s.num_waiting == 1
        # grow rid 0 to fill the pool, then prepare_decode keeps it running
        for _ in range(3):
            s.append_token(0)
            assert s.prepare_decode() == []
        assert s.num_free_blocks == 0

    def test_preemption_round_trip(self, sched_factory):
        s = sched_factory(num_blocks=9, block_size=1, max_num_seqs=2)
        s.add(0, 3)
        s.add(1, 3)
        assert s.admit_next() == 0
        assert s.admit_next() == 1
        assert s.num_free_blocks == 0
        s.append_token(0)  # rid 0 now needs a 5th block
        preempted = s.prepare_decode()
        assert preempted == [1]
        assert s.slot(1) == -1
        assert s.num_waiting == 1
        assert s.block_row(1) == []
        # rid 1 re-admits once rid 0 finishes, with tokens intact
        s.finish(0)
        assert s.admit_next() == 1
        assert len(s.block_row(1)) == 4  # 3 tokens + 1 headroom

    def test_exhausted_single_sequence_raises(self, sched_factory):
        s = sched_factory(num_blocks=4, block_size=1, max_num_seqs=2)
        s.add(0, 2)
        assert s.admit_next() == 0  # takes all 3 usable blocks (2+1)
        s.append_token(0)
        with pytest.raises(SchedulerExhausted):
            s.prepare_decode()  # needs a 4th block, pool has 3 usable

    def test_exhausted_reports_prior_preemptions(self, sched_factory):
        # rid 0 grows so much in one prepare_decode that preempting BOTH
        # younger sequences still cannot satisfy it: the fatal error must
        # carry the preemptions already performed (they are not rolled
        # back — their requests sit in the waiting queue).
        s = sched_factory(num_blocks=10, block_size=1, max_num_seqs=3)
        for rid in (0, 1, 2):
            s.add(rid, 2)
            assert s.admit_next() == rid  # 3 blocks each: pool now empty
        for _ in range(7):
            s.append_token(0)  # rid 0 now needs blocks for 10 tokens
        with pytest.raises(SchedulerExhausted) as excinfo:
            s.prepare_decode()
        assert excinfo.value.preempted == [2, 1]
        assert s.slot(1) == -1 and s.slot(2) == -1
        assert s.num_waiting == 2

    def test_admit_impossible_request_raises(self, sched_factory):
        s = sched_factory(num_blocks=4, block_size=1, max_num_seqs=2)
        s.add(0, 10)
        with pytest.raises(SchedulerExhausted):
            s.admit_next()

    def test_duplicate_rid_rejected(self, sched_factory):
        s = sched_factory()
        s.add(0, 1)
        with pytest.raises(ValueError):
            s.add(0, 1)

    def test_finish_waiting_request(self, sched_factory):
        s = sched_factory()
        s.add(0, 1)
        s.finish(0)
        assert not s.has_unfinished


class TestPrepareDecodeK:
    """Multi-token reservation (the fused decode window's contract)."""

    @pytest.fixture(params=['py', 'native'])
    def sched_factory(self, request):
        if request.param == 'native' and not native_available():
            pytest.skip('no C++ toolchain')
        cls = PyScheduler if request.param == 'py' else NativeScheduler
        return cls

    def test_reserves_k_tokens_of_blocks(self, sched_factory):
        sched = sched_factory(num_blocks=32, block_size=4, max_num_seqs=2)
        sched.add(0, 6)  # needs 2 blocks for 7 tokens at admission
        assert sched.admit_next() == 0
        owned = len(sched.block_row(0))
        # Reserve 9 more tokens: 6 + 9 = 15 -> ceil(15/4) = 4 blocks.
        sched.prepare_decode(9)
        assert len(sched.block_row(0)) == 4
        assert len(sched.block_row(0)) >= owned

    def test_k_preempts_youngest_on_pressure(self, sched_factory):
        sched = sched_factory(num_blocks=8, block_size=4, max_num_seqs=2)
        sched.add(0, 4)
        sched.add(1, 4)
        assert sched.admit_next() == 0
        assert sched.admit_next() == 1
        # 7 usable blocks; both own 2 (4+1 tokens), 3 free. Reserving 12
        # more tokens each needs 2 extra blocks per sequence -> the second
        # extension falls short and the youngest (1) is preempted.
        preempted = sched.prepare_decode(12)
        assert preempted == [1]
        assert sched.slot(1) == -1
        assert len(sched.block_row(0)) == 4  # ceil((4+12)/4)

    def test_k_invalid_raises(self, sched_factory):
        sched = sched_factory(num_blocks=8, block_size=4, max_num_seqs=2)
        with pytest.raises(ValueError):
            sched.prepare_decode(0)

    def test_rows_filter_extends_only_selected(self, sched_factory):
        """Mixed serving windows: rows mid-prefill ride the window but
        take no decode steps, so prepare_decode(k, rids) must grant the
        k-token headroom only to the listed rows."""
        sched = sched_factory(num_blocks=16, block_size=4, max_num_seqs=3)
        sched.add(0, 4)
        sched.add(1, 4)
        assert sched.admit_next() == 0
        assert sched.admit_next() == 1
        free_before = sched.num_free_blocks
        assert sched.prepare_decode(8, [0]) == []
        assert len(sched.block_row(0)) == 3  # ceil((4+8)/4)
        assert len(sched.block_row(1)) == 2  # untouched
        assert sched.num_free_blocks == free_before - 1
        # Empty selection is a no-op (chunk-only windows never call this,
        # but the contract must hold).
        assert sched.prepare_decode(8, []) == []
        assert sched.num_free_blocks == free_before - 1

    def test_per_row_ks_extends_each_row_its_own_headroom(
        self, sched_factory
    ):
        """Speculative verify windows: prepare_decode(k, rids, ks) grants
        each listed row ITS OWN reservation instead of the batch max."""
        sched = sched_factory(num_blocks=32, block_size=4, max_num_seqs=3)
        sched.add(0, 4)
        sched.add(1, 4)
        assert sched.admit_next() == 0
        assert sched.admit_next() == 1
        assert sched.prepare_decode(1, [0, 1], [9, 1]) == []
        assert len(sched.block_row(0)) == 4  # ceil((4+9)/4)
        assert len(sched.block_row(1)) == 2  # ceil((4+1)/4) — untouched

    def test_per_row_ks_validation(self, sched_factory):
        sched = sched_factory(num_blocks=16, block_size=4, max_num_seqs=2)
        sched.add(0, 4)
        assert sched.admit_next() == 0
        with pytest.raises(ValueError):
            sched.prepare_decode(1, [0], [2, 3])  # length mismatch
        with pytest.raises(ValueError):
            sched.prepare_decode(1, [0], [0])  # per-row k < 1
        with pytest.raises(ValueError):
            sched.prepare_decode(1, None, [2])  # ks without rids
        with pytest.raises(ValueError):
            # duplicate rids make the per-row k ambiguous (and would
            # resolve differently in the two backends)
            sched.prepare_decode(1, [0, 0], [2, 3])

    def test_trim_returns_overreservation_restoring_free_order(
        self, sched_factory
    ):
        """trim frees owned tail blocks beyond num_tokens + 1, newest
        first, so the LIFO free list is restored exactly — a later
        extension re-pops the identical blocks (the never-drafted-state
        equality the speculative rollback relies on)."""
        sched = sched_factory(num_blocks=16, block_size=4, max_num_seqs=2)
        sched.add(0, 4)
        assert sched.admit_next() == 0
        free_before = sched.num_free_blocks
        row_before = sched.block_row(0)
        assert sched.prepare_decode(9, [0]) == []  # reserve to 4 blocks
        assert len(sched.block_row(0)) == 4
        assert sched.trim(0) == 2  # back to ceil(5/4) = 2 blocks
        assert sched.block_row(0) == row_before
        assert sched.num_free_blocks == free_before
        # Re-extending hands back the same blocks in the same order.
        grown = sched.block_row(0)
        sched.prepare_decode(9, [0])
        assert sched.block_row(0)[: len(grown)] == grown
        assert sched.trim(0) == 2
        assert sched.num_free_blocks == free_before

    def test_trim_noop_and_unknown_rid(self, sched_factory):
        sched = sched_factory(num_blocks=16, block_size=4, max_num_seqs=2)
        sched.add(0, 4)
        assert sched.admit_next() == 0
        assert sched.trim(0) == 0  # admission reserve is exactly right
        with pytest.raises(KeyError):
            sched.trim(99)

    def test_trim_never_frees_borrowed_prefix(self, sched_factory):
        """Borrowed (prefix-cache) blocks are cache property even when
        num_tokens shrinks below their coverage after preemption."""
        sched = sched_factory(num_blocks=16, block_size=4, max_num_seqs=2)
        sched.add(0, 3, cached_blocks=[5, 6, 7])  # 12 cached tokens > 3+1
        assert sched.admit_next() == 0
        assert sched.trim(0) == 0
        assert sched.block_row(0) == [5, 6, 7]

    def test_rows_filter_can_preempt_unselected_victim(self, sched_factory):
        """Victims are still chosen youngest-first over ALL running rows:
        a mid-prefill (unselected) youngest can be recompute-preempted to
        fund a decode-ready row's reservation."""
        sched = sched_factory(num_blocks=8, block_size=4, max_num_seqs=2)
        sched.add(0, 4)
        sched.add(1, 4)
        assert sched.admit_next() == 0
        assert sched.admit_next() == 1
        # 7 usable; each owns 2, 3 free. Row 0 reserving 20 more tokens
        # needs ceil(24/4)=6 blocks (+4): only preempting row 1 funds it.
        preempted = sched.prepare_decode(20, [0])
        assert preempted == [1]
        assert sched.slot(1) == -1
        assert len(sched.block_row(0)) == 6
