"""Argo-proxy and direct-OpenAI generators against a mocked HTTP server
(reference parity: chat_argoproxy.py:216-352)."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from distllm_tpu.generate import get_generator
from distllm_tpu.generate.generators.chat_endpoints import (
    ArgoGenerator,
    ArgoGeneratorConfig,
    OpenAIAPIGenerator,
    OpenAIAPIGeneratorConfig,
)


class _Handler(BaseHTTPRequestHandler):
    requests: list[dict] = []
    content: str | None = 'mock reply'
    finish_reason = 'stop'

    def do_POST(self):
        length = int(self.headers['Content-Length'])
        body = json.loads(self.rfile.read(length))
        body['_path'] = self.path
        body['_auth'] = self.headers.get('Authorization', '')
        _Handler.requests.append(body)
        payload = {
            'choices': [
                {
                    'message': {'content': _Handler.content},
                    'finish_reason': _Handler.finish_reason,
                }
            ]
        }
        data = json.dumps(payload).encode()
        self.send_response(200)
        self.send_header('Content-Type', 'application/json')
        self.send_header('Content-Length', str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, *args):
        pass


@pytest.fixture()
def mock_server():
    _Handler.requests = []
    _Handler.content = 'mock reply'
    server = HTTPServer(('127.0.0.1', 0), _Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f'http://127.0.0.1:{server.server_port}'
    server.shutdown()


def test_argo_generator(mock_server):
    gen = ArgoGenerator(
        ArgoGeneratorConfig(
            model='argo:gpt-4o', base_url=mock_server, user='alice'
        )
    )
    out = gen.generate('hello argo')
    assert out == ['mock reply']
    req = _Handler.requests[0]
    # /v1 appended, user field injected, system prompt prepended.
    assert req['_path'] == '/v1/chat/completions'
    assert req['user'] == 'alice'
    assert req['model'] == 'argo:gpt-4o'
    assert req['messages'][0]['role'] == 'system'
    assert req['messages'][1]['content'] == 'hello argo'
    assert 'max_tokens' in req


def test_argo_per_call_overrides(mock_server):
    gen = ArgoGenerator(ArgoGeneratorConfig(base_url=mock_server))
    gen.generate('x', temperature=0.7, max_tokens=12)
    req = _Handler.requests[-1]
    assert req['temperature'] == 0.7
    assert req['max_tokens'] == 12


def test_argo_error_returned_not_raised():
    gen = ArgoGenerator(
        ArgoGeneratorConfig(
            base_url='http://127.0.0.1:1', max_tries=1, timeout=0.2
        )
    )
    out = gen.generate('x')
    assert out[0].startswith('Error:')


def test_openai_requires_api_key(monkeypatch):
    monkeypatch.delenv('OPENAI_API_KEY', raising=False)
    with pytest.raises(ValueError, match='API key is required'):
        OpenAIAPIGenerator(OpenAIAPIGeneratorConfig(api_key=''))


def test_openai_generator(mock_server):
    gen = OpenAIAPIGenerator(
        OpenAIAPIGeneratorConfig(
            model='gpt-4.1', api_key='sk-test', base_url=mock_server
        )
    )
    out = gen.generate(['q1', 'q2'])
    assert out == ['mock reply', 'mock reply']
    req = _Handler.requests[0]
    # Modern field name + bearer auth.
    assert 'max_completion_tokens' in req and 'max_tokens' not in req
    assert req['_auth'] == 'Bearer sk-test'


def test_openai_none_content_reports_finish_reason(mock_server):
    _Handler.content = None
    _Handler.finish_reason = 'content_filter'
    gen = OpenAIAPIGenerator(
        OpenAIAPIGeneratorConfig(api_key='sk-test', base_url=mock_server)
    )
    out = gen.generate('q')
    assert out == ['[No content returned. Finish reason: content_filter]']


def test_factory_dispatch(mock_server):
    gen = get_generator(
        {'name': 'argo', 'base_url': mock_server, 'user': 'bob'}
    )
    assert isinstance(gen, ArgoGenerator)
    gen2 = get_generator(
        {'name': 'openai', 'api_key': 'sk-x', 'base_url': mock_server}
    )
    assert isinstance(gen2, OpenAIAPIGenerator)
