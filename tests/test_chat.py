"""Chat CLI + OpenAI-compatible server tests."""

import json
import re
import threading

import pytest

from distllm_tpu.chat import (
    ChatAppConfig,
    ChatSession,
    ConversationPromptTemplate,
    chat_with_model,
)


def test_conversation_template():
    template = ConversationPromptTemplate('be helpful')
    prompt = template.render(
        [
            {'role': 'user', 'content': 'hi'},
            {'role': 'assistant', 'content': 'hello'},
            {'role': 'user', 'content': 'what are cells'},
        ],
        contexts=['cells are small'],
        scores=[0.9],
    )
    assert prompt.startswith('be helpful')
    assert '[Context from retrieval]' in prompt
    assert '(score 0.900) cells are small' in prompt
    assert prompt.rstrip().endswith('assistant:')
    assert prompt.index('[Context') < prompt.index('user: hi')


def test_chat_session_history_grows():
    session = ChatSession(ChatAppConfig(generator_config={'name': 'fake'}))
    first = session.ask('hello there')
    assert 'hello there' in first or first  # fake echoes prompt fragment
    session.ask('second message')
    assert [t['role'] for t in session.history] == [
        'user', 'assistant', 'user', 'assistant',
    ]


def test_chat_repl_quit_and_transcript(tmp_path):
    config = ChatAppConfig(
        generator_config={'name': 'fake'}, transcript_dir=tmp_path
    )
    inputs = iter(['hello', 'quit'])
    outputs = []
    chat_with_model(config, input_fn=lambda _: next(inputs), echo=outputs.append)
    assert any('assistant>' in str(o) for o in outputs)
    transcripts = list(tmp_path.glob('chat_*.json'))
    assert len(transcripts) == 1
    history = json.loads(transcripts[0].read_text())
    assert history[0] == {'role': 'user', 'content': 'hello'}


def test_chat_inspect_command(tmp_path):
    from datasets import Dataset

    from distllm_tpu.embed import get_encoder, get_pooler
    from distllm_tpu.embed.embedders.full_sequence import compute_embeddings

    encoder = get_encoder({'name': 'fake', 'embedding_size': 16})
    pooler = get_pooler({'name': 'mean'})
    texts = ['protein folding basics', 'star formation rates']
    embeddings = compute_embeddings(texts, encoder, pooler, 2)
    Dataset.from_dict(
        {'text': texts, 'embeddings': [e for e in embeddings]}
    ).save_to_disk(str(tmp_path / 'corpus'))

    config = ChatAppConfig(
        generator_config={'name': 'fake'},
        retriever_config={
            'faiss_config': {'dataset_dir': str(tmp_path / 'corpus')},
            'encoder_config': {'name': 'fake', 'embedding_size': 16},
            'pooler_config': {'name': 'mean'},
        },
    )
    inputs = iter(['/inspect protein folding basics', 'quit'])
    outputs = []
    chat_with_model(config, input_fn=lambda _: next(inputs), echo=outputs.append)
    inspect_lines = [o for o in outputs if str(o).startswith('[')]
    assert inspect_lines, outputs
    assert 'score=' in inspect_lines[0]
    from distllm_tpu.registry import registry

    registry().clear()


def _start_chat_server(config: ChatAppConfig):
    """Boot ``build_app(config)`` on a free port in a daemon thread;
    returns ``(base_url, stop)``. Shared by the fixture and the tests
    that need their own server state (drain is one-way per process)."""
    pytest.importorskip('aiohttp')
    import socket

    from aiohttp import web

    from distllm_tpu.chat_server import build_app

    app = build_app(config)

    with socket.socket() as s:
        s.bind(('127.0.0.1', 0))
        port = s.getsockname()[1]

    loop_holder = {}

    def run():
        import asyncio

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        loop_holder['loop'] = loop
        runner = web.AppRunner(app)
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, '127.0.0.1', port)
        loop.run_until_complete(site.start())
        loop_holder['runner'] = runner
        loop.run_forever()

    thread = threading.Thread(target=run, daemon=True)
    thread.start()
    import time

    import requests

    for _ in range(50):
        try:
            requests.get(f'http://127.0.0.1:{port}/health', timeout=1)
            break
        except Exception:
            time.sleep(0.1)

    def stop():
        loop = loop_holder['loop']

        async def _shutdown():
            # Run the app's on_cleanup hooks (history sampler/observer
            # teardown) before stopping the loop — a bare loop.stop()
            # would leak the sampler thread into the next test.
            await loop_holder['runner'].cleanup()
            loop.stop()

        loop.call_soon_threadsafe(lambda: loop.create_task(_shutdown()))
        thread.join(timeout=10)

    return f'http://127.0.0.1:{port}', stop


@pytest.fixture
def chat_server_client(tmp_path):
    base, stop = _start_chat_server(
        ChatAppConfig(
            generator_config={'name': 'fake', 'response_template': 'server says: {prompt}', 'max_prompt_chars': 2000}
        )
    )
    yield base
    stop()


def test_chat_server_endpoints(chat_server_client):
    import requests

    base = chat_server_client
    assert requests.get(f'{base}/health').json()['status'] == 'ok'

    r = requests.post(
        f'{base}/v1/chat/completions',
        json={
            'model': 'm',
            'messages': [{'role': 'user', 'content': 'hello world'}],
        },
    )
    body = r.json()
    assert body['object'] == 'chat.completion'
    assert 'hello world' in body['choices'][0]['message']['content']

    # Missing messages -> 400
    r = requests.post(f'{base}/v1/chat/completions', json={})
    assert r.status_code == 400

    # Streaming: single delta + DONE
    r = requests.post(
        f'{base}/v1/chat/completions',
        json={
            'messages': [{'role': 'user', 'content': 'stream me'}],
            'stream': True,
        },
        stream=True,
    )
    lines = [line for line in r.iter_lines() if line]
    assert lines[-1] == b'data: [DONE]'
    chunk = json.loads(lines[0][len(b'data: ') :])
    assert chunk['object'] == 'chat.completion.chunk'
    assert 'stream me' in chunk['choices'][0]['delta']['content']


def test_chat_server_health_enriched(chat_server_client):
    import requests

    base = chat_server_client
    requests.post(
        f'{base}/v1/chat/completions',
        json={'messages': [{'role': 'user', 'content': 'warm up'}]},
    )
    body = requests.get(f'{base}/health').json()
    assert body['status'] == 'ok'
    assert body['uptime_s'] >= 0
    assert body['in_flight'] == 0  # this request is excluded from its own count
    assert body['requests_served'] >= 1
    assert isinstance(body['version'], str)


def test_chat_server_metrics_exposition(chat_server_client):
    import requests

    base = chat_server_client
    # Drive one request through so the latency histogram has observations.
    requests.post(
        f'{base}/v1/chat/completions',
        json={'messages': [{'role': 'user', 'content': 'measure me'}]},
    )
    r = requests.get(f'{base}/metrics')
    assert r.status_code == 200
    assert r.headers['Content-Type'].startswith('text/plain')
    text = r.text
    # Acceptance criteria: engine throughput, KV occupancy, queue depth and
    # the request-latency histogram must all be present in one scrape.
    assert '# TYPE distllm_engine_generated_tokens_total counter' in text
    assert '# TYPE distllm_kv_cache_occupancy_ratio gauge' in text
    assert '# TYPE distllm_scheduler_queue_depth gauge' in text
    assert (
        '# TYPE distllm_http_request_duration_seconds histogram' in text
    )
    assert 'distllm_http_request_duration_seconds_bucket{path="/v1/chat/completions",le="+Inf"}' in text
    assert 'distllm_http_request_duration_seconds_count{path="/v1/chat/completions"}' in text
    # Every sample line parses as <name>{labels} <value>.
    sample_re = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? '
        r'(\+Inf|-Inf|NaN|[0-9.eE+-]+)$'
    )
    for line in text.strip().splitlines():
        if line.startswith('#'):
            assert line.startswith(('# HELP ', '# TYPE ')), line
        else:
            assert sample_re.match(line), line


def test_chat_server_traces_endpoint(chat_server_client):
    import requests

    base = chat_server_client
    requests.post(
        f'{base}/v1/chat/completions',
        json={'messages': [{'role': 'user', 'content': 'trace me'}]},
    )
    body = requests.get(f'{base}/debug/traces?limit=50').json()
    assert 'spans' in body
    names = [s['name'] for s in body['spans']]
    assert 'chat-generate' in names
    for span in body['spans']:
        assert span['status'] in ('ok', 'error')
        assert span['duration_s'] >= 0
    assert requests.get(f'{base}/debug/traces?limit=x').status_code == 400


def test_chat_server_flight_endpoint(chat_server_client):
    import requests

    from distllm_tpu.observability import get_flight_recorder

    base = chat_server_client
    get_flight_recorder().record(
        'decode', duration_s=0.01, batch=2, queue_depth=0
    )
    body = requests.get(f'{base}/debug/flight?limit=50').json()
    assert body['total_recorded'] >= 1
    assert body['capacity'] >= 1
    kinds = [r['kind'] for r in body['records']]
    assert 'decode' in kinds
    for record in body['records']:
        assert 't_wall' in record
    assert requests.get(f'{base}/debug/flight?limit=x').status_code == 400


def test_chat_server_request_id_propagation(chat_server_client):
    """X-Request-Id: accepted inbound, echoed in header + payload, and
    stamped onto the spans recorded inside the request's scope."""
    import requests

    from distllm_tpu.observability import get_trace_buffer

    base = chat_server_client
    r = requests.post(
        f'{base}/v1/chat/completions',
        json={'messages': [{'role': 'user', 'content': 'trace this'}]},
        headers={'X-Request-Id': 'req-propagated-1'},
    )
    assert r.headers['X-Request-Id'] == 'req-propagated-1'
    assert r.json()['request_id'] == 'req-propagated-1'
    generate_spans = [
        s for s in get_trace_buffer().snapshot()
        if s.name == 'chat-generate'
        and s.attributes.get('request_id') == 'req-propagated-1'
    ]
    assert generate_spans, 'chat-generate span missing the propagated id'

    # No header -> a generated req-<hex> id, still echoed both ways.
    r = requests.post(
        f'{base}/v1/chat/completions',
        json={'messages': [{'role': 'user', 'content': 'no header'}]},
    )
    generated = r.headers['X-Request-Id']
    assert re.match(r'^req-[0-9a-f]{16}$', generated)
    assert r.json()['request_id'] == generated

    # A malformed inbound id is replaced, not echoed (header hygiene).
    r = requests.post(
        f'{base}/v1/chat/completions',
        json={'messages': [{'role': 'user', 'content': 'bad header'}]},
        headers={'X-Request-Id': 'bad id with spaces'},
    )
    assert re.match(r'^req-[0-9a-f]{16}$', r.headers['X-Request-Id'])

    # Streaming responses echo the id too.
    r = requests.post(
        f'{base}/v1/chat/completions',
        json={
            'messages': [{'role': 'user', 'content': 'stream'}],
            'stream': True,
        },
        headers={'X-Request-Id': 'req-stream-7'},
        stream=True,
    )
    assert r.headers['X-Request-Id'] == 'req-stream-7'
    chunk = json.loads(
        [line for line in r.iter_lines() if line][0][len(b'data: '):]
    )
    assert chunk['request_id'] == 'req-stream-7'


def test_chat_server_perfetto_endpoint(chat_server_client):
    """GET /debug/perfetto returns a structurally valid trace with the
    request-id-correlated server span on it (tentpole acceptance)."""
    import requests

    from distllm_tpu.observability import (
        get_flight_recorder,
        validate_trace_events,
    )

    base = chat_server_client
    requests.post(
        f'{base}/v1/chat/completions',
        json={'messages': [{'role': 'user', 'content': 'trace me'}]},
        headers={'X-Request-Id': 'req-perfetto-1'},
    )
    # An engine-style step + lifecycle pair as the serving side of the
    # correlation (the fake chat generator has no real engine).
    get_flight_recorder().record(
        'decode', duration_s=0.05, batch=1, tokens=8
    )
    get_flight_recorder().record(
        'request', request_id=0, trace_id='req-perfetto-1', e2e_s=0.2,
        ttft_s=0.1, output_tokens=8,
    )
    r = requests.get(f'{base}/debug/perfetto?limit=500')
    assert r.status_code == 200
    doc = r.json()
    assert validate_trace_events(doc) == []
    events = [e for e in doc['traceEvents'] if e.get('ph') != 'M']
    names = {e['name'] for e in events}
    assert 'decode' in names and 'chat-generate' in names
    # Request correlation: the lifecycle slice and the server span share
    # one track keyed by the propagated id.
    lifecycle = [e for e in events if e['name'] == 'req-perfetto-1']
    assert lifecycle
    tid = lifecycle[0]['tid']
    assert any(
        e['name'] == 'chat-generate' and e['tid'] == tid for e in events
    )
    assert requests.get(f'{base}/debug/perfetto?limit=x').status_code == 400


def test_chat_server_bundle_endpoint(chat_server_client, tmp_path, monkeypatch):
    import requests

    monkeypatch.setenv('DISTLLM_DEBUG_DIR', str(tmp_path))
    base = chat_server_client
    body = requests.get(f'{base}/debug/bundle').json()
    assert body['bundle_dir'].startswith(str(tmp_path))
    paths = body['paths']
    assert set(paths) >= {
        'flight', 'metrics', 'traces', 'meta', 'startup', 'history', 'slo'
    }
    from pathlib import Path

    assert Path(paths['meta']).exists()
    assert 'distllm_engine_generated_tokens_total' in Path(
        paths['metrics']
    ).read_text()
    startup = json.loads(Path(paths['startup']).read_text())
    assert 'compile' in startup and 'profiler' in startup


def test_chat_server_perfetto_startup_track(chat_server_client):
    """Compile-phase records from the process watcher surface as the
    dedicated startup track in GET /debug/perfetto (ISSUE 11 acceptance:
    a warmup ladder is visible shape by shape)."""
    import requests

    from distllm_tpu.observability import (
        get_compile_watcher,
        validate_trace_events,
    )

    base = chat_server_client
    with get_compile_watcher().phase('decode_window', 'b8x16'):
        pass
    doc = requests.get(f'{base}/debug/perfetto?limit=500').json()
    assert validate_trace_events(doc) == []
    startup = [
        e for e in doc['traceEvents'] if e.get('cat') == 'startup'
    ]
    assert any(e['name'] == 'decode_window:b8x16' for e in startup)


def test_chat_server_xprof_endpoint(chat_server_client, tmp_path, monkeypatch):
    import requests

    monkeypatch.setenv('DISTLLM_DEBUG_DIR', str(tmp_path))
    base = chat_server_client
    r = requests.get(f'{base}/debug/xprof?seconds=0.2')
    assert r.status_code == 200, r.text
    body = r.json()
    assert body['ok'] and body['trace_dir'].startswith(str(tmp_path))
    assert body['state']['active'] is None
    assert body['state']['captures_total'] >= 1
    # Bad input -> 400, never a capture.
    assert requests.get(f'{base}/debug/xprof?seconds=x').status_code == 400


def test_chat_server_history_endpoint(chat_server_client):
    """GET /debug/history serves the distllm-history/v1 ring with the
    background sampler running (DISTLLM_HISTORY_S default 1s); a bad
    limit is a 400, never a traceback."""
    import time

    import requests

    base = chat_server_client
    requests.post(
        f'{base}/v1/chat/completions',
        json={'messages': [{'role': 'user', 'content': 'sample me'}]},
    )
    # The ring fills on the sampler's cadence, not the request path:
    # counters need TWO folds before their first delta point exists, so
    # wait out (at most) a few ticks.
    deadline = time.time() + 15.0
    while True:
        body = requests.get(f'{base}/debug/history?limit=50').json()
        if body['samples'] >= 2 or time.time() > deadline:
            break
        time.sleep(0.2)
    assert body['schema'] == 'distllm-history/v1'
    assert body['sampler_running'] is True
    assert body['samples'] >= 2
    assert body['capacity'] >= 2 and isinstance(body['series'], dict)
    assert 'distllm_engine_generated_tokens_total' in body['series']
    # The prefix filter narrows the series map to matching names.
    narrowed = requests.get(f'{base}/debug/history?prefix=distllm_http').json()
    assert narrowed['series']
    assert all(k.startswith('distllm_http') for k in narrowed['series'])
    assert requests.get(f'{base}/debug/history?limit=x').status_code == 400


def test_chat_server_slo_endpoint(chat_server_client):
    """GET /debug/slo: the burn-rate verdict document plus the sentinel
    state (disarmed here — no DISTLLM_BASELINE in the test env)."""
    import requests

    base = chat_server_client
    body = requests.get(f'{base}/debug/slo').json()
    assert body['schema'] == 'distllm-slo/v1'
    assert body['verdict'] in ('ok', 'warn', 'page')
    assert set(body['burn_rates']) == {'60s', '300s', '600s', '3600s'}
    sentinel = body['sentinel']
    assert sentinel['schema'] == 'distllm-sentinel/v1'
    assert sentinel['armed'] is False and sentinel['degraded'] == []


# ------------------------------------------- resilience surface (ISSUE 15)
def test_chat_server_drain_lifecycle():
    """POST /drain: stop admitting (503 + Retry-After on completions),
    flip /health to not-ready — the readiness signal the multi-replica
    router polls (docs/resilience.md "Drain lifecycle")."""
    import requests

    base, stop = _start_chat_server(
        ChatAppConfig(generator_config={'name': 'fake'})
    )
    try:
        health = requests.get(f'{base}/health')
        assert health.status_code == 200
        body = health.json()
        assert body['ready'] is True and body['draining'] is False

        # A completion still serves before the drain.
        ok = requests.post(
            f'{base}/v1/chat/completions',
            json={'messages': [{'role': 'user', 'content': 'hi'}]},
        )
        assert ok.status_code == 200

        drained = requests.post(f'{base}/drain', params={'seconds': '0'})
        assert drained.status_code == 200
        body = drained.json()
        assert body['draining'] is True
        assert body['drained'] is True  # nothing else was in flight
        assert body['in_flight_remaining'] == 0

        health = requests.get(f'{base}/health')
        assert health.status_code == 503
        body = health.json()
        assert body['status'] == 'draining'
        assert body['ready'] is False

        refused = requests.post(
            f'{base}/v1/chat/completions',
            json={'messages': [{'role': 'user', 'content': 'late'}]},
        )
        assert refused.status_code == 503
        assert refused.headers['Retry-After']
        assert refused.json()['error']['type'] == 'draining'

        # Bad drain inputs are 400s, not crashes.
        assert requests.post(
            f'{base}/drain', params={'seconds': 'nan'}
        ).status_code == 400
    finally:
        stop()


def test_chat_server_drain_metrics_and_ready_gauge():
    import requests

    from distllm_tpu.observability import instruments

    base, stop = _start_chat_server(
        ChatAppConfig(generator_config={'name': 'fake'})
    )
    try:
        requests.post(f'{base}/drain', params={'seconds': '0'})
        shed_before = instruments.RESILIENCE_SHED.labels(
            reason='draining'
        ).value
        requests.post(
            f'{base}/v1/chat/completions',
            json={'messages': [{'role': 'user', 'content': 'x'}]},
        )
        assert instruments.RESILIENCE_SHED.labels(
            reason='draining'
        ).value == shed_before + 1
        metrics = requests.get(f'{base}/metrics').text
        assert 'distllm_server_ready 0' in metrics
        assert 'distllm_resilience_shed_requests_total' in metrics
    finally:
        stop()


def test_chat_server_overload_returns_429_with_retry_after():
    """EngineOverloaded from the generate path (SLO-aware admission
    shedding) surfaces as 429 + an honest Retry-After header."""
    import requests

    base, stop = _start_chat_server(
        ChatAppConfig(
            generator_config={'name': 'fake', 'overload_every': 2}
        )
    )
    try:
        payload = {'messages': [{'role': 'user', 'content': 'hello'}]}
        first = requests.post(f'{base}/v1/chat/completions', json=payload)
        assert first.status_code == 200
        second = requests.post(
            f'{base}/v1/chat/completions', json=payload,
            headers={'X-Request-Id': 'shed-me-1'},
        )
        assert second.status_code == 429
        assert second.headers['Retry-After'] == '3'
        assert second.headers['X-Request-Id'] == 'shed-me-1'
        body = second.json()
        assert body['error']['type'] == 'overloaded'
        assert body['error']['predicted_ttft_s'] > 0
        # The server recovered: the next request serves again.
        third = requests.post(f'{base}/v1/chat/completions', json=payload)
        assert third.status_code == 200
    finally:
        stop()
