"""Prompt-lookup speculative decoding (docs/speculative.md): drafter
units, the greedy on/off identity matrix, the acceptance-rule edge
matrix, rejected-suffix rollback state equality, and the per-accepted-
token TPOT/goodput accounting."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distllm_tpu.generate.engine import (
    EngineConfig,
    LLMEngine,
    SamplingParams,
)
from distllm_tpu.generate.engine.spec import PromptLookupDrafter
from distllm_tpu.models import mistral


class IdTokenizer:
    eos_id = None

    def decode(self, ids):
        return ' '.join(str(i) for i in ids)


def _tiny_cfg(**kw):
    base = dict(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=64, dtype='float32',
    )
    base.update(kw)
    return mistral.MistralConfig(**base)


def _engine(model_cfg, params, **cfg_kw):
    base = dict(
        block_size=4, num_blocks=96, max_num_seqs=2, max_model_len=96,
        prefer_native_allocator=False,
    )
    base.update(cfg_kw)
    return LLMEngine(model_cfg, params, IdTokenizer(), EngineConfig(**base))


def _dense_greedy_reference(cfg, params, prompt, n_tokens):
    ids = list(prompt)
    for _ in range(n_tokens):
        arr = np.asarray([ids], np.int32)
        hidden = mistral.apply(params, cfg, arr, np.ones_like(arr))
        lg = mistral.logits(params, cfg, hidden[:, -1])
        ids.append(int(np.argmax(np.asarray(lg)[0])))
    return ids[len(prompt):]


_STAGGER_PROMPT_LENS = (5, 21, 3, 33, 7, 13)
_STAGGER_OUT_LENS = (3, 17, 9, 5, 12, 8)


def _stagger_prompts(vocab, seed=1):
    """The mixed-window staggered serving workload, plus repetition: two
    prompts share a 2-block prefix (cache-hit tails), long prompts chunk,
    and half the prompts tile an n-gram motif so the prompt-lookup
    drafter has material."""
    rng = np.random.default_rng(seed)
    prompts = [
        list(rng.integers(1, vocab, size=n)) for n in _STAGGER_PROMPT_LENS
    ]
    shared = list(rng.integers(1, vocab, size=8))
    motif = list(rng.integers(1, vocab, size=4))
    for i in (1, 3):
        prompts[i] = (motif * (1 + len(prompts[i]) // 4))[: len(prompts[i])]
    prompts[0] = shared + prompts[0]
    prompts[4] = shared + prompts[4]
    return prompts


def _run_stagger(engine, vocab, seed=1):
    prompts = _stagger_prompts(vocab, seed)
    rids = [
        engine.add_request(p, SamplingParams(temperature=0.0, max_tokens=n))
        for p, n in zip(prompts, _STAGGER_OUT_LENS)
    ]
    engine._run_to_completion()
    return [engine._finished.pop(r).output_ids for r in rids]


# --------------------------------------------------------------- drafter
def test_drafter_proposes_latest_continuation():
    d = PromptLookupDrafter(ngram=2)
    history = [1, 2, 3, 9, 1, 2, 4, 7, 1, 2]
    # Final 2-gram (1, 2) last occurred at positions 4-5 -> continuation
    # [4, 7, 1, 2] (most recent match wins over the 0-1 occurrence).
    assert d.draft(history, 4) == [4, 7, 1, 2]
    assert d.draft(history, 2) == [4, 7]


def test_drafter_no_match_and_short_history():
    d = PromptLookupDrafter(ngram=3)
    assert d.draft([1, 2], 4) == []  # shorter than the n-gram
    assert d.draft([1, 2, 3, 4, 5], 4) == []  # (3,4,5) never seen before
    assert d.draft([1, 2, 3], 0) == []  # k == 0


def test_drafter_incremental_observation():
    d = PromptLookupDrafter(ngram=2)
    assert d.draft([5, 6, 7], 3) == []
    # Growing the history indexes only the new positions; the (5, 6)
    # occurrence is found once the suffix repeats it.
    assert d.draft([5, 6, 7, 5, 6], 3) == [7, 5, 6]
    # Terminal n-gram is never indexed against itself: a history ending
    # in its only occurrence proposes nothing rather than [].
    d2 = PromptLookupDrafter(ngram=2)
    assert d2.draft([1, 2, 3, 4], 3) == []


def test_drafter_rejects_bad_ngram():
    with pytest.raises(ValueError):
        PromptLookupDrafter(ngram=0)


# ------------------------------------------------- ragged rollback (op)
def test_ragged_decode_row_ignores_stale_suffix_kv(rng):
    """Rejected-draft K/V sits at positions >= the row's context; the
    ragged kernel must mask it out of every later query, which is the
    whole device-side rollback story (docs/speculative.md)."""
    from distllm_tpu.ops.paged_attention import (
        ragged_paged_attention_xla,
        write_chunk_kv,
    )

    block_size = 4
    k_cache = jnp.asarray(
        rng.normal(size=(8, block_size, 2, 8)).astype(np.float32)
    )
    v_cache = jnp.asarray(
        rng.normal(size=(8, block_size, 2, 8)).astype(np.float32)
    )
    block_tables = jnp.asarray([[2, 5]], dtype=jnp.int32)
    q = jnp.asarray(rng.normal(size=(1, 1, 4, 8)).astype(np.float32))
    q_positions = jnp.asarray([[5]], dtype=jnp.int32)
    context_lens = jnp.asarray([6], dtype=jnp.int32)
    clean = np.asarray(
        ragged_paged_attention_xla(
            q, k_cache, v_cache, block_tables, context_lens, q_positions,
            q_lens=jnp.asarray([1], jnp.int32),
        )
    )
    # Trash the suffix positions 6..7 (a rejected draft's writes).
    junk_k = jnp.full((1, 2, 2, 8), 1e9, jnp.float32)
    junk_v = jnp.full((1, 2, 2, 8), -1e9, jnp.float32)
    k_dirty, v_dirty = write_chunk_kv(
        k_cache, v_cache, junk_k, junk_v, block_tables,
        jnp.asarray([[6, 7]], jnp.int32), jnp.ones((1, 2), bool),
    )
    dirty = np.asarray(
        ragged_paged_attention_xla(
            q, k_dirty, v_dirty, block_tables, context_lens, q_positions,
            q_lens=jnp.asarray([1], jnp.int32),
        )
    )
    np.testing.assert_array_equal(clean, dirty)


# ------------------------------------------------------ identity matrix
def test_spec_token_identity_fast_canary():
    """Fast-tier spec on/off identity canary (fp32): prefix cache +
    chunked config on the staggered workload, and drafting must actually
    fire. The full matrix (sliding window, gemma2, mixed) runs in the
    slow tier."""
    cfg = _tiny_cfg()
    params = mistral.init(jax.random.PRNGKey(0), cfg)
    kw = dict(enable_prefix_cache=True, prefill_chunk_tokens=4)
    off = _run_stagger(
        _engine(cfg, params, draft_k=0, **kw), cfg.vocab_size
    )
    eng = _engine(cfg, params, draft_k=4, **kw)
    on = _run_stagger(eng, cfg.vocab_size)
    assert on == off
    assert eng._stats['spec_windows'] > 0
    assert eng._stats['spec_draft_tokens'] > 0
    assert eng._stats['spec_accepted_tokens'] > 0


@pytest.mark.slow
@pytest.mark.parametrize(
    'cfg_kw, engine_kw',
    [
        ({}, {}),
        ({}, {'enable_prefix_cache': True}),
        ({}, {'enable_prefix_cache': True, 'prefill_chunk_tokens': 4}),
        ({'sliding_window': 4}, {'prefill_chunk_tokens': 4}),
        (
            {},
            {
                'enable_mixed_batching': True,
                'enable_prefix_cache': True,
                'prefill_chunk_tokens': 4,
                'max_window_prefill_tokens': 8,
                'max_window_prefill_seqs': 2,
            },
        ),
    ],
    ids=[
        'plain', 'prefix_cache', 'prefix_cache_chunked', 'sliding_window',
        'mixed_batching',
    ],
)
def test_spec_token_identity_matrix(cfg_kw, engine_kw):
    """Greedy speculation on/off is token-identical across the engine
    identity matrix (fp32 — the regime where the decode-scan and ragged
    kernels agree bitwise; docs/speculative.md covers the bf16 kernel-
    universe caveat and its structural test below)."""
    cfg = _tiny_cfg(**cfg_kw)
    params = mistral.init(jax.random.PRNGKey(0), cfg)
    off = _run_stagger(
        _engine(cfg, params, draft_k=0, **engine_kw), cfg.vocab_size
    )
    eng = _engine(cfg, params, draft_k=4, **engine_kw)
    on = _run_stagger(eng, cfg.vocab_size)
    assert on == off
    assert eng._stats['spec_windows'] > 0
    if engine_kw.get('enable_mixed_batching'):
        # Chunk spans actually rode verify windows (mixed composition).
        assert eng._stats.get('spec_chunk_windows', 0) > 0
        assert eng._stats.get('mixed_prefill_tokens', 0) > 0


@pytest.mark.slow
def test_spec_token_identity_gemma2():
    """gemma2 serving (alternating windows, softcaps, sandwich norms,
    query_scale) through speculative windows stays token-exact."""
    from distllm_tpu.models import gemma

    cfg = gemma.GemmaConfig(
        name='gemma2', vocab_size=64, hidden_size=32, num_layers=4,
        num_heads=4, num_kv_heads=2, head_dim=16, intermediate_size=64,
        max_position_embeddings=128, dtype='float32',
        activation='gelu_new', embedding_multiplier=32 ** 0.5,
        norm_plus_one=True, post_norms=True, query_scale=16 ** -0.5,
        attn_logit_softcap=50.0, final_logit_softcap=30.0,
        sliding_window=6, sliding_window_pattern='alternating',
        tie_word_embeddings=True, rms_norm_eps=1e-6,
    )
    params = gemma.init(jax.random.PRNGKey(1), cfg)
    off = _run_stagger(
        _engine(cfg, params, draft_k=0, prefill_chunk_tokens=4),
        cfg.vocab_size,
    )
    eng = _engine(cfg, params, draft_k=4, prefill_chunk_tokens=4)
    on = _run_stagger(eng, cfg.vocab_size)
    assert on == off
    assert eng._stats['spec_windows'] > 0


def test_spec_structural_identity_bf16():
    """Drafting on vs off INSIDE the verify kernel is bit-identical even
    in bf16 (same fixed-shape executable; valid columns are independent
    of draft-column content) — the structural half of the bit-identity
    story that the gen_spec bench stage asserts on chip. Cross-KERNEL
    identity (vs the decode scan) is fp32-only: two compiled programs
    may round a near-tied bf16 logit differently."""
    cfg = _tiny_cfg(vocab_size=256, hidden_size=64, intermediate_size=128,
                    dtype='bfloat16')
    params = mistral.init(jax.random.PRNGKey(0), cfg)
    null = _run_stagger(
        _engine(cfg, params, draft_k=4, spec_draft_source='none',
                enable_prefix_cache=True),
        cfg.vocab_size,
    )
    eng = _engine(cfg, params, draft_k=4, enable_prefix_cache=True)
    on = _run_stagger(eng, cfg.vocab_size)
    assert on == null
    assert eng._stats['spec_accepted_tokens'] > 0


# -------------------------------------------------- acceptance edge matrix
class _StubDrafter:
    """Deterministic proposals for the acceptance-rule edge matrix."""

    def __init__(self, proposals):
        self.proposals = list(proposals)

    def draft(self, history, k):
        start = len(history)
        return self.proposals[start:start + k]


def _force_drafts(engine, rid, proposals, prompt_len):
    """Install a stub drafter proposing ``proposals`` (indexed by
    absolute history position past the prompt)."""
    pad = [0] * prompt_len
    engine._requests[rid].drafter = _StubDrafter(pad + list(proposals))


def test_acceptance_all_accepted_matches_reference():
    cfg = _tiny_cfg()
    params = mistral.init(jax.random.PRNGKey(0), cfg)
    prompt = [5, 9, 12]
    n = 9
    ref = _dense_greedy_reference(cfg, params, prompt, n)
    eng = _engine(cfg, params, draft_k=4)
    rid = eng.add_request(
        prompt, SamplingParams(temperature=0.0, max_tokens=n)
    )
    # Propose the exact greedy continuation: every draft must be accepted
    # (ref[i] is the token at history position len(prompt)+i; drafts for
    # a history ending at position p propose ref[p-len(prompt):]).
    _force_drafts(eng, rid, ref + [0] * 8, len(prompt))
    eng._run_to_completion()
    assert eng._finished.pop(rid).output_ids == ref
    # 9 tokens in 1 prefill emission + ceil(8 / (1+4)) spec windows:
    # full drafts accepted -> far fewer windows than tokens.
    assert eng._stats['spec_accepted_tokens'] > 0
    assert (
        eng._stats['spec_accepted_tokens']
        == eng._stats['spec_draft_tokens']
    )
    assert eng._stats['spec_windows'] < n


def test_acceptance_zero_accepted_matches_reference():
    cfg = _tiny_cfg()
    params = mistral.init(jax.random.PRNGKey(0), cfg)
    prompt = [7, 3, 22]
    n = 6
    ref = _dense_greedy_reference(cfg, params, prompt, n)
    eng = _engine(cfg, params, draft_k=3)
    rid = eng.add_request(
        prompt, SamplingParams(temperature=0.0, max_tokens=n)
    )
    # Propose deliberately wrong tokens: nothing accepted, output exact.
    wrong = [(t + 1) % cfg.vocab_size for t in ref] + [1] * 8
    _force_drafts(eng, rid, wrong, len(prompt))
    eng._run_to_completion()
    assert eng._finished.pop(rid).output_ids == ref
    assert eng._stats['spec_accepted_tokens'] == 0
    assert eng._stats['spec_draft_tokens'] > 0


def test_acceptance_eos_inside_accepted_prefix():
    """EOS (a stop token) accepted mid-span finishes the request there;
    the already-verified suffix is discarded, not emitted."""
    cfg = _tiny_cfg()
    params = mistral.init(jax.random.PRNGKey(0), cfg)
    prompt = [5, 9, 12]
    ref = _dense_greedy_reference(cfg, params, prompt, 8)
    stop = ref[3]
    eng = _engine(cfg, params, draft_k=4)
    rid = eng.add_request(
        prompt,
        SamplingParams(
            temperature=0.0, max_tokens=20, stop_token_ids=(stop,)
        ),
    )
    _force_drafts(eng, rid, ref + [0] * 16, len(prompt))
    eng._run_to_completion()
    # Raw output_ids keep the stop token (generate_ids strips it): the
    # stream must end EXACTLY at the stop, the verified suffix discarded.
    out = eng._finished.pop(rid).output_ids
    assert out == ref[: ref.index(stop) + 1]


def test_acceptance_preemption_mid_draft():
    """A pool too small for every row forces recompute preemption between
    verify windows; outputs stay exact and no blocks leak."""
    cfg = _tiny_cfg()
    params = mistral.init(jax.random.PRNGKey(0), cfg)
    eng = _engine(
        cfg, params, draft_k=4, num_blocks=14, max_num_seqs=3,
        max_model_len=64,
    )
    prompts = [[5, 9, 12], [7, 3, 22, 31], [1, 2, 3, 4, 5]]
    n = 6
    rids = [
        eng.add_request(p, SamplingParams(temperature=0.0, max_tokens=n))
        for p in prompts
    ]
    eng._run_to_completion()
    for prompt, rid in zip(prompts, rids):
        ref = _dense_greedy_reference(cfg, params, prompt, n)
        assert eng._finished.pop(rid).output_ids == ref
    assert eng.sched.num_free_blocks == 13  # no leaks


def test_temperature_rows_draft_with_sampled_verification():
    """Stochastic rows draft too: device-side rejection sampling verifies
    their spans (docs/speculative.md "Sampled verification"). The stub
    drafter guarantees proposals regardless of what the sampled history
    looks like (prompt-lookup matches would be luck on a random model)."""
    cfg = _tiny_cfg()
    params = mistral.init(jax.random.PRNGKey(0), cfg)
    eng = _engine(cfg, params, draft_k=4)
    prompt = [5, 9, 12, 5, 9, 12]
    rid = eng.add_request(
        prompt, SamplingParams(temperature=0.9, max_tokens=7)
    )
    # Sampled rows get the real prompt-lookup drafter attached now (the
    # old greedy-only gate is gone) ...
    assert eng._requests[rid].drafter is not None
    # ... which the stub then replaces so drafting is deterministic here.
    _force_drafts(eng, rid, [7] * 16, len(prompt))
    eng._run_to_completion()
    assert len(eng._finished.pop(rid).output_ids) == 7
    assert eng._stats.get('spec_draft_tokens', 0) > 0
    assert eng._stats['spec_windows'] > 0


# ------------------------------------------- rejected-suffix rollback state
def test_rejected_suffix_rolls_back_to_never_drafted_state():
    """After a window whose drafts are ALL rejected, KV block rows, the
    scheduler free list (content AND order), and PrefixCache refcounts
    must equal a never-drafted run at the same point — the rollback
    contract (per-row reservation + sched.trim)."""
    cfg = _tiny_cfg()
    params = mistral.init(jax.random.PRNGKey(0), cfg)
    prompt = [5, 9, 12, 4, 7, 3, 22, 31]  # 2 full blocks for the cache

    def run_one_window(draft_k, wrong_drafts):
        eng = _engine(
            cfg, params, draft_k=draft_k, enable_prefix_cache=True,
            decode_steps=1, pipeline_depth=1,
        )
        rid = eng.add_request(
            prompt, SamplingParams(temperature=0.0, max_tokens=8)
        )
        if wrong_drafts:
            ref = _dense_greedy_reference(cfg, params, prompt, 8)
            _force_drafts(
                eng, rid, [(t + 1) % cfg.vocab_size for t in ref] + [1] * 8,
                len(prompt),
            )
        # Admit + prefill, then exactly two decode/verify windows.
        for _ in range(2):
            eng.step()
        return eng, rid

    spec, rid_a = run_one_window(4, wrong_drafts=True)
    base, rid_b = run_one_window(0, wrong_drafts=False)
    assert spec._stats['spec_draft_tokens'] > 0
    assert spec._stats['spec_accepted_tokens'] == 0
    a, b = spec._requests[rid_a], base._requests[rid_b]
    assert a.output_ids == b.output_ids
    assert spec.sched.block_row(rid_a) == base.sched.block_row(rid_b)
    assert spec.sched.num_free_blocks == base.sched.num_free_blocks
    # Free-list CONTENT equality, not just count (PyScheduler backend).
    assert spec.sched._inner._free == base.sched._inner._free
    # PrefixCache state: same inserted digests, same refcounts.
    pc_a, pc_b = spec.prefix_cache, base.prefix_cache
    assert set(pc_a._entries) == set(pc_b._entries)
    for digest, entry in pc_a._entries.items():
        assert entry.refcount == pc_b._entries[digest].refcount


# ---------------------------------------- accounting, metrics, and flight
def test_tpot_and_goodput_count_accepted_tokens():
    """distllm_request_tpot_seconds divides by ACCEPTED TOKENS (n_out-1)
    and distllm_engine_goodput_tokens_total advances by accepted tokens,
    not windows — multi-token speculative windows must not deflate
    either series."""
    from distllm_tpu.observability import instruments as metrics

    cfg = _tiny_cfg()
    params = mistral.init(jax.random.PRNGKey(0), cfg)
    n = 9
    eng = _engine(cfg, params, draft_k=4, ttft_slo_s=60.0)
    ref = _dense_greedy_reference(cfg, params, [5, 9, 12], n)
    goodput_before = metrics.GOODPUT_TOKENS.value
    tpot_count_before = metrics.REQUEST_TPOT.count
    tpot_sum_before = metrics.REQUEST_TPOT.sum
    rid = eng.add_request(
        [5, 9, 12], SamplingParams(temperature=0.0, max_tokens=n)
    )
    _force_drafts(eng, rid, ref + [0] * 8, len([5, 9, 12]))
    eng._run_to_completion()
    request = eng._finished[rid]
    n_out = len(request.output_ids)
    assert n_out == n
    # Goodput counts every accepted token of the SLO-met request.
    assert metrics.GOODPUT_TOKENS.value - goodput_before == n_out
    assert eng._stats['goodput_tokens'] == n_out
    # TPOT: one observation per finished request, normalized per token —
    # (finish - first) / (n_out - 1), so several tokens landing in one
    # verify window measure as genuinely fast tokens, not one window.
    assert metrics.REQUEST_TPOT.count - tpot_count_before == 1
    observed = metrics.REQUEST_TPOT.sum - tpot_sum_before
    expected = (request.t_finish - request.t_first_token) / (n_out - 1)
    assert observed == pytest.approx(expected)
    # Fewer windows than tokens (speculation!) yet full token accounting.
    assert eng._stats['spec_windows'] < n_out


def test_spec_flight_records_and_metrics():
    """Verify windows record kind='spec' with draft/accepted payloads and
    the distllm_engine_spec_* series advance."""
    from distllm_tpu.observability import instruments as metrics
    from distllm_tpu.observability.flight import get_flight_recorder

    cfg = _tiny_cfg()
    params = mistral.init(jax.random.PRNGKey(0), cfg)
    before = len(
        [r for r in get_flight_recorder().snapshot() if r['kind'] == 'spec']
    )
    windows_before = metrics.SPEC_WINDOWS.value
    drafts_before = metrics.SPEC_DRAFT_TOKENS.value
    accepted_before = metrics.SPEC_ACCEPTED_TOKENS.value
    eng = _engine(cfg, params, draft_k=4)
    _run_stagger(eng, cfg.vocab_size)
    records = [
        r for r in get_flight_recorder().snapshot() if r['kind'] == 'spec'
    ]
    assert len(records) > before
    rec = records[-1]
    assert 'draft_tokens' in rec and 'accepted_tokens' in rec
    assert metrics.SPEC_WINDOWS.value > windows_before
    assert metrics.SPEC_DRAFT_TOKENS.value > drafts_before
    assert metrics.SPEC_ACCEPTED_TOKENS.value >= accepted_before


# ----------------------------------------------------------- validation
def test_spec_config_validation():
    with pytest.raises(ValueError, match='draft_k'):
        EngineConfig(draft_k=-1)
    with pytest.raises(ValueError, match='spec_ngram'):
        EngineConfig(spec_ngram=0)
    with pytest.raises(ValueError, match='mutually exclusive'):
        EngineConfig(draft_k=4, defer_prefill=True)
    with pytest.raises(ValueError, match='spec_draft_source'):
        EngineConfig(spec_draft_source='oracle')
    # Normal composition stays legal.
    assert EngineConfig(
        draft_k=4, enable_mixed_batching=True, prefill_chunk_tokens=16
    ).draft_k == 4


def test_tpu_generator_config_allows_spec_with_temperature():
    # Sampled verification lifted the old greedy-only rejection: draft_k
    # composes with temperature > 0 (docs/speculative.md "Sampled
    # verification").
    from distllm_tpu.generate.generators.tpu_backend import (
        TpuGeneratorConfig,
    )

    cfg = TpuGeneratorConfig(
        pretrained_model_name_or_path='/tmp/x', temperature=0.5,
        draft_k=4,
    )
    assert cfg.draft_k == 4
    cfg = TpuGeneratorConfig(
        pretrained_model_name_or_path='/tmp/x', temperature=0.0, draft_k=4,
    )
    assert cfg.draft_k == 4
