"""Embed pipeline tests: datasets, poolers, embedders, writers, end-to-end."""

import json

import numpy as np
import pytest

import jax.numpy as jnp

from distllm_tpu.embed import (
    get_dataset,
    get_embedder,
    get_encoder,
    get_pooler,
    get_writer,
)
from distllm_tpu.embed.embedders.full_sequence import compute_embeddings
from distllm_tpu.embed.embedders.semantic_chunk import (
    build_chunks,
    calculate_distances_between_buffer,
)
from distllm_tpu.embed.poolers.last_token import last_token_pool
from distllm_tpu.embed.poolers.mean import average_pool


# ---------------------------------------------------------------- datasets
def _write_jsonl(path, entries):
    with open(path, 'w') as fh:
        for e in entries:
            fh.write(json.dumps(e) + '\n')


def test_jsonl_dataset(tmp_path):
    f = tmp_path / 'data.jsonl'
    _write_jsonl(f, [{'text': 'hello', 'path': 'a'}, {'text': 'world', 'path': 'b'}])
    ds = get_dataset({'name': 'jsonl'})
    corpus = ds.read(f)
    assert corpus.texts == ['hello', 'world']
    assert corpus.metadata == [{'path': 'a'}, {'path': 'b'}]


def test_jsonl_chunk_dataset(tmp_path):
    text = (
        'Machine learning is great. ' * 3
        + 'Bananas are yellow fruit. ' * 3
    )
    f = tmp_path / 'd.jsonl'
    _write_jsonl(f, [{'text': text, 'path': 'doc1'}])
    ds = get_dataset({'name': 'jsonl_chunk', 'min_buffer_length': 10, 'buffer_size': 1})
    corpus = ds.read(f)
    assert len(corpus) > 0
    # every buffer carries the source sentence + original metadata
    assert all('sentence' in m and m['path'] == 'doc1' for m in corpus.metadata)
    # buffers are windows, so interior buffers span >= their own sentence
    assert all(len(t) >= len(m['sentence']) for t, m in zip(corpus.texts, corpus.metadata))


def test_fasta_dataset(tmp_path):
    f = tmp_path / 'seqs.fasta'
    f.write_text('>seq1 desc\nacgt\nACGT\n>seq2\nmkvl\n')
    corpus = get_dataset({'name': 'fasta'}).read(f)
    assert corpus.texts == ['ACGTACGT', 'MKVL']
    assert corpus.metadata[0]['tags'] == 'seq1 desc'


def test_sequence_per_line_dataset(tmp_path):
    f = tmp_path / 'lines.txt'
    f.write_text('header\nAAA\nBBB\n\n')
    corpus = get_dataset({'name': 'sequence_per_line', 'header_lines': 1}).read(f)
    assert corpus.texts == ['AAA', 'BBB']


def test_huggingface_dataset(tmp_path):
    from datasets import Dataset

    Dataset.from_dict({'text': ['x', 'y'], 'path': ['p1', 'p2']}).save_to_disk(
        str(tmp_path / 'hf')
    )
    corpus = get_dataset(
        {'name': 'huggingface', 'metadata_fields': ['path']}
    ).read(tmp_path / 'hf')
    assert corpus.texts == ['x', 'y']
    assert corpus.metadata == [{'path': 'p1'}, {'path': 'p2'}]


def test_unknown_strategy():
    with pytest.raises(ValueError, match='Unknown dataset'):
        get_dataset({'name': 'bogus'})


# ---------------------------------------------------------------- poolers
def test_average_pool_excludes_start_end_per_row():
    # Row 0: valid length 4 -> interior tokens at positions 1, 2
    # Row 1: valid length 3 -> interior token at position 1
    hidden = jnp.arange(2 * 5 * 2, dtype=jnp.float32).reshape(2, 5, 2)
    mask = jnp.array([[1, 1, 1, 1, 0], [1, 1, 1, 0, 0]])
    pooled = np.asarray(average_pool(hidden, mask))
    expected0 = np.asarray(hidden[0, 1:3]).mean(axis=0)
    expected1 = np.asarray(hidden[1, 1:2]).mean(axis=0)
    np.testing.assert_allclose(pooled[0], expected0)
    np.testing.assert_allclose(pooled[1], expected1)


def test_average_pool_zero_length_no_nan():
    hidden = jnp.ones((1, 4, 3))
    mask = jnp.zeros((1, 4), dtype=jnp.int32)
    pooled = np.asarray(average_pool(hidden, mask))
    assert np.isfinite(pooled).all()


def test_last_token_pool_right_padded():
    hidden = jnp.arange(2 * 4 * 2, dtype=jnp.float32).reshape(2, 4, 2)
    mask = jnp.array([[1, 1, 1, 0], [1, 1, 1, 1]])
    pooled = np.asarray(last_token_pool(hidden, mask))
    np.testing.assert_allclose(pooled[0], np.asarray(hidden[0, 2]))
    np.testing.assert_allclose(pooled[1], np.asarray(hidden[1, 3]))


def test_last_token_pool_left_padded():
    hidden = jnp.arange(2 * 4 * 2, dtype=jnp.float32).reshape(2, 4, 2)
    mask = jnp.array([[0, 1, 1, 1], [1, 1, 1, 1]])
    pooled = np.asarray(last_token_pool(hidden, mask))
    np.testing.assert_allclose(pooled[0], np.asarray(hidden[0, 3]))
    np.testing.assert_allclose(pooled[1], np.asarray(hidden[1, 3]))


# ------------------------------------------------------------- embedders
def test_compute_embeddings_order_and_determinism():
    encoder = get_encoder({'name': 'fake', 'embedding_size': 16})
    pooler = get_pooler({'name': 'mean'})
    texts = ['one two three', 'a much longer text with many more words here', 'x']
    out1 = compute_embeddings(texts, encoder, pooler, batch_size=2)
    out2 = compute_embeddings(texts, encoder, pooler, batch_size=3)
    assert out1.shape == (3, 16)
    # batch size must not change results (order restoration works)
    np.testing.assert_allclose(out1, out2, atol=1e-5)


def test_compute_embeddings_normalized():
    encoder = get_encoder({'name': 'fake', 'embedding_size': 8})
    pooler = get_pooler({'name': 'mean'})
    out = compute_embeddings(['hello world foo', 'bar baz'], encoder, pooler, 2, normalize=True)
    np.testing.assert_allclose(np.linalg.norm(out, axis=1), 1.0, atol=1e-5)


def test_distances_and_chunk_building():
    embeds = np.array([[1, 0], [1, 0.01], [0, 1], [0, 1.01]], dtype=np.float32)
    d = calculate_distances_between_buffer(embeds)
    assert len(d) == 3
    assert d[1] > d[0] and d[1] > d[2]  # breakpoint in the middle
    groups = build_chunks(d, breakpoint_percentile_threshold=50)
    assert groups[0] == (0, 2)
    assert groups[-1][1] == len(d) + 1
    assert build_chunks(np.zeros(0), 90) == [(0, 0)]


def test_semantic_chunk_embedder_end_to_end(tmp_path):
    rng = np.random.default_rng(0)
    sents_a = ['alpha beta gamma delta. '] * 4
    sents_b = ['totally different subject matter now. '] * 4
    text = ''.join(sents_a + sents_b)
    f = tmp_path / 'doc.jsonl'
    _write_jsonl(f, [{'text': text, 'path': 'docA'}])
    corpus = get_dataset(
        {'name': 'jsonl_chunk', 'min_buffer_length': 5, 'buffer_size': 1}
    ).read(f)
    encoder = get_encoder({'name': 'fake', 'embedding_size': 32})
    pooler = get_pooler({'name': 'mean'})
    embedder = get_embedder(
        {'name': 'semantic_chunk', 'min_chunk_length': 10, 'chunk_batch_size': 4}
    )
    result = embedder.embed(corpus, encoder, pooler, batch_size=4)
    assert len(result.text) == len(result.embeddings)
    assert result.embeddings.shape[1] == 32
    assert all('sentence' not in m for m in result.metadata)
    assert all(m['path'] == 'docA' for m in result.metadata)


# ---------------------------------------------------------------- writers
def _small_result():
    from distllm_tpu.embed.embedders.base import EmbedderResult

    return EmbedderResult(
        embeddings=np.arange(6, dtype=np.float32).reshape(2, 3),
        text=['t1', 't2'],
        metadata=[{'path': 'a'}, {'path': 'b'}],
    )


def test_numpy_writer_roundtrip_and_merge(tmp_path):
    writer = get_writer({'name': 'numpy'})
    writer.write(tmp_path / 's1', _small_result())
    writer.write(tmp_path / 's2', _small_result())
    writer.merge([tmp_path / 's1', tmp_path / 's2'], tmp_path / 'merged')
    merged = np.load(tmp_path / 'merged' / 'embeddings.npy')
    assert merged.shape == (4, 3)
    texts = np.load(tmp_path / 'merged' / 'text.npy', allow_pickle=True)
    assert list(texts) == ['t1', 't2', 't1', 't2']


def test_huggingface_writer_roundtrip_and_merge(tmp_path):
    from datasets import load_from_disk

    writer = get_writer({'name': 'huggingface'})
    writer.write(tmp_path / 's1', _small_result())
    writer.write(tmp_path / 's2', _small_result())
    writer.merge(
        [tmp_path / 's1', tmp_path / 's2', tmp_path / 'missing'],
        tmp_path / 'merged',
    )
    ds = load_from_disk(str(tmp_path / 'merged'))
    assert len(ds) == 4
    assert set(ds.column_names) == {'text', 'embeddings', 'path'}


# ---------------------------------------------------------- warmstart
def test_encoder_warmstart_registry():
    from distllm_tpu.registry import registry

    e1 = get_encoder({'name': 'fake', 'embedding_size': 8}, register=True)
    e2 = get_encoder({'name': 'fake', 'embedding_size': 8}, register=True)
    assert e1 is e2
    e3 = get_encoder({'name': 'fake', 'embedding_size': 16}, register=True)
    assert e3 is not e1
    registry().clear()


def test_tokenize_ahead_matches_inline():
    """Background-thread tokenize-ahead must be a pure perf knob: same
    embeddings, same order, any depth."""
    import numpy as np

    from distllm_tpu.embed import get_encoder, get_pooler
    from distllm_tpu.embed.embedders.full_sequence import compute_embeddings

    encoder = get_encoder({'name': 'fake', 'embedding_size': 16})
    pooler = get_pooler({'name': 'mean'})
    texts = [f'doc {i} ' + 'tok ' * (3 + (i * 7) % 40) for i in range(23)]

    base = compute_embeddings(texts, encoder, pooler, 4, tokenize_ahead=0)
    for depth in (1, 2, 5):
        ahead = compute_embeddings(
            texts, encoder, pooler, 4, tokenize_ahead=depth
        )
        np.testing.assert_array_equal(base, ahead)
