"""Quantized int8 KV cache tests (docs/serving.md "Quantized KV cache"):
per-block absmax error bounds, quantize-at-write parity across the three
write paths, fused-dequant attention parity (XLA and the interpreted
Pallas kernel), backend resolution for the int8 sublane tile, and
engine-level identity / accuracy contracts for ``kv_cache_dtype``.

Error-bound discipline: a SINGLE-SHOT write (prefill, block-aligned
chunks) quantizes every row once at its block's final scale, so the
round-trip error is at most half a quantization step — ``scale / 2 ==
absmax / 254``. The APPEND path (decode's rescale-on-append) re-expresses
earlier int8 rows whenever the running absmax grows, adding a second
rounding — its bound is ~1 step of the FINAL scale, not absmax/254. The
tests below encode the distinction; collapsing them to one bound would
either mask append-path regressions or flake on legitimate rescales.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distllm_tpu.generate.engine import EngineConfig, LLMEngine, SamplingParams
from distllm_tpu.generate.engine.kv_cache import PagedKVCache
from distllm_tpu.models import mistral
from distllm_tpu.ops.paged_attention import (
    KV_QUANT_MAX,
    QuantizedKV,
    kv_storage_dtype,
    kv_sublane_tile,
    paged_attention_pallas,
    paged_attention_xla,
    quantize_kv_rows,
    resolve_attn_backend,
    write_chunk_kv,
    write_prefill_kv,
    write_token_kv,
)


def _dequant(cache: QuantizedKV) -> np.ndarray:
    data = np.asarray(cache.data, np.float32)
    scale = np.asarray(cache.scale, np.float32)
    return data * scale[:, None, :, None]


def _zero_quant_cache(num_blocks=4, block_size=4, nkv=2, hd=8):
    data = jnp.zeros((num_blocks, block_size, nkv, hd), jnp.int8)
    scale = jnp.zeros((num_blocks, nkv), jnp.float32)
    return QuantizedKV(data, scale)


# ------------------------------------------------------------ unit: quantize
def test_quantize_kv_rows_error_bound(rng):
    rows = jnp.asarray(rng.normal(size=(6, 3, 16)).astype(np.float32)) * 5.0
    absmax = jnp.max(jnp.abs(rows), axis=-1)  # [6, 3]
    scale = absmax / KV_QUANT_MAX
    q = quantize_kv_rows(rows, scale)
    assert q.dtype == jnp.int8
    err = np.abs(
        np.asarray(q, np.float32) * np.asarray(scale)[..., None]
        - np.asarray(rows)
    )
    # Single-shot bound: half a step of the row's own scale.
    bound = np.asarray(scale)[..., None] / 2 + 1e-6
    assert (err <= bound).all()


def test_quantize_kv_rows_zero_scale_is_exact_zero(rng):
    # Fresh all-zero blocks and trash-block garbage carry scale 0: the
    # guarded division must emit exact zeros, never NaN/inf (a NaN here
    # would poison every masked softmax that multiplies the trash block).
    rows = jnp.asarray(rng.normal(size=(2, 2, 4)).astype(np.float32))
    q = quantize_kv_rows(rows, jnp.zeros((2, 2), jnp.float32))
    assert np.asarray(q).sum() == 0
    assert np.isfinite(np.asarray(q, np.float32)).all()


# ------------------------------------------------------- unit: write paths
def test_write_prefill_kv_quantized_scales_and_error(rng):
    k_cache = _zero_quant_cache()
    v_cache = _zero_quant_cache()
    k_seq = jnp.asarray(rng.normal(size=(8, 2, 8)).astype(np.float32))
    v_seq = jnp.asarray(rng.normal(size=(8, 2, 8)).astype(np.float32))
    row = jnp.asarray([1, 2, 0, 0], dtype=jnp.int32)
    k_cache, v_cache = write_prefill_kv(
        k_cache, v_cache, k_seq, v_seq, row, jnp.int32(6)
    )
    assert isinstance(k_cache, QuantizedKV)
    assert kv_storage_dtype(k_cache) == jnp.dtype(jnp.int8)
    # Block 1 holds tokens 0..3, block 2 tokens 4..5: each block's scale
    # is exactly the absmax of its LIVE rows / 127, K and V independent.
    k_np = np.asarray(k_seq)
    expect_b1 = np.abs(k_np[:4]).max(axis=(0, 2)) / KV_QUANT_MAX
    expect_b2 = np.abs(k_np[4:6]).max(axis=(0, 2)) / KV_QUANT_MAX
    np.testing.assert_allclose(
        np.asarray(k_cache.scale[1]), expect_b1, rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(k_cache.scale[2]), expect_b2, rtol=1e-6
    )
    deq = _dequant(k_cache)
    scale = np.asarray(k_cache.scale)
    # Single-shot bound over the live rows.
    err1 = np.abs(deq[1] - k_np[:4])
    assert (err1 <= scale[1][None, :, None] / 2 + 1e-6).all()
    err2 = np.abs(deq[2][:2] - k_np[4:6])
    assert (err2 <= scale[2][None, :, None] / 2 + 1e-6).all()
    # Rows past `length` stayed zero (the trash block ate the padding).
    assert np.asarray(k_cache.data[2][2:]).sum() == 0


def test_write_token_kv_rescale_on_append_error_bound(rng):
    # Fill one block token by token with GROWING magnitudes, forcing a
    # rescale of the already-written int8 rows on every append — the
    # worst case for the running-absmax path.
    block_size, nkv, hd = 4, 2, 8
    k_cache = _zero_quant_cache(block_size=block_size, nkv=nkv, hd=hd)
    v_cache = _zero_quant_cache(block_size=block_size, nkv=nkv, hd=hd)
    table = jnp.asarray([[1, 0, 0, 0]], dtype=jnp.int32)
    rows = [
        rng.normal(size=(1, nkv, hd)).astype(np.float32) * (1.0 + 3.0 * t)
        for t in range(block_size)
    ]
    for t, r in enumerate(rows):
        k_cache, v_cache = write_token_kv(
            k_cache, v_cache, jnp.asarray(r), jnp.asarray(r * 2.0),
            table, jnp.asarray([t], dtype=jnp.int32),
        )
    written = np.concatenate(rows, axis=0)  # [block_size, nkv, hd]
    final_scale = np.asarray(k_cache.scale[1])  # [nkv]
    # The running absmax only grows, so the final scale covers the
    # largest row exactly.
    np.testing.assert_allclose(
        final_scale, np.abs(written).max(axis=(0, 2)) / KV_QUANT_MAX,
        rtol=1e-6,
    )
    err = np.abs(_dequant(k_cache)[1] - written)
    # APPEND bound: ~1 step of the FINAL scale (quantize once + at most
    # a ratio re-round per row), looser than the single-shot scale/2.
    assert (err <= 1.5 * final_scale[None, :, None] + 1e-6).all()


def test_write_chunk_kv_quantized_block_aligned_matches_prefill(rng):
    # Block-aligned chunks write each block fresh in one shot, so the
    # chunk path must land the SAME scales (and the same single-shot
    # error bound) as one whole-sequence prefill of the identical rows.
    block_size, nkv, hd = 4, 2, 8
    seq = rng.normal(size=(8, nkv, hd)).astype(np.float32)
    row = jnp.asarray([1, 2, 0, 0], dtype=jnp.int32)

    pk, pv = write_prefill_kv(
        _zero_quant_cache(), _zero_quant_cache(),
        jnp.asarray(seq), jnp.asarray(seq), row, jnp.int32(8),
    )

    ck, cv = _zero_quant_cache(), _zero_quant_cache()
    table = row[None, :]
    for start in (0, 4):
        positions = jnp.arange(start, start + block_size)[None, :]
        ck, cv = write_chunk_kv(
            ck, cv,
            jnp.asarray(seq[start:start + block_size])[None],
            jnp.asarray(seq[start:start + block_size])[None],
            table, positions, jnp.ones((1, block_size), bool),
        )
    np.testing.assert_allclose(
        np.asarray(ck.scale), np.asarray(pk.scale), rtol=1e-6
    )
    np.testing.assert_array_equal(np.asarray(ck.data), np.asarray(pk.data))


# -------------------------------------------------- fused-dequant attention
def _random_quant_cache(rng, num_blocks=8, block_size=4, nkv=2, hd=8):
    data = rng.integers(-127, 128, size=(num_blocks, block_size, nkv, hd))
    scale = rng.uniform(0.01, 0.1, size=(num_blocks, nkv))
    return QuantizedKV(
        jnp.asarray(data.astype(np.int8)),
        jnp.asarray(scale.astype(np.float32)),
    )


def test_paged_attention_xla_int8_matches_dequantized_cache(rng):
    # The fused gather-dequant must be numerically the SAME attention as
    # running the bare-array path over a materialized fp32 dequant.
    k_cache = _random_quant_cache(rng)
    v_cache = _random_quant_cache(rng)
    block_tables = jnp.asarray([[2, 5], [7, 0]], dtype=jnp.int32)
    context_lens = jnp.asarray([6, 3], dtype=jnp.int32)
    q = jnp.asarray(rng.normal(size=(2, 4, 8)).astype(np.float32))
    fused = np.asarray(
        paged_attention_xla(q, k_cache, v_cache, block_tables, context_lens)
    )
    dense = np.asarray(
        paged_attention_xla(
            q, jnp.asarray(_dequant(k_cache)), jnp.asarray(_dequant(v_cache)),
            block_tables, context_lens,
        )
    )
    np.testing.assert_allclose(fused, dense, atol=1e-5, rtol=1e-4)


def test_paged_attention_pallas_interpret_matches_xla_int8(rng):
    # The kernel's per-page scale DMA + fused scores/probs scaling
    # against the XLA gather-dequant reference, on the interpreter.
    k_cache = _random_quant_cache(rng)
    v_cache = _random_quant_cache(rng)
    block_tables = jnp.asarray([[2, 5], [7, 0]], dtype=jnp.int32)
    context_lens = jnp.asarray([6, 3], dtype=jnp.int32)
    q = jnp.asarray(rng.normal(size=(2, 4, 8)).astype(np.float32))
    ref = np.asarray(
        paged_attention_xla(q, k_cache, v_cache, block_tables, context_lens)
    )
    out = np.asarray(
        paged_attention_pallas(
            q, k_cache, v_cache, block_tables, context_lens, interpret=True
        )
    )
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-4)


# ------------------------------------------------------- backend resolution
def test_kv_sublane_tile_by_dtype():
    assert kv_sublane_tile('int8') == 32
    assert kv_sublane_tile('bfloat16') == 16
    assert kv_sublane_tile('float32') == 8


def test_resolve_auto_int8_misaligned_block_size_keeps_xla():
    # 'auto' must NEVER trace into the kernel's geometry ValueError: the
    # default block_size=16 with an int8 pool (sublane tile 32) resolves
    # to the XLA tier on every platform. Alignment alone doesn't force
    # 'pallas' (that needs a TPU), but misalignment must force 'xla'.
    cfg = mistral.MistralConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=64, dtype='float32',
    )
    assert resolve_attn_backend(
        'auto', cfg, block_size=16, kv_dtype='int8'
    ) == 'xla'
    # Explicit pins pass through untouched — the ENGINE owns the loud
    # construction-time raise for those (test below).
    assert resolve_attn_backend(
        'pallas', cfg, block_size=16, kv_dtype='int8'
    ) == 'pallas'


# ------------------------------------------------------------------ engine
def _engine(kv_cache_dtype='auto', dtype='float32', **cfg_kw):
    cfg = mistral.MistralConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=64, dtype=dtype,
    )
    params = mistral.init(jax.random.PRNGKey(0), cfg)

    class IdTokenizer:
        eos_id = None

        def decode(self, ids):
            return ' '.join(str(i) for i in ids)

    engine_cfg = EngineConfig(
        block_size=cfg_kw.pop('block_size', 4),
        num_blocks=cfg_kw.pop('num_blocks', 64),
        max_num_seqs=4,
        max_model_len=64,
        prefer_native_allocator=False,
        kv_cache_dtype=kv_cache_dtype,
        **cfg_kw,
    )
    return LLMEngine(cfg, params, IdTokenizer(), engine_cfg)


def test_engine_explicit_pallas_pin_int8_misaligned_raises():
    # The actionable construction-time raise (NOT a mid-warmup Mosaic
    # trace error): explicit kernel pin + int8 + block_size 4.
    with pytest.raises(ValueError, match='use block_size=32'):
        _engine(kv_cache_dtype='int8', attn_backend='interpret')


def test_engine_fp32_pin_matches_auto_bit_exact():
    # Explicit 'fp32' on an fp32 model is the SAME pool dtype 'auto'
    # picks: token streams must be bit-identical (the default-config
    # compatibility contract — kv_cache_dtype='auto' changes nothing).
    prompts = [[5, 9, 12], [7, 3, 22, 31, 40, 2, 17]]
    sp = SamplingParams(temperature=0.0, max_tokens=6)
    auto = _engine('auto').generate_ids(prompts, sp)
    pinned = _engine('fp32').generate_ids(prompts, sp)
    assert pinned == auto


@pytest.mark.parametrize(
    'extra',
    [
        dict(enable_prefix_cache=True),
        dict(enable_prefix_cache=True, prefill_chunk_tokens=8),
        dict(
            enable_prefix_cache=True,
            prefill_chunk_tokens=8,
            enable_mixed_batching=True,
            max_window_prefill_tokens=8,
        ),
        dict(draft_k=2),
    ],
    ids=['prefix', 'chunked', 'mixed', 'spec'],
)
def test_engine_bf16_pin_identity_matrix(extra):
    # Satellite: explicit kv_cache_dtype='bf16' on a bf16 model IS
    # today's default pool — token identity must hold across the
    # existing identity matrix (prefix cache x chunked x mixed x spec),
    # not just the plain batched path.
    shared = list(range(1, 11))
    prompts = [shared + [20], shared + [30, 31, 32], [7, 3, 22, 31, 40]]
    sp = SamplingParams(temperature=0.0, max_tokens=6)
    auto = _engine('auto', dtype='bfloat16', **extra)
    pinned = _engine('bf16', dtype='bfloat16', **extra)
    assert auto.generate_ids(prompts, sp) == pinned.generate_ids(prompts, sp)
    assert pinned.telemetry['kv_cache_dtype'] == 'bfloat16'


def test_engine_int8_end_to_end_greedy_divergence_recorded():
    # int8 serves end to end; divergence from the float engine is
    # MEASURED and bounded below, not asserted to zero — per-block absmax
    # keeps a tiny random model's greedy stream mostly aligned, and a
    # collapse of the match fraction means the quantizer broke.
    prompts = [[5, 9, 12], [7, 3, 22, 31, 40, 2, 17], [1, 2, 3, 4, 5]]
    sp = SamplingParams(temperature=0.0, max_tokens=8)
    ref_engine = _engine('auto')
    ref = ref_engine.generate_ids(prompts, sp)
    q_engine = _engine('int8')
    assert q_engine.telemetry['kv_cache_dtype'] == 'int8'
    assert q_engine.kv.quantized
    out = q_engine.generate_ids(prompts, sp)
    assert [len(o) for o in out] == [len(r) for r in ref]
    total = sum(len(r) for r in ref)
    matched = sum(
        sum(1 for a, b in zip(o, r) if a == b) for o, r in zip(out, ref)
    )
    match = matched / total
    # Evidence floor, not an identity claim: sustained agreement shows
    # the dequantized cache is feeding real attention, while the exact
    # fraction stays a recorded metric (bench gen_kvq_greedy_match).
    assert match >= 0.5, f'greedy match collapsed: {match:.3f}'


def test_engine_int8_pool_bytes_halve():
    fp = _engine('fp32')
    q = _engine('int8')
    ratio = q.kv.hbm_bytes / fp.kv.hbm_bytes
    # int8 data is 1/4 of fp32 + the fp32 scale planes; against a bf16
    # pool the same layout lands at ~0.5. Either way it must be well
    # under the full-precision pool.
    assert ratio < 0.5
    assert isinstance(q.kv.k, QuantizedKV)
    assert q.kv.k.scale.shape == (2, 64, 2)


def test_paged_kv_cache_int8_spec_is_quantized_pytree():
    pool = PagedKVCache(
        num_layers=2, num_blocks=8, block_size=4, num_kv_heads=2,
        head_dim=8, dtype='int8',
    )
    spec = pool.spec()
    assert isinstance(spec, QuantizedKV)
    assert spec.data.dtype == jnp.dtype(jnp.int8)
    assert spec.scale.shape == (2, 8, 2)
