"""ModernBERT JAX implementation vs transformers golden numerics."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distllm_tpu.models import modernbert

transformers = pytest.importorskip('transformers')


def _tiny_hf_config():
    from transformers import ModernBertConfig as HFConfig

    return HFConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=96,
        num_hidden_layers=5,  # layers 0,3 global; 1,2,4 local
        num_attention_heads=4,
        max_position_embeddings=128,
        global_attn_every_n_layers=3,
        local_attention=8,  # window small enough to matter at S=24
        global_rope_theta=160000.0,
        local_rope_theta=10000.0,
        norm_eps=1e-5,
        pad_token_id=0,
        reference_compile=False,
        attn_implementation='eager',
    )


@pytest.fixture(scope='module')
def hf_model():
    import torch

    from transformers import ModernBertModel

    torch.manual_seed(0)
    model = ModernBertModel(_tiny_hf_config())
    model.eval()
    return model


def test_matches_transformers(hf_model):
    import torch

    hf_cfg = hf_model.config.to_dict()
    cfg = modernbert.ModernBertConfig.from_hf_config(hf_cfg)
    cfg.dtype = 'float32'
    assert cfg.num_layers == 5 and cfg.local_attention == 8

    state = {k: v.numpy() for k, v in hf_model.state_dict().items()}
    params = modernbert.params_from_hf(state, cfg)

    rng = np.random.default_rng(0)
    ids = rng.integers(1, 256, size=(3, 24)).astype(np.int64)
    mask = np.ones((3, 24), np.int64)
    mask[1, 17:] = 0  # padded row exercises the key-validity mask
    ids[1, 17:] = 0

    with torch.no_grad():
        want = hf_model(
            input_ids=torch.from_numpy(ids),
            attention_mask=torch.from_numpy(mask),
        ).last_hidden_state.numpy()

    got = np.asarray(
        modernbert.apply(
            params, cfg, jnp.asarray(ids, jnp.int32),
            jnp.asarray(mask, jnp.int32),
        )
    )
    # Padded positions produce garbage in both stacks; compare valid rows.
    np.testing.assert_allclose(got[0], want[0], atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(got[2], want[2], atol=2e-4, rtol=2e-4)
    np.testing.assert_allclose(
        got[1, :17], want[1, :17], atol=2e-4, rtol=2e-4
    )


def test_local_window_actually_restricts(hf_model):
    """Changing a token outside every local window must not change a far
    position's output at local layers — but DOES reach it through global
    layers; so instead verify our window mask logic directly against a
    global-only variant: with local_attention >= 2*S the model must equal
    a config where every layer is global."""
    hf_cfg = hf_model.config.to_dict()
    cfg = modernbert.ModernBertConfig.from_hf_config(hf_cfg)
    cfg.dtype = 'float32'
    cfg.local_attention = 4 * 24  # window covers everything
    # Match thetas so ONLY the mask differs between local and global.
    cfg.local_rope_theta = cfg.global_rope_theta
    params = modernbert.init(jax.random.PRNGKey(0), cfg)

    cfg_all_global = cfg.model_copy(
        update={'global_attn_every_n_layers': 1}
    )
    params_all_global = dict(params)
    params_all_global['global_flag'] = modernbert._global_flags(
        cfg_all_global
    )

    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(1, 256, size=(2, 24)), jnp.int32)
    mask = jnp.ones((2, 24), jnp.int32)
    a = modernbert.apply(params, cfg, ids, mask)
    b = modernbert.apply(params_all_global, cfg_all_global, ids, mask)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_auto_encoder_dispatches_modernbert(tmp_path):
    """AutoEncoder routes model_type=modernbert through the JAX stack."""
    import json

    import torch

    from transformers import ModernBertModel

    torch.manual_seed(0)
    model = ModernBertModel(_tiny_hf_config())
    model.save_pretrained(tmp_path)
    # Synthesize a minimal fast tokenizer on disk (zero egress).
    from tokenizers import Tokenizer
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import Whitespace

    vocab = {'[UNK]': 0, '[PAD]': 1}
    vocab.update({f'w{i}': i + 2 for i in range(100)})
    tok_fast = Tokenizer(WordLevel(vocab, unk_token='[UNK]'))
    tok_fast.pre_tokenizer = Whitespace()
    tok_fast.save(str(tmp_path / 'tokenizer.json'))
    (tmp_path / 'tokenizer_config.json').write_text(
        json.dumps({'tokenizer_class': 'PreTrainedTokenizerFast',
                    'pad_token': '[PAD]', 'unk_token': '[UNK]',
                    'model_max_length': 128})
    )

    from distllm_tpu.embed.encoders.auto import AutoEncoder, AutoEncoderConfig

    enc = AutoEncoder(
        AutoEncoderConfig(
            pretrained_model_name_or_path=str(tmp_path),
            half_precision=False,
        )
    )
    assert enc.embedding_size == 64
    assert type(enc.model_cfg).__name__ == 'ModernBertConfig'
