"""Open-loop load generator tests (ISSUE 10 tentpole + CI satellite):
deterministic seeded workloads, the in-process run harness against a tiny
real engine, and the ``gen_load`` bench stage as a CPU smoke (fast tier —
tens of requests, seeded) asserting non-zero TTFT percentiles, a
warm-prefix hit, and attribution-on/off token identity."""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

import jax

from distllm_tpu.generate.engine import EngineConfig, LLMEngine
from distllm_tpu.generate.loadgen import (
    LoadgenConfig,
    build_workload,
    run_loadgen,
)
from distllm_tpu.models import mistral

REPO = Path(__file__).resolve().parent.parent


# ----------------------------------------------------------- workload build
def test_build_workload_deterministic():
    cfg = LoadgenConfig(seed=7, num_requests=40)
    a = build_workload(cfg)
    b = build_workload(cfg)
    assert a == b  # same seed -> byte-identical workload
    c = build_workload(LoadgenConfig(seed=8, num_requests=40))
    assert a != c


def test_cache_blocks_is_an_engine_knob_not_a_workload_knob():
    """cache_blocks overrides the ENGINE pool size (so CPU smokes can
    force HBM-tier eviction with tiny pools — the gen_tier stage); the
    workload itself must be byte-identical across pool sizes, or tier
    on/off A/Bs would silently measure different traffic."""
    a = build_workload(LoadgenConfig(seed=7, num_requests=40))
    b = build_workload(
        LoadgenConfig(seed=7, num_requests=40, cache_blocks=48)
    )
    assert a == b
    assert LoadgenConfig().cache_blocks is None


def test_build_workload_poisson_arrivals_and_mix():
    cfg = LoadgenConfig(
        seed=0, num_requests=200, rate_rps=10.0, num_sessions=3,
        warm_fraction=0.5, prefix_tokens=16,
    )
    workload = build_workload(cfg)
    assert len(workload) == 200
    ats = [a.at_s for a in workload]
    assert ats == sorted(ats)
    assert all(at > 0 for at in ats)
    # Mean inter-arrival gap ~ 1/rate (Poisson process, generous bound).
    mean_gap = ats[-1] / len(ats)
    assert 0.05 < mean_gap < 0.2
    warm = [a for a in workload if a.session is not None]
    cold = [a for a in workload if a.session is None]
    assert len(warm) > 50 and len(cold) > 50  # both sides of the mix
    # Warm requests share their session's full prefix; sessions differ.
    by_session: dict = {}
    for a in warm:
        by_session.setdefault(a.session, []).append(a)
    assert len(by_session) == 3
    for session, arrivals in by_session.items():
        prefixes = {a.prompt_ids[: cfg.prefix_tokens] for a in arrivals}
        assert len(prefixes) == 1
    all_prefixes = {
        arrivals[0].prompt_ids[: cfg.prefix_tokens]
        for arrivals in by_session.values()
    }
    assert len(all_prefixes) == 3
    # Output budgets stay in range.
    lo, hi = cfg.output_tokens
    assert all(lo <= a.max_tokens <= hi for a in workload)


def test_build_workload_rejects_bad_config():
    import pytest

    with pytest.raises(ValueError):
        build_workload(LoadgenConfig(num_requests=0))
    with pytest.raises(ValueError):
        build_workload(LoadgenConfig(rate_rps=0.0))


# --------------------------------------------------------- run harness
def test_run_loadgen_tiny_engine_reports():
    cfg = mistral.MistralConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=64, dtype='float32',
    )
    params = mistral.init(jax.random.PRNGKey(0), cfg)

    class IdTokenizer:
        eos_id = None

    engine = LLMEngine(
        cfg, params, IdTokenizer(),
        EngineConfig(
            block_size=4, num_blocks=64, max_num_seqs=4, max_model_len=64,
            prefer_native_allocator=False, enable_prefix_cache=True,
            ttft_slo_s=30.0, decode_steps=4,
        ),
    )
    load_cfg = LoadgenConfig(
        seed=3, num_requests=10, rate_rps=200.0, num_sessions=2,
        warm_fraction=0.6, prefix_tokens=8, prompt_tokens=(3, 10),
        output_tokens=(2, 6), vocab_size=cfg.vocab_size,
    )
    workload = build_workload(load_cfg)
    report = run_loadgen(engine, workload)
    assert report.requests == 10
    assert report.tokens > 0
    assert len(report.tokens_by_request) == 10
    for arrival, tokens in zip(
        sorted(workload, key=lambda a: a.at_s), report.tokens_by_request
    ):
        assert 0 < len(tokens) <= arrival.max_tokens
    # Histogram-estimated percentiles exist and are positive and ordered.
    p50 = report.percentiles['ttft_p50']
    p95 = report.percentiles['ttft_p95']
    p99 = report.percentiles['ttft_p99']
    assert p50 and p50 > 0
    assert p95 and p50 <= p95 <= p99
    assert report.percentiles['queue_wait_p50'] is not None
    # Warm sessions actually hit the prefix cache (2-block prefixes).
    assert report.warm_prefix_hit_tokens > 0
    assert report.warm_requests + report.cold_requests == 10
    # SLO accounting: a 30 s SLO on a tiny engine is always met.
    assert report.slo_met == 10 and report.slo_missed == 0
    assert report.goodput_tokens == report.tokens
    # Roofline attribution ran per window kind.
    assert 'decode' in report.roofline and 'prefill' in report.roofline
    assert report.roofline['decode']['mfu'] > 0
    assert report.roofline['decode']['bw_util'] > 0
    # Flight records carry the attribution split on this run's windows.
    decode_records = [
        r for r in engine.flight.snapshot()
        if r['kind'] == 'decode' and 'fetch_s' in r
    ]
    assert decode_records
    assert all('dispatch_s' in r and 'mfu' in r for r in decode_records)
    # And the fragment flattening used by the bench stage is total —
    # and strict-JSON clean (no inf/nan leaks into the bench record).
    fragment = report.to_fragment('x_')
    assert fragment['x_requests'] == 10
    assert fragment['x_ttft_p50'] == round(p50, 6)
    assert 'x_mfu_decode' in fragment and 'x_bw_util_decode' in fragment
    json.loads(json.dumps(fragment, allow_nan=False))

    # Attribution-off replay on the SAME warm engine: bit-identical
    # greedy tokens, and the roofline summary is delta-scoped — nothing
    # accumulates while attribution is off, so the off arm reports {}
    # instead of the on arm's stale aggregate.
    engine.attribution = False
    off = run_loadgen(engine, workload)
    assert off.tokens_by_request == report.tokens_by_request
    assert off.roofline == {}
    # Flipping attribution ON at runtime works even though this engine
    # could have been built with attribution off (cost model is always
    # constructed): the next run accumulates again.
    engine.attribution = True
    back_on = run_loadgen(engine, workload)
    assert back_on.tokens_by_request == report.tokens_by_request
    assert 'decode' in back_on.roofline


def test_run_loadgen_single_request_offered_rps_is_json_safe():
    from distllm_tpu.generate.loadgen import LoadReport

    report = LoadReport(
        requests=1, tokens=4, elapsed_s=0.1, offered_rps=None,
        achieved_tok_s=40.0, percentiles={}, window_tok_s={},
        goodput_tokens=4, goodput_frac=1.0, slo_met=1, slo_missed=0,
        warm_prefix_hit_tokens=0, warm_requests=0, cold_requests=1,
        roofline={}, tokens_by_request=[[1, 2, 3, 4]],
    )
    fragment = report.to_fragment('x_')
    assert fragment['x_offered_rps'] is None
    json.loads(json.dumps(fragment, allow_nan=False))


# -------------------------------------------- gen_load bench stage (smoke)
def _run_stage(tmp_path, **env_extra):
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS='cpu',
        DISTLLM_BENCH_SMALL='1',
        DISTLLM_BENCH_RECORD_DIR=str(tmp_path),
        DISTLLM_BENCH_BUNDLE_DIR=str(tmp_path / 'bundles'),
        DISTLLM_BENCH_WATCHDOG_S='0',
    )
    env.update(env_extra)
    proc = subprocess.run(
        [sys.executable, str(REPO / 'bench.py'), '--stage', 'gen_load'],
        capture_output=True, text=True, timeout=420, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_gen_load_stage_cpu_smoke(tmp_path):
    """The CI satellite: the checkpointed gen_load fragment reports
    non-zero TTFT percentiles, at least one warm-prefix hit, per-kind
    MFU/bandwidth utilization, and attribution-on/off token identity."""
    fragment = _run_stage(tmp_path)
    assert fragment['gen_load_requests'] == 24
    assert fragment['gen_load_ttft_p50'] > 0
    assert fragment['gen_load_ttft_p95'] > 0
    assert fragment['gen_load_ttft_p99'] >= fragment['gen_load_ttft_p95']
    assert fragment['gen_load_tpot_p50'] > 0
    assert fragment['gen_load_queue_wait_p50'] is not None
    assert fragment['gen_load_warm_prefix_hit_tokens'] >= 1
    assert fragment['gen_load_tokens_identical'] is True
    assert 'gen_load_error' not in fragment
    # Goodput: SLO accounting plus per-request delivered-rate percentiles.
    assert fragment['gen_load_goodput_tokens'] > 0
    assert fragment['gen_load_goodput_tok_s_p50'] > 0
    assert fragment['gen_load_slo_met'] + fragment['gen_load_slo_missed'] == 24
    # Per-window-kind roofline attribution in the checkpointed fragment.
    assert fragment['gen_load_mfu_decode'] > 0
    assert fragment['gen_load_bw_util_decode'] > 0
    assert fragment['gen_load_mfu_prefill'] > 0


def test_gen_load_stage_env_skip(tmp_path):
    fragment = _run_stage(tmp_path, DISTLLM_BENCH_LOAD='0')
    assert fragment == {'gen_load_skipped': 'DISTLLM_BENCH_LOAD=0'}


def test_loadgen_cli_reports_history_excerpt():
    """scripts/loadgen.py (ISSUE 18 satellite): the CLI owns the process
    history sampler for its run, and the JSON report line carries the
    compact ``loadgen_history_*`` excerpt — the sampled tok/s series plus
    the SLO burn-rate gauges — not just end-of-run aggregates."""
    env = dict(os.environ)
    env.update(JAX_PLATFORMS='cpu')
    proc = subprocess.run(
        [
            sys.executable, str(REPO / 'scripts' / 'loadgen.py'),
            '--small', '--requests', '8', '--rate', '50', '--slo', '2.0',
            '--history-interval', '0.2',
        ],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    fragment = json.loads(proc.stdout.strip().splitlines()[-1])
    assert fragment['loadgen_requests'] == 8
    assert fragment['loadgen_history_window_s'] == 60.0
    assert fragment['loadgen_history_samples'] >= 2
    assert fragment['loadgen_history_tok_s'] > 0
    assert fragment['loadgen_history_tok_points']
    assert set(fragment['loadgen_history_burn_rates']) == {
        '60s', '300s', '600s', '3600s'
    }
