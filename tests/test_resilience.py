"""Resilience layer tests (ISSUE 15): deterministic fault injection,
engine crash-domain recovery (retry → quarantine, deadlines), and
SLO-aware admission shedding (docs/resilience.md).

The chaos matrix is the acceptance contract: under each injected fault
class the engine either RECOVERS (retry succeeds, tokens bit-identical
to the fault-free run in greedy fp32) or fails ONLY the affected
requests with a recorded error — never wedges the window loop, never
drops a request silently.
"""

from __future__ import annotations

import pytest

import jax

from distllm_tpu.generate.engine import (
    EngineConfig,
    LLMEngine,
    SamplingParams,
)
from distllm_tpu.generate.engine.engine import RequestState
from distllm_tpu.models import mistral
from distllm_tpu.observability import instruments as _metrics
from distllm_tpu.resilience import (
    FAULT_SITES,
    EngineLoadView,
    EngineOverloaded,
    FaultInjector,
    InjectedFault,
    get_fault_injector,
    parse_fault_spec,
    predict_ttft,
    shed_decision,
)


@pytest.fixture(autouse=True)
def _disarm_injector():
    """Every test starts and ends with an inert process injector."""
    injector = get_fault_injector()
    injector.disarm()
    yield injector
    injector.disarm()


# ------------------------------------------------------------ faults unit
class TestFaultInjector:
    def test_inert_by_default(self):
        injector = FaultInjector()
        assert not injector.armed
        assert injector.fire('dispatch') is None
        injector.fail('dispatch')  # no raise
        assert injector.maybe_sleep('slow_window') == 0.0

    def test_deterministic_schedule(self):
        injector = FaultInjector()
        injector.arm('dispatch', times=2, after=3)
        fires = [injector.fire('dispatch') is not None for _ in range(8)]
        # 3 skipped calls, 2 fires, then exhausted.
        assert fires == [False, False, False, True, True,
                         False, False, False]
        assert injector.fired('dispatch') == 2

    def test_seeded_probability_reproducible(self):
        a, b = FaultInjector(), FaultInjector()
        for injector in (a, b):
            injector.arm('tier_io', times=None, prob=0.5, seed=7)
        seq_a = [a.fire('tier_io') is not None for _ in range(32)]
        seq_b = [b.fire('tier_io') is not None for _ in range(32)]
        assert seq_a == seq_b
        assert any(seq_a) and not all(seq_a)

    def test_unknown_site_rejected(self):
        injector = FaultInjector()
        with pytest.raises(ValueError):
            injector.arm('no-such-site')
        injector.arm('dispatch')
        with pytest.raises(ValueError):
            injector.fire('no-such-site')

    def test_fail_raises_injected_fault(self):
        injector = FaultInjector()
        injector.arm('dispatch', times=1)
        with pytest.raises(InjectedFault) as err:
            injector.fail('dispatch')
        assert err.value.site == 'dispatch'
        injector.fail('dispatch')  # exhausted: no raise

    def test_fail_io_raises_oserror(self):
        injector = FaultInjector()
        injector.arm('tier_io', times=1)
        with pytest.raises(OSError):
            injector.fail_io('tier_io')

    def test_env_spec_parse(self):
        specs = parse_fault_spec(
            'dispatch:times=2:after=4, slow_window:delay_s=0.2,'
            'tier_io:prob=0.5:seed=7:times=inf'
        )
        assert specs[0] == {'site': 'dispatch', 'times': 2, 'after': 4}
        assert specs[1] == {'site': 'slow_window', 'delay_s': 0.2}
        assert specs[2]['times'] is None
        with pytest.raises(ValueError):
            parse_fault_spec('typo_site:times=1')
        with pytest.raises(ValueError):
            parse_fault_spec('dispatch:bogus_key=1')

    def test_fire_counts_metric_and_flight(self):
        injector = FaultInjector()
        injector.arm('dispatch', times=1)
        before = _metrics.RESILIENCE_FAULTS.labels(site='dispatch').value
        from distllm_tpu.observability.flight import get_flight_recorder

        total_before = get_flight_recorder().total_recorded
        assert injector.fire('dispatch') is not None
        assert (
            _metrics.RESILIENCE_FAULTS.labels(site='dispatch').value
            == before + 1
        )
        records = get_flight_recorder().snapshot()
        assert get_flight_recorder().total_recorded == total_before + 1
        assert records[-1]['kind'] == 'fault'
        assert records[-1]['site'] == 'dispatch'

    def test_sites_catalogued(self):
        # The metric pre-registration list and the site catalog must
        # agree (the FLIGHT_KINDS pattern).
        assert set(_metrics.FAULT_SITE_LABELS) == set(FAULT_SITES)


# ------------------------------------------------------- admission unit
class TestAdmissionPolicy:
    def _view(self, **kw):
        base = dict(
            waiting_tokens=0, pending_decode_tokens=0, num_waiting=0,
            num_running=0, max_num_seqs=4, decode_steps=4,
            prefill_s_per_token=0.01, window_s=0.1, slo_s=1.0,
        )
        base.update(kw)
        return EngineLoadView(**base)

    def test_monotonic_in_backlog(self):
        idle = predict_ttft(self._view(), prompt_tokens=10)
        queued = predict_ttft(
            self._view(waiting_tokens=500, num_waiting=5), prompt_tokens=10
        )
        saturated = predict_ttft(
            self._view(
                waiting_tokens=500, num_waiting=5, num_running=4,
                pending_decode_tokens=400,
            ),
            prompt_tokens=10,
        )
        assert idle < queued < saturated
        # The decode-drain term: one window serves max_num_seqs x
        # decode_steps tokens, so 400 pending tokens = 25 windows.
        drain_only = predict_ttft(
            self._view(pending_decode_tokens=400, prefill_s_per_token=0.0),
            prompt_tokens=0,
        )
        assert drain_only == pytest.approx(25 * 0.1)

    def test_shed_decision_thresholds(self):
        admit, predicted, retry = shed_decision(self._view(), 10)
        assert admit and retry == 0.0 and predicted > 0
        admit, predicted, retry = shed_decision(
            self._view(waiting_tokens=100_000), 10
        )
        assert not admit
        assert 1.0 <= retry <= 60.0
        # No SLO = no shedding, whatever the backlog.
        admit, _, _ = shed_decision(
            self._view(waiting_tokens=100_000, slo_s=0.0), 10
        )
        assert admit


# ------------------------------------------------------------ chaos matrix
def _tiny_engine(**cfg_kwargs):
    cfg = mistral.MistralConfig(
        vocab_size=64,
        hidden_size=32,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        intermediate_size=64,
        dtype='float32',
    )
    params = mistral.init(jax.random.PRNGKey(0), cfg)

    class IdTokenizer:
        eos_id = None

        def decode(self, ids):
            return ' '.join(str(i) for i in ids)

    engine_kw = dict(
        block_size=4,
        num_blocks=32,
        max_num_seqs=2,
        max_model_len=64,
        prefer_native_allocator=False,
    )
    engine_kw.update(cfg_kwargs)
    engine = LLMEngine(
        cfg, params, IdTokenizer(), EngineConfig(**engine_kw)
    )
    return cfg, params, engine


RECOVER = dict(max_dispatch_retries=3, retry_backoff_s=0.0)
GREEDY = SamplingParams(temperature=0.0, max_tokens=6)
PROMPTS = [[1, 2, 3, 4, 5], [9, 8, 7]]


def _clean_tokens():
    _, _, engine = _tiny_engine()
    return engine.generate_ids(PROMPTS, GREEDY)


class TestChaosMatrix:
    def test_dispatch_fault_recovers_bit_identical(self, _disarm_injector):
        clean = _clean_tokens()
        _disarm_injector.arm('dispatch', times=2)
        _, _, engine = _tiny_engine(**RECOVER)
        got = engine.generate_ids(PROMPTS, GREEDY)
        assert got == clean
        assert engine._stats['window_retries'] >= 2
        assert engine._stats['recoveries'] >= 1
        assert not engine._stats.get('quarantined_requests')

    def test_sched_exhausted_fault_recovers(self, _disarm_injector):
        clean = _clean_tokens()
        _disarm_injector.arm('sched_exhausted', times=2)
        _, _, engine = _tiny_engine(**RECOVER)
        got = engine.generate_ids(PROMPTS, GREEDY)
        assert got == clean
        assert engine._stats['window_retries'] >= 1

    def test_persistent_fault_quarantines_only_affected(
        self, _disarm_injector
    ):
        """A fault that outlives the retry budget fails the requests in
        the failing dispatches — with errors recorded — then later
        requests serve normally once the fault clears. Never a wedge."""
        clean = _clean_tokens()
        # Exactly enough fires to exhaust the first batch's retry budget
        # (both requests share the padded prefill dispatch, so each fire
        # charges both; the third consecutive failure quarantines), then
        # the injector runs dry and the engine heals.
        _disarm_injector.arm('dispatch', times=3)
        _, _, engine = _tiny_engine(max_dispatch_retries=2,
                                    retry_backoff_s=0.0)
        failed = engine.generate_ids(PROMPTS, GREEDY)
        assert failed == [[], []]  # affected requests failed, recorded
        assert engine._stats['quarantined_requests'] == 2
        # The loop is alive: fresh requests serve bit-identically.
        healed = engine.generate_ids(PROMPTS, GREEDY)
        assert healed == clean

    def test_quarantine_records_error_and_frees_blocks(
        self, _disarm_injector
    ):
        _disarm_injector.arm('dispatch', times=None)  # permanent
        _, _, engine = _tiny_engine(max_dispatch_retries=1,
                                    retry_backoff_s=0.0)
        rid = engine.add_request(list(PROMPTS[0]), GREEDY)
        while engine.has_unfinished:
            engine.step()
        _disarm_injector.disarm()
        request = engine._finished.pop(rid)
        assert request.state is RequestState.FAILED
        assert request.finish_reason == 'dispatch_failed'
        assert request.error
        # Every block is back: nothing leaked through quarantine.
        assert engine.sched.num_free_blocks == engine.config.num_blocks - 1
        assert engine.sched.num_running == 0

    def test_device_put_fault_degrades_to_cold_prefill(
        self, _disarm_injector, tmp_path
    ):
        """A failed promotion transfer must fall back to cold prefill —
        same tokens, tier error counted, no exception in admission."""
        pool = dict(num_blocks=12, max_num_seqs=2, max_model_len=48,
                    enable_prefix_cache=True)
        prompt_a = list(range(1, 25))
        prompt_b = list(range(30, 54))
        cfg, params, engine = _tiny_engine(
            host_kv_tier_bytes=64 << 20, **pool
        )
        _, _, ref = _tiny_engine(**pool)
        errors_before = _metrics.PREFIX_TIER_ERRORS.labels(
            tier='host'
        ).value
        _disarm_injector.arm('device_put', times=None)
        for prompt in (prompt_a, prompt_b, prompt_a):
            got = engine.generate_ids([prompt], GREEDY)[0]
            want = ref.generate_ids([prompt], GREEDY)[0]
            assert got == want
        _disarm_injector.disarm()
        # The second PROMPT_A arrival found tier entries, began a
        # promotion, hit the injected transfer fault, and re-prefilled.
        assert engine._stats.get('tier_promotion_failures', 0) >= 1
        assert (
            _metrics.PREFIX_TIER_ERRORS.labels(tier='host').value
            > errors_before
        )

    def test_tier_io_fault_degrades_to_miss(
        self, _disarm_injector, tmp_path
    ):
        """Injected disk-tier IO errors: spills and loads degrade to
        misses (counted), generation stays bit-exact, nothing raises
        into add_request."""
        pool = dict(num_blocks=12, max_num_seqs=2, max_model_len=48,
                    enable_prefix_cache=True)
        prompt_a = list(range(1, 25))
        prompt_b = list(range(30, 54))
        cfg, params, engine = _tiny_engine(
            host_kv_tier_bytes=2048,  # a couple of blocks: disk matters
            disk_kv_tier_dir=str(tmp_path / 'tier'),
            **pool,
        )
        _, _, ref = _tiny_engine(**pool)
        errors_before = _metrics.PREFIX_TIER_ERRORS.labels(
            tier='disk'
        ).value
        _disarm_injector.arm('tier_io', times=None)
        for prompt in (prompt_a, prompt_b, prompt_a, prompt_b):
            got = engine.generate_ids([prompt], GREEDY)[0]
            want = ref.generate_ids([prompt], GREEDY)[0]
            assert got == want
        _disarm_injector.disarm()
        assert (
            _metrics.PREFIX_TIER_ERRORS.labels(tier='disk').value
            > errors_before
        )

    def test_slow_window_deadline_times_out_and_frees(
        self, _disarm_injector
    ):
        """A stalled window loop: the per-request deadline fires, the
        request finishes with a timeout status, and its blocks free."""
        _disarm_injector.arm('slow_window', times=None, delay_s=0.06)
        _, _, engine = _tiny_engine(
            request_deadline_s=0.05, decode_steps=2, **RECOVER
        )
        outs = engine.generate_ids(
            [PROMPTS[0]], SamplingParams(temperature=0.0, max_tokens=40)
        )
        _disarm_injector.disarm()
        assert len(outs[0]) < 40  # timed out mid-generation
        assert engine._stats['quarantined_requests'] == 1
        assert engine.sched.num_free_blocks == engine.config.num_blocks - 1
        # A later request is unaffected (deadline is per-request).
        fresh = engine.generate_ids([PROMPTS[1]], GREEDY)[0]
        _, _, ref = _tiny_engine()
        assert fresh == ref.generate_ids([PROMPTS[1]], GREEDY)[0]

    def test_deadline_timeout_status_on_request(self, _disarm_injector):
        _disarm_injector.arm('slow_window', times=None, delay_s=0.06)
        _, _, engine = _tiny_engine(
            request_deadline_s=0.05, decode_steps=2, **RECOVER
        )
        rid = engine.add_request(
            list(PROMPTS[0]),
            SamplingParams(temperature=0.0, max_tokens=40),
        )
        while engine.has_unfinished:
            engine.step()
        _disarm_injector.disarm()
        request = engine._finished.pop(rid)
        assert request.state is RequestState.FAILED
        assert request.finish_reason == 'timeout'
        assert 'request_deadline_s' in (request.error or '')

    def test_prefill_fault_never_decodes_unwritten_kv(
        self, _disarm_injector
    ):
        """A failed prefill dispatch re-prefills on retry — the decode
        gate must hold, so recovered tokens match the clean run exactly
        (decoding over unwritten KV would corrupt them silently)."""
        clean = _clean_tokens()
        # after=0: the FIRST dispatch (admission prefill) faults.
        _disarm_injector.arm('dispatch', times=1, after=0)
        _, _, engine = _tiny_engine(**RECOVER)
        got = engine.generate_ids(PROMPTS, GREEDY)
        assert got == clean

    def test_recovery_off_preserves_legacy_raise(self, _disarm_injector):
        _disarm_injector.arm('dispatch', times=1)
        _, _, engine = _tiny_engine()  # max_dispatch_retries=0
        with pytest.raises(InjectedFault):
            engine.generate_ids(PROMPTS, GREEDY)


# ------------------------------------------------------------- overload
class TestOverloadShedding:
    def _run(self, engine, workload):
        from distllm_tpu.generate.loadgen import run_loadgen

        # Warm the serving shapes the workload uses (bucket-16 and
        # bucket-32 prefills + the decode window): compiles inside the
        # measured run would poison every TTFT, and the warm generates
        # also feed the shed arm's EWMA predictor measured rates.
        engine.generate_ids(
            [list(range(1, 9)), list(range(1, 33))],
            SamplingParams(temperature=0.0, max_tokens=2),
        )
        # The warm generates' durations INCLUDED the jit compiles, so
        # they poison the EWMA with rates off by orders of magnitude
        # (production engines warm via engine.warmup(), which bypasses
        # _record_step entirely); drop them so the predictor sees only
        # steady-state measurements.
        engine._ewma.clear()
        return run_loadgen(engine, workload)

    def _workload(self):
        from distllm_tpu.generate.loadgen import Arrival

        # Four paced arrivals the engine serves comfortably inside the
        # SLO, then a burst far beyond roofline-predicted capacity at
        # t=2.0 — on this 2-slot engine the burst's queue drain takes
        # many times the SLO, so a no-shedding baseline must miss for
        # most of it.
        paced = [
            Arrival(at_s=0.4 * i, prompt_ids=tuple(range(1, 9)),
                    max_tokens=4, session=None)
            for i in range(4)
        ]
        burst = [
            Arrival(at_s=2.0, prompt_ids=tuple(range(10 + i, 42 + i)),
                    max_tokens=12, session=None)
            for i in range(48)
        ]
        return paced + burst

    def test_shed_beats_no_shed_on_slo_attainment(self):
        workload = self._workload()
        slo = dict(ttft_slo_s=0.25, decode_steps=2)

        _, _, baseline = _tiny_engine(**slo)
        base = self._run(baseline, workload)
        assert base.shed_requests == 0
        base_total = base.slo_met + base.slo_missed
        base_attain = base.slo_met / base_total

        _, _, shedding = _tiny_engine(admission_control=True, **slo)
        shed = self._run(shedding, workload)
        assert shed.shed_requests > 0
        assert shed.shed_rate and 0 < shed.shed_rate < 1
        admitted_total = shed.slo_met + shed.slo_missed
        assert admitted_total == len(workload) - shed.shed_requests
        attain = shed.slo_met / admitted_total
        # The acceptance bar: admitted requests' SLO attainment stays
        # ABOVE the no-shedding baseline under the same offered load.
        assert attain > base_attain
        # Alignment contract: shed arrivals hold empty/None slots.
        assert len(shed.tokens_by_request) == len(workload)
        assert len(shed.ttft_by_request) == len(workload)

    def test_shed_records_carry_retry_after(self):
        workload = self._workload()
        # Tighter SLO than the attainment test: this test only cares
        # that every shed carries an honest Retry-After, so it forces a
        # decisive shed regime.
        _, _, engine = _tiny_engine(
            admission_control=True, ttft_slo_s=0.1, decode_steps=2
        )
        before = engine.flight.total_recorded
        report = self._run(engine, workload)
        assert report.shed_requests > 0
        records = engine.flight.snapshot()
        grew = engine.flight.total_recorded - before
        sheds = [
            r for r in records[-grew:] if r.get('kind') == 'shed'
        ]
        assert len(sheds) == report.shed_requests
        assert all(r['retry_after_s'] >= 1.0 for r in sheds)
        assert all(r['reason'] == 'overload' for r in sheds)

    def test_engine_overloaded_carries_honest_retry_after(self):
        _, _, engine = _tiny_engine(
            admission_control=True, ttft_slo_s=1e-9
        )
        with pytest.raises(EngineOverloaded) as err:
            engine.add_request(list(range(1, 30)))
        assert err.value.retry_after_s >= 1.0
        assert err.value.predicted_ttft_s > 0
        # Nothing was enqueued for the shed arrival.
        assert engine.sched.num_waiting == 0
        assert not engine._requests

    def test_admission_control_requires_slo(self):
        with pytest.raises(Exception):
            EngineConfig(admission_control=True)


# ------------------------------------------------------- chaos via loadgen
def test_loadgen_chaos_smoke(_disarm_injector):
    """The gen_chaos stage's core loop at unit scale: faults firing mid
    open-loop run, nonzero goodput, recovery, fault-off token identity."""
    from distllm_tpu.generate.loadgen import (
        LoadgenConfig,
        build_workload,
        run_loadgen,
    )

    load_cfg = LoadgenConfig(
        seed=0, num_requests=10, rate_rps=40.0, num_sessions=2,
        warm_fraction=0.5, prefix_tokens=8, prompt_tokens=(4, 10),
        output_tokens=(3, 6), vocab_size=64,
    )
    workload = build_workload(load_cfg)
    engine_kw = dict(
        enable_prefix_cache=True, ttft_slo_s=5.0, decode_steps=2, **RECOVER
    )
    _, _, engine = _tiny_engine(**engine_kw)
    clean = run_loadgen(engine, workload)

    _disarm_injector.arm('dispatch', times=2, after=2)
    _disarm_injector.arm('slow_window', times=1, delay_s=0.01)
    _, _, chaos_engine = _tiny_engine(**engine_kw)
    chaos = run_loadgen(chaos_engine, workload)
    _disarm_injector.disarm()

    assert chaos.tokens_by_request == clean.tokens_by_request
    assert chaos.goodput_tokens > 0
    assert chaos.window_retries >= 1
    assert chaos.recoveries >= 1
    assert chaos.quarantined == 0 and chaos.failed_requests == 0


def test_gen_chaos_stage_cpu_smoke(tmp_path):
    """Acceptance smoke: the gen_chaos bench stage completes on CPU with
    nonzero goodput while faults are firing, every armed fault fired, at
    least one recovery, no quarantines, and chaos/clean token identity
    (greedy fp32). Run directly: ``JAX_PLATFORMS=cpu
    DISTLLM_BENCH_SMALL=1 python bench.py --stage gen_chaos``."""
    import json
    import os
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parent.parent
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS='cpu',
        DISTLLM_BENCH_SMALL='1',
        DISTLLM_BENCH_RECORD_DIR=str(tmp_path),
        DISTLLM_BENCH_BUNDLE_DIR=str(tmp_path / 'bundles'),
        DISTLLM_BENCH_WATCHDOG_S='0',
    )
    env.pop('DISTLLM_FAULTS', None)  # the stage arms its own schedule
    proc = subprocess.run(
        [sys.executable, str(repo / 'bench.py'), '--stage', 'gen_chaos'],
        capture_output=True, text=True, timeout=420, cwd=repo, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    fragment = json.loads(proc.stdout.strip().splitlines()[-1])
    assert 'gen_chaos_error' not in fragment, fragment.get('gen_chaos_error')
    assert fragment['gen_chaos_tokens_identical'] is True
    assert fragment['gen_chaos_goodput_tokens'] > 0
    assert fragment['gen_chaos_faults_injected'] >= 3
    assert fragment['gen_chaos_recoveries'] >= 1
    assert fragment['gen_chaos_quarantined'] == 0
    assert fragment['gen_chaos_shed_requests'] > 0  # overload arm shed
    assert 0 < fragment['gen_chaos_shed_rate'] <= 1
