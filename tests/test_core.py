"""Core layer tests: config IO, batching, timers, warmstart registry."""

from typing import Literal

import pytest

from distllm_tpu import __version__
from distllm_tpu.registry import WarmstartRegistry, register, registry
from distllm_tpu.timer import TimeLogger, Timer
from distllm_tpu.utils import BaseConfig, batch_data, expo_backoff_retry


def test_version():
    assert __version__


class _DemoSub(BaseConfig):
    name: Literal['demo'] = 'demo'
    width: int = 4


class _DemoConfig(BaseConfig):
    title: str
    sub: _DemoSub = _DemoSub()


def test_config_yaml_roundtrip(tmp_path):
    cfg = _DemoConfig(title='hello', sub=_DemoSub(width=7))
    path = tmp_path / 'cfg.yaml'
    cfg.write_yaml(path)
    loaded = _DemoConfig.from_yaml(path)
    assert loaded == cfg


def test_config_json_roundtrip(tmp_path):
    cfg = _DemoConfig(title='x')
    path = tmp_path / 'cfg.json'
    cfg.write_json(path)
    assert _DemoConfig.from_json(path) == cfg


def test_config_env_substitution(tmp_path, monkeypatch):
    monkeypatch.setenv('DISTLLM_TEST_TITLE', 'from-env')
    path = tmp_path / 'cfg.yaml'
    path.write_text('title: ${env:DISTLLM_TEST_TITLE}\n')
    assert _DemoConfig.from_yaml(path).title == 'from-env'


def test_config_rejects_unknown_fields():
    with pytest.raises(Exception):
        _DemoConfig(title='x', bogus=1)


def test_batch_data():
    assert batch_data([1, 2, 3, 4, 5], 2) == [[1, 2], [3, 4], [5]]
    assert batch_data([], 3) == []
    assert batch_data([1], 10) == [[1]]
    with pytest.raises(ValueError):
        batch_data([1], 0)


def test_timer_roundtrip(capsys):
    with Timer('stage-a', 'file-1'):
        pass
    with Timer('stage-a', 'file-2'):
        pass
    with Timer('stage-b'):
        pass
    out = capsys.readouterr().out
    stats = TimeLogger().parse_lines(out)
    assert stats[('stage-a', 'file-1')].count == 1
    assert stats[('stage-b',)].count == 1
    assert stats[('stage-b',)].total_s >= 0


def test_timer_logfile(tmp_path, capsys):
    with Timer('x'):
        pass
    log = tmp_path / 'log.txt'
    log.write_text(capsys.readouterr().out)
    stats = TimeLogger().parse_logs(log)
    assert ('x',) in stats


class _Expensive:
    built = 0

    def __init__(self, size):
        self.size = size
        _Expensive.built += 1
        self.dead = False

    def shutdown(self):
        self.dead = True


def test_registry_warmstart():
    reg = WarmstartRegistry()
    a = reg.get(_Expensive, size=1)
    b = reg.get(_Expensive, size=1)
    assert a is b  # cache hit, no rebuild
    c = reg.get(_Expensive, size=2)
    assert c is not a
    assert a.dead  # old instance shut down on swap


def test_registry_slots():
    reg = WarmstartRegistry(max_slots=2)
    a = reg.get(_Expensive, slot='encoder', size=1)
    g = reg.get(_Expensive, slot='generator', size=9)
    assert reg.get(_Expensive, slot='encoder', size=1) is a
    assert reg.get(_Expensive, slot='generator', size=9) is g


def test_register_decorator():
    calls = []

    @register(slot='test-deco')
    def make(value: int):
        calls.append(value)
        return {'value': value}

    r1 = make(value=5)
    r2 = make(value=5)
    assert r1 is r2
    assert calls == [5]
    registry().clear()


def test_expo_backoff_retry():
    attempts = []

    def flaky():
        attempts.append(1)
        if len(attempts) < 3:
            raise RuntimeError('boom')
        return 'ok'

    assert expo_backoff_retry(flaky, sleep=lambda _: None) == 'ok'
    assert len(attempts) == 3

    class AuthError(Exception):
        pass

    def fatal():
        raise AuthError('no')

    with pytest.raises(AuthError):
        expo_backoff_retry(
            fatal, give_up_on=(AuthError,), sleep=lambda _: None
        )


class TestInstantiate:
    """``_target_`` class dispatch (reference ``chat_argoproxy.py:511-549``)."""

    def test_target_dispatch(self):
        from distllm_tpu.utils import instantiate

        obj = instantiate(
            {'_target_': 'pathlib.PurePosixPath', 'args': None}
            | {'_target_': 'collections.Counter'},
            _allow_=('collections.',),
        )
        import collections

        assert isinstance(obj, collections.Counter)

    def test_target_outside_allowlist_rejected(self):
        # Unrestricted import+call would let any loaded YAML execute
        # arbitrary code; default allowlist is distllm_tpu.* only.
        from distllm_tpu.utils import instantiate

        with pytest.raises(ValueError, match='allowed prefixes'):
            instantiate({'_target_': 'os.system', 'command': 'true'})

    def test_target_within_package_allowed_by_default(self):
        from distllm_tpu.utils import instantiate

        timer = instantiate({'_target_': 'distllm_tpu.timer.Timer'})
        from distllm_tpu.timer import Timer

        assert isinstance(timer, Timer)

    def test_nested_and_env(self, monkeypatch):
        from distllm_tpu.utils import instantiate

        monkeypatch.setenv('VFY_NAME', 'hello')
        out = instantiate(
            {
                'inner': {'_target_': 'fractions.Fraction', 'numerator': 3},
                'plain': '${env:VFY_NAME}',
            },
            _allow_=('fractions.',),
        )
        import fractions

        assert out['inner'] == fractions.Fraction(3)
        assert out['plain'] == 'hello'

    def test_bad_target_raises(self):
        from distllm_tpu.utils import instantiate

        with pytest.raises(ValueError, match='dotted path'):
            instantiate({'_target_': 'NoDots'})

    def test_passthrough(self):
        from distllm_tpu.utils import instantiate

        assert instantiate({'a': [1, 2]}) == {'a': [1, 2]}


def test_apply_platform_env_honors_env(monkeypatch):
    """apply_platform_env re-applies JAX_PLATFORMS through the config API
    (the pinned-platform image's sitecustomize beats the bare env var)."""
    import jax

    from distllm_tpu.utils import apply_platform_env

    before = jax.config.jax_platforms
    try:
        monkeypatch.setenv('JAX_PLATFORMS', 'cpu')
        apply_platform_env()
        assert jax.config.jax_platforms == 'cpu'
        # Unset env leaves the config untouched.
        monkeypatch.delenv('JAX_PLATFORMS')
        jax.config.update('jax_platforms', 'cpu')
        apply_platform_env()
        assert jax.config.jax_platforms == 'cpu'
    finally:
        jax.config.update('jax_platforms', before)


def test_canonical_function_rebinds_main():
    from distllm_tpu.utils import batch_data, canonical_function

    # Functions already owned by an importable module pass through.
    assert canonical_function(batch_data, 'distllm_tpu.utils') is batch_data

    # A __main__-defined function (driver run via `python -m`) is re-resolved
    # from its canonical module so fabric workers can unpickle it.
    import types

    fake_main = types.FunctionType(
        batch_data.__code__, batch_data.__globals__, 'batch_data'
    )
    fake_main.__module__ = '__main__'
    assert (
        canonical_function(fake_main, 'distllm_tpu.utils') is batch_data
    )
