"""Native provider wires of the API generator (openai/anthropic/google).

Reference parity: ``distllm/generate/generators/langchain_backend.py:50-103``
selects an LLM class per model name (gpt → OpenAI, gemini-pro → Google,
claude-3-opus → Anthropic); here each wire is spoken natively and selection
follows the same model-name convention.
"""


import pytest

from distllm_tpu.generate.generators.api_backend import (
    ApiGenerator,
    ApiGeneratorConfig,
)


class _Resp:
    def __init__(self, payload):
        self.payload = payload
        self.status_code = 200

    def raise_for_status(self):
        pass

    def json(self):
        return self.payload


@pytest.fixture
def capture(monkeypatch):
    calls = []

    def fake_post(url, json=None, headers=None, timeout=None):
        calls.append({'url': url, 'body': json, 'headers': headers})
        return _Resp(fake_post.payload)

    import requests

    monkeypatch.setattr(requests, 'post', fake_post)
    fake_post.calls = calls
    return fake_post


def test_auto_provider_inference():
    assert ApiGeneratorConfig(model='gpt-4').resolved_provider() == 'openai'
    assert (
        ApiGeneratorConfig(model='claude-3-opus').resolved_provider()
        == 'anthropic'
    )
    assert (
        ApiGeneratorConfig(model='gemini-pro').resolved_provider() == 'google'
    )
    # Explicit provider beats the name heuristic (proxies rename models).
    assert (
        ApiGeneratorConfig(
            model='claude-3-opus', provider='openai'
        ).resolved_provider()
        == 'openai'
    )


def test_openai_wire(capture):
    capture.payload = {
        'choices': [{'message': {'content': 'hello'}}]
    }
    gen = ApiGenerator(
        ApiGeneratorConfig(model='gpt-4', api_key='sk-test', max_tries=1)
    )
    assert gen.generate('hi') == ['hello']
    call = capture.calls[0]
    assert call['url'].endswith('/chat/completions')
    assert call['headers']['Authorization'] == 'Bearer sk-test'
    assert call['body']['messages'] == [{'role': 'user', 'content': 'hi'}]


def test_anthropic_wire(capture):
    capture.payload = {
        'content': [{'type': 'text', 'text': 'from claude'}]
    }
    gen = ApiGenerator(
        ApiGeneratorConfig(
            model='claude-3-opus', api_key='ak-test', max_tries=1,
            max_tokens=77,
        )
    )
    assert gen.generate(['q']) == ['from claude']
    call = capture.calls[0]
    assert call['url'].endswith('/v1/messages')
    assert call['headers']['x-api-key'] == 'ak-test'
    assert 'anthropic-version' in call['headers']
    assert call['body']['max_tokens'] == 77
    assert call['body']['messages'] == [{'role': 'user', 'content': 'q'}]


def test_google_wire(capture):
    capture.payload = {
        'candidates': [
            {'content': {'parts': [{'text': 'from gemini'}]}}
        ]
    }
    gen = ApiGenerator(
        ApiGeneratorConfig(
            model='gemini-pro', api_key='gk-test', max_tries=1,
            temperature=0.5,
        )
    )
    assert gen.generate(['q']) == ['from gemini']
    call = capture.calls[0]
    assert ':generateContent' in call['url']
    assert call['headers']['x-goog-api-key'] == 'gk-test'
    assert call['body']['contents'] == [{'parts': [{'text': 'q'}]}]
    assert call['body']['generationConfig']['temperature'] == 0.5


def test_provider_key_env_defaults(monkeypatch, capture):
    capture.payload = {'content': [{'type': 'text', 'text': 'ok'}]}
    monkeypatch.setenv('ANTHROPIC_API_KEY', 'env-key')
    gen = ApiGenerator(
        ApiGeneratorConfig(model='claude-3-haiku', max_tries=1)
    )
    gen.generate('x')
    assert capture.calls[0]['headers']['x-api-key'] == 'env-key'


def test_multi_part_anthropic_response(capture):
    capture.payload = {
        'content': [
            {'type': 'text', 'text': 'a'},
            {'type': 'tool_use', 'id': 't'},
            {'type': 'text', 'text': 'b'},
        ]
    }
    gen = ApiGenerator(
        ApiGeneratorConfig(model='claude-3-opus', max_tries=1)
    )
    assert gen.generate('x') == ['ab']


def test_google_key_in_header_not_url(capture):
    capture.payload = {
        'candidates': [{'content': {'parts': [{'text': 'ok'}]}}]
    }
    gen = ApiGenerator(
        ApiGeneratorConfig(model='gemini-pro', api_key='gk-secret',
                           max_tries=1)
    )
    gen.generate('x')
    call = capture.calls[0]
    assert 'gk-secret' not in call['url']
    assert call['headers']['x-goog-api-key'] == 'gk-secret'


def test_google_safety_block_no_retry(capture):
    from distllm_tpu.generate.generators.api_backend import ApiResponseError

    capture.payload = {'candidates': [{'finishReason': 'SAFETY'}]}
    gen = ApiGenerator(
        ApiGeneratorConfig(model='gemini-pro', max_tries=5)
    )
    with pytest.raises(ApiResponseError, match='SAFETY'):
        gen.generate('x')
    assert len(capture.calls) == 1  # deterministic block: no re-billing


def test_google_extra_generation_config_merges(capture):
    capture.payload = {
        'candidates': [{'content': {'parts': [{'text': 'ok'}]}}]
    }
    gen = ApiGenerator(
        ApiGeneratorConfig(
            model='gemini-pro', max_tries=1,
            extra_body={'generationConfig': {'topP': 0.9},
                        'safetySettings': [{'category': 'X'}]},
        )
    )
    gen.generate('x')
    body = capture.calls[0]['body']
    assert body['generationConfig']['topP'] == 0.9
    assert body['generationConfig']['maxOutputTokens'] == 512
    assert body['safetySettings'] == [{'category': 'X'}]


def test_auto_prefers_openai_when_base_set():
    # A claude* model pointed at an OpenAI-compatible proxy must use the
    # configured base with the openai wire, not reroute to api.anthropic.com.
    cfg = ApiGeneratorConfig(
        model='claude-3-opus', openai_api_base='http://proxy:8000/v1'
    )
    assert cfg.resolved_provider() == 'openai'
    # Without an explicit base, the name heuristic still applies.
    assert (
        ApiGeneratorConfig(model='claude-3-opus').resolved_provider()
        == 'anthropic'
    )


def test_malformed_payload_not_retried(capture):
    # A 200 carrying a proxy error body is deterministic: ApiResponseError
    # (in give_up_on), never a KeyError re-billed by the retry loop.
    from distllm_tpu.generate.generators.api_backend import ApiResponseError

    capture.payload = {'error': {'message': 'upstream exploded'}}
    for model in ('gpt-4', 'claude-3-opus'):
        gen = ApiGenerator(
            ApiGeneratorConfig(model=model, api_key='k', max_tries=3)
        )
        with pytest.raises(ApiResponseError):
            gen.generate('hi')
    # max_tries=3 but each model made exactly ONE request (no retries).
    assert len(capture.calls) == 2


def test_non_dict_and_string_block_payloads(capture):
    # Proxy bodies that are legal JSON but the wrong shape entirely: a
    # string content block (AttributeError path) and a bare list body.
    from distllm_tpu.generate.generators.api_backend import ApiResponseError

    capture.payload = {'content': 'upstream error text'}
    gen = ApiGenerator(
        ApiGeneratorConfig(model='claude-3-opus', api_key='k', max_tries=3)
    )
    with pytest.raises(ApiResponseError):
        gen.generate('hi')

    capture.payload = [{'error': 'x'}, {'error': 'y'}]
    gen = ApiGenerator(
        ApiGeneratorConfig(model='gpt-4', api_key='k', max_tries=3)
    )
    with pytest.raises(ApiResponseError):
        gen.generate('hi')
    assert len(capture.calls) == 2  # one request each, no re-billing


def test_auto_provider_survives_yaml_roundtrip(tmp_path):
    # write_yaml re-passes every default as an explicit kwarg on reload;
    # the proxy-base heuristic must compare values, not model_fields_set,
    # or a round trip silently flips claude* routing to the openai wire.
    cfg = ApiGeneratorConfig(model='claude-3-opus')
    assert cfg.resolved_provider() == 'anthropic'
    path = tmp_path / 'cfg.yaml'
    cfg.write_yaml(path)
    assert (
        ApiGeneratorConfig.from_yaml(path).resolved_provider() == 'anthropic'
    )
