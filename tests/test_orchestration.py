"""Orchestration tests: executors, ZMQ fabric, distributed embedding driver."""

import json
import threading
import time

import numpy as np
import pytest

from distllm_tpu.parallel.launcher import (
    LocalConfig,
    PodConfig,
    WorkstationConfig,
    get_compute_config,
)


def test_get_compute_config():
    assert isinstance(get_compute_config({'name': 'local'}), LocalConfig)
    assert isinstance(
        get_compute_config({'name': 'workstation', 'max_workers': 2}),
        WorkstationConfig,
    )
    assert isinstance(get_compute_config({'name': 'pod'}), PodConfig)
    with pytest.raises(ValueError):
        get_compute_config({'name': 'slurm'})


def test_serial_executor(tmp_path):
    ex = LocalConfig().get_executor(tmp_path / 'run')
    assert ex.map(lambda x: x * 2, [1, 2, 3]) == [2, 4, 6]


def _square(x):
    return x * x


def test_process_pool_executor(tmp_path):
    ex = WorkstationConfig(max_workers=2).get_executor(tmp_path / 'run')
    assert ex.map(_square, [1, 2, 3, 4]) == [1, 4, 9, 16]


def _work(x):
    if x == 'boom':
        raise ValueError('boom')
    return f'done-{x}'


def test_zmq_fabric_roundtrip():
    zmq = pytest.importorskip('zmq')
    from distllm_tpu.parallel.fabric import (
        Coordinator,
        FabricWorker,
        ZmqPoolExecutor,
    )

    coordinator = Coordinator(bind='tcp://*:0', retries=0)
    workers = [FabricWorker(coordinator.endpoint) for _ in range(2)]
    threads = [threading.Thread(target=w.run, daemon=True) for w in workers]
    for t in threads:
        t.start()
    try:
        results = ZmqPoolExecutor(coordinator).map(_work, ['a', 'b', 'c', 'd'])
        assert results == ['done-a', 'done-b', 'done-c', 'done-d']
    finally:
        for w in workers:
            w.stop()
        coordinator.close()


def test_zmq_fabric_propagates_errors():
    zmq = pytest.importorskip('zmq')
    from distllm_tpu.parallel.fabric import (
        Coordinator,
        FabricWorker,
        ZmqPoolExecutor,
    )

    coordinator = Coordinator(bind='tcp://*:0', retries=0)
    worker = FabricWorker(coordinator.endpoint)
    thread = threading.Thread(target=worker.run, daemon=True)
    thread.start()
    try:
        with pytest.raises(RuntimeError, match='boom'):
            ZmqPoolExecutor(coordinator).map(_work, ['a', 'boom'])
    finally:
        worker.stop()
        coordinator.close()


def test_idle_worker_heartbeats_survive_starved_heartbeat_thread():
    """The idle poll loop holds the (unfair) socket lock nearly 100% of
    the time, so the heartbeat thread can starve — heartbeats must come
    from the poll loop itself during the idle phase. Simulated worst
    case: the heartbeat thread never sends at all."""
    zmq = pytest.importorskip('zmq')
    from distllm_tpu.parallel import fabric

    router = zmq.Context.instance().socket(zmq.ROUTER)
    port = router.bind_to_random_port('tcp://127.0.0.1')
    worker = fabric.FabricWorker(
        f'tcp://127.0.0.1:{port}', heartbeat_interval=0.1
    )
    worker._heartbeat_loop = lambda: None
    thread = threading.Thread(target=worker.run, daemon=True)
    thread.start()
    try:
        heartbeats = 0
        deadline = time.monotonic() + 10
        while heartbeats < 2 and time.monotonic() < deadline:
            if router.poll(timeout=200):
                frames = router.recv_multipart()
                heartbeats += frames[-1] == fabric._HEARTBEAT
        assert heartbeats >= 2, 'idle worker sent no heartbeats'
    finally:
        worker.stop()
        thread.join(timeout=5)
        router.close(linger=0)


def _slow_task(x):
    import time

    time.sleep(3)
    return x + 1


def test_zmq_fabric_survives_long_tasks():
    """Task duration >> heartbeat threshold must not livelock (worker
    heartbeats from a background thread during execution)."""
    zmq = pytest.importorskip('zmq')
    from distllm_tpu.parallel.fabric import (
        Coordinator,
        FabricWorker,
        ZmqPoolExecutor,
    )

    coordinator = Coordinator(bind='tcp://*:0', retries=0, heartbeat_threshold=1.0)
    worker = FabricWorker(coordinator.endpoint, heartbeat_interval=0.2)
    thread = threading.Thread(target=worker.run, daemon=True)
    thread.start()
    try:
        results = ZmqPoolExecutor(coordinator).map(_slow_task, [1])
        assert results == [2]
    finally:
        worker.stop()
        coordinator.close()


def test_distributed_embedding_end_to_end(tmp_path):
    """Full driver: YAML config -> glob -> worker -> shards -> merge."""
    from datasets import load_from_disk

    from distllm_tpu.distributed_embedding import main
    from distllm_tpu.embed import get_writer

    input_dir = tmp_path / 'in'
    input_dir.mkdir()
    for i in range(3):
        with open(input_dir / f'part{i}.jsonl', 'w') as fh:
            for j in range(4):
                fh.write(
                    json.dumps(
                        {'text': f'document {i} chunk {j} words here', 'path': f'doc{i}'}
                    )
                    + '\n'
                )

    config = {
        'input_dir': str(input_dir),
        'output_dir': str(tmp_path / 'out'),
        'glob_patterns': ['*.jsonl'],
        'dataset_config': {'name': 'jsonl', 'batch_size': 2},
        'encoder_config': {'name': 'fake', 'embedding_size': 16},
        'pooler_config': {'name': 'mean'},
        'embedder_config': {'name': 'full_sequence'},
        'writer_config': {'name': 'huggingface'},
        'compute_config': {'name': 'local'},
    }
    import yaml

    config_path = tmp_path / 'config.yaml'
    config_path.write_text(yaml.safe_dump(config))
    assert main(['--config', str(config_path)]) == 0

    shard_dirs = sorted((tmp_path / 'out' / 'embeddings').iterdir())
    assert len(shard_dirs) == 3
    # audit copy exists
    assert (tmp_path / 'out' / 'config.yaml').exists()
    # merge step (the reduce)
    writer = get_writer({'name': 'huggingface'})
    writer.merge(shard_dirs, tmp_path / 'merged')
    merged = load_from_disk(str(tmp_path / 'merged'))
    assert len(merged) == 12
    assert np.asarray(merged['embeddings']).shape == (12, 16)
    from distllm_tpu.registry import registry

    registry().clear()


def test_cli_embed_and_merge(tmp_path, capsys):
    from distllm_tpu.cli import main as cli_main

    input_dir = tmp_path / 'in'
    input_dir.mkdir()
    with open(input_dir / 'a.jsonl', 'w') as fh:
        fh.write(json.dumps({'text': 'alpha beta gamma', 'path': 'p'}) + '\n')

    rc = cli_main(
        [
            'embed',
            '--input_dir', str(input_dir),
            '--output_dir', str(tmp_path / 'out'),
            '--glob_patterns', '*.jsonl',
            '--encoder_name', 'fake',
            '--dataset_name', 'jsonl',
            '--pooler_name', 'mean',
            '--writer_name', 'numpy',
        ]
    )
    assert rc == 0
    shards = list((tmp_path / 'out' / 'embeddings').iterdir())
    assert len(shards) == 1
    rc = cli_main(
        [
            'merge',
            '--dataset_dir', str(tmp_path / 'out' / 'embeddings'),
            '--output_dir', str(tmp_path / 'merged'),
            '--writer_name', 'numpy',
        ]
    )
    assert rc == 0
    assert (tmp_path / 'merged' / 'embeddings.npy').exists()
    from distllm_tpu.registry import registry

    registry().clear()


def test_cli_chunk_fasta(tmp_path):
    from distllm_tpu.cli import main as cli_main

    fasta = tmp_path / 'seqs.fasta'
    fasta.write_text(''.join(f'>s{i}\nACGT\n' for i in range(10)))
    rc = cli_main(
        [
            'chunk_fasta_file',
            '--fasta_file', str(fasta),
            '--output_dir', str(tmp_path / 'chunks'),
            '--num_chunks', '3',
        ]
    )
    assert rc == 0
    chunks = sorted((tmp_path / 'chunks').glob('*.fasta'))
    assert len(chunks) == 3
    from distllm_tpu.embed.datasets.fasta import read_fasta

    total = sum(len(read_fasta(c)) for c in chunks)
    assert total == 10


def test_fabric_worker_idle_self_destruct():
    """A worker that never hears from a coordinator (straggler host booting
    after the driver exited) exits on its own — it cannot rely on SIGTERM
    once it joined the global JAX runtime (preemption notifier)."""
    pytest.importorskip('zmq')
    from distllm_tpu.parallel.fabric import FabricWorker

    # Endpoint nobody listens on.
    worker = FabricWorker(
        'tcp://127.0.0.1:1', heartbeat_interval=0.2, idle_timeout=1.5
    )
    thread = threading.Thread(target=worker.run, daemon=True)
    start = time.monotonic()
    thread.start()
    thread.join(timeout=15)
    assert not thread.is_alive(), 'worker did not self-destruct'
    assert time.monotonic() - start >= 1.5


def test_fabric_poison_pill_and_heartbeat_acks():
    """Graceful shutdown ends worker loops without signals, and coordinator
    heartbeat acks keep a live worker's idle clock fresh while it waits."""
    pytest.importorskip('zmq')
    from distllm_tpu.parallel.fabric import (
        Coordinator,
        FabricWorker,
        ZmqPoolExecutor,
    )

    coordinator = Coordinator(bind='tcp://*:0', retries=0)
    # idle_timeout shorter than the run: only the coordinator's HB acks
    # (sent while pumping) keep the worker alive until the pill arrives.
    worker = FabricWorker(
        coordinator.endpoint, heartbeat_interval=0.2, idle_timeout=2.0
    )
    thread = threading.Thread(target=worker.run, daemon=True)
    thread.start()
    try:
        executor = ZmqPoolExecutor(coordinator)
        assert executor.map(_work, ['x']) == ['done-x']
        executor.shutdown()
        thread.join(timeout=10)
        assert not thread.is_alive(), 'poison pill did not stop the worker'
    finally:
        worker.stop()
        coordinator.close()
