"""Generate pipeline tests: readers, prompts, writers, distributed driver."""

import json

import pytest

from distllm_tpu.generate import (
    get_generator,
    get_prompt_template,
    get_reader,
    get_writer,
)


# ---------------------------------------------------------------- readers
def test_jsonl_reader(tmp_path):
    f = tmp_path / 'in.jsonl'
    f.write_text(
        json.dumps({'text': 'hello', 'path': 'p1'})
        + '\n'
        + json.dumps({'text': 'world'})
        + '\n'
    )
    texts, paths = get_reader({'name': 'jsonl'}).read(f)
    assert texts == ['hello', 'world']
    assert paths == ['p1', str(f)]


def test_huggingface_reader(tmp_path):
    from datasets import Dataset

    Dataset.from_dict({'text': ['a', 'b'], 'path': ['x', 'y']}).save_to_disk(
        str(tmp_path / 'ds')
    )
    texts, paths = get_reader({'name': 'huggingface'}).read(tmp_path / 'ds')
    assert texts == ['a', 'b']
    assert paths == ['x', 'y']


def test_amp_json_reader(tmp_path):
    f = tmp_path / 'amp.json'
    f.write_text(
        json.dumps(
            {
                'groupA': [{'Protein_Name': 'P1', 'Function': 'binds stuff'}],
                'groupB': [{'Protein_Name': 'P2', 'Function': 'cuts stuff'}],
            }
        )
    )
    texts, paths = get_reader({'name': 'amp_json'}).read(f)
    assert len(texts) == 2
    assert texts == paths
    assert json.loads(texts[0])['Protein_Name'] == 'P1'


# ---------------------------------------------------------------- prompts
def test_identity_prompt():
    pt = get_prompt_template({'name': 'identity'})
    assert pt.preprocess('abc') == ['abc']
    assert pt.postprocess(['x']) == ['x']


def test_question_chunk_prompt():
    pt = get_prompt_template({'name': 'question_chunk'})
    prompts = pt.preprocess(['some science text'])
    assert 'some science text' in prompts[0]
    out = pt.postprocess(
        ['Here is context. What drives protein folding? Another statement.']
    )
    assert out == ['What drives protein folding?']
    assert pt.postprocess(['No questions here.']) == ['']


def test_question_answer_prompt():
    pt = get_prompt_template({'name': 'question_answer'})
    with_ctx = pt.preprocess(
        ['Which is true?'], contexts=[['ctx one']], scores=[[0.9]]
    )
    assert 'Context (with relevance scores)' in with_ctx[0]
    assert 'score: 0.9' in with_ctx[0]
    no_ctx = pt.preprocess(['Which is true?'])
    assert 'Context' not in no_ctx[0]
    assert pt.postprocess(['2. The Answer.']) == ['the answer']
    assert pt.postprocess(['Plain']) == ['plain']


def test_keyword_selection_prompt(tmp_path):
    kw = tmp_path / 'kw.txt'
    kw.write_text('radiation\ndosimetry\nbiology\n')
    pt = get_prompt_template({'name': 'keyword_selection', 'keywords': kw})
    prompts = pt.preprocess(['a paragraph'])
    assert 'dosimetry' in prompts[0]
    pt2 = get_prompt_template(
        {'name': 'keyword_selection', 'keywords': ['a', 'b']}
    )
    assert pt2.keywords_list == ['a', 'b']


def test_amp_question_prompt_roundtrip():
    pt = get_prompt_template({'name': 'amp_question'})
    entry = json.dumps({'Protein_Name': 'LL-37', 'Function': 'antimicrobial'})
    prompts = pt.preprocess([entry])
    assert 'LL-37' in prompts[0]
    response = (
        'Sure!\nQuestion: What does LL-37 do? '
        'A) Kills microbes B) Stores iron C) Binds DNA D) Nothing '
        'Answer: A) Kills microbes'
    )
    parsed = json.loads(pt.postprocess([response])[0])
    assert parsed['correct_answer'] == 'Kills microbes'
    assert len(parsed['distractors']) == 3
    assert 'What does LL-37 do?' in parsed['full_question_text']
    # Unparseable response -> null fields
    bad = json.loads(pt.postprocess(['gibberish'])[0])
    assert bad['correct_answer'] is None


# -------------------------------------------------------------- generators
def test_fake_generator():
    gen = get_generator({'name': 'fake'})
    out = gen.generate(['one', 'two'])
    assert out == ['response to: one', 'response to: two']


def test_tpu_generator_config_xor():
    from distllm_tpu.generate.generators.tpu_backend import TpuGeneratorConfig

    with pytest.raises(ValueError, match='top_p or min_p'):
        TpuGeneratorConfig(
            pretrained_model_name_or_path='/x', top_p=0.9, min_p=0.1
        )
    cfg = TpuGeneratorConfig(pretrained_model_name_or_path='/x', name='vllm')
    assert cfg.min_p == 0.1


def test_unknown_generator():
    with pytest.raises(ValueError, match='Unknown generator'):
        get_generator({'name': 'bogus'})


# ---------------------------------------------------------------- writers
def test_hf_generate_writer_and_merge(tmp_path):
    from datasets import load_from_disk

    writer = get_writer({'name': 'huggingface'})
    writer.write(tmp_path / 's1', ['p1'], ['t1'], ['r1'])
    writer.write(tmp_path / 's2', ['p2'], ['t2'], ['r2'])
    writer.merge(
        [tmp_path / 's1', tmp_path / 's2', tmp_path / 'gone'], tmp_path / 'm'
    )
    ds = load_from_disk(str(tmp_path / 'm'))
    assert sorted(ds['response']) == ['r1', 'r2']


def test_amp_jsonl_writer(tmp_path):
    writer = get_writer({'name': 'amp_jsonl'})
    entry = json.dumps({'Protein_Name': 'P1', 'Function': 'x'})
    response = json.dumps({'correct_answer': 'A'})
    writer.write(tmp_path / 's1', [entry], [entry], [response])
    lines = (
        (tmp_path / 's1' / 'amp_questions_0.jsonl').read_text().splitlines()
    )
    merged = json.loads(lines[0])
    assert merged['Protein_Name'] == 'P1'
    assert merged['correct_answer'] == 'A'
    writer.merge([tmp_path / 's1'], tmp_path / 'm')
    assert (tmp_path / 'm' / 'amp_questions_merged.jsonl').exists()


# ----------------------------------------------------------------- driver
def test_distributed_generation_end_to_end(tmp_path):
    import yaml

    from distllm_tpu.distributed_generation import main
    from distllm_tpu.registry import registry

    input_dir = tmp_path / 'in'
    input_dir.mkdir()
    for i in range(2):
        with open(input_dir / f'f{i}.jsonl', 'w') as fh:
            fh.write(json.dumps({'text': f'chunk {i}', 'path': f'p{i}'}) + '\n')

    config = {
        'input_dir': str(input_dir),
        'output_dir': str(tmp_path / 'out'),
        'glob_patterns': ['*.jsonl'],
        'reader_config': {'name': 'jsonl'},
        'prompt_config': {'name': 'identity'},
        'generator_config': {'name': 'fake'},
        'writer_config': {'name': 'huggingface'},
        'compute_config': {'name': 'local'},
    }
    cfg_path = tmp_path / 'gen.yaml'
    cfg_path.write_text(yaml.safe_dump(config))
    assert main(['--config', str(cfg_path)]) == 0
    shards = sorted((tmp_path / 'out' / 'generations').iterdir())
    assert len(shards) == 2
    # Clobber guard: second run refuses.
    assert main(['--config', str(cfg_path)]) == 1
    registry().clear()


def test_distributed_tokenization_worker(tmp_path):
    """Worker-level test with a local tokenizer dir (no hub access)."""
    from datasets import load_from_disk
    from transformers import BertTokenizerFast

    # Build a tiny local WordPiece vocab.
    vocab = ['[PAD]', '[UNK]', '[CLS]', '[SEP]', 'hello', 'world']
    vocab_file = tmp_path / 'vocab.txt'
    vocab_file.write_text('\n'.join(vocab))
    tok = BertTokenizerFast(vocab_file=str(vocab_file))
    tok.save_pretrained(str(tmp_path / 'tok'))

    f = tmp_path / 'in.jsonl'
    f.write_text(json.dumps({'text': 'hello world'}) + '\n')

    from distllm_tpu.distributed_tokenization import tokenizer_worker

    shard = tokenizer_worker(
        str(f),
        output_dir=str(tmp_path / 'out'),
        tokenizer_kwargs={
            'tokenizer_name_or_path': str(tmp_path / 'tok'),
            'return_labels': True,
        },
    )
    ds = load_from_disk(shard)
    assert ds[0]['input_ids'][0] == 2  # [CLS]
    assert ds[0]['labels'] == ds[0]['input_ids']


def test_decoder_family_dispatch():
    from distllm_tpu.models import decoder_family, mistral, mixtral

    cfg_cls, family = decoder_family('mixtral')
    assert cfg_cls is mixtral.MixtralConfig and family is mixtral
    cfg_cls, family = decoder_family('qwen2')
    assert cfg_cls is mistral.MistralConfig and family is mistral
    with pytest.raises(ValueError, match='Unsupported decoder'):
        decoder_family('bert')


def test_decoder_family_gemma_dispatch():
    from distllm_tpu.models import decoder_family, gemma

    for model_type in ('gemma', 'gemma2'):
        cfg_cls, family = decoder_family(model_type)
        assert cfg_cls is gemma.GemmaConfig and family is gemma
    cfg = gemma.GemmaConfig.from_hf_config(
        {'model_type': 'gemma2', 'vocab_size': 64, 'hidden_size': 32,
         'num_hidden_layers': 2, 'num_attention_heads': 4,
         'num_key_value_heads': 2, 'head_dim': 16, 'intermediate_size': 64,
         'hidden_activation': 'gelu_pytorch_tanh',
         'query_pre_attn_scalar': 16, 'sliding_window': 8,
         'attn_logit_softcapping': 50.0, 'final_logit_softcapping': 30.0}
    )
    assert cfg.post_norms and cfg.sliding_window_pattern == 'alternating'
    # The Pallas auto-gate is purely the head-dim CI contract now: the
    # ragged kernel natively supports softcap / alternating windows /
    # query_scale, so a gemma2 config at head_dim 128 IS eligible while
    # this 16-head-dim config stays on XLA.
    from types import SimpleNamespace

    from distllm_tpu.ops.paged_attention import supports_model

    assert not supports_model(cfg)  # head_dim 16: outside the DMA contract
    assert supports_model(
        SimpleNamespace(head_size=128, attn_logit_softcap=50.0,
                        sliding_window_pattern='alternating')
    )


def test_generation_config_eos_fallback(tmp_path):
    import json

    from distllm_tpu.generate.generators.tpu_backend import (
        _generation_config_eos,
    )

    assert _generation_config_eos(tmp_path) == ()
    (tmp_path / 'generation_config.json').write_text(
        json.dumps({'eos_token_id': 1})
    )
    assert _generation_config_eos(tmp_path) == (1,)
    # gemma-2-it style: EVERY listed id must stop generation (vLLM parity).
    (tmp_path / 'generation_config.json').write_text(
        json.dumps({'eos_token_id': [106, 107]})
    )
    assert _generation_config_eos(tmp_path) == (106, 107)
    for bad in ('not json', '[1, 2]', '{"eos_token_id": "<eos>"}'):
        (tmp_path / 'generation_config.json').write_text(bad)
        assert _generation_config_eos(tmp_path) == ()


def test_tpu_generator_config_mixed_batching_knobs():
    """Serving configs can opt into mixed prefill+decode windows; None
    defaults inherit EngineConfig's single-owner defaults."""
    from distllm_tpu.generate.generators.tpu_backend import TpuGeneratorConfig

    cfg = TpuGeneratorConfig(
        pretrained_model_name_or_path='/x',
        enable_mixed_batching=True,
        max_window_prefill_tokens=128,
    )
    assert cfg.enable_mixed_batching is True
    assert cfg.max_window_prefill_tokens == 128
    default = TpuGeneratorConfig(pretrained_model_name_or_path='/x')
    assert default.enable_mixed_batching is None
    assert default.max_window_prefill_tokens is None
