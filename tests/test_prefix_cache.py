"""Automatic prefix caching: hash-chain cache units, scheduler
borrowed-prefix accounting (both backends), and engine-level token-exact
reuse — cache on vs off must be byte-identical, with zero blocks
allocated for cached prefixes (docs/prefix_caching.md)."""

from __future__ import annotations

import numpy as np
import pytest

import jax

from distllm_tpu.generate.engine import (
    EngineConfig,
    LLMEngine,
    SamplingParams,
)
from distllm_tpu.generate.engine.kv_cache import (
    PrefixCache,
    block_digests,
    hash_block_tokens,
)
from distllm_tpu.generate.engine.scheduler import (
    NativeScheduler,
    PyScheduler,
)
from distllm_tpu.models import mistral


# ---------------------------------------------------------------- digests
def test_block_digests_chain_identifies_whole_prefix():
    bs = 4
    a = block_digests([1, 2, 3, 4, 5, 6, 7, 8, 9], bs)
    b = block_digests([1, 2, 3, 4, 5, 6, 7, 8], bs)
    assert len(a) == 2 and len(b) == 2
    assert a == b  # partial trailing token does not hash
    # Divergence in block 0 changes EVERY later digest (chained).
    c = block_digests([9, 2, 3, 4, 5, 6, 7, 8], bs)
    assert c[0] != a[0] and c[1] != a[1]
    # Same block content under a different prefix hashes differently.
    assert hash_block_tokens(None, [5, 6, 7, 8]) != a[1]


def test_block_digests_short_prompt_has_no_full_block():
    assert block_digests([1, 2, 3], 4) == []


# ------------------------------------------------------------ cache logic
def test_prefix_cache_acquire_insert_release_evict():
    cache = PrefixCache(block_size=4)
    d = block_digests(list(range(1, 13)), 4)  # 3 full blocks
    assert cache.match(d) == []
    # rid 0 prefills and inserts blocks 7, 8, 9.
    for digest, block in zip(d, (7, 8, 9)):
        assert cache.insert(0, digest, block)
    assert not cache.insert(1, d[0], 11)  # first writer wins
    assert cache.num_cached == 3 and cache.num_evictable == 0

    # rid 2 matches the full chain and pins it.
    assert cache.acquire(2, d) == [7, 8, 9]
    assert cache.num_shared == 3
    assert cache.evict(10) == []  # everything referenced -> nothing evicts

    cache.release(0)
    assert cache.num_evictable == 0  # rid 2 still holds refs
    cache.release(2)
    assert cache.num_evictable == 3
    # A new acquire resurrects evictable entries (removes them from LRU).
    assert cache.acquire(3, d[:1]) == [7]
    assert cache.num_evictable == 2
    # LRU eviction pops oldest-released first and skips referenced blocks.
    assert cache.evict(5) == [8, 9]
    assert cache.num_cached == 1
    cache.release(3)
    assert cache.evict(5) == [7]
    assert cache.num_cached == 0


def test_prefix_cache_partial_match_stops_at_first_miss():
    cache = PrefixCache(block_size=2)
    d = block_digests([1, 2, 3, 4, 5, 6], 2)
    cache.insert(0, d[0], 3)
    # d[1] missing: match must stop there even though d[2] is "cached".
    cache.insert(0, d[2], 4)
    assert cache.acquire(1, d) == [3]


# ------------------------------------------- scheduler borrowed prefixes
def _native_available() -> bool:
    try:
        NativeScheduler(8, 4, 2)
        return True
    except (RuntimeError, OSError):
        return False


@pytest.fixture(params=['py', 'native'])
def sched_cls(request):
    if request.param == 'native' and not _native_available():
        pytest.skip('no C++ toolchain')
    return PyScheduler if request.param == 'py' else NativeScheduler


class TestSchedulerBorrowedPrefix:
    def test_admission_allocates_only_shortfall(self, sched_cls):
        s = sched_cls(16, 4, 2)
        free0 = s.num_free_blocks
        s.add(0, 10, cached_blocks=[11, 12])  # 2 of the 3 needed blocks
        assert s.admit_next() == 0
        assert s.num_free_blocks == free0 - 1  # shortfall only
        row = s.block_row(0)
        assert row[:2] == [11, 12] and len(row) == 3
        assert s.num_borrowed(0) == 2

    def test_finish_frees_only_owned_tail(self, sched_cls):
        s = sched_cls(16, 4, 2)
        free0 = s.num_free_blocks
        s.add(0, 10, cached_blocks=[11, 12])
        s.admit_next()
        s.finish(0)
        # Borrowed blocks 11/12 are cache property: NOT back in free list.
        assert s.num_free_blocks == free0

    def test_release_blocks_returns_evicted_to_free_list(self, sched_cls):
        s = sched_cls(16, 4, 2)
        free0 = s.num_free_blocks
        s.release_blocks([11, 12])
        assert s.num_free_blocks == free0 + 2

    def test_lend_prefix_marks_blocks_unfreeable(self, sched_cls):
        s = sched_cls(16, 4, 2)
        free0 = s.num_free_blocks
        s.add(0, 10)
        s.admit_next()  # allocates 3
        s.lend_prefix(0, 2)
        assert s.num_borrowed(0) == 2
        s.finish(0)
        assert s.num_free_blocks == free0 - 2  # lent blocks stay out

    def test_preemption_keeps_borrowed_prefix(self, sched_cls):
        # block_size 1, pool 9 usable: rid 0 (3+1) and rid 1 (2 owned +
        # 2 borrowed + 1 headroom = 3 owned) fill the pool.
        s = sched_cls(10, 1, 2)
        s.add(0, 5)
        s.add(1, 4, cached_blocks=[20, 21])
        assert s.admit_next() == 0  # 6 blocks
        assert s.admit_next() == 1  # 3 more owned
        assert s.num_free_blocks == 0
        s.append_token(0)
        preempted = s.prepare_decode()
        assert preempted == [1]
        assert s.block_row(1) == [20, 21]  # borrowed prefix survives
        assert s.num_borrowed(1) == 2

    def test_lend_prefix_beyond_row_raises(self, sched_cls):
        s = sched_cls(16, 4, 2)
        s.add(0, 3)
        s.admit_next()
        with pytest.raises((ValueError, KeyError)):
            s.lend_prefix(0, 99)


# ----------------------------------------------------------------- engine
def _tiny_engine(
    num_blocks=64,
    max_num_seqs=4,
    max_model_len=64,
    prefer_native=False,
    **cfg_kwargs,
):
    cfg = mistral.MistralConfig(
        vocab_size=64,
        hidden_size=32,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        intermediate_size=64,
        dtype='float32',
    )
    params = mistral.init(jax.random.PRNGKey(0), cfg)

    class IdTokenizer:
        eos_id = None

        def decode(self, ids):
            return ' '.join(str(i) for i in ids)

    engine = LLMEngine(
        cfg,
        params,
        IdTokenizer(),
        EngineConfig(
            block_size=4,
            num_blocks=num_blocks,
            max_num_seqs=max_num_seqs,
            max_model_len=max_model_len,
            prefer_native_allocator=prefer_native,
            **cfg_kwargs,
        ),
    )
    return cfg, params, engine


def _dense_greedy(cfg, params, prompt, n_tokens):
    ids = list(prompt)
    for _ in range(n_tokens):
        arr = np.asarray([ids], np.int32)
        hidden = mistral.apply(params, cfg, arr, np.ones_like(arr))
        lg = mistral.logits(params, cfg, hidden[:, -1])
        ids.append(int(np.argmax(np.asarray(lg)[0])))
    return ids[len(prompt):]


GREEDY = SamplingParams(temperature=0.0, max_tokens=6)


def test_second_request_reuses_prefix_blocks_and_tokens_match():
    """Acceptance: a second request sharing an N-block prefix allocates
    ZERO new blocks for that prefix and generates byte-identical tokens
    to a cache-off run."""
    cfg, params, engine = _tiny_engine(enable_prefix_cache=True)
    shared = [7, 3, 22, 31, 40, 2, 17, 9]  # 2 full blocks at block_size 4
    p1 = shared + [11, 12]
    p2 = shared + [33, 34, 35]
    out1 = engine.generate_ids([p1], GREEDY)[0]
    assert out1 == _dense_greedy(cfg, params, p1, 6)

    # p1 finished: its prompt blocks sit in the cache, evictable.
    assert engine.prefix_cache.num_evictable == 2
    free_before = engine.sched.num_free_blocks
    rid = engine.add_request(p2, GREEDY)
    request = engine._requests[rid]
    assert request.num_cached_tokens == 8
    assert request.num_borrowed_blocks == 2
    # Admission must allocate blocks for the TAIL only.
    while engine.has_unfinished:
        engine.step()
    out2 = engine._finished.pop(rid).output_ids
    assert out2 == _dense_greedy(cfg, params, p2, 6)
    # Zero new blocks for the shared prefix: total allocation for p2 ==
    # blocks_needed(len(p2) + 6 generated) - the 2 cached blocks. All
    # owned blocks are freed at finish, so free-count round-trips.
    assert engine.sched.num_free_blocks == free_before
    assert engine._stats['prefix_hit_tokens'] == 8


def test_cache_on_off_identical_across_workload():
    """Whole-workload identity: shared-stem prompts (the MCQA pattern),
    repeats, and unshared prompts — cache on == cache off, token for
    token, across sequential generate_ids calls. (The cache-off engine is
    dense-reference-checked by test_engine.py; identity is the claim
    here.)"""
    stem = list(range(1, 13))  # 3 full blocks
    prompts = [
        stem + [20 + i] for i in range(4)
    ] + [[5, 9, 12], stem + [20]]
    _, _, engine_off = _tiny_engine(num_blocks=128, max_num_seqs=4)
    _, _, engine_on = _tiny_engine(
        num_blocks=128, max_num_seqs=4, enable_prefix_cache=True
    )
    for batch in (prompts[:4], prompts[4:]):
        outs_off = engine_off.generate_ids(batch, GREEDY)
        outs_on = engine_on.generate_ids(batch, GREEDY)
        assert outs_on == outs_off
    assert engine_on.telemetry['prefix_hit_tokens'] > 0


def test_cow_on_aligned_full_cover_repeat():
    """Re-submitting a block-aligned prompt hits every block; the last
    token recomputes into a COW copy of the shared final block."""
    cfg, params, engine = _tiny_engine(enable_prefix_cache=True)
    prompt = [7, 3, 22, 31, 40, 2, 17, 9]  # len 8 == 2 * block_size
    out1 = engine.generate_ids([prompt], GREEDY)[0]
    out2 = engine.generate_ids([prompt], GREEDY)[0]
    assert out1 == out2 == _dense_greedy(cfg, params, prompt, 6)
    assert engine.telemetry['prefix_cow_copies'] == 1
    assert engine.telemetry['prefix_hit_tokens'] == 7  # len - 1


def test_eviction_under_pool_pressure_no_leaks():
    """A small pool forces LRU eviction of cached blocks; outputs stay
    exact and every block is accounted for afterwards."""
    cfg, params, engine = _tiny_engine(
        num_blocks=16, max_num_seqs=2, max_model_len=32,
        enable_prefix_cache=True,
    )
    rng = np.random.default_rng(7)
    sp = SamplingParams(temperature=0.0, max_tokens=4)
    # Each 9-token prompt leaves 2 cached blocks behind; by run 8 the
    # 15-block pool cannot admit without evicting someone's prefix.
    for i in range(10):
        prompt = list(rng.integers(1, 64, size=9))
        out = engine.generate_ids([prompt], sp)[0]
        assert out == _dense_greedy(cfg, params, prompt, 4)
    # Invariant: free blocks + cache-held blocks == usable pool.
    assert (
        engine.sched.num_free_blocks + engine.prefix_cache.num_cached == 15
    )
    assert engine.prefix_cache.stats['evictions'] > 0


def test_chunked_prefill_matches_dense():
    """Long uncached tails split into chunks must stay token-exact (each
    chunk attends over the paged cache), with and without the cache."""
    prompts = [list(range(1, 23)), [5, 9, 12]]
    refs = None
    for extra in ({}, {'enable_prefix_cache': True}):
        cfg, params, engine = _tiny_engine(
            num_blocks=128, prefill_chunk_tokens=8, **extra
        )
        if refs is None:
            refs = [_dense_greedy(cfg, params, p, 6) for p in prompts]
        outs = engine.generate_ids(prompts, GREEDY)
        assert outs == refs, extra
        assert engine.telemetry['prefill_chunks'] >= 2


def test_prefix_cache_with_pipelined_decode_and_deferred_prefill():
    """Cache + chunking under the production serving loop shape
    (multi-step windows, pipeline depth 2, deferred prefill)."""
    cfg, params, engine = _tiny_engine(
        num_blocks=128,
        max_num_seqs=2,
        enable_prefix_cache=True,
        prefill_chunk_tokens=8,
        decode_steps=4,
        pipeline_depth=2,
        defer_prefill=True,
    )
    stem = list(range(1, 10))
    prompts = [stem + [30], stem + [31], list(range(40, 58)), [5, 9]]
    lens = [6, 9, 5, 7]
    rids = [
        engine.add_request(p, SamplingParams(temperature=0.0, max_tokens=n))
        for p, n in zip(prompts, lens)
    ]
    engine._run_to_completion()
    for p, n, rid in zip(prompts, lens, rids):
        got = engine._finished.pop(rid).output_ids
        assert got == _dense_greedy(cfg, params, p, n), p


def test_prefix_cache_preemption_pressure_matches_dense():
    """Recompute preemption with borrowed prefixes: victims keep cached
    blocks, re-prefill only the rest, outputs stay exact."""
    cfg, params, engine = _tiny_engine(
        num_blocks=14, max_num_seqs=3, enable_prefix_cache=True
    )
    stem = [7, 3, 22, 31]
    prompts = [stem + [5], stem + [9, 2], [1, 2, 3, 4, 5]]
    outs = engine.generate_ids(prompts, GREEDY)
    for p, o in zip(prompts, outs):
        assert o == _dense_greedy(cfg, params, p, 6)


@pytest.mark.skipif(not _native_available(), reason='no C++ toolchain')
def test_prefix_cache_scheduler_backend_parity():
    """PyScheduler and NativeScheduler drive identical cache decisions."""
    stem = list(range(1, 13))
    prompts = [stem + [20 + i] for i in range(5)] + [[9, 8, 7]]
    results = []
    for native in (False, True):
        _, _, engine = _tiny_engine(
            num_blocks=32,
            max_num_seqs=2,
            enable_prefix_cache=True,
            prefer_native=native,
        )
        outs = engine.generate_ids(prompts, GREEDY)
        results.append(
            (
                outs,
                engine.telemetry.get('prefix_hit_tokens', 0),
                engine.sched.num_free_blocks,
                engine.prefix_cache.num_cached,
            )
        )
    assert results[0] == results[1]


def test_warmup_covers_paged_prefill_without_state_damage():
    cfg, params, engine = _tiny_engine(
        enable_prefix_cache=True, prefill_chunk_tokens=8
    )
    key_before = engine._key
    engine.warmup()
    assert engine.sched.num_running == 0
    assert engine.sched.num_free_blocks == 63
    assert engine.prefix_cache.num_cached == 0
    assert (np.asarray(engine._key) == np.asarray(key_before)).all()
    prompt = [5, 9, 12, 4, 7]
    out = engine.generate_ids([prompt], GREEDY)[0]
    assert out == _dense_greedy(cfg, params, prompt, 6)


def test_prefix_metrics_exported():
    from distllm_tpu.observability import render_prometheus

    _, _, engine = _tiny_engine(enable_prefix_cache=True)
    engine.generate_ids([[1, 2, 3, 4, 5]], GREEDY)
    text = render_prometheus()
    for series in (
        'distllm_prefix_cache_hit_tokens_total',
        'distllm_prefix_cache_lookup_tokens_total',
        'distllm_prefix_cache_blocks',
        'distllm_prefix_cache_evictions_total',
        'distllm_prefix_cache_cow_copies_total',
        'distllm_engine_prefill_chunks_total',
    ):
        assert series in text, series
