"""Ring attention + Ulysses sequence parallelism vs full attention.

The reference truncates long inputs instead of parallelizing them
(``distllm/embed/encoders/auto.py:74``; SURVEY.md §5 "Long-context"); these
tests pin our sequence-parallel attention to exact full-attention numerics on
the 8-device CPU mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distllm_tpu.ops.ring_attention import ring_attention, ulysses_attention
from distllm_tpu.parallel.mesh import MeshSpec, make_mesh


def full_attention(q, k, v, kv_mask=None, causal=False):
    """fp32 reference: ordinary softmax attention over [B, S, N, H]."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum(
        'bqnh,bknh->bnqk', q.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale
    mask = jnp.ones((q.shape[0], 1, q.shape[1], k.shape[1]), bool)
    if kv_mask is not None:
        mask = mask & kv_mask[:, None, None, :].astype(bool)
    if causal:
        pos = jnp.arange(q.shape[1])
        mask = mask & (pos[None, None, None, :] <= pos[None, None, :, None])
    s = jnp.where(mask, s, -1e30)
    w = jax.nn.softmax(s, axis=-1)
    w = jnp.where(jnp.any(mask, axis=-1, keepdims=True), w, 0.0)
    return jnp.einsum('bnqk,bknh->bqnh', w, v.astype(jnp.float32))


def _qkv(rng, b=2, s=32, n=8, h=8):
    # n=8 on the seq=4 mesh gives 2 heads per device — the Ulysses head
    # regrouping is only non-trivial when heads-per-device > 1.
    shape = (b, s, n, h)
    mk = lambda: jnp.asarray(rng.standard_normal(shape), jnp.float32)
    return mk(), mk(), mk()


@pytest.fixture(scope='module')
def seq_mesh():
    return make_mesh(MeshSpec(data=2, seq=4, expert=1, model=1))


class TestRingAttention:
    def test_matches_full_attention(self, rng, seq_mesh):
        q, k, v = _qkv(rng)
        out = ring_attention(q, k, v, seq_mesh)
        ref = full_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_causal(self, rng, seq_mesh):
        q, k, v = _qkv(rng)
        out = ring_attention(q, k, v, seq_mesh, causal=True)
        ref = full_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_padding_mask(self, rng, seq_mesh):
        q, k, v = _qkv(rng)
        lengths = np.array([20, 9])
        kv_mask = jnp.asarray(np.arange(32)[None, :] < lengths[:, None])
        out = ring_attention(q, k, v, seq_mesh, kv_mask=kv_mask)
        ref = full_attention(q, k, v, kv_mask=kv_mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_causal_plus_padding(self, rng, seq_mesh):
        q, k, v = _qkv(rng)
        lengths = np.array([32, 17])
        kv_mask = jnp.asarray(np.arange(32)[None, :] < lengths[:, None])
        out = ring_attention(q, k, v, seq_mesh, kv_mask=kv_mask, causal=True)
        ref = full_attention(q, k, v, kv_mask=kv_mask, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_seq_only_mesh(self, rng):
        mesh = make_mesh(MeshSpec(data=1, seq=8, expert=1, model=1))
        q, k, v = _qkv(rng, b=1, s=64)
        out = ring_attention(q, k, v, mesh, causal=True)
        ref = full_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_jit_compatible(self, rng, seq_mesh):
        q, k, v = _qkv(rng)
        fn = jax.jit(lambda q, k, v: ring_attention(q, k, v, seq_mesh))
        np.testing.assert_allclose(
            np.asarray(fn(q, k, v)),
            np.asarray(full_attention(q, k, v)),
            atol=1e-5,
        )


class TestUlyssesAttention:
    def test_matches_full_attention(self, rng, seq_mesh):
        q, k, v = _qkv(rng)
        out = ulysses_attention(q, k, v, seq_mesh)
        ref = full_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_causal_and_padding(self, rng, seq_mesh):
        q, k, v = _qkv(rng)
        lengths = np.array([25, 13])
        kv_mask = jnp.asarray(np.arange(32)[None, :] < lengths[:, None])
        out = ulysses_attention(q, k, v, seq_mesh, kv_mask=kv_mask, causal=True)
        ref = full_attention(q, k, v, kv_mask=kv_mask, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_head_divisibility_guard(self, rng, seq_mesh):
        q, k, v = _qkv(rng, n=6)  # 6 heads not divisible by seq=4
        with pytest.raises(ValueError, match='divisible'):
            ulysses_attention(q, k, v, seq_mesh)

    def test_agrees_with_ring(self, rng, seq_mesh):
        q, k, v = _qkv(rng)
        a = ring_attention(q, k, v, seq_mesh, causal=True)
        b = ulysses_attention(q, k, v, seq_mesh, causal=True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


class TestModelSequenceParallel:
    """mistral.apply with seq_parallel matches the dense forward."""

    @pytest.mark.parametrize('strategy', ['ring', 'ulysses'])
    def test_mistral_seq_parallel_matches_dense(self, rng, seq_mesh, strategy):
        import jax.numpy as jnp

        from distllm_tpu.models import mistral

        cfg = mistral.MistralConfig(
            vocab_size=128,
            hidden_size=32,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            intermediate_size=64,
            dtype='float32',
        )
        params = mistral.init(jax.random.PRNGKey(0), cfg)
        ids = np.asarray(rng.integers(0, 128, (2, 32)), np.int32)
        mask = np.ones((2, 32), np.int32)
        mask[1, 20:] = 0

        dense = mistral.apply(params, cfg, ids, mask)
        sp = mistral.apply(
            params, cfg, ids, mask, mesh=seq_mesh, seq_parallel=strategy
        )
        np.testing.assert_allclose(
            np.asarray(sp), np.asarray(dense), atol=1e-4
        )

    def test_sliding_window_guard(self, seq_mesh):
        from distllm_tpu.models import mistral

        cfg = mistral.MistralConfig(
            vocab_size=64, hidden_size=16, num_layers=1, num_heads=4,
            num_kv_heads=4, intermediate_size=32, sliding_window=8,
            dtype='float32',
        )
        params = mistral.init(jax.random.PRNGKey(0), cfg)
        ids = np.ones((1, 16), np.int32)
        with pytest.raises(NotImplementedError):
            mistral.apply(
                params, cfg, ids, ids, mesh=seq_mesh, seq_parallel='ring'
            )
