"""Runtime tests: mesh construction, sharding, tokenizer bucketing, loader."""

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from distllm_tpu.models.loader import (
    read_checkpoint,
    save_checkpoint,
    unflatten,
)
from distllm_tpu.models.tokenizer import (
    TokenBatch,
    WhitespaceTokenizer,
    bucket_ladder,
    pick_bucket,
)
from distllm_tpu.parallel import make_mesh, shard_pytree
from distllm_tpu.parallel.mesh import MeshSpec


def test_mesh_spec_resolution():
    assert MeshSpec(data=-1, model=2).resolve(8) == {
        'data': 4,
        'seq': 1,
        'expert': 1,
        'model': 2,
    }
    with pytest.raises(ValueError):
        MeshSpec(data=3, model=2).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(data=-1, model=-1).resolve(8)


def test_make_mesh_axes():
    mesh = make_mesh(MeshSpec(data=2, seq=2, model=2))
    assert mesh.shape == {'data': 2, 'seq': 2, 'expert': 1, 'model': 2}


def test_shard_pytree_matmul():
    mesh = make_mesh(MeshSpec(data=1, model=8))
    params = {'w': np.arange(32 * 16, dtype=np.float32).reshape(32, 16)}
    specs = {'w': P(None, 'model')}
    sharded = shard_pytree(params, specs, mesh)
    x = np.ones((4, 32), np.float32)
    out = jax.jit(lambda p, x: x @ p['w'])(sharded, x)
    np.testing.assert_allclose(np.asarray(out), x @ params['w'])


def test_bucket_ladder():
    assert bucket_ladder(512, 16) == [
        16, 32, 64, 96, 128, 160, 192, 224, 256, 288, 320, 352, 384,
        448, 512,
    ]
    assert bucket_ladder(100, 16) == [16, 32, 64, 96, 100]
    assert bucket_ladder(1024, 16)[-3:] == [768, 896, 1024]
    assert pick_bucket(33, [16, 32, 64]) == 64
    assert pick_bucket(999, [16, 32, 64]) == 64


def test_whitespace_tokenizer_buckets():
    tok = WhitespaceTokenizer(vocab_size=1000, model_max_length=64)
    batch = tok(['hello world', 'a b c d e f g'])
    assert batch.shape == (2, 16)  # smallest bucket
    assert batch.attention_mask[0].sum() == 4  # cls + 2 tokens + sep
    # Determinism across instances:
    tok2 = WhitespaceTokenizer(vocab_size=1000, model_max_length=64)
    batch2 = tok2(['hello world', 'a b c d e f g'])
    np.testing.assert_array_equal(batch.input_ids, batch2.input_ids)


def test_whitespace_tokenizer_truncation():
    tok = WhitespaceTokenizer(vocab_size=1000, model_max_length=8)
    batch = tok(['one two three four five six seven eight nine ten'])
    assert batch.shape == (1, 8)
    assert batch.input_ids[0, 0] == tok.cls_id
    assert batch.input_ids[0, 7] == tok.sep_id


def test_token_batch_pad_batch():
    tb = TokenBatch(
        np.ones((2, 8), np.int32), np.ones((2, 8), np.int32)
    ).pad_batch_to(4)
    assert tb.shape == (4, 8)
    assert tb.attention_mask[2:].sum() == 0


def test_checkpoint_roundtrip(tmp_path):
    state = {
        'layer.weight': np.random.default_rng(0).normal(size=(4, 3)).astype(np.float32)
    }
    save_checkpoint(state, tmp_path / 'ckpt')
    loaded = read_checkpoint(tmp_path / 'ckpt')
    np.testing.assert_array_equal(loaded['layer.weight'], state['layer.weight'])


def test_checkpoint_missing_dir():
    with pytest.raises(FileNotFoundError):
        read_checkpoint('/nonexistent/model/dir')


def test_unflatten():
    tree = unflatten({'a.b.c': 1, 'a.d': 2})
    assert tree == {'a': {'b': {'c': 1}, 'd': 2}}
