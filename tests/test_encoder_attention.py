"""Pallas encoder-attention kernel vs jnp oracle (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distllm_tpu.ops.encoder_attention import (
    encoder_attention,
    encoder_attention_reference,
)


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def _case(rng, b, s, d, dtype):
    q = jnp.asarray(rng.normal(size=(b, s, d)), dtype)
    k = jnp.asarray(rng.normal(size=(b, s, d)), dtype)
    v = jnp.asarray(rng.normal(size=(b, s, d)), dtype)
    return q, k, v


@pytest.mark.parametrize('s', [32, 160])
def test_matches_reference_full_mask(rng, s):
    q, k, v = _case(rng, 2, s, 64, jnp.float32)
    mask = jnp.ones((2, s), jnp.int32)
    got = encoder_attention(q, k, v, mask, num_heads=4, interpret=True)
    want = encoder_attention_reference(q, k, v, mask, num_heads=4)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_key_mask_excludes_padding(rng):
    b, s, d = 2, 64, 48
    q, k, v = _case(rng, b, s, d, jnp.float32)
    lens = [40, 64]
    mask = jnp.asarray(
        [[1] * n + [0] * (s - n) for n in lens], jnp.int32
    )
    got = encoder_attention(q, k, v, mask, num_heads=3, interpret=True)
    want = encoder_attention_reference(q, k, v, mask, num_heads=3)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-5
    )
    # Truncating the padded tail entirely must not change valid outputs:
    # proves padded keys carry zero attention weight.
    n = lens[0]
    got_trunc = encoder_attention(
        q[:1, :n], k[:1, :n], v[:1, :n], mask[:1, :n],
        num_heads=3, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got[0, :n]), np.asarray(got_trunc[0]), atol=2e-5
    )


def test_fully_padded_rows_finite(rng):
    q, k, v = _case(rng, 2, 32, 32, jnp.float32)
    mask = jnp.zeros((2, 32), jnp.int32)  # batch-dim pad rows
    got = encoder_attention(q, k, v, mask, num_heads=2, interpret=True)
    assert np.isfinite(np.asarray(got)).all()


def test_bfloat16_close(rng):
    q, k, v = _case(rng, 1, 64, 96, jnp.bfloat16)
    mask = jnp.ones((1, 64), jnp.int32)
    got = encoder_attention(q, k, v, mask, num_heads=12, interpret=True)
    want = encoder_attention_reference(q, k, v, mask, num_heads=12)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), atol=3e-2
    )


def test_additive_bias_matches_reference(rng):
    b, s, d = 2, 64, 48
    q, k, v = _case(rng, b, s, d, jnp.float32)
    mask = jnp.asarray([[1] * 40 + [0] * 24, [1] * 64], jnp.int32)
    # A sliding-window mask as the bias (the ModernBERT use case).
    dist = np.abs(np.arange(s)[:, None] - np.arange(s)[None, :])
    bias = jnp.asarray(np.where(dist <= 8, 0.0, -1e9), jnp.float32)
    got = encoder_attention(
        q, k, v, mask, num_heads=3, bias=bias, interpret=True
    )
    want = encoder_attention_reference(q, k, v, mask, num_heads=3, bias=bias)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)
    # Zero bias must reduce to the no-bias kernel exactly.
    zero = encoder_attention(
        q, k, v, mask, num_heads=3, bias=jnp.zeros((s, s)), interpret=True
    )
    plain = encoder_attention(q, k, v, mask, num_heads=3, interpret=True)
    np.testing.assert_allclose(np.asarray(zero), np.asarray(plain), atol=2e-5)


def test_modernbert_apply_pallas_path_matches_xla(rng):
    """modernbert.apply(attn_impl='pallas') == 'xla': exercises the
    window-bias select for both global (layer 0) and local layers."""
    import distllm_tpu.ops.encoder_attention as ea
    from distllm_tpu.models import modernbert

    cfg = modernbert.ModernBertConfig(
        vocab_size=128, hidden_size=48, num_layers=3, num_heads=3,
        intermediate_size=96, max_position_embeddings=64,
        global_attn_every_n_layers=2, local_attention=16, dtype='float32',
    )
    params = modernbert.init(jax.random.PRNGKey(0), cfg)
    ids = jnp.asarray(rng.integers(0, 128, size=(2, 32)), jnp.int32)
    mask = jnp.asarray([[1] * 32, [1] * 20 + [0] * 12], jnp.int32)

    orig = ea.encoder_attention
    try:
        ea.encoder_attention = lambda *a, **kw: orig(
            *a, **{**kw, 'interpret': True}
        )
        got = modernbert.apply(params, cfg, ids, mask, attn_impl='pallas')
    finally:
        ea.encoder_attention = orig
    want = modernbert.apply(params, cfg, ids, mask, attn_impl='xla')
    # Compare valid rows only: a padded query whose sliding window holds no
    # valid key is fully masked, and the two backends emit different
    # (equally meaningless) uniform-softmax garbage there; poolers mask
    # those rows out downstream.
    valid = np.asarray(mask, bool)
    np.testing.assert_allclose(
        np.asarray(got)[valid], np.asarray(want)[valid], atol=1e-4
    )


def test_bert_apply_pallas_path_matches_xla(rng):
    """bert.apply(attn_impl='pallas') == attn_impl='xla' (interpret via env
    is not available inside apply, so drive the kernel's own interpret mode
    through monkeypatched encoder_attention)."""
    import distllm_tpu.ops.encoder_attention as ea
    from distllm_tpu.models import bert

    cfg = bert.BertConfig(
        vocab_size=128, hidden_size=48, num_layers=2, num_heads=3,
        intermediate_size=96, max_position_embeddings=64, dtype='float32',
    )
    params = bert.init(jax.random.PRNGKey(0), cfg)
    ids = jnp.asarray(rng.integers(0, 128, size=(2, 32)), jnp.int32)
    mask = jnp.asarray([[1] * 32, [1] * 20 + [0] * 12], jnp.int32)

    orig = ea.encoder_attention
    try:
        ea.encoder_attention = lambda *a, **kw: orig(
            *a, **{**kw, 'interpret': True}
        )
        got = bert.apply(params, cfg, ids, mask, attn_impl='pallas')
    finally:
        ea.encoder_attention = orig
    want = bert.apply(params, cfg, ids, mask, attn_impl='xla')
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-4
    )
