"""Sampled speculative verification (docs/speculative.md "Sampled
verification"): distribution preservation of the device-side rejection
sampler (chi-square on a tiny vocab), the analytic point-mass q edge
cases, cross-kernel sampled parity (decode scan vs. spec 'none' verify
window in fp32), filter parity, and engine-level determinism with
accepted drafts at temperature > 0."""

import numpy as np

import jax
import jax.numpy as jnp

from distllm_tpu.generate.engine import (
    EngineConfig,
    LLMEngine,
    SamplingParams,
)
from distllm_tpu.models import mistral
from distllm_tpu.ops.sampling import filter_logits, verify_spans


class IdTokenizer:
    eos_id = None

    def decode(self, ids):
        return ' '.join(str(i) for i in ids)


def _tiny_cfg(**kw):
    base = dict(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=64, dtype='float32',
    )
    base.update(kw)
    return mistral.MistralConfig(**base)


def _engine(model_cfg, params, **cfg_kw):
    base = dict(
        block_size=4, num_blocks=96, max_num_seqs=2, max_model_len=96,
        prefer_native_allocator=False,
    )
    base.update(cfg_kw)
    return LLMEngine(model_cfg, params, IdTokenizer(), EngineConfig(**base))


def _dense_greedy_reference(cfg, params, prompt, n_tokens):
    ids = list(prompt)
    for _ in range(n_tokens):
        arr = np.asarray([ids], np.int32)
        hidden = mistral.apply(params, cfg, arr, np.ones_like(arr))
        lg = mistral.logits(params, cfg, hidden[:, -1])
        ids.append(int(np.argmax(np.asarray(lg)[0])))
    return ids[len(prompt):]


class _StubDrafter:
    def __init__(self, proposals):
        self.proposals = list(proposals)

    def draft(self, history, k):
        start = len(history)
        return self.proposals[start:start + k]


def _force_drafts(engine, rid, proposals, prompt_len):
    pad = [0] * prompt_len
    engine._requests[rid].drafter = _StubDrafter(pad + list(proposals))


# ------------------------------------------------- verify_spans op level
def _verify_batch(logits_row, draft, n, temperature=1.0, top_p=1.0,
                  min_p=0.0, top_k=0, top_window=0):
    """Run ``n`` independent single-draft spans (distinct seeds) of the
    same logits row through verify_spans; returns the packed [n, 3]."""
    vocab = len(logits_row)
    span_logits = jnp.broadcast_to(
        jnp.asarray(logits_row, jnp.float32)[None, None, :], (n, 2, vocab)
    )
    span_ids = jnp.broadcast_to(
        jnp.asarray([0, draft], jnp.int32)[None, :], (n, 2)
    )
    span_lens = jnp.full((n,), 2, jnp.int32)
    span_positions = jnp.broadcast_to(
        jnp.asarray([3, 4], jnp.int32)[None, :], (n, 2)
    )
    ones = jnp.ones((n,), jnp.float32)
    packed = verify_spans(
        span_logits, span_ids, span_lens, span_positions,
        ones * temperature, ones * top_p, ones * min_p,
        jnp.full((n,), top_k, jnp.int32),
        jnp.arange(n, dtype=jnp.uint32),
        top_window=top_window,
    )
    return np.asarray(packed)


def _expected_probs(logits_row, temperature=1.0, top_p=1.0, min_p=0.0,
                    top_k=0):
    """The served distribution p̃ as a dense [V] numpy vector, via the
    same filter_logits the kernels use."""
    vocab = len(logits_row)
    filtered, top_idx = filter_logits(
        jnp.asarray(logits_row, jnp.float32)[None, :],
        jnp.asarray([temperature], jnp.float32),
        jnp.asarray([top_p], jnp.float32),
        jnp.asarray([min_p], jnp.float32),
        top_k=jnp.asarray([top_k], jnp.int32),
    )
    filtered = np.asarray(filtered)[0]
    top_idx = np.asarray(top_idx)[0]
    finite = np.isfinite(filtered)
    probs_win = np.zeros_like(filtered)
    probs_win[finite] = np.exp(
        filtered[finite] - filtered[finite].max()
    )
    probs_win /= probs_win.sum()
    dense = np.zeros(vocab)
    dense[top_idx] = probs_win
    return dense


def _chi_square(counts, probs, n):
    expected = probs * n
    keep = expected > 0
    return float(((counts[keep] - expected[keep]) ** 2
                  / expected[keep]).sum())


def test_rejection_sampling_preserves_target_distribution():
    """The marginal of the FIRST emitted token (draft if accepted, else
    residual resample) must equal the served distribution p̃ exactly —
    the defining property of speculative sampling. Chi-square over 4096
    deterministic seeded trials on an 8-token vocab; df = 7, threshold
    35 sits past the 1e-4 tail, and a wrong distribution scales the
    statistic with N (thousands, not tens)."""
    rng = np.random.default_rng(42)
    logits_row = rng.normal(0.0, 1.5, size=8)
    n = 4096
    draft = 3
    packed = _verify_batch(logits_row, draft, n)
    emitted = packed[:, 0]
    counts = np.bincount(emitted, minlength=8).astype(float)
    probs = _expected_probs(logits_row)
    assert _chi_square(counts, probs, n) < 35.0
    # The acceptance rate itself is p̃(draft) for a point-mass q.
    accept_rate = packed[:, -1].mean()
    assert abs(accept_rate - probs[draft]) < 0.05


def test_rejection_sampling_preserves_filtered_distribution():
    """Same chi-square contract with top-p + top-k active: emitted
    tokens stay inside the kept set and follow the renormalized
    filtered target."""
    rng = np.random.default_rng(7)
    logits_row = rng.normal(0.0, 1.5, size=8)
    n = 4096
    draft = int(np.argsort(logits_row)[-2])  # second-likeliest: in-set
    packed = _verify_batch(
        logits_row, draft, n, top_p=0.8, top_k=5,
    )
    emitted = packed[:, 0]
    probs = _expected_probs(logits_row, top_p=0.8, top_k=5)
    kept = set(np.flatnonzero(probs > 0).tolist())
    assert set(emitted.tolist()) <= kept
    counts = np.bincount(emitted, minlength=8).astype(float)
    assert _chi_square(counts, probs, n) < 35.0


def test_point_mass_draft_on_sole_support_always_accepts():
    """top_k=1 with the draft equal to the argmax: the kept set is
    exactly {draft}, so p̃(draft) = 1 and every trial accepts (the
    residual is empty; the bonus slot falls back to the full filtered
    target, which is again the argmax)."""
    rng = np.random.default_rng(3)
    logits_row = rng.normal(0.0, 1.5, size=8)
    argmax = int(np.argmax(logits_row))
    packed = _verify_batch(logits_row, argmax, 256, top_k=1)
    assert (packed[:, -1] == 1).all()
    assert (packed[:, 0] == argmax).all()
    assert (packed[:, 1] == argmax).all()  # bonus = sole survivor


def test_point_mass_draft_outside_kept_set_never_accepts():
    """top_k=1 with a non-argmax draft: p̃(draft) = 0, so acceptance
    probability is exactly zero and the correction resamples the kept
    set (the argmax, its only member)."""
    rng = np.random.default_rng(3)
    logits_row = rng.normal(0.0, 1.5, size=8)
    argmax = int(np.argmax(logits_row))
    draft = (argmax + 1) % 8
    packed = _verify_batch(logits_row, draft, 256, top_k=1)
    assert (packed[:, -1] == 0).all()
    assert (packed[:, 0] == argmax).all()


def test_greedy_rows_keep_argmax_semantics():
    """temperature == 0 rows are untouched by the sampler: out is the
    argmax everywhere and a draft is accepted iff it equals it."""
    rng = np.random.default_rng(11)
    logits_row = rng.normal(0.0, 1.5, size=8)
    argmax = int(np.argmax(logits_row))
    hit = _verify_batch(logits_row, argmax, 4, temperature=0.0)
    miss = _verify_batch(
        logits_row, (argmax + 1) % 8, 4, temperature=0.0
    )
    assert (hit[:, 0] == argmax).all() and (hit[:, -1] == 1).all()
    assert (miss[:, 0] == argmax).all() and (miss[:, -1] == 0).all()


def test_verify_spans_deterministic_per_seed():
    rng = np.random.default_rng(5)
    logits_row = rng.normal(0.0, 1.5, size=8)
    a = _verify_batch(logits_row, 2, 64)
    b = _verify_batch(logits_row, 2, 64)
    assert (a == b).all()
    # Distinct seeds (rows here) actually decorrelate the draws.
    assert len(set(a[:, 0].tolist())) > 1


# ---------------------------------------------- cross-kernel parity (fp32)
def _sampled_outputs(engine, prompts, budgets, **sp_kw):
    rids = [
        engine.add_request(
            p, SamplingParams(max_tokens=n, seed=100 + i, **sp_kw)
        )
        for i, (p, n) in enumerate(zip(prompts, budgets))
    ]
    engine._run_to_completion()
    return [engine._finished.pop(r).output_ids for r in rids]


def _parity_workload(vocab):
    rng = np.random.default_rng(2)
    prompts = [list(rng.integers(1, vocab, size=n)) for n in (5, 11, 3)]
    budgets = [6, 4, 7]
    return prompts, budgets


def test_spec_none_matches_decode_scan_when_sampled():
    """'none' structural baseline at temperature > 0: draft_k > 0 with
    drafting disabled rides the verify kernel with span length 1, and the
    counter-based PRNG makes its sampled stream BIT-IDENTICAL (fp32) to
    the classic decode scan at draft_k = 0."""
    cfg = _tiny_cfg()
    params = mistral.init(jax.random.PRNGKey(0), cfg)
    prompts, budgets = _parity_workload(cfg.vocab_size)
    sp = dict(temperature=0.8)
    classic = _sampled_outputs(
        _engine(cfg, params), prompts, budgets, **sp
    )
    spec_none = _engine(cfg, params, draft_k=4, spec_draft_source='none')
    none_out = _sampled_outputs(spec_none, prompts, budgets, **sp)
    assert spec_none._stats['spec_windows'] > 0
    assert classic == none_out


def test_spec_filter_parity_with_decode_scan_when_sampled():
    """top-p/top-k parity: the verify kernel applies the same
    filter_logits as plain decode, so filtered sampled streams agree
    across kernels too (fp32)."""
    cfg = _tiny_cfg()
    params = mistral.init(jax.random.PRNGKey(0), cfg)
    prompts, budgets = _parity_workload(cfg.vocab_size)
    sp = dict(temperature=0.9, top_p=0.9, top_k=8)
    classic = _sampled_outputs(
        _engine(cfg, params), prompts, budgets, **sp
    )
    spec_none = _engine(cfg, params, draft_k=4, spec_draft_source='none')
    none_out = _sampled_outputs(spec_none, prompts, budgets, **sp)
    assert spec_none._stats['spec_windows'] > 0
    assert classic == none_out


# ----------------------------------------------------- engine determinism
def test_engine_sampled_spec_deterministic_with_accepts():
    """Two fresh engines, the same (seed, schedule), temperature > 0
    with top_k=1, drafts forced to the greedy reference: the filtered
    target is a point mass on the argmax, so p̃(draft) = 1 and every
    reference draft is accepted by the rejection sampler — a nonzero
    accepted count that does not hinge on the tiny random model's
    near-flat logits. Outputs are identical across runs AND equal to
    the greedy reference."""
    cfg = _tiny_cfg()
    params = mistral.init(jax.random.PRNGKey(0), cfg)
    prompt = [5, 9, 12]
    n = 9
    ref = _dense_greedy_reference(cfg, params, prompt, n)

    def run():
        eng = _engine(cfg, params, draft_k=4)
        rid = eng.add_request(
            prompt,
            SamplingParams(
                temperature=0.9, top_k=1, max_tokens=n, seed=7
            ),
        )
        _force_drafts(eng, rid, ref + [0] * 8, len(prompt))
        eng._run_to_completion()
        out = eng._finished.pop(rid).output_ids
        return out, dict(eng._stats)

    out1, st1 = run()
    out2, st2 = run()
    assert out1 == out2 == ref
    assert st1['spec_accepted_tokens'] > 0
    assert st1['spec_sampled_rows'] > 0
    assert st1['spec_accepted_tokens'] == st2['spec_accepted_tokens']


def test_engine_sampled_spec_deterministic_unfiltered():
    """Determinism without filters: a genuinely stochastic request
    (near-flat tiny-model logits at temperature 0.8) under speculation
    reproduces bit-for-bit across fresh engines."""
    cfg = _tiny_cfg()
    params = mistral.init(jax.random.PRNGKey(0), cfg)
    prompt = [5, 9, 12]
    n = 9

    def run():
        eng = _engine(cfg, params, draft_k=4)
        rid = eng.add_request(
            prompt,
            SamplingParams(temperature=0.8, max_tokens=n, seed=7),
        )
        eng._run_to_completion()
        return eng._finished.pop(rid).output_ids

    out1, out2 = run(), run()
    assert out1 == out2
    assert len(out1) == n


def test_engine_sampled_spec_seed_changes_stream():
    """The explicit per-request seed is load-bearing: a different seed
    yields a different sampled stream under speculation."""
    cfg = _tiny_cfg()
    params = mistral.init(jax.random.PRNGKey(0), cfg)
    prompt = [5, 9, 12]
    n = 12

    def run(seed):
        eng = _engine(cfg, params, draft_k=4)
        rid = eng.add_request(
            prompt,
            SamplingParams(temperature=1.2, max_tokens=n, seed=seed),
        )
        eng._run_to_completion()
        return eng._finished.pop(rid).output_ids

    assert run(7) != run(8)
