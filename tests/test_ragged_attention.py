"""Parity matrix for the fused ragged Pallas paged-attention kernel.

``ragged_paged_attention_pallas`` (interpret mode — the same kernel code
path Mosaic compiles on TPU, executed on CPU) is pinned against
``ragged_paged_attention_xla``, the always-available bit-exactness
baseline, across the full serving feature surface: decode rows × chunk
rows × GQA grouping × static/traced sliding windows × logit softcap ×
custom scale × ``q_lens`` padding × query tiling. The engine-level
greedy fp32 token-identity test at the bottom flips the backend under a
real serving loop (prefix cache + chunked prefill, so ragged spans and
decode spans both dispatch through the kernel).

Boundary being tested: VALID rows/queries must match the XLA path to
fp32 tolerance; PAD queries are exact zeros from the kernel (the XLA
twin emits finite key-0 garbage there) — both finite, both discarded by
every caller (docs/serving.md "Attention kernel backends").
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distllm_tpu.ops.paged_attention import (
    ragged_paged_attention_pallas,
    ragged_paged_attention_xla,
)


def _setup(rng, *, num_blocks=12, block_size=4, nkv=2, nh=4, hd=8, b=3,
           s=5):
    k = jnp.asarray(
        rng.normal(size=(num_blocks, block_size, nkv, hd)).astype(np.float32)
    )
    v = jnp.asarray(
        rng.normal(size=(num_blocks, block_size, nkv, hd)).astype(np.float32)
    )
    max_blocks = 8
    # Block 0 is the trash block by engine convention; tables point at
    # arbitrary scattered real blocks like the paged allocator produces.
    bt = jnp.asarray(
        rng.integers(1, num_blocks, size=(b, max_blocks)), jnp.int32
    )
    # Row 0: mid-stream chunk; row 1: span == context (fresh prefill);
    # row 2: decode-like single live query (rest is q_lens padding).
    ctx = jnp.asarray([17, s, 9][:b], jnp.int32)
    q_lens = jnp.asarray([s, s, 1][:b], jnp.int32)
    q0 = ctx - q_lens
    pos = q0[:, None] + jnp.arange(s)[None, :]
    q = jnp.asarray(rng.normal(size=(b, s, nh, hd)).astype(np.float32))
    return q, k, v, bt, ctx, pos, q_lens


def _assert_parity(out, ref, q_lens, s):
    out, ref = np.asarray(out), np.asarray(ref)
    assert np.isfinite(out).all(), 'pallas emitted non-finite values'
    valid = np.arange(s)[None, :] < np.asarray(q_lens)[:, None]
    np.testing.assert_allclose(
        out[valid], ref[valid], atol=1e-5, rtol=1e-4
    )


@pytest.mark.parametrize('nh,nkv', [(4, 4), (4, 2), (8, 2)])
@pytest.mark.parametrize(
    'window',
    [None, 3, 'traced', 'traced_zero'],
    ids=['nowin', 'win3', 'traced', 'traced0'],
)
def test_ragged_parity_gqa_by_window(rng, nh, nkv, window):
    """GQA grouping × sliding-window variants, ragged q_lens rows."""
    q, k, v, bt, ctx, pos, q_lens = _setup(rng, nkv=nkv, nh=nh)
    if window == 'traced':
        window = jnp.int32(4)  # traced per-layer window (gemma2 shape)
    elif window == 'traced_zero':
        window = jnp.int32(0)  # traced disable: <= 0 means global
    ref = ragged_paged_attention_xla(
        q, k, v, bt, ctx, pos, q_lens=q_lens, sliding_window=window
    )
    out = ragged_paged_attention_pallas(
        q, k, v, bt, ctx, pos, q_lens=q_lens, sliding_window=window,
        interpret=True,
    )
    _assert_parity(out, ref, q_lens, q.shape[1])


@pytest.mark.parametrize('softcap', [None, 30.0], ids=['nocap', 'cap30'])
@pytest.mark.parametrize('scale', [None, 0.25], ids=['defscale', 'scale'])
def test_ragged_parity_softcap_and_scale(rng, softcap, scale):
    """gemma2 knobs: tanh logit softcap and query_pre_attn_scalar scale,
    with a sliding window riding along."""
    q, k, v, bt, ctx, pos, q_lens = _setup(rng)
    ref = ragged_paged_attention_xla(
        q, k, v, bt, ctx, pos, q_lens=q_lens, sliding_window=5,
        scale=scale, logit_softcap=softcap,
    )
    out = ragged_paged_attention_pallas(
        q, k, v, bt, ctx, pos, q_lens=q_lens, sliding_window=5,
        scale=scale, logit_softcap=softcap, interpret=True,
    )
    _assert_parity(out, ref, q_lens, q.shape[1])


def test_ragged_parity_decode_rows(rng):
    """Span-1 rows (the decode degenerate case) match the decode op."""
    from distllm_tpu.ops.paged_attention import (
        paged_attention_pallas,
        paged_attention_xla,
    )

    q, k, v, bt, ctx, pos, _ = _setup(rng, s=1)
    qd = q[:, 0]
    for window in (None, 6):
        ref = paged_attention_xla(
            qd, k, v, bt, ctx, sliding_window=window
        )
        out = paged_attention_pallas(
            qd, k, v, bt, ctx, sliding_window=window, interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-4
        )


def test_ragged_parity_query_tiling_and_chunking(rng):
    """Long spans across multiple query tiles and multi-page KV chunks:
    tiling must be invisible (same values as the untiled XLA gather)."""
    q, k, v, bt, ctx, pos, q_lens = _setup(
        rng, s=13, nh=8, nkv=2, num_blocks=16
    )
    ctx = jnp.asarray([30, 13, 22], jnp.int32)
    q_lens = jnp.asarray([13, 13, 7], jnp.int32)
    pos = (ctx - q_lens)[:, None] + jnp.arange(13)[None, :]
    for window in (None, 5):
        ref = ragged_paged_attention_xla(
            q, k, v, bt, ctx, pos, q_lens=q_lens, sliding_window=window
        )
        out = ragged_paged_attention_pallas(
            q, k, v, bt, ctx, pos, q_lens=q_lens, sliding_window=window,
            span_tile=4, pages_per_chunk=2, interpret=True,
        )
        _assert_parity(out, ref, q_lens, 13)


def test_ragged_pad_rows_are_exact_zeros(rng):
    """q_lens=0 rows and pad queries emit exact finite zeros — stricter
    than the XLA twin's key-0 garbage, and the property that keeps a NaN
    out of the trash block under sliding windows."""
    q, k, v, bt, ctx, pos, _ = _setup(rng, s=6)
    q_lens = jnp.asarray([6, 0, 2], jnp.int32)
    out = np.asarray(
        ragged_paged_attention_pallas(
            q, k, v, bt, ctx, pos, q_lens=q_lens, sliding_window=2,
            interpret=True,
        )
    )
    assert np.isfinite(out).all()
    assert np.abs(out[1]).max() == 0.0  # fully padded row
    assert np.abs(out[2, 2:]).max() == 0.0  # pad tail of a ragged row


def test_ragged_q_lens_none_matches_xla(rng):
    """q_lens=None: every span position is computed as a live query (the
    prefill alias contract) — full-tensor parity, not just valid rows."""
    q, k, v, bt, ctx, pos, _ = _setup(rng)
    ctx = jnp.asarray([17, 9, 12], jnp.int32)
    pos = (ctx - q.shape[1])[:, None] + jnp.arange(q.shape[1])[None, :]
    ref = ragged_paged_attention_xla(q, k, v, bt, ctx, pos, q_lens=None)
    out = ragged_paged_attention_pallas(
        q, k, v, bt, ctx, pos, q_lens=None, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-4
    )


def test_dispatcher_backend_routing(rng):
    """The one serving callsite: 'xla' and 'interpret' agree on valid
    rows; an unresolved selector fails loudly."""
    from distllm_tpu.ops.paged_attention import ragged_paged_attention

    q, k, v, bt, ctx, pos, q_lens = _setup(rng)
    ref = ragged_paged_attention(
        q, k, v, bt, ctx, pos, q_lens=q_lens, backend='xla'
    )
    out = ragged_paged_attention(
        q, k, v, bt, ctx, pos, q_lens=q_lens, backend='interpret'
    )
    _assert_parity(out, ref, q_lens, q.shape[1])
    with pytest.raises(ValueError, match='attn backend'):
        ragged_paged_attention(
            q, k, v, bt, ctx, pos, q_lens=q_lens, backend='auto'
        )


def test_resolve_attn_backend_contract(monkeypatch):
    from types import SimpleNamespace

    from distllm_tpu.ops.paged_attention import resolve_attn_backend

    mc = SimpleNamespace(head_size=128)
    # CPU: 'auto' must land on the always-available XLA fallback.
    assert resolve_attn_backend('auto', mc) == 'xla'
    # Explicit pins pass through untouched.
    assert resolve_attn_backend('pallas', mc) == 'pallas'
    assert resolve_attn_backend('interpret', mc) == 'interpret'
    with pytest.raises(ValueError, match='attn_backend'):
        resolve_attn_backend('cuda', mc)
    # On TPU, 'auto' eligibility includes the kernel's DMA contract on
    # the KV block geometry: a block_size the kernel would reject must
    # resolve to XLA (never trace into the kernel's ValueError), while
    # the default geometry selects the kernel.
    monkeypatch.setattr(jax, 'default_backend', lambda: 'tpu')
    # Head-dim CI contract: 128 is tested, 256 is a multiple of 128 but
    # outside TESTED_HEAD_DIMS so 'auto' must keep XLA.
    assert resolve_attn_backend('auto', mc) == 'pallas'
    assert (
        resolve_attn_backend('auto', SimpleNamespace(head_size=256)) == 'xla'
    )
    assert resolve_attn_backend(
        'auto', mc, block_size=16, kv_dtype='bfloat16'
    ) == 'pallas'
    assert resolve_attn_backend(
        'auto', mc, block_size=8, kv_dtype='bfloat16'
    ) == 'xla'
    assert resolve_attn_backend(
        'auto', mc, block_size=8, kv_dtype='float32'
    ) == 'pallas'  # fp32 sublane tile is 8


def _tiny_engine(attn_backend):
    from distllm_tpu.generate.engine import EngineConfig, LLMEngine
    from distllm_tpu.models import mistral

    cfg = mistral.MistralConfig(
        vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=64, dtype='float32',
    )
    params = mistral.init(jax.random.PRNGKey(0), cfg)

    class _Tok:
        eos_id = None

    engine_cfg = EngineConfig(
        block_size=4, num_blocks=48, max_num_seqs=3, max_model_len=64,
        decode_steps=4, pipeline_depth=1, attn_backend=attn_backend,
        enable_prefix_cache=True, prefill_chunk_tokens=8,
    )
    return LLMEngine(cfg, params, _Tok(), engine_cfg)


@pytest.mark.parametrize('flipped', ['interpret'])
def test_engine_token_identity_backend_flipped(flipped):
    """Greedy fp32 serving produces IDENTICAL tokens with the backend
    flipped from 'xla' to the ragged Pallas kernel (interpret mode — the
    same kernel the TPU compiles). Prefix cache + chunked prefill are on,
    so both ragged chunk spans and span-1 decode rows dispatch through
    the flipped kernel. This is the engine-level identity boundary from
    docs/serving.md: cross-kernel identity is pinned in fp32 (bf16 may
    round a near-tied logit differently across compiled programs)."""
    from distllm_tpu.generate.engine import SamplingParams

    rng = np.random.default_rng(7)
    shared = list(rng.integers(1, 128, size=10))
    prompts = [
        shared + list(rng.integers(1, 128, size=int(n)))
        for n in (3, 11, 6)
    ]
    sampling = SamplingParams(temperature=0.0, max_tokens=6)
    outs = {}
    for backend in ('xla', flipped):
        engine = _tiny_engine(backend)
        assert engine.telemetry['attn_backend'] == backend
        outs[backend] = engine.generate_ids(prompts, sampling)
        engine.shutdown()
    assert outs['xla'] == outs[flipped], (
        'greedy fp32 token stream diverged when the attention backend '
        'flipped — the kernel identity contract is broken'
    )
