"""Bench trajectory gate (``scripts/benchdiff.py``): the fast-tier smoke
runs it over the REAL in-repo BENCH_r01/r02 records (the known
embed/gen deltas must appear, exit 0) and over an injected regression
(exit nonzero) — the acceptance shape of the ISSUE 11 tentpole."""

from __future__ import annotations

import importlib.util
import json
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
BENCHDIFF = REPO / 'scripts' / 'benchdiff.py'

_spec = importlib.util.spec_from_file_location('benchdiff', BENCHDIFF)
benchdiff = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(benchdiff)


def _run(*args):
    return subprocess.run(
        [sys.executable, str(BENCHDIFF), *map(str, args)],
        capture_output=True, text=True, timeout=120,
    )


def test_real_r01_r02_records_pass_and_report_known_deltas():
    """r01 crashed before emitting (no metrics); r02 is the last clean
    full record: 1619.88 emb/s and 184.18 tok/s appear as new metrics,
    and a new metric is never a regression."""
    proc = _run(REPO / 'BENCH_r01.json', REPO / 'BENCH_r02.json')
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = proc.stdout
    assert '| value |' in out and '1619.88' in out
    assert '| gen_value |' in out and '184.18' in out
    assert '| mfu |' in out and '0.463' in out
    assert 'new' in out
    assert 'No regressions' in out
    # r01's empty payload is surfaced, not crashed over.
    assert 'r01' in out and 'no metrics' in out


def test_injected_regression_exits_nonzero(tmp_path):
    fake = {
        'n': 6,
        'rc': 0,
        'parsed': {
            'metric': 'embeddings/sec/chip',
            'value': 1400.0,       # 1619.88 -> 1400: -13.6%
            'unit': 'emb/s',
            'gen_value': 100.0,    # 184.18 -> 100: -45.7%
            'gen_mfu': 0.0135,     # unchanged: must NOT be flagged
        },
    }
    candidate = tmp_path / 'BENCH_r06.json'
    candidate.write_text(json.dumps(fake))
    proc = _run(
        REPO / 'BENCH_r01.json', REPO / 'BENCH_r02.json', candidate,
        '--markdown', tmp_path / 'trajectory.md',
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    out = proc.stdout
    assert 'REGRESSED' in out
    assert 'gen_value' in out and '-45.7%' in out
    assert (tmp_path / 'trajectory.md').read_text() == out
    # Within-threshold and informational metrics never gate.
    assert '| gen_mfu |' in out and 'gen_mfu' not in [
        line.split('`')[1]
        for line in out.splitlines()
        if line.startswith('- `')
    ]


def test_threshold_and_direction_semantics(tmp_path):
    base = tmp_path / 'a.json'
    base.write_text(json.dumps({
        'parsed': {'value': 100.0, 'gen_ttft_s': 1.0, 'n_tokens': 500}
    }))

    def candidate(**metrics):
        path = tmp_path / 'b.json'
        path.write_text(json.dumps({'parsed': metrics}))
        return path

    # Latency is lower-better: a rise beyond threshold regresses...
    proc = _run(
        base, candidate(value=100.0, gen_ttft_s=1.5, n_tokens=500)
    )
    assert proc.returncode == 1 and 'gen_ttft_s' in proc.stdout
    # ...a fall (plus a small within-threshold throughput dip) passes.
    proc = _run(
        base, candidate(value=98.0, gen_ttft_s=0.5, n_tokens=500)
    )
    assert proc.returncode == 0, proc.stdout
    # Informational counters never gate, even when they collapse.
    proc = _run(base, candidate(value=100.0, gen_ttft_s=1.0, n_tokens=1))
    assert proc.returncode == 0, proc.stdout
    # --strict-missing turns a lost gated metric into a failure.
    proc = _run(base, candidate(value=100.0))
    assert proc.returncode == 0
    proc = _run(base, candidate(value=100.0), '--strict-missing')
    assert proc.returncode == 1


def test_non_finite_metrics_never_crash_or_silently_pass(tmp_path):
    """bench records round-trip NaN/inf through json (allow_nan): the
    gate must neither crash formatting them nor let a NaN slide past
    every threshold comparison — a non-finite value reads as 'not
    reported' (lost under --strict-missing)."""
    base = tmp_path / 'a.json'
    base.write_text(json.dumps({'parsed': {'value': 100.0}}))
    cand = tmp_path / 'b.json'
    cand.write_text(json.dumps(
        {'parsed': {'value': float('nan'), 'gen_value': float('inf')}}
    ))
    proc = _run(base, cand)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert 'Traceback' not in proc.stderr
    assert 'value' in proc.stdout and 'lost' in proc.stdout
    proc = _run(base, cand, '--strict-missing')
    assert proc.returncode == 1


def test_library_surface_matches_cli():
    records = [
        benchdiff.load_record(REPO / 'BENCH_r01.json'),
        benchdiff.load_record(REPO / 'BENCH_r02.json'),
    ]
    assert records[0]['metrics'] == {}
    assert records[1]['metrics']['value'] == 1619.88
    assert records[1]['metrics']['gen_value'] == 184.18
    regressions, lost = benchdiff.diff_records(records, threshold=0.05)
    assert regressions == [] and lost == []
    assert benchdiff.gate_direction('gen_value') == 'higher'
    assert benchdiff.gate_direction('gen_load_ttft_p95_s') == 'lower'
    assert benchdiff.gate_direction('warmup_secs') == 'lower'
    assert benchdiff.gate_direction('n_tokens') is None
    # gen_tier (KV-tier) metrics: warm/cold TTFT gate lower-better,
    # promotion overlap and hit rate higher-better, the speedup ratio
    # higher-better despite its 'ttft' substring, and raw spill /
    # promotion counts stay informational.
    assert benchdiff.gate_direction('gen_tier_warm_ttft_s') == 'lower'
    assert benchdiff.gate_direction('gen_tier_cold_ttft_s') == 'lower'
    assert benchdiff.gate_direction('gen_tier_warm_ttft_speedup') == 'higher'
    assert (
        benchdiff.gate_direction('gen_tier_promotion_overlap') == 'higher'
    )
    assert benchdiff.gate_direction('gen_tier_hit_rate') == 'higher'
    # gen_router (multi-replica tier) headline gates: the affinity-vs-RR
    # warm-TTFT speedup ratio and the replica-kill goodput both gate
    # higher-better (docs/routing.md).
    assert (
        benchdiff.gate_direction('gen_router_router_warm_ttft_speedup')
        == 'higher'
    )
    assert (
        benchdiff.gate_direction('gen_router_failover_goodput') == 'higher'
    )
    assert (
        benchdiff.gate_direction('gen_router_affinity_ttft_p95') == 'lower'
    )
    assert benchdiff.gate_direction('gen_tier_spills') is None
    assert benchdiff.gate_direction('gen_tier_promotions') is None
    assert benchdiff.gate_direction('gen_tier_spilled_blocks') is None


def test_gen_chaos_gate_directions():
    """ISSUE 15: goodput-under-fault and recoveries gate higher-better;
    shed metrics stay informational (shed volume is offered-load policy,
    not quality)."""
    assert benchdiff.gate_direction('gen_chaos_goodput_tokens') == 'higher'
    assert benchdiff.gate_direction('gen_chaos_recoveries') == 'higher'
    assert benchdiff.gate_direction('gen_chaos_tok_s') == 'higher'
    assert benchdiff.gate_direction('gen_chaos_shed_rate') is None
    assert benchdiff.gate_direction('gen_chaos_shed_requests') is None
    assert benchdiff.gate_direction('gen_chaos_retries') is None
    assert benchdiff.gate_direction('gen_chaos_quarantined') is None
    assert benchdiff.gate_direction('gen_chaos_faults_injected') is None


def test_gen_kvq_gate_directions():
    """ISSUE 17: the quantized-KV stage's accuracy fraction gates
    higher-better — a FALLING greedy match is a quality regression (the
    compression got lossier) and must trip the gate like a throughput
    fall. Byte/capacity evidence stays informational: pool bytes and
    capacity are geometry facts, not round-over-round quality."""
    assert benchdiff.gate_direction('gen_kvq_greedy_match') == 'higher'
    assert benchdiff.gate_direction('gen_kvq_int8_tok_s') == 'higher'
    assert benchdiff.gate_direction('gen_kvq_bf16_tok_s') == 'higher'
    assert (
        benchdiff.gate_direction('gen_kvq_int8_bw_util_measured') == 'higher'
    )
    assert benchdiff.gate_direction('gen_kvq_speedup') == 'higher'
    assert benchdiff.gate_direction('gen_kvq_int8_kv_pool_bytes') is None
    assert benchdiff.gate_direction('gen_kvq_kv_pool_bytes_ratio') is None
    assert benchdiff.gate_direction('gen_kvq_int8_capacity_blocks') is None
    assert (
        benchdiff.gate_direction('gen_kvq_int8_decode_bytes_accessed') is None
    )


def test_gen_history_gate_directions():
    """ISSUE 18: the telemetry stage's throughput/latency arms gate like
    every other serving stage; sentinel fire counts, burn rates and shed
    volume stay informational — they are schedule/policy facts, and the
    stage itself errors when the slow arm fails to fire."""
    assert benchdiff.gate_direction('gen_history_tok_s') == 'higher'
    assert benchdiff.gate_direction('gen_history_ttft_p95') == 'lower'
    assert benchdiff.gate_direction('gen_history_tpot_p95') == 'lower'
    assert benchdiff.gate_direction('gen_history_clean_regressions') is None
    assert benchdiff.gate_direction('gen_history_slow_regressions') is None
    assert benchdiff.gate_direction('gen_history_burn_60s') is None
    assert benchdiff.gate_direction('gen_history_overload_slo_missed') is None
    assert benchdiff.gate_direction('gen_history_shed_requests') is None


def test_emit_baseline_distills_newest_usable_record(tmp_path):
    """--emit-baseline (ISSUE 18 satellite): r02 is the newest record
    carrying envelope-source metrics, so its gen_value becomes the tok_s
    baseline — through the SAME extraction code the runtime sentinel
    loads, so gate and sentinel cannot disagree on what a record says."""
    out = tmp_path / 'baseline.json'
    proc = _run(
        REPO / 'BENCH_r01.json', REPO / 'BENCH_r02.json',
        '--emit-baseline', out,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(out.read_text())
    assert doc['schema'] == 'distllm-baseline-envelope/v1'
    assert doc['source'] == 'r02'
    assert doc['metrics']['tok_s'] == {
        'value': 184.18, 'direction': 'higher', 'from_key': 'gen_value',
    }
    # Envelope-only invocations are legal at any record count: a single
    # record emits and exits 0 (nothing to diff), and a pile with no
    # usable metrics emits the EMPTY envelope (the sentinel's counted
    # disarm mode), never a crash.
    solo = _run(REPO / 'BENCH_r02.json', '--emit-baseline', out)
    assert solo.returncode == 0, solo.stdout + solo.stderr
    assert json.loads(out.read_text())['source'] == 'r02'
    empty = _run(REPO / 'BENCH_r01.json', '--emit-baseline', out)
    assert empty.returncode == 0, empty.stdout + empty.stderr
    doc = json.loads(out.read_text())
    assert doc['metrics'] == {} and doc['source'] == ''


def test_gen_kvq_accuracy_regression_trips_gate(tmp_path):
    """A fallen greedy-match fraction alone (tok/s flat) trips the gate:
    the accuracy arm is enforceable, not decorative."""
    prior = {
        'n': 7, 'rc': 0,
        'parsed': {
            'gen_kvq_int8_tok_s': 180.0,
            'gen_kvq_greedy_match': 0.95,
            'gen_kvq_kv_pool_bytes_ratio': 0.502,
        },
    }
    ok_current = {
        'n': 8, 'rc': 0,
        'parsed': {
            'gen_kvq_int8_tok_s': 182.0,
            'gen_kvq_greedy_match': 0.94,  # within --threshold
            'gen_kvq_kv_pool_bytes_ratio': 0.51,
        },
    }
    bad_current = {
        'n': 8, 'rc': 0,
        'parsed': {
            'gen_kvq_int8_tok_s': 181.0,    # throughput fine
            'gen_kvq_greedy_match': 0.40,   # compression got lossier
            'gen_kvq_kv_pool_bytes_ratio': 0.51,
        },
    }
    (tmp_path / 'prior.json').write_text(json.dumps(prior))
    (tmp_path / 'ok.json').write_text(json.dumps(ok_current))
    (tmp_path / 'bad.json').write_text(json.dumps(bad_current))

    proc = _run(tmp_path / 'prior.json', tmp_path / 'ok.json')
    assert proc.returncode == 0, proc.stdout + proc.stderr

    proc = _run(tmp_path / 'prior.json', tmp_path / 'bad.json')
    assert proc.returncode == 1
    assert 'gen_kvq_greedy_match' in proc.stdout


def test_gen_chaos_regression_trips_gate(tmp_path):
    """A CPU-smoke-shaped gen_chaos fragment: dropped recoveries and
    goodput trip the gate; a shed-rate swing alone does not."""
    prior = {
        'n': 7, 'rc': 0,
        'parsed': {
            'gen_chaos_goodput_tokens': 226.0,
            'gen_chaos_recoveries': 2.0,
            'gen_chaos_shed_rate': 0.10,
        },
    }
    ok_current = {
        'n': 8, 'rc': 0,
        'parsed': {
            'gen_chaos_goodput_tokens': 230.0,
            'gen_chaos_recoveries': 2.0,
            'gen_chaos_shed_rate': 0.90,  # informational: never gated
        },
    }
    bad_current = {
        'n': 8, 'rc': 0,
        'parsed': {
            'gen_chaos_goodput_tokens': 150.0,  # -34%
            'gen_chaos_recoveries': 0.0,        # faults stopped surviving
            'gen_chaos_shed_rate': 0.10,
        },
    }
    (tmp_path / 'prior.json').write_text(json.dumps(prior))
    (tmp_path / 'ok.json').write_text(json.dumps(ok_current))
    (tmp_path / 'bad.json').write_text(json.dumps(bad_current))

    proc = _run(tmp_path / 'prior.json', tmp_path / 'ok.json')
    assert proc.returncode == 0, proc.stdout + proc.stderr

    proc = _run(tmp_path / 'prior.json', tmp_path / 'bad.json')
    assert proc.returncode == 1
    assert 'gen_chaos_goodput_tokens' in proc.stdout
    assert 'gen_chaos_recoveries' in proc.stdout
    assert 'gen_chaos_shed_rate' not in proc.stdout.split('regression')[-1]
